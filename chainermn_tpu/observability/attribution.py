"""Step-time attribution — where did the step's wall time go, and on
which rank.

Consumes the per-rank span trees :mod:`chainermn_tpu.observability.spans`
reconstructs and answers the question every perf round had to answer by
hand (BENCH r01–r05, RESNET_PROBE r09):

* :func:`attribute_step` decomposes ONE step tree into the six buckets
  ``compute / ici_comm / dcn_comm / host_input / checkpoint / stall`` by
  interval arithmetic (union the classified spans, subtract by
  priority), so the buckets are disjoint and sum to the measured step
  time exactly — the residual the spans cannot explain is ``stall``;
* :func:`merge_ranks` + :func:`attribution_report` merge trees across
  ranks (each rank's timestamps shifted into the reference rank's
  timebase by a clock-handshake offset) and compute the per-step
  cross-rank critical path (:func:`critical_path`);
* :func:`clock_handshake` estimates the wall-clock offset between this
  rank and rank 0 over the communicator's object/control plane with the
  NTP midpoint formula (min-RTT sample wins) —
  :func:`offset_from_samples` is the pure math, shared with the
  watchdog's probe/reply handshake;
* :func:`to_trace_events` exports a merged timeline as Chrome/Perfetto
  trace-event JSON (``chrome://tracing`` / https://ui.perfetto.dev).

Bucket definitions (docs/observability.md "Attribution & tracing"):

=============  =============================================================
``host_input``  ``data_load`` + ``host_put`` phases — iterator and batch
                sharding time the device spent idle (unless prefetch hid it)
``ici_comm``    union of spans tagged ``link="ici"`` (intra-scope plan
                stages, FSDP bucket collectives) plus untagged collective
                spans — fast-interconnect time
``dcn_comm``    union of spans tagged ``link="dcn"`` (inter/all-scope plan
                stages) plus object-plane ops — slow-boundary time
``checkpoint``  checkpoint_save spans
``compute``     device window (``dispatch`` + ``device_block`` phases, or
                the whole step when phases are absent) minus everything
                above — includes codec compute (separable in the tree)
``stall``       measured step time minus every bucket — time no span
                explains (scheduler noise, GIL, untraced waits)
=============  =============================================================
"""

from __future__ import annotations

import time
from typing import Dict, List, Optional, Sequence, Tuple

from chainermn_tpu.observability.spans import Span, build_step_trees

BUCKETS = ("compute", "ici_comm", "dcn_comm", "host_input", "checkpoint",
           "stall")

#: span kinds whose link field (or default) classifies comm time
_HOST_PHASES = ("data_load", "host_put")
_DEVICE_PHASES = ("dispatch", "device_block")


# ---------------------------------------------------------------------------
# interval arithmetic (half-open [t0, t1) semantics, merged ascending)
# ---------------------------------------------------------------------------

def _merge(intervals: Sequence[Tuple[float, float]]) -> List[Tuple[float, float]]:
    ivs = sorted((a, b) for a, b in intervals if b > a)
    out: List[Tuple[float, float]] = []
    for a, b in ivs:
        if out and a <= out[-1][1]:
            out[-1] = (out[-1][0], max(out[-1][1], b))
        else:
            out.append((a, b))
    return out


def _subtract(a: List[Tuple[float, float]],
              b: List[Tuple[float, float]]) -> List[Tuple[float, float]]:
    """``a - b``; both merged ascending."""
    out: List[Tuple[float, float]] = []
    for a0, a1 in a:
        cur = a0
        for b0, b1 in b:
            if b1 <= cur or b0 >= a1:
                continue
            if b0 > cur:
                out.append((cur, b0))
            cur = max(cur, b1)
            if cur >= a1:
                break
        if cur < a1:
            out.append((cur, a1))
    return out


def _clip(intervals: List[Tuple[float, float]],
          t0: float, t1: float) -> List[Tuple[float, float]]:
    return [(max(a, t0), min(b, t1)) for a, b in intervals
            if min(b, t1) > max(a, t0)]


def _total(intervals: List[Tuple[float, float]]) -> float:
    return sum(b - a for a, b in intervals)


# ---------------------------------------------------------------------------
# bucket decomposition
# ---------------------------------------------------------------------------

def classify_span(span: Span) -> Optional[str]:
    """Bucket a leaf span contributes comm/checkpoint time to, or
    ``None`` for spans that stay inside the compute bucket (codec
    compute, serving sub-spans, phases — phases are handled
    separately)."""
    link = span.meta.get("link")
    if link == "ici":
        return "ici_comm"
    if link == "dcn":
        return "dcn_comm"
    if span.kind == "plan_stage":
        return "dcn_comm" if span.meta.get("scope") in ("inter", "all") \
            else "ici_comm"
    if span.kind == "fsdp":
        return "ici_comm"
    if span.kind == "collective":
        return "ici_comm"
    if span.kind == "object":
        return "dcn_comm"
    if span.kind == "checkpoint":
        return "checkpoint"
    return None


def attribute_step(step: Span) -> dict:
    """Decompose one step tree into the six buckets.

    Construction guarantees the buckets are disjoint, clipped to the
    step window, and sum to the measured step time exactly: classified
    spans are unioned per bucket then subtracted in priority order
    (checkpoint > dcn > ici > host_input), compute is the device window
    minus all of those, and stall is the unexplained remainder.
    """
    t0, t1 = step.t0, step.t1
    total = step.dur_s
    by_bucket: Dict[str, List[Tuple[float, float]]] = {
        "ici_comm": [], "dcn_comm": [], "checkpoint": []}
    host_iv: List[Tuple[float, float]] = []
    device_iv: List[Tuple[float, float]] = []
    for sp in step.walk():
        if sp is step:
            continue
        if sp.kind == "phase":
            name = sp.meta.get("phase")
            if name in _HOST_PHASES:
                host_iv.append((sp.t0, sp.t1))
            elif name in _DEVICE_PHASES:
                device_iv.append((sp.t0, sp.t1))
            continue
        bucket = classify_span(sp)
        if bucket is not None:
            by_bucket[bucket].append((sp.t0, sp.t1))
    ckpt = _clip(_merge(by_bucket["checkpoint"]), t0, t1)
    dcn = _subtract(_clip(_merge(by_bucket["dcn_comm"]), t0, t1), ckpt)
    used = _merge(ckpt + dcn)
    ici = _subtract(_clip(_merge(by_bucket["ici_comm"]), t0, t1), used)
    used = _merge(used + ici)
    host = _subtract(_clip(_merge(host_iv), t0, t1), used)
    used = _merge(used + host)
    dev_window = _clip(_merge(device_iv), t0, t1) if device_iv \
        else [(t0, t1)]
    compute = _subtract(dev_window, used)
    buckets = {
        "compute": _total(compute),
        "ici_comm": _total(ici),
        "dcn_comm": _total(dcn),
        "host_input": _total(host),
        "checkpoint": _total(ckpt),
    }
    buckets["stall"] = max(total - sum(buckets.values()), 0.0)
    ssum = sum(buckets.values())
    return {
        "rank": step.rank,
        "iteration": step.meta.get("iteration"),
        "step_s": total,
        "buckets": buckets,
        "sum_s": ssum,
        "sum_frac": ssum / total if total > 0 else 1.0,
    }


# ---------------------------------------------------------------------------
# clock offset estimation (the control-plane handshake)
# ---------------------------------------------------------------------------

def offset_from_samples(
        samples: Sequence[Tuple[float, float, float]]) -> Tuple[float, float]:
    """NTP midpoint estimate from ``(t_send, t_peer, t_recv)`` samples,
    all on the local clock except ``t_peer``: the min-RTT sample gives
    ``offset = t_peer - (t_send + t_recv) / 2`` (add ``offset`` to a
    local stamp to land in the peer's timebase) with uncertainty
    ``rtt / 2``.  Returns ``(offset_s, rtt_s)``."""
    if not samples:
        raise ValueError("offset_from_samples needs at least one sample")
    t_send, t_peer, t_recv = min(samples, key=lambda s: s[2] - s[0])
    rtt = max(t_recv - t_send, 0.0)
    return t_peer - 0.5 * (t_send + t_recv), rtt


def clock_handshake(comm, rounds: int = 8) -> dict:
    """Estimate this rank's wall-clock offset to rank 0 over the
    communicator's object plane.  COLLECTIVE (every rank must call it at
    the same point); each round is one ``allgather_obj`` of wall stamps,
    bracketed by local send/recv stamps — the NTP request/response pair
    with the allgather as both legs.  Single-host worlds return a zero
    offset without touching the wire.

    Returns ``{"rank", "offset_s", "rtt_s", "rounds"}`` where
    ``local_ts + offset_s ≈ the same instant on rank 0's clock`` — the
    shift :func:`merge_ranks` applies.
    """
    rank = int(getattr(comm, "rank", 0) or 0)
    if comm is None or int(getattr(comm, "host_size", 1) or 1) <= 1:
        return {"rank": rank, "offset_s": 0.0, "rtt_s": 0.0, "rounds": 0}
    samples = []
    for _ in range(max(int(rounds), 1)):
        t_send = time.time()
        stamps = comm.allgather_obj({"rank": rank, "wall": time.time()})
        t_recv = time.time()
        ref = next((s for s in stamps if s.get("rank") == 0), None)
        if ref is not None:
            samples.append((t_send, float(ref["wall"]), t_recv))
    offset, rtt = offset_from_samples(samples) if samples else (0.0, 0.0)
    if rank == 0:
        offset = 0.0  # rank 0 IS the reference timebase
    return {"rank": rank, "offset_s": offset, "rtt_s": rtt,
            "rounds": len(samples)}


# ---------------------------------------------------------------------------
# cross-rank merge + critical path
# ---------------------------------------------------------------------------

def merge_ranks(events_by_rank: Dict[int, List[dict]],
                offsets: Optional[Dict[int, float]] = None
                ) -> Dict[int, List[Span]]:
    """Build per-rank step trees with every rank's timestamps shifted
    into the reference timebase.  ``offsets`` maps rank -> the
    ``offset_s`` its :func:`clock_handshake` reported (missing ranks
    shift by zero — single-host merges need no correction)."""
    offsets = offsets or {}
    return {int(r): build_step_trees(evs, rank=int(r),
                                     offset=float(offsets.get(int(r), 0.0)))
            for r, evs in events_by_rank.items()}


def _match_collective(trees_by_rank: Dict[int, Span], rank: int,
                      span: Span) -> Optional[Tuple[int, Span]]:
    """The last entrant into a symmetric collective: the rank whose
    matching (op, op_seq) span starts latest — the one everybody else
    waited for."""
    op, seq = span.meta.get("op"), span.meta.get("op_seq")
    if op is None or seq is None:
        return None
    best = None
    for r, tree in trees_by_rank.items():
        for sp in tree.walk():
            if (sp.kind == span.kind and sp.meta.get("op") == op
                    and sp.meta.get("op_seq") == seq):
                if best is None or sp.t0 > best[1].t0:
                    best = (r, sp)
    if best is not None and best[0] != rank:
        return best
    return None


def critical_path(trees_by_rank: Dict[int, Span]) -> List[dict]:
    """Cross-rank critical path of ONE step: start at the gating rank
    (longest step), greedily descend into the longest child; at a
    collective present on several ranks, hop to the last entrant (the
    rank the others blocked on) and keep descending there.  Each entry
    names a (rank, span) pair."""
    if not trees_by_rank:
        return []
    rank = max(trees_by_rank, key=lambda r: trees_by_rank[r].dur_s)
    span = trees_by_rank[rank]
    path: List[dict] = []
    visited = set()
    while span is not None and id(span) not in visited:
        visited.add(id(span))
        entry = {"rank": rank, "name": span.name, "kind": span.kind,
                 "dur_s": span.dur_s, "t0": span.t0, "t1": span.t1}
        if span.kind in ("collective", "plan_stage", "fsdp"):
            hop = _match_collective(trees_by_rank, rank, span)
            if hop is not None and id(hop[1]) not in visited:
                entry["blocked_by_rank"] = hop[0]
                path.append(entry)
                rank, span = hop
                visited.add(id(span))
                entry = {"rank": rank, "name": span.name, "kind": span.kind,
                         "dur_s": span.dur_s, "t0": span.t0, "t1": span.t1}
        path.append(entry)
        span = max(span.children, key=lambda s: s.dur_s, default=None)
    return path


def attribution_report(events_by_rank: Dict[int, List[dict]],
                       offsets: Optional[Dict[int, float]] = None) -> dict:
    """The full cross-rank report: per-iteration bucket decomposition on
    every rank plus the critical path, and a mean-bucket summary —
    what ``obs_report --attribution`` renders and the ATTRIBUTION
    runbook leg asserts over."""
    merged = merge_ranks(events_by_rank, offsets=offsets)
    by_iter: Dict[object, Dict[int, Span]] = {}
    for r, trees in merged.items():
        for i, tree in enumerate(trees):
            key = tree.meta.get("iteration")
            by_iter.setdefault(key if key is not None else f"#{i}",
                               {})[r] = tree
    steps = []
    totals = {b: 0.0 for b in BUCKETS}
    n = 0
    for key in sorted(by_iter, key=str):
        ranks = by_iter[key]
        attrs = {r: attribute_step(t) for r, t in sorted(ranks.items())}
        for a in attrs.values():
            for b in BUCKETS:
                totals[b] += a["buckets"][b]
            n += 1
        steps.append({
            "iteration": key,
            "step_s": max(t.dur_s for t in ranks.values()),
            "ranks": {str(r): a for r, a in attrs.items()},
            "critical_path": critical_path(ranks),
        })
    return {
        "kind": "attribution_report",
        "schema": 1,
        "n_ranks": len(merged),
        "n_steps": len(steps),
        "offsets": {str(r): float((offsets or {}).get(r, 0.0))
                    for r in merged},
        "steps": steps,
        "summary": {
            "mean_buckets_s": {b: totals[b] / n if n else 0.0
                               for b in BUCKETS},
        },
    }


def span_summary(events: List[dict], rank: int = 0, k: int = 3) -> dict:
    """Top-``k`` critical-path spans aggregated over every step in an
    event stream — the compact per-run attribution the benchmark
    artifacts embed (``bench.py --metrics`` / ``bench_serving.py``)."""
    trees = build_step_trees(events, rank=rank)
    agg: Dict[Tuple[str, str], List[float]] = {}
    for tree in trees:
        for entry in critical_path({rank: tree}):
            if entry["kind"] == "step":
                continue
            agg.setdefault((entry["name"], entry["kind"]),
                           []).append(entry["dur_s"])
    mean_step = (sum(t.dur_s for t in trees) / len(trees)) if trees else 0.0
    spans = sorted(
        ({"name": name, "kind": kind,
          "mean_dur_s": sum(ds) / len(ds), "hits": len(ds),
          "frac_of_step": (sum(ds) / len(ds)) / mean_step
          if mean_step > 0 else 0.0}
         for (name, kind), ds in agg.items()),
        key=lambda s: -s["mean_dur_s"])[:max(int(k), 0)]
    return {"steps": len(trees), "mean_step_s": mean_step,
            "top_spans": spans}


# ---------------------------------------------------------------------------
# Chrome / Perfetto trace-event export
# ---------------------------------------------------------------------------

#: span kind -> trace lane (tid) inside each rank's process track
_LANES = {"step": 0, "phase": 1, "collective": 2, "plan_stage": 3,
          "compute": 4, "fsdp": 5, "object": 6, "serving": 7,
          "checkpoint": 8}


def to_trace_events(trees_by_rank: Dict[int, List[Span]]) -> dict:
    """Merged timeline as Chrome trace-event JSON (the ``traceEvents``
    array format both ``chrome://tracing`` and https://ui.perfetto.dev
    open directly): one process per rank, one thread lane per span
    kind, ``"X"`` complete events in microseconds relative to the
    earliest span start."""
    base = min((sp.t0 for trees in trees_by_rank.values()
                for t in trees for sp in t.walk()), default=0.0)
    events: List[dict] = []
    for rank in sorted(trees_by_rank):
        events.append({"ph": "M", "name": "process_name", "pid": rank,
                       "tid": 0, "args": {"name": f"rank{rank}"}})
        lanes_used = set()
        for tree in trees_by_rank[rank]:
            for sp in tree.walk():
                tid = _LANES.get(sp.kind, 9)
                lanes_used.add((tid, sp.kind))
                args = {k: v for k, v in sp.meta.items() if v is not None}
                events.append({
                    "ph": "X", "name": sp.name, "cat": sp.kind,
                    "ts": (sp.t0 - base) * 1e6,
                    "dur": sp.dur_s * 1e6,
                    "pid": rank, "tid": tid, "args": args,
                })
        for tid, kind in sorted(lanes_used):
            events.append({"ph": "M", "name": "thread_name", "pid": rank,
                           "tid": tid, "args": {"name": kind}})
    return {"traceEvents": events, "displayTimeUnit": "ms"}


__all__ = [
    "BUCKETS",
    "attribute_step",
    "attribution_report",
    "classify_span",
    "clock_handshake",
    "critical_path",
    "merge_ranks",
    "offset_from_samples",
    "span_summary",
    "to_trace_events",
]
