"""Streaming fleet telemetry — per-step rank summaries over the DCN
control plane (ISSUE 16 tentpole, part b).

Each rank cuts a **compact** summary of its flight-recorder window every
N steps — per-(link, owner) occupancy from :func:`~.contention.
occupancy_from_events`, step durations, dropped-event counts, and the
shippable states of its serving latency streaming histograms — and
ships it to rank 0 on the reserved control-plane telemetry tag
(:data:`~chainermn_tpu.runtime.control_plane.TELEMETRY_TAG`).  Rank 0
folds the summaries into one ``fleet_telemetry/v1`` document:

* fleet occupancy + a live overlap matrix per link class,
* straggler flags (a rank whose mean step time exceeds the fleet
  median by the straggler factor),
* fleet-merged serving latency distributions with p50/p95/p99 — the
  SLO percentile gauges, published back into the registry as
  ``fleet_<metric>`` gauges labelled by quantile.

``tools/obs_report.py --contention`` renders the documents from the
metrics JSONL; ``--live`` tail-follows them.

Zero-cost-when-disabled contract: construct the aggregator only when
:func:`~chainermn_tpu.observability.enabled` is on (``MetricsReport``
does exactly that).  A constructed-but-never-collected aggregator makes
no control-plane sends; a disabled run never constructs one, so the
HLO and the DCN wire are byte-identical to a run without this module.

Timebase caveat: the live view merges per-rank wall-clock intervals
WITHOUT the clock handshake (that would cost a collective per window).
Same-host ranks share a wall clock so the live overlap matrix is
exact there; across hosts it is approximate, and the post-hoc
:func:`~.contention.contention_report` (clock-corrected) is the
authoritative cut.  Interval lists are additionally capped at
``max_intervals`` per (link, owner) per window; a capped row ships
``truncated``/``dropped_s`` and the fleet document lists the affected
``(link, owner)`` pairs under ``truncated`` — union ``busy_s`` and the
live matrix are lower bounds for those (per-rank ``by_rank`` busy
stays exact: it is computed before the cap).
"""

from __future__ import annotations

from typing import Dict, List, Optional

from chainermn_tpu.observability import contention
from chainermn_tpu.observability.attribution import _merge, _total
from chainermn_tpu.observability.flight_recorder import get_flight_recorder
from chainermn_tpu.observability.registry import (
    StreamingHistogram, get_registry)

SCHEMA = "fleet_telemetry/v1"

#: serving latency streaming histograms shipped by default (the SLO set)
DEFAULT_HISTOGRAMS = (
    "serving_ttft_seconds",
    "serving_token_seconds",
    "serving_step_seconds",
)

_SLO_QUANTILES = (0.5, 0.95, 0.99)


def _plane_of(comm):
    """The control plane under a communicator (also looks through the
    instrumented wrapper); ``None`` when the comm has none."""
    for c in (comm, getattr(comm, "_comm", None)):
        cp = getattr(c, "_cp", None)
        if cp is not None:
            return cp
    return None


class TelemetryAggregator:
    """Per-rank summary builder + rank-0 fleet folder.

    ``collect(step)`` is a COLLECTIVE over the control plane — every
    rank must call it on the same steps (``MetricsReport`` triggers it
    on its emit interval, which is trigger-synchronized by
    construction).  Returns the fleet document on rank 0, ``None``
    elsewhere.
    """

    def __init__(self, comm, max_intervals: int = 32,
                 straggler_factor: float = 1.2,
                 histograms=DEFAULT_HISTOGRAMS):
        self._comm = comm
        self._plane = _plane_of(comm)
        self._fr = get_flight_recorder()
        self._reg = get_registry()
        self._max_intervals = int(max_intervals)
        self._straggler_factor = float(straggler_factor)
        self._hist_names = tuple(histograms)
        # flight-recorder cursor: each window ships once.  events_since
        # is strictly-greater, and the first recorded event has seq 0,
        # so the cursor must start BELOW it.
        self._seq = -1
        self._dropped_last = 0
        self.rank = getattr(comm, "rank", 0)
        self.size = getattr(comm, "size", 1)

    # ---- per-rank summary --------------------------------------------------

    def _window_events(self) -> List[dict]:
        if self._fr is None:
            return []
        events = self._fr.events_since(self._seq)
        if events:
            self._seq = max(int(e.get("seq", 0)) for e in events)
        return events

    def local_summary(self, step: int) -> dict:
        """The compact summary this rank ships: occupancy per (link,
        owner) with capped interval lists, step durations in the
        window, dropped-event delta, and serving histogram states.

        When a (link, owner) timeline exceeds ``max_intervals`` only
        the newest intervals ship; the row then carries ``truncated``
        and ``dropped_s`` (busy seconds of the intervals cut) so the
        fleet fold can mark its live matrix a lower bound instead of
        silently undercounting — ``busy_s`` itself is always the full
        uncapped window total."""
        events = self._window_events()
        occ = contention.occupancy_from_events(events, rank=self.rank)
        occ_doc: Dict[str, dict] = {}
        for link in sorted(occ):
            occ_doc[link] = {}
            for owner in sorted(occ[link]):
                ivs = occ[link][owner]
                dropped = ivs[:-self._max_intervals] \
                    if len(ivs) > self._max_intervals else []
                occ_doc[link][owner] = {
                    "busy_s": _total(ivs),
                    "n_intervals": len(ivs),
                    "intervals": [[a, b]
                                  for a, b in ivs[-self._max_intervals:]],
                    "truncated": bool(dropped),
                    "dropped_s": _total(dropped),
                }
        step_durs = [float(e["dur_s"]) for e in events
                     if e.get("kind") == "step" and e.get("dur_s")]
        dropped = int(getattr(self._fr, "dropped_events", 0) or 0) \
            if self._fr is not None else 0
        dropped_delta = max(dropped - self._dropped_last, 0)
        self._dropped_last = dropped
        hists = {}
        for name in self._hist_names:
            m = self._reg.get(name)
            if not isinstance(m, StreamingHistogram):
                continue
            hists[name] = {
                "lo": m.lo, "hi": m.hi,
                "buckets_per_decade": m.buckets_per_decade,
                "series": [{"labels": labels, "state": m.state(**labels)}
                           for labels in m.labels_seen()],
            }
        return {
            "rank": self.rank,
            "step": int(step),
            "occupancy": occ_doc,
            "step_durs": step_durs,
            "dropped_events": dropped_delta,
            "histograms": hists,
        }

    # ---- rank-0 fleet fold -------------------------------------------------

    def collect(self, step: int) -> Optional[dict]:
        """Gather every rank's summary to rank 0 and fold the fleet
        document.  Collective; returns the document on rank 0 only."""
        summary = self.local_summary(step)
        if self._plane is not None:
            gathered = self._plane.gather_telemetry(summary, root=0)
        elif hasattr(self._comm, "gather_obj"):
            gathered = self._comm.gather_obj(summary, root=0)
        else:
            gathered = [summary]
        if gathered is None:
            return None
        return self._fold(step, [s for s in gathered if s is not None])

    def _fold(self, step: int, summaries: List[dict]) -> dict:
        # fleet occupancy: union each (link, owner) across ranks, then
        # the live overlap matrix on the merged timelines
        timelines: Dict[str, Dict[str, list]] = {}
        per_rank_busy: Dict[str, dict] = {}
        dropped_s: Dict[str, Dict[str, float]] = {}
        for s in summaries:
            for link, owners in s.get("occupancy", {}).items():
                for owner, row in owners.items():
                    timelines.setdefault(link, {}).setdefault(
                        owner, []).extend(
                        tuple(iv) for iv in row.get("intervals", []))
                    per_rank_busy.setdefault(link, {}).setdefault(
                        owner, {})[str(s["rank"])] = row.get("busy_s", 0.0)
                    if row.get("truncated"):
                        cell = dropped_s.setdefault(link, {})
                        cell[owner] = cell.get(owner, 0.0) \
                            + float(row.get("dropped_s", 0.0))
        timelines = {link: {o: _merge(ivs) for o, ivs in owners.items()}
                     for link, owners in timelines.items()}
        matrix = contention.overlap_matrix(timelines)
        # union busy_s / the live matrix only see the SHIPPED intervals;
        # a truncated (link, owner) makes both lower bounds for this
        # window, so the fold says so instead of undercounting silently
        truncated = sorted(
            [link, owner]
            for link, owners in dropped_s.items() for owner in owners)
        occupancy_doc = {
            link: {owner: dict(
                       {"busy_s": _total(ivs),
                        "by_rank": per_rank_busy[link][owner]},
                       **({"truncated": True,
                           "dropped_s": dropped_s[link][owner]}
                          if owner in dropped_s.get(link, {}) else {}))
                   for owner, ivs in sorted(timelines[link].items())}
            for link in sorted(timelines)}

        # straggler flags: mean step time vs the fleet median of means
        means = {s["rank"]: (sum(s["step_durs"]) / len(s["step_durs"]))
                 for s in summaries if s.get("step_durs")}
        stragglers = []
        if len(means) >= 2:
            ordered = sorted(means.values())
            median = ordered[len(ordered) // 2]
            if median > 0:
                stragglers = sorted(
                    r for r, m in means.items()
                    if m > self._straggler_factor * median)

        # fleet-merged serving histograms -> SLO percentiles; publish
        # the percentiles back into the registry as fleet gauges so the
        # Prometheus sink exposes them on the next snapshot
        slo: Dict[str, dict] = {}
        for name in self._hist_names:
            grids = [s["histograms"][name] for s in summaries
                     if name in s.get("histograms", {})]
            if not any(g["series"] for g in grids):
                continue
            g0 = grids[0]
            fleet = StreamingHistogram(
                name, lo=g0["lo"], hi=g0["hi"],
                buckets_per_decade=g0["buckets_per_decade"])
            for g in grids:
                for series in g["series"]:
                    fleet.merge(series["state"], **series["labels"])
            counts = [0] * (len(fleet.bounds) + 1)
            total = 0
            total_sum = 0.0
            for labels in fleet.labels_seen():
                st = fleet.state(**labels)
                for i, c in enumerate(st["counts"]):
                    counts[i] += c
                total += st["count"]
                total_sum += st["sum"]
            quantiles = {
                f"p{int(q * 100)}": fleet._quantile_from_counts(counts, q)
                for q in _SLO_QUANTILES}
            slo[name] = {"count": total, "sum": total_sum,
                         "quantiles": quantiles}
            gauge = self._reg.gauge(
                f"fleet_{name}", f"fleet percentile of {name}")
            for label, v in quantiles.items():
                if v is not None:
                    gauge.set(v, quantile=label)

        return {
            "kind": "fleet_telemetry",
            "schema": SCHEMA,
            "step": int(step),
            "n_ranks": len(summaries),
            "occupancy": occupancy_doc,
            "truncated": truncated,
            "overlap": contention._matrix_rows(matrix),
            "step_time": {str(r): m for r, m in sorted(means.items())},
            "stragglers": stragglers,
            "dropped_events": sum(int(s.get("dropped_events", 0))
                                  for s in summaries),
            "slo": slo,
        }


__all__ = [
    "DEFAULT_HISTOGRAMS",
    "SCHEMA",
    "TelemetryAggregator",
]
