"""Run ledger — the longitudinal layer over the repo's run artifacts.

Every bench/train/serving run in this repo publishes a JSON artifact
(an ``ALLREDUCE_SWEEP_r*.json``, a ``SERVING_r*.json``, ...).  Within a
run the observability stack is deep (attribution, contention, fleet
telemetry); ACROSS runs there was nothing: ~40 committed artifacts with
no common envelope, so "has the substrate under this gated claim
drifted?" (ROADMAP item 5's standing caveat — every r06+ win is
modeled, not measured) could not even be asked mechanically.

This module supplies the three pieces:

* :func:`stamp_envelope` — the common artifact envelope (``schema``,
  ``schema_version``, ``device_kind``, ``n_devices``, ``backend``,
  ``git_sha``) every writer stamps on its document;
* :func:`classify_artifact` — maps ANY committed artifact, enveloped or
  r01–r05-era legacy (``suite``-keyed, ``bench``-keyed, bare driver
  logs), to a registered schema name — unknown shapes return ``None``
  and the census test keeps them from landing silently;
* :class:`RunLedger` — an append-only JSONL ledger of
  ``run_manifest/v1`` records (one per artifact: schema, device kind,
  git sha, topology, plan-table hash, modeled-vs-measured link rates,
  headline metrics), with :func:`ingest_artifacts` backfilling every
  existing committed r-artifact and per-``(device_kind, schema)``
  baseline selection for ``tools/perf_gate.py --ledger``.

``tools/ledger.py`` is the CLI (``ingest`` / ``diff`` / ``trend``);
:mod:`~chainermn_tpu.observability.diffing` consumes two runs' worth of
flight spans and localizes a regression to an attribution bucket.
"""

from __future__ import annotations

import fnmatch
import glob
import hashlib
import json
import os
import re
import subprocess
from typing import Dict, Iterable, List, Optional, Tuple

SCHEMA = "run_manifest/v1"
LEDGER_SCHEMA = "run_ledger/v1"

#: the uniform envelope every JSON writer stamps (satellite 1)
ENVELOPE_FIELDS = ("schema", "schema_version", "device_kind",
                   "n_devices", "backend", "git_sha")

#: every schema a committed artifact may declare.  Adding a writer means
#: adding its schema here — the artifact-census test walks the repo root
#: and fails on any artifact that maps to nothing.
KNOWN_SCHEMAS = {
    # enveloped (modern) writers
    "allreduce_sweep/v1",
    "alltoall_sweep/v1",
    "plan_table/v1",
    "planner_gate/v1",
    "online_tune/v1",
    "tracing_overhead/v1",
    "bench_serving/v1",
    "bench_serving/v2",
    "moe_sweep/v1",
    "moe_bench/v1",
    "moe_gate/v1",
    "remat_tune/v1",
    "resnet_probe/v1",
    "perf_budgets/v1",
    "perf_gate/v1",
    "ledger_gate/v1",
    "flight_recorder/v1",
    "fleet_telemetry/v1",
    "contention/v1",
    "contention_smoke/v1",
    "joint_sweep/v1",
    "joint_plan_table/v1",
    "step_workload/v1",
    "attribution_smoke/v1",
    "bench_headline/v1",
    "cmn_lint/v1",
    "protocol_lint/v1",
    "db_overlap_check/v1",
    "restart_manifest/v1",
    "elastic_smoke/v1",
    # the longitudinal layer itself
    "run_manifest/v1",
    "run_ledger/v1",
    "run_diff/v1",
    # legacy (pre-envelope) shapes, named retroactively
    "tpu_smoke/v1",
    "convergence_ledger/v1",
    "collective_census/v1",
    "pallas_conv_probe/v1",
    "flash_64k_probe/v1",
    "bench_lm/v1",
    "bench_vit/v1",
    "bench_driver/v1",
    "multichip_log/v1",
    "run_configs/v1",
}

#: legacy ``suite`` marker -> retroactive schema name
_LEGACY_SUITES = {
    "tpu_smoke": "tpu_smoke/v1",
    "convergence_ledger": "convergence_ledger/v1",
    "collective_census": "collective_census/v1",
    "pallas_conv_probe": "pallas_conv_probe/v1",
    "flash_64k_probe": "flash_64k_probe/v1",
    "cmn_lint": "cmn_lint/v1",
}

#: legacy ``bench`` marker -> retroactive schema name
_LEGACY_BENCHES = {
    "benchmarks/bench_lm.py": "bench_lm/v1",
    "benchmarks/bench_vit.py": "bench_vit/v1",
}

#: repo-root filename globs the backfill ingester walks
ARTIFACT_PATTERNS = ("*_r*.json", "BENCH_*.json")

#: headline metric extraction per artifact schema — dotted paths into
#: the document.  Only scalars listed here become ledger ``metrics``
#: (trend/baseline material); everything else stays in the artifact.
_METRIC_PATHS: Dict[str, Dict[str, str]] = {
    "tracing_overhead/v1": {
        "tracing_overhead_pct": "tracing_overhead_pct"},
    "online_tune/v1": {"retune_speedup": "retune.best_speedup"},
    "bench_serving/v1": {
        "serving_tokens_per_sec": "continuous.tokens_per_sec",
        "serving_speedup": "speedup"},
    "bench_serving/v2": {
        "serving_tokens_per_sec": "continuous.tokens_per_sec",
        "serving_speedup": "speedup"},
    "moe_bench/v1": {"moe_final_loss": "moe.final_loss",
                     "dense_final_loss": "dense.final_loss"},
    "moe_gate/v1": {"moe_final_loss": "moe.final_loss"},
    "planner_gate/v1": {"tuned_wins": "tuned_wins", "cells": "cells"},
    "bench_driver/v1": {"headline": "parsed.value"},
    "bench_headline/v1": {"headline": "value"},
    "bench_vit/v1": {"vit_throughput": "official.value"},
    "bench_lm/v1": {"lm_throughput": "official.value"},
    "remat_tune/v1": {"fused_norm_speedup": "fused_norm.speedup"},
    "joint_sweep/v1": {
        "joint_schedule_speedup": "comparison.speedup"},
    "elastic_smoke/v1": {
        "async_ckpt_stall_ms": "async_ckpt.stall_ms",
        "elastic_resume_lost_steps": "chaos.lost_steps"},
}


# ---------------------------------------------------------------------------
# envelope
# ---------------------------------------------------------------------------

def schema_version(schema: Optional[str]) -> Optional[int]:
    """The integer version of a ``name/v<N>`` schema string."""
    if not schema:
        return None
    m = re.search(r"/v(\d+)$", schema)
    return int(m.group(1)) if m else None


_SHA_CACHE: Dict[str, Optional[str]] = {}


def git_sha(root: Optional[str] = None) -> Optional[str]:
    """HEAD commit of the repo at ``root`` (default: this file's repo);
    ``None`` outside a checkout or without git — the envelope is then
    stamped without provenance rather than the writer failing."""
    root = os.path.abspath(root or os.path.dirname(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__)))))
    if root not in _SHA_CACHE:
        try:
            out = subprocess.run(
                ["git", "-C", root, "rev-parse", "HEAD"],
                capture_output=True, text=True, timeout=10)
            _SHA_CACHE[root] = out.stdout.strip() \
                if out.returncode == 0 and out.stdout.strip() else None
        except Exception:
            _SHA_CACHE[root] = None
    return _SHA_CACHE[root]


def detect_device_kind() -> Optional[str]:
    """Device kind of the default jax backend (``device_kind`` when the
    runtime exposes one, else the platform name); ``None`` when jax is
    unavailable.  Used only as the stamp fallback — a writer that knows
    better passes ``device_kind=`` explicitly."""
    try:
        import jax
        dev = jax.devices()[0]
        return str(getattr(dev, "device_kind", None) or dev.platform)
    except Exception:
        return None


def stamp_envelope(doc: dict, schema: Optional[str] = None, *,
                   device_kind: Optional[str] = None,
                   n_devices: Optional[int] = None,
                   backend: Optional[str] = None,
                   root: Optional[str] = None) -> dict:
    """Stamp the common envelope onto ``doc`` in place (and return it).

    Present fields are never clobbered — a writer that already records
    ``backend``/``n_devices`` keeps its values; the stamp fills the
    gaps (``schema_version`` from the schema string, ``device_kind``
    from the live backend, ``git_sha`` from the checkout)."""
    if schema and not doc.get("schema"):
        doc["schema"] = schema
    if doc.get("schema") and doc.get("schema_version") is None:
        doc["schema_version"] = schema_version(doc["schema"])
    if device_kind is not None and doc.get("device_kind") is None:
        doc["device_kind"] = device_kind
    if doc.get("device_kind") is None:
        doc["device_kind"] = detect_device_kind()
    if n_devices is not None and doc.get("n_devices") is None:
        doc["n_devices"] = int(n_devices)
    if backend is not None and doc.get("backend") is None:
        doc["backend"] = backend
    if doc.get("git_sha") is None:
        doc["git_sha"] = git_sha(root)
    return doc


# ---------------------------------------------------------------------------
# classification — every committed artifact maps to a registered schema
# ---------------------------------------------------------------------------

def classify_artifact(doc, path: str = "") -> Optional[dict]:
    """Map one parsed artifact to its registered schema.

    Returns ``{"schema", "schema_version", "legacy"}`` — ``legacy`` is
    true when the artifact predates the envelope (its schema is
    inferred from shape, or it declares a schema but carries no
    ``git_sha``).  Unknown shapes and undeclared schemas return
    ``None``: the caller (census test, backfill, artifact-drift lint)
    decides how loudly to complain."""
    if isinstance(doc, list):
        # RUN_CONFIGS_r05.json — a bare list of config rows
        if doc and isinstance(doc[0], dict) \
                and {"config", "metric", "value"} <= set(doc[0]):
            return {"schema": "run_configs/v1", "schema_version": 1,
                    "legacy": True}
        return None
    if not isinstance(doc, dict):
        return None
    declared = doc.get("schema")
    if declared:
        if declared not in KNOWN_SCHEMAS:
            return None
        return {"schema": declared,
                "schema_version": doc.get("schema_version")
                or schema_version(declared),
                "legacy": doc.get("git_sha") is None}
    for marker, table in (("kind", None), ("suite", _LEGACY_SUITES),
                          ("bench", _LEGACY_BENCHES)):
        val = doc.get(marker)
        if not isinstance(val, str):
            continue
        if table is None:           # "kind": already a schema-shaped name
            schema = val if val in KNOWN_SCHEMAS else None
        else:
            schema = table.get(val)
        if schema:
            return {"schema": schema,
                    "schema_version": schema_version(schema),
                    "legacy": True}
    keys = set(doc)
    if {"n", "cmd", "rc", "tail"} <= keys:
        return {"schema": "bench_driver/v1", "schema_version": 1,
                "legacy": True}
    if {"n_devices", "rc", "ok", "tail"} <= keys:
        return {"schema": "multichip_log/v1", "schema_version": 1,
                "legacy": True}
    return None


# ---------------------------------------------------------------------------
# manifest extraction
# ---------------------------------------------------------------------------

def _round_of(path: str) -> Optional[str]:
    m = re.search(r"_r(\d+)", os.path.basename(path))
    return f"r{int(m.group(1)):02d}" if m else None


def _dig(doc, dotted: str):
    cur = doc
    for part in dotted.split("."):
        if not isinstance(cur, dict) or part not in cur:
            return None
        cur = cur[part]
    return cur


def _modeled_rates(doc: dict) -> Dict[str, float]:
    rates: Dict[str, float] = {}
    lg = doc.get("link_gbps")
    if isinstance(lg, dict):
        rates.update({str(k): float(v) for k, v in lg.items()
                      if isinstance(v, (int, float))})
    if isinstance(doc.get("dcn_gbps"), (int, float)):
        rates.setdefault("dcn", float(doc["dcn_gbps"]))
    rep = doc.get("report")
    if isinstance(rep, dict):
        for link, row in (rep.get("rates") or {}).items():
            if isinstance(row, dict) \
                    and isinstance(row.get("modeled_gbps"), (int, float)):
                rates.setdefault(str(link), float(row["modeled_gbps"]))
    return rates


def _measured_rates(doc: dict) -> Dict[str, float]:
    rates: Dict[str, float] = {}
    obs = doc.get("observed_gbps")
    if isinstance(obs, dict):
        rates.update({str(k): float(v) for k, v in obs.items()
                      if isinstance(v, (int, float))})
    rep = doc.get("report")
    if isinstance(rep, dict):
        for link, row in (rep.get("rates") or {}).items():
            if isinstance(row, dict) \
                    and isinstance(row.get("effective_gbps"),
                                   (int, float)):
                rates.setdefault(str(link), float(row["effective_gbps"]))
    return rates


def _plan_table_hash(doc: dict) -> Optional[str]:
    h = _dig(doc, "retune.table_hash")
    if isinstance(h, str):
        return h
    if doc.get("schema") == "plan_table/v1":
        blob = json.dumps(doc.get("entries"), sort_keys=True,
                          separators=(",", ":"))
        return hashlib.sha256(blob.encode()).hexdigest()[:16]
    return None


def _device_kind_of(doc: dict) -> Optional[str]:
    dk = doc.get("device_kind")
    if isinstance(dk, str):
        return dk
    backend = doc.get("backend")
    if backend == "cpu":
        # every CPU-backend artifact shares one substrate; a TPU
        # artifact without a device_kind stays unresolved (v4 vs v5
        # baselines must never cross)
        return "cpu"
    return None


def build_manifest(doc, path: str, *, root: Optional[str] = None,
                   classification: Optional[dict] = None) -> dict:
    """One ``run_manifest/v1`` record for a parsed artifact.

    ``git_sha`` prefers the artifact's own stamp (``git_sha_source:
    "artifact"``); a legacy artifact gets the ingesting checkout's HEAD
    (``"ingest"``) so the record is at least anchored to when it was
    registered, never silently unanchored."""
    cls = classification or classify_artifact(doc, path)
    rel = os.path.relpath(os.path.abspath(path),
                          os.path.abspath(root)) if root else path
    d = doc if isinstance(doc, dict) else {}
    own_sha = d.get("git_sha")
    manifest = {
        "schema": SCHEMA,
        "schema_version": 1,
        "artifact": rel,
        "round": _round_of(path),
        "artifact_schema": cls["schema"] if cls else None,
        "artifact_schema_version": cls["schema_version"] if cls else None,
        "legacy_envelope": bool(cls["legacy"]) if cls else True,
        "device_kind": _device_kind_of(d),
        "n_devices": d.get("n_devices")
        if isinstance(d.get("n_devices"), int) else None,
        "backend": d.get("backend"),
        "git_sha": own_sha or git_sha(root),
        "git_sha_source": "artifact" if own_sha else "ingest",
        "topology": d.get("topology") or _dig(d, "meta.topology"),
        "plan_table_hash": _plan_table_hash(d),
        "link_gbps_modeled": _modeled_rates(d),
        "link_gbps_measured": _measured_rates(d),
        "metrics": {},
        "timestamp": d.get("timestamp"),
    }
    if d.get("noise_dominated") is not None:
        # a noise-guarded measurement (bench_allreduce --traced): the
        # record stays in the trend, but baseline() skips it
        manifest["noise_dominated"] = bool(d["noise_dominated"])
    if cls:
        for metric, dotted in _METRIC_PATHS.get(cls["schema"],
                                                {}).items():
            val = _dig(d, dotted)
            if isinstance(val, (int, float)) \
                    and not isinstance(val, bool):
                manifest["metrics"][metric] = float(val)
        if cls["schema"] == "tracing_overhead/v1" \
                and "noise_dominated" not in manifest \
                and manifest["metrics"].get(
                    "tracing_overhead_pct", 0.0) < 0:
            # pre-guard artifact publishing a negative overhead: hooks
            # cannot speed a program up, so the value is measurement
            # noise — keep it out of baseline selection
            manifest["noise_dominated"] = True
    slo = d.get("slo")
    if isinstance(slo, dict):
        manifest["histograms"] = {
            name: row.get("quantiles", {})
            for name, row in slo.items() if isinstance(row, dict)}
    return manifest


# ---------------------------------------------------------------------------
# the ledger
# ---------------------------------------------------------------------------

class RunLedger:
    """Append-only run ledger.

    ``path=None`` keeps the ledger in memory (tests, one-shot
    snapshots); with a path every :meth:`append` also appends one JSON
    line to the file, and construction replays existing lines — the
    file IS the ledger, restarts lose nothing."""

    def __init__(self, path: Optional[str] = None):
        self.path = path
        self._records: List[dict] = []
        if path and os.path.exists(path):
            with open(path) as fh:
                for line in fh:
                    line = line.strip()
                    if line:
                        self._records.append(json.loads(line))

    def __len__(self) -> int:
        return len(self._records)

    def append(self, manifest: dict) -> dict:
        if manifest.get("schema") != SCHEMA:
            raise ValueError(
                f"ledger records must be {SCHEMA} documents, got "
                f"schema={manifest.get('schema')!r}")
        self._records.append(manifest)
        if self.path:
            os.makedirs(os.path.dirname(os.path.abspath(self.path)),
                        exist_ok=True)
            with open(self.path, "a") as fh:
                fh.write(json.dumps(manifest, sort_keys=True) + "\n")
        return manifest

    # -- queries ---------------------------------------------------------

    def records(self, artifact_schema: Optional[str] = None,
                device_kind: Optional[str] = None) -> List[dict]:
        out = list(self._records)
        if artifact_schema is not None:
            out = [r for r in out
                   if r.get("artifact_schema") == artifact_schema]
        if device_kind is not None:
            out = [r for r in out
                   if r.get("device_kind") == device_kind]
        return out

    @staticmethod
    def _order(rec: dict) -> tuple:
        return (rec.get("round") or "", rec.get("timestamp") or "")

    def latest(self, artifact_schema: str,
               device_kind: Optional[str] = None) -> Optional[dict]:
        rows = self.records(artifact_schema, device_kind)
        return max(rows, key=self._order) if rows else None

    def baseline(self, artifact_schema: str, device_kind: Optional[str],
                 metric: str, direction: str = "higher",
                 exclude_artifact: Optional[str] = None
                 ) -> Optional[dict]:
        """The baseline record for one ``(device_kind, schema)`` cell:
        among that cell's records carrying ``metric``, the best value
        seen (``direction`` as in perf_budgets: the side that counts as
        good).  ``exclude_artifact`` keeps the run under test from
        being its own baseline; records flagged ``noise_dominated``
        stay in the trend but never become the bar other runs are held
        to."""
        rows = [r for r in self.records(artifact_schema, device_kind)
                if metric in r.get("metrics", {})
                and r.get("artifact") != exclude_artifact
                and not r.get("noise_dominated")]
        if not rows:
            return None
        key = (lambda r: r["metrics"][metric])
        return (max if direction == "higher" else min)(rows, key=key)

    def trend(self, metric: str,
              artifact_schema: Optional[str] = None,
              device_kind: Optional[str] = None) -> List[dict]:
        rows = [r for r in self.records(artifact_schema, device_kind)
                if metric in r.get("metrics", {})]
        rows.sort(key=self._order)
        return [{"round": r.get("round"), "artifact": r.get("artifact"),
                 "device_kind": r.get("device_kind"),
                 "artifact_schema": r.get("artifact_schema"),
                 "git_sha": r.get("git_sha"),
                 "value": r["metrics"][metric]} for r in rows]

    def cells(self) -> Dict[Tuple[Optional[str], Optional[str]], int]:
        """Record counts per ``(device_kind, artifact_schema)`` — the
        baseline-selection grid."""
        out: Dict[Tuple[Optional[str], Optional[str]], int] = {}
        for r in self._records:
            k = (r.get("device_kind"), r.get("artifact_schema"))
            out[k] = out.get(k, 0) + 1
        return out

    # -- snapshot --------------------------------------------------------

    def to_doc(self) -> dict:
        doc = {
            "schema": LEDGER_SCHEMA,
            "schema_version": 1,
            "n_records": len(self._records),
            "cells": [{"device_kind": dk, "artifact_schema": s,
                       "n": n}
                      for (dk, s), n in sorted(
                          self.cells().items(),
                          key=lambda kv: (str(kv[0][0]),
                                          str(kv[0][1])))],
            "records": list(self._records),
        }
        return stamp_envelope(doc)

    @classmethod
    def from_doc(cls, doc: dict) -> "RunLedger":
        if doc.get("schema") != LEDGER_SCHEMA:
            raise ValueError(
                f"not a {LEDGER_SCHEMA} document: "
                f"schema={doc.get('schema')!r}")
        led = cls()
        led._records = list(doc.get("records", []))
        return led

    @classmethod
    def load(cls, path: str) -> "RunLedger":
        """A ledger from either its JSONL file or a committed
        ``run_ledger/v1`` snapshot document."""
        with open(path) as fh:
            head = fh.read(1)
        if not head:
            return cls(path)
        with open(path) as fh:
            first_line = fh.readline()
        try:
            first = json.loads(first_line)
        except json.JSONDecodeError:
            first = None
        if isinstance(first, dict) and first.get("schema") == SCHEMA:
            return cls(path)            # JSONL of manifests
        with open(path) as fh:
            return cls.from_doc(json.load(fh))


# ---------------------------------------------------------------------------
# backfill
# ---------------------------------------------------------------------------

def iter_artifacts(root: str,
                   patterns: Iterable[str] = ARTIFACT_PATTERNS
                   ) -> List[str]:
    """Committed artifact paths under ``root`` (non-recursive — the
    convention is repo-root artifacts), sorted, deduplicated."""
    seen = {}
    for pat in patterns:
        for p in glob.glob(os.path.join(root, pat)):
            if os.path.isfile(p):
                seen[os.path.abspath(p)] = None
    return sorted(seen)


def ingest_artifacts(root: str, ledger: Optional[RunLedger] = None,
                     patterns: Iterable[str] = ARTIFACT_PATTERNS
                     ) -> Tuple[List[dict], List[dict]]:
    """Backfill: register every committed artifact under ``root``.

    Returns ``(manifests, problems)`` — a problem row is an unreadable
    or unknown-schema artifact (``{"artifact", "reason"}``).  Problems
    are reported, never appended: the ledger stays a registry of
    classified runs."""
    ledger = ledger if ledger is not None else RunLedger()
    manifests: List[dict] = []
    problems: List[dict] = []
    for path in iter_artifacts(root, patterns):
        rel = os.path.relpath(path, root)
        try:
            with open(path) as fh:
                doc = json.load(fh)
        except Exception as e:  # noqa: BLE001 — unreadable is a finding
            problems.append({"artifact": rel,
                             "reason": f"unreadable: {e}"})
            continue
        cls = classify_artifact(doc, path)
        if cls is None:
            declared = doc.get("schema") if isinstance(doc, dict) \
                else None
            problems.append({
                "artifact": rel,
                "reason": (f"undeclared schema {declared!r}"
                           if declared else "unknown artifact shape")})
            continue
        manifests.append(ledger.append(
            build_manifest(doc, path, root=root, classification=cls)))
    return manifests, problems


def matches_patterns(path: str,
                     patterns: Iterable[str] = ARTIFACT_PATTERNS) -> bool:
    name = os.path.basename(path)
    return any(fnmatch.fnmatch(name, pat) for pat in patterns)


__all__ = [
    "ARTIFACT_PATTERNS",
    "ENVELOPE_FIELDS",
    "KNOWN_SCHEMAS",
    "LEDGER_SCHEMA",
    "RunLedger",
    "SCHEMA",
    "build_manifest",
    "classify_artifact",
    "detect_device_kind",
    "git_sha",
    "ingest_artifacts",
    "iter_artifacts",
    "matches_patterns",
    "schema_version",
    "stamp_envelope",
]
