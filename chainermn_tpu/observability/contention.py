"""Link-contention observatory — who occupied each link class, when,
and what the overlap cost.

Every subsystem issues its own tuned collectives — FSDP prefetch
gathers, MoE all-to-alls, serving multicasts, plan-compiled allreduce
hops, online-tune control traffic — and each is priced and observed in
isolation.  The attribution span trees (:mod:`.attribution`) already
record the real concurrency; this module re-cuts them per *physical
link class* instead of per step:

* :func:`occupancy_timelines` — busy intervals per ici/dcn link keyed
  by owning subsystem (``fsdp`` / ``moe`` / ``serving`` /
  ``plan:<scope>`` / ``control`` / ``collective``), merged across
  ranks (feed it :func:`~.attribution.merge_ranks` output so all
  timestamps share rank 0's timebase);
* :func:`overlap_matrix` — pairwise contended seconds between owners
  on the same link class: the evidence a contention-aware scheduler
  (ROADMAP item 4) needs before it can exist;
* :func:`link_rates` — effective vs modeled GB/s per link under
  overlap.  *Modeled* prices every span alone (bytes / its own
  duration, overlap double-counted — exactly what per-span tuning
  assumes); *effective* is bytes over the union busy window (what the
  link actually delivered per wall-second).  The ratio is the
  contention derate, and :func:`feed_link_observations` pushes the
  effective rates into the online tuner's
  :class:`~chainermn_tpu.planner.online.LinkObservations` so re-tuning
  prices links at their contended rates (ROADMAP item 5 calibration);
* :func:`attribution_consistency` — per (rank, step, link): the
  occupancy union must reconcile exactly with the ici_comm/dcn_comm
  attribution buckets once the higher-priority shave
  (checkpoint > dcn > ici) is added back.  The CONTENTION runbook leg
  asserts this;
* :func:`contention_report` — the ``contention/v1`` document
  ``tools/obs_report.py --contention`` renders and
  ``tools/contention_smoke.py`` commits as ``CONTENTION_r16.json``.

Double-count guard: a trace-time ``collective`` span *contains* its
plan-stage children — the same wire traffic recorded twice — so
unioning both under different owners would manufacture fake
self-contention.  Occupancy therefore drops those wrapper parents
(:func:`leaf_comm_spans`).  The guard is deliberately narrow: only a
same-rank known *decomposition* pair (a ``collective`` wrapper over its
``plan_stage`` stages or a nested instrumented call, an ``object`` op
over the ops composing it) marks a parent; mere time-containment — one
rank's FSDP gather spanning another subsystem's hop, on the same rank
or across ranks — is genuine concurrency and is KEPT, because that is
exactly the contention this module exists to measure.  The consistency
check uses the full classified union on purpose — that is what
:func:`~.attribution.attribute_step` buckets.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

from chainermn_tpu.observability.attribution import (
    _clip, _merge, _subtract, _total, attribute_step, classify_span,
    merge_ranks)
from chainermn_tpu.observability.spans import Span, pair_events

#: the physical link classes occupancy is cut by
LINK_CLASSES = ("ici", "dcn")

_EPS = 1e-9

_Interval = Tuple[float, float]


# ---------------------------------------------------------------------------
# span classification: link class + owning subsystem
# ---------------------------------------------------------------------------

def span_link(span: Span) -> Optional[str]:
    """Link class a span occupies (``"ici"`` / ``"dcn"``), or ``None``
    for non-comm spans.  Derived from the same classification the
    attribution buckets use, so occupancy and buckets cut the same
    spans."""
    bucket = classify_span(span)
    if bucket == "ici_comm":
        return "ici"
    if bucket == "dcn_comm":
        return "dcn"
    return None


def span_owner(span: Span) -> Optional[str]:
    """Owning subsystem of a comm span: which tuner/issuer put that
    traffic on the link.

    * ``fsdp`` — bucketed-FSDP gathers/scatters;
    * ``moe`` — all-to-all dispatch/combine plan stages
      (``alltoall_*`` plans);
    * ``serving`` — serving engine spans and ``serving*`` plans
      (weight multicast, decode collectives);
    * ``plan:<scope>`` — any other compiled plan stage, keyed by its
      hop scope (``intra``/``inter``/``all``);
    * ``control`` — object-plane traffic (plan-table broadcasts,
      checkpoints' metadata, the control plane itself);
    * ``collective`` — a bare trace-time collective span with no plan
      decomposition under it (the flat pre-planner path).
    """
    if span.kind == "fsdp":
        return "fsdp"
    if span.kind == "serving":
        return "serving"
    if span.kind == "object":
        return "control"
    if span.kind == "plan_stage":
        plan = str(span.meta.get("plan") or "")
        if plan.startswith("alltoall"):
            return "moe"
        if plan.startswith("serving"):
            return "serving"
        return f"plan:{span.meta.get('scope', '?')}"
    if span.kind == "collective":
        return "collective"
    if span_link(span) is not None:
        return span.kind or "?"
    return None


#: the workload tag a jointly-tuned plan name carries
#: (``<base>@wl:<signature>`` — written by ``planner.schedule.tag_plan``;
#: the literal is duplicated here so observability does not import the
#: planner, and ``tests/test_planner.py`` pins the two in sync)
_WORKLOAD_TAG = "@wl:"


def plan_identity(span: Span) -> Optional[str]:
    """Tuning identity of a comm span — spans sharing an identity were
    tuned TOGETHER (a striped plan's concurrent groups share a plan
    name: their ratio split is one co-tuned decision; plans co-tuned in
    one ``StepWorkload`` share the workload signature their ``@wl:``
    name tag carries), spans with different identities were tuned
    independently.  The ``overlapping-collectives`` lint keys on this,
    so a joint schedule's deliberate cross-communicator overlap is
    exempt exactly like one striped plan's concurrent groups."""
    if span.kind == "plan_stage":
        plan = span.meta.get("plan")
        if plan is not None:
            _base, sep, sig = str(plan).partition(_WORKLOAD_TAG)
            if sep and sig:
                return f"workload:{sig}"
            return f"plan:{plan}"
        return "plan:?"
    if span.kind == "fsdp":
        return "fsdp"
    if span.kind == "collective":
        return f"collective:{span.meta.get('op', '?')}"
    if span.kind == "object":
        return f"object:{span.meta.get('op', '?')}"
    if span.kind == "serving":
        return f"serving:{span.meta.get('op', '?')}"
    if span_link(span) is not None:
        return span.kind or "?"
    return None


#: (parent kind, child kind) pairs that are true traffic
#: decompositions: the parent is a host-side wrapper whose wire bytes
#: its contained child re-emits.  A trace-time ``collective`` covers
#: the ``plan_stage`` edges of its own compiled plan (and a nested
#: instrumented call); a control-plane ``object`` op covers the object
#: ops it is composed of.  Everything else that merely time-contains a
#: comm span — an FSDP gather spanning a MoE hop — is independent
#: traffic contending for the link, not a re-count of it.
_DECOMPOSITION = frozenset({
    ("collective", "plan_stage"),
    ("collective", "collective"),
    ("object", "object"),
})


def leaf_comm_spans(spans: Sequence[Span]) -> List[Span]:
    """Comm spans minus wrapper parents whose traffic a contained span
    re-emits — the double-count guard.

    A span is dropped ONLY when, on the SAME rank, it time-contains
    another comm span in a known decomposition relationship
    (:data:`_DECOMPOSITION` — e.g. a trace-time ``collective`` wrapper
    over its ``plan_stage`` children).  Plain containment is NOT
    parenthood: a rank-0 FSDP gather that happens to span a rank-1 MoE
    all-to-all, or a same-rank gather spanning a concurrent hop of
    another subsystem, is genuine concurrency — dropping either side
    would erase the very contention signal occupancy exists to
    measure.  Works on flat :func:`~.spans.pair_events` output and on
    tree walks alike (a per-rank stack sweep over ``(t0, -t1)``
    order)."""
    comm = [sp for sp in spans if span_link(sp) is not None]
    non_leaf = set()
    by_rank: Dict[int, List[Span]] = {}
    for sp in comm:
        by_rank.setdefault(sp.rank, []).append(sp)
    for rank_spans in by_rank.values():
        rank_spans.sort(key=lambda s: (s.t0, -s.t1))
        stack: List[Span] = []
        for sp in rank_spans:
            while stack and not (sp.t0 >= stack[-1].t0 - _EPS
                                 and sp.t1 <= stack[-1].t1 + _EPS):
                stack.pop()
            for anc in stack:
                if (anc.kind, sp.kind) in _DECOMPOSITION:
                    non_leaf.add(id(anc))
            stack.append(sp)
    comm.sort(key=lambda s: (s.t0, -s.t1))
    return [sp for sp in comm if id(sp) not in non_leaf]


def _tree_spans(trees_by_rank: Dict[int, List[Span]]) -> List[Span]:
    return [sp for trees in trees_by_rank.values()
            for tree in trees for sp in tree.walk()]


# ---------------------------------------------------------------------------
# interval helpers on top of attribution's arithmetic
# ---------------------------------------------------------------------------

def _intersect(a: List[_Interval], b: List[_Interval]) -> List[_Interval]:
    """``a ∩ b``; both merged ascending."""
    out: List[_Interval] = []
    i = j = 0
    while i < len(a) and j < len(b):
        lo = max(a[i][0], b[j][0])
        hi = min(a[i][1], b[j][1])
        if hi > lo:
            out.append((lo, hi))
        if a[i][1] < b[j][1]:
            i += 1
        else:
            j += 1
    return out


# ---------------------------------------------------------------------------
# occupancy timelines + overlap matrix
# ---------------------------------------------------------------------------

def occupancy_timelines(trees_by_rank: Dict[int, List[Span]]
                        ) -> Dict[str, Dict[str, List[_Interval]]]:
    """``{link: {owner: merged busy intervals}}`` over every rank's
    leaf comm spans.  Trees must already share a timebase
    (:func:`~.attribution.merge_ranks` applies the clock-handshake
    offsets) — occupancy is a property of the *link*, not of any one
    rank's clock."""
    out: Dict[str, Dict[str, List[_Interval]]] = {}
    for sp in leaf_comm_spans(_tree_spans(trees_by_rank)):
        link, owner = span_link(sp), span_owner(sp)
        if link is None or owner is None:
            continue
        out.setdefault(link, {}).setdefault(owner, []).append(
            (sp.t0, sp.t1))
    return {link: {owner: _merge(ivs) for owner, ivs in owners.items()}
            for link, owners in out.items()}


def overlap_matrix(timelines: Dict[str, Dict[str, List[_Interval]]]
                   ) -> Dict[str, Dict[Tuple[str, str], float]]:
    """Pairwise contended seconds between owners sharing a link class:
    ``{link: {(owner_a, owner_b): seconds}}`` with ``owner_a <
    owner_b`` and zero-overlap pairs dropped."""
    out: Dict[str, Dict[Tuple[str, str], float]] = {}
    for link, owners in timelines.items():
        names = sorted(owners)
        cells: Dict[Tuple[str, str], float] = {}
        for i, a in enumerate(names):
            for b in names[i + 1:]:
                sec = _total(_intersect(owners[a], owners[b]))
                if sec > 0.0:
                    cells[(a, b)] = sec
        out[link] = cells
    return out


# ---------------------------------------------------------------------------
# effective vs modeled link rates under overlap
# ---------------------------------------------------------------------------

def link_rates(trees_by_rank: Dict[int, List[Span]],
               modeled_gbps: Optional[Dict[str, float]] = None
               ) -> Dict[str, dict]:
    """Per-link transfer accounting under overlap.

    For each link class: ``busy_s`` (union across owners), ``solo_s``
    vs ``contended_s`` (busy time with exactly one / more than one
    owner on the link), total ``bytes``, and three rates in GB/s:

    * ``modeled_gbps`` — bytes over the SUM of span durations: each
      span priced alone, concurrent seconds double-counted.  This is
      what per-span tuning (``LinkObservations.ingest_spans``) sees;
    * ``effective_gbps`` — bytes over the union busy window: what the
      link actually delivered per wall-second;
    * ``derate`` — effective / modeled (1.0 with no overlap; drops as
      contention stretches spans).

    ``modeled_gbps`` (the argument) optionally supplies static
    planner-table rates per link; when given, each link row also
    carries ``static_gbps`` and ``vs_static`` so the report shows
    effective-vs-modeled against the tuner's pricing too.
    """
    spans = [sp for sp in leaf_comm_spans(_tree_spans(trees_by_rank))]
    per_link: Dict[str, List[Span]] = {}
    for sp in spans:
        link = span_link(sp)
        if link is not None:
            per_link.setdefault(link, []).append(sp)
    timelines = occupancy_timelines(trees_by_rank)
    out: Dict[str, dict] = {}
    for link, link_spans in sorted(per_link.items()):
        owners = timelines.get(link, {})
        busy = _merge([iv for ivs in owners.values() for iv in ivs])
        contended: List[_Interval] = []
        names = sorted(owners)
        for i, a in enumerate(names):
            for b in names[i + 1:]:
                contended.extend(_intersect(owners[a], owners[b]))
        contended = _merge(contended)
        busy_s = _total(busy)
        contended_s = _total(contended)
        span_s = sum(sp.dur_s for sp in link_spans)
        nbytes = sum(int(sp.meta.get("nbytes") or 0) for sp in link_spans)
        modeled = nbytes / span_s / 1e9 if span_s > 0 else 0.0
        effective = nbytes / busy_s / 1e9 if busy_s > 0 else 0.0
        row = {
            "n_spans": len(link_spans),
            "bytes": nbytes,
            "span_s": span_s,
            "busy_s": busy_s,
            "solo_s": max(busy_s - contended_s, 0.0),
            "contended_s": contended_s,
            "modeled_gbps": modeled,
            "effective_gbps": effective,
            "derate": effective / modeled if modeled > 0 else 1.0,
        }
        if modeled_gbps and link in modeled_gbps:
            static = float(modeled_gbps[link])
            row["static_gbps"] = static
            row["vs_static"] = effective / static if static > 0 else 0.0
        out[link] = row
    return out


def feed_link_observations(observations, rates: Dict[str, dict]) -> None:
    """Push the contention-derated effective rates into an online
    tuner's :class:`~chainermn_tpu.planner.online.LinkObservations`:
    one aggregate (bytes, union-busy-seconds) sample per link, so
    ``observed_gbps`` prices links at what they deliver UNDER the
    measured overlap, not at per-span isolation rates."""
    for link, row in sorted(rates.items()):
        nbytes, busy_s = int(row.get("bytes", 0)), float(
            row.get("busy_s", 0.0))
        if nbytes > 0 and busy_s > 0.0:
            observations.add(link, nbytes, busy_s)


# ---------------------------------------------------------------------------
# consistency against the attribution buckets
# ---------------------------------------------------------------------------

_LINK_BUCKET = {"ici": "ici_comm", "dcn": "dcn_comm"}


def _step_link_intervals(step: Span) -> Dict[str, List[_Interval]]:
    """Per-link classified interval unions of one step tree, built the
    way :func:`~.attribution.attribute_step` builds its buckets (ALL
    classified spans, ancestors included) plus the checkpoint union —
    so the consistency check reconciles against identical geometry."""
    ivs: Dict[str, List[_Interval]] = {"ici": [], "dcn": [],
                                       "checkpoint": []}
    for sp in step.walk():
        if sp is step:
            continue
        bucket = classify_span(sp)
        if bucket == "ici_comm":
            ivs["ici"].append((sp.t0, sp.t1))
        elif bucket == "dcn_comm":
            ivs["dcn"].append((sp.t0, sp.t1))
        elif bucket == "checkpoint":
            ivs["checkpoint"].append((sp.t0, sp.t1))
    return {k: _clip(_merge(v), step.t0, step.t1) for k, v in ivs.items()}


def attribution_consistency(trees_by_rank: Dict[int, List[Span]],
                            tol: float = 1e-6) -> List[dict]:
    """Reconcile per-link occupancy with the attribution buckets, per
    (rank, step, link).

    The buckets are the occupancy minus the higher-priority shave
    (``dcn_comm = dcn − checkpoint``, ``ici_comm = ici − (checkpoint ∪
    dcn)``), so for every row::

        occupancy_s − shaved_s == bucket_s   (within tol)

    Returns one row per (rank, iteration, link) with ``ok`` per row —
    the CONTENTION smoke's acceptance assert.
    """
    rows: List[dict] = []
    for rank, trees in sorted(trees_by_rank.items()):
        for step in trees:
            attr = attribute_step(step)
            ivs = _step_link_intervals(step)
            ckpt = ivs["checkpoint"]
            higher = {"dcn": ckpt, "ici": _merge(ckpt + ivs["dcn"])}
            for link in LINK_CLASSES:
                occupancy_s = _total(ivs[link])
                if occupancy_s <= 0.0:
                    continue
                shaved_s = _total(_intersect(ivs[link], higher[link]))
                bucket_s = attr["buckets"][_LINK_BUCKET[link]]
                err = abs((occupancy_s - shaved_s) - bucket_s)
                rows.append({
                    "rank": rank,
                    "iteration": step.meta.get("iteration"),
                    "link": link,
                    "occupancy_s": occupancy_s,
                    "shaved_s": shaved_s,
                    "bucket_s": bucket_s,
                    "abs_err_s": err,
                    "ok": err <= tol,
                })
    return rows


# ---------------------------------------------------------------------------
# the contention/v1 report document
# ---------------------------------------------------------------------------

def _matrix_rows(matrix: Dict[str, Dict[Tuple[str, str], float]]
                 ) -> List[dict]:
    return [{"link": link, "owners": [a, b], "contended_s": sec}
            for link in sorted(matrix)
            for (a, b), sec in sorted(matrix[link].items())]


def contention_report(events_by_rank: Dict[int, List[dict]],
                      offsets: Optional[Dict[int, float]] = None,
                      modeled_gbps: Optional[Dict[str, float]] = None,
                      max_intervals: int = 256) -> dict:
    """The full observatory document from raw per-rank flight events:
    clock-corrected merge, per-(link, owner) occupancy timelines, the
    overlap matrix, effective-vs-modeled link rates, and the
    per-step attribution reconciliation.  Schema ``contention/v1``."""
    trees = merge_ranks(events_by_rank, offsets=offsets)
    timelines = occupancy_timelines(trees)
    matrix = overlap_matrix(timelines)
    rates = link_rates(trees, modeled_gbps=modeled_gbps)
    consistency = attribution_consistency(trees)
    tl_doc = {}
    for link in sorted(timelines):
        tl_doc[link] = {}
        for owner in sorted(timelines[link]):
            ivs = timelines[link][owner]
            tl_doc[link][owner] = {
                "busy_s": _total(ivs),
                "n_intervals": len(ivs),
                "intervals": [[a, b] for a, b in ivs[-max_intervals:]],
            }
    return {
        "kind": "contention_report",
        "schema": "contention/v1",
        "n_ranks": len(trees),
        "n_steps": sum(len(t) for t in trees.values()),
        "links": sorted(timelines),
        "timelines": tl_doc,
        "overlap": _matrix_rows(matrix),
        "rates": rates,
        "consistency": consistency,
        "consistency_ok": all(r["ok"] for r in consistency),
    }


# ---------------------------------------------------------------------------
# flat-event occupancy (the streaming aggregator's per-window cut)
# ---------------------------------------------------------------------------

def occupancy_from_events(events: Sequence[dict], rank: int = 0
                          ) -> Dict[str, Dict[str, List[_Interval]]]:
    """``{link: {owner: merged busy intervals}}`` from ONE rank's raw
    flight events (no step trees, no clock correction) — the compact
    per-window cut each rank ships over the control plane
    (:class:`~chainermn_tpu.observability.streaming.TelemetryAggregator`)."""
    spans = pair_events(list(events), rank=rank)
    out: Dict[str, Dict[str, List[_Interval]]] = {}
    for sp in leaf_comm_spans(spans):
        link, owner = span_link(sp), span_owner(sp)
        if link is None or owner is None:
            continue
        out.setdefault(link, {}).setdefault(owner, []).append(
            (sp.t0, sp.t1))
    return {link: {owner: _merge(ivs) for owner, ivs in owners.items()}
            for link, owners in out.items()}


__all__ = [
    "LINK_CLASSES",
    "attribution_consistency",
    "contention_report",
    "feed_link_observations",
    "leaf_comm_spans",
    "link_rates",
    "occupancy_from_events",
    "occupancy_timelines",
    "overlap_matrix",
    "plan_identity",
    "span_link",
    "span_owner",
]
