"""Collective hang watchdog — turns a silent wedge into a dump.

Companion to :mod:`chainermn_tpu.observability.flight_recorder`: a daemon
thread that watches the recorder for no-progress conditions and, when one
fires, writes ``flight_<rank>.json`` (ring buffer + all-thread stacks +
cross-rank collective state) instead of letting the run burn a TPU slice
silently.

Three stall predicates (all knobs are env-tunable, see
:class:`WatchdogConfig`):

* **collective deadline** — any tracked span (collective, object op, DCN
  recv, p2p) open longer than ``deadline_s``;
* **step stall** — no step completed for ``step_stall_factor`` x the
  trailing-median step time (catches device-side hangs inside the jitted
  step, where no host-side span is open);
* **heartbeat loss** — a peer controller stopped sending watchdog
  heartbeats over the DCN control plane (its process died or wedged
  below the GIL).

On stall the watchdog broadcasts its collective state to every peer on a
dedicated control-plane tag, collects their states for a bounded window,
and dumps with a desync analysis naming the rank(s) the world is waiting
for.  A rank *receiving* a peer's stall notice replies with its own state
and dumps too, so every reachable controller leaves an artifact —
``tools/obs_report.py --flight`` merges them.

Start it with :func:`start_watchdog`, which returns ``None`` when
observability is disabled: a disabled run starts **zero** watchdog
threads (pinned by tests/test_flight_recorder.py).
"""

from __future__ import annotations

import os
import pickle
import threading
import time
from dataclasses import dataclass, replace
from typing import Dict, List, Optional

from chainermn_tpu.observability import flight_recorder as _flight
from chainermn_tpu.runtime.control_plane import reserved_tag

# Dedicated control-plane tag namespace for watchdog traffic, claimed as
# the "flight" band in runtime.control_plane.RESERVED_TAG_BANDS.  Far
# above the collective tags (tag<~1000), the p2p grad tags (1<<20) and
# meta tags (1<<21), so watchdog messages never collide with training
# traffic.
FLIGHT_TAG = reserved_tag("flight")

_THREAD_PREFIX = "chainermn-tpu-watchdog"


def _env_float(env: Dict[str, str], name: str, default: float) -> float:
    raw = env.get(name)
    if raw is None or raw == "":
        return default
    try:
        return float(raw)
    except ValueError:
        return default


@dataclass(frozen=True)
class WatchdogConfig:
    """Watchdog knobs; each maps 1:1 to an env var so a launcher can tune
    a fleet without code changes (``CHAINERMN_TPU_WATCHDOG_*``,
    ``CHAINERMN_TPU_FLIGHT_DIR``).  ``from_env``/``to_env`` round-trip,
    which the multichip runbook's DRY_RUN asserts."""

    deadline_s: float = 300.0           # CHAINERMN_TPU_WATCHDOG_DEADLINE
    step_stall_factor: float = 8.0      # CHAINERMN_TPU_WATCHDOG_STEP_K
    heartbeat_interval_s: float = 10.0  # CHAINERMN_TPU_WATCHDOG_HEARTBEAT
    heartbeat_timeout_s: float = 30.0   # CHAINERMN_TPU_WATCHDOG_HB_TIMEOUT
    poll_interval_s: float = 1.0        # CHAINERMN_TPU_WATCHDOG_POLL
    collect_window_s: float = 2.0       # CHAINERMN_TPU_WATCHDOG_COLLECT
    max_dumps: int = 3                  # CHAINERMN_TPU_WATCHDOG_MAX_DUMPS
    out_dir: str = "."                  # CHAINERMN_TPU_FLIGHT_DIR

    _ENV = {
        "deadline_s": "CHAINERMN_TPU_WATCHDOG_DEADLINE",
        "step_stall_factor": "CHAINERMN_TPU_WATCHDOG_STEP_K",
        "heartbeat_interval_s": "CHAINERMN_TPU_WATCHDOG_HEARTBEAT",
        "heartbeat_timeout_s": "CHAINERMN_TPU_WATCHDOG_HB_TIMEOUT",
        "poll_interval_s": "CHAINERMN_TPU_WATCHDOG_POLL",
        "collect_window_s": "CHAINERMN_TPU_WATCHDOG_COLLECT",
        "max_dumps": "CHAINERMN_TPU_WATCHDOG_MAX_DUMPS",
        "out_dir": "CHAINERMN_TPU_FLIGHT_DIR",
    }

    # Timeout/interval knobs that must be > 0: a launcher exporting a
    # zero or negative value would not "turn the check off", it would
    # silently break the predicate (a <=0 deadline fires on every open
    # span; a <=0 heartbeat timeout declares every peer dead).  The
    # deliberate off-switch is CHAINERMN_TPU_WATCHDOG_HEARTBEAT<=0
    # (heartbeat_interval_s), which start() honors by not spawning the
    # heartbeat thread — so that one knob stays out of this set.
    _POSITIVE = ("deadline_s", "step_stall_factor",
                 "heartbeat_timeout_s", "poll_interval_s",
                 "collect_window_s")

    @classmethod
    def from_env(cls, env: Optional[Dict[str, str]] = None,
                 **overrides) -> "WatchdogConfig":
        env = os.environ if env is None else env
        base = cls()
        kw = {}
        for field, var in cls._ENV.items():
            if field == "out_dir":
                kw[field] = env.get(var) or base.out_dir
            elif field == "max_dumps":
                kw[field] = int(_env_float(env, var, base.max_dumps))
            else:
                val = _env_float(env, var, getattr(base, field))
                if field in cls._POSITIVE and val <= 0:
                    raise ValueError(
                        f"{var}={env.get(var)!r} parses to {val:g} — a "
                        f"non-positive value would silently break the "
                        f"{field} stall predicate instead of disabling "
                        f"it; set {var} to a positive number or unset "
                        f"it to use the default "
                        f"({getattr(base, field):g})")
                kw[field] = val
        kw.update(overrides)
        return cls(**kw)

    def to_env(self) -> Dict[str, str]:
        """The env mapping that reproduces this config via ``from_env``
        (``from_env(env=cfg.to_env()) == cfg``)."""
        out = {}
        for field, var in self._ENV.items():
            v = getattr(self, field)
            out[var] = v if isinstance(v, str) else repr(v)
        return out

    def as_dict(self) -> dict:
        return {f: getattr(self, f) for f in self._ENV}


class Watchdog:
    """The watchdog threads.  Use :func:`start_watchdog` rather than
    constructing directly — it owns the observability gating."""

    def __init__(self, recorder: _flight.FlightRecorder,
                 config: WatchdogConfig,
                 control_plane=None, rank: Optional[int] = None):
        self._rec = recorder
        self._cfg = config
        self._plane = control_plane
        self.rank = int(rank if rank is not None
                        else getattr(control_plane, "rank", 0) or 0)
        self.size = int(getattr(control_plane, "size", 1) or 1)
        # Peer exchange needs a transport with timed recv (the socket
        # control plane); anything else degrades to local-only dumps.
        self._tp = getattr(control_plane, "_tp", None)
        self._peers = [r for r in range(self.size) if r != self.rank] \
            if (self._tp is not None and self.size > 1) else []
        self._closed = threading.Event()
        self._threads: List[threading.Thread] = []
        self._trigger_lock = threading.Lock()
        self._incidents: set = set()
        self._peer_states: Dict[int, dict] = {}
        self._hb_seen: Dict[int, float] = {}
        # clock handshake state: per-peer (t_send, t_peer_wall, t_recv)
        # NTP samples collected by the listen threads, and the offsets
        # clock_sync() last derived from them (embedded in every dump so
        # attribution can merge cross-host timelines drift-corrected)
        self._clock_samples: Dict[int, List[tuple]] = {}
        self.clock_offsets: Dict[int, dict] = {}
        self._started_at = time.time()
        self.dump_paths: List[str] = []
        self.incidents: List[dict] = []

    # ---- lifecycle ---------------------------------------------------------
    def start(self) -> "Watchdog":
        self._started_at = time.time()
        self._spawn(self._monitor_loop, f"{_THREAD_PREFIX}-monitor")
        for src in self._peers:
            self._spawn(lambda s=src: self._listen_loop(s),
                        f"{_THREAD_PREFIX}-listen-{src}")
        if self._peers and self._cfg.heartbeat_interval_s > 0:
            self._spawn(self._heartbeat_loop, f"{_THREAD_PREFIX}-heartbeat")
        return self

    def _spawn(self, target, name):
        t = threading.Thread(target=target, name=name, daemon=True)
        t.start()
        self._threads.append(t)

    def stop(self, join_timeout: float = 5.0) -> None:
        self._closed.set()
        for t in self._threads:
            t.join(timeout=join_timeout)

    @property
    def stopped(self) -> bool:
        return self._closed.is_set()

    # ---- stall predicates --------------------------------------------------
    def _check(self) -> Optional[str]:
        now = time.time()
        for rec in self._rec.open_spans(now):
            if rec["age_s"] > self._cfg.deadline_s:
                label = ("collective_timeout"
                         if rec["kind"] in ("collective", "object")
                         else "span_timeout")
                return (f"{label}:{rec['op']} seq={rec['op_seq']} "
                        f"open {rec['age_s']:.1f}s "
                        f"(deadline {self._cfg.deadline_s:.1f}s)")
        med = self._rec.trailing_step_median()
        last_end = self._rec.last_step_end
        if (med is not None and last_end is not None
                and self._rec.steps >= 5):
            quiet = now - last_end
            limit = max(self._cfg.step_stall_factor * med,
                        2 * self._cfg.poll_interval_s)
            if quiet > limit:
                return (f"step_stall: no step for {quiet:.1f}s "
                        f"({self._cfg.step_stall_factor:g}x trailing "
                        f"median {med:.3f}s)")
        if self._peers and self._cfg.heartbeat_interval_s > 0:
            for src in self._peers:
                seen = self._hb_seen.get(src, self._started_at)
                if now - seen > self._cfg.heartbeat_timeout_s:
                    return (f"heartbeat_loss:rank{src} "
                            f"last seen {now - seen:.1f}s ago")
        return None

    # ---- threads -----------------------------------------------------------
    def _monitor_loop(self):
        while not self._closed.wait(self._cfg.poll_interval_s):
            try:
                reason = self._check()
            except Exception:
                continue
            if reason is not None:
                self._trigger(reason, broadcast=True)

    def _heartbeat_loop(self):
        while not self._closed.wait(self._cfg.heartbeat_interval_s):
            self._send_all({"kind": "hb", "rank": self.rank,
                            "ts": time.time()})

    def _listen_loop(self, src: int):
        while not self._closed.is_set():
            try:
                payload = self._tp.recv(src, FLIGHT_TAG,
                                        timeout=self._cfg.poll_interval_s)
            except TimeoutError:
                continue
            except Exception:
                if self._closed.is_set():
                    return
                time.sleep(self._cfg.poll_interval_s)
                continue
            try:
                msg = pickle.loads(payload)
            except Exception:
                continue
            kind = msg.get("kind")
            if kind == "hb":
                self._hb_seen[src] = time.time()
            elif kind == "stall":
                self._hb_seen[src] = time.time()
                self._peer_states[src] = msg.get("state", {})
                self._send(src, {"kind": "state_reply",
                                 "incident": msg.get("incident"),
                                 "rank": self.rank,
                                 "state": self._rec.collective_state()})
                self._trigger(f"peer_stall:rank{src} ({msg.get('reason')})",
                              broadcast=False,
                              incident=msg.get("incident"))
            elif kind == "state_reply":
                self._peer_states[src] = msg.get("state", {})
            elif kind == "clock_probe":
                self._send(src, {"kind": "clock_reply", "rank": self.rank,
                                 "probe": msg.get("probe"),
                                 "wall": time.time()})
            elif kind == "clock_reply":
                probe = msg.get("probe") or {}
                t_send = probe.get("t_send")
                if t_send is not None:
                    self._clock_samples.setdefault(src, []).append(
                        (float(t_send), float(msg.get("wall", 0.0)),
                         time.time()))

    # ---- the control-plane clock handshake ---------------------------------
    def clock_sync(self, rounds: int = 4,
                   window_s: Optional[float] = None) -> Dict[int, dict]:
        """Estimate per-peer wall-clock offsets over the watchdog's
        control-plane tag: each round sends a ``clock_probe`` stamped
        with the local send time, peers echo it back in a
        ``clock_reply`` carrying their wall clock, and the listen
        threads bank the ``(t_send, t_peer, t_recv)`` samples.  The NTP
        midpoint of the min-RTT sample per peer
        (:func:`~chainermn_tpu.observability.attribution.
        offset_from_samples`) gives ``local_ts + offset_s`` ≈ the same
        instant on that peer's clock — what the attribution merge and
        the straggler detector use instead of trusting raw wall clocks
        across hosts.  Best-effort: unreachable peers simply stay
        absent from the result."""
        from chainermn_tpu.observability.attribution import \
            offset_from_samples

        if not self._peers:
            self.clock_offsets = {}
            return {}
        if window_s is None:
            window_s = self._cfg.collect_window_s
        rounds = max(int(rounds), 1)
        for _ in range(rounds):
            self._send_all({"kind": "clock_probe", "rank": self.rank,
                            "probe": {"t_send": time.time()}})
            time.sleep(min(max(window_s, 0.05) / rounds, 0.25))
        deadline = time.time() + window_s
        while (time.time() < deadline and not self._closed.is_set()
               and any(not self._clock_samples.get(p)
                       for p in self._peers)):
            time.sleep(0.02)
        out: Dict[int, dict] = {}
        for p in self._peers:
            samples = self._clock_samples.get(p)
            if samples:
                off, rtt = offset_from_samples(samples)
                out[p] = {"offset_s": off, "rtt_s": rtt,
                          "samples": len(samples)}
        self.clock_offsets = out
        return out

    # ---- messaging (best-effort: a dead peer must not kill the dump) -------
    def _send(self, dest: int, msg: dict):
        try:
            self._tp.send(dest, FLIGHT_TAG,
                          pickle.dumps(msg, protocol=pickle.HIGHEST_PROTOCOL))
        except Exception:
            pass

    def _send_all(self, msg: dict):
        for dest in self._peers:
            self._send(dest, msg)

    # ---- the dump ----------------------------------------------------------
    def _trigger(self, reason: str, broadcast: bool,
                 incident: Optional[str] = None) -> Optional[str]:
        with self._trigger_lock:
            if len(self.dump_paths) >= self._cfg.max_dumps:
                return None
            if incident is None:
                incident = f"{self.rank}:{len(self._incidents)}"
            if incident in self._incidents:
                return None
            self._incidents.add(incident)
        state = self._rec.collective_state()
        if broadcast and self._peers:
            self._send_all({"kind": "stall", "incident": incident,
                            "rank": self.rank, "reason": reason,
                            "state": state})
        if self._peers:
            # Collect peer states for a bounded window — every reachable
            # peer replied or the window closed; either way we dump.
            deadline = time.time() + self._cfg.collect_window_s
            while (time.time() < deadline
                   and len(self._peer_states) < len(self._peers)
                   and not self._closed.is_set()):
                time.sleep(0.05)
        if self._peers and not self.clock_offsets:
            try:  # best-effort: the dump must not hang on a dead peer
                self.clock_sync(
                    rounds=2,
                    window_s=min(1.0, self._cfg.collect_window_s))
            except Exception:
                pass
        extra = {"incident": incident, "world_size": self.size,
                 "watchdog": self._cfg.as_dict()}
        if self.clock_offsets:
            extra["clock"] = {"rank": self.rank,
                              "offsets": {str(r): dict(d) for r, d in
                                          self.clock_offsets.items()}}
        path = self._rec.dump(
            out_dir=self._cfg.out_dir, rank=self.rank, reason=reason,
            peers=dict(self._peer_states) or None, extra=extra)
        self.dump_paths.append(path)
        self.incidents.append({"incident": incident, "reason": reason,
                               "path": path, "ts": time.time()})
        return path

    def dump_now(self, reason: str = "manual") -> Optional[str]:
        """Force a dump through the full cross-rank exchange path (crash
        handlers and tests)."""
        return self._trigger(reason, broadcast=bool(self._peers))


def watchdog_thread_count() -> int:
    """Live watchdog threads in this process (tests pin this to zero when
    observability is disabled)."""
    return sum(1 for t in threading.enumerate()
               if t.name.startswith(_THREAD_PREFIX))


def start_watchdog(recorder: Optional[_flight.FlightRecorder] = None,
                   control_plane=None,
                   config: Optional[WatchdogConfig] = None,
                   out_dir: Optional[str] = None,
                   force: bool = False,
                   **overrides) -> Optional[Watchdog]:
    """Start the hang watchdog; returns ``None`` (and starts **zero**
    threads) when observability is disabled and ``force`` is not set.

    ``overrides`` are :class:`WatchdogConfig` fields (e.g.
    ``deadline_s=30``); ``out_dir`` is where ``flight_<rank>.json``
    lands (next to metrics.jsonl when started by ``MetricsReport``).
    """
    rec = recorder if recorder is not None else _flight.get_flight_recorder()
    if rec is None:
        if not force:
            return None
        rec = _flight.install_flight_recorder()
    cfg = config or WatchdogConfig.from_env(**overrides)
    if config is not None and overrides:
        cfg = replace(cfg, **overrides)
    if out_dir is not None:
        cfg = replace(cfg, out_dir=out_dir)
    return Watchdog(rec, cfg, control_plane=control_plane).start()


__all__ = [
    "FLIGHT_TAG",
    "Watchdog",
    "WatchdogConfig",
    "start_watchdog",
    "watchdog_thread_count",
]
