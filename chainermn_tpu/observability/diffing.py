"""Differential attribution — localize a cross-run regression.

Two runs of the same program rarely disagree everywhere at once: a DCN
derate slows exactly the ``dcn_comm`` bucket, a straggler input
pipeline grows exactly ``host_input``.  This module turns two runs'
flight-recorder windows into comparable **run profiles** and diffs
them along every axis the within-run stack already measures:

* attribution-bucket seconds (:func:`~.attribution.classify_span` over
  the paired spans; the exact six-bucket ``attribute_step``
  decomposition when the window carries ``step`` events);
* per-``(link, owner)`` occupancy
  (:func:`~.contention.occupancy_from_events`);
* per-plan-stage span timings — count, mean, effective GB/s per
  ``(plan, stage, op, scope, link)``;
* :class:`~.registry.StreamingHistogram` states — the fixed log grid
  is shared by construction, so cross-run quantile deltas are computed
  on the merged counts EXACTLY, not re-estimated from summaries.

:func:`diff_profiles` emits a ``run_diff/v1`` document whose
``regression`` block names the regressed bucket with magnitude
(``delta_s`` / ``ratio``), a confidence (the share of the total
positive drift that bucket explains), and corroborating link / stage
evidence.  Acceptance story: replaying
``tests/data/degraded_dcn_spans.json`` against a healthy twin names
``dcn_comm``.  ``tools/ledger.py diff A B`` is the CLI;
``obs_report --diff`` renders the document.
"""

from __future__ import annotations

import json
from typing import Dict, List, Optional, Sequence, Tuple

from chainermn_tpu.observability import contention
from chainermn_tpu.observability.attribution import (
    BUCKETS, _merge, _total, attribute_step, classify_span)
from chainermn_tpu.observability.ledger import stamp_envelope
from chainermn_tpu.observability.spans import (
    build_step_trees, pair_events)

SCHEMA = "run_diff/v1"

#: link class -> the attribution bucket its spans land in (evidence
#: cross-referencing only; classification itself is classify_span's)
_LINK_BUCKET = {"ici": "ici_comm", "dcn": "dcn_comm"}

#: a bucket must drift by at least this much to be called a regression
MIN_ABS_S = 1e-4
#: ... and by at least this relative factor over the baseline
MIN_REL = 0.10


# ---------------------------------------------------------------------------
# run profiles
# ---------------------------------------------------------------------------

def _events_by_rank(events) -> Dict[int, List[dict]]:
    if isinstance(events, dict):
        return {int(r): list(e) for r, e in events.items()}
    return {0: list(events or [])}


def _stage_key(span) -> Optional[str]:
    if span.kind != "plan_stage":
        return None
    m = span.meta
    grp = m.get("group")
    return (f"{m.get('plan', '?')}:"
            f"{'g%s:' % grp if grp is not None else ''}"
            f"{m.get('stage', '?')}:{m.get('op', '?')}:"
            f"{m.get('scope', '?')}:{m.get('link', '?')}")


def run_profile(events, label: str = "",
                histograms: Optional[dict] = None) -> dict:
    """A comparable profile of one run's flight-recorder window.

    ``events`` is a flat event list or ``{rank: events}``;
    ``histograms`` optionally carries streaming-histogram grid docs in
    the ``TelemetryAggregator.local_summary`` shape (``{name: {lo, hi,
    buckets_per_decade, series: [{labels, state}]}}``) for exact
    cross-run quantile diffs.

    Bucket seconds come from the exact :func:`attribute_step`
    decomposition when the window has ``step`` events; a step-less
    window (a raw span dump, e.g. the online-tune replay inputs) falls
    back to merged classified span intervals per bucket — ``compute``
    and ``stall`` are then structurally zero and the profile says so
    (``bucket_source: "spans"``)."""
    by_rank = _events_by_rank(events)
    buckets = {b: 0.0 for b in BUCKETS}
    bucket_source = "spans"
    steps_n, steps_total = 0, 0.0
    occupancy: Dict[str, Dict[str, float]] = {}
    stages: Dict[str, dict] = {}
    n_events = 0
    for rank, evs in sorted(by_rank.items()):
        n_events += len(evs)
        trees = build_step_trees(evs, rank=rank)
        if trees:
            bucket_source = "steps"
            for step in trees:
                att = attribute_step(step)
                steps_n += 1
                steps_total += att["step_s"]
                for b, v in att["buckets"].items():
                    buckets[b] = buckets.get(b, 0.0) + float(v)
        spans = pair_events(evs, rank=rank)
        if not trees:
            per_bucket: Dict[str, list] = {}
            for sp in contention.leaf_comm_spans(spans):
                b = classify_span(sp)
                if b is not None:
                    per_bucket.setdefault(b, []).append((sp.t0, sp.t1))
            for sp in spans:
                b = classify_span(sp)
                if b in ("checkpoint", "host_input"):
                    per_bucket.setdefault(b, []).append((sp.t0, sp.t1))
            for b, ivs in per_bucket.items():
                buckets[b] = buckets.get(b, 0.0) + _total(_merge(ivs))
        for link, owners in contention.occupancy_from_events(
                evs, rank=rank).items():
            row = occupancy.setdefault(link, {})
            for owner, ivs in owners.items():
                row[owner] = row.get(owner, 0.0) + _total(ivs)
        for sp in spans:
            key = _stage_key(sp)
            if key is None:
                continue
            cell = stages.setdefault(key, {
                "link": sp.meta.get("link"), "n": 0,
                "total_s": 0.0, "bytes": 0})
            cell["n"] += 1
            cell["total_s"] += sp.dur_s
            cell["bytes"] += int(sp.meta.get("nbytes") or 0)
    for cell in stages.values():
        cell["mean_s"] = cell["total_s"] / cell["n"] if cell["n"] else 0.0
        cell["gbps"] = (cell["bytes"] / cell["total_s"] / 1e9
                        if cell["total_s"] > 0 else None)
    return {
        "label": label,
        "n_ranks": len(by_rank),
        "n_events": n_events,
        "bucket_source": bucket_source,
        "buckets_s": buckets,
        "steps": {"n": steps_n, "total_s": steps_total,
                  "mean_s": steps_total / steps_n if steps_n else None},
        "occupancy": occupancy,
        "stages": stages,
        "histograms": histograms or {},
    }


def load_run(path_or_doc, label: str = "") -> dict:
    """A run profile from a flight dump path/document (``{"events":
    [...]}``, a bare event list, or ``{rank: events}``)."""
    doc = path_or_doc
    if isinstance(doc, str):
        label = label or doc
        with open(doc) as fh:
            doc = json.load(fh)
    if isinstance(doc, dict) and "events" in doc:
        events = doc["events"]
    else:
        events = doc
    hists = doc.get("histograms") if isinstance(doc, dict) else None
    return run_profile(events, label=label, histograms=hists)


# ---------------------------------------------------------------------------
# histogram state diffing (exact on the shared grid)
# ---------------------------------------------------------------------------

def _fold_states(grid: dict) -> Optional[list]:
    """Elementwise-sum the counts of every labelled series on one
    histogram grid doc; ``None`` when empty."""
    counts: Optional[list] = None
    for series in grid.get("series", []):
        st = series.get("state", {})
        cs = st.get("counts")
        if cs is None:
            continue
        if counts is None:
            counts = [0] * len(cs)
        if len(cs) != len(counts):
            return None
        counts = [a + b for a, b in zip(counts, cs)]
    return counts


def diff_histograms(a: dict, b: dict,
                    quantiles: Sequence[float] = (0.5, 0.95, 0.99)
                    ) -> Dict[str, dict]:
    """Per-metric quantile deltas between two runs' streaming-histogram
    states.  Both runs' grids must agree (same ``lo``/``hi``/
    ``buckets_per_decade`` — they do by construction, the grid is fixed
    at metric definition); a mismatch is reported as such instead of a
    wrong delta, because counts from different grids do not merge."""
    from chainermn_tpu.observability.registry import StreamingHistogram
    out: Dict[str, dict] = {}
    for name in sorted(set(a) & set(b)):
        ga, gb = a[name], b[name]
        grid_keys = ("lo", "hi", "buckets_per_decade")
        if any(ga.get(k) != gb.get(k) for k in grid_keys):
            out[name] = {"grid_mismatch": True,
                         "a_grid": {k: ga.get(k) for k in grid_keys},
                         "b_grid": {k: gb.get(k) for k in grid_keys}}
            continue
        ca, cb = _fold_states(ga), _fold_states(gb)
        if ca is None or cb is None:
            continue
        hist = StreamingHistogram(
            name, lo=ga["lo"], hi=ga["hi"],
            buckets_per_decade=ga["buckets_per_decade"])
        row = {}
        for q in quantiles:
            qa = hist._quantile_from_counts(ca, q)
            qb = hist._quantile_from_counts(cb, q)
            row[f"p{int(q * 100)}"] = {
                "a": qa, "b": qb,
                "delta": (qb - qa) if qa is not None and qb is not None
                else None}
        out[name] = row
    return out


# ---------------------------------------------------------------------------
# the diff
# ---------------------------------------------------------------------------

def _ratio(a: float, b: float) -> Optional[float]:
    if a > 0:
        return b / a
    return None if b == 0 else float("inf")


def diff_profiles(base: dict, cand: dict, *,
                  min_abs_s: float = MIN_ABS_S,
                  min_rel: float = MIN_REL) -> dict:
    """``run_diff/v1``: candidate run vs baseline run.

    The ``regression`` block names the attribution bucket with the
    largest positive drift, provided it clears both the absolute
    (``min_abs_s``) and relative (``min_rel``) floors; ``confidence``
    is the share of all positive bucket drift that bucket explains
    (1.0 = the whole slowdown is in one bucket).  ``evidence`` carries
    the per-link occupancy drift and the worst-moved plan stage on the
    bucket's link class — the "which wire, which hop" pointer."""
    bucket_rows = []
    for b in BUCKETS:
        a_s = float(base["buckets_s"].get(b, 0.0))
        c_s = float(cand["buckets_s"].get(b, 0.0))
        bucket_rows.append({"bucket": b, "base_s": a_s, "cand_s": c_s,
                            "delta_s": c_s - a_s,
                            "ratio": _ratio(a_s, c_s)})

    # per-(link, owner) occupancy drift
    occ_rows = []
    links = set(base["occupancy"]) | set(cand["occupancy"])
    for link in sorted(links):
        oa = base["occupancy"].get(link, {})
        ob = cand["occupancy"].get(link, {})
        for owner in sorted(set(oa) | set(ob)):
            a_s, c_s = oa.get(owner, 0.0), ob.get(owner, 0.0)
            occ_rows.append({"link": link, "owner": owner,
                             "base_s": a_s, "cand_s": c_s,
                             "delta_s": c_s - a_s,
                             "ratio": _ratio(a_s, c_s)})

    # per-stage timing drift
    stage_rows = []
    for key in sorted(set(base["stages"]) | set(cand["stages"])):
        sa = base["stages"].get(key)
        sb = cand["stages"].get(key)
        row = {"stage": key,
               "link": (sb or sa or {}).get("link"),
               "base_mean_s": sa["mean_s"] if sa else None,
               "cand_mean_s": sb["mean_s"] if sb else None,
               "base_gbps": sa["gbps"] if sa else None,
               "cand_gbps": sb["gbps"] if sb else None}
        if sa and sb:
            row["mean_ratio"] = _ratio(sa["mean_s"], sb["mean_s"])
        stage_rows.append(row)

    # localization
    positive = [r for r in bucket_rows if r["delta_s"] > 0.0]
    total_pos = sum(r["delta_s"] for r in positive)
    regression = None
    if positive:
        top = max(positive, key=lambda r: r["delta_s"])
        rel_ok = top["base_s"] == 0.0 or (
            top["ratio"] is not None
            and top["ratio"] >= 1.0 + min_rel)
        if top["delta_s"] >= min_abs_s and rel_ok:
            link = next((lk for lk, bk in _LINK_BUCKET.items()
                         if bk == top["bucket"]), None)
            link_rows = [r for r in occ_rows if r["link"] == link]
            worst_owner = max(link_rows, key=lambda r: r["delta_s"]) \
                if link_rows else None
            cand_stages = [
                r for r in stage_rows
                if r.get("link") == link
                and r.get("mean_ratio") is not None] if link else []
            worst_stage = max(cand_stages,
                              key=lambda r: r["mean_ratio"]) \
                if cand_stages else None
            regression = {
                "bucket": top["bucket"],
                "base_s": top["base_s"],
                "cand_s": top["cand_s"],
                "delta_s": top["delta_s"],
                "ratio": top["ratio"],
                "confidence": (top["delta_s"] / total_pos
                               if total_pos > 0 else 1.0),
                "evidence": {
                    "link": link,
                    "occupancy": worst_owner,
                    "stage": worst_stage,
                },
            }

    doc = {
        "schema": SCHEMA,
        "schema_version": 1,
        "baseline": {k: base[k] for k in
                     ("label", "n_ranks", "n_events", "bucket_source",
                      "steps")},
        "candidate": {k: cand[k] for k in
                      ("label", "n_ranks", "n_events", "bucket_source",
                       "steps")},
        "buckets": bucket_rows,
        "occupancy": occ_rows,
        "stages": stage_rows,
        "histograms": diff_histograms(base.get("histograms") or {},
                                      cand.get("histograms") or {}),
        "regression": regression,
        "regressed": regression is not None,
    }
    return stamp_envelope(doc)


def diff_runs(base, cand, *, label_a: str = "baseline",
              label_b: str = "candidate", **kw) -> dict:
    """``diff_profiles`` over two flight dumps (paths, documents, or
    event lists) — the ``tools/ledger.py diff A B`` entry point."""
    return diff_profiles(load_run(base, label=label_a),
                         load_run(cand, label=label_b), **kw)


def diff_manifests(a: dict, b: dict) -> dict:
    """Metric deltas between two ledger ``run_manifest/v1`` records —
    the shallow (summary-level) cousin of :func:`diff_profiles` for
    artifacts that carry headline metrics but no spans."""
    ma, mb = a.get("metrics", {}), b.get("metrics", {})
    rows = []
    for metric in sorted(set(ma) | set(mb)):
        va, vb = ma.get(metric), mb.get(metric)
        rows.append({
            "metric": metric, "base": va, "cand": vb,
            "delta": (vb - va) if va is not None and vb is not None
            else None,
            "ratio": _ratio(va, vb)
            if va is not None and vb is not None else None})
    doc = {
        "schema": SCHEMA,
        "schema_version": 1,
        "baseline": {"artifact": a.get("artifact"),
                     "round": a.get("round"),
                     "device_kind": a.get("device_kind")},
        "candidate": {"artifact": b.get("artifact"),
                      "round": b.get("round"),
                      "device_kind": b.get("device_kind")},
        "metrics": rows,
        "regression": None,
        "regressed": False,
    }
    return stamp_envelope(doc)


__all__ = [
    "MIN_ABS_S",
    "MIN_REL",
    "SCHEMA",
    "diff_histograms",
    "diff_manifests",
    "diff_profiles",
    "diff_runs",
    "load_run",
    "run_profile",
]
