"""Span-tree reconstruction — from flight-recorder events to a per-step
tree of timed regions.

The flight recorder (ISSUE 2) stores *edges*: ``<kind>_begin`` /
``<kind>_end`` pairs for tracked spans, plain ``phase`` / ``step``
progress markers from the updater, ``fsdp_{gather,scatter}_{begin,end}``
bucket edges from the bucketed FSDP step, and (new here) per-stage
``plan_stage_{begin,end}`` edges from the plan compiler.  This module
pairs those edges back into :class:`Span` intervals and nests them by
containment into one tree per train step::

    step #12 [0.034s]
      ├─ phase:data_load [0.002s]
      ├─ phase:host_put  [0.001s]
      ├─ phase:dispatch  [0.009s]
      │    └─ collective allreduce_grad (trace-time)
      └─ phase:device_block [0.022s]
           ├─ plan_stage hier:0 reduce-scatter intra (ici)
           ├─ plan_stage hier:1 all-reduce inter (dcn)
           │    └─ compute compress:plan:inter
           └─ plan_stage hier:2 all-gather intra (ici)

:mod:`chainermn_tpu.observability.attribution` consumes these trees for
the cross-rank merge, bucket decomposition, critical path, and the
Perfetto export; ``tools/obs_report.py --attribution`` renders them.

The second half of the module is :class:`PlanObs` /
:func:`get_plan_obs` — the compiler-side hook that EMITS the per-stage
edges, following the ``compression/observe.py`` pattern exactly: bound
once per trace, ``None`` while observability is off (zero callbacks in
a disabled program), delivered from device-side ``jax.debug.callback``\\ s
gated to one representative device per controller so every process's
recorder carries its own stage stream.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

#: pairing slack for float timestamps (well under any real span)
_EPS = 1e-9


@dataclass
class Span:
    """One timed region on one rank.  ``meta`` keeps the raw event
    fields (op_seq, plan, stage, scope, link, nbytes, iteration, ...)."""

    name: str
    kind: str
    rank: int
    t0: float
    t1: float
    meta: dict = field(default_factory=dict)
    children: List["Span"] = field(default_factory=list)

    @property
    def dur_s(self) -> float:
        return max(self.t1 - self.t0, 0.0)

    def walk(self):
        """Yield self and every descendant (pre-order)."""
        yield self
        for c in self.children:
            yield from c.walk()

    def to_dict(self) -> dict:
        return {
            "name": self.name, "kind": self.kind, "rank": self.rank,
            "t0": self.t0, "t1": self.t1, "dur_s": self.dur_s,
            "meta": dict(self.meta),
            "children": [c.to_dict() for c in self.children],
        }


# ---------------------------------------------------------------------------
# edge pairing
# ---------------------------------------------------------------------------

def _span_key(ev: dict) -> Optional[tuple]:
    """Pairing key for a ``*_begin``/``*_end`` edge event, or ``None``
    for non-edge events.  Tracked spans pair on (kind, op, op_seq); the
    plan-stage lane pairs on (plan, stage); the FSDP lane on
    (leg, bucket) — each mirrors how its emitter sequences edges."""
    k = ev.get("kind", "")
    if k.startswith("plan_stage_"):
        # ``group`` disambiguates concurrent stripes of a striped plan
        # (stage 0 of group 0 vs stage 0 of group 1); absent/None for
        # plain plans and events recorded before striping existed.
        return ("plan_stage", ev.get("plan"), ev.get("group"),
                ev.get("stage"))
    if k.startswith("fsdp_gather_") or k.startswith("fsdp_scatter_"):
        leg = k.split("_")[1]
        return ("fsdp", leg, ev.get("bucket"))
    if k.endswith("_begin") or k.endswith("_end"):
        base = k.rsplit("_", 1)[0]
        return (base, ev.get("op"), ev.get("op_seq"))
    return None


def _span_from_pair(begin: dict, end: dict, rank: int) -> Span:
    k = begin.get("kind", "")
    if k.startswith("plan_stage_"):
        grp = begin.get("group")
        tag = f"g{grp}:" if grp is not None else ""
        name = (f"plan_stage {begin.get('plan', '?')}:{tag}"
                f"{begin.get('stage', '?')} {begin.get('op', '?')} "
                f"{begin.get('scope', '?')}")
        kind = "plan_stage"
    elif k.startswith("fsdp_"):
        leg = k.split("_")[1]
        name = f"fsdp_{leg} b{begin.get('bucket', '?')}"
        kind = "fsdp"
    else:
        kind = k.rsplit("_", 1)[0]
        name = f"{kind} {begin.get('op', '?')}"
    meta = {kk: vv for kk, vv in begin.items()
            if kk not in ("kind", "ts", "seq", "mono")}
    for kk, vv in end.items():
        if kk not in ("kind", "ts", "seq", "mono") and kk not in meta:
            meta[kk] = vv
    return Span(name=name, kind=kind, rank=rank,
                t0=begin.get("ts", 0.0), t1=end.get("ts", 0.0), meta=meta)


def pair_events(events: List[dict], rank: int = 0) -> List[Span]:
    """Pair begin/end edges into flat (un-nested) spans, oldest first.
    Unmatched begins (still-open spans, or begins whose end was
    overwritten by ring wraparound) are dropped — attribution only
    counts completed regions."""
    open_edges: Dict[tuple, dict] = {}
    out: List[Span] = []
    for ev in events:
        key = _span_key(ev)
        if key is None:
            continue
        k = ev.get("kind", "")
        if k.endswith("_begin"):
            open_edges[key] = ev
        else:
            begin = open_edges.pop(key, None)
            if begin is not None:
                out.append(_span_from_pair(begin, ev, rank))
    out.sort(key=lambda s: (s.t0, -s.t1))
    return out


def stage_link_timings(events: List[dict]) -> List[tuple]:
    """Per-stage link timings from raw flight events: one
    ``(link, nbytes, dur_s)`` tuple per COMPLETED ``plan_stage`` span
    with a link class and a positive payload.  This is the export the
    online tuner's observation window eats (``planner.online``) — the
    per-link transfer evidence, stripped of plan/step structure."""
    out = []
    for sp in pair_events(list(events)):
        if sp.kind != "plan_stage":
            continue
        link, nbytes = sp.meta.get("link"), sp.meta.get("nbytes")
        if link and nbytes:
            out.append((str(link), int(nbytes), sp.dur_s))
    return out


def step_windows(events: List[dict], rank: int = 0) -> List[Span]:
    """Step root spans.  ``step`` events are END-stamped (the updater
    records ``dur_s`` at step completion), so each window is
    ``[ts - dur_s, ts]``.  Serving runs have no ``step`` events — their
    ``serving serving_step`` spans become the roots instead."""
    out = []
    for ev in events:
        if ev.get("kind") == "step":
            t1 = ev.get("ts", 0.0)
            dur = float(ev.get("dur_s", 0.0))
            out.append(Span(name=f"step #{ev.get('iteration', '?')}",
                            kind="step", rank=rank, t0=t1 - dur, t1=t1,
                            meta={"iteration": ev.get("iteration"),
                                  "dur_s": dur}))
    if not out:
        for sp in pair_events(events, rank=rank):
            if sp.kind == "serving" and sp.meta.get("op") == "serving_step":
                out.append(Span(name=f"step #{sp.meta.get('step', '?')}",
                                kind="step", rank=rank, t0=sp.t0, t1=sp.t1,
                                meta=dict(sp.meta,
                                          iteration=sp.meta.get("step"))))
    out.sort(key=lambda s: s.t0)
    return out


def phase_spans(events: List[dict], steps: List[Span],
                rank: int = 0) -> List[Span]:
    """Turn ``phase`` markers (recorded at phase START) into spans: each
    phase runs until the next phase marker of the same iteration, else
    to its enclosing step window's end."""
    markers = [ev for ev in events if ev.get("kind") == "phase"]
    out: List[Span] = []
    for i, ev in enumerate(markers):
        t0 = ev.get("ts", 0.0)
        nxt = markers[i + 1] if i + 1 < len(markers) else None
        t1 = None
        if nxt is not None and nxt.get("iteration") == ev.get("iteration"):
            t1 = nxt.get("ts", 0.0)
        if t1 is None:
            for st in steps:
                if st.t0 - _EPS <= t0 <= st.t1 + _EPS:
                    t1 = st.t1
                    break
        if t1 is None:
            t1 = nxt.get("ts", t0) if nxt is not None else t0
        out.append(Span(name=f"phase:{ev.get('phase', '?')}", kind="phase",
                        rank=rank, t0=t0, t1=max(t1, t0),
                        meta={"phase": ev.get("phase"),
                              "iteration": ev.get("iteration")}))
    return out


def _nest(parent: Span, spans: List[Span]) -> None:
    """Nest ``spans`` (pre-sorted by (t0, -t1)) under ``parent`` by
    interval containment — the classic stack sweep."""
    stack = [parent]
    for s in spans:
        while len(stack) > 1 and not (
                s.t0 >= stack[-1].t0 - _EPS and s.t1 <= stack[-1].t1 + _EPS):
            stack.pop()
        stack[-1].children.append(s)
        stack.append(s)


def build_step_trees(events: List[dict], rank: int = 0,
                     offset: float = 0.0) -> List[Span]:
    """The tree builder: step roots, phases + paired spans nested inside
    by containment.  ``offset`` (seconds) is added to every timestamp —
    the attribution merge passes each rank's clock-handshake offset so
    all trees land in the reference rank's timebase."""
    has_step_events = any(ev.get("kind") == "step" for ev in events)
    steps = step_windows(events, rank=rank)
    leaves = phase_spans(events, steps, rank=rank)
    # In the serving fallback the serving_step spans ARE the roots —
    # keep them out of the leaf set so a root never nests under itself.
    leaves.extend(
        sp for sp in pair_events(events, rank=rank)
        if has_step_events or not (sp.kind == "serving"
                                   and sp.meta.get("op") == "serving_step"))
    leaves.sort(key=lambda s: (s.t0, -s.t1))
    for st in steps:
        inside = [s for s in leaves
                  if st.t0 - _EPS <= 0.5 * (s.t0 + s.t1) <= st.t1 + _EPS]
        _nest(st, inside)
    if offset:
        for st in steps:
            for sp in st.walk():
                sp.t0 += offset
                sp.t1 += offset
    return steps


# ---------------------------------------------------------------------------
# PlanObs — the compiler-side per-stage span hooks
# ---------------------------------------------------------------------------

class PlanObs:
    """Begin/end edges for each emitted plan stage, delivered from
    device-side ``jax.debug.callback``\\ s inserted by
    ``planner/compiler._run_stages_flat``.

    Gating: the callback fires on every device of the SPMD region;
    ``rep_rank`` picks ONE representative global device index per
    controller (``get_plan_obs`` derives it from the communicator's
    rank/host layout), so each process's flight recorder carries exactly
    one stage stream — unlike the compression lane, which keeps a single
    global stream on rank 0, the attribution merge needs per-controller
    events to see cross-host skew.

    Metric family (labels ``plan``/``stage``/``op``/``scope``/``link``/
    ``group`` — ``group`` is the concurrent stripe index of a striped
    plan, ``"-"`` for plain plans):

    * ``plan_stage_seconds`` (histogram) — host-observed latency between
      a stage's begin and end callbacks;
    * ``plan_stage_bytes`` (counter) — wire bytes the stage moved
      (``_stage_wire_elem_bytes`` pricing, compression included).
    """

    def __init__(self, flight, registry, rep_rank: int = 0,
                 rep_stride: int = 1):
        self.flight = flight
        self.registry = registry
        self.rep_rank = int(rep_rank)
        # devices per controller: the compiler's device-side gate fires
        # the callback only where global_idx % rep_stride == 0 (one shard
        # per controller — the same shards rep_rank picks host-side)
        self.rep_stride = max(int(rep_stride), 1)
        self._begin: dict = {}
        if registry is not None:
            self._seconds = registry.histogram(
                "plan_stage_seconds",
                "host-observed per-stage latency of an executed plan")
            self._bytes = registry.counter(
                "plan_stage_bytes",
                "wire bytes moved per executed plan stage")

    def edge(self, edge: str, plan: str, stage: int, op: str, scope: str,
             link: str, nbytes: int, group: Optional[int] = None) -> None:
        now = time.perf_counter()
        key = (plan, group, stage)
        if self.flight is not None:
            kw = dict(plan=plan, stage=stage, op=op, scope=scope,
                      link=link, nbytes=nbytes)
            if group is not None:
                kw["group"] = group
            self.flight.record(f"plan_stage_{edge}", **kw)
        if self.registry is not None:
            labels = {"plan": plan, "stage": str(stage), "op": op,
                      "scope": scope, "link": link,
                      "group": str(group) if group is not None else "-"}
            if edge == "begin":
                self._begin[key] = now
            else:
                t0 = self._begin.pop(key, None)
                if t0 is not None:
                    self._seconds.observe(now - t0, **labels)
                self._bytes.inc(nbytes, **labels)

    def make_callback(self, edge: str, plan: str, stage: int, op: str,
                      scope: str, link: str, nbytes: int,
                      group: Optional[int] = None):
        """A rank-gated debug callback for one stage edge.  Called with
        ``(rank_idx, _dep)`` — ``_dep`` pins when the device reaches the
        edge (the stage's input on begin, its output on end).  ``group``
        is the concurrent stripe index for striped plans."""

        def cb(rank_idx, _dep):
            if int(rank_idx) == self.rep_rank:
                self.edge(edge, plan, stage, op, scope, link, nbytes,
                          group=group)
        return cb


def get_plan_obs(comm=None) -> Optional[PlanObs]:
    """The build-time hook: ``None`` while observability is off (a
    disabled ``execute_plan`` trace carries no callbacks at all).  With
    a communicator, the representative device is this controller's
    first local device under the contiguous device→process mesh layout
    (``rank * (size // host_size)``)."""
    from chainermn_tpu.observability import flight_recorder as _flight
    from chainermn_tpu.observability import registry as _registry

    fr = _flight.get_flight_recorder()
    reg = _registry.get_registry() if _registry.enabled() else None
    if fr is None and reg is None:
        return None
    rep, stride = 0, 1
    if comm is not None:
        try:
            size = int(getattr(comm, "size", 1) or 1)
            hosts = max(int(getattr(comm, "host_size", 1) or 1), 1)
            stride = max(size // hosts, 1)
            rep = int(getattr(comm, "rank", 0) or 0) * stride
        except Exception:
            rep, stride = 0, 1
    return PlanObs(fr, reg, rep_rank=rep, rep_stride=stride)


__all__ = [
    "PlanObs",
    "Span",
    "build_step_trees",
    "get_plan_obs",
    "pair_events",
    "phase_spans",
    "stage_link_timings",
    "step_windows",
]
