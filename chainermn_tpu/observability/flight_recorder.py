"""Distributed flight recorder — the always-on event ring behind hang dumps.

The failure mode that kills multi-controller runs is one rank wedged
inside a collective or a DCN transfer while every other rank blocks
forever; steady-state metrics (ISSUE 1) show *nothing* because nothing is
progressing.  Production collective stacks answer this with a bounded
per-process ring of structured events plus a watchdog that dumps the ring
when progress stalls (the NCCL / PyTorch "flight recorder" design).  This
module is the ring; :mod:`chainermn_tpu.observability.watchdog` is the
watchdog.

What rides the ring (each event one small dict, O(1) to record):

* collective entry/exit — per-op sequence number, op, comm name, payload
  bytes (recorded by :class:`~chainermn_tpu.observability.instrument.
  InstrumentedCommunicator`);
* transport frames — DCN send/recv with peer, tag, byte count
  (:class:`~chainermn_tpu.runtime.transport.PyTransport`);
* cross-controller p2p — the blocking host callbacks of
  ``functions/point_to_point_communication.py``;
* step-phase transitions and step completions (``StandardUpdater``);
* checkpoint begin/end (``_MultiNodeCheckpointer``).

Zero-cost-when-disabled: call sites obtain a recorder ONCE at
construction via :func:`get_flight_recorder`, which returns ``None``
while observability is off — a disabled hot loop carries a dormant
``None`` and performs no recording calls at all (same contract as the
metrics registry, pinned by tests/test_flight_recorder.py).

The dump (``flight_<rank>.json``, next to metrics.jsonl) carries the
ring, the per-op collective state (last-completed seq + open spans), the
Python stacks of every thread, and — when the watchdog could reach peers
— their collective states plus a desync analysis from
:func:`identify_desync`.  ``tools/obs_report.py --flight`` merges the
per-rank dumps into one timeline.
"""

from __future__ import annotations

import os
import sys
import threading
import time
import traceback
from typing import Dict, List, Optional

from chainermn_tpu.observability import registry as _registry
from chainermn_tpu.observability.sinks import atomic_write_json

DUMP_SCHEMA = 1

_DEFAULT_CAPACITY = 4096


def _capacity_from_env() -> int:
    raw = os.environ.get("CHAINERMN_TPU_FLIGHT_CAPACITY")
    if not raw:
        return _DEFAULT_CAPACITY
    try:
        val = int(raw)
    except ValueError:
        return _DEFAULT_CAPACITY
    return val if val > 0 else _DEFAULT_CAPACITY


def thread_stacks() -> List[dict]:
    """Python stacks of every live thread (``sys._current_frames``), as
    plain data so they serialize into the dump.  The complementary
    ``faulthandler`` wiring in ``runtime/bootstrap.py`` covers crashes
    where the interpreter itself cannot run this."""
    names = {t.ident: t for t in threading.enumerate()}
    out = []
    for ident, frame in sys._current_frames().items():
        t = names.get(ident)
        out.append({
            "thread": t.name if t else f"thread-{ident}",
            "ident": ident,
            "daemon": bool(t.daemon) if t else None,
            "stack": [ln.rstrip("\n")
                      for ln in traceback.format_stack(frame)],
        })
    return out


class FlightRecorder:
    """Bounded ring of structured events + per-op collective state.

    Thread-safe; a record is a dict build plus a list store under a lock
    (the same overhead class as a Counter.inc).  ``capacity`` bounds
    memory no matter how long the run (oldest events overwritten).

    Spans (collective/p2p/transport-recv/checkpoint) are recorded as a
    ``*_begin`` event plus a ``*_end`` event and tracked in an
    open-span table while in flight — the watchdog's "collective open
    longer than the deadline" predicate reads that table, and the dump's
    desync analysis compares per-op last-completed sequence numbers
    across ranks.
    """

    def __init__(self, capacity: Optional[int] = None):
        self.capacity = capacity if capacity else _capacity_from_env()
        self._buf: List[Optional[dict]] = [None] * self.capacity
        self._pos = 0
        self._event_seq = 0
        # ring-overflow accounting: every record() that overwrites a
        # still-live slot bumps this, so dumps can say how many events
        # the ring LOST instead of silently presenting a truncated
        # history as complete (satellite of ISSUE 10).
        self.dropped_events = 0
        self._lock = threading.Lock()
        self._span_seq = 0
        # per-op collective sequence numbers (key: op name) — the
        # cross-rank comparable state.  A collective is "completed" when
        # its end event records; an entry sits in _open until then.
        self._op_seq: Dict[str, int] = {}
        self._last_completed: Dict[str, int] = {}
        self._open: Dict[int, dict] = {}
        # step progress (trailing window for the watchdog's k x median)
        self._step_durations: List[float] = []
        self._step_window = 64
        self.steps = 0
        self.last_step_end: Optional[float] = None

    # ---- core recording ----------------------------------------------------
    def record(self, kind: str, **fields) -> dict:
        # both clocks: ``ts`` (wall) for cross-rank merging after offset
        # correction, ``mono`` (monotonic) for drift-immune local ages
        ev = {"kind": kind, "ts": time.time(), "mono": time.monotonic(),
              **fields}
        with self._lock:
            ev["seq"] = self._event_seq
            self._event_seq += 1
            if self._buf[self._pos] is not None:
                self.dropped_events += 1
            self._buf[self._pos] = ev
            self._pos = (self._pos + 1) % self.capacity
        return ev

    def events_since(self, seq: int) -> List[dict]:
        """Events with ``seq`` strictly greater than ``seq``, oldest
        first — the incremental slice online consumers (the attribution
        watch) pull per emit without re-walking the whole ring."""
        return [ev for ev in self.snapshot() if ev.get("seq", -1) > seq]

    def span_begin(self, kind: str, op: str, **fields) -> int:
        """Open a tracked span (collective / p2p / transport recv /
        checkpoint).  Returns a token for :meth:`span_end`.  ``op`` keys
        the per-op sequence numbering used for cross-rank desync
        comparison, so it must be identical on every rank for symmetric
        collectives."""
        with self._lock:
            self._span_seq += 1
            token = self._span_seq
            op_seq = self._op_seq.get(op, 0) + 1
            self._op_seq[op] = op_seq
        ev = self.record(f"{kind}_begin", op=op, op_seq=op_seq, **fields)
        with self._lock:
            self._open[token] = {"kind": kind, "op": op, "op_seq": op_seq,
                                 "ts": ev["ts"], "mono": ev["mono"],
                                 **fields}
        return token

    def span_end(self, token: int, **fields) -> None:
        with self._lock:
            open_rec = self._open.pop(token, None)
        if open_rec is None:
            return
        self.record(f"{open_rec['kind']}_end", op=open_rec["op"],
                    op_seq=open_rec["op_seq"],
                    dur_s=time.monotonic() - open_rec["mono"], **fields)
        with self._lock:
            prev = self._last_completed.get(open_rec["op"], 0)
            if open_rec["op_seq"] > prev:
                self._last_completed[open_rec["op"]] = open_rec["op_seq"]

    # ---- convenience entry points ------------------------------------------
    def collective_begin(self, op: str, comm: str = "",
                         nbytes: int = 0) -> int:
        return self.span_begin("collective", op, comm=comm, nbytes=nbytes)

    def collective_end(self, token: int) -> None:
        self.span_end(token)

    def record_step(self, duration_s: float, iteration: int) -> None:
        """One completed train step — the watchdog's progress heartbeat
        and the trailing-median baseline for the step-stall predicate."""
        self.record("step", iteration=iteration, dur_s=duration_s)
        with self._lock:
            self._step_durations.append(float(duration_s))
            if len(self._step_durations) > self._step_window:
                self._step_durations.pop(0)
            self.steps += 1
            self.last_step_end = time.time()

    def record_phase(self, phase: str, iteration: int) -> None:
        self.record("phase", phase=phase, iteration=iteration)

    # ---- state views -------------------------------------------------------
    def snapshot(self) -> List[dict]:
        """Ring contents, oldest first."""
        with self._lock:
            tail = [e for e in self._buf[self._pos:] if e is not None]
            head = [e for e in self._buf[:self._pos] if e is not None]
        return tail + head

    def open_spans(self, now: Optional[float] = None) -> List[dict]:
        """Currently-open spans with ``age_s``.  Ages come from the
        MONOTONIC clock (``now`` is only the wall-clock fallback for
        legacy records without a ``mono`` stamp) — an NTP step or
        cross-host drift can no longer mint phantom stragglers or
        phantom collective timeouts."""
        now = time.time() if now is None else now
        mono_now = time.monotonic()
        with self._lock:
            out = [dict(rec, age_s=(mono_now - rec["mono"])
                        if "mono" in rec else (now - rec["ts"]))
                   for rec in self._open.values()]
        return sorted(out, key=lambda r: r["ts"])

    def trailing_step_median(self) -> Optional[float]:
        with self._lock:
            w = sorted(self._step_durations)
        if not w:
            return None
        n = len(w)
        return w[n // 2] if n % 2 else 0.5 * (w[n // 2 - 1] + w[n // 2])

    def collective_state(self) -> dict:
        """The cross-rank comparable summary: per-op last-completed
        sequence numbers plus currently-open spans.  This is what the
        watchdog exchanges between ranks and what
        :func:`identify_desync` consumes."""
        with self._lock:
            last = dict(self._last_completed)
            steps = self.steps
            event_seq = self._event_seq
            dropped = self.dropped_events
        return {"last_completed": last, "open": self.open_spans(),
                "steps": steps, "event_seq": event_seq,
                "dropped_events": dropped, "ts": time.time(),
                "mono": time.monotonic()}

    # ---- the dump ----------------------------------------------------------
    def dump(self, out_dir: str = ".", rank: int = 0, reason: str = "",
             peers: Optional[Dict[int, dict]] = None,
             extra: Optional[dict] = None) -> str:
        """Write ``flight_<rank>.json`` (atomic rename; a crashed dumper
        never leaves a torn file).  Returns the path."""
        local_state = self.collective_state()
        doc = {
            "kind": "flight_dump",
            "schema": DUMP_SCHEMA,
            "rank": int(rank),
            "ts": time.time(),
            "reason": reason,
            # events the ring overwrote before this dump — a nonzero
            # count means the timeline below is missing its oldest part
            # (the restart-manifest evidence stamp reads all three: the
            # PR 16 telemetry truncation convention at crash time)
            "dropped_events": int(self.dropped_events),
            "ring_capacity": int(self.capacity),
            "evidence_truncated": bool(self.dropped_events),
            "collective_state": local_state,
            "events": self.snapshot(),
            "threads": thread_stacks(),
        }
        if peers:
            doc["peers"] = {str(r): s for r, s in peers.items()}
            states = dict(peers)
            states[int(rank)] = local_state
            doc["analysis"] = identify_desync(states)
        if extra:
            doc.update(extra)
        os.makedirs(out_dir or ".", exist_ok=True)
        path = os.path.join(out_dir or ".", f"flight_{int(rank)}.json")
        atomic_write_json(path, doc)
        return path


# ---- cross-rank desync analysis (pure function; obs_report shares it) ------

def identify_desync(states: Dict[int, dict]) -> dict:
    """Name the desynchronized rank(s) from per-rank collective states.

    ``states`` maps rank -> ``collective_state()`` dict.  For every op
    with an open span anywhere, take the highest open sequence number N:
    the ranks blocked inside (op, N) are *waiting*; a rank whose position
    for that op (its open seq, else its last-completed seq) is behind N
    never entered the collective — it is the desynchronized one the
    others are waiting for.  Only collective/object spans participate
    (transport/p2p/checkpoint spans are local diagnostics, not symmetric
    across ranks).

    Open ``kind="compute"`` spans (e.g. the compression subsystem's
    compress/decompress regions) are reported separately as
    ``compute_stragglers``: a rank stuck in local compute is the CAUSE of
    a stall, not a wedged collective, and must not be misattributed to
    the wire — the slow-quantizer-looks-like-a-hang failure mode.
    """
    states = {int(r): s for r, s in states.items()}
    stalls: List[dict] = []
    desynced: set = set()
    compute_stragglers: List[dict] = []
    for r, s in states.items():
        for rec in s.get("open", ()):
            if rec.get("kind") == "compute":
                compute_stragglers.append({
                    "op": rec.get("op"),
                    "rank": r,
                    "age_s": float(rec.get("age_s", 0.0)),
                })
    compute_stragglers.sort(key=lambda x: -x["age_s"])
    ops = set()
    for s in states.values():
        for rec in s.get("open", ()):
            if rec.get("kind") in ("collective", "object"):
                ops.add(rec["op"])
    for op in sorted(ops):
        open_seqs = {}
        positions = {}
        for r, s in states.items():
            open_here = [rec for rec in s.get("open", ())
                         if rec.get("kind") in ("collective", "object")
                         and rec.get("op") == op]
            completed = int(s.get("last_completed", {}).get(op, 0))
            if open_here:
                open_seqs[r] = max(int(rec["op_seq"]) for rec in open_here)
                positions[r] = open_seqs[r]
            else:
                positions[r] = completed
        if not open_seqs:
            continue
        front = max(open_seqs.values())
        waiting = sorted(r for r, s in open_seqs.items() if s == front)
        behind = sorted(r for r, p in positions.items() if p < front)
        stalls.append({
            "op": op,
            "seq": front,
            "waiting_ranks": waiting,
            "desynced_ranks": behind,
            "positions": {str(r): positions[r] for r in sorted(positions)},
        })
        desynced.update(behind)
    return {
        "stalled_collectives": stalls,
        "desynced_ranks": sorted(desynced),
        "compute_stragglers": compute_stragglers,
        "n_ranks": len(states),
    }


# ---- process-wide recorder (same gating contract as the registry) ----------

_RECORDER: Optional[FlightRecorder] = None
_RECORDER_LOCK = threading.Lock()


def get_flight_recorder() -> Optional[FlightRecorder]:
    """The process-wide recorder, or ``None`` while observability is
    disabled.  Call sites bind the result ONCE at construction — a
    ``None`` handle is the zero-cost disabled path.  Lazily created on
    first enabled call, so ``observability.enable()`` before building
    communicators/updaters is the whole wiring."""
    if _RECORDER is not None:
        return _RECORDER
    if not _registry.enabled():
        return None
    return install_flight_recorder()


def install_flight_recorder(
        recorder: Optional[FlightRecorder] = None) -> FlightRecorder:
    """Force-install a recorder (tests; or recording while the metrics
    switch stays off).  Idempotent when one already exists and no
    replacement is given."""
    global _RECORDER
    with _RECORDER_LOCK:
        if recorder is not None:
            _RECORDER = recorder
        elif _RECORDER is None:
            _RECORDER = FlightRecorder()
        return _RECORDER


def reset_flight_recorder() -> None:
    """Drop the process-wide recorder (tests)."""
    global _RECORDER
    with _RECORDER_LOCK:
        _RECORDER = None


__all__ = [
    "DUMP_SCHEMA",
    "FlightRecorder",
    "get_flight_recorder",
    "identify_desync",
    "install_flight_recorder",
    "reset_flight_recorder",
    "thread_stacks",
]
