"""Compressor protocol + registry — the pluggable gradient-wire codecs.

**Beyond-reference extension** (labeled like the other `parallel/`
extensions).  The anaruse fork's signature trick was a reduced-precision
gradient wire (`allreduce_grad_dtype='float16'`): cast in, allreduce in
the wire dtype, cast back.  This package generalizes that cast into a
``Compressor`` protocol so the same three exchange seams —
``allreduce_grad``, ``create_multi_node_optimizer``, and the bucketed
FSDP reduce-scatter — can ride anything from a plain dtype cast
(:class:`NoCompression`, which lowers to the exact current program) to
int8/fp8 quantization with error feedback (``quantize.py`` /
``error_feedback.py``), the DynamiQ/FlexLink recipe.

A compressor is identified by its **spec** — a canonical JSON string of
its name + config — which is what bucket layouts, checkpoints sidecars,
and the resume guard compare.  Construction routes through
:func:`resolve_compressor`, which accepts a registry name (``"int8"``),
a spec string/dict, or an instance.
"""

from __future__ import annotations

import json
from typing import Any, Dict, Optional, Type

import jax.numpy as jnp


class Compressor:
    """Protocol for a gradient wire codec.

    ``compress(buf, state) -> (wire, state)`` encodes one flat float
    buffer into its wire representation; ``decompress(wire, state) ->
    (buf, state)`` decodes the *summed* wire buffer back (the collective
    between the two SUMS wire values in wire arithmetic, so codecs must
    be closed under summation — int8 codes clip to ``max_code //
    world_size`` for exactly this reason).  Stateful codecs carry an
    :class:`~chainermn_tpu.compression.error_feedback.CompressionState`
    (EF residual + delayed scales + step counter) through both calls.

    Identity/config:

    * ``name`` — registry key;
    * ``config()`` — JSON-serializable kwargs that reconstruct it;
    * ``spec`` — the canonical JSON identity string (checkpoint guard).
    """

    name: str = "?"
    stateful: bool = False

    # -- identity ------------------------------------------------------------
    def config(self) -> Dict[str, Any]:
        return {}

    @property
    def spec(self) -> str:
        return json.dumps({"name": self.name, **self.config()},
                          sort_keys=True)

    def __repr__(self) -> str:
        return f"{type(self).__name__}({self.config()})"

    def __eq__(self, other) -> bool:
        return isinstance(other, Compressor) and self.spec == other.spec

    def __hash__(self) -> int:
        return hash(self.spec)

    # -- wire ----------------------------------------------------------------
    def wire_dtype_for(self, dtype) -> jnp.dtype:
        """Dtype the collective runs in for a buffer of ``dtype``."""
        return jnp.dtype(dtype)

    def compress(self, buf, state=None, rank=None):
        raise NotImplementedError

    def decompress(self, wire, state=None):
        raise NotImplementedError


class NoCompression(Compressor):
    """The identity codec — today's wire-dtype cast, as a Compressor.

    ``NoCompression(wire_dtype="bfloat16")`` IS ``allreduce_grad_dtype=
    "bfloat16"``: every seam detects it and lowers to the exact program
    the bare dtype knob produced (pack -> cast -> collective in the wire
    dtype -> cast back -> scale), bit for bit.  ``NoCompression()`` with
    no wire dtype is the do-nothing default.
    """

    name = "none"
    stateful = False

    def __init__(self, wire_dtype=None):
        if wire_dtype is not None:
            wire = jnp.dtype(wire_dtype)
            if not jnp.issubdtype(wire, jnp.floating):
                raise ValueError(
                    f"NoCompression wire_dtype must be floating, got "
                    f"{wire} — integer wires need a quantizer ('int8')")
            wire_dtype = str(wire)
        self.wire_dtype = wire_dtype

    def config(self):
        return {"wire_dtype": self.wire_dtype}

    @property
    def wire(self) -> Optional[jnp.dtype]:
        return jnp.dtype(self.wire_dtype) if self.wire_dtype else None

    def wire_dtype_for(self, dtype):
        if self.wire_dtype is not None \
                and jnp.issubdtype(jnp.dtype(dtype), jnp.floating):
            return jnp.dtype(self.wire_dtype)
        return jnp.dtype(dtype)

    def compress(self, buf, state=None, rank=None):
        return buf.astype(self.wire_dtype_for(buf.dtype)), state

    def decompress(self, wire, state=None):
        return wire, state


# ---- registry ---------------------------------------------------------------

_REGISTRY: Dict[str, Type[Compressor]] = {}


def register_compressor(name: str, cls: Type[Compressor]) -> None:
    _REGISTRY[name] = cls


def available_compressors():
    return sorted(_REGISTRY)


register_compressor(NoCompression.name, NoCompression)


def resolve_compressor(value) -> Optional[Compressor]:
    """Turn any accepted compression designation into a Compressor.

    Accepts ``None`` (no compression), a :class:`Compressor` instance, a
    registry name (``"int8"``), a plain wire dtype string
    (``"bfloat16"`` -> ``NoCompression(wire_dtype=...)``), a spec JSON
    string, or a config dict (``{"name": "int8", "chunk_size": 512}``).
    """
    if value is None or isinstance(value, Compressor):
        return value
    cfg = None
    if isinstance(value, dict):
        cfg = dict(value)
    elif isinstance(value, str):
        s = value.strip()
        if s.startswith("{"):
            cfg = json.loads(s)
        elif s in _REGISTRY:
            cfg = {"name": s}
        else:
            # a bare dtype string is the legacy wire knob's spelling
            try:
                jnp.dtype(s)
            except TypeError:
                raise ValueError(
                    f"unknown compressor {value!r}; available: "
                    f"{available_compressors()} (or a wire dtype like "
                    f"'bfloat16', or a spec dict/JSON)") from None
            cfg = {"name": "none", "wire_dtype": s}
    else:
        raise TypeError(
            f"cannot resolve a compressor from {type(value).__name__}; "
            f"pass a name, spec dict/JSON, dtype string, or Compressor")
    name = cfg.pop("name", None)
    if name not in _REGISTRY:
        raise ValueError(
            f"unknown compressor {name!r}; available: "
            f"{available_compressors()}")
    return _REGISTRY[name](**cfg)


__all__ = ["Compressor", "NoCompression", "available_compressors",
           "register_compressor", "resolve_compressor"]
