"""Error-feedback state — the residual memory that makes lossy wires safe.

Error feedback (EF / EF-SGD): each rank adds the compression error it
committed last step back into this step's gradient before compressing
again, so quantization error ACCUMULATES into the update stream instead
of being lost — the property that makes 1-byte wires converge like
full-precision ones on smooth objectives.

:class:`CompressionState` is the per-buffer carrier:

* ``ef`` — this rank's residual, full buffer length (device-varying:
  every rank keeps its own error);
* ``scale`` — the delayed quantization scale state, stored as base-2
  EXPONENTS (``scale = 2**e``).  Power-of-two scales are exactly
  representable in every float wire dtype, which is what lets the FSDP
  seam piggyback scale redistribution on the parameter all-gather
  without a dedicated collective;
* ``step`` — a float32 step counter seeding the stochastic-rounding
  PRNG stream (float so the whole state is a valid cotangent: the FSDP
  seam threads EF state through the backward as a custom-VJP cotangent).

The state is a registered pytree whose *static* aux data carries the
compressor spec and an ``EF_VERSION``, so checkpoints persist the
config alongside the arrays and the resume guard can refuse a
mismatched compressor with an actionable error (mirroring the FSDP
``num_buckets`` guard).
"""

from __future__ import annotations

from typing import Any, List, Optional

import jax
import jax.numpy as jnp

EF_VERSION = 1


@jax.tree_util.register_pytree_node_class
class CompressionState:
    """Per-buffer EF + delayed-scale state (see module docstring).

    Children (arrays): ``ef``, ``scale`` (base-2 exponents), ``step``.
    Static aux: ``spec`` (the compressor's canonical JSON identity),
    ``ef_version``, and ``hop`` (the plan stage index for per-hop
    states, ``None`` for whole-collective states) — all ride the
    treedef, so two states with different compressor configs *or*
    different hop assignments are *structurally* different pytrees.
    """

    def __init__(self, ef, scale, step, spec: str = "",
                 ef_version: int = EF_VERSION,
                 hop: Optional[int] = None):
        self.ef = ef
        self.scale = scale
        self.step = step
        self.spec = spec
        self.ef_version = ef_version
        self.hop = hop

    def tree_flatten(self):
        return (self.ef, self.scale, self.step), (self.spec,
                                                  self.ef_version,
                                                  self.hop)

    @classmethod
    def tree_unflatten(cls, aux, children):
        ef, scale, step = children
        # aux grew a trailing hop slot; treedefs pickled before that keep
        # unflattening (hop=None).
        hop = aux[2] if len(aux) > 2 else None
        return cls(ef, scale, step, spec=aux[0], ef_version=aux[1],
                   hop=hop)

    def _replace(self, **kw):
        d = {"ef": self.ef, "scale": self.scale, "step": self.step,
             "spec": self.spec, "ef_version": self.ef_version,
             "hop": self.hop}
        d.update(kw)
        return CompressionState(**d)

    def __repr__(self):
        hop = f", hop={self.hop}" if self.hop is not None else ""
        return (f"CompressionState(ef={jnp.shape(self.ef)}, "
                f"scale={jnp.shape(self.scale)}, spec={self.spec}{hop})")


def init_state(compressor, length: int, n_scales: int,
               hop: Optional[int] = None) -> CompressionState:
    """Fresh single-rank EF state for one flat buffer: zero residual,
    unit scales (``e=0`` -> ``2**0``; the delayed-scale update converges
    geometrically from any initialization because EF re-feeds what the
    warmup steps clipped or zeroed), step 0.  ``hop`` tags a per-stage
    state with its plan stage index (see ``planner.compiler``)."""
    return CompressionState(
        ef=jnp.zeros((int(length),), jnp.float32),
        scale=jnp.zeros((int(n_scales),), jnp.float32),
        step=jnp.zeros((1,), jnp.float32),
        spec=compressor.spec,
        ef_version=EF_VERSION,
        hop=hop,
    )


def iter_compression_states(tree) -> List[CompressionState]:
    """Every CompressionState in a pytree/container (checkpoint guard)."""
    return [x for x in jax.tree.leaves(
        tree, is_leaf=lambda x: isinstance(x, CompressionState))
        if isinstance(x, CompressionState)]


def compression_layout(tree) -> Optional[dict]:
    """Compression config of every EF state inside ``tree`` (``None``
    when there is none) — what the multi-node checkpointer persists in
    its sidecar and compares on resume, exactly like the FSDP
    world-size/num_buckets layout.  Sorted spec list so the comparison
    is order-independent across save/restore tree walks."""
    states = iter_compression_states(tree)
    if not states:
        return None
    out = {
        "specs": sorted({s.spec for s in states}),
        "n_states": len(states),
        "ef_version": max(s.ef_version for s in states),
    }
    # Per-hop states additionally pin WHICH stage carries WHICH spec
    # (sorted "stage:spec" strings): swapping the int8 and fp8 hops of a
    # plan yields the same spec set but a different layout, and the
    # resume guard must refuse it.
    hops = sorted(f"{s.hop}:{s.spec}" for s in states
                  if s.hop is not None)
    if hops:
        out["hops"] = hops
    return out


__all__ = ["EF_VERSION", "CompressionState", "compression_layout",
           "init_state", "iter_compression_states"]
