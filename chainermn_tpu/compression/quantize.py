"""int8 / fp8 quantizers — per-chunk power-of-two scales, stochastic
rounding, in-wire summation.

The collective between ``compress`` and ``decompress`` SUMS wire values
in wire arithmetic (``lax.psum`` / ``lax.psum_scatter`` over int8 or
float8 buffers — XLA lowers both natively).  That forces two design
points:

* **Overflow-safe codes**: each rank clips its codes to ``max_code //
  world_size`` (int8: ``127 // W``; fp8 e4m3: ``448 / W``), so the
  summed wire value cannot overflow/saturate no matter how adversarial
  the addends.  What clipping loses, error feedback re-feeds next step.
* **Rank-identical scales**: summing codes is only meaningful when all
  ranks quantized with the same scale.  Scales are therefore *delayed*:
  step t uses the scales derived from step t-1's **summed** (hence
  globally identical) gradient, so every rank updates them identically
  with zero extra collectives.  Scales are powers of two (stored as
  exponents), exactly representable in any float wire — which the FSDP
  seam exploits to piggyback scale redistribution on the parameter
  all-gather.  A cold scale (init ``2**0``) converges geometrically:
  too-small scales clip (EF retries), all-zero codes shrink the
  exponent by 2 per step.
* **Saturation flags on the wire**: the summed amax *underestimates*
  per-rank amplitude whenever ranks cancel (random-sign gradients sum
  to ~``sqrt(W)`` x the per-rank scale), so an amax-only update can
  wedge the scale below the clip point forever — every rank clips,
  the clipped sum looks small, the scale never grows, and the EF
  residual diverges linearly.  Each rank therefore appends one 0/1
  flag per chunk ("did I clip anywhere in this chunk?") to the code
  buffer; the SAME collective sums them into a per-chunk clip count
  (bounded by ``world <= max_code/2``, so the in-wire sum cannot
  saturate and stays nonzero whenever any rank clipped), and any
  nonzero count forces the exponent up by at least 1.  Zero extra
  collectives, ~``1/chunk_size`` wire overhead.

Stochastic rounding (``floor(v/s + u)``, ``u ~ U[0,1)``) keeps the
quantizer unbiased; the PRNG stream is derived from an explicit
``(seed, step, rank)`` triple threaded through the step — deterministic
replay, no hidden RNG state.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from chainermn_tpu.compression import error_feedback as _ef
from chainermn_tpu.compression.base import Compressor, register_compressor

_E_MIN, _E_MAX = -60.0, 60.0   # exponent clamp (2**±60 covers f32 grads)


class _ScaledQuantizer(Compressor):
    """Shared machinery of the int8/fp8 codecs (see module docstring).

    Subclasses pin ``wire`` (the collective dtype), ``max_code`` (the
    symmetric wire range), and ``_round`` (integer vs float-ulp
    stochastic rounding).
    """

    stateful = True
    wire: str = "?"
    max_code: float = 0.0

    def __init__(self, chunk_size: int = 1024, stochastic: bool = True,
                 seed: int = 0, headroom: float = 2.0):
        if chunk_size < 1:
            raise ValueError(f"chunk_size must be >= 1, got {chunk_size}")
        self.chunk_size = int(chunk_size)
        self.stochastic = bool(stochastic)
        self.seed = int(seed)
        self.headroom = float(headroom)

    def config(self):
        return {"chunk_size": self.chunk_size,
                "stochastic": self.stochastic,
                "seed": self.seed, "headroom": self.headroom}

    def wire_dtype_for(self, dtype):
        return jnp.dtype(self.wire)

    # -- wire budget ---------------------------------------------------------
    def clip_limit(self, world_size: int) -> float:
        """Per-rank |code| bound so the in-wire sum cannot overflow."""
        c = self.max_code / world_size
        if c < 2.0:
            raise ValueError(
                f"{self.name} in-wire summation needs max_code/world >= 2 "
                f"(got {self.max_code}/{world_size}): too few code levels "
                f"per rank — use fp8 or an uncompressed wire at this world "
                f"size")
        return c

    def effective_clip(self, world_size: int) -> float:
        """The |code| bound :meth:`encode` actually applies (the int8
        codec floors :meth:`clip_limit` to the integer grid)."""
        return self.clip_limit(world_size)

    #: saturation-flag threshold, in multiples of the clip limit.  Mild
    #: tail clipping (a lone outlier a hair past the limit) is GOOD —
    #: EF re-feeds it and the finer scale helps every other coordinate
    #: — so the flag only fires past this margin.  A genuinely wedged
    #: scale blows through it within a step or two regardless: the
    #: clipped excess re-enters through the EF residual, so the
    #: pre-quantization value COMPOUNDS until the flag trips.
    sat_margin = 2.0

    def saturation_flags(self, v, scale_pos, world_size: int,
                         chunk_len: int):
        """Per-chunk 0/1 wire flags: did THIS rank clip past
        ``sat_margin`` x the clip limit anywhere in the chunk?
        Appended to the code buffer so the clip count rides the codes'
        own collective — the summed count tells every rank to escalate
        a wedged scale even when cancellation hides the clipping from
        the summed amax (see module docstring)."""
        c = self.sat_margin * self.effective_clip(world_size)
        over = jnp.abs(v / scale_pos) > c
        return jnp.any(over.reshape(-1, chunk_len),
                       axis=1).astype(jnp.dtype(self.wire))

    # -- PRNG ----------------------------------------------------------------
    def make_key(self, step, rank=None):
        """Stochastic-rounding key for (seed, step[, rank]) — explicit
        and replayable; ``rank`` decorrelates the per-rank dither."""
        key = jax.random.fold_in(jax.random.PRNGKey(self.seed),
                                 jnp.asarray(step, jnp.int32))
        if rank is not None:
            key = jax.random.fold_in(key, jnp.asarray(rank, jnp.int32))
        return key

    # -- codec primitives (shared by both exchange seams) --------------------
    def encode(self, v, scale_pos, key, world_size: int):
        raise NotImplementedError

    def decode(self, codes, scale_pos):
        return codes.astype(jnp.float32) * scale_pos

    def next_exponent(self, e_prev, summed_amax, world_size: int,
                      sat_count=None):
        """Delayed pow2 scale update from the globally-identical SUMMED
        amax (per chunk): target per-rank amplitude ``amax/world`` at
        ``clip/headroom`` code levels; all-zero chunks shrink by 2**-2
        per step so a cold-started too-large scale converges fast.

        ``sat_count`` (the summed per-chunk clip flags, identical on
        every rank because the wire sum is) breaks the cancellation
        stall: any rank reporting heavy clipping (``sat_margin`` past
        the limit — mild tail clipping stays invisible, EF handles it)
        forces the exponent up by at least 1 that step.  A wedged scale
        re-trips the flag every couple of steps because the clipped
        excess compounds through the EF residual, so the scale climbs
        until the bulk of the mass fits."""
        c = self.clip_limit(world_size)
        target = (self.headroom * summed_amax) / (world_size * c)
        e_new = jnp.ceil(jnp.log2(jnp.maximum(target, 2.0 ** _E_MIN)))
        cand = jnp.where(summed_amax > 0, e_new, e_prev - 2.0)
        if sat_count is not None:
            cand = jnp.where(sat_count > 0,
                             jnp.maximum(cand, e_prev + 1.0), cand)
        return jnp.clip(cand, _E_MIN, _E_MAX)

    # -- allreduce-seam protocol --------------------------------------------
    def _padded(self, length: int) -> int:
        return length + (-length) % self.chunk_size

    def n_chunks(self, length: int) -> int:
        return self._padded(length) // self.chunk_size

    def init_state(self, length: int, world_size: int = 1, hop=None):
        del world_size  # shape-independent; kept for API symmetry
        return _ef.init_state(self, self._padded(int(length)),
                              self.n_chunks(int(length)), hop=hop)

    def scale_per_pos(self, scale_e):
        return jnp.repeat(jnp.exp2(scale_e), self.chunk_size)

    def compress(self, buf, state, rank=None, world_size: int = 1):
        """EF-compress one flat float buffer into wire codes (padded to
        the chunk grid, with one trailing saturation flag per chunk).
        Residual and step advance; scales are read only (they update in
        :meth:`decompress`, from summed data)."""
        m = int(buf.shape[0])
        mp = self._padded(m)
        v = jnp.zeros((mp,), jnp.float32).at[:m].set(
            buf.astype(jnp.float32))
        v = v + state.ef
        sp = self.scale_per_pos(state.scale)
        key = self.make_key(state.step[0], rank)
        codes = self.encode(v, sp, key, world_size)
        new_ef = v - self.decode(codes, sp)
        flags = self.saturation_flags(v, sp, world_size, self.chunk_size)
        return (jnp.concatenate([codes, flags]),
                state._replace(ef=new_ef, step=state.step + 1.0))

    def decompress(self, wire, state, world_size: int = 1):
        """Decode the SUMMED wire buffer back to a float32 SUM (the
        caller divides by world for mean semantics) and advance the
        delayed scales from its per-chunk amax and summed clip count —
        identical on every rank because the summed wire is."""
        mp = int(state.ef.shape[0])
        sp = self.scale_per_pos(state.scale)
        out = self.decode(wire[:mp], sp)
        amax = jnp.max(jnp.abs(out).reshape(-1, self.chunk_size), axis=1)
        new_e = self.next_exponent(state.scale, amax, world_size,
                                   wire[mp:].astype(jnp.float32))
        return out, state._replace(scale=new_e)


class Int8Compressor(_ScaledQuantizer):
    """int8 wire: ``codes = clip(round(v / 2**e), ±(127 // W))``, summed
    across ranks in int8 arithmetic (~4x fewer wire bytes than f32)."""

    name = "int8"
    wire = "int8"
    max_code = 127.0

    def effective_clip(self, world_size: int) -> float:
        return float(int(self.clip_limit(world_size)))

    def encode(self, v, scale_pos, key, world_size: int):
        c = self.effective_clip(world_size)
        q = v / scale_pos
        if self.stochastic:
            q = jnp.floor(q + jax.random.uniform(key, q.shape))
        else:
            q = jnp.round(q)
        return jnp.clip(q, -c, c).astype(jnp.int8)


class Fp8Compressor(_ScaledQuantizer):
    """float8_e4m3 wire: scaled values cast to fp8 and summed in fp8
    arithmetic — coarser than int8 near the chunk amax (3 mantissa
    bits) but with ~2**15 dynamic range inside a chunk, so it tolerates
    heavy-tailed gradients that int8's uniform grid clips.  Stochastic
    rounding dithers by the value's own e4m3 ulp before the cast."""

    name = "fp8"
    wire = "float8_e4m3fn"
    max_code = 448.0

    def encode(self, v, scale_pos, key, world_size: int):
        c = self.clip_limit(world_size)
        q = jnp.clip(v / scale_pos, -c, c)
        if self.stochastic:
            # e4m3 has 3 mantissa bits: ulp(x) = 2**(floor(log2|x|) - 3);
            # frexp's exponent e has |x| in [2**(e-1), 2**e)
            _, e = jnp.frexp(q)
            ulp = jnp.exp2(jnp.asarray(e - 1 - 3, jnp.float32))
            q = q + (jax.random.uniform(key, q.shape) - 0.5) * ulp
        return jnp.clip(q, -c, c).astype(jnp.float8_e4m3fn)


register_compressor(Int8Compressor.name, Int8Compressor)
register_compressor(Fp8Compressor.name, Fp8Compressor)

# The quantizing codecs, for seams that must branch on "lossy or not".
QUANTIZERS = (Int8Compressor, Fp8Compressor)


def is_quantizing(comp) -> bool:
    return isinstance(comp, _ScaledQuantizer)


def wire_bits_per_param(comp, length: int, world_size: int = 1) -> float:
    """Achieved wire bits per parameter, counting the chunk-grid pad
    and the per-chunk saturation flags (the
    ``compression_bits_per_param`` metric)."""
    if not is_quantizing(comp):
        return float(np.dtype(jnp.float32).itemsize * 8)
    mp = comp._padded(int(length)) + comp.n_chunks(int(length))
    item_bits = jnp.dtype(comp.wire).itemsize * 8
    return item_bits * mp / max(int(length), 1)


# -- weight quantization (serving) -------------------------------------------
# Unlike the gradient codecs above (delayed pow2 scales, in-wire
# summation), inference weights are quantized ONCE, offline, with exact
# per-channel amax scales — no EF, no wire-sum overflow budget.


def quantize_per_channel_int8(w, channel_axis: int = -1):
    """Symmetric per-channel int8: ``codes = round(w / s)`` with
    ``s = amax / 127`` per slice along ``channel_axis`` (the output
    channel for a ``[in, out]`` kernel).  Returns ``(codes int8,
    scale f32)`` with ``scale`` shaped to broadcast against ``codes``.
    All-zero channels get scale 1 (codes are all zero anyway)."""
    w = jnp.asarray(w, jnp.float32)
    axes = tuple(a for a in range(w.ndim)
                 if a != channel_axis % w.ndim)
    amax = jnp.max(jnp.abs(w), axis=axes, keepdims=True)
    scale = jnp.where(amax > 0, amax / 127.0, 1.0)
    codes = jnp.clip(jnp.round(w / scale), -127, 127).astype(jnp.int8)
    return codes, scale


def quantize_per_tensor_int8(w):
    """One scale for the whole tensor — the baseline the per-channel
    property test beats (``tests/test_compression.py``)."""
    w = jnp.asarray(w, jnp.float32)
    amax = jnp.max(jnp.abs(w))
    scale = jnp.where(amax > 0, amax / 127.0, 1.0)
    codes = jnp.clip(jnp.round(w / scale), -127, 127).astype(jnp.int8)
    return codes, scale


def dequantize_int8(codes, scale):
    """Inverse of either weight quantizer (scale broadcasts)."""
    return codes.astype(jnp.float32) * scale


__all__ = ["Fp8Compressor", "Int8Compressor", "QUANTIZERS",
           "dequantize_int8", "is_quantizing",
           "quantize_per_channel_int8", "quantize_per_tensor_int8",
           "wire_bits_per_param"]
