"""Gradient compression subsystem (beyond-reference extension).

Pluggable wire codecs for the three gradient exchange seams —
``Communicator.allreduce_grad(compressor=...)``,
``create_multi_node_optimizer(compression=...)``, and
``fsdp_init(bucket_compressors=...)`` — generalizing the anaruse fork's
``allreduce_grad_dtype`` cast (now exactly ``NoCompression(wire_dtype)``)
into int8/fp8 quantization with error feedback.  See ``base.py`` for
the protocol, ``quantize.py`` for the codecs, ``error_feedback.py`` for
the checkpointed EF state, and ``docs/compression.md`` for when to
reach for which wire.
"""

from chainermn_tpu.compression.base import (
    Compressor,
    NoCompression,
    available_compressors,
    register_compressor,
    resolve_compressor,
)
from chainermn_tpu.compression.error_feedback import (
    EF_VERSION,
    CompressionState,
    compression_layout,
    init_state,
    iter_compression_states,
)
from chainermn_tpu.compression.quantize import (
    Fp8Compressor,
    Int8Compressor,
    is_quantizing,
    wire_bits_per_param,
)
from chainermn_tpu.compression.observe import (
    CompressionObs,
    get_compression_obs,
)

__all__ = [
    "CompressionObs",
    "CompressionState",
    "Compressor",
    "EF_VERSION",
    "Fp8Compressor",
    "Int8Compressor",
    "NoCompression",
    "available_compressors",
    "compression_layout",
    "get_compression_obs",
    "init_state",
    "is_quantizing",
    "iter_compression_states",
    "register_compressor",
    "resolve_compressor",
    "wire_bits_per_param",
]
