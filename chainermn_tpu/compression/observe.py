"""Compression observability — the ``compression_*`` metric family plus
compute-tagged flight spans.

Same zero-cost-when-disabled contract as ``parallel/fsdp._FsdpObs``:
the seams obtain a :class:`CompressionObs` ONCE at build time; with the
metrics switch and the flight recorder both off it is ``None`` and the
traced program carries no callbacks at all.

Metrics (labels ``seam`` ∈ {allreduce, fsdp}, ``bucket``, ``compressor``):

* ``compression_bits_per_param`` (gauge) — achieved wire bits/param
  including the chunk-grid pad and (FSDP) the piggybacked scale slot;
* ``compression_wire_bytes_saved`` (counter) — bytes NOT moved per
  collective vs the uncompressed f32 wire;
* ``compression_residual_norm`` (gauge) — L2 norm of this rank's EF
  residual after compress (the convergence health signal: a decaying /
  flat-low residual is healthy, a growing one means the wire is too
  narrow for the gradient stream).

Flight spans: ``compress`` / ``decompress`` are recorded with
``kind="compute"`` — a slow quantizer must show up in
``identify_desync`` as a *compute straggler*, never as a wedged
collective (the desync analysis only treats collective/object spans as
cross-rank-symmetric progress markers).
"""

from __future__ import annotations

from typing import Optional


class CompressionObs:
    """Begin/end edges for one compress or decompress region, delivered
    from device-side ``jax.debug.callback``\\ s gated to rank 0 (one
    event stream per process, like the FSDP overlap lane)."""

    def __init__(self, flight, registry):
        self.flight = flight
        self.registry = registry
        self._open: dict = {}
        if registry is not None:
            self._bits = registry.gauge(
                "compression_bits_per_param",
                "achieved wire bits per parameter (pad + scale overhead "
                "included)")
            self._saved = registry.counter(
                "compression_wire_bytes_saved",
                "wire bytes not moved vs an uncompressed f32 collective")
            self._residual = registry.gauge(
                "compression_residual_norm",
                "L2 norm of this rank's error-feedback residual")
            self._sat = registry.gauge(
                "compression_saturated_chunks",
                "summed per-chunk saturation-flag count of the last "
                "collective (nonzero = some rank clipped hard; the "
                "delayed scale escalates next step)")

    def edge(self, phase: str, edge: str, seam: str, bucket: int,
             compressor: str, bits_per_param: float, bytes_saved: int,
             residual_norm: Optional[float]) -> None:
        labels = {"seam": seam, "bucket": str(bucket),
                  "compressor": compressor}
        key = (phase, seam, bucket)
        if self.flight is not None:
            if edge == "begin":
                self._open[key] = self.flight.span_begin(
                    "compute", f"{phase}:{seam}", bucket=bucket,
                    compressor=compressor)
            else:
                tok = self._open.pop(key, None)
                if tok is not None:
                    self.flight.span_end(tok)
        if self.registry is not None and edge == "end" \
                and phase == "compress":
            self._bits.set(bits_per_param, **labels)
            self._saved.inc(bytes_saved, **labels)
            if residual_norm is not None:
                self._residual.set(residual_norm, **labels)

    def make_callback(self, phase: str, edge: str, seam: str, bucket: int,
                      compressor: str, bits_per_param: float,
                      bytes_saved: int):
        """A rank-gated debug callback for one edge.  Called with
        ``(rank_idx, residual_norm, _dep)`` where ``_dep`` is a data
        dependency pinning when the device reaches this edge."""

        def cb(rank_idx, residual_norm, _dep):
            if int(rank_idx) == 0:
                self.edge(phase, edge, seam, bucket, compressor,
                          bits_per_param, bytes_saved,
                          float(residual_norm))
        return cb

    def make_sat_callback(self, seam: str, bucket: int, compressor: str):
        """A rank-gated callback recording the summed saturation-flag
        count of one collective (the per-hop planner lane reports it per
        stage).  Called with ``(rank_idx, sat_count, _dep)``."""

        def cb(rank_idx, sat_count, _dep):
            if int(rank_idx) == 0 and self.registry is not None:
                self._sat.set(float(sat_count), seam=seam,
                              bucket=str(bucket), compressor=compressor)
        return cb


def get_compression_obs() -> Optional[CompressionObs]:
    """The build-time hook: ``None`` while observability is off."""
    from chainermn_tpu.observability import flight_recorder as _flight
    from chainermn_tpu.observability import registry as _registry

    fr = _flight.get_flight_recorder()
    reg = _registry.get_registry() if _registry.enabled() else None
    return CompressionObs(fr, reg) if (fr or reg) else None


__all__ = ["CompressionObs", "get_compression_obs"]
