"""Deprecated location — the captured-constant audit moved to
:mod:`chainermn_tpu.analysis.captured` when it was promoted into the
cmn-lint static-analysis subsystem (it is the ``captured-constant``
rule's core; see docs/static_analysis.md).

This module re-exports the public API unchanged so existing imports
(``from chainermn_tpu.utils.jaxpr_audit import
assert_no_captured_constants``) keep working; new code should import
from ``chainermn_tpu.analysis`` instead.
"""

from __future__ import annotations

from chainermn_tpu.analysis.captured import (  # noqa: F401
    CapturedConstantError,
    DEFAULT_MAX_BYTES,
    assert_no_captured_constants,
    captured_constant_message,
    constants_in_jaxpr,
    find_captured_constants,
)

__all__ = ["CapturedConstantError", "DEFAULT_MAX_BYTES",
           "assert_no_captured_constants", "find_captured_constants"]
