"""Device-trace analysis helpers — turn `jax.profiler` captures into
per-op time tables.

**Beyond-reference addition** (the reference had no profiling subsystem —
SURVEY.md §5.1; this is the TPU-side toolbox that replaced nvprof in its
workflow).  The round-2 performance investigation (docs/performance.md)
was driven entirely by these two primitives:

* :func:`device_op_times` — parse a trace directory into summed
  device-side op durations (host/tunnel time excluded, which on
  tunneled dev platforms differs from wall clock by 10s of percent);
* :func:`device_time` — time a callable by device timestamps instead of
  wall clock (profile-capture + parse in one call), immune to the
  async-dispatch and early-`block_until_ready` illusions.

Usage::

    from chainermn_tpu.utils.trace import device_time, top_ops

    ms = device_time(step, (params, opt_state, batch), steps=10)
    table = top_ops("/tmp/trace_dir", n=20)   # [(name, ms, count), ...]
"""

from __future__ import annotations

import collections
import glob
import gzip
import json
import re
import shutil
import tempfile
from typing import Callable, Dict, List, Optional, Tuple

import numpy as np

_CATEGORY_RE = re.compile(r"\.\d+$")


def _load_trace(trace_dir: str) -> Tuple[dict, Dict[int, str]]:
    paths = sorted(glob.glob(
        f"{trace_dir}/plugins/profile/*/*.trace.json.gz"))
    if not paths:
        raise FileNotFoundError(
            f"no trace under {trace_dir!r} (expected "
            "plugins/profile/*/*.trace.json.gz — was the capture stopped?)")
    data = json.load(gzip.open(paths[-1]))
    pids = {}
    for e in data["traceEvents"]:
        if e.get("ph") == "M" and e.get("name") == "process_name":
            pids[e["pid"]] = e["args"]["name"]
    return data, pids


def _detect_device_track(pids: Dict[int, str]) -> str:
    """Pick the device track from the trace's process names.

    Prefers a TPU track (lowest-numbered), falls back to the first
    ``/device:`` track of any backend — so the same analysis code reads
    CPU-mesh and GPU captures without callers hard-coding
    ``/device:TPU:0`` (which silently sums zero events off-TPU).
    """
    tracks = sorted(v for v in pids.values() if v.startswith("/device:"))
    if not tracks:
        raise ValueError(
            "no /device: track in trace (process names: "
            f"{sorted(set(pids.values()))}) — not a device capture?")
    for t in tracks:
        if t.startswith("/device:TPU:"):
            return t
    return tracks[0]


def device_op_times(trace_dir: str,
                    device: Optional[str] = None) -> Dict[str, Tuple[float, int]]:
    """Sum device-side op durations from a profiler capture.

    Returns ``{op_name: (total_ms, count)}`` for complete events on the
    given device track, excluding the per-program wrapper events
    (``jit_*`` and bare step numbers) so the values are real op time.
    ``device=None`` auto-detects the track (TPU preferred, else the
    first ``/device:`` process in the capture).
    """
    data, pids = _load_trace(trace_dir)
    if device is None:
        device = _detect_device_track(pids)
    acc: Dict[str, List[float]] = collections.defaultdict(lambda: [0.0, 0])
    for e in data["traceEvents"]:
        if (e.get("ph") == "X" and "dur" in e
                and pids.get(e["pid"]) == device):
            name = e["name"]
            if name.startswith("jit_") or re.fullmatch(r"\d+", name):
                continue
            a = acc[name]
            a[0] += e["dur"] / 1e3
            a[1] += 1
    return {k: (v[0], v[1]) for k, v in acc.items()}


def top_ops(trace_dir: str, n: int = 20, by_category: bool = False,
            device: Optional[str] = None) -> List[Tuple[str, float, int]]:
    """Top-``n`` ops (or name-categories, with trailing ``.N`` stripped)
    by total device time: ``[(name, total_ms, count), ...]`` descending."""
    times = device_op_times(trace_dir, device=device)
    if by_category:
        cat: Dict[str, List[float]] = collections.defaultdict(lambda: [0.0, 0])
        for name, (ms, c) in times.items():
            a = cat[_CATEGORY_RE.sub("", name)]
            a[0] += ms
            a[1] += c
        times = {k: (v[0], v[1]) for k, v in cat.items()}
    rows = [(k, ms, c) for k, (ms, c) in times.items()]
    rows.sort(key=lambda r: -r[1])
    return rows[:n]


def device_time(fn: Callable, args: tuple, steps: int = 5, warmup: int = 2,
                trace_dir: Optional[str] = None,
                device: Optional[str] = None) -> float:
    """Per-call device-side milliseconds of ``fn(*args)``.

    Captures a profiler trace around ``steps`` calls and sums the device
    track — the number wall clocks cannot give on platforms where
    dispatch is asynchronous and ``block_until_ready`` may return early
    (this image's tunnel inflates wall time by a fixed ~10 ms/call and
    once overstated a throughput 20×; see docs/performance.md).

    The final output is fenced with a device→host VALUE read, so every
    timed call has actually executed.  ``trace_dir=None`` uses (and
    removes) a temporary directory; pass a path to keep the capture.
    """
    import jax

    def fence(out):
        leaf = jax.tree.leaves(out)[0]
        jax.block_until_ready(leaf)
        np.asarray(jax.device_get(leaf)).ravel()[:1]

    for _ in range(warmup):
        out = fn(*args)
    fence(out)
    tmp = trace_dir or tempfile.mkdtemp(prefix="chainermn_tpu_trace_")
    try:
        jax.profiler.start_trace(tmp)
        for _ in range(steps):
            out = fn(*args)
        fence(out)
        jax.profiler.stop_trace()
        total = sum(ms for ms, _ in device_op_times(tmp, device=device).values())
    finally:
        if trace_dir is None:
            shutil.rmtree(tmp, ignore_errors=True)
    return total / steps


__all__ = ["device_op_times", "device_time", "top_ops"]
