"""Host-local placement onto multi-controller meshes.

``jax.device_put`` onto a sharding that spans OTHER processes issues
cross-host point-to-point transfers whose wire order is not coordinated
between ranks.  Two ranks placing several leaves concurrently (a resume,
a bcast, an optimizer init) can interleave those transfers into a gloo
size-mismatch abort — observed on the CPU collectives backend as

    gloo::EnforceNotMet ... op.preamble.length <= op.nbytes. A vs B

during elastic restarts, where A and B are two different leaves' shard
byte counts.  Every call site in this codebase that places host values
into a mesh-wide sharding already holds the bytes its own devices need
(replicated params after a control-plane ``bcast_obj``, a restored
checkpoint read from the rank's own file, identically-computed init
state), so the global array can be assembled purely from addressable
shards — no network, no ordering hazard.
"""

from __future__ import annotations

import jax
import numpy as np

__all__ = ["local_device_put"]


def local_device_put(x, sharding):
    """``jax.device_put(x, sharding)`` that never crosses processes.

    When ``sharding`` is fully addressable (single-controller worlds,
    sub-meshes owned by this process) this IS ``jax.device_put``.  When
    it spans other processes, each leaf's global array is built from the
    host-local value via ``jax.make_array_from_callback`` — valid
    because the caller guarantees this process already holds the data
    for its own shards (replicated values, or per-device stacks computed
    identically on every rank).

    Pytree-aware; leaves must be host-materializable on this process
    (numpy arrays or fully-addressable jax arrays).
    """
    if getattr(sharding, "is_fully_addressable", True):
        return jax.device_put(x, sharding)

    def _leaf(v):
        arr = np.asarray(v)
        return jax.make_array_from_callback(
            arr.shape, sharding, lambda idx: arr[idx])

    return jax.tree.map(_leaf, x)
