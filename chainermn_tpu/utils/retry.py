"""Bounded retry for tunneled-TPU transient failures.

The development image reaches its TPU through a network tunnel whose
remote-compile requests occasionally drop mid-read; round 2's official
benchmark number was lost to exactly one such hiccup.  This module is the
ONE copy of the transient/deterministic classification used by ``bench.py``,
``tools/tpu_smoke.py`` and any other hardware-evidence harness: transient
transport failures are retried (after clearing compile caches), while
deterministic failures (OOM, INVALID_ARGUMENT, UNIMPLEMENTED) surface
immediately — re-running a doomed measurement for minutes only to hit the
same wall is worse than failing fast.
"""

from __future__ import annotations

import sys
import time
from typing import Callable, TypeVar

T = TypeVar("T")

# Substrings identifying a transient tunnel/transport failure worth
# retrying (lower-cased match against "TypeName: message").
TRANSIENT_MARKERS = (
    "remote_compile", "read body", "closed before", "unavailable",
    "deadline", "connection", "socket", "reset by peer", "broken pipe",
    "eof", "timed out", "timeout", "internal: ", "transport",
)

DETERMINISTIC_MARKERS = (
    "resource_exhausted", "invalid_argument", "out of memory",
    "unimplemented", "not implemented",
)


def is_transient(exc: BaseException) -> bool:
    msg = f"{type(exc).__name__}: {exc}".lower()
    if any(s in msg for s in DETERMINISTIC_MARKERS):
        return False
    if any(s in msg for s in TRANSIENT_MARKERS):
        return True
    # Any other XLA/jax runtime error on the tunneled backend is far more
    # likely a transport hiccup than a harness bug (the code paths are
    # test-covered on CPU); err on the side of retrying those too.
    return "xlaruntimeerror" in msg or "jaxruntimeerror" in msg


def retry_transient(fn: Callable[[], T], attempts: int = 3,
                    label: str = "attempt") -> T:
    """Run ``fn`` with up to ``attempts`` tries on transient failures.

    Between tries: closes any profiler trace the failed attempt left open
    (``start_trace`` would raise on the retry) and drops compiled
    executables so the next attempt re-issues remote_compile on a fresh
    request; then backs off 5 s x attempt-number.
    """
    for attempt in range(1, max(1, attempts) + 1):
        try:
            return fn()
        except Exception as e:  # noqa: BLE001 — classified below
            transient = is_transient(e)
            print(f"{label}: try {attempt}/{attempts} failed with "
                  f"{type(e).__name__}: {e} (transient={transient})",
                  file=sys.stderr, flush=True)
            if attempt >= attempts or not transient:
                raise
            try:
                import jax

                try:
                    jax.profiler.stop_trace()
                except Exception:
                    pass
                jax.clear_caches()
            except Exception as ce:
                print(f"{label}: backend cleanup failed ({ce}); continuing",
                      file=sys.stderr, flush=True)
            time.sleep(5 * attempt)
    raise AssertionError("unreachable")


__all__ = ["is_transient", "retry_transient", "TRANSIENT_MARKERS"]
