"""Virtual CPU device-mesh bootstrap.

The test/dryrun analogue of the reference's ``mpiexec -n 8`` on one box
(SURVEY.md §4): an n-device CPU mesh in a single process, over which every
communicator runs real XLA collectives.

This image's sitecustomize pre-initializes the TPU backend at interpreter
startup, so ``JAX_PLATFORMS``/``JAX_NUM_CPU_DEVICES`` set later are ignored.
The only reliable in-process recovery is to tear the backend down
(``jax.extend.backend.clear_backends()`` clears the "initialized" latch)
and re-configure.  That fragile sequence lives here, once, shared by
``tests/conftest.py`` and ``__graft_entry__.dryrun_multichip``.
"""

from __future__ import annotations


def _set_cpu_device_flags(n: int) -> None:
    """Request ``n`` CPU devices on whichever knob this jax version has.

    jax >= 0.5 exposes ``jax_num_cpu_devices`` (re-readable after a backend
    reset); older versions only honor ``--xla_force_host_platform_device_count``
    in XLA_FLAGS, which the CPU client latches at its FIRST creation — so on
    those versions this must run before any backend exists.
    """
    import os
    import re

    import jax

    try:
        jax.config.update("jax_num_cpu_devices", n)
    except AttributeError:
        # Replace any inherited count rather than defer to it: a spawned
        # worker inherits its parent's XLA_FLAGS (e.g. the test suite's
        # 8-device mesh) but needs its OWN local device count.
        flags = re.sub(r"--xla_force_host_platform_device_count=\d+", "",
                       os.environ.get("XLA_FLAGS", ""))
        os.environ["XLA_FLAGS"] = (
            flags.strip() + f" --xla_force_host_platform_device_count={n}"
        ).strip()


def _backend_uninitialized() -> bool:
    """True when no XLA client has been created yet in this process (so
    CPU-mesh config can still take effect on every jax version)."""
    try:
        from jax._src import xla_bridge

        return not xla_bridge._backends
    except Exception:
        return False


def reset_to_cpu_mesh(n: int) -> None:
    """Tear down the current JAX backend and bring up ``n`` CPU devices."""
    import jax
    import jax.extend as jex

    jex.backend.clear_backends()
    jax.config.update("jax_platforms", "cpu")
    _set_cpu_device_flags(n)
    devs = jax.devices()
    assert jax.default_backend() == "cpu" and len(devs) >= n, (
        f"CPU mesh bootstrap failed: backend={jax.default_backend()} "
        f"devices={len(devs)} (wanted >= {n})")


def ensure_cpu_mesh(n: int = 8) -> None:
    """Guarantee a CPU backend with at least ``n`` devices (tests)."""
    import jax

    if _backend_uninitialized():
        # Configure BEFORE the first backend is created: on jax < 0.5 the
        # CPU device count is read from XLA_FLAGS exactly once, at first
        # client creation, and a post-hoc reset cannot grow the mesh.
        jax.config.update("jax_platforms", "cpu")
        _set_cpu_device_flags(n)
    try:
        ok = jax.default_backend() == "cpu" and len(jax.devices()) >= n
    except Exception:
        ok = False
    if not ok:
        reset_to_cpu_mesh(n)


def ensure_device_count(n: int):
    """Return >= ``n`` devices on the current backend if it already has
    them (real chips win), else reset to an ``n``-device CPU mesh.

    Guarded against a pre-initialized backend that fails outright (e.g. the
    TPU plugin present but no chip attached): any error counts as zero
    devices and triggers the CPU-mesh reset.
    """
    import jax

    try:
        devices = jax.devices()
    except Exception:
        devices = []
    if len(devices) < n:
        reset_to_cpu_mesh(n)
        devices = jax.devices()
    return devices
