"""Virtual CPU device-mesh bootstrap.

The test/dryrun analogue of the reference's ``mpiexec -n 8`` on one box
(SURVEY.md §4): an n-device CPU mesh in a single process, over which every
communicator runs real XLA collectives.

This image's sitecustomize pre-initializes the TPU backend at interpreter
startup, so ``JAX_PLATFORMS``/``JAX_NUM_CPU_DEVICES`` set later are ignored.
The only reliable in-process recovery is to tear the backend down
(``jax.extend.backend.clear_backends()`` clears the "initialized" latch)
and re-configure.  That fragile sequence lives here, once, shared by
``tests/conftest.py`` and ``__graft_entry__.dryrun_multichip``.
"""

from __future__ import annotations


def reset_to_cpu_mesh(n: int) -> None:
    """Tear down the current JAX backend and bring up ``n`` CPU devices."""
    import jax
    import jax.extend as jex

    jex.backend.clear_backends()
    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_num_cpu_devices", n)
    devs = jax.devices()
    assert jax.default_backend() == "cpu" and len(devs) >= n, (
        f"CPU mesh bootstrap failed: backend={jax.default_backend()} "
        f"devices={len(devs)} (wanted >= {n})")


def ensure_cpu_mesh(n: int = 8) -> None:
    """Guarantee a CPU backend with at least ``n`` devices (tests)."""
    import jax

    try:
        ok = jax.default_backend() == "cpu" and len(jax.devices()) >= n
    except Exception:
        ok = False
    if not ok:
        reset_to_cpu_mesh(n)


def ensure_device_count(n: int):
    """Return >= ``n`` devices on the current backend if it already has
    them (real chips win), else reset to an ``n``-device CPU mesh.

    Guarded against a pre-initialized backend that fails outright (e.g. the
    TPU plugin present but no chip attached): any error counts as zero
    devices and triggers the CPU-mesh reset.
    """
    import jax

    try:
        devices = jax.devices()
    except Exception:
        devices = []
    if len(devices) < n:
        reset_to_cpu_mesh(n)
        devices = jax.devices()
    return devices
