"""Gradient-accumulation scan shared by the SPMD step builders.

One implementation of the subtle carry machinery (vma-varying zero
accumulators, f32 loss carry, aux averaging) used by BOTH
``optimizers.make_train_step(accum_steps=K)`` and
``parallel.fsdp.make_fsdp_train_step(accum_steps=K)`` — they previously
carried near-verbatim copies that had already drifted cosmetically.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from chainermn_tpu.utils import pvary


def accumulate_microbatches(compute, model_state, batch, accum_steps,
                            has_aux):
    """Scan ``compute`` over K equal microbatches of the local shard.

    ``compute(model_state, microbatch) -> (loss, aux, model_state,
    grads)`` with ``aux`` None when ``has_aux`` is False; ``grads`` is
    any pytree (a param tree, a shard list, ...).  Returns the same
    4-tuple with loss/aux/grads AVERAGED over the K microbatches and the
    model state threaded through sequentially.  Must be called inside
    the shard_map body: the accumulators are typed (shape, dtype, AND
    varying-axes) from an abstract trace of one microbatch, so both
    device-varying local losses and psum-reduced invariant global losses
    carry through the scan correctly.
    """
    b_local = jax.tree.leaves(batch)[0].shape[0]
    if b_local % accum_steps:
        raise ValueError(
            f"accum_steps ({accum_steps}) must divide the "
            f"per-device batch ({b_local})")
    micro = jax.tree.map(
        lambda a: a.reshape((accum_steps, b_local // accum_steps)
                            + a.shape[1:]), batch)

    def body(carry, mb):
        ms, g_acc, loss_acc, aux_acc = carry
        loss, aux, ms, grads = compute(ms, mb)
        g_acc = jax.tree.map(jnp.add, g_acc, grads)
        aux_acc = (jax.tree.map(jnp.add, aux_acc, aux)
                   if has_aux else aux_acc)
        return (ms, g_acc, loss_acc + loss, aux_acc), None

    # accumulators start as zeros shaped (and varying-axes-TYPED) like one
    # microbatch's outputs; eval_shape traces abstractly (no extra
    # compile) and its structs carry the exact vma the scan carry must
    # match — a psum-reduced (invariant) loss stays invariant, per-device
    # grads stay varying
    shapes = jax.eval_shape(
        lambda: compute(model_state, jax.tree.map(lambda a: a[0], micro)))

    def zeros_typed(s):
        z = jnp.zeros(s.shape, s.dtype)
        want = tuple(getattr(s, "vma", None) or ())
        return pvary(z, want) if want else z

    g0 = jax.tree.map(zeros_typed, shapes[3])
    a0 = jax.tree.map(zeros_typed, shapes[1]) if has_aux else None
    l0 = jnp.zeros((), jnp.float32)
    loss_vma = tuple(getattr(shapes[0], "vma", None) or ())
    if loss_vma:
        l0 = pvary(l0, loss_vma)
    (model_state, grads, loss, aux), _ = jax.lax.scan(
        body, (model_state, g0, l0, a0), micro)
    k = jnp.float32(accum_steps)
    grads = jax.tree.map(lambda g: g / k.astype(g.dtype), grads)
    loss = loss / k
    if has_aux:
        aux = jax.tree.map(lambda a: a / k.astype(a.dtype), aux)
    return loss, aux, model_state, grads


__all__ = ["accumulate_microbatches"]
