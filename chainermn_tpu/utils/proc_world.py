"""Spawn a real multi-controller world for validation.

The reference's multi-node tests ran under ``mpiexec -n N pytest``
〔SURVEY.md §4〕; this rebuild has no launcher, so validation harnesses
(tests, the driver's ``dryrun_multichip``) spawn N controller processes
directly: each child gets the ``CHAINERMN_TPU_*`` bootstrap env contract,
its own CPU device set, and reports results as a ``RESULT {json}`` stdout
line.  This module is the ONE copy of that choreography — port pairing,
env construction, harvest, and orphan cleanup (a surviving child blocked
in a collective against a dead coordinator would outlive the whole run).
"""

from __future__ import annotations

import json
import os
import socket
import subprocess
import sys
import time
from typing import Dict, Optional


def free_port() -> int:
    """A free TCP port (single — for control planes with no sidecar)."""
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def free_port_pair() -> int:
    """A free TCP port whose successor is also free: the control plane
    binds the given port and jax's coordination service binds port+1."""
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    t = socket.socket()
    try:
        t.bind(("127.0.0.1", port + 1))
    except OSError:
        t.close()
        return free_port_pair()
    t.close()
    return port


def spawn_world(worker_src: str, n_procs: int = 2, local_devices: int = 4,
                timeout: float = 600.0,
                repo: Optional[str] = None) -> Dict[int, dict]:
    """Run ``worker_src`` in ``n_procs`` controller processes and return
    ``{rank: parsed_result}`` from each worker's ``RESULT {json}`` line.

    Workers bootstrap with ``chainermn_tpu.init_distributed(
    local_device_count=...)`` using the ``CHAINERMN_TPU_*`` env contract
    set here; ``CHAINERMN_TPU_REPO`` points at the package checkout (the
    children drop axon_site from PYTHONPATH so they come up as pure-CPU
    worlds).  On any failure every still-running child is killed before
    the error propagates — no orphans; a crashed rank surfaces as soon as
    it exits, even while its siblings are still blocked on it.

    Workers must keep their stdout/stderr small (a RESULT line plus
    incidental warnings): pipes are only drained after exit, so a child
    streaming more than the ~64 KB pipe buffer would block itself.
    """
    if repo is None:
        repo = os.path.dirname(os.path.dirname(
            os.path.dirname(os.path.abspath(__file__))))
    coord = f"127.0.0.1:{free_port_pair()}"
    procs = []
    for r in range(n_procs):
        env = dict(os.environ)
        env.update({
            "CHAINERMN_TPU_COORDINATOR": coord,
            "CHAINERMN_TPU_NUM_PROCESSES": str(n_procs),
            "CHAINERMN_TPU_PROCESS_ID": str(r),
            "CHAINERMN_TPU_REPO": repo,
            "PYTHONPATH": repo,
            "JAX_PLATFORMS": "cpu",
            "JAX_NUM_CPU_DEVICES": str(local_devices),
        })
        procs.append(subprocess.Popen(
            [sys.executable, "-c", worker_src], env=env,
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True))
    results: Dict[int, dict] = {}
    try:
        # Poll ALL children: a crashed rank must surface immediately, not
        # after the full timeout spent blocking on a sibling that is itself
        # only hung waiting for the dead one.
        deadline = time.monotonic() + timeout
        while True:
            states = [p.poll() for p in procs]
            for r, (p, st) in enumerate(zip(procs, states)):
                if st is not None and st != 0:
                    stdout, stderr = p.communicate()
                    raise RuntimeError(
                        f"worker rank {r} failed (rc={st})\n"
                        f"stderr:\n{stderr[-3000:]}\n"
                        f"stdout:\n{stdout[-1000:]}")
            if all(st is not None for st in states):
                break
            if time.monotonic() > deadline:
                alive = [r for r, st in enumerate(states) if st is None]
                raise RuntimeError(
                    f"spawn_world timed out after {timeout}s; "
                    f"rank(s) {alive} still running")
            time.sleep(0.1)
        for r, p in enumerate(procs):
            stdout, _ = p.communicate()
            lines = [l for l in stdout.splitlines()
                     if l.startswith("RESULT ")]
            if not lines:
                raise RuntimeError(
                    f"worker rank {r} produced no RESULT line:\n{stdout}")
            results[r] = json.loads(lines[0][len("RESULT "):])
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    return results
