"""TPU device metadata shared by the benchmarks.

One table so every bench computes MFU against the same peak; a number
corrected here propagates to bench.py, bench_vit.py and any future MFU
report at once (they used to carry private copies that could drift).
"""

from __future__ import annotations

# bf16 peak TFLOP/s per chip, keyed by a lowercase substring of
# jax.Device.device_kind
PEAK_TFLOPS = {
    "tpu v5 lite": 197.0,
    "tpu v5e": 197.0,
    "tpu v4": 275.0,
    "tpu v6 lite": 918.0,
    "tpu v6e": 918.0,
}

_DEFAULT_PEAK = 197.0  # assume v5e-class when the kind string is unknown


def peak_tflops(device) -> float:
    """bf16 peak of ``device`` (a ``jax.Device``), by device_kind substring."""
    kind = getattr(device, "device_kind", "").lower()
    for k, v in PEAK_TFLOPS.items():
        if k in kind:
            return v
    return _DEFAULT_PEAK


__all__ = ["PEAK_TFLOPS", "peak_tflops"]
