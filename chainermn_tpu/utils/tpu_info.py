"""TPU device metadata shared by the benchmarks.

One table so every bench computes MFU against the same peak; a number
corrected here propagates to bench.py, bench_vit.py and any future MFU
report at once (they used to carry private copies that could drift).
"""

from __future__ import annotations

# bf16 peak TFLOP/s per chip, keyed by a lowercase substring of
# jax.Device.device_kind
PEAK_TFLOPS = {
    "tpu v5 lite": 197.0,
    "tpu v5e": 197.0,
    "tpu v4": 275.0,
    "tpu v6 lite": 918.0,
    "tpu v6e": 918.0,
}

_DEFAULT_PEAK = 197.0  # assume v5e-class when the kind string is unknown


def peak_tflops(device) -> float:
    """bf16 peak of ``device`` (a ``jax.Device``), by device_kind substring."""
    return peak_tflops_info(device)[0]


def peak_tflops_info(device):
    """``(peak, matched_kind)`` — ``matched_kind`` is the PEAK_TFLOPS key
    that matched ``device.device_kind``, or ``None`` when the device is
    unknown and ``peak`` is the assumed v5e-class default.  Benchmarks use
    the None case to mark their MFU as computed against an ASSUMED peak
    (``peak_assumed: true`` in the bench JSON) instead of presenting a
    made-up utilization as fact (ADVICE r5)."""
    kind = getattr(device, "device_kind", "").lower()
    for k, v in PEAK_TFLOPS.items():
        if k in kind:
            return v, k
    return _DEFAULT_PEAK, None


__all__ = ["PEAK_TFLOPS", "peak_tflops", "peak_tflops_info"]
