"""Small shared utilities (version compatibility, tree helpers)."""

from __future__ import annotations

import jax


def pvary(x, axes):
    """Mark ``x`` as varying over mesh ``axes`` inside shard_map.

    ``jax.lax.pvary`` is deprecated in favor of ``jax.lax.pcast(..., to=
    'varying')``; this shim targets whichever this jax version provides.
    """
    if hasattr(jax.lax, "pcast"):
        return jax.lax.pcast(x, axes, to="varying")
    return jax.lax.pvary(x, axes)


def axis_size(axis_name) -> int:
    """Size of a bound mesh axis (``lax.axis_size`` where available, else
    the ``psum(1)`` idiom older jax versions require)."""
    if hasattr(jax.lax, "axis_size"):
        return jax.lax.axis_size(axis_name)
    return jax.lax.psum(1, axis_name)


__all__ = ["axis_size", "pvary"]
