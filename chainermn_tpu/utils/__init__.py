"""Small shared utilities (version compatibility, tree helpers)."""

from __future__ import annotations

import jax


def pvary(x, axes):
    """Mark ``x`` as varying over mesh ``axes`` inside shard_map.

    Idempotent (axes already in the value's vma are skipped — pcast
    rejects varying→varying).  ``jax.lax.pvary`` is deprecated in favor
    of ``jax.lax.pcast(..., to='varying')``; this shim targets whichever
    this jax version provides.
    """
    want = (axes,) if isinstance(axes, str) else tuple(axes)
    try:
        have = jax.typeof(x).vma
        missing = tuple(a for a in want if a not in have)
    except (AttributeError, TypeError):
        missing = want
    if not missing:
        return x
    if hasattr(jax.lax, "pcast"):
        return jax.lax.pcast(x, missing, to="varying")
    return jax.lax.pvary(x, missing)


def axis_size(axis_name) -> int:
    """Size of a bound mesh axis (``lax.axis_size`` where available, else
    the ``psum(1)`` idiom older jax versions require)."""
    if hasattr(jax.lax, "axis_size"):
        return jax.lax.axis_size(axis_name)
    return jax.lax.psum(1, axis_name)


__all__ = ["axis_size", "pvary"]
