"""Small shared utilities (version compatibility, tree helpers)."""

from __future__ import annotations

import jax


def pvary(x, axes):
    """Mark ``x`` as varying over mesh ``axes`` inside shard_map.

    Idempotent (axes already in the value's vma are skipped — pcast
    rejects varying→varying).  ``jax.lax.pvary`` is deprecated in favor
    of ``jax.lax.pcast(..., to='varying')``; this shim targets whichever
    this jax version provides.
    """
    want = (axes,) if isinstance(axes, str) else tuple(axes)
    try:
        have = jax.typeof(x).vma
        missing = tuple(a for a in want if a not in have)
    except (AttributeError, TypeError):
        missing = want
    if not missing:
        return x
    if hasattr(jax.lax, "pcast"):
        return jax.lax.pcast(x, missing, to="varying")
    if hasattr(jax.lax, "pvary"):
        return jax.lax.pvary(x, missing)
    return x  # pre-vma jax: shard_map's check_rep tracks replication itself


_native_shard_map = getattr(jax, "shard_map", None)


def shard_map(f, *, mesh, in_specs, out_specs, **kwargs):
    """``jax.shard_map`` where this jax exports it (>= 0.5), else the
    ``jax.experimental.shard_map`` spelling of older versions, with the
    ``check_vma``/``check_rep`` kwarg rename translated."""
    if _native_shard_map is not None:
        return _native_shard_map(f, mesh=mesh, in_specs=in_specs,
                                 out_specs=out_specs, **kwargs)
    from jax.experimental.shard_map import shard_map as _shard_map

    if "check_vma" in kwargs:
        kwargs["check_rep"] = kwargs.pop("check_vma")
    # Old shard_map's check_rep raises spurious "Scan carry ... mismatched
    # replication types" errors on valid programs (the error text itself
    # suggests check_rep=False); default it off unless the caller asked.
    kwargs.setdefault("check_rep", False)
    return _shard_map(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                      **kwargs)


def axis_size(axis_name) -> int:
    """Size of a bound mesh axis (``lax.axis_size`` where available, else
    the ``psum(1)`` idiom older jax versions require)."""
    if hasattr(jax.lax, "axis_size"):
        return jax.lax.axis_size(axis_name)
    return jax.lax.psum(1, axis_name)


def typeof(x):
    """``jax.typeof`` where available; older versions fall back to the
    abstract value, which simply lacks ``vma`` metadata (callers probe it
    with ``getattr(..., "vma", None)``)."""
    t = getattr(jax, "typeof", None)
    if t is not None:
        return t(x)
    from jax import core

    return core.get_aval(x)


def _install_jax_shard_map_alias() -> None:
    # jax < 0.5 has no jax.shard_map; alias the compat wrapper onto the
    # jax namespace so tests/examples written against the current API
    # (jax.shard_map(..., check_vma=...)) run unchanged on this version.
    if not hasattr(jax, "shard_map"):
        jax.shard_map = shard_map


_install_jax_shard_map_alias()

__all__ = ["axis_size", "pvary", "shard_map", "typeof"]
