"""Small shared utilities (version compatibility, tree helpers)."""

from __future__ import annotations

import jax


def pvary(x, axes):
    """Mark ``x`` as varying over mesh ``axes`` inside shard_map.

    ``jax.lax.pvary`` is deprecated in favor of ``jax.lax.pcast(..., to=
    'varying')``; this shim targets whichever this jax version provides.
    """
    if hasattr(jax.lax, "pcast"):
        return jax.lax.pcast(x, axes, to="varying")
    return jax.lax.pvary(x, axes)


__all__ = ["pvary"]
