"""Concrete mesh-backed communicator machinery.

Reference being rebuilt (path unverified, SURVEY.md provenance):
``MpiCommunicatorBase`` in 〔chainermn/communicators/mpi_communicator_base.py〕
— the generic object/array transport shared by every communicator flavor,
plus the rank bookkeeping of ``init_ranks``.

TPU-native design (see ``communicator_base.py`` for the two-level model):

* object ops delegate to the DCN control plane (host level);
* array collectives are *traced* ops over the communicator's mesh axes —
  XLA lowers them to ICI collectives; there is no hand-rolled transport,
  no pinned staging, no >2 GiB chunking (XLA owns the data plane, which is
  precisely the reference plumbing this rebuild deletes by design —
  SURVEY.md §2.3);
* ``run_spmd`` is the "mpiexec" analogue: it launches a per-device SPMD
  region in which each device acts as one reference rank.
"""

from __future__ import annotations

from collections import OrderedDict
from typing import Callable, List, Optional, Sequence, Tuple

import jax

from chainermn_tpu.utils import shard_map as _shard_map
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from chainermn_tpu.communicators.communicator_base import CommunicatorBase
from chainermn_tpu.parallel import topology as topo_mod
from chainermn_tpu.runtime import control_plane as cp_mod
from chainermn_tpu.utils.placement import local_device_put


class _SplitControlPlane(cp_mod.ControlPlane):
    """Sub-world view over a parent control plane (reference: ``mpi_comm.Split``
    〔mpi_communicator_base.py〕).  Tags are namespaced per split group."""

    def __init__(self, parent: cp_mod.ControlPlane, members: List[int], color: int):
        self._parent = parent
        self._members = members  # parent ranks, ordered by (key, rank)
        self._color = color
        self.rank = members.index(parent.rank)
        self.size = len(members)

    def _tag(self, tag: int) -> int:
        return (self._color + 1) * 100003 + tag

    def send_obj(self, obj, dest, tag=0):
        self._parent.send_obj(obj, self._members[dest], tag=self._tag(tag))

    def recv_obj(self, source, tag=0):
        return self._parent.recv_obj(self._members[source], tag=self._tag(tag))


class MeshCommunicator(CommunicatorBase):
    """Communicator bound to (mesh, data_axes, control plane).

    The collective decomposition is the only thing that distinguishes
    the reference's communicator zoo (naive/flat/hierarchical/...), and
    the same is true here — but the decomposition is now *data*: each
    flavor names a fixed :class:`~chainermn_tpu.planner.ir.Plan` (via
    the ``flavor`` class attribute), and :meth:`_allreduce_grad_traced`
    feeds it to the one plan compiler
    (:func:`chainermn_tpu.planner.compiler.execute_plan`).  Subclasses
    keep their historical hand-lowered bodies as
    ``_legacy_allreduce_grad_traced`` — the parity reference
    ``tests/test_planner.py`` pins HLO-census equivalence against.
    """

    # Only the xla (pure_nccl analogue) communicator accepts a communication
    # dtype, mirroring create_communicator's restriction in the reference
    # factory 〔communicators/__init__.py〕.
    supports_allreduce_grad_dtype = False

    #: fixed-plan name this class executes (chainermn_tpu.planner.plans)
    flavor = "naive"

    def __init__(
        self,
        topology: Optional[topo_mod.Topology] = None,
        mesh: Optional[Mesh] = None,
        data_axes: Optional[Sequence[str]] = None,
        allreduce_grad_dtype=None,
        control_plane: Optional[cp_mod.ControlPlane] = None,
        intra_size: Optional[int] = None,
        compression=None,
    ):
        if topology is None:
            topology = (topo_mod.topology_from_mesh(mesh) if mesh is not None
                        else topo_mod.init_topology(intra_size=intra_size))
        self._topology = topology
        self._mesh = topology.mesh
        self._data_axes: Tuple[str, ...] = tuple(data_axes or self._mesh.axis_names)
        for ax in self._data_axes:
            if ax not in self._mesh.shape:
                raise ValueError(f"axis {ax!r} not in mesh {self._mesh.axis_names}")
        # ``compression`` subsumes the legacy dtype knob: a NoCompression
        # wire folds INTO allreduce_grad_dtype (so every downstream reader
        # of the attribute — ZeRO-1, packing — behaves identically), while
        # quantizing codecs ride their own collective path in
        # allreduce_grad.
        from chainermn_tpu.compression import NoCompression, \
            resolve_compressor
        self.compression = resolve_compressor(compression)
        if isinstance(self.compression, NoCompression) \
                and self.compression.wire is not None:
            if allreduce_grad_dtype is not None and \
                    jnp.dtype(allreduce_grad_dtype) != self.compression.wire:
                raise ValueError(
                    f"conflicting wire dtypes: allreduce_grad_dtype="
                    f"{allreduce_grad_dtype} vs compression="
                    f"{self.compression!r} — pass only "
                    f"compression=NoCompression(wire_dtype=...)")
            allreduce_grad_dtype = self.compression.wire
        if allreduce_grad_dtype is not None and not self.supports_allreduce_grad_dtype:
            # Parity with the reference: only pure_nccl accepts the dtype knob.
            raise ValueError(
                f"{type(self).__name__} does not support allreduce_grad_dtype "
                "(only the 'xla'/'pure_nccl' communicator does)")
        self.allreduce_grad_dtype = (
            jnp.dtype(allreduce_grad_dtype) if allreduce_grad_dtype is not None else None)
        self._cp = control_plane if control_plane is not None else cp_mod.get_control_plane()
        # LRU keyed by (f identity, jit flag).  Bounded: callers that define
        # their body per call would otherwise grow it without limit (and pin
        # the closures' captured arrays) while never hitting.
        self._jit_cache: OrderedDict = OrderedDict()
        self._jit_cache_max = 32

    # ---- topology ----------------------------------------------------------
    @property
    def mesh(self) -> Mesh:
        return self._mesh

    @property
    def data_axes(self) -> Tuple[str, ...]:
        return self._data_axes

    @property
    def rank(self) -> int:
        return self._cp.rank

    @property
    def size(self) -> int:
        return int(np.prod([self._mesh.shape[a] for a in self._data_axes]))

    @property
    def host_size(self) -> int:
        return self._cp.size

    @property
    def host_rank(self) -> int:
        """Controller-process rank — alias of :attr:`rank` (which is already
        host-granular; device-level position is :meth:`axis_index`)."""
        return self.rank

    def _local_coords(self) -> Tuple[int, int]:
        """(inter, intra) grid coordinates of this host's first device."""
        grid = self._mesh.devices
        first_local = None
        for idx, d in np.ndenumerate(grid):
            if d.process_index == jax.process_index():
                first_local = idx
                break
        if first_local is None:
            return (0, 0)
        # Collapse to (leading axes, trailing axis) = (inter-ish, intra-ish).
        return (int(first_local[0]) if len(first_local) > 1 else 0,
                int(first_local[-1]))

    @property
    def intra_rank(self) -> int:
        """HOST-level intra coordinate (this controller's first device).

        The reference's ``intra_rank`` was per-GPU because one process drove
        one GPU; here one controller drives many devices, so in
        single-controller mode this is 0 — device-level coordinates exist
        only inside an SPMD region: use :meth:`intra_axis_index` there (or
        :meth:`axis_index` for the flat per-device rank).
        """
        return self._local_coords()[1]

    @property
    def intra_size(self) -> int:
        return self.plan_topology().intra_size

    @property
    def inter_rank(self) -> int:
        """HOST-level inter coordinate — see :attr:`intra_rank` for the
        host-vs-device semantics caveat; inside SPMD use
        :meth:`inter_axis_index`."""
        return self._local_coords()[0]

    @property
    def inter_size(self) -> int:
        return self.plan_topology().inter_size

    def plan_topology(self):
        """This communicator's data axes as a serializable
        :class:`~chainermn_tpu.planner.ir.PlanTopology` — the ONE source
        of truth for group sizes: the plan compiler, the derived census
        (``analysis.rules.expected_kinds``), the plan table key, and the
        ``intra_size``/``inter_size`` properties all read it.  Last data
        axis = the intra/ICI axis, by the mesh convention."""
        from chainermn_tpu.planner.ir import PlanTopology
        return PlanTopology(axes=tuple(
            (a, int(self._mesh.shape[a])) for a in self._data_axes))

    def plan(self):
        """The fixed plan this flavor executes (xla threads its
        communication dtype in as the plan's wire dtype)."""
        from chainermn_tpu.planner.plans import flavor_plan
        wire = None
        if self.supports_allreduce_grad_dtype and \
                self.allreduce_grad_dtype is not None:
            wire = np.dtype(self.allreduce_grad_dtype).name
        return flavor_plan(self.flavor, wire_dtype=wire)

    def intra_axis_index(self):
        """Device-level intra-node rank (position on the last data axis —
        the ICI axis).  Only meaningful inside an SPMD region; this is the
        device-granular analogue of the reference's per-GPU ``intra_rank``."""
        return lax.axis_index(self._data_axes[-1])

    def inter_axis_index(self):
        """Device-level inter-node rank (flat position on the leading data
        axes — the DCN-ish axes).  Only meaningful inside an SPMD region."""
        if len(self._data_axes) == 1:
            return jnp.zeros((), jnp.int32)
        lead = self._data_axes[:-1]
        return lax.axis_index(lead if len(lead) > 1 else lead[0])

    # ---- object plane ------------------------------------------------------
    def send_obj(self, obj, dest, tag=0):
        self._cp.send_obj(obj, dest, tag=tag)

    def recv_obj(self, source, tag=0):
        return self._cp.recv_obj(source, tag=tag)

    def bcast_obj(self, obj, root=0, tag=0):
        return self._cp.bcast_obj(obj, root=root, tag=tag)

    def gather_obj(self, obj, root=0, tag=0):
        return self._cp.gather_obj(obj, root=root, tag=tag)

    def allgather_obj(self, obj, tag=0):
        return self._cp.allgather_obj(obj, tag=tag)

    def scatter_obj(self, objs, root=0, tag=0):
        return self._cp.scatter_obj(objs, root=root, tag=tag)

    def allreduce_obj(self, obj, op="sum", tag=0):
        return self._cp.allreduce_obj(obj, op=op, tag=tag)

    def barrier(self, tag=900):
        self._cp.barrier(tag=tag)

    # ---- SPMD context ------------------------------------------------------
    def _axis_arg(self):
        return self._data_axes if len(self._data_axes) > 1 else self._data_axes[0]

    def in_spmd_context(self) -> bool:
        """True when called under a trace where this communicator's mesh axes
        are bound (i.e. inside :meth:`run_spmd` / a user ``shard_map``)."""
        try:
            lax.axis_index(self._axis_arg())
            return True
        except NameError:
            return False

    def axis_index(self):
        """Device-level rank (0..size-1) — the reference's per-GPU ``rank``.
        Only meaningful inside an SPMD region."""
        return lax.axis_index(self._axis_arg())

    def run_spmd(self, f: Callable, *stacked_args, jit: bool = True):
        """Run ``f`` once per device, SPMD — the "mpiexec -n size" analogue.

        Every leaf of every arg must have a leading axis of length ``size``
        holding the per-rank values; results come back stacked the same way.
        Inside ``f``, this communicator's traced collectives and
        ``axis_index()`` behave like the reference's per-rank API.

        The mapped/jitted program is cached per ``f`` (by identity), so
        calling ``run_spmd`` with the same function in a loop reuses the
        compiled executable instead of retracing every iteration.
        """
        fn = self._spmd_program(f, jit)
        for i, arg in enumerate(stacked_args):
            for leaf in jax.tree.leaves(arg):
                shape = jnp.shape(leaf)
                if not shape or shape[0] != self.size:
                    raise ValueError(
                        f"run_spmd arg {i}: expected leading per-rank axis of "
                        f"length {self.size}, got shape {shape}")
        return fn(tuple(stacked_args))

    def _spmd_program(self, f: Callable, jit: bool = True):
        """The (cached) shard_map program :meth:`run_spmd` executes."""
        spec = P(self._data_axes)
        key = (f, jit)
        fn = self._jit_cache.get(key)
        if fn is not None:
            self._jit_cache.move_to_end(key)
            return fn

        def per_rank(args):
            squeezed = jax.tree.map(lambda a: jnp.squeeze(a, 0), args)
            out = f(*squeezed)
            return jax.tree.map(lambda a: jnp.expand_dims(a, 0), out)

        fn = _shard_map(per_rank, mesh=self._mesh,
                           in_specs=spec, out_specs=spec)
        if jit:
            fn = jax.jit(fn)
        self._jit_cache[key] = fn
        while len(self._jit_cache) > self._jit_cache_max:
            self._jit_cache.popitem(last=False)
        return fn

    def compiled_hlo(self, f: Callable, *stacked_args) -> str:
        """Optimized HLO text of the program :meth:`run_spmd` would run.

        This is how the per-flavor collective decomposition is pinned as
        an artifact rather than prose: ``bench_allreduce.py --census``
        regex-counts the collectives in this text per flavor and commits
        the result (round-4 judge 'next #5').
        """
        fn = self._spmd_program(f, jit=True)
        return fn.lower(tuple(stacked_args)).compile().as_text()

    # ---- traced collectives ------------------------------------------------
    def allreduce(self, x, op: str = "sum"):
        ax = self._axis_arg()
        if op == "sum":
            return jax.tree.map(lambda v: lax.psum(v, ax), x)
        if op == "mean":
            return jax.tree.map(lambda v: lax.psum(v, ax) / self.size, x)
        if op == "max":
            return jax.tree.map(lambda v: lax.pmax(v, ax), x)
        if op == "min":
            return jax.tree.map(lambda v: lax.pmin(v, ax), x)
        raise ValueError(f"unknown op {op!r}")

    def bcast(self, x, root: int = 0):
        idx = self.axis_index()
        return jax.tree.map(
            lambda v: lax.psum(jnp.where(idx == root, v, jnp.zeros_like(v)),
                               self._axis_arg()),
            x)

    def allgather(self, x):
        """Per-rank value -> stacked [size, ...] on every rank."""
        return jax.tree.map(
            lambda v: lax.all_gather(v, self._axis_arg(), tiled=False), x)

    def gather(self, x, root: int = 0):
        # SPMD programs produce the same output shape on every device, so an
        # asymmetric root-only gather cannot exist inside one XLA program:
        # every device gets the stacked result (an all_gather — ring cost
        # ~bytes/link, the cheapest primitive that realizes these semantics
        # on ICI).  ``root`` is kept for reference-signature parity only;
        # host-level root-only gathers are ``gather_obj`` on the DCN plane.
        del root
        return self.allgather(x)

    def alltoall(self, xs):
        """xs: per-rank array with leading axis == size (one slot per peer).
        Returns the transposed exchange, as the reference's ``alltoall``."""
        if len(self._data_axes) == 1:
            return jax.tree.map(
                lambda v: lax.all_to_all(v, self._data_axes[0], 0, 0, tiled=False),
                xs)
        # Multi-axis worlds (round 3): view the peer axis as the
        # [S1, S2, ...] axis grid — row-major, matching axis_index over the
        # axis tuple — and exchange ONE mesh axis at a time, splitting and
        # concatenating along that axis's own slot dimension.  After all
        # axes, out[(j1, j2)] = in_(j1, j2)[(r1, r2)]: the full transposed
        # exchange at O(bytes/axis) wire cost, vs the previous
        # allgather+slice fallback's O(size x bytes).
        sizes = tuple(self._mesh.shape[a] for a in self._data_axes)

        def one(v):
            g = v.reshape(sizes + v.shape[1:])
            for d, a in enumerate(self._data_axes):
                g = lax.all_to_all(g, a, d, d, tiled=False)
            return g.reshape(v.shape)

        return jax.tree.map(one, xs)

    def scatter(self, x, root: int = 0):
        """x: stacked [size, ...] (meaningful on root; SPMD requires the value
        be present everywhere) -> this rank's slice.

        Implemented as a psum_scatter of the root-masked stack: device i
        receives sum_j masked_j[i] = root's slice i.  One ring reduce-scatter
        pass (~bytes/link) — half the wire traffic of the naive
        bcast-then-slice (a full allreduce, ~2x bytes/link), and no device
        ever materializes the [size, ...] stack it doesn't need.
        """
        idx = self.axis_index()

        def one(v):
            masked = jnp.where(idx == root, v, jnp.zeros_like(v))
            return lax.psum_scatter(masked, self._axis_arg(), tiled=False)

        return jax.tree.map(one, x)

    def reduce_scatter(self, x):
        return jax.tree.map(
            lambda v: lax.psum_scatter(v, self._axis_arg(), tiled=True), x)

    def ppermute(self, x, perm: List[Tuple[int, int]]):
        # lax.ppermute takes one axis name; that's fine as long as at most one
        # data axis is non-trivial (size > 1).  Multi-axis worlds should
        # split_axes() down to the axis they mean.
        nontrivial = [a for a in self._data_axes if self._mesh.shape[a] > 1]
        if len(nontrivial) > 1:
            raise ValueError("ppermute requires a single non-trivial axis; "
                             "use split_axes() to select one mesh axis")
        axis = nontrivial[0] if nontrivial else self._data_axes[-1]
        return jax.tree.map(lambda v: lax.ppermute(v, axis, perm), x)

    # ---- gradient entry points ---------------------------------------------
    def allreduce_grad(self, grads, *, compressor=None, state=None):
        """Average gradients across the data-parallel world.

        Reference: ``Communicator.allreduce_grad(model)``
        〔communicator_base.py〕, in-place on ``param.grad``; here functional.

        * Inside an SPMD region (``run_spmd`` / shard_map): performs this
          communicator's collective decomposition (psum-mean over mesh axes).
        * Eagerly in single-controller mode: gradients computed from a
          globally-sharded batch are already the global mean (XLA inserted
          the collective during backward); only the communication-dtype
          roundtrip remains observable, and it is applied for numerical
          parity with the reference's cast-allreduce-cast path.

        ``compressor`` selects the wire codec for THIS call (default: the
        communicator's ``compression=`` / ``allreduce_grad_dtype`` config):

        * ``None`` / ``NoCompression()`` — the paths above, unchanged;
        * ``NoCompression(wire_dtype=...)`` — pack-cast-psum-unpack,
          bit-for-bit the ``allreduce_grad_dtype`` program;
        * a quantizer (``"int8"`` / ``"fp8"``) — stateful EF compression:
          pass ``state`` (a :class:`~chainermn_tpu.compression.\
CompressionState` from :meth:`init_compression_state`) and the call
          returns ``(mean_grads, new_state)`` instead of just grads;
        * a :class:`~chainermn_tpu.planner.Plan` with per-hop
          ``Stage.compression`` specs — the DynamiQ path: quantize only
          the stages that cross the slow hop, with one EF state per
          compressed stage.  ``state`` is the ``{stage_index:
          CompressionState}`` dict from :meth:`init_compression_state`
          (returns ``(mean_grads, new_states)``); with ``state=None``
          the plan runs from cold in-trace EF (one-shot semantics).
          Passing a stage-keyed ``state`` dict with ``compressor=None``
          runs this communicator's own :meth:`plan` per hop.
        """
        from chainermn_tpu.compression import base as _cbase
        from chainermn_tpu.compression import quantize as _cq
        from chainermn_tpu.planner.ir import Plan as _Plan
        plan = compressor if isinstance(compressor, _Plan) else None
        if plan is None and isinstance(state, dict):
            plan = self.plan()
        if plan is not None:
            return self._allreduce_grad_plan(grads, plan, state)
        comp = (_cbase.resolve_compressor(compressor)
                if compressor is not None else
                (self.compression if _cq.is_quantizing(self.compression)
                 else None))
        if _cq.is_quantizing(comp):
            if state is None:
                raise ValueError(
                    f"compressor {comp.name!r} keeps error-feedback state: "
                    "pass state=comm.init_compression_state(grads, "
                    "compressor) and thread the returned new state into "
                    "the next call")
            return self._allreduce_grad_compressed(grads, comp, state)
        wire = comp.wire if comp is not None else None
        if self.in_spmd_context():
            if wire is not None:
                return self._allreduce_grad_wire(grads, wire)
            return self._allreduce_grad_traced(grads)
        dt = wire if wire is not None else self.allreduce_grad_dtype
        if dt is None:
            return grads
        return jax.tree.map(lambda g: g.astype(dt).astype(g.dtype), grads)

    # Upstream ChainerMN later renamed this; keep both spellings.
    multi_node_mean_grad = allreduce_grad

    def init_compression_state(self, tree, compressor=None):
        """Fresh error-feedback state for quantized :meth:`allreduce_grad`
        over ``tree``-shaped gradients (``None`` for stateless codecs).
        Sized for the single packed float32 buffer the compressed path
        exchanges.

        ``compressor`` may also be a :class:`~chainermn_tpu.planner.Plan`
        with per-hop ``Stage.compression`` specs, in which case the
        result is the ``{stage_index: CompressionState}`` dict of
        per-hop EF states, each sized to the buffer AT that stage
        (post-reduce-scatter hops see a shard, not the full packed
        buffer) and tagged with its stage index for the checkpoint
        sidecar."""
        from chainermn_tpu.compression import base as _cbase
        from chainermn_tpu.compression import quantize as _cq
        from chainermn_tpu.planner.ir import Plan as _Plan
        n = sum(int(np.prod(jnp.shape(l))) for l in jax.tree.leaves(tree))
        if isinstance(compressor, _Plan):
            from chainermn_tpu.planner.compiler import (
                init_plan_compression_states)
            return init_plan_compression_states(
                compressor, self.plan_topology(), n)
        comp = (_cbase.resolve_compressor(compressor)
                if compressor is not None else self.compression)
        if not _cq.is_quantizing(comp):
            return None
        return comp.init_state(n, self.size)

    def _allreduce_grad_plan(self, grads, plan, states):
        """Per-hop compressed exchange: execute ``plan`` with one EF
        state per quantizing stage (``states`` keyed by stage index).
        Returns ``(mean_grads, new_states)`` when ``states`` is given,
        plain ``mean_grads`` for the stateless one-shot path."""
        from chainermn_tpu.planner.compiler import (
            execute_plan, plan_compressed_hops)
        if not self.in_spmd_context():
            raise ValueError(
                "per-hop compressed allreduce_grad executes a plan and "
                "must run inside an SPMD region (run_spmd / shard_map); "
                "eager single-controller mode has no per-stage hops")
        if states is not None:
            hops = plan_compressed_hops(plan, self.plan_topology())
            missing = sorted(set(hops) - set(states))
            if missing:
                raise ValueError(
                    f"per-hop compression states missing for stage(s) "
                    f"{missing} of plan {plan.name!r}: build them with "
                    "comm.init_compression_state(grads, plan)")
        return execute_plan(plan, self, grads, states=states)

    def _allreduce_grad_wire(self, grads, wire):
        """NoCompression(wire_dtype): the exact cast-allreduce-cast
        program of the ``allreduce_grad_dtype`` knob (xla communicator's
        non-pallas lowering) — one packed buffer in the wire dtype, one
        psum, unpack with the 1/size mean folded in."""
        from chainermn_tpu.communicators import _packing
        buffers, meta = _packing.pack(grads, comm_dtype=wire)
        ax = self._axis_arg()
        buffers = [lax.psum(b, ax) for b in buffers]
        return _packing.unpack(buffers, meta, scale=1.0 / self.size)

    def _allreduce_grad_compressed(self, grads, comp, state):
        """Quantized exchange: pack to one f32 buffer, EF-encode to wire
        codes, SUM the codes in wire arithmetic, decode + delayed-scale
        update, mean, unpack.  Returns ``(mean_grads, new_state)``."""
        from chainermn_tpu.communicators import _packing
        from chainermn_tpu.compression import observe as _cobs
        from chainermn_tpu.compression import quantize as _cq
        traced = self.in_spmd_context()
        n = self.size if traced else 1
        buffers, meta = _packing.pack(grads, comm_dtype=jnp.float32)
        buf = buffers[0]
        m = int(buf.shape[0])
        if int(state.ef.shape[0]) != comp._padded(m):
            raise ValueError(
                f"compression state sized for ef={state.ef.shape[0]} "
                f"does not match this gradient tree (needs "
                f"{comp._padded(m)}): build it with "
                "comm.init_compression_state(grads, compressor)")
        obs = _cobs.get_compression_obs() if traced else None
        rank = self.axis_index() if traced else None
        if obs is not None:
            bpp = _cq.wire_bits_per_param(comp, m, n)
            saved = (m * 4 - (comp._padded(m) + comp.n_chunks(m))
                     * jnp.dtype(comp.wire).itemsize)
            jax.debug.callback(
                obs.make_callback("compress", "begin", "allreduce", 0,
                                  comp.name, bpp, saved),
                rank, 0.0, buf[0])
        codes, state = comp.compress(buf, state, rank=rank, world_size=n)
        if obs is not None:
            rnorm = jnp.sqrt(jnp.sum(jnp.square(state.ef)))
            jax.debug.callback(
                obs.make_callback("compress", "end", "allreduce", 0,
                                  comp.name, bpp, saved),
                rank, rnorm, codes[0])
        summed = lax.psum(codes, self._axis_arg()) if traced else codes
        if obs is not None:
            jax.debug.callback(
                obs.make_callback("decompress", "begin", "allreduce", 0,
                                  comp.name, bpp, saved),
                rank, 0.0, summed[0])
        out, state = comp.decompress(summed, state, world_size=n)
        if obs is not None:
            jax.debug.callback(
                obs.make_callback("decompress", "end", "allreduce", 0,
                                  comp.name, bpp, saved),
                rank, 0.0, out[0])
        out = out[:m]
        scale = (1.0 / n) if traced else None
        return _packing.unpack([out], meta, scale=scale), state

    def _allreduce_grad_traced(self, grads):
        """Execute this flavor's fixed plan through the one compiler.
        The zoo's per-class hand-lowered bodies live on as
        ``_legacy_allreduce_grad_traced`` parity references."""
        from chainermn_tpu.planner.compiler import execute_plan
        return execute_plan(self.plan(), self, grads)

    def _legacy_allreduce_grad_traced(self, grads):
        """Pre-planner decomposition (naive): per-leaf psum over all
        data axes.  Kept verbatim as the census-parity reference."""
        n = self.size
        ax = self._axis_arg()
        return jax.tree.map(lambda g: lax.psum(g, ax) / n, grads)

    def bcast_data(self, params):
        """Broadcast model parameters from rank 0 to the whole world.

        Reference: ``Communicator.bcast_data(model)`` — called once after
        model init so every worker starts from identical weights.
        """
        if self.in_spmd_context():
            return self.bcast(params, root=0)
        if self.host_size > 1:
            host_vals = jax.device_get(params)
            host_vals = self.bcast_obj(host_vals, root=0)
            params = host_vals
        repl = NamedSharding(self._mesh, P())
        # after the control-plane bcast every host holds the bytes, so
        # placement must stay process-local (utils/placement.py)
        return local_device_put(params, repl)

    # ---- sub-communicators -------------------------------------------------
    def split(self, color: int, key: int) -> "MeshCommunicator":
        """Host-level split (reference: ``CommunicatorBase.split`` via
        ``mpi_comm.Split``).  Hosts sharing ``color`` form a new world,
        ranked by ``key``; the new communicator's mesh spans the member
        hosts' devices."""
        # Allgather both the control-plane rank and jax.process_index(): the
        # two numberings need not agree (env-var bootstrap may order ranks
        # differently), so device membership is decided by process_index.
        infos = self.allgather_obj((color, key, self.rank, jax.process_index()))
        group = sorted((t for t in infos if t[0] == color),
                       key=lambda t: (t[1], t[2]))
        members = [t[2] for t in group]
        member_procs = {t[3] for t in group}
        sub_cp = _SplitControlPlane(self._cp, members, color)
        if self.host_size == 1:
            sub_topo = self._topology
        else:
            devs = [d for d in self._mesh.devices.flat
                    if d.process_index in member_procs]
            sub_topo = topo_mod.init_topology(devices=devs)
        return type(self)(topology=sub_topo, control_plane=sub_cp)

    def split_axes(self, axes: Sequence[str]) -> "MeshCommunicator":
        """TPU-idiomatic split: a communicator over a subset of this mesh's
        axes (e.g. hybrid data x model parallelism on one mesh — the
        factorization the reference reached via ``comm.split``).

        Keeps this communicator's flavor (collective decomposition and
        communication dtype) when the flavor's axis requirements still hold
        on the sub-world; otherwise falls back to the generic per-leaf psum
        communicator.
        """
        from chainermn_tpu.compression import quantize as _cq
        kwargs = {}
        if self.supports_allreduce_grad_dtype and self.allreduce_grad_dtype is not None:
            kwargs["allreduce_grad_dtype"] = self.allreduce_grad_dtype
        if _cq.is_quantizing(self.compression):
            # Quantizers are flavor-independent (they ride pack/psum), so
            # they survive any sub-world — unlike the dtype knob above.
            kwargs["compression"] = self.compression
        try:
            return type(self)(topology=self._topology, data_axes=tuple(axes),
                              control_plane=self._cp, **kwargs)
        except ValueError:
            # e.g. hierarchical/two_dimensional need >= 2 axes
            return MeshCommunicator(topology=self._topology, data_axes=tuple(axes),
                                    control_plane=self._cp,
                                    compression=kwargs.get("compression"))
