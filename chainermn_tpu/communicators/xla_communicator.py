"""XLA communicator — the ``pure_nccl`` analogue and this framework's flagship.

Reference (path unverified, SURVEY.md provenance): ``PureNcclCommunicator``
〔chainermn/communicators/pure_nccl_communicator.py〕 — the fork's signature
component: every collective over one global NCCL communicator; gradient
allreduce = pack -> ncclAllReduce -> scale 1/size -> unpack, entirely on GPU
streams; ``allreduce_grad_dtype='float16'`` casts fp32 grads to an fp16
buffer (runtime-compiled CUDA cast kernel), allreduces in fp16, casts back —
the mixed-precision contribution behind the 15-minute ImageNet result.

TPU-native version: one packed flat buffer in the communication dtype
(``allreduce_grad_dtype``; pass ``bfloat16`` for the TPU-natural half type,
``None`` keeps each leaf's own dtype), a single ``lax.psum`` over
*all* data axes at once (XLA emits the fused ICI/DCN collective), and a
cast+scale fused into unpack.  The cast-in / scale+cast-out can optionally
run through the Pallas kernel in ``chainermn_tpu/ops/cast_scale.py`` (the
native-kernel parity item, SURVEY.md §2.3) — by default XLA's own fusion is
used, which profiling shows is already a single fused op.
"""

from typing import Optional

import jax.numpy as jnp
from jax import lax

from chainermn_tpu.communicators import _packing
from chainermn_tpu.communicators.mesh_communicator_base import MeshCommunicator


class XlaCommunicator(MeshCommunicator):
    supports_allreduce_grad_dtype = True
    flavor = "xla"

    def __init__(self, *args, allreduce_grad_dtype=None, use_pallas_cast: bool = False,
                 **kwargs):
        super().__init__(*args, allreduce_grad_dtype=allreduce_grad_dtype, **kwargs)
        self.use_pallas_cast = use_pallas_cast

    def _allreduce_grad_traced(self, grads):
        if self.use_pallas_cast and self.allreduce_grad_dtype is not None:
            # The Pallas cast+scale kernel path stays hand-lowered: it
            # is a kernel-selection knob, not a decomposition (the stage
            # sequence is identical to the plan's single all-reduce).
            return self._pallas_allreduce_grad_traced(grads)
        # Plan path: flat pack in the wire dtype, one all-reduce, fused
        # cast-back+scale — the base delegates to the plan compiler.
        return super()._allreduce_grad_traced(grads)

    def _pallas_allreduce_grad_traced(self, grads):
        comm_dtype = self.allreduce_grad_dtype
        ax = self._axis_arg()
        scale = 1.0 / self.size
        from chainermn_tpu.ops.cast_scale import cast_scale

        # Per-dtype groups keep each leaf's original dtype in meta so the
        # cast-back target is known per buffer.
        buffers, meta = _packing.pack(grads)
        _, group_dtypes, _ = meta
        comm_bufs = [cast_scale(b, comm_dtype, 1.0) for b in buffers]
        comm_bufs = [lax.psum(b, ax) for b in comm_bufs]
        out = [cast_scale(b, jnp.dtype(k), scale)
               for b, k in zip(comm_bufs, group_dtypes)]
        return _packing.unpack(out, meta, scale=None)

    def _legacy_allreduce_grad_traced(self, grads):
        # pre-planner lowering, kept as the census-parity reference
        if self.use_pallas_cast and self.allreduce_grad_dtype is not None:
            return self._pallas_allreduce_grad_traced(grads)
        comm_dtype = self.allreduce_grad_dtype
        ax = self._axis_arg()
        buffers, meta = _packing.pack(grads, comm_dtype=comm_dtype)
        buffers = [lax.psum(b, ax) for b in buffers]
        return _packing.unpack(buffers, meta, scale=1.0 / self.size)


# The reference name, kept as an alias so stock scripts'
# ``create_communicator('pure_nccl')`` resolves to the TPU data-plane class.
PureXlaCommunicator = XlaCommunicator
