"""Flat communicator — single packed-buffer allreduce.

Reference (path unverified, SURVEY.md provenance): ``FlatCommunicator`` in
〔chainermn/communicators/flat_communicator.py〕 — pack all grads into one
contiguous GPU buffer, one CUDA-aware ``MPI.Allreduce`` over it, unpack.

Here: concatenate all leaves into flat per-dtype buffers, one ``lax.psum``
per buffer, split back.  The pack/unpack is traced; XLA owns the memory
(reference's ``DeviceMemory`` staging disappears by design, SURVEY.md §2.3).
"""

from jax import lax

from chainermn_tpu.communicators import _packing
from chainermn_tpu.communicators.mesh_communicator_base import MeshCommunicator


class FlatCommunicator(MeshCommunicator):
    flavor = "flat"

    def _legacy_allreduce_grad_traced(self, grads):
        # pre-planner lowering, kept as the census-parity reference
        buffers, meta = _packing.pack(grads)
        ax = self._axis_arg()
        buffers = [lax.psum(b, ax) for b in buffers]
        return _packing.unpack(buffers, meta, scale=1.0 / self.size)
