"""Single-node communicator — ICI only.

Reference (path unverified, SURVEY.md provenance): ``SingleNodeCommunicator``
〔chainermn/communicators/single_node_communicator.py〕 — NCCL-only, asserts
``size == intra_size``.  Here: asserts the world is one slice (no inter axis)
and reduces over ICI alone.
"""

from jax import lax

from chainermn_tpu.communicators.mesh_communicator_base import MeshCommunicator


class SingleNodeCommunicator(MeshCommunicator):
    flavor = "single_node"

    def __init__(self, *args, **kwargs):
        super().__init__(*args, **kwargs)
        # inter_size reads the shared PlanTopology descriptor — the same
        # group sizes the plan compiler and derived census see
        if self.plan_topology().inter_size != 1:
            raise ValueError(
                f"single_node communicator requires inter_size == 1, got "
                f"{self.inter_size}; use 'hierarchical' for multi-host worlds")

    def _legacy_allreduce_grad_traced(self, grads):
        # pre-planner lowering, kept as the census-parity reference
        import jax
        intra_axis = self._data_axes[-1]
        inter_axes = self._data_axes[:-1]
        n = self.size

        def one(g):
            g = lax.psum(g, intra_axis)   # the ICI leg — the whole reduction
            if inter_axes:
                # inter_size == 1 is a class invariant (checked in
                # __init__), so this psum moves no data.  It exists to
                # clear the device-varying type over the trivial inter
                # axes: pvary marks gradients varying over ALL data axes,
                # and shard_map's replication check rejects the invariant
                # params out_spec if any axis's variance survives —
                # exactly what happened on a 1-device world (found by
                # tools/tpu_smoke.py on the real chip).
                g = lax.psum(g, inter_axes)
            return g / n

        return jax.tree.map(one, grads)
