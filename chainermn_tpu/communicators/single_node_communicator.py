"""Single-node communicator — ICI only.

Reference (path unverified, SURVEY.md provenance): ``SingleNodeCommunicator``
〔chainermn/communicators/single_node_communicator.py〕 — NCCL-only, asserts
``size == intra_size``.  Here: asserts the world is one slice (no inter axis)
and reduces over ICI alone.
"""

from jax import lax

from chainermn_tpu.communicators.mesh_communicator_base import MeshCommunicator


class SingleNodeCommunicator(MeshCommunicator):
    def __init__(self, *args, **kwargs):
        super().__init__(*args, **kwargs)
        if self.inter_size != 1:
            raise ValueError(
                f"single_node communicator requires inter_size == 1, got "
                f"{self.inter_size}; use 'hierarchical' for multi-host worlds")

    def _allreduce_grad_traced(self, grads):
        import jax
        intra_axis = self._data_axes[-1]
        n = self.size
        return jax.tree.map(lambda g: lax.psum(g, intra_axis) / n, grads)
