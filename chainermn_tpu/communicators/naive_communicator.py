"""Naive communicator — per-parameter allreduce.

Reference (path unverified, SURVEY.md provenance): ``NaiveCommunicator`` in
〔chainermn/communicators/naive_communicator.py〕 — one ``MPI.Allreduce`` per
parameter on host arrays; CPU-friendly, the test/CI workhorse.

Here: one ``lax.psum`` per gradient leaf over all data axes.  XLA will often
fuse/combine them anyway, but the decomposition is structurally per-leaf,
matching the reference.  Works on any backend, including the virtual CPU mesh
used by the test suite.
"""

from chainermn_tpu.communicators.mesh_communicator_base import MeshCommunicator


class NaiveCommunicator(MeshCommunicator):
    flavor = "naive"  # the base's per-leaf plan *is* the naive decomposition
