"""Auto communicator — the tuned flavor.

``create_communicator("auto", plan_table=...)`` routes every
``allreduce_grad`` through the plan the autotuned table selects for this
(topology, gradient dtype, packed byte size) — the planner's answer to
the fixed zoo: instead of the user picking a flavor once, the table
picks the measured-fastest decomposition per message-size bucket
(``chainermn_tpu/planner/autotune.py``; tuned from
``bench_allreduce.py --sweep`` rows).

Message size is static at trace time (gradient shapes are known), so
plan selection happens in Python during tracing — different step
functions/bucket sizes compile to different decompositions with zero
runtime dispatch cost, and retracing on a new tree shape re-selects.

With no table (or a table miss) the flat plan runs — the generic
single-all-reduce decomposition that is legal on every topology.
"""

from __future__ import annotations

from typing import Optional, Union

import jax
import jax.numpy as jnp
import numpy as np

from chainermn_tpu.communicators.mesh_communicator_base import MeshCommunicator
from chainermn_tpu.planner.autotune import PlanTable
from chainermn_tpu.planner.ir import Plan
from chainermn_tpu.planner.plans import flavor_plan


class AutoCommunicator(MeshCommunicator):
    flavor = "auto"

    def __init__(self, *args,
                 plan_table: Union[None, str, dict, PlanTable] = None,
                 **kwargs):
        super().__init__(*args, **kwargs)
        if plan_table is None:
            self.plan_table = PlanTable()
        elif isinstance(plan_table, PlanTable):
            self.plan_table = plan_table
        elif isinstance(plan_table, dict):
            self.plan_table = PlanTable.from_dict(plan_table)
        else:
            self.plan_table = PlanTable.load(plan_table)

    def swap_plan_table(self, plan_table: Union[dict, PlanTable]) -> None:
        """Hot-swap the plan table (the online tuner's step-boundary
        apply).  Selection is trace-time, so the swap is the assignment
        plus dropping this communicator's cached SPMD programs — the
        next dispatch retraces and ``plan_for`` re-selects against the
        new table.  Callers holding their own ``jax.jit`` step (e.g.
        ``make_train_step``'s) must drop that cache too
        (``step_fn.clear_cache()``); ``MetricsReport`` does both."""
        self.plan_table = plan_table if isinstance(plan_table, PlanTable) \
            else PlanTable.from_dict(plan_table)
        cache = getattr(self, "_jit_cache", None)
        if cache is not None:
            cache.clear()

    def plan(self) -> Plan:
        """The fallback plan (table-independent); per-message selection
        happens in :meth:`plan_for`."""
        return flavor_plan("flat")

    def plan_for(self, nbytes: int, dtype) -> Plan:
        """Tuned plan for a packed payload of ``nbytes`` of ``dtype`` on
        this communicator's topology (fallback: the flat plan)."""
        found = self.plan_table.lookup(self.plan_topology(),
                                       np.dtype(dtype).name, int(nbytes))
        return found if found is not None else self.plan()

    def _allreduce_grad_traced(self, grads):
        from chainermn_tpu.planner.compiler import execute_plan
        from chainermn_tpu.planner.schedule import register_plan_slot
        leaves = jax.tree.leaves(grads)
        nbytes = sum(int(np.prod(jnp.shape(l)) or 1)
                     * jnp.dtype(l.dtype).itemsize for l in leaves)
        # key the lookup on the dominant gradient dtype (by bytes)
        by_dtype: dict = {}
        for l in leaves:
            name = np.dtype(l.dtype).name
            by_dtype[name] = by_dtype.get(name, 0) + \
                int(np.prod(jnp.shape(l)) or 1) * jnp.dtype(l.dtype).itemsize
        dtype = max(by_dtype, key=lambda k: by_dtype[k]) if by_dtype \
            else "float32"
        # announce the in-flight gradient allreduce to the global
        # scheduler (trace time — shapes are static), so a joint retune
        # can re-price it against whatever else shares the links; its
        # compiled plan stages show up in occupancy timelines under
        # "plan:<scope>" (or "fsdp"/"collective" on pre-planner paths)
        register_plan_slot("allreduce", nbytes=nbytes, dtype=dtype,
                           op="all-reduce",
                           owners=("plan:", "fsdp", "collective"))
        return execute_plan(self.plan_for(nbytes, dtype), self, grads)
