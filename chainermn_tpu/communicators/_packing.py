"""Flat-buffer pack / unpack of gradient pytrees.

Reference being rebuilt (path unverified, SURVEY.md provenance):
``pack_params`` / ``unpack_params`` / ``DeviceMemory`` in
〔chainermn/communicators/_memory_utility.py〕 — gather every ``param.grad``
into one contiguous GPU buffer by byte offset (with optional dtype cast via a
runtime-compiled CUDA kernel), run one collective over the buffer, scatter
back.

TPU-native version: the "buffer" is a flat jnp array built inside the traced
allreduce; XLA owns the actual memory.  Leaves are grouped by dtype (one flat
buffer per dtype) unless a communication dtype is forced, in which case a
single buffer is used and the cast in/out is fused by XLA (or by the Pallas
cast+scale kernel, see ``chainermn_tpu/ops/cast_scale.py``).
"""

from __future__ import annotations

from typing import Any, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np


def pack(tree: Any, comm_dtype: Optional[jnp.dtype] = None):
    """Flatten a pytree into per-dtype flat buffers.

    Returns ``(buffers, meta)`` where ``buffers`` is a list of 1-D arrays and
    ``meta`` recovers the tree via :func:`unpack`.
    """
    leaves, treedef = jax.tree.flatten(tree)
    if not leaves:
        return [], (treedef, [], [])
    groups: dict = {}
    order = []  # (group_key, index_within_group, shape, orig_dtype)
    for leaf in leaves:
        key = "comm" if comm_dtype is not None else str(leaf.dtype)
        groups.setdefault(key, [])
        order.append((key, len(groups[key]), leaf.shape, leaf.dtype))
        flat = leaf.reshape(-1)
        if comm_dtype is not None and leaf.dtype != comm_dtype:
            flat = flat.astype(comm_dtype)
        groups[key].append(flat)
    keys = list(groups.keys())
    buffers = [jnp.concatenate(groups[k]) if len(groups[k]) > 1 else groups[k][0]
               for k in keys]
    return buffers, (treedef, keys, order)


def unpack(buffers: List[jnp.ndarray], meta, scale: Optional[float] = None):
    """Inverse of :func:`pack`; optionally fuses a ``*= scale`` (the
    reference's 1/size multiply, fused with the cast-back kernel)."""
    treedef, keys, order = meta
    if not order:
        return jax.tree.unflatten(treedef, [])
    if scale is not None:
        buffers = [b * jnp.asarray(scale, b.dtype) for b in buffers]
    # Compute split points per group.
    offsets = {k: [0] for k in keys}
    sizes: dict = {k: [] for k in keys}
    for key, _, shape, _ in order:
        n = int(np.prod(shape)) if shape else 1
        sizes[key].append(n)
        offsets[key].append(offsets[key][-1] + n)
    pieces_by_group = {}
    for k, buf in zip(keys, buffers):
        cuts = offsets[k][1:-1]
        pieces_by_group[k] = jnp.split(buf, cuts) if cuts else [buf]
    leaves = []
    for key, idx, shape, dtype in order:
        piece = pieces_by_group[key][idx].reshape(shape)
        if piece.dtype != dtype:
            piece = piece.astype(dtype)
        leaves.append(piece)
    return jax.tree.unflatten(treedef, leaves)


def pad_to_multiple(buf: jnp.ndarray, m: int) -> Tuple[jnp.ndarray, int]:
    """Pad a flat buffer so its length divides ``m`` (needed by the
    reduce-scatter leg of the two-dimensional communicator)."""
    n = buf.shape[0]
    rem = (-n) % m
    if rem:
        buf = jnp.concatenate([buf, jnp.zeros((rem,), buf.dtype)])
    return buf, rem
