"""Flat-buffer pack / unpack of gradient pytrees.

Reference being rebuilt (path unverified, SURVEY.md provenance):
``pack_params`` / ``unpack_params`` / ``DeviceMemory`` in
〔chainermn/communicators/_memory_utility.py〕 — gather every ``param.grad``
into one contiguous GPU buffer by byte offset (with optional dtype cast via a
runtime-compiled CUDA kernel), run one collective over the buffer, scatter
back.

TPU-native version: the "buffer" is a flat jnp array built inside the traced
allreduce; XLA owns the actual memory.  Leaves are grouped by dtype (one flat
buffer per dtype) unless a communication dtype is forced, in which case a
single buffer is used and the cast in/out is fused by XLA (or by the Pallas
cast+scale kernel, see ``chainermn_tpu/ops/cast_scale.py``).
"""

from __future__ import annotations

from typing import Any, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np


def pack(tree: Any, comm_dtype: Optional[jnp.dtype] = None):
    """Flatten a pytree into per-dtype flat buffers.

    Returns ``(buffers, meta)`` where ``buffers`` is a list of 1-D arrays and
    ``meta`` recovers the tree via :func:`unpack`.
    """
    leaves, treedef = jax.tree.flatten(tree)
    if not leaves:
        return [], (treedef, [], [])
    groups: dict = {}
    order = []  # (group_key, index_within_group, shape, orig_dtype)
    for leaf in leaves:
        key = "comm" if comm_dtype is not None else str(leaf.dtype)
        groups.setdefault(key, [])
        order.append((key, len(groups[key]), leaf.shape, leaf.dtype))
        flat = leaf.reshape(-1)
        if comm_dtype is not None and leaf.dtype != comm_dtype:
            flat = flat.astype(comm_dtype)
        groups[key].append(flat)
    keys = list(groups.keys())
    buffers = [jnp.concatenate(groups[k]) if len(groups[k]) > 1 else groups[k][0]
               for k in keys]
    return buffers, (treedef, keys, order)


def unpack(buffers: List[jnp.ndarray], meta, scale: Optional[float] = None):
    """Inverse of :func:`pack`; optionally fuses a ``*= scale`` (the
    reference's 1/size multiply, fused with the cast-back kernel).

    The scale is applied AFTER the cast back to each leaf's original
    dtype: with a reduced-precision comm dtype (bf16 wire) a wire-dtype
    multiply would round the 1/size factor into the wire's mantissa
    before the full-precision restore — the cast-back must see the raw
    reduced values and the scaling happen in leaf precision."""
    treedef, keys, order = meta
    if not order:
        return jax.tree.unflatten(treedef, [])
    # Compute split points per group.
    offsets = {k: [0] for k in keys}
    sizes: dict = {k: [] for k in keys}
    for key, _, shape, _ in order:
        n = int(np.prod(shape)) if shape else 1
        sizes[key].append(n)
        offsets[key].append(offsets[key][-1] + n)
    pieces_by_group = {}
    for k, buf in zip(keys, buffers):
        cuts = offsets[k][1:-1]
        pieces_by_group[k] = jnp.split(buf, cuts) if cuts else [buf]
    leaves = []
    for key, idx, shape, dtype in order:
        piece = pieces_by_group[key][idx].reshape(shape)
        if piece.dtype != dtype:
            piece = piece.astype(dtype)
        if scale is not None:
            piece = piece * jnp.asarray(scale, piece.dtype)
        leaves.append(piece)
    return jax.tree.unflatten(treedef, leaves)


class PadStrip(int):
    """The pad amount returned by :func:`pad_to_multiple`, doubling as
    the inverse operation: ``strip(buf)`` slices a flat buffer of the
    padded length back to the original one.  Subclasses ``int`` so the
    historical ``buf, pad = pad_to_multiple(...)`` call sites keep their
    arithmetic/truthiness semantics (``full[:n - pad]``, ``if pad:``)
    unchanged."""

    def __new__(cls, rem: int, orig_len: int):
        self = super().__new__(cls, rem)
        self.orig_len = int(orig_len)
        return self

    def __call__(self, buf: jnp.ndarray) -> jnp.ndarray:
        return buf[: self.orig_len]


def pad_to_multiple(buf: jnp.ndarray, m: int) -> Tuple[jnp.ndarray, PadStrip]:
    """Pad a flat buffer so its length divides ``m`` (needed by the
    reduce-scatter leg of the two-dimensional communicator and by the
    FSDP shard layout).

    Returns ``(padded, strip)``.  ``strip`` makes the inverse contract
    explicit: ``strip(padded) == buf`` (it is also the pad amount as an
    ``int``, for callers that track offsets themselves)."""
    n = int(buf.shape[0])
    rem = (-n) % m
    if rem:
        buf = jnp.concatenate([buf, jnp.zeros((rem,), buf.dtype)])
    return buf, PadStrip(rem, n)
