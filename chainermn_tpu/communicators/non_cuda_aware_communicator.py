"""Host-staged communicator.

Reference (path unverified, SURVEY.md provenance):
``NonCudaAwareCommunicator`` 〔chainermn/communicators/non_cuda_aware_communicator.py〕
— like flat, but stages GPU buffers through pinned host memory before MPI,
for MPI builds that are not CUDA-aware.

TPU-native interpretation: the eager path genuinely stages gradients through
*host* memory and reduces across hosts over the DCN control plane — the
debugging/escape-hatch path when one wants the data plane off the ICI (the
exact role the reference class played).  Inside a traced SPMD region there is
no host to stage through (XLA owns execution), so the traced decomposition
falls back to flat-buffer psum and the class documents that staging is an
eager-mode behavior.
"""

import jax
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from chainermn_tpu.communicators.flat_communicator import FlatCommunicator
from chainermn_tpu.utils.placement import local_device_put


class NonCudaAwareCommunicator(FlatCommunicator):
    # same stage sequence as flat (host staging is eager-only), but its
    # own plan name so sweep rows / plan tables attribute timings right
    flavor = "non_cuda_aware"

    def allreduce_grad(self, grads, *, compressor=None, state=None):
        from chainermn_tpu.compression import base as _cbase
        from chainermn_tpu.compression import quantize as _cq
        comp = (_cbase.resolve_compressor(compressor)
                if compressor is not None else
                (self.compression if _cq.is_quantizing(self.compression)
                 else None))
        if _cq.is_quantizing(comp) or self.in_spmd_context():
            # No host exists inside an XLA program, and quantizing codecs
            # ride the in-wire-summing collective either way; use the flat
            # decomposition (codec handling included).
            return super().allreduce_grad(
                grads, compressor=compressor, state=state)
        # Eager: device -> host -> (DCN mean across hosts) -> device, the
        # staged path the reference implements with pinned buffers.
        if comp is not None and comp.wire is not None:
            # Honor an explicit lossless wire codec with the same
            # cast-roundtrip the in-program path observes.
            grads = jax.tree.map(
                lambda g: g.astype(comp.wire).astype(g.dtype), grads)
        host = jax.device_get(grads)
        if self.host_size > 1:
            summed = self.allreduce_obj(host, op="sum")
            host = jax.tree.map(lambda a: np.asarray(a) / self.host_size, summed)
        repl = NamedSharding(self._mesh, P())
        # every host holds the reduced value — place process-locally
        return local_device_put(host, repl)
