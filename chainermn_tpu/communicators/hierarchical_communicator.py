"""Hierarchical communicator — intra-slice reduce, inter-host allreduce.

Reference (path unverified, SURVEY.md provenance):
``HierarchicalCommunicator`` 〔chainermn/communicators/hierarchical_communicator.py〕
— intra-node NCCL reduce -> inter-node MPI allreduce (host staged) ->
intra-node NCCL bcast.  This is the component BASELINE.json:north_star maps
onto ICI x DCN.

Here the two legs are the two mesh axes: ``psum`` over the ``intra`` (ICI)
axis first, then ``psum`` over the ``inter`` (DCN) axis.  In SPMD terms
psum(intra) already leaves the intra-reduced value everywhere in the slice
(reduce+bcast fused), so the NCCL-bcast third leg is implicit.  XLA lowers
each psum to the collective native to that axis's interconnect.
"""

from jax import lax

from chainermn_tpu.communicators.mesh_communicator_base import MeshCommunicator


class HierarchicalCommunicator(MeshCommunicator):
    flavor = "hierarchical"

    def __init__(self, *args, **kwargs):
        super().__init__(*args, **kwargs)
        # group-size inference routed through the shared descriptor —
        # the same PlanTopology the compiler and derived census read
        if len(self.plan_topology().axes) < 2:
            raise ValueError(
                "hierarchical communicator needs a 2-axis (inter, intra) mesh; "
                "use 'naive'/'flat'/'xla' for flat worlds")

    def _legacy_allreduce_grad_traced(self, grads):
        # pre-planner lowering, kept as the census-parity reference
        inter_axes = self._data_axes[:-1]
        intra_axis = self._data_axes[-1]
        n = self.size

        def one(g):
            g = lax.psum(g, intra_axis)        # ICI leg (reference: NCCL reduce)
            g = lax.psum(g, inter_axes)        # DCN leg (reference: MPI allreduce)
            return g / n
        import jax
        return jax.tree.map(one, grads)
