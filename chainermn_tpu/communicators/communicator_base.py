"""Abstract communicator API.

Reference being rebuilt (path unverified, SURVEY.md provenance):
``CommunicatorBase`` in 〔chainermn/communicators/communicator_base.py〕 —
properties ``rank/size/intra_rank/inter_rank/...``, object and array
``send/recv/bcast/gather/alltoall``, and the two gradient entry points
``allreduce_grad(model)`` / ``bcast_data(model)``.

TPU-native re-interpretation (NOT a port — see README):

* The reference world is one MPI rank per GPU.  Here there are two levels:

  - **host level** — one controller process per host.  ``rank``/``size`` (and
    the whole object plane: ``send_obj``, ``bcast_obj``, ...) are host-level,
    carried by the DCN control plane.  This is what gates logging to rank 0
    and shards datasets, exactly where the reference used its MPI rank.
  - **device level** — the mesh.  Array collectives (``allreduce``, ``bcast``,
    ``allgather``, ``alltoall``, ...) are *traced* ops: they run inside an
    SPMD region (``jax.shard_map`` over the communicator's mesh) where each
    device plays the role of a reference rank; ``comm.axis_index()`` is the
    device-level rank.  ``comm.run_spmd(f, *args)`` launches such a region
    from eager code (the analogue of "everyone executes the script under
    mpiexec").

* ``allreduce_grad`` / ``bcast_data`` are functional: they take and return
  pytrees instead of mutating a Chainer link in place.
"""

from __future__ import annotations

import abc
from typing import Any, Callable, List, Optional


class CommunicatorBase(abc.ABC):
    # ---- host-level topology (the reference's rank properties) -------------
    @property
    @abc.abstractmethod
    def rank(self) -> int:
        """Host-level rank (controller process index).  Use for rank-0 gating
        of logging/checkpointing, as the reference does with its MPI rank."""

    @property
    @abc.abstractmethod
    def size(self) -> int:
        """Total number of *devices* in the data-parallel world — the
        gradient-averaging denominator, as in the reference where one rank
        owned one GPU."""

    @property
    @abc.abstractmethod
    def host_size(self) -> int: ...

    @property
    @abc.abstractmethod
    def intra_rank(self) -> int: ...

    @property
    @abc.abstractmethod
    def intra_size(self) -> int: ...

    @property
    @abc.abstractmethod
    def inter_rank(self) -> int: ...

    @property
    @abc.abstractmethod
    def inter_size(self) -> int: ...

    # ---- object plane (control plane over DCN; reference: pickled MPI) -----
    @abc.abstractmethod
    def send_obj(self, obj: Any, dest: int, tag: int = 0) -> None: ...

    @abc.abstractmethod
    def recv_obj(self, source: int, tag: int = 0) -> Any: ...

    # Every collective object op carries ``tag=`` end to end — reserved
    # bands (telemetry, barrier, ...) ride these entry points, so a
    # communicator that narrowed the signature would strand them (see
    # runtime.control_plane.RESERVED_TAG_BANDS and the
    # wrapper-surface-drift protocol lint rule).
    @abc.abstractmethod
    def bcast_obj(self, obj: Any, root: int = 0, tag: int = 0) -> Any: ...

    @abc.abstractmethod
    def gather_obj(self, obj: Any, root: int = 0,
                   tag: int = 0) -> Optional[List[Any]]: ...

    @abc.abstractmethod
    def allgather_obj(self, obj: Any, tag: int = 0) -> List[Any]: ...

    @abc.abstractmethod
    def scatter_obj(self, objs: Optional[List[Any]], root: int = 0,
                    tag: int = 0) -> Any: ...

    @abc.abstractmethod
    def allreduce_obj(self, obj: Any,
                      op: "str | Callable[[Any, Any], Any]" = "sum",
                      tag: int = 0) -> Any:
        """Reduce picklable objects across hosts.  ``op``: "sum"/"prod"/
        "max"/"min" (applied structurally through dicts/lists, ndarray-aware)
        or any binary callable for custom reducibles."""

    @abc.abstractmethod
    def barrier(self, tag: int = 900) -> None: ...

    # ---- device plane (traced SPMD collectives) ----------------------------
    @abc.abstractmethod
    def axis_index(self): ...

    @abc.abstractmethod
    def allreduce(self, x, op: str = "sum"): ...

    @abc.abstractmethod
    def bcast(self, x, root: int = 0): ...

    @abc.abstractmethod
    def allgather(self, x): ...

    @abc.abstractmethod
    def alltoall(self, xs): ...

    @abc.abstractmethod
    def gather(self, x, root: int = 0): ...

    @abc.abstractmethod
    def scatter(self, x, root: int = 0): ...

    @abc.abstractmethod
    def run_spmd(self, f: Callable, *stacked_args): ...

    # ---- gradient entry points (the hot path) ------------------------------
    @abc.abstractmethod
    def allreduce_grad(self, grads, *, compressor=None, state=None): ...

    @abc.abstractmethod
    def bcast_data(self, params): ...

    # ---- sub-communicators -------------------------------------------------
    @abc.abstractmethod
    def split(self, color: int, key: int) -> "CommunicatorBase": ...
