"""Two-dimensional communicator — reduce-scatter / allreduce / all-gather.

Reference (path unverified, SURVEY.md provenance):
``TwoDimensionalCommunicator`` 〔chainermn/communicators/two_dimensional_communicator.py〕
— intra-node NCCL reduce-scatter -> inter-node MPI allreduce of each shard ->
intra-node NCCL allgather.  The bandwidth-optimal decomposition for fat nodes
on thin inter-node links; maps directly onto the 2-D ICI torus here.

Here, on a packed flat buffer: ``psum_scatter`` over ``intra`` (each chip in
the slice owns 1/intra_size of the gradient), ``psum`` over ``inter`` of the
owned shard, ``all_gather`` over ``intra``.  Every leg is the XLA collective
native to its axis.
"""

from jax import lax

from chainermn_tpu.communicators import _packing
from chainermn_tpu.communicators.mesh_communicator_base import MeshCommunicator


class TwoDimensionalCommunicator(MeshCommunicator):
    def __init__(self, *args, **kwargs):
        super().__init__(*args, **kwargs)
        if len(self._data_axes) < 2:
            raise ValueError(
                "two_dimensional communicator needs a 2-axis (inter, intra) mesh")

    def _allreduce_grad_traced(self, grads):
        inter_axes = self._data_axes[:-1]
        intra_axis = self._data_axes[-1]
        intra_size = int(self._mesh.shape[intra_axis])
        buffers, meta = _packing.pack(grads)
        out = []
        for buf in buffers:
            buf, pad = _packing.pad_to_multiple(buf, intra_size)
            shard = lax.psum_scatter(buf, intra_axis, tiled=True)   # ICI leg 1
            shard = lax.psum(shard, inter_axes)                     # DCN leg
            full = lax.all_gather(shard, intra_axis, tiled=True)    # ICI leg 2
            out.append(full[:buf.shape[0] - pad] if pad else full)
        return _packing.unpack(out, meta, scale=1.0 / self.size)
