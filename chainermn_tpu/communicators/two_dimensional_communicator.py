"""Two-dimensional communicator — reduce-scatter / allreduce / all-gather.

Reference (path unverified, SURVEY.md provenance):
``TwoDimensionalCommunicator`` 〔chainermn/communicators/two_dimensional_communicator.py〕
— intra-node NCCL reduce-scatter -> inter-node MPI allreduce of each shard ->
intra-node NCCL allgather.  The bandwidth-optimal decomposition for fat nodes
on thin inter-node links; maps directly onto the 2-D ICI torus here.

Here, on a packed flat buffer: ``psum_scatter`` over ``intra`` (each chip in
the slice owns 1/intra_size of the gradient), ``psum`` over ``inter`` of the
owned shard, then the gather-back leg over ``intra``.

The gather-back leg is expressed as a masked psum (each chip contributes its
shard placed at its offset in a zero buffer) rather than ``all_gather``:
the two are value-identical, but JAX's varying-axes type system types an
``all_gather`` output as *varying* over the axis, which would poison the
updated parameters' replicated out_spec in ``make_train_step`` — psum output
is invariant by construction.  Cost on the ICI leg: ~2x the bytes of a true
all-gather (ring allreduce vs ring gather); the decomposition's point — the
DCN leg carries only 1/intra_size of the gradient — is unchanged, and ICI
bandwidth is the cheap resource the trade spends.
"""

import jax.numpy as jnp
from jax import lax

from chainermn_tpu.communicators import _packing
from chainermn_tpu.communicators.mesh_communicator_base import MeshCommunicator


class TwoDimensionalCommunicator(MeshCommunicator):
    flavor = "two_dimensional"

    def __init__(self, *args, **kwargs):
        super().__init__(*args, **kwargs)
        # group-size inference routed through the shared descriptor —
        # the same PlanTopology the compiler and derived census read
        if len(self.plan_topology().axes) < 2:
            raise ValueError(
                "two_dimensional communicator needs a 2-axis (inter, intra) mesh")

    def _legacy_allreduce_grad_traced(self, grads):
        # pre-planner lowering, kept as the census-parity reference
        inter_axes = self._data_axes[:-1]
        intra_axis = self._data_axes[-1]
        intra_size = self.plan_topology().intra_size
        me = lax.axis_index(intra_axis)
        buffers, meta = _packing.pack(grads)
        out = []
        for buf in buffers:
            buf, strip = _packing.pad_to_multiple(buf, intra_size)
            n = buf.shape[0]
            shard = lax.psum_scatter(buf, intra_axis, tiled=True)   # ICI leg 1
            shard = lax.psum(shard, inter_axes)                     # DCN leg
            # ICI leg 2: gather-back as a masked psum (invariant-typed;
            # see module docstring)
            placed = lax.dynamic_update_slice_in_dim(
                jnp.zeros((n,), buf.dtype), shard,
                me * (n // intra_size), 0)
            full = lax.psum(placed, intra_axis)
            out.append(strip(full))
        return _packing.unpack(out, meta, scale=1.0 / self.size)
