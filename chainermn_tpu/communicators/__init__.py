"""Communicator factory.

Reference (path unverified, SURVEY.md provenance): ``create_communicator`` in
〔chainermn/communicators/__init__.py〕 — string -> class dispatch over
``naive``, ``flat``, ``hierarchical`` (default), ``two_dimensional``,
``single_node``, ``non_cuda_aware``, ``pure_nccl``; only ``pure_nccl``
accepts ``allreduce_grad_dtype``.

The same names resolve here (so stock scripts run unchanged), plus the
TPU-native name ``xla`` for the pure-collective data path — ``pure_nccl`` is
an alias for it, since NCCL's role belongs to XLA/ICI on TPU
(BASELINE.json:north_star).
"""

from typing import Optional

from chainermn_tpu.communicators.communicator_base import CommunicatorBase
from chainermn_tpu.communicators.mesh_communicator_base import MeshCommunicator
from chainermn_tpu.communicators.naive_communicator import NaiveCommunicator
from chainermn_tpu.communicators.flat_communicator import FlatCommunicator
from chainermn_tpu.communicators.hierarchical_communicator import HierarchicalCommunicator
from chainermn_tpu.communicators.two_dimensional_communicator import TwoDimensionalCommunicator
from chainermn_tpu.communicators.single_node_communicator import SingleNodeCommunicator
from chainermn_tpu.communicators.non_cuda_aware_communicator import NonCudaAwareCommunicator
from chainermn_tpu.communicators.xla_communicator import XlaCommunicator
from chainermn_tpu.communicators.auto_communicator import AutoCommunicator

_COMMUNICATORS = {
    "naive": NaiveCommunicator,
    "flat": FlatCommunicator,
    "hierarchical": HierarchicalCommunicator,
    "two_dimensional": TwoDimensionalCommunicator,
    "single_node": SingleNodeCommunicator,
    "non_cuda_aware": NonCudaAwareCommunicator,
    "xla": XlaCommunicator,
    "pure_nccl": XlaCommunicator,  # reference name -> TPU data plane
    # tuned flavor: per-message-size plans from an autotuned plan table
    # (create_communicator("auto", plan_table="plan_table.json"))
    "auto": AutoCommunicator,
}


def create_communicator(
    communicator_name: str = "hierarchical",
    mesh=None,
    allreduce_grad_dtype=None,
    intra_size: Optional[int] = None,
    compression=None,
    **kwargs,
) -> CommunicatorBase:
    """Create a communicator by name (reference signature:
    ``create_communicator(communicator_name, mpi_comm, allreduce_grad_dtype)``;
    the ``mpi_comm`` argument becomes ``mesh`` — topology is discovered from
    the device list when omitted, no launcher in the loop).

    ``compression`` selects the gradient wire codec (name, instance, or
    config dict — see :mod:`chainermn_tpu.compression`).  A
    ``NoCompression(wire_dtype=...)`` is exactly the legacy
    ``allreduce_grad_dtype`` knob (same 'xla'-only restriction); the
    quantizers (``"int8"``, ``"fp8"``) work with every flavor because
    they ride the generic pack/psum path.

    The TPU-native extra name ``"auto"`` is the tuned flavor: pass
    ``plan_table=`` (path / dict / ``planner.PlanTable``) and each
    ``allreduce_grad`` runs the autotuned plan for its message size —
    see ``docs/collective_planner.md``.
    """
    try:
        cls = _COMMUNICATORS[communicator_name]
    except KeyError:
        raise ValueError(
            f"unknown communicator {communicator_name!r}; available: "
            f"{sorted(_COMMUNICATORS)}") from None
    from chainermn_tpu.compression import NoCompression, resolve_compressor
    compression = resolve_compressor(compression)
    wire_knob = allreduce_grad_dtype is not None or (
        isinstance(compression, NoCompression)
        and compression.wire is not None)
    if wire_knob and not cls.supports_allreduce_grad_dtype:
        # Parity with the reference factory's restriction.
        raise ValueError(
            "allreduce_grad_dtype (= compression=NoCompression(wire_dtype)) "
            "is only supported by the 'xla'/'pure_nccl' communicator")
    if allreduce_grad_dtype is not None:
        kwargs["allreduce_grad_dtype"] = allreduce_grad_dtype
    if compression is not None:
        kwargs["compression"] = compression
    return cls(mesh=mesh, intra_size=intra_size, **kwargs)


__all__ = [
    "CommunicatorBase",
    "MeshCommunicator",
    "NaiveCommunicator",
    "FlatCommunicator",
    "HierarchicalCommunicator",
    "TwoDimensionalCommunicator",
    "SingleNodeCommunicator",
    "NonCudaAwareCommunicator",
    "XlaCommunicator",
    "AutoCommunicator",
    "create_communicator",
]
