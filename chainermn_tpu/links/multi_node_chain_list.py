"""Model-parallel chain composition.

Reference being rebuilt (path unverified, SURVEY.md provenance):
``MultiNodeChainList`` in 〔chainermn/links/multi_node_chain_list.py〕 — the
reference's *entire* model/pipeline parallelism (SURVEY.md §2.4): register
per-rank sub-chains with ``add_link(chain, rank_in, rank_out)``; ``__call__``
recv-s inputs from ``rank_in``, runs the local chain, send-s outputs to
``rank_out``; supports multi-input/multi-output and pipeline shapes; one
``backward()`` spans all ranks via delegate variables.  Sequential, depth-1
in flight — no 1F1B schedule, and none is invented here (anti-goal).

TPU-native re-interpretation (single controller, MPMD over device groups):

* each *stage* ("rank") owns a contiguous group of the communicator's
  devices; stage parameters live replicated on their group, activations are
  batch-sharded over the group (per-stage data parallelism for free);
* ``apply(params, x)`` runs the stages in registration order inside one
  differentiable Python composition: sends/recvs are the channel functions
  of :mod:`chainermn_tpu.functions` and the actual inter-group ICI transfer
  is a differentiable ``jax.device_put`` at each recv;
* each stage's compute is jitted on its own group; the backward is the
  autodiff transpose of the whole composition — the reference's
  delegate-variable choreography with no hand-written reverse messages.

The execution is eager at stage granularity (matching the reference's
define-by-run semantics); for homogeneous-stage high-throughput pipelining
see ``chainermn_tpu.parallel.pipeline``.

Multi-controller mode (the reference's actual deployment shape — one MPI
process per node): when the communicator spans several controller
processes (``comm.host_size > 1``), stage ``s`` executes on process
``s % host_size`` using that process's local devices, and stage
boundaries that cross processes become :func:`cross_send` /
:func:`cross_recv` — differentiable DCN transfers whose backward ships
the cotangent the opposite way, exactly the reference's
``Send.backward -> comm.recv(grad)`` over MPI.  Every process runs the
same registration/apply code (SPMD at the script level, like running
under ``mpiexec``); ``apply`` returns the real outputs on the process
owning the exit stage and a zero-size *delegate* elsewhere — pass it to
:func:`pseudo_loss` so one ``jax.value_and_grad`` per process drives the
globally-connected backward, the reference's ``pseudo_connect`` +
``loss.backward()`` choreography.
"""

from __future__ import annotations

from typing import Any, Callable, List, Optional, Sequence, Union

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from chainermn_tpu import functions as F

STAGE_DP_AXIS = "stage_dp"

Ranks = Union[int, Sequence[int], None]


_instance_counter = iter(range(1 << 30))
# Cross-process chains need a SMALL stable namespace (tags are packed into
# the transport's 20-bit payload-tag space); counted separately so ordinary
# single-controller instances don't consume it.
_cross_instance_counter = iter(range(1 << 30))
_MAX_CROSS_INSTANCES = 32


def pseudo_loss(out) -> "jax.Array":
    """Scalar pseudo-loss for a delegate returned by ``apply`` on a process
    that does not own the exit stage — the reference's "call backward() on
    the delegate variable" idiom.  Value is 0.0 but it is data-dependent on
    every cross-process send, so ``jax.value_and_grad`` reaches their
    backward transfers."""
    leaves = jax.tree.leaves(out)
    acc = jnp.zeros((), jnp.float32)
    for l in leaves:
        if jnp.issubdtype(jnp.result_type(l), jnp.inexact):
            acc = acc + jnp.sum(l).astype(jnp.float32)
    return acc


class MultiNodeChainList:
    def __init__(self, comm, n_stages: Optional[int] = None):
        self._comm = comm
        self._links: List[tuple] = []  # (module, rank_in, rank_out)
        # Explicit controller-process pin per stage (None = round-robin
        # default).  The reference let the user choose each link's MPI rank
        # via add_link(chain, rank_in, rank_out); `process=` is that choice.
        self._stage_proc: List[Optional[int]] = []
        self._n_stages_hint = n_stages
        self._stage_meshes: Optional[List[Mesh]] = None
        self._jits: dict = {}
        # Private tag namespace: several chain lists (or user-level raw
        # F.send/F.recv, which default to tag 0) may share one communicator;
        # each instance's channels must neither collide with nor clear theirs.
        self._tag = 1 + next(_instance_counter)
        if self._n_procs > 1:
            self._cross_base = next(_cross_instance_counter)
            if self._cross_base >= _MAX_CROSS_INSTANCES:
                raise RuntimeError(
                    f"more than {_MAX_CROSS_INSTANCES} cross-process "
                    "MultiNodeChainList instances in one process; the packed "
                    "DCN tag namespace is exhausted")

    # -- multi-controller placement -----------------------------------------
    @property
    def _n_procs(self) -> int:
        return int(getattr(self._comm, "host_size", 1))

    def stage_owner(self, s: int) -> int:
        """Controller process that executes stage ``s`` — the explicit
        ``process=`` pin from :meth:`add_link` when given (reference: the MPI
        rank the link was assigned to), else registration order mod world."""
        if not 0 <= s < len(self._stage_proc):
            raise ValueError(
                f"stage reference {s} is out of range: this chain has "
                f"{len(self._stage_proc)} registered stage(s) — check the "
                f"rank_in/rank_out values passed to add_link")
        pin = self._stage_proc[s]
        return (s % self._n_procs) if pin is None else pin

    def is_local_stage(self, s: int) -> bool:
        return (self._n_procs == 1
                or self.stage_owner(s) == self._comm.host_rank)

    @property
    def owns_output(self) -> bool:
        """True when this process executes an exit stage (``rank_out=None``)
        — i.e. ``apply`` returns real outputs here, a delegate elsewhere."""
        return any(self.is_local_stage(s)
                   for s, (_, _, rout) in enumerate(self._links)
                   if rout is None)

    def _cross_tag(self, src: int, dst: int, occ: int) -> int:
        if src >= 32 or dst >= 32 or occ >= 32:
            raise ValueError("cross-process chains support at most 32 "
                             "stages and 32 sends per stage pair")
        return ((self._cross_base % _MAX_CROSS_INSTANCES) << 15 \
                | src << 10 | dst << 5 | occ)

    # -- registration --------------------------------------------------------
    def add_link(self, module, rank_in: Ranks = None, rank_out: Ranks = None,
                 process: Optional[int] = None):
        """Reference signature: ``add_link(chain, rank_in=..., rank_out=...)``.
        The link's stage index is its registration order.

        ``process`` pins the stage to a chosen controller process (the
        reference's "which MPI rank owns this link" decision) — required for
        deliberate placement of uneven models, e.g. a heavy encoder and a
        light decoder on different hosts.  Default ``None`` keeps the
        round-robin ``stage % host_size`` placement.  All processes must
        register identical pins (the composition is SPMD at script level).
        """
        if process is not None:
            n = self._n_procs
            if not 0 <= process < n:
                raise ValueError(
                    f"add_link(process={process}) out of range: this "
                    f"communicator spans {n} controller process(es)")
        self._links.append((module, rank_in, rank_out))
        self._stage_proc.append(process)
        self._stage_meshes = None  # re-partition lazily
        return self

    @property
    def n_stages(self) -> int:
        return len(self._links)

    # -- placement -----------------------------------------------------------
    def _meshes(self) -> List[Optional[Mesh]]:
        if self._stage_meshes is None:
            if self._n_procs > 1:
                # Multi-controller: a stage's devices are its owner
                # process's LOCAL devices (remote stages get None — their
                # placement is not this process's business, matching the
                # reference where each MPI rank only ever names its own
                # GPU).  Several local stages split the local devices.
                local = [d for d in self._comm.mesh.devices.flat
                         if d.process_index == jax.process_index()]
                mine = [s for s in range(self.n_stages)
                        if self.is_local_stage(s)]
                meshes: List[Optional[Mesh]] = [None] * self.n_stages
                if mine:
                    if len(local) >= len(mine):
                        groups = np.array_split(
                            np.asarray(local, dtype=object), len(mine))
                    else:
                        groups = [np.asarray([local[i % len(local)]],
                                             dtype=object)
                                  for i in range(len(mine))]
                    for s, g in zip(mine, groups):
                        meshes[s] = Mesh(g, (STAGE_DP_AXIS,))
                self._stage_meshes = meshes
                return self._stage_meshes
            devs = list(self._comm.mesh.devices.flat)
            if len(devs) >= self.n_stages:
                groups = np.array_split(np.asarray(devs, dtype=object),
                                        self.n_stages)
            else:
                # fewer devices than stages (e.g. a single chip): stages
                # share devices round-robin instead of crashing on an
                # empty group
                groups = [np.asarray([devs[s % len(devs)]], dtype=object)
                          for s in range(self.n_stages)]
            self._stage_meshes = [
                Mesh(g, (STAGE_DP_AXIS,)) for g in groups]
        return self._stage_meshes

    def _local_mesh(self, stage: int) -> Mesh:
        mesh = self._meshes()[stage]
        if mesh is None:
            raise ValueError(
                f"stage {stage} is owned by controller process "
                f"{self.stage_owner(stage)}, not this process "
                f"({self._comm.host_rank}); its placement is only known "
                "on its owner")
        return mesh

    def stage_devices(self, stage: int):
        return list(self._local_mesh(stage).devices.flat)

    def _param_sharding(self, stage: int) -> NamedSharding:
        return NamedSharding(self._local_mesh(stage), P())

    def _act_sharding(self, stage: int) -> NamedSharding:
        return NamedSharding(self._local_mesh(stage), P(STAGE_DP_AXIS))

    def _place_act(self, x, stage: int):
        shd = self._act_sharding(stage)
        return jax.tree.map(lambda a: jax.device_put(a, shd), x)

    def place_activation(self, x, stage: int):
        """Place an activation pytree on ``stage``'s device group
        (batch-sharded) — for driving a stage's module directly outside
        :meth:`apply`, e.g. autoregressive decoding against stage
        parameters (the seq2seq example's translate path)."""
        return self._place_act(x, stage)

    # -- init ----------------------------------------------------------------
    def init(self, rng, *inputs, stage_inputs: Optional[dict] = None):
        """Initialize per-stage parameters by tracing the composition once.
        Returns a list of parameter pytrees, each placed on its stage's
        device group."""
        params_list: List[Any] = []

        def init_stage(s, mod, args):
            sub_rng = jax.random.fold_in(rng, s)
            p = mod.init(sub_rng, *args)
            return jax.device_put(p, self._param_sharding(s))

        self._run(init_stage_hook=init_stage, params_list=params_list,
                  inputs=inputs, stage_inputs=stage_inputs or {})
        return params_list

    # -- forward -------------------------------------------------------------
    def apply(self, params_list, *inputs, stage_inputs: Optional[dict] = None):
        """The composed forward (reference ``__call__``).  ``inputs`` feed
        stages with ``rank_in=None``; ``stage_inputs[s]`` supplies extra
        local arrays to stage ``s`` (the single-controller analogue of each
        reference rank feeding its own local data, e.g. decoder targets)."""
        return self._run(params_list=list(params_list), inputs=inputs,
                         stage_inputs=stage_inputs or {})

    __call__ = apply

    def traced(self):
        """One-XLA-program composition (single-controller only).

        The eager :meth:`apply` dispatches each stage on its own device
        group — matching the reference's define-by-run MPMD shape, but
        (a) giving XLA no cross-stage program to fuse/overlap and (b)
        leaving (S−1)/S of the machine idle at any instant, since the
        stages are sequential anyway.  On a single controller that
        placement is an emulation, not a necessity — so this returns
        ``fn(params_list, *inputs)``: the SAME composition as pure value
        flow (send/recv edges become direct data dependencies) under one
        ``jax.jit``, letting XLA fuse across stage boundaries and run
        every stage data-parallel over the full machine.  Semantics and
        gradients are identical to ``apply``; pass uncommitted (host or
        replicated) parameters — per-group-committed arrays would pin
        the program to conflicting device sets.

        Cross-controller chains must stay on the eager ``apply`` (their
        stage boundaries are real DCN transfers with host-side ordering).
        """
        if self._n_procs > 1:
            raise ValueError(
                "traced() is single-controller only; cross-controller "
                "chains need the eager apply (DCN transfers are host-side)")
        links = list(self._links)
        entry_stages = [s for s, (_, rin, _) in enumerate(links)
                        if rin is None]

        @jax.jit
        def fn(params_list, *inputs, stage_inputs=None):
            stage_inputs = stage_inputs or {}
            slots: dict = {}
            outputs = []
            for s, (mod, rank_in, rank_out) in enumerate(links):
                received: List[Any] = []
                if rank_in is None:
                    if inputs:
                        if len(entry_stages) == 1:
                            received.extend(inputs)
                        else:
                            received.append(inputs[entry_stages.index(s)])
                else:
                    ranks = (rank_in if isinstance(rank_in, (list, tuple))
                             else [rank_in])
                    for r in ranks:
                        received.append(slots[(r, s)].pop(0))
                received.extend(stage_inputs.get(s, ()))
                y = mod.apply(params_list[s], *received)
                if rank_out is None:
                    outputs.append(y)
                else:
                    ranks = (rank_out if isinstance(rank_out, (list, tuple))
                             else [rank_out])
                    for r in ranks:
                        slots.setdefault((s, r), []).append(y)
            leftovers = [k for k, q in slots.items() if q]
            if leftovers:
                raise RuntimeError(
                    f"unconsumed sends on edges {leftovers}: some rank_out "
                    "has no matching rank_in consumer in this chain list")
            return outputs[0] if len(outputs) == 1 else tuple(outputs)

        return fn

    def _pick_anchor(self, params_list, s: int):
        """Anchor pytree for a cross-process recv's backward: stage ``s``'s
        params if they contain an inexact leaf, else any local stage's.
        The anchor must be part of what the caller differentiates — JAX
        prunes the reverse transfer otherwise (see :func:`cross_recv`) and
        the PRODUCER process would then block forever awaiting the
        cotangent, a hang with no pointer to the real cause."""
        candidates = [params_list[s]] + [
            p for i, p in enumerate(params_list)
            if i != s and self.is_local_stage(i)]
        for cand in candidates:
            if cand is not None and any(
                    jnp.issubdtype(jnp.result_type(l), jnp.inexact)
                    for l in jax.tree.leaves(cand)):
                return cand
        raise ValueError(
            f"cross-process recv at stage {s} has no anchor: neither that "
            "stage nor any other local stage has float parameters, so the "
            "backward transfer would be pruned and the sending process "
            "would hang waiting for the cotangent")

    def _stage_jit(self, s, mod):
        key = (s, id(mod))
        if key not in self._jits:
            self._jits[key] = jax.jit(
                lambda p, *args: mod.apply(p, *args))
        return self._jits[key]

    def _run(self, params_list, inputs, stage_inputs,
             init_stage_hook: Optional[Callable] = None):
        from chainermn_tpu.functions.point_to_point_communication import _channels

        # Fresh composition: a previous apply() that raised mid-flight (or a
        # mis-wired graph) must not leak stale activations into this one.
        # Only THIS instance's tag namespace is cleared — other chain lists'
        # and user-level raw send/recv channels on the same communicator are
        # not ours to destroy.
        channels = _channels(self._comm)
        for k in [k for k in channels.slots if k[2] == self._tag]:
            del channels.slots[k]

        # Input routing mirrors the reference's MPMD shape: with one entry
        # stage (rank_in=None) it receives all model inputs; with several,
        # entry stage k receives inputs[k] (each "rank" feeds its own data).
        entry_stages = [s for s, (_, rin, _) in enumerate(self._links)
                        if rin is None]
        if len(entry_stages) > 1 and inputs and len(inputs) != len(entry_stages):
            raise ValueError(
                f"{len(entry_stages)} entry stages but {len(inputs)} inputs; "
                "with multiple rank_in=None stages pass exactly one input per "
                "entry stage (or use stage_inputs)")

        outputs = []
        cross_delegates: List[Any] = []
        # Occurrence counters per (src, dst) stage pair.  Sends count at the
        # producer's program position, recvs at the consumer's; both follow
        # the same registration order on every process, so the i-th send of
        # a pair meets the i-th recv and their packed DCN tags agree.
        occ_send: dict = {}
        occ_recv: dict = {}
        for s, (mod, rank_in, rank_out) in enumerate(self._links):
            local = self.is_local_stage(s)
            received: List[Any] = []
            if rank_in is None:
                if local and inputs:
                    if len(entry_stages) == 1:
                        received.extend(inputs)
                    else:
                        received.append(inputs[entry_stages.index(s)])
            else:
                ranks = rank_in if isinstance(rank_in, (list, tuple)) else [rank_in]
                for r in ranks:
                    src_local = self.is_local_stage(r)
                    if local and src_local:
                        received.append(F.recv(
                            self._comm, r, self_rank=s, tag=self._tag,
                            device_put=lambda v, _s=s: self._place_act(v, _s)))
                    elif local:  # producer on another controller process
                        occ = occ_recv[(r, s)] = occ_recv.get((r, s), 0)
                        occ_recv[(r, s)] += 1
                        anchor = (self._pick_anchor(params_list, s)
                                  if init_stage_hook is None else None)
                        shd = self._act_sharding(s)
                        received.append(F.cross_recv(
                            self._comm, self.stage_owner(r),
                            tag=self._cross_tag(r, s, occ), anchor=anchor,
                            device_put=lambda a, _shd=shd: jax.device_put(
                                a, _shd)))
            if not local:
                # Not this controller's stage — its sends/recvs happen on
                # its owner.  (Occurrence counters stay consistent without
                # bookkeeping here: a (src, dst) pair's owners are fixed,
                # so every occurrence of the pair is counted on the same
                # two processes, in the shared registration order.)
                if init_stage_hook is not None:
                    params_list.append(None)
                continue
            received.extend(stage_inputs.get(s, ()))
            args = tuple(received)
            if init_stage_hook is not None:
                params_list.append(init_stage_hook(s, mod, args))
            y = self._stage_jit(s, mod)(params_list[s], *args)
            if rank_out is None:
                outputs.append(y)
            else:
                ranks = rank_out if isinstance(rank_out, (list, tuple)) else [rank_out]
                for r in ranks:
                    if self.is_local_stage(r):
                        F.send(y, self._comm, r, self_rank=s, tag=self._tag)
                    else:
                        occ = occ_send[(s, r)] = occ_send.get((s, r), 0)
                        occ_send[(s, r)] += 1
                        cross_delegates.append(F.cross_send(
                            y, self._comm, self.stage_owner(r),
                            tag=self._cross_tag(s, r, occ)))
        leftovers = [k for k, q in channels.slots.items()
                     if q and k[2] == self._tag]
        if leftovers:
            raise RuntimeError(
                f"unconsumed sends on channels {leftovers}: some rank_out "
                "has no matching rank_in consumer in this chain list")
        if not outputs:
            if cross_delegates:
                return (cross_delegates[0] if len(cross_delegates) == 1
                        else jnp.concatenate(
                            [d.ravel() for d in cross_delegates]))
            return None
        if cross_delegates:
            # Thread the cross-send delegates into the local outputs so the
            # caller's single value_and_grad also drives those backwards.
            tied = F.pseudo_connect(
                jnp.concatenate([d.ravel() for d in cross_delegates]),
                *outputs)
            outputs = list(tied) if len(outputs) > 1 else [tied]
        return outputs[0] if len(outputs) == 1 else tuple(outputs)
