"""Model-parallel chain composition.

Reference being rebuilt (path unverified, SURVEY.md provenance):
``MultiNodeChainList`` in 〔chainermn/links/multi_node_chain_list.py〕 — the
reference's *entire* model/pipeline parallelism (SURVEY.md §2.4): register
per-rank sub-chains with ``add_link(chain, rank_in, rank_out)``; ``__call__``
recv-s inputs from ``rank_in``, runs the local chain, send-s outputs to
``rank_out``; supports multi-input/multi-output and pipeline shapes; one
``backward()`` spans all ranks via delegate variables.  Sequential, depth-1
in flight — no 1F1B schedule, and none is invented here (anti-goal).

TPU-native re-interpretation (single controller, MPMD over device groups):

* each *stage* ("rank") owns a contiguous group of the communicator's
  devices; stage parameters live replicated on their group, activations are
  batch-sharded over the group (per-stage data parallelism for free);
* ``apply(params, x)`` runs the stages in registration order inside one
  differentiable Python composition: sends/recvs are the channel functions
  of :mod:`chainermn_tpu.functions` and the actual inter-group ICI transfer
  is a differentiable ``jax.device_put`` at each recv;
* each stage's compute is jitted on its own group; the backward is the
  autodiff transpose of the whole composition — the reference's
  delegate-variable choreography with no hand-written reverse messages.

The execution is eager at stage granularity (matching the reference's
define-by-run semantics); for homogeneous-stage high-throughput pipelining
see ``chainermn_tpu.parallel.pipeline``.
"""

from __future__ import annotations

from typing import Any, Callable, List, Optional, Sequence, Union

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from chainermn_tpu import functions as F

STAGE_DP_AXIS = "stage_dp"

Ranks = Union[int, Sequence[int], None]


_instance_counter = iter(range(1 << 30))


class MultiNodeChainList:
    def __init__(self, comm, n_stages: Optional[int] = None):
        self._comm = comm
        self._links: List[tuple] = []  # (module, rank_in, rank_out)
        self._n_stages_hint = n_stages
        self._stage_meshes: Optional[List[Mesh]] = None
        self._jits: dict = {}
        # Private tag namespace: several chain lists (or user-level raw
        # F.send/F.recv, which default to tag 0) may share one communicator;
        # each instance's channels must neither collide with nor clear theirs.
        self._tag = 1 + next(_instance_counter)

    # -- registration --------------------------------------------------------
    def add_link(self, module, rank_in: Ranks = None, rank_out: Ranks = None):
        """Reference signature: ``add_link(chain, rank_in=..., rank_out=...)``.
        The link's stage index is its registration order."""
        self._links.append((module, rank_in, rank_out))
        self._stage_meshes = None  # re-partition lazily
        return self

    @property
    def n_stages(self) -> int:
        return len(self._links)

    # -- placement -----------------------------------------------------------
    def _meshes(self) -> List[Mesh]:
        if self._stage_meshes is None:
            devs = list(self._comm.mesh.devices.flat)
            if len(devs) >= self.n_stages:
                groups = np.array_split(np.asarray(devs, dtype=object),
                                        self.n_stages)
            else:
                # fewer devices than stages (e.g. a single chip): stages
                # share devices round-robin instead of crashing on an
                # empty group
                groups = [np.asarray([devs[s % len(devs)]], dtype=object)
                          for s in range(self.n_stages)]
            self._stage_meshes = [
                Mesh(g, (STAGE_DP_AXIS,)) for g in groups]
        return self._stage_meshes

    def stage_devices(self, stage: int):
        return list(self._meshes()[stage].devices.flat)

    def _param_sharding(self, stage: int) -> NamedSharding:
        return NamedSharding(self._meshes()[stage], P())

    def _act_sharding(self, stage: int) -> NamedSharding:
        return NamedSharding(self._meshes()[stage], P(STAGE_DP_AXIS))

    def _place_act(self, x, stage: int):
        shd = self._act_sharding(stage)
        return jax.tree.map(lambda a: jax.device_put(a, shd), x)

    # -- init ----------------------------------------------------------------
    def init(self, rng, *inputs, stage_inputs: Optional[dict] = None):
        """Initialize per-stage parameters by tracing the composition once.
        Returns a list of parameter pytrees, each placed on its stage's
        device group."""
        params_list: List[Any] = []

        def init_stage(s, mod, args):
            sub_rng = jax.random.fold_in(rng, s)
            p = mod.init(sub_rng, *args)
            return jax.device_put(p, self._param_sharding(s))

        self._run(init_stage_hook=init_stage, params_list=params_list,
                  inputs=inputs, stage_inputs=stage_inputs or {})
        return params_list

    # -- forward -------------------------------------------------------------
    def apply(self, params_list, *inputs, stage_inputs: Optional[dict] = None):
        """The composed forward (reference ``__call__``).  ``inputs`` feed
        stages with ``rank_in=None``; ``stage_inputs[s]`` supplies extra
        local arrays to stage ``s`` (the single-controller analogue of each
        reference rank feeding its own local data, e.g. decoder targets)."""
        return self._run(params_list=list(params_list), inputs=inputs,
                         stage_inputs=stage_inputs or {})

    __call__ = apply

    def _stage_jit(self, s, mod):
        key = (s, id(mod))
        if key not in self._jits:
            self._jits[key] = jax.jit(
                lambda p, *args: mod.apply(p, *args))
        return self._jits[key]

    def _run(self, params_list, inputs, stage_inputs,
             init_stage_hook: Optional[Callable] = None):
        from chainermn_tpu.functions.point_to_point_communication import _channels

        # Fresh composition: a previous apply() that raised mid-flight (or a
        # mis-wired graph) must not leak stale activations into this one.
        # Only THIS instance's tag namespace is cleared — other chain lists'
        # and user-level raw send/recv channels on the same communicator are
        # not ours to destroy.
        channels = _channels(self._comm)
        for k in [k for k in channels.slots if k[2] == self._tag]:
            del channels.slots[k]

        # Input routing mirrors the reference's MPMD shape: with one entry
        # stage (rank_in=None) it receives all model inputs; with several,
        # entry stage k receives inputs[k] (each "rank" feeds its own data).
        entry_stages = [s for s, (_, rin, _) in enumerate(self._links)
                        if rin is None]
        if len(entry_stages) > 1 and inputs and len(inputs) != len(entry_stages):
            raise ValueError(
                f"{len(entry_stages)} entry stages but {len(inputs)} inputs; "
                "with multiple rank_in=None stages pass exactly one input per "
                "entry stage (or use stage_inputs)")

        outputs = []
        for s, (mod, rank_in, rank_out) in enumerate(self._links):
            received: List[Any] = []
            if rank_in is None:
                if inputs:
                    if len(entry_stages) == 1:
                        received.extend(inputs)
                    else:
                        received.append(inputs[entry_stages.index(s)])
            else:
                ranks = rank_in if isinstance(rank_in, (list, tuple)) else [rank_in]
                for r in ranks:
                    received.append(F.recv(
                        self._comm, r, self_rank=s, tag=self._tag,
                        device_put=lambda v, _s=s: self._place_act(v, _s)))
            received.extend(stage_inputs.get(s, ()))
            args = tuple(received)
            if init_stage_hook is not None:
                params_list.append(init_stage_hook(s, mod, args))
            y = self._stage_jit(s, mod)(params_list[s], *args)
            if rank_out is None:
                outputs.append(y)
            else:
                ranks = rank_out if isinstance(rank_out, (list, tuple)) else [rank_out]
                for r in ranks:
                    F.send(y, self._comm, r, self_rank=s, tag=self._tag)
        leftovers = [k for k, q in channels.slots.items()
                     if q and k[2] == self._tag]
        if leftovers:
            raise RuntimeError(
                f"unconsumed sends on channels {leftovers}: some rank_out "
                "has no matching rank_in consumer in this chain list")
        if not outputs:
            return None
        return outputs[0] if len(outputs) == 1 else tuple(outputs)
