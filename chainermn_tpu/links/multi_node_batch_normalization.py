"""Synchronized (multi-node) batch normalization.

Reference being rebuilt (path unverified, SURVEY.md provenance):
``MultiNodeBatchNormalization`` in 〔chainermn/links/batch_normalization.py〕
(upstream ChainerMN v1.2/1.3 era — the fork's era): BatchNorm whose batch
mean/variance are computed over the GLOBAL batch via an allreduce across
ranks, instead of each rank's local slice.  The reference implemented the
cross-rank moment reduction with ``comm.allreduce`` inside the link's
forward.

TPU-native form: flax's ``nn.BatchNorm`` already reduces its batch moments
with ``lax.pmean(..., axis_name)`` when given mesh axis names — exactly the
collective the reference hand-rolled.  This wrapper binds a communicator's
data axes to that parameter, so inside ``make_train_step`` /
``comm.run_spmd`` the normalization statistics are global-batch statistics.

Semantics note (SURVEY.md §7 hard part 5): the model zoo's default BN is
*local* + ``AllreducePersistent`` for checkpoint-time sync — the
reference's default training recipe.  Use this link where the reference
would use ``MultiNodeBatchNormalization`` (small per-rank batches where
local statistics are too noisy).
"""

from __future__ import annotations

from typing import Any, Optional, Sequence, Union

import flax.linen as nn


def MultiNodeBatchNormalization(
    communicator=None,
    *,
    axis_name: Optional[Union[str, Sequence[str]]] = None,
    use_running_average: Optional[bool] = None,
    momentum: float = 0.9,
    epsilon: float = 2e-5,
    dtype: Any = None,
    **kwargs,
) -> nn.BatchNorm:
    """Build a BatchNorm whose batch statistics are reduced across the
    communicator's data axes (reference signature:
    ``MultiNodeBatchNormalization(size, comm, decay, eps, ...)`` — the
    size is implied by the normalized feature axis here, and ``decay``/
    ``eps`` keep their reference defaults 0.9 / 2e-5).

    Exactly one of ``communicator`` / ``axis_name`` must be given.  The
    returned module only performs the cross-device reduction when applied
    inside an SPMD region where those axes are bound (``run_spmd`` /
    ``make_train_step``); applied outside one, flax raises on the unbound
    axis name — same failure mode as calling the reference's link without
    an initialized communicator.
    """
    if (communicator is None) == (axis_name is None):
        raise ValueError(
            "pass exactly one of communicator= or axis_name=")
    axes = tuple(communicator.data_axes) if communicator is not None \
        else axis_name
    return nn.BatchNorm(
        use_running_average=use_running_average,
        momentum=momentum,
        epsilon=epsilon,
        dtype=dtype,
        axis_name=axes,
        **kwargs,
    )


__all__ = ["MultiNodeBatchNormalization"]
