from chainermn_tpu.links.multi_node_chain_list import MultiNodeChainList

__all__ = ["MultiNodeChainList"]
