from chainermn_tpu.links.multi_node_chain_list import (MultiNodeChainList,
                                                        pseudo_loss)
from chainermn_tpu.links.multi_node_batch_normalization import (
    MultiNodeBatchNormalization,
)

__all__ = ["MultiNodeBatchNormalization", "MultiNodeChainList",
           "pseudo_loss"]
