"""Batch iterator (the Chainer ``SerialIterator`` role — external dependency
in the reference, supplied here so the training integration is standalone)."""

from __future__ import annotations

from typing import Optional

import numpy as np


class SerialIterator:
    def __init__(self, dataset, batch_size: int, *, repeat: bool = True,
                 shuffle: bool = True, seed: Optional[int] = None,
                 collate: bool = True):
        self.dataset = dataset
        self.batch_size = batch_size
        self._repeat = repeat
        self._shuffle = shuffle
        # collate=False yields the raw example list — required for
        # variable-size samples (e.g. undecoded/uncropped images) that a
        # downstream PrefetchIterator transforms and stacks itself
        self._collate = collate
        self._rng = np.random.RandomState(seed)
        self.epoch = 0
        self.iteration = 0
        self.is_new_epoch = False
        self._order = self._new_order()
        self._pos = 0

    def _new_order(self):
        n = len(self.dataset)
        return self._rng.permutation(n) if self._shuffle else np.arange(n)

    def reset(self):
        self.epoch = 0
        self.iteration = 0
        self.is_new_epoch = False
        self._order = self._new_order()
        self._pos = 0

    def __iter__(self):
        return self

    def __next__(self):
        n = len(self.dataset)
        if self._pos >= n:
            if not self._repeat:
                raise StopIteration
            self.epoch += 1
            self._order = self._new_order()
            self._pos = 0
        start, end = self._pos, min(self._pos + self.batch_size, n)
        idx = self._order[start:end]
        if len(idx) < self.batch_size and self._repeat:
            # wrap to keep batches full (static shapes keep XLA happy)
            extra = self._order[: self.batch_size - len(idx)]
            idx = np.concatenate([idx, extra])
            self.epoch += 1
            self._order = self._new_order()
            self._pos = 0
            self.is_new_epoch = True
        elif end >= n and self._repeat:
            # exact epoch boundary: advance the epoch now so reporting and
            # epoch-triggers see the completed epoch immediately
            self.is_new_epoch = True
            self.epoch += 1
            self._order = self._new_order()
            self._pos = 0
        else:
            self.is_new_epoch = end >= n
            self._pos = end
        self.iteration += 1
        examples = [self.dataset[int(i)] for i in idx]
        return _collate(examples) if self._collate else examples

    next = __next__

    @property
    def epoch_detail(self):
        return self.epoch + self._pos / max(len(self.dataset), 1)


def _collate(examples):
    first = examples[0]
    if isinstance(first, tuple):
        return tuple(np.stack([e[i] for e in examples]) for i in range(len(first)))
    return np.stack(examples)
