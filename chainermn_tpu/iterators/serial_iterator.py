"""Batch iterator (the Chainer ``SerialIterator`` role — external dependency
in the reference, supplied here so the training integration is standalone)."""

from __future__ import annotations

import threading
from typing import Optional

import numpy as np


class SerialIterator:
    def __init__(self, dataset, batch_size: int, *, repeat: bool = True,
                 shuffle: bool = True, seed: Optional[int] = None,
                 collate: bool = True):
        self.dataset = dataset
        self.batch_size = batch_size
        self._repeat = repeat
        self._shuffle = shuffle
        # collate=False yields the raw example list — required for
        # variable-size samples (e.g. undecoded/uncropped images) that a
        # downstream PrefetchIterator transforms and stacks itself
        self._collate = collate
        self._rng = np.random.RandomState(seed)
        self.epoch = 0
        self.iteration = 0
        self.is_new_epoch = False
        self._order = self._new_order()
        self._pos = 0
        # Guards next() vs state_dict(): a PrefetchIterator's producer
        # thread draws batches while a Snapshot extension serializes state
        # from the trainer thread — without this the snapshot could tear
        # (pos from before a reshuffle, order/rng from after).
        self._state_lock = threading.Lock()
        # Observability seam, bound once at construction: None when the
        # switch is off, so __next__ does one attribute check and nothing
        # else.  Latency lands in iterator_next_seconds (decode/collate
        # time; masked time when a PrefetchIterator sits in front).
        self._obs_timer = None
        from chainermn_tpu.observability import enabled, get_registry
        if enabled():
            self._obs_timer = get_registry().timer(
                "iterator_next_seconds",
                "host time per SerialIterator batch draw",
                iterator=type(self).__name__)

    def _new_order(self):
        n = len(self.dataset)
        return self._rng.permutation(n) if self._shuffle else np.arange(n)

    def reset(self):
        self.epoch = 0
        self.iteration = 0
        self.is_new_epoch = False
        self._order = self._new_order()
        self._pos = 0

    def __iter__(self):
        return self

    def __next__(self):
        if self._obs_timer is not None:
            with self._obs_timer:
                return self._draw()
        return self._draw()

    def _draw(self):
        with self._state_lock:
            n = len(self.dataset)
            if self._pos >= n:
                if not self._repeat:
                    raise StopIteration
                self.epoch += 1
                self._order = self._new_order()
                self._pos = 0
            start, end = self._pos, min(self._pos + self.batch_size, n)
            idx = self._order[start:end]
            if len(idx) < self.batch_size and self._repeat:
                # wrap to keep batches full (static shapes keep XLA happy)
                extra = self._order[: self.batch_size - len(idx)]
                idx = np.concatenate([idx, extra])
                self.epoch += 1
                self._order = self._new_order()
                self._pos = 0
                self.is_new_epoch = True
            elif end >= n and self._repeat:
                # exact epoch boundary: advance the epoch now so reporting
                # and epoch-triggers see the completed epoch immediately
                self.is_new_epoch = True
                self.epoch += 1
                self._order = self._new_order()
                self._pos = 0
            else:
                self.is_new_epoch = end >= n
                self._pos = end
            self.iteration += 1
        # dataset access (possibly decode-heavy) stays outside the lock
        examples = [self.dataset[int(i)] for i in idx]
        return _collate(examples) if self._collate else examples

    next = __next__

    @property
    def epoch_detail(self):
        return self.epoch + self._pos / max(len(self.dataset), 1)

    # -- checkpointable state (the reference serialized its iterators into
    # snapshots 〔extensions/checkpoint.py usage〕; same contract here) ----
    def state_dict(self) -> dict:
        """Position, epoch bookkeeping, current order, and RNG state as a
        flat dict of numpy arrays — checkpointer-friendly (every leaf is
        an array; structure is static for a given dataset).  Atomic with
        respect to :meth:`next` (a prefetching producer thread may be
        drawing batches while a snapshot extension serializes)."""
        with self._state_lock:
            keys, pos, has_gauss, cached = self._rng.get_state()[1:]
            return {
                "epoch": np.int64(self.epoch),
                "iteration": np.int64(self.iteration),
                "is_new_epoch": np.int64(self.is_new_epoch),
                "pos": np.int64(self._pos),
                "order": np.asarray(self._order, np.int64),
                "rng_keys": np.asarray(keys, np.uint32),
                "rng_pos": np.int64(pos),
                "rng_has_gauss": np.int64(has_gauss),
                "rng_cached": np.float64(cached),
            }

    def load_state_dict(self, state: dict) -> None:
        """Restore :meth:`state_dict` output: the next batch drawn equals
        the one the snapshotted iterator would have drawn."""
        order = np.asarray(state["order"])
        if len(order) != len(self.dataset):
            raise ValueError(
                f"iterator state is for a {len(order)}-example dataset; "
                f"this iterator has {len(self.dataset)} examples")
        with self._state_lock:
            self.epoch = int(state["epoch"])
            self.iteration = int(state["iteration"])
            self.is_new_epoch = bool(int(state["is_new_epoch"]))
            self._pos = int(state["pos"])
            self._order = order
            self._rng.set_state((
                "MT19937", np.asarray(state["rng_keys"], np.uint32),
                int(state["rng_pos"]), int(state["rng_has_gauss"]),
                float(state["rng_cached"])))


def _collate(examples):
    first = examples[0]
    if isinstance(first, tuple):
        return tuple(np.stack([e[i] for e in examples]) for i in range(len(first)))
    return np.stack(examples)
