from chainermn_tpu.iterators.serial_iterator import SerialIterator
from chainermn_tpu.iterators.multi_node_iterator import (
    create_multi_node_iterator,
    create_synchronized_iterator,
)

__all__ = [
    "SerialIterator",
    "create_multi_node_iterator",
    "create_synchronized_iterator",
]
