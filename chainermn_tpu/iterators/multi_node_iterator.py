"""Multi-node iterators.

Reference being rebuilt (path unverified, SURVEY.md provenance):
〔chainermn/iterators/〕 — ``create_multi_node_iterator(iterator, comm)``:
the master (rank 0) iterates the real dataset and broadcasts each batch;
other ranks' iterators are receive-only proxies.  Used when the dataset
cannot be sharded.  ``create_synchronized_iterator`` instead synchronizes
the random state so every rank draws identical batches.
"""

from __future__ import annotations

import numpy as np


class _MasterIterator:
    def __init__(self, iterator, comm, rank_master: int, tag: int = 700):
        self._it = iterator
        self._comm = comm
        self._master = rank_master
        self._tag = tag

    def __iter__(self):
        return self

    def __next__(self):
        try:
            batch = self._it.next()
            payload = ("batch", batch, self._it.epoch, self._it.is_new_epoch)
        except StopIteration:
            payload = ("stop", None, self._it.epoch, False)
        payload = self._comm.bcast_obj(payload, root=self._master)
        if payload[0] == "stop":
            raise StopIteration
        return payload[1]

    next = __next__

    def __getattr__(self, name):
        return getattr(self._it, name)


class _SlaveIterator:
    def __init__(self, comm, rank_master: int):
        self._comm = comm
        self._master = rank_master
        self.epoch = 0
        self.is_new_epoch = False

    def __iter__(self):
        return self

    def __next__(self):
        kind, batch, epoch, new_epoch = self._comm.bcast_obj(
            None, root=self._master)
        self.epoch = epoch
        self.is_new_epoch = new_epoch
        if kind == "stop":
            raise StopIteration
        return batch

    next = __next__


def create_multi_node_iterator(actual_iterator, communicator,
                               rank_master: int = 0):
    """Reference: rank-0-feeds-everyone iterator.  On the master host pass
    the real iterator; other hosts may pass ``None``."""
    if communicator.rank == rank_master:
        return _MasterIterator(actual_iterator, communicator, rank_master)
    return _SlaveIterator(communicator, rank_master)


def create_synchronized_iterator(actual_iterator, communicator):
    """Synchronize the iterator's RNG across hosts so every host draws the
    same batch order (reference: ``create_synchronized_iterator``)."""
    seed = None
    if communicator.rank == 0:
        seed = int(np.random.randint(0, 2**31 - 1))
    seed = communicator.bcast_obj(seed, root=0)
    if not hasattr(actual_iterator, "_rng"):
        # Silently returning an unsynchronized iterator would be exactly the
        # divergence this function exists to prevent.
        raise TypeError(
            f"{type(actual_iterator).__name__} exposes no _rng to "
            "synchronize; use SerialIterator or synchronize it manually "
            "with the broadcast seed")
    actual_iterator._rng = np.random.RandomState(seed)
    if hasattr(actual_iterator, "reset"):
        actual_iterator.reset()
    return actual_iterator
