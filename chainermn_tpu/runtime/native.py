"""ctypes binding for the native C++ DCN transport core.

Loads (building on demand with g++ if needed) ``dcn_transport.cpp`` — the
rebuild's native communication surface (SURVEY.md §2.3).  Wire-compatible
with :class:`chainermn_tpu.runtime.transport.PyTransport`; ``create_transport``
prefers this backend and falls back to pure Python when no compiler is
available (mirroring the reference's pure-Python install path, which ran
without its optional Cython NCCL extension).

Build cache: ``_libdcn.so`` next to the source, rebuilt when the source is
newer.  Disable with ``CHAINERMN_TPU_NATIVE_BUILD=0``.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Optional

_SRC = os.path.join(os.path.dirname(__file__), "dcn_transport.cpp")
_LIB = os.path.join(os.path.dirname(__file__), "_libdcn.so")
_BUILD_LOCK = threading.Lock()
_lib: Optional[ctypes.CDLL] = None


def _build() -> str:
    if os.environ.get("CHAINERMN_TPU_NATIVE_BUILD") == "0":
        raise ImportError("native build disabled (CHAINERMN_TPU_NATIVE_BUILD=0)")
    if (os.path.exists(_LIB)
            and os.path.getmtime(_LIB) >= os.path.getmtime(_SRC)):
        return _LIB
    tmp = _LIB + f".tmp{os.getpid()}"
    cmd = ["g++", "-O2", "-std=c++17", "-fPIC", "-shared", "-pthread",
           _SRC, "-o", tmp]
    try:
        subprocess.run(cmd, check=True, capture_output=True, timeout=120)
    except (OSError, subprocess.SubprocessError) as e:
        stderr = getattr(e, "stderr", b"") or b""
        raise ImportError(
            f"building dcn_transport failed: {e}\n{stderr.decode()}") from e
    os.replace(tmp, _LIB)  # atomic under concurrent builders
    return _LIB


def _load() -> ctypes.CDLL:
    global _lib
    with _BUILD_LOCK:
        if _lib is None:
            lib = ctypes.CDLL(_build())
            lib.dcn_create.restype = ctypes.c_void_p
            lib.dcn_create.argtypes = [ctypes.c_int, ctypes.c_int,
                                       ctypes.c_char_p, ctypes.c_char_p]
            lib.dcn_send.restype = ctypes.c_int
            lib.dcn_send.argtypes = [ctypes.c_void_p, ctypes.c_int,
                                     ctypes.c_uint32, ctypes.c_char_p,
                                     ctypes.c_uint64]
            lib.dcn_recv.restype = ctypes.c_int64
            lib.dcn_recv.argtypes = [ctypes.c_void_p, ctypes.c_int,
                                     ctypes.c_uint32, ctypes.c_double,
                                     ctypes.POINTER(ctypes.POINTER(ctypes.c_uint8))]
            lib.dcn_free.argtypes = [ctypes.POINTER(ctypes.c_uint8)]
            lib.dcn_peers.restype = ctypes.c_int64
            lib.dcn_peers.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                      ctypes.c_int64]
            lib.dcn_stats.argtypes = [ctypes.c_void_p,
                                      ctypes.POINTER(ctypes.c_uint64),
                                      ctypes.POINTER(ctypes.c_uint64)]
            lib.dcn_close.argtypes = [ctypes.c_void_p]
            lib.dcn_shutdown.argtypes = [ctypes.c_void_p]
            lib.dcn_destroy.argtypes = [ctypes.c_void_p]
            lib.dcn_last_error.restype = ctypes.c_char_p
            _lib = lib
    return _lib


class NativeTransport:
    """Same surface as ``PyTransport`` (send/recv/close/peers), C++ core.

    Lifetime safety: every FFI call into the handle is bracketed by an
    in-flight counter.  ``close()`` (a) marks the transport closed so new
    callers fail fast with OSError, (b) runs the native shutdown — which
    is what unblocks callers already inside ``dcn_send``/``dcn_recv`` —
    (c) waits for the in-flight count to reach zero, and only then (d)
    frees the native object.  Without (c)/(d) split a concurrent caller
    could touch freed memory (use-after-free).
    """

    def __init__(self, rank: int, size: int, coordinator: str):
        lib = _load()
        self._lib = lib
        self.rank = rank
        self.size = size
        my_host = os.environ.get("CHAINERMN_TPU_HOST", "127.0.0.1")
        handle = lib.dcn_create(rank, size, coordinator.encode(),
                                my_host.encode())
        if not handle:
            raise OSError(
                f"native transport init failed: "
                f"{lib.dcn_last_error().decode()}")
        self._handle = handle
        self._closed = False
        self._inflight = 0
        self._cv = threading.Condition()
        self._destroyed = threading.Event()

    def _enter(self):
        with self._cv:
            if self._closed:
                raise OSError("transport closed")
            self._inflight += 1

    def _exit(self):
        with self._cv:
            self._inflight -= 1
            if self._inflight == 0:
                self._cv.notify_all()

    @property
    def peers(self):
        import json

        self._enter()
        try:
            buf = ctypes.create_string_buffer(65536)
            n = self._lib.dcn_peers(self._handle, buf, len(buf))
            if n < 0:
                raise OSError("peer table too large")
            return {int(r): a for r, a in json.loads(buf.value.decode())}
        finally:
            self._exit()

    @property
    def peak_inbox_bytes(self) -> int:
        """High-water mark of inbox buffering (backpressure evidence)."""
        self._enter()
        try:
            cur = ctypes.c_uint64()
            peak = ctypes.c_uint64()
            self._lib.dcn_stats(self._handle, ctypes.byref(cur),
                                ctypes.byref(peak))
            return int(peak.value)
        finally:
            self._exit()

    def send(self, dest: int, tag: int, payload: bytes):
        self._enter()
        try:
            rc = self._lib.dcn_send(self._handle, dest, tag, payload,
                                    len(payload))
            if rc != 0:
                raise OSError(f"native send failed: "
                              f"{self._lib.dcn_last_error().decode()}")
        finally:
            self._exit()

    def recv(self, source: int, tag: int, timeout: float = 300.0) -> bytes:
        self._enter()
        try:
            out = ctypes.POINTER(ctypes.c_uint8)()
            n = self._lib.dcn_recv(self._handle, source, tag, timeout,
                                   ctypes.byref(out))
            if n < 0:
                raise TimeoutError(
                    f"native recv from rank {source} (tag {tag}): "
                    f"{self._lib.dcn_last_error().decode()}")
            try:
                if n < (1 << 31):
                    return ctypes.string_at(out, n)
                # ctypes._string_at takes a C int internally; >=2 GiB sizes
                # wrap negative.  Cast to a fixed-size array instead (array
                # lengths are ssize_t) and copy out.
                return bytes(
                    ctypes.cast(out, ctypes.POINTER(ctypes.c_uint8 * n))
                    .contents)
            finally:
                self._lib.dcn_free(out)
        finally:
            self._exit()

    def close(self):
        with self._cv:
            already_closing = self._closed
            self._closed = True  # new callers now fail fast in _enter
        if already_closing:
            # A concurrent closer won the race; close() returning must
            # still mean "the winner's teardown finished", so wait for it.
            self._destroyed.wait()
            return
        try:
            # Shutdown unblocks in-flight callers (fd shutdown + cv
            # wakeups); it must run BEFORE waiting on them, or a blocked
            # recv would pin close() for its full timeout.
            self._lib.dcn_shutdown(self._handle)
            with self._cv:
                while self._inflight:
                    self._cv.wait()
            self._lib.dcn_destroy(self._handle)
        finally:
            # Set even on failure: a raised close() must not convert every
            # later close() into a permanent _destroyed.wait() hang.
            self._destroyed.set()
