"""DCN point-to-point byte transport.

Wire layer under :mod:`chainermn_tpu.runtime.control_plane`.  Two backends:

* the native C++ framing core (``dcn_transport.cpp``, loaded via ctypes) —
  the rebuild's analogue of the reference's native MPI/NCCL surface
  (SURVEY.md §2.3); and
* this pure-Python fallback (same wire format), always available.

Wire format (identical for both backends so they interoperate):
  frame := u32 src | u32 tag | u64 len | len bytes payload
Handshake: every rank connects to the coordinator (rank 0) and sends its
listen address; rank 0 replies with the full peer table.  This mirrors the
reference's hostname-allgather bootstrap 〔_communication_utility.py〕.
"""

from __future__ import annotations

import json
import os
import queue
import socket
import struct
import threading
import time
from typing import Dict, Tuple

_HDR = struct.Struct("<IIQ")

# Inbox high-water mark (bytes).  When a reader thread would push the inbox
# past this, it blocks until a consumer drains — TCP flow control then
# backpressures the sender, so memory stays bounded at roughly
# HWM + one message no matter how far ahead a peer runs.  The reference hit
# the same scale problem as an INT_MAX chunking workaround
# 〔mpi_communicator_base.py, SURVEY §2.1〕; here the u64 framing removes the
# wire limit and this budget bounds the buffering.
_DEFAULT_HWM = 1 << 30


def _inbox_hwm() -> int:
    # Mirrors the C++ transport's guard: non-numeric or <= 0 values fall
    # back to the default rather than making the reader-park predicate
    # (inbox_bytes >= hwm) permanently true and deadlocking every recv.
    raw = os.environ.get("CHAINERMN_TPU_INBOX_HWM")
    if raw is None:
        return _DEFAULT_HWM
    try:
        val = int(raw)
    except ValueError:
        return _DEFAULT_HWM
    return val if val > 0 else _DEFAULT_HWM


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    # recv_into a preallocated buffer: GiB-scale frames must not allocate a
    # fresh buffer per recv() call (socket.recv allocates its bufsize
    # argument up front) and must not round-trip through bytearray.extend.
    buf = bytearray(n)
    view = memoryview(buf)
    got = 0
    while got < n:
        k = sock.recv_into(view[got:], n - got)
        if not k:
            raise ConnectionError("peer closed connection")
        got += k
    return bytes(buf)


class PyTransport:
    """Pure-Python full-mesh TCP transport with a listener thread per rank."""

    def __init__(self, rank: int, size: int, coordinator: str):
        self.rank = rank
        self.size = size
        # Flight-recorder seam, bound once at construction (None when
        # observability is off — the disabled wire path records nothing).
        from chainermn_tpu.observability import flight_recorder as _flight
        self._flight = _flight.get_flight_recorder()
        self._inbox: Dict[Tuple[int, int], queue.Queue] = {}
        self._inbox_lock = threading.Lock()
        # Inbox byte budget (backpressure) — see _DEFAULT_HWM above.
        self._hwm = _inbox_hwm()
        self._inbox_bytes = 0
        self.peak_inbox_bytes = 0
        self._budget_cv = threading.Condition(self._inbox_lock)
        self._out: Dict[int, socket.socket] = {}
        # Per-destination locks: one slow peer must not serialize the whole
        # outbound plane (bcast from rank 0 fans out concurrently).
        self._out_locks: Dict[int, threading.Lock] = {}
        self._out_locks_guard = threading.Lock()
        self._closed = False

        # Listen on an ephemeral port; learn everyone's address via rank 0.
        self._listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._listener.bind(("0.0.0.0", 0))
        self._listener.listen(size + 8)
        my_port = self._listener.getsockname()[1]
        my_host = os.environ.get("CHAINERMN_TPU_HOST", "127.0.0.1")

        self._accept_thread = threading.Thread(target=self._accept_loop, daemon=True)
        self._accept_thread.start()

        chost, cport = coordinator.rsplit(":", 1)
        self.peers = self._handshake(chost, int(cport), f"{my_host}:{my_port}")

    # -- bootstrap -----------------------------------------------------------
    def _handshake(self, chost: str, cport: int, my_addr: str):
        if self.rank == 0:
            table = {0: my_addr}
            srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
            srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
            srv.bind((chost if chost not in ("127.0.0.1", "localhost") else "0.0.0.0", cport))
            srv.listen(self.size + 8)
            conns = []
            while len(table) < self.size:
                c, _ = srv.accept()
                r, _, payload = self._read_frame(c)
                table[r] = payload.decode()
                conns.append((r, c))
            blob = json.dumps(sorted(table.items())).encode()
            for r, c in conns:
                self._write_frame(c, 0, 0, blob)
                c.close()
            srv.close()
            return dict(sorted(table.items()))
        # Non-root: register with coordinator, get the table back.
        deadline = time.time() + 60
        while True:
            try:
                c = socket.create_connection((chost, cport), timeout=5)
                break
            except OSError:
                if time.time() > deadline:
                    raise
                time.sleep(0.05)
        self._write_frame(c, self.rank, 0, my_addr.encode())
        _, _, blob = self._read_frame(c)
        c.close()
        # JSON, not pickle/eval: the handshake reads from an unauthenticated
        # socket and must not be able to execute anything.
        return {int(r): addr for r, addr in json.loads(blob.decode())}

    # -- framing -------------------------------------------------------------
    @staticmethod
    def _write_frame(sock, src, tag, payload: bytes):
        if len(payload) <= 64 * 1024:
            # One write for small frames (avoids a partial-header interleave
            # risk under TCP_NODELAY and halves syscalls on the hot
            # control-plane path).
            sock.sendall(_HDR.pack(src, tag, len(payload)) + payload)
        else:
            # Large frames: header then the payload itself — concatenating
            # would copy the whole (possibly multi-GiB) buffer.  sendall
            # streams from the original object; the kernel chunks it.
            sock.sendall(_HDR.pack(src, tag, len(payload)))
            sock.sendall(payload)

    @staticmethod
    def _read_frame(sock):
        src, tag, n = _HDR.unpack(_recv_exact(sock, _HDR.size))
        return src, tag, _recv_exact(sock, n)

    # -- receive path --------------------------------------------------------
    def _accept_loop(self):
        while not self._closed:
            try:
                conn, _ = self._listener.accept()
            except OSError:
                return
            threading.Thread(target=self._reader_loop, args=(conn,), daemon=True).start()

    def _reader_loop(self, conn):
        try:
            while True:
                src, tag, payload = self._read_frame(conn)
                self._enqueue(src, tag, payload, wait_budget=True)
        except (ConnectionError, OSError):
            conn.close()

    def _q(self, src, tag):
        with self._inbox_lock:
            return self._inbox.setdefault((src, tag), queue.Queue())

    def _enqueue(self, src, tag, payload, wait_budget: bool):
        with self._budget_cv:
            if wait_budget:
                # Reader threads block while the inbox is over budget; the
                # unread bytes then sit in the kernel socket buffers and TCP
                # flow control stalls the sender.  One message is always
                # admitted once the inbox is under the mark, so a single
                # payload larger than the budget still passes (peak usage
                # <= HWM + largest message).  Self-sends (wait_budget=False)
                # never block: the sender would be waiting on itself.
                while self._inbox_bytes >= self._hwm and not self._closed:
                    self._budget_cv.wait()
                if self._closed:
                    return
            self._inbox_bytes += len(payload)
            self.peak_inbox_bytes = max(self.peak_inbox_bytes,
                                        self._inbox_bytes)
            q = self._inbox.setdefault((src, tag), queue.Queue())
        q.put(payload)

    # -- public API ----------------------------------------------------------
    def send(self, dest: int, tag: int, payload: bytes):
        if self._flight is not None and tag < (1 << 28):
            self._flight.record("transport_send", dest=dest, tag=tag,
                                nbytes=len(payload))
        if dest == self.rank:
            self._enqueue(self.rank, tag, payload, wait_budget=False)
            return
        with self._out_locks_guard:
            lock = self._out_locks.setdefault(dest, threading.Lock())
        with lock:
            sock = self._out.get(dest)
            if sock is None:
                host, port = self.peers[dest].rsplit(":", 1)
                sock = socket.create_connection((host, int(port)), timeout=30)
                sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
                self._out[dest] = sock
            self._write_frame(sock, self.rank, tag, payload)

    def recv(self, source: int, tag: int, timeout: float = 300.0) -> bytes:
        # A wedged recv is the DCN face of a hang: track it as an open
        # span so the watchdog's deadline predicate sees it.  Watchdog
        # traffic itself (short-poll recvs on its own tag) stays out of
        # the ring.
        fl = self._flight
        if fl is not None and tag >= (1 << 28):
            fl = None
        tok = None
        if fl is not None:
            tok = fl.span_begin("transport_recv", f"recv[src={source}]",
                                tag=tag)
        try:
            payload = self._q(source, tag).get(timeout=timeout)
        except queue.Empty:
            if tok is not None:
                fl.span_end(tok, timed_out=True)
            raise TimeoutError(
                f"recv from rank {source} (tag {tag}) timed out after {timeout}s"
            ) from None
        if tok is not None:
            fl.span_end(tok, nbytes=len(payload))
        with self._budget_cv:
            self._inbox_bytes -= len(payload)
            self._budget_cv.notify_all()
        return payload

    def close(self):
        self._closed = True
        with self._budget_cv:
            self._budget_cv.notify_all()  # wake readers parked on the budget
        try:
            self._listener.close()
        except OSError:
            pass
        for s in list(self._out.values()):
            try:
                s.close()
            except OSError:
                pass
        self._out.clear()


def create_transport(rank: int, size: int, coordinator: str):
    """Prefer the native C++ core; fall back to pure Python (same protocol)."""
    if os.environ.get("CHAINERMN_TPU_PURE_PY_TRANSPORT") != "1":
        try:
            from chainermn_tpu.runtime.native import NativeTransport

            return NativeTransport(rank, size, coordinator)
        except (ImportError, OSError):
            pass
    return PyTransport(rank, size, coordinator)
