"""Multi-controller bootstrap — ``jax.distributed`` with no launcher.

Reference analogue (SURVEY.md §2.5, §3.1): the reference's world came from
``mpiexec -n N`` + ``MPI.COMM_WORLD``; the north star
(`BASELINE.json:north_star`) replaces that with "one controller process per
TPU host, topology from TPU slice metadata, no MPI launcher in the loop".

One env contract covers the whole stack (shared with
:mod:`chainermn_tpu.runtime.control_plane`):

    CHAINERMN_TPU_COORDINATOR=host:port   rank-0 host
    CHAINERMN_TPU_NUM_PROCESSES=N
    CHAINERMN_TPU_PROCESS_ID=r

``init_distributed()`` wires ``jax.distributed.initialize`` from it —
the JAX coordination service listens on ``port + 1`` (the control plane
owns ``port``).  On real TPU slices the arguments can be omitted
entirely: ``jax.distributed.initialize()`` discovers everything from
slice metadata, which IS the no-launcher path.  On CPU it also selects
gloo cross-process collectives so the multi-controller tests/examples
run on any machine.
"""

from __future__ import annotations

import os
import sys
from typing import Optional


def install_crash_dumps(out_dir: Optional[str] = None,
                        rank: Optional[int] = None,
                        recorder=None, watchdog=None,
                        signals=None, force: bool = False):
    """Wire the flight-recorder dump path to process-fatal events:

    * unhandled exceptions (``sys.excepthook`` — dump, then chain to the
      previous hook so the traceback still prints);
    * fatal signals (default: ``SIGTERM``, the preemption/kill signal —
      dump, restore the prior disposition, re-deliver);
    * native crashes (``faulthandler.enable`` into
      ``flight_<rank>.stacks.txt`` — when the interpreter cannot run the
      JSON dump, the C-level stack writer still can).

    Returns an ``uninstall()`` callable, or ``None`` (installing nothing)
    when observability is disabled and ``force`` is not set.  When a
    ``watchdog`` handle is passed, dumps go through its cross-rank state
    exchange; otherwise the dump is local-only.
    """
    import faulthandler
    import signal as _signal

    from chainermn_tpu.observability import flight_recorder as _flight

    rec = recorder if recorder is not None else _flight.get_flight_recorder()
    if rec is None:
        if not force:
            return None
        rec = _flight.install_flight_recorder()
    if out_dir is None:
        out_dir = os.environ.get("CHAINERMN_TPU_FLIGHT_DIR", ".")
    if rank is None:
        rank = int(os.environ.get("CHAINERMN_TPU_PROCESS_ID", "0") or 0)

    def _dump(reason: str) -> None:
        try:
            if watchdog is not None:
                watchdog.dump_now(reason)
            else:
                # crash-time evidence stamp: ring capacity + overwrite
                # count travel in the dump so a restart manifest can
                # flag a truncated evidence window (the dump itself
                # also records both; stamping here keeps the contract
                # explicit even for pre-ring readers of `extra`)
                rec.dump(out_dir=out_dir, rank=rank, reason=reason,
                         extra={"crash_dump": True,
                                "ring_capacity": int(rec.capacity),
                                "dropped_events": int(rec.dropped_events)})
        except Exception:
            pass  # the dump path must never mask the original failure

    prev_hook = sys.excepthook

    def _excepthook(tp, val, tb):
        _dump(f"unhandled_exception:{tp.__name__}: {val}")
        prev_hook(tp, val, tb)

    sys.excepthook = _excepthook

    fh_file = None
    try:
        os.makedirs(out_dir or ".", exist_ok=True)
        fh_file = open(os.path.join(out_dir or ".",
                                    f"flight_{rank}.stacks.txt"), "w")
        faulthandler.enable(file=fh_file)
    except OSError:
        fh_file = None

    prev_handlers = {}
    sigs = signals if signals is not None else (_signal.SIGTERM,)
    for sig in sigs:
        try:
            prev = _signal.getsignal(sig)

            def _handler(signum, frame, _prev=prev):
                _dump(f"signal:{_signal.Signals(signum).name}")
                restore = _prev if (callable(_prev) or _prev in (
                    _signal.SIG_IGN, _signal.SIG_DFL)) else _signal.SIG_DFL
                _signal.signal(signum, restore)
                os.kill(os.getpid(), signum)  # re-deliver to prior handler

            _signal.signal(sig, _handler)
            prev_handlers[sig] = prev
        except (ValueError, OSError):
            pass  # not the main thread, or unsupported signal

    def uninstall():
        sys.excepthook = prev_hook
        for sig, prev in prev_handlers.items():
            try:
                _signal.signal(sig, prev)
            except (ValueError, OSError):
                pass
        if fh_file is not None:
            try:
                faulthandler.disable()
                fh_file.close()
            except (OSError, ValueError):
                pass

    return uninstall


def _tpu_metadata_present() -> bool:
    """True when this host looks like part of a Cloud TPU slice.

    On standard Cloud TPU VMs ``JAX_PLATFORMS`` is typically unset (the
    TPU plugin is auto-discovered), so platform config alone cannot
    decide whether the no-arg ``jax.distributed.initialize()`` pod path
    should run.  Check the slice-metadata env the TPU runtime exports
    (any one suffices).  Deliberately NOT a libtpu-presence check: the
    wheel being installed says nothing about running on a slice, and a
    false positive here costs an off-GCP metadata-server probe.
    """
    for var in ("TPU_WORKER_HOSTNAMES", "TPU_WORKER_ID", "CLOUD_TPU_TASK_ID",
                "TPU_SKIP_MDS_QUERY", "TPU_ACCELERATOR_TYPE"):
        if os.environ.get(var):
            return True
    return False


def init_distributed(
    coordinator: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
    local_device_count: Optional[int] = None,
) -> None:
    """Initialize the JAX multi-controller runtime from args or env.

    No-op when neither args, env, nor TPU metadata indicate a
    multi-process world (single-controller remains the default).
    """
    import jax

    coordinator = coordinator or os.environ.get("CHAINERMN_TPU_COORDINATOR")
    if num_processes is None:
        n = os.environ.get("CHAINERMN_TPU_NUM_PROCESSES")
        num_processes = int(n) if n else None
    if process_id is None:
        r = os.environ.get("CHAINERMN_TPU_PROCESS_ID")
        process_id = int(r) if r else None

    # IMPORTANT: nothing in this function may query the backend
    # (jax.devices()/default_backend()) before initialize() — that would
    # initialize XLA and make jax.distributed.initialize() fail.
    if coordinator is None and num_processes is None and process_id is None:
        # TPU pod path: `jax.distributed.initialize()` with no args reads
        # slice metadata.  Attempt it when the configured platform looks
        # like TPU — or when Cloud TPU metadata is present even though
        # JAX_PLATFORMS is unset (the common case: the TPU plugin is
        # auto-discovered, nobody exports JAX_PLATFORMS).  Off-TPU stay
        # single-controller.
        platforms = (os.environ.get("JAX_PLATFORMS")
                     or getattr(jax.config, "jax_platforms", None) or "")
        if "tpu" in platforms or ("cpu" not in platforms and _tpu_metadata_present()):
            try:
                jax.distributed.initialize()
            except RuntimeError as e:
                # "already initialized" is fine; so is "must be called
                # before any JAX calls" on a SINGLE-host slice (some TPU
                # platform plugins initialize the backend at interpreter
                # startup, before user code can run — single-controller
                # is then exactly the right world).  On a multi-host
                # slice the same condition must NOT be swallowed: each
                # host silently proceeding as its own single-controller
                # world would train divergent models.
                msg = str(e).lower()
                hosts = [h for h in os.environ.get(
                    "TPU_WORKER_HOSTNAMES", "").split(",") if h]
                single_host = len(hosts) <= 1
                if "already" in msg:
                    pass
                elif "must be called before" in msg and single_host:
                    pass
                else:
                    raise
            except Exception as e:
                import warnings

                warnings.warn(
                    f"jax.distributed.initialize() from TPU metadata "
                    f"failed ({e!r}); continuing single-controller. If "
                    f"this host is part of a multi-host slice, fix the "
                    f"bootstrap — training would silently diverge.",
                    RuntimeWarning)
        install_crash_dumps()   # no-op when observability is disabled
        return

    if coordinator is None or num_processes is None or process_id is None:
        raise ValueError(
            "multi-process bootstrap needs coordinator, num_processes and "
            "process_id (args or CHAINERMN_TPU_* env)")

    host, port = coordinator.rsplit(":", 1)
    jax_coord = f"{host}:{int(port) + 1}"   # control plane owns `port`

    platforms = (os.environ.get("JAX_PLATFORMS")
                 or getattr(jax.config, "jax_platforms", None) or "")
    if not platforms or platforms.startswith("cpu"):
        # cross-process CPU collectives (the tests' multi-host analogue)
        jax.config.update("jax_cpu_collectives_implementation", "gloo")
    if local_device_count is not None:
        # jax < 0.5 has no jax_num_cpu_devices option; fall back to the
        # XLA_FLAGS knob (must land before the first backend exists,
        # which holds here — bootstrap precedes any jax.devices() call)
        from chainermn_tpu.utils.cpu_mesh import _set_cpu_device_flags

        _set_cpu_device_flags(local_device_count)

    jax.distributed.initialize(
        coordinator_address=jax_coord,
        num_processes=num_processes,
        process_id=process_id,
    )
    install_crash_dumps(rank=process_id)  # no-op when observability is off


__all__ = ["init_distributed", "install_crash_dumps"]
