// DCN point-to-point byte transport — native core.
//
// The rebuild's analogue of the reference's native communication surface
// (SURVEY.md §2.3: MPI C library + NCCL binding): the framing/socket layer
// under chainermn_tpu.runtime.control_plane, carrying pickled control-plane
// objects between TPU host controllers over DCN.  Wire-compatible with the
// pure-Python fallback in transport.py:
//
//   frame    := u32 src | u32 tag | u64 len | payload          (little endian)
//   handshake: every rank connects to the coordinator (rank 0) and sends its
//   listen address; rank 0 replies with the full peer table as JSON
//   [[rank, "host:port"], ...] — the reference's hostname-allgather bootstrap
//   (init_ranks 〔_communication_utility.py〕) over sockets.
//
// Exposed as a C ABI consumed by ctypes (runtime/native.py); no Python.h
// dependency, so it builds with a bare `g++ -shared`.
//
// Build: g++ -O2 -std=c++17 -fPIC -shared -pthread dcn_transport.cpp -o _libdcn.so

#include <arpa/inet.h>
#include <netdb.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <sys/socket.h>
#include <unistd.h>

#include <algorithm>
#include <atomic>
#include <chrono>
#include <condition_variable>
#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <deque>
#include <map>
#include <mutex>
#include <string>
#include <thread>
#include <vector>

namespace {

struct FrameHeader {
  uint32_t src;
  uint32_t tag;
  uint64_t len;
} __attribute__((packed));

static_assert(sizeof(FrameHeader) == 16, "header must match Python struct <IIQ");

thread_local std::string g_last_error;

void set_error(const std::string& msg) { g_last_error = msg; }

bool send_all(int fd, const void* buf, size_t n) {
  const char* p = static_cast<const char*>(buf);
  while (n > 0) {
    ssize_t k = ::send(fd, p, n, MSG_NOSIGNAL);
    if (k <= 0) {
      if (k < 0 && (errno == EINTR)) continue;
      return false;
    }
    p += k;
    n -= static_cast<size_t>(k);
  }
  return true;
}

bool recv_all(int fd, void* buf, size_t n) {
  char* p = static_cast<char*>(buf);
  while (n > 0) {
    ssize_t k = ::recv(fd, p, n, 0);
    if (k <= 0) {
      if (k < 0 && errno == EINTR) continue;
      return false;
    }
    p += k;
    n -= static_cast<size_t>(k);
  }
  return true;
}

// Frame payload holder.  ::malloc-backed (NOT std::string: resize()
// value-initializes, which at GiB payloads is a full extra memory pass —
// measured to collapse loopback goodput to 58 MB/s at 2 GiB on this
// 1-core host).  Ownership moves through the inbox and is RELEASED to the
// ctypes caller in dcn_recv (who frees via dcn_free -> ::free), so the
// receive path's only copies are the socket read and the final
// Python-bytes construction — same count as the Python fallback.
struct Buffer {
  uint8_t* data = nullptr;
  uint64_t len = 0;
  Buffer() = default;
  explicit Buffer(uint64_t n)
      : data(static_cast<uint8_t*>(::malloc(n ? n : 1))), len(n) {}
  Buffer(const void* src, uint64_t n) : Buffer(n) {
    if (data && n) std::memcpy(data, src, n);
  }
  Buffer(Buffer&& o) noexcept : data(o.data), len(o.len) {
    o.data = nullptr;
    o.len = 0;
  }
  Buffer& operator=(Buffer&& o) noexcept {
    if (this != &o) {
      ::free(data);
      data = o.data;
      len = o.len;
      o.data = nullptr;
      o.len = 0;
    }
    return *this;
  }
  Buffer(const Buffer&) = delete;
  Buffer& operator=(const Buffer&) = delete;
  ~Buffer() { ::free(data); }
  uint8_t* release() {
    uint8_t* p = data;
    data = nullptr;
    len = 0;
    return p;
  }
};

bool write_frame(int fd, uint32_t src, uint32_t tag, const void* payload,
                 uint64_t len) {
  FrameHeader h{src, tag, len};
  // One buffered write for small frames avoids a partial-header race with
  // TCP_NODELAY; large payloads go in two writes.
  if (len <= 64 * 1024) {
    std::vector<char> buf(sizeof(h) + len);
    std::memcpy(buf.data(), &h, sizeof(h));
    if (len) std::memcpy(buf.data() + sizeof(h), payload, len);
    return send_all(fd, buf.data(), buf.size());
  }
  return send_all(fd, &h, sizeof(h)) && send_all(fd, payload, len);
}

bool read_frame(int fd, uint32_t* src, uint32_t* tag, Buffer* payload) {
  FrameHeader h;
  if (!recv_all(fd, &h, sizeof(h))) return false;
  Buffer buf(h.len);
  if (!buf.data) return false;  // allocation failed (absurd len / OOM)
  if (h.len && !recv_all(fd, buf.data, h.len)) return false;
  *payload = std::move(buf);
  *src = h.src;
  *tag = h.tag;
  return true;
}

int connect_to(const std::string& host, int port, double timeout_s,
               const std::atomic<bool>* cancel = nullptr) {
  auto deadline = std::chrono::steady_clock::now() +
                  std::chrono::duration<double>(timeout_s);
  for (;;) {
    // Abort promptly when the owning transport is closing: a sender stuck
    // retrying an unreachable peer must not pin close() for the full
    // connect timeout via the in-flight drain.
    if (cancel && cancel->load()) return -1;
    struct addrinfo hints {};
    hints.ai_family = AF_INET;
    hints.ai_socktype = SOCK_STREAM;
    struct addrinfo* res = nullptr;
    std::string port_s = std::to_string(port);
    if (getaddrinfo(host.c_str(), port_s.c_str(), &hints, &res) == 0 && res) {
      int fd = ::socket(res->ai_family, res->ai_socktype, res->ai_protocol);
      if (fd >= 0) {
        if (::connect(fd, res->ai_addr, res->ai_addrlen) == 0) {
          freeaddrinfo(res);
          int one = 1;
          setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
          return fd;
        }
        ::close(fd);
      }
      freeaddrinfo(res);
    }
    if (std::chrono::steady_clock::now() > deadline) return -1;
    std::this_thread::sleep_for(std::chrono::milliseconds(50));
  }
}

// Parse the handshake table [[rank, "host:port"], ...] (json.dumps output of
// the Python side).  Minimal scanner — the format is fixed and machine
// generated; anything unexpected fails the handshake rather than guessing.
bool parse_table(const std::string& s, std::map<int, std::string>* out) {
  size_t i = 0;
  auto skip_ws = [&] { while (i < s.size() && isspace((unsigned char)s[i])) ++i; };
  skip_ws();
  if (i >= s.size() || s[i] != '[') return false;
  ++i;
  for (;;) {
    skip_ws();
    if (i < s.size() && s[i] == ']') { ++i; return true; }
    if (i >= s.size() || s[i] != '[') return false;
    ++i;
    skip_ws();
    size_t j = i;
    while (j < s.size() && (isdigit((unsigned char)s[j]) || s[j] == '-')) ++j;
    if (j == i) return false;
    int rank = std::stoi(s.substr(i, j - i));
    i = j;
    skip_ws();
    if (i >= s.size() || s[i] != ',') return false;
    ++i;
    skip_ws();
    if (i >= s.size() || s[i] != '"') return false;
    ++i;
    j = s.find('"', i);
    if (j == std::string::npos) return false;
    (*out)[rank] = s.substr(i, j - i);
    i = j + 1;
    skip_ws();
    if (i >= s.size() || s[i] != ']') return false;
    ++i;
    skip_ws();
    if (i < s.size() && s[i] == ',') ++i;
  }
}

std::string dump_table(const std::map<int, std::string>& table) {
  std::string out = "[";
  bool first = true;
  for (const auto& [rank, addr] : table) {
    if (!first) out += ", ";
    first = false;
    out += "[" + std::to_string(rank) + ", \"" + addr + "\"]";
  }
  out += "]";
  return out;
}

class Transport {
 public:
  Transport(int rank, int size) : rank_(rank), size_(size) {
    // Inbox byte budget (backpressure): a reader thread blocks before
    // pushing the inbox past this mark, so unread bytes stay in the kernel
    // socket buffers and TCP flow control stalls the peer.  Memory is then
    // bounded at ~HWM + one message regardless of how far ahead a sender
    // runs (the GiB-scale analogue of the reference's INT_MAX chunking
    // concern).  Mirrors the Python fallback's budget.
    if (const char* env = std::getenv("CHAINERMN_TPU_INBOX_HWM")) {
      char* end = nullptr;
      unsigned long long v = std::strtoull(env, &end, 10);
      if (end && *end == '\0' && v > 0) hwm_ = v;
    }
  }

  bool init(const std::string& coordinator, const std::string& my_host) {
    // Listen on an ephemeral port.
    listen_fd_ = ::socket(AF_INET, SOCK_STREAM, 0);
    if (listen_fd_ < 0) return fail("socket() failed");
    int one = 1;
    setsockopt(listen_fd_, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
    sockaddr_in addr{};
    addr.sin_family = AF_INET;
    addr.sin_addr.s_addr = INADDR_ANY;
    addr.sin_port = 0;
    if (::bind(listen_fd_, (sockaddr*)&addr, sizeof(addr)) != 0)
      return fail("bind() failed");
    if (::listen(listen_fd_, size_ + 8) != 0) return fail("listen() failed");
    socklen_t alen = sizeof(addr);
    getsockname(listen_fd_, (sockaddr*)&addr, &alen);
    int my_port = ntohs(addr.sin_port);

    accept_thread_ = std::thread([this] { accept_loop(); });

    std::string my_addr = my_host + ":" + std::to_string(my_port);
    auto colon = coordinator.rfind(':');
    if (colon == std::string::npos) return fail("coordinator must be host:port");
    std::string chost = coordinator.substr(0, colon);
    int cport = std::stoi(coordinator.substr(colon + 1));
    return handshake(chost, cport, my_addr);
  }

  bool send(int dest, uint32_t tag, const void* data, uint64_t len) {
    if (dest == rank_) {
      Buffer copy(data, len);
      if (!copy.data) return fail("self-send allocation failed");
      push(rank_, tag, std::move(copy), /*wait_budget=*/false);
      return true;
    }
    // Register as an in-flight sender for the WHOLE call — including the
    // connect phase, which can block for seconds with no fd registered
    // anywhere close() could shut down.  close()/~Transport wait for
    // active_sends_ == 0 before the object (out_locks_, out_fds_, peers_)
    // is torn down; registering only around the write would let close()
    // return while a connecting sender still holds references into us.
    struct SendGuard {
      Transport* t;
      ~SendGuard() {
        {
          std::lock_guard<std::mutex> g(t->out_mutex_);
          --t->active_sends_;
        }
        t->out_cv_.notify_all();
      }
    };
    {
      std::lock_guard<std::mutex> g(out_mutex_);
      if (closed_.load()) return fail("transport closed");
      ++active_sends_;
    }
    SendGuard guard{this};
    std::unique_lock<std::mutex> out_guard(out_mutex_);
    auto& lock = out_locks_[dest];  // per-dest serialization
    out_guard.unlock();
    std::lock_guard<std::mutex> g(lock);
    int fd;
    {
      std::lock_guard<std::mutex> g2(out_mutex_);
      if (closed_.load()) return fail("transport closed");
      auto it = out_fds_.find(dest);
      fd = it == out_fds_.end() ? -1 : it->second;
    }
    if (fd < 0) {
      auto it = peers_.find(dest);
      if (it == peers_.end()) return fail("unknown peer " + std::to_string(dest));
      auto colon = it->second.rfind(':');
      fd = connect_to(it->second.substr(0, colon),
                      std::stoi(it->second.substr(colon + 1)), 30.0,
                      &closed_);
      if (fd < 0) return fail("connect to peer " + std::to_string(dest) + " failed");
      std::lock_guard<std::mutex> g2(out_mutex_);
      if (closed_.load()) {
        ::close(fd);
        return fail("transport closed");
      }
      out_fds_[dest] = fd;
    }
    if (!write_frame(fd, rank_, tag, data, len))
      return fail("send to peer " + std::to_string(dest) + " failed");
    return true;
  }

  // Returns true and fills *out, or false on timeout/shutdown.
  bool recv(int source, uint32_t tag, double timeout_s, Buffer* out) {
    std::unique_lock<std::mutex> lk(inbox_mutex_);
    // Registered so close() can wait for in-flight receivers to drain
    // before the object is destroyed (use-after-free otherwise).
    ++active_recvs_;
    auto key = std::make_pair(source, tag);
    bool ok = inbox_cv_.wait_for(
        lk, std::chrono::duration<double>(timeout_s),
        [&] { return closed_.load() || !inbox_[key].empty(); });
    bool success = ok && !inbox_[key].empty();
    if (success) {
      *out = std::move(inbox_[key].front());
      inbox_[key].pop_front();
      inbox_bytes_ -= out->len;  // releases parked readers via notify
    }
    --active_recvs_;
    inbox_cv_.notify_all();
    if (!success)
      return fail(closed_.load() ? "transport closed"
                                 : "recv timed out (source " +
                                       std::to_string(source) + ", tag " +
                                       std::to_string(tag) + ")");
    return true;
  }

  void close() {
    closed_.store(true);
    if (listen_fd_ >= 0) {
      ::shutdown(listen_fd_, SHUT_RDWR);
      ::close(listen_fd_);
      listen_fd_ = -1;
    }
    {
      // Shut down first (unblocks any sender mid-write; fd stays valid),
      // drain in-flight senders, then close — never close under a writer.
      std::unique_lock<std::mutex> g(out_mutex_);
      for (auto& [dest, fd] : out_fds_) ::shutdown(fd, SHUT_RDWR);
      out_cv_.wait(g, [&] { return active_sends_ == 0; });
      for (auto& [dest, fd] : out_fds_) ::close(fd);
      out_fds_.clear();
    }
    {
      std::lock_guard<std::mutex> g(conn_mutex_);
      for (int fd : in_fds_) ::shutdown(fd, SHUT_RDWR);
    }
    {
      // Wake blocked receivers and wait for them to leave recv() before the
      // destructor tears down the mutex/condvar they are using.
      std::unique_lock<std::mutex> lk(inbox_mutex_);
      inbox_cv_.notify_all();
      inbox_cv_.wait(lk, [&] { return active_recvs_ == 0; });
    }
    if (accept_thread_.joinable()) accept_thread_.join();
    for (auto& t : reader_threads_)
      if (t.joinable()) t.join();
  }

  // If close() threw partway, threads may still be blocked on live fds; a
  // detached thread would then dereference freed members (use-after-free).
  // Re-run the (idempotent) shutdown passes so every blocked syscall returns,
  // then join.  Detach only as a last resort if a join itself throws —
  // destroying a joinable std::thread would std::terminate the process.
  ~Transport() {
    closed_.store(true);
    if (listen_fd_ >= 0) ::shutdown(listen_fd_, SHUT_RDWR);
    {
      std::lock_guard<std::mutex> g(out_mutex_);
      for (auto& [dest, fd] : out_fds_) ::shutdown(fd, SHUT_RDWR);
    }
    {
      std::lock_guard<std::mutex> g(conn_mutex_);
      for (int fd : in_fds_) ::shutdown(fd, SHUT_RDWR);
    }
    try {
      // Drain caller threads still inside send()/recv() (close() may have
      // thrown before its own drains ran) — they hold references to the
      // members destroyed right after this returns.
      {
        std::unique_lock<std::mutex> g(out_mutex_);
        out_cv_.wait(g, [&] { return active_sends_ == 0; });
      }
      {
        std::unique_lock<std::mutex> lk(inbox_mutex_);
        inbox_cv_.notify_all();
        inbox_cv_.wait(lk, [&] { return active_recvs_ == 0; });
      }
      if (accept_thread_.joinable()) accept_thread_.join();
      for (auto& t : reader_threads_)
        if (t.joinable()) t.join();
    } catch (...) {
      if (accept_thread_.joinable()) accept_thread_.detach();
      for (auto& t : reader_threads_)
        if (t.joinable()) t.detach();
    }
  }

  const std::map<int, std::string>& peers() const { return peers_; }

 private:
  bool fail(const std::string& msg) {
    set_error(msg + (errno ? std::string(": ") + strerror(errno) : ""));
    return false;
  }

  // wait_budget: reader threads park while the inbox is over budget
  // (backpressure via TCP); self-sends never wait (the sender would be
  // waiting on itself).  One message is always admitted once under the
  // mark, so payloads larger than the budget still pass.
  //
  // IN-ORDER-CONSUMPTION ASSUMPTION (applies equally to the HWM knob,
  // CHAINERMN_TPU_INBOX_HWM): if a consumer blocks in recv() on a
  // (src, tag) frame that sits BEHIND >= HWM bytes of unconsumed frames
  // on the same connection, the reader thread parks on the budget and
  // that frame never arrives — recv fails by timeout.  Every collective
  // in this codebase consumes frames in send order per peer (tags are
  // issued and awaited monotonically), so the stall cannot occur there;
  // out-of-order consumers must either drain eagerly or raise the HWM
  // above their reorder window.  Same shape in PyTransport._enqueue.
  void push(int src, uint32_t tag, Buffer&& payload, bool wait_budget) {
    {
      std::unique_lock<std::mutex> lk(inbox_mutex_);
      if (wait_budget) {
        inbox_cv_.wait(lk, [&] {
          return closed_.load() || inbox_bytes_ < hwm_;
        });
        if (closed_.load()) return;  // teardown: connection is dying anyway
      }
      inbox_bytes_ += payload.len;
      peak_inbox_bytes_ = std::max(peak_inbox_bytes_, inbox_bytes_);
      inbox_[{src, tag}].push_back(std::move(payload));
    }
    inbox_cv_.notify_all();
  }

  void accept_loop() {
    for (;;) {
      int fd = ::accept(listen_fd_, nullptr, nullptr);
      if (fd < 0) {
        if (closed_.load()) return;
        if (errno == EINTR) continue;
        return;
      }
      {
        std::lock_guard<std::mutex> g(conn_mutex_);
        // A connection can be accepted concurrently with close(): close()
        // sets closed_ and shuts down the fds already in in_fds_, but this
        // fd is not registered yet, so close() would miss it and the reader
        // spawned for it could block forever.  Re-checking closed_ under
        // conn_mutex_ closes the window: either close()'s shutdown pass ran
        // first (we see closed_ and drop the fd) or we register first (the
        // pass shuts the fd down).
        if (closed_.load()) {
          ::close(fd);
          return;
        }
        in_fds_.push_back(fd);
        reader_threads_.emplace_back([this, fd] { reader_loop(fd); });
      }
    }
  }

  void reader_loop(int fd) {
    uint32_t src, tag;
    Buffer payload;
    while (read_frame(fd, &src, &tag, &payload)) {
      push(static_cast<int>(src), tag, std::move(payload),
           /*wait_budget=*/true);
    }
    {
      // De-register before closing: otherwise close() could ::shutdown a
      // recycled descriptor number belonging to an unrelated connection.
      std::lock_guard<std::mutex> g(conn_mutex_);
      in_fds_.erase(std::remove(in_fds_.begin(), in_fds_.end(), fd),
                    in_fds_.end());
    }
    ::close(fd);
  }

  bool handshake(const std::string& chost, int cport, const std::string& my_addr) {
    if (rank_ == 0) {
      int srv = ::socket(AF_INET, SOCK_STREAM, 0);
      if (srv < 0) return fail("coordinator socket() failed");
      int one = 1;
      setsockopt(srv, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
      sockaddr_in addr{};
      addr.sin_family = AF_INET;
      addr.sin_addr.s_addr = INADDR_ANY;
      addr.sin_port = htons(cport);
      if (::bind(srv, (sockaddr*)&addr, sizeof(addr)) != 0)
        return fail("coordinator bind(" + std::to_string(cport) + ") failed");
      if (::listen(srv, size_ + 8) != 0) return fail("coordinator listen failed");
      peers_[0] = my_addr;
      std::vector<std::pair<int, int>> conns;  // (rank, fd)
      while (static_cast<int>(peers_.size()) < size_) {
        int c = ::accept(srv, nullptr, nullptr);
        if (c < 0) {
          if (errno == EINTR) continue;
          ::close(srv);
          return fail("coordinator accept failed");
        }
        uint32_t src, tag;
        Buffer payload;
        if (!read_frame(c, &src, &tag, &payload)) {
          ::close(c);
          continue;
        }
        peers_[static_cast<int>(src)] = std::string(
            reinterpret_cast<const char*>(payload.data), payload.len);
        conns.emplace_back(static_cast<int>(src), c);
      }
      std::string blob = dump_table(peers_);
      for (auto& [r, c] : conns) {
        write_frame(c, 0, 0, blob.data(), blob.size());
        ::close(c);
      }
      ::close(srv);
      return true;
    }
    int c = connect_to(chost, cport, 60.0);
    if (c < 0) return fail("connect to coordinator failed");
    if (!write_frame(c, rank_, 0, my_addr.data(), my_addr.size())) {
      ::close(c);
      return fail("handshake send failed");
    }
    uint32_t src, tag;
    Buffer raw;
    bool ok = read_frame(c, &src, &tag, &raw);
    ::close(c);
    if (!ok) return fail("handshake recv failed");
    std::string blob(reinterpret_cast<const char*>(raw.data), raw.len);
    if (!parse_table(blob, &peers_)) return fail("bad handshake table: " + blob);
    return true;
  }

  int rank_, size_;
  int listen_fd_ = -1;
  int active_recvs_ = 0;  // guarded by inbox_mutex_
  int active_sends_ = 0;  // guarded by out_mutex_
  std::atomic<bool> closed_{false};
  std::map<int, std::string> peers_;

  std::thread accept_thread_;
  std::mutex conn_mutex_;
  std::vector<int> in_fds_;
  std::vector<std::thread> reader_threads_;

  std::mutex out_mutex_;
  std::condition_variable out_cv_;
  std::map<int, int> out_fds_;
  std::map<int, std::mutex> out_locks_;

  std::mutex inbox_mutex_;
  std::condition_variable inbox_cv_;
  std::map<std::pair<int, uint32_t>, std::deque<Buffer>> inbox_;

 public:
  uint64_t hwm_ = 1ull << 30;          // see ctor
  uint64_t inbox_bytes_ = 0;           // guarded by inbox_mutex_
  uint64_t peak_inbox_bytes_ = 0;      // guarded by inbox_mutex_

  void stats(uint64_t* inbox_bytes, uint64_t* peak) {
    std::lock_guard<std::mutex> g(inbox_mutex_);
    *inbox_bytes = inbox_bytes_;
    *peak = peak_inbox_bytes_;
  }
};

}  // namespace

// C++ exceptions (std::stoi on malformed ports/ranks, bad_alloc, ...) must
// not unwind into the ctypes FFI frame — that std::terminates the whole
// Python process.  Every extern "C" body is exception-contained.
extern "C" {

void* dcn_create(int rank, int size, const char* coordinator,
                 const char* my_host) try {
  auto* t = new Transport(rank, size);
  if (!t->init(coordinator, my_host)) {
    // close() joins the already-running accept thread; deleting a Transport
    // with a joinable std::thread would std::terminate the whole process.
    std::string err = g_last_error;  // close() may overwrite it
    t->close();
    g_last_error = err;
    delete t;
    return nullptr;
  }
  return t;
} catch (const std::exception& e) {
  set_error(std::string("native transport init: ") + e.what());
  return nullptr;
} catch (...) {
  set_error("native transport init: unknown C++ exception");
  return nullptr;
}

int dcn_send(void* handle, int dest, uint32_t tag, const uint8_t* data,
             uint64_t len) try {
  return static_cast<Transport*>(handle)->send(dest, tag, data, len) ? 0 : -1;
} catch (const std::exception& e) {
  set_error(std::string("native send: ") + e.what());
  return -1;
} catch (...) {
  set_error("native send: unknown C++ exception");
  return -1;
}

// On success returns len and sets *out (caller frees with dcn_free); on
// failure returns -1.  Zero-copy: the Buffer read off the wire is released
// to the caller directly (::malloc-backed, freed by dcn_free's ::free).
int64_t dcn_recv(void* handle, int source, uint32_t tag, double timeout_s,
                 uint8_t** out) try {
  Buffer payload;
  if (!static_cast<Transport*>(handle)->recv(source, tag, timeout_s, &payload))
    return -1;
  int64_t n = static_cast<int64_t>(payload.len);
  *out = payload.release();
  return n;
} catch (const std::exception& e) {
  set_error(std::string("native recv: ") + e.what());
  return -1;
} catch (...) {
  set_error("native recv: unknown C++ exception");
  return -1;
}

void dcn_free(uint8_t* buf) { ::free(buf); }

// Peer table as the handshake JSON (for introspection/debugging).
int64_t dcn_peers(void* handle, char* out, int64_t cap) {
  std::string s = dump_table(static_cast<Transport*>(handle)->peers());
  if (static_cast<int64_t>(s.size()) + 1 > cap) return -(int64_t)s.size() - 1;
  std::memcpy(out, s.c_str(), s.size() + 1);
  return static_cast<int64_t>(s.size());
}

// Two-phase teardown for callers that may still have threads inside
// dcn_send/dcn_recv: dcn_shutdown drains and unblocks them WITHOUT freeing
// (safe to call while they are in flight — it is what makes them return),
// the caller then waits for its own in-flight count to reach zero, and
// only then dcn_destroy frees the object.  The Python binding does exactly
// this; calling dcn_destroy with callers still inside is a use-after-free.
void dcn_shutdown(void* handle) {
  auto* t = static_cast<Transport*>(handle);
  try {
    t->close();
  } catch (...) {
    set_error("native close: unknown C++ exception");
  }
}

void dcn_destroy(void* handle) { delete static_cast<Transport*>(handle); }

// One-shot close-and-free, kept for single-threaded callers.  The
// destructor re-runs the shutdown passes and drains registered callers,
// but cannot protect a caller that has not yet entered the counter.
void dcn_close(void* handle) {
  dcn_shutdown(handle);
  dcn_destroy(handle);
}

// Inbox buffering stats: current bytes queued and the high-water peak.
// Lets callers (tests, benchmarks) assert the backpressure bound
// peak <= HWM + largest message without instrumenting the process.
void dcn_stats(void* handle, uint64_t* inbox_bytes, uint64_t* peak) {
  static_cast<Transport*>(handle)->stats(inbox_bytes, peak);
}

const char* dcn_last_error() { return g_last_error.c_str(); }

}  // extern "C"
