"""Host-level object transport — the control plane.

Reference behavior being rebuilt (paths unverified, see SURVEY.md provenance):
the MPI side of 〔chainermn/communicators/mpi_communicator_base.py〕 — pickled
object ``send/recv/bcast/gather/scatter/allreduce_obj`` between ranks, plus the
bootstrap handshake of 〔_communication_utility.py〕.

On TPU the data plane (gradients, activations) is XLA collectives over ICI and
never touches this module.  The control plane carries *small Python objects*
between controller processes over DCN: dataset shards, metric dicts, seeds,
barrier tokens.  The reference used MPI for this; we use a socket transport
(C++ framing core in ``chainermn_tpu/runtime/dcn_transport.cpp`` with a
pure-Python fallback — see ``transport.py``).

Single-controller runs (the common TPU case: one process driving the whole
slice) get :class:`SingleProcessControlPlane`, where every op is local.
"""

from __future__ import annotations

import abc
import os
import pickle
from typing import Any, Callable, List, Optional

def _np():
    import numpy
    return numpy


def _pair_max(a, b):
    if hasattr(a, "shape") or hasattr(b, "shape"):
        return _np().maximum(a, b)
    return max(a, b)


def _pair_min(a, b):
    if hasattr(a, "shape") or hasattr(b, "shape"):
        return _np().minimum(a, b)
    return min(a, b)


# Pairwise reducers applied structurally (dicts / lists / scalars / ndarrays).
# The reference's allreduce_obj handled arbitrary reducibles over MPI ops
# 〔communicator_base.py〕; names map to the MPI op set, and any binary
# callable is accepted for custom reductions (applied at the object level —
# the caller owns the structure in that case).
_PAIR_OPS = {
    "sum": lambda a, b: a + b,
    "prod": lambda a, b: a * b,
    "max": _pair_max,
    "min": _pair_min,
}


def _structural(op):
    def apply(a, b):
        if isinstance(a, dict):
            return {k: apply(a[k], b[k]) for k in a}
        if isinstance(a, (list, tuple)):
            return type(a)(apply(x, y) for x, y in zip(a, b))
        return op(a, b)
    return apply


class TagBand:
    """One reserved slice of the control-plane tag space.

    ``base`` is the first tag in the band and ``width`` the number of
    consecutive tags the owner may consume starting there.  Width matters
    because the tree collectives are *arithmetic* consumers: a call to
    :meth:`ControlPlane.allgather_obj` or :meth:`ControlPlane.allreduce_obj`
    at ``tag`` uses both ``tag`` (the fold/gather leg) and ``tag + 1``
    (the broadcast leg), so every band they ride needs width >= 2.
    """

    __slots__ = ("name", "base", "width", "owner", "doc")

    def __init__(self, name: str, base: int, width: int, owner: str,
                 doc: str = ""):
        self.name = name
        self.base = base
        self.width = width
        self.owner = owner
        self.doc = doc

    @property
    def stop(self) -> int:
        """One past the last tag in the band."""
        return self.base + self.width

    def __contains__(self, tag: int) -> bool:
        return self.base <= tag < self.stop

    def as_dict(self) -> dict:
        return {"name": self.name, "base": self.base, "width": self.width,
                "owner": self.owner, "doc": self.doc}

    def __repr__(self):
        return (f"TagBand({self.name!r}, base={self.base}, "
                f"width={self.width}, owner={self.owner!r})")


#: Central registry of every reserved control-plane tag band.  Subsystems
#: that need a private tag namespace MUST claim a band here instead of
#: picking a magic number — ``cmn_lint --protocol`` (tag-band-collision)
#: cross-checks every static call site against this table.
RESERVED_TAG_BANDS = {band.name: band for band in (
    TagBand("default", 0, 2, "runtime",
            "The tag=0 object plane every collective defaults to; "
            "allgather/allreduce consume tags 0 and 1."),
    TagBand("telemetry", 770, 2, "observability",
            "Streaming fleet-telemetry gathers "
            "(ControlPlane.gather_telemetry)."),
    TagBand("barrier", 900, 2, "runtime",
            "ControlPlane.barrier rides an allgather at 900, "
            "so it consumes 900 and 901."),
    TagBand("p2p_grad", 1 << 20, 1 << 20, "functions",
            "Reverse-transfer (cotangent) namespace for cross-process "
            "p2p: user tag t maps to (1<<20) + t."),
    TagBand("p2p_meta", 1 << 21, 1 << 20, "functions",
            "Trace-time shape/treedef handshake namespace for "
            "cross-process p2p: user tag t maps to (1<<21) + t."),
    TagBand("flight", (1 << 28) + 7, 1, "observability",
            "Watchdog flight-dump solicitation over the raw transport."),
)}


def reserved_tag(name: str) -> int:
    """Base tag of the named reserved band (KeyError on unknown names)."""
    return RESERVED_TAG_BANDS[name].base


def band_of(tag: int):
    """The :class:`TagBand` covering ``tag``, or None if unreserved."""
    for band in RESERVED_TAG_BANDS.values():
        if tag in band:
            return band
    return None


#: Reserved tag band for the streaming fleet-telemetry aggregator
#: (observability/streaming.py).  Kept far from the default tag=0 object
#: plane and the barrier band (900) so per-step telemetry gathers can
#: never cross wires with user sends in flight on the same edge.
TELEMETRY_TAG = reserved_tag("telemetry")

#: Default barrier tag — barrier() is an allgather at this tag, so it
#: consumes BARRIER_TAG and BARRIER_TAG + 1 (see the "barrier" band).
BARRIER_TAG = reserved_tag("barrier")


def _resolve_op(op):
    if callable(op):
        return op  # custom binary reducible — object-level
    try:
        return _structural(_PAIR_OPS[op])
    except KeyError:
        raise ValueError(f"unknown op {op!r} "
                         f"(expected one of {sorted(_PAIR_OPS)} or a callable)")


class ControlPlane(abc.ABC):
    """Abstract host-level object transport (the reference's MPI role)."""

    rank: int
    size: int

    @abc.abstractmethod
    def send_obj(self, obj: Any, dest: int, tag: int = 0) -> None: ...

    @abc.abstractmethod
    def recv_obj(self, source: int, tag: int = 0) -> Any: ...

    def bcast_obj(self, obj: Any, root: int = 0, tag: int = 0) -> Any:
        """Binomial-tree broadcast: O(log n) DCN hops on the critical path
        (the reference got this for free from MPI's tree collectives
        〔mpi_communicator_base.py〕; a rank-0-serial loop would be O(n))."""
        if self.size == 1:
            return obj
        vrank = (self.rank - root) % self.size
        mask = 1
        while mask < self.size:
            if vrank & mask:
                src = ((vrank ^ mask) + root) % self.size
                obj = self.recv_obj(src, tag=tag)
                break
            mask <<= 1
        # children: vrank + m for each power of two m below our receive bit
        mask >>= 1
        while mask > 0:
            if vrank + mask < self.size:
                dst = ((vrank + mask) + root) % self.size
                self.send_obj(obj, dst, tag=tag)
            mask >>= 1
        return obj

    def _tree_fold(self, obj: Any, root: int, tag: int,
                   fold: Optional[Callable]) -> Optional[dict]:
        """Binomial-tree combine toward ``root``.

        With ``fold=None`` accumulates a {vrank: obj} dict (gather); with a
        binary ``fold`` combines payloads pairwise at each hop (reduce) so
        every edge carries one object, not a subtree list.
        Returns the combined payload on root, None elsewhere.
        """
        vrank = (self.rank - root) % self.size
        acc = obj if fold is not None else {vrank: obj}
        mask = 1
        while mask < self.size:
            if vrank & mask:
                dst = ((vrank ^ mask) + root) % self.size
                self.send_obj(acc, dst, tag=tag)
                return None
            src_v = vrank + mask
            if src_v < self.size:
                got = self.recv_obj((src_v + root) % self.size, tag=tag)
                acc = fold(acc, got) if fold is not None else {**acc, **got}
            mask <<= 1
        return acc

    def gather_obj(self, obj: Any, root: int = 0, tag: int = 0) -> Optional[List[Any]]:
        if self.size == 1:
            return [obj]
        acc = self._tree_fold(obj, root, tag, fold=None)
        if acc is None:
            return None
        return [acc[(r - root) % self.size] for r in range(self.size)]

    def allgather_obj(self, obj: Any, tag: int = 0) -> List[Any]:
        # Arithmetic tag consumer: the gather leg runs at ``tag`` and the
        # broadcast leg at ``tag + 1`` — callers must own BOTH tags (see
        # RESERVED_TAG_BANDS; every band an allgather rides needs width 2).
        gathered = self.gather_obj(obj, root=0, tag=tag)
        return self.bcast_obj(gathered, root=0, tag=tag + 1)

    def scatter_obj(self, objs: Optional[List[Any]], root: int = 0, tag: int = 0) -> Any:
        """Binomial-tree scatter: root hands each subtree its slice of the
        list, so the root sends O(log n) messages instead of n-1 (total
        payload-hops grow by the tree depth — the standard MPI small-message
        trade of bytes for latency)."""
        if self.size == 1:
            return objs[0]
        vrank = (self.rank - root) % self.size
        if self.rank == root:
            assert objs is not None and len(objs) == self.size
            sub = {i: objs[(i + root) % self.size] for i in range(self.size)}
            mask = 1
            while mask < self.size:
                mask <<= 1
            mask >>= 1
        else:
            sub = None
            mask = 1
            while mask < self.size:
                if vrank & mask:
                    sub = self.recv_obj(((vrank ^ mask) + root) % self.size,
                                        tag=tag)
                    break
                mask <<= 1
            mask >>= 1
        # forward each child its half of our subtree {vrank: obj} table
        # (invariant: sub holds exactly [vrank, vrank + 2*mask) ∩ [0, size))
        while mask > 0:
            child = vrank + mask
            if child < self.size:
                child_share = {i: o for i, o in sub.items()
                               if child <= i < child + mask}
                self.send_obj(child_share, ((child + root) % self.size),
                              tag=tag)
                sub = {i: o for i, o in sub.items() if i not in child_share}
            mask >>= 1
        return sub[vrank]

    def allreduce_obj(self, obj: Any, op="sum", tag: int = 0) -> Any:
        """Reference analogue: ``allreduce_obj`` on the communicator base —
        reduce pickled objects (numbers / dicts / nested ndarrays) across
        hosts.  ``op`` is "sum"/"prod"/"max"/"min" (applied structurally
        through dicts/lists, ndarray-aware) or any binary callable for
        custom reducibles.  Tree-reduce up + tree-bcast down: each DCN edge
        carries ONE combined object and the critical path is O(log n).

        Note: tree order ≠ serial left-fold order, so float sums can differ
        in the last ulp across world sizes (deterministic for a fixed
        size/topology) — same caveat as MPI's tree allreduce.
        """
        # Arithmetic tag consumer like allgather_obj: fold at ``tag``,
        # broadcast at ``tag + 1``.
        fold = _resolve_op(op)
        acc = self._tree_fold(obj, 0, tag, fold=fold)
        return self.bcast_obj(acc, root=0, tag=tag + 1)

    def barrier(self, tag: int = BARRIER_TAG) -> None:
        self.allgather_obj(None, tag=tag)

    def gather_telemetry(self, summary: Any, root: int = 0) -> Optional[List[Any]]:
        """Ship one compact per-step telemetry summary to ``root`` on the
        reserved :data:`TELEMETRY_TAG` band.  Collective: every rank must
        call it on the same step (the aggregator's emit trigger guarantees
        this).  Returns the rank-ordered list on root, None elsewhere."""
        return self.gather_obj(summary, root=root, tag=TELEMETRY_TAG)


class SingleProcessControlPlane(ControlPlane):
    """Degenerate world: one controller process (the usual single-host case)."""

    def __init__(self):
        self.rank = 0
        self.size = 1
        self._loopback: dict = {}

    def send_obj(self, obj, dest, tag=0):
        if dest != 0:
            raise ValueError(f"invalid dest {dest} in a single-process world")
        # Loopback send-to-self: buffer it (used by tests / rank-agnostic code)
        self._loopback.setdefault(tag, []).append(pickle.dumps(obj))

    def recv_obj(self, source, tag=0):
        if source != 0 or not self._loopback.get(tag):
            raise ValueError("nothing to receive in a single-process world")
        return pickle.loads(self._loopback[tag].pop(0))


class SocketControlPlane(ControlPlane):
    """Multi-process control plane over the DCN socket transport.

    Bootstrap mirrors the reference's ``init_ranks`` handshake
    〔_communication_utility.py〕: every process registers its listen address
    with the coordinator (rank 0), which broadcasts the full peer table —
    the "hostname allgather" of the MPI world, done over DCN.
    """

    def __init__(self, rank: int, size: int, coordinator: str, transport=None):
        from chainermn_tpu.runtime import transport as transport_mod

        self.rank = rank
        self.size = size
        self._tp = transport or transport_mod.create_transport(rank, size, coordinator)
        # Observability seam, bound once at construction (None when off, so
        # the DCN path adds no per-message work): pickled wire bytes and
        # message counts by direction — the heartbeat/straggler traffic and
        # object-plane payloads of a multi-controller run.
        self._obs_msgs = self._obs_bytes = None
        from chainermn_tpu.observability import enabled, get_registry
        if enabled():
            reg = get_registry()
            self._obs_msgs = reg.counter(
                "control_plane_messages", "DCN control-plane messages")
            self._obs_bytes = reg.counter(
                "control_plane_bytes", "pickled DCN control-plane bytes")

    def send_obj(self, obj, dest, tag=0):
        payload = pickle.dumps(obj, protocol=pickle.HIGHEST_PROTOCOL)
        if self._obs_msgs is not None:
            self._obs_msgs.inc(direction="send")
            self._obs_bytes.inc(len(payload), direction="send")
        self._tp.send(dest, tag, payload)

    def recv_obj(self, source, tag=0):
        payload = self._tp.recv(source, tag)
        if self._obs_msgs is not None:
            self._obs_msgs.inc(direction="recv")
            self._obs_bytes.inc(len(payload), direction="recv")
        return pickle.loads(payload)

    def shutdown(self):
        self._tp.close()


_DEFAULT_PLANE: Optional[ControlPlane] = None


def get_control_plane() -> ControlPlane:
    """Return the process-wide default control plane (memoized — the socket
    bootstrap must run exactly once per process, like ``MPI_Init``).

    Env contract (the no-MPI-launcher bootstrap, BASELINE.json:north_star):
      CHAINERMN_TPU_COORDINATOR=host:port, CHAINERMN_TPU_NUM_PROCESSES,
      CHAINERMN_TPU_PROCESS_ID — or fall back to jax.process_* discovery,
      or a single-process world.
    """
    global _DEFAULT_PLANE
    if _DEFAULT_PLANE is None:
        _DEFAULT_PLANE = _create_control_plane()
    return _DEFAULT_PLANE


def _create_control_plane() -> ControlPlane:
    coord = os.environ.get("CHAINERMN_TPU_COORDINATOR")
    if coord:
        rank = int(os.environ["CHAINERMN_TPU_PROCESS_ID"])
        size = int(os.environ["CHAINERMN_TPU_NUM_PROCESSES"])
        return SocketControlPlane(rank, size, coord)
    import jax

    if jax.process_count() > 1:
        # jax.distributed already bootstrapped; piggyback a socket world on the
        # same hosts using the coordinator address convention.
        coord = os.environ.get("JAX_COORDINATOR_ADDRESS")
        if coord:
            host, port = coord.rsplit(":", 1)
            return SocketControlPlane(
                jax.process_index(), jax.process_count(), f"{host}:{int(port) + 1}")
    return SingleProcessControlPlane()
