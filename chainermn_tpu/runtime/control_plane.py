"""Host-level object transport — the control plane.

Reference behavior being rebuilt (paths unverified, see SURVEY.md provenance):
the MPI side of 〔chainermn/communicators/mpi_communicator_base.py〕 — pickled
object ``send/recv/bcast/gather/scatter/allreduce_obj`` between ranks, plus the
bootstrap handshake of 〔_communication_utility.py〕.

On TPU the data plane (gradients, activations) is XLA collectives over ICI and
never touches this module.  The control plane carries *small Python objects*
between controller processes over DCN: dataset shards, metric dicts, seeds,
barrier tokens.  The reference used MPI for this; we use a socket transport
(C++ framing core in ``chainermn_tpu/runtime/dcn_transport.cpp`` with a
pure-Python fallback — see ``transport.py``).

Single-controller runs (the common TPU case: one process driving the whole
slice) get :class:`SingleProcessControlPlane`, where every op is local.
"""

from __future__ import annotations

import abc
import os
import pickle
from typing import Any, Callable, List, Optional

_REDUCE_OPS = {
    "sum": lambda xs: _tree_reduce(xs, lambda a, b: a + b),
    "max": lambda xs: _tree_reduce(xs, max),
    "min": lambda xs: _tree_reduce(xs, min),
}


def _tree_reduce(xs, op):
    out = xs[0]
    for x in xs[1:]:
        if isinstance(out, dict):
            out = {k: op(out[k], x[k]) for k in out}
        elif isinstance(out, (list, tuple)):
            out = type(out)(op(a, b) for a, b in zip(out, x))
        else:
            out = op(out, x)
    return out


class ControlPlane(abc.ABC):
    """Abstract host-level object transport (the reference's MPI role)."""

    rank: int
    size: int

    @abc.abstractmethod
    def send_obj(self, obj: Any, dest: int, tag: int = 0) -> None: ...

    @abc.abstractmethod
    def recv_obj(self, source: int, tag: int = 0) -> Any: ...

    def bcast_obj(self, obj: Any, root: int = 0, tag: int = 0) -> Any:
        if self.size == 1:
            return obj
        if self.rank == root:
            for r in range(self.size):
                if r != root:
                    self.send_obj(obj, r, tag=tag)
            return obj
        return self.recv_obj(root, tag=tag)

    def gather_obj(self, obj: Any, root: int = 0, tag: int = 0) -> Optional[List[Any]]:
        if self.size == 1:
            return [obj]
        if self.rank == root:
            out = []
            for r in range(self.size):
                out.append(obj if r == root else self.recv_obj(r, tag=tag))
            return out
        self.send_obj(obj, root, tag=tag)
        return None

    def allgather_obj(self, obj: Any, tag: int = 0) -> List[Any]:
        gathered = self.gather_obj(obj, root=0, tag=tag)
        return self.bcast_obj(gathered, root=0, tag=tag + 1)

    def scatter_obj(self, objs: Optional[List[Any]], root: int = 0, tag: int = 0) -> Any:
        if self.size == 1:
            return objs[0]
        if self.rank == root:
            assert objs is not None and len(objs) == self.size
            for r in range(self.size):
                if r != root:
                    self.send_obj(objs[r], r, tag=tag)
            return objs[root]
        return self.recv_obj(root, tag=tag)

    def allreduce_obj(self, obj: Any, op: str = "sum", tag: int = 0) -> Any:
        """Reference analogue: ``allreduce_obj`` on the communicator base —
        reduce pickled objects (numbers / dicts / nested) across hosts."""
        xs = self.allgather_obj(obj, tag=tag)
        return _REDUCE_OPS[op](xs)

    def barrier(self, tag: int = 900) -> None:
        self.allgather_obj(None, tag=tag)


class SingleProcessControlPlane(ControlPlane):
    """Degenerate world: one controller process (the usual single-host case)."""

    def __init__(self):
        self.rank = 0
        self.size = 1
        self._loopback: dict = {}

    def send_obj(self, obj, dest, tag=0):
        if dest != 0:
            raise ValueError(f"invalid dest {dest} in a single-process world")
        # Loopback send-to-self: buffer it (used by tests / rank-agnostic code)
        self._loopback.setdefault(tag, []).append(pickle.dumps(obj))

    def recv_obj(self, source, tag=0):
        if source != 0 or not self._loopback.get(tag):
            raise ValueError("nothing to receive in a single-process world")
        return pickle.loads(self._loopback[tag].pop(0))


class SocketControlPlane(ControlPlane):
    """Multi-process control plane over the DCN socket transport.

    Bootstrap mirrors the reference's ``init_ranks`` handshake
    〔_communication_utility.py〕: every process registers its listen address
    with the coordinator (rank 0), which broadcasts the full peer table —
    the "hostname allgather" of the MPI world, done over DCN.
    """

    def __init__(self, rank: int, size: int, coordinator: str, transport=None):
        from chainermn_tpu.runtime import transport as transport_mod

        self.rank = rank
        self.size = size
        self._tp = transport or transport_mod.create_transport(rank, size, coordinator)

    def send_obj(self, obj, dest, tag=0):
        self._tp.send(dest, tag, pickle.dumps(obj, protocol=pickle.HIGHEST_PROTOCOL))

    def recv_obj(self, source, tag=0):
        return pickle.loads(self._tp.recv(source, tag))

    def shutdown(self):
        self._tp.close()


_DEFAULT_PLANE: Optional[ControlPlane] = None


def get_control_plane() -> ControlPlane:
    """Return the process-wide default control plane (memoized — the socket
    bootstrap must run exactly once per process, like ``MPI_Init``).

    Env contract (the no-MPI-launcher bootstrap, BASELINE.json:north_star):
      CHAINERMN_TPU_COORDINATOR=host:port, CHAINERMN_TPU_NUM_PROCESSES,
      CHAINERMN_TPU_PROCESS_ID — or fall back to jax.process_* discovery,
      or a single-process world.
    """
    global _DEFAULT_PLANE
    if _DEFAULT_PLANE is None:
        _DEFAULT_PLANE = _create_control_plane()
    return _DEFAULT_PLANE


def _create_control_plane() -> ControlPlane:
    coord = os.environ.get("CHAINERMN_TPU_COORDINATOR")
    if coord:
        rank = int(os.environ["CHAINERMN_TPU_PROCESS_ID"])
        size = int(os.environ["CHAINERMN_TPU_NUM_PROCESSES"])
        return SocketControlPlane(rank, size, coord)
    import jax

    if jax.process_count() > 1:
        # jax.distributed already bootstrapped; piggyback a socket world on the
        # same hosts using the coordinator address convention.
        coord = os.environ.get("JAX_COORDINATOR_ADDRESS")
        if coord:
            host, port = coord.rsplit(":", 1)
            return SocketControlPlane(
                jax.process_index(), jax.process_count(), f"{host}:{int(port) + 1}")
    return SingleProcessControlPlane()
