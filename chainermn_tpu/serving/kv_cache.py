"""Paged KV cache — fixed-size pages, per-sequence page tables.

The physical cache for one layer is ``[num_pages + 1, page_size, Hkv,
Dh]``: ``num_pages`` allocatable pages plus one TRASH page (the last
physical index) that absorbs the writes of padded/inactive batch rows so
the fused prefill+decode step can run a fixed ``[B, S]`` shape every
step without conditionals.

**Layout invariant** (everything below leans on it): a sequence's page
table row maps logical page ``j`` to the physical page holding its
global token positions ``j*page_size .. (j+1)*page_size - 1``, filled
left to right with no holes.  Gathering a row's pages back-to-back
therefore reconstructs the sequence contiguously — gathered index ``i``
IS global position ``i`` — so causal attention over the gathered cache
needs no extra validity mask: positions beyond a sequence's current
length are strictly greater than its query positions and the
global-position causal mask of
:func:`~chainermn_tpu.ops.flash_attention.flash_attention` (per-sequence
``q_offset``) drops them.  Unwritten tails of partial pages and
never-allocated table entries sit in that masked region by construction.

Page accounting (alloc on admission, free on retirement/eviction) is
host-side and deterministic — :class:`PageAllocator` always hands out
the lowest-numbered free pages — so every controller of a multi-process
serving world reaches the identical physical layout from the identical
admission plan (the lockstep contract of
:class:`~chainermn_tpu.serving.engine.InferenceEngine`).
"""

from __future__ import annotations

import bisect
from typing import List, NamedTuple, Optional, Sequence

import jax.numpy as jnp

from chainermn_tpu.ops.flash_attention import flash_attention


class KvCache(NamedTuple):
    """Per-layer stacked physical pages: ``k``/``v`` are
    ``[n_layers, num_pages + 1, page_size, Hkv, Dh]`` (last physical
    page = trash)."""

    k: jnp.ndarray
    v: jnp.ndarray

    @property
    def num_pages(self) -> int:
        """Allocatable pages (the trash page is not counted)."""
        return int(self.k.shape[1]) - 1

    @property
    def page_size(self) -> int:
        return int(self.k.shape[2])


def init_kv_cache(n_layers: int, num_pages: int, page_size: int,
                  n_kv_heads: int, head_dim: int,
                  dtype=jnp.float32) -> KvCache:
    """Zero-initialized cache with ``num_pages`` allocatable pages plus
    the trash page."""
    shape = (n_layers, num_pages + 1, page_size, n_kv_heads, head_dim)
    return KvCache(k=jnp.zeros(shape, dtype), v=jnp.zeros(shape, dtype))


def write_kv(cache_layer, page_table, pos0, n_new, new):
    """Scatter one step's K or V into a layer's pages (functional).

    ``cache_layer`` [P+1, page, H, D]; ``page_table`` [B, max_pages]
    int32; ``pos0`` [B] = each sequence's length BEFORE this step (its
    first new token's global position); ``n_new`` [B] = valid new tokens
    this step (0 for idle slots); ``new`` [B, S, H, D].  Row ``b``'s
    token ``t`` lands at global position ``pos0[b] + t`` — its page and
    in-page offset follow from the layout invariant; padded tokens
    (``t >= n_new[b]``) land in the trash page.
    """
    n_phys, page_size, h, d = cache_layer.shape
    trash = n_phys - 1
    b, s = new.shape[:2]
    t = jnp.arange(s)[None, :]
    pos = pos0[:, None] + t                                  # [B, S]
    logical = jnp.clip(pos // page_size, 0, page_table.shape[1] - 1)
    phys = jnp.take_along_axis(page_table, logical, axis=1)  # [B, S]
    valid = t < n_new[:, None]
    phys = jnp.where(valid, phys, trash)
    flat_idx = phys * page_size + pos % page_size
    flat = cache_layer.reshape(-1, h, d)
    flat = flat.at[flat_idx.reshape(-1)].set(
        new.astype(cache_layer.dtype).reshape(-1, h, d))
    return flat.reshape(cache_layer.shape)


def gather_kv(cache_layer, page_table):
    """Gather every sequence's pages back into contiguous
    ``[B, max_pages * page_size, H, D]`` — index ``i`` is global
    position ``i`` (layout invariant)."""
    n_phys, page_size, h, d = cache_layer.shape
    b, m = page_table.shape
    idx = (page_table[:, :, None] * page_size +
           jnp.arange(page_size)[None, None, :]).reshape(b, m * page_size)
    return cache_layer.reshape(-1, h, d)[idx]


def paged_attention(q, cache_k_layer, cache_v_layer, page_table, pos0,
                    sm_scale: Optional[float] = None):
    """Cache-offset-aware causal attention over the paged cache.

    ``q`` [B, S, H, D] are this step's queries at global positions
    ``pos0[b] + t`` (write the step's K/V first so queries see
    themselves).  Layered directly on the fused kernel: the gathered
    cache is position-aligned, so the per-sequence ``q_offset`` vector
    is the whole masking story — garbage beyond each sequence's length
    is causal-masked, real history is visible.  GQA passes through
    (``Hkv`` divides ``H``).
    """
    kc = gather_kv(cache_k_layer, page_table)
    vc = gather_kv(cache_v_layer, page_table)
    return flash_attention(q, kc, vc, causal=True, sm_scale=sm_scale,
                           q_offset=pos0)


class PageAllocator:
    """Deterministic host-side free-page list.

    Always allocates the lowest-numbered free pages, so identical
    alloc/free call sequences on different controllers produce identical
    physical layouts (the lockstep-admission contract).
    """

    def __init__(self, num_pages: int):
        if num_pages < 1:
            raise ValueError(f"num_pages must be >= 1, got {num_pages}")
        self.num_pages = num_pages
        self._free: List[int] = list(range(num_pages))  # sorted ascending

    @property
    def num_free(self) -> int:
        return len(self._free)

    def alloc(self, n: int) -> Optional[List[int]]:
        """Take the ``n`` lowest free pages, or None (nothing taken) if
        fewer than ``n`` are free."""
        if n < 0:
            raise ValueError(f"alloc({n})")
        if n > len(self._free):
            return None
        taken, self._free = self._free[:n], self._free[n:]
        return taken

    def free(self, pages: Sequence[int]) -> None:
        for p in pages:
            if not 0 <= p < self.num_pages:
                raise ValueError(f"freeing out-of-range page {p}")
            i = bisect.bisect_left(self._free, p)
            if i < len(self._free) and self._free[i] == p:
                raise ValueError(f"double free of page {p}")
            self._free.insert(i, p)


__all__ = ["KvCache", "PageAllocator", "gather_kv", "init_kv_cache",
           "paged_attention", "write_kv"]
