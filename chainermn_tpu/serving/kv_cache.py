"""Paged KV cache — fixed-size pages, per-sequence page tables.

The physical cache for one layer is ``[num_pages + 1, page_size, Hkv,
Dh]``: ``num_pages`` allocatable pages plus one TRASH page (the last
physical index) that absorbs the writes of padded/inactive batch rows so
the fused prefill+decode step can run a fixed ``[B, S]`` shape every
step without conditionals.

**Layout invariant** (everything below leans on it): a sequence's page
table row maps logical page ``j`` to the physical page holding its
global token positions ``j*page_size .. (j+1)*page_size - 1``, filled
left to right with no holes.  Gathering a row's pages back-to-back
therefore reconstructs the sequence contiguously — gathered index ``i``
IS global position ``i`` — so causal attention over the gathered cache
needs no extra validity mask: positions beyond a sequence's current
length are strictly greater than its query positions and the
global-position causal mask of
:func:`~chainermn_tpu.ops.flash_attention.flash_attention` (per-sequence
``q_offset``) drops them.  Unwritten tails of partial pages and
never-allocated table entries sit in that masked region by construction.

Page accounting (alloc on admission, free on retirement/eviction) is
host-side and deterministic — :class:`PageAllocator` always hands out
the lowest-numbered free pages — so every controller of a multi-process
serving world reaches the identical physical layout from the identical
admission plan (the lockstep contract of
:class:`~chainermn_tpu.serving.engine.InferenceEngine`).
"""

from __future__ import annotations

import bisect
from typing import Dict, List, NamedTuple, Optional, Sequence, Tuple

import jax.numpy as jnp

from chainermn_tpu.ops.flash_attention import flash_attention


class KvCache(NamedTuple):
    """Per-layer stacked physical pages: ``k``/``v`` are
    ``[n_layers, num_pages + 1, page_size, Hkv, Dh]`` (last physical
    page = trash)."""

    k: jnp.ndarray
    v: jnp.ndarray

    @property
    def num_pages(self) -> int:
        """Allocatable pages (the trash page is not counted)."""
        return int(self.k.shape[1]) - 1

    @property
    def page_size(self) -> int:
        return int(self.k.shape[2])


def init_kv_cache(n_layers: int, num_pages: int, page_size: int,
                  n_kv_heads: int, head_dim: int,
                  dtype=jnp.float32) -> KvCache:
    """Zero-initialized cache with ``num_pages`` allocatable pages plus
    the trash page."""
    shape = (n_layers, num_pages + 1, page_size, n_kv_heads, head_dim)
    return KvCache(k=jnp.zeros(shape, dtype), v=jnp.zeros(shape, dtype))


def write_kv(cache_layer, page_table, pos0, n_new, new):
    """Scatter one step's K or V into a layer's pages (functional).

    ``cache_layer`` [P+1, page, H, D]; ``page_table`` [B, max_pages]
    int32; ``pos0`` [B] = each sequence's length BEFORE this step (its
    first new token's global position); ``n_new`` [B] = valid new tokens
    this step (0 for idle slots); ``new`` [B, S, H, D].  Row ``b``'s
    token ``t`` lands at global position ``pos0[b] + t`` — its page and
    in-page offset follow from the layout invariant; padded tokens
    (``t >= n_new[b]``) land in the trash page.
    """
    n_phys, page_size, h, d = cache_layer.shape
    trash = n_phys - 1
    b, s = new.shape[:2]
    t = jnp.arange(s)[None, :]
    pos = pos0[:, None] + t                                  # [B, S]
    logical_raw = pos // page_size
    logical = jnp.clip(logical_raw, 0, page_table.shape[1] - 1)
    phys = jnp.take_along_axis(page_table, logical, axis=1)  # [B, S]
    # Positions past the table's reach (speculative over-run when a
    # sequence reserves every table entry) must not clip-alias into the
    # last real page — route them to trash alongside padded tokens.
    valid = (t < n_new[:, None]) & (logical_raw < page_table.shape[1])
    phys = jnp.where(valid, phys, trash)
    flat_idx = phys * page_size + pos % page_size
    flat = cache_layer.reshape(-1, h, d)
    flat = flat.at[flat_idx.reshape(-1)].set(
        new.astype(cache_layer.dtype).reshape(-1, h, d))
    return flat.reshape(cache_layer.shape)


def gather_kv(cache_layer, page_table):
    """Gather every sequence's pages back into contiguous
    ``[B, max_pages * page_size, H, D]`` — index ``i`` is global
    position ``i`` (layout invariant)."""
    n_phys, page_size, h, d = cache_layer.shape
    b, m = page_table.shape
    idx = (page_table[:, :, None] * page_size +
           jnp.arange(page_size)[None, None, :]).reshape(b, m * page_size)
    return cache_layer.reshape(-1, h, d)[idx]


def paged_attention(q, cache_k_layer, cache_v_layer, page_table, pos0,
                    sm_scale: Optional[float] = None):
    """Cache-offset-aware causal attention over the paged cache.

    ``q`` [B, S, H, D] are this step's queries at global positions
    ``pos0[b] + t`` (write the step's K/V first so queries see
    themselves).  Layered directly on the fused kernel: the gathered
    cache is position-aligned, so the per-sequence ``q_offset`` vector
    is the whole masking story — garbage beyond each sequence's length
    is causal-masked, real history is visible.  GQA passes through
    (``Hkv`` divides ``H``).
    """
    kc = gather_kv(cache_k_layer, page_table)
    vc = gather_kv(cache_v_layer, page_table)
    return flash_attention(q, kc, vc, causal=True, sm_scale=sm_scale,
                           q_offset=pos0)


class PageAllocator:
    """Deterministic host-side refcounted free-page list.

    Always allocates the lowest-numbered free pages, so identical
    alloc/retain/free call sequences on different controllers produce
    identical physical layouts (the lockstep-admission contract).

    Every allocated page carries a reference count: ``alloc`` hands out
    pages at refcount 1, :meth:`retain` adds a holder (copy-on-write
    prefix sharing — the prefix index and each admitted sequence count
    as separate holders), and :meth:`free` drops one holder, returning
    the page to the free list only when the last holder lets go.
    Freeing a page with no holders is still the hard "double free"
    error it always was.
    """

    def __init__(self, num_pages: int):
        if num_pages < 1:
            raise ValueError(f"num_pages must be >= 1, got {num_pages}")
        self.num_pages = num_pages
        self._free: List[int] = list(range(num_pages))  # sorted ascending
        self._refs: Dict[int, int] = {}

    @property
    def num_free(self) -> int:
        return len(self._free)

    def refcount(self, page: int) -> int:
        """Current holder count (0 for a free page)."""
        if not 0 <= page < self.num_pages:
            raise ValueError(f"out-of-range page {page}")
        return self._refs.get(page, 0)

    def alloc(self, n: int) -> Optional[List[int]]:
        """Take the ``n`` lowest free pages at refcount 1, or None
        (nothing taken) if fewer than ``n`` are free."""
        if n < 0:
            raise ValueError(f"alloc({n})")
        if n > len(self._free):
            return None
        taken, self._free = self._free[:n], self._free[n:]
        for p in taken:
            self._refs[p] = 1
        return taken

    def retain(self, pages: Sequence[int]) -> None:
        """Add one holder to each (already-allocated) page."""
        for p in pages:
            if not 0 <= p < self.num_pages:
                raise ValueError(f"retaining out-of-range page {p}")
            if p not in self._refs:
                raise ValueError(f"retaining free page {p}")
        for p in pages:
            self._refs[p] += 1

    def free(self, pages: Sequence[int]) -> None:
        """Drop one holder per page; a page returns to the free list
        when its refcount reaches zero."""
        for p in pages:
            if not 0 <= p < self.num_pages:
                raise ValueError(f"freeing out-of-range page {p}")
            r = self._refs.get(p, 0)
            if r <= 0:
                raise ValueError(f"double free of page {p}")
            if r == 1:
                del self._refs[p]
                bisect.insort(self._free, p)
            else:
                self._refs[p] = r - 1

    def would_free(self, pages: Sequence[int]) -> int:
        """How many pages a ``free(pages)`` call would return to the
        free list (pure — admission planning looks ahead with this)."""
        pending: Dict[int, int] = {}
        n = 0
        for p in pages:
            pending[p] = pending.get(p, 0) + 1
            if self._refs.get(p, 0) == pending[p]:
                n += 1
        return n


class _TrieNode:
    """One cached full page of a token prefix."""

    __slots__ = ("key", "page", "children", "parent", "last_used")

    def __init__(self, key: Tuple[int, ...], page: int,
                 parent: Optional["_TrieNode"], last_used: int):
        self.key = key
        self.page = page
        self.children: Dict[Tuple[int, ...], "_TrieNode"] = {}
        self.parent = parent
        self.last_used = last_used


class PrefixCache:
    """Token-prefix → page-list index: a hash trie over page-aligned
    prompt chunks.

    Each trie node owns one *full* physical page (the trie holds one
    allocator reference to it) keyed by that page's ``page_size`` token
    chunk; a root-to-node path spells out a page-aligned token prefix
    whose KV is already resident.  :meth:`lookup` is pure;
    admission-plan application calls :meth:`touch` (LRU clock is a
    deterministic counter, never wall time) and prefill completion calls
    :meth:`insert` — both driven by lockstep-identical state, so every
    controller's trie is identical.

    Eviction is leaf-first LRU and refcount-respecting: only pages whose
    sole holder is the trie itself (``refcount == 1``) are candidates —
    evicting a page a live sequence still maps would not free memory and
    would only destroy reuse.  :meth:`plan_evictions` is the pure
    planning half (rank 0 puts its result in the admission plan);
    :meth:`evict_pages` applies it everywhere.
    """

    def __init__(self, page_size: int, allocator: PageAllocator):
        if page_size < 1:
            raise ValueError(f"page_size must be >= 1, got {page_size}")
        self.page_size = page_size
        self.allocator = allocator
        self._root: Dict[Tuple[int, ...], _TrieNode] = {}
        self._by_page: Dict[int, _TrieNode] = {}
        self._clock = 0
        self.evictions = 0

    def __len__(self) -> int:
        """Number of cached pages."""
        return len(self._by_page)

    def _chunks(self, prompt: Sequence[int], n_pages: int):
        ps = self.page_size
        for j in range(n_pages):
            yield tuple(prompt[j * ps:(j + 1) * ps])

    def lookup(self, prompt: Sequence[int]) -> Tuple[List[int], int]:
        """Longest cached page-aligned prefix of ``prompt`` → (pages,
        hit tokens).  Pure.  At least one prompt token is always left
        for the admitted sequence to prefill (the step that completes
        prefill is what samples the first output token), so a fully
        cached prompt still hits at most ``(len-1) // page_size`` pages.
        """
        max_pages = max(0, (len(prompt) - 1) // self.page_size)
        pages: List[int] = []
        level = self._root
        for key in self._chunks(prompt, max_pages):
            node = level.get(key)
            if node is None:
                break
            pages.append(node.page)
            level = node.children
        return pages, len(pages) * self.page_size

    def touch(self, prompt: Sequence[int], n_pages: int) -> None:
        """Refresh the LRU clock along the first ``n_pages`` of
        ``prompt``'s path (called when a plan admits a cache hit)."""
        level = self._root
        for key in self._chunks(prompt, n_pages):
            node = level.get(key)
            if node is None:
                raise ValueError("prefix-cache touch of a missing path")
            self._clock += 1
            node.last_used = self._clock
            level = node.children

    def insert(self, prompt: Sequence[int], pages: Sequence[int],
               n_pages: int) -> int:
        """Index the first ``n_pages`` full pages of a prefilled
        sequence.  Chunks already present keep their existing page (the
        KV content is identical by determinism); new nodes retain the
        sequence's page.  Returns how many pages were newly adopted."""
        adopted = 0
        level = self._root
        parent: Optional[_TrieNode] = None
        for j, key in enumerate(self._chunks(prompt, n_pages)):
            node = level.get(key)
            if node is None:
                page = int(pages[j])
                self.allocator.retain([page])
                self._clock += 1
                node = _TrieNode(key, page, parent, self._clock)
                level[key] = node
                self._by_page[page] = node
                adopted += 1
            parent = node
            level = node.children
        return adopted

    def plan_evictions(self, n_needed: int,
                       exclude: Sequence[int] = ()) -> List[int]:
        """Pure leaf-first LRU plan: up to ``n_needed`` pages whose only
        holder is the trie, ordered children-before-parents so
        :meth:`evict_pages` can apply them in sequence.  ``exclude``
        protects pages (e.g. hits being admitted this very plan)."""
        if n_needed <= 0:
            return []
        import heapq
        protected = set(int(p) for p in exclude)

        def evictable(node: _TrieNode) -> bool:
            return (node.page not in protected
                    and self.allocator.refcount(node.page) == 1)

        kids = {id(n): len(n.children) for n in self._by_page.values()}
        heap = [(n.last_used, n.page, n) for n in self._by_page.values()
                if kids[id(n)] == 0 and evictable(n)]
        heapq.heapify(heap)
        planned: List[int] = []
        while heap and len(planned) < n_needed:
            _, _, node = heapq.heappop(heap)
            planned.append(node.page)
            parent = node.parent
            if parent is not None:
                kids[id(parent)] -= 1
                if kids[id(parent)] == 0 and evictable(parent):
                    heapq.heappush(
                        heap, (parent.last_used, parent.page, parent))
        return planned

    def evict_pages(self, pages: Sequence[int]) -> None:
        """Drop the trie nodes holding ``pages`` (in the given
        children-before-parents order) and release their references."""
        for p in pages:
            node = self._by_page.get(int(p))
            if node is None:
                raise ValueError(f"evicting uncached page {p}")
            if node.children:
                raise ValueError(f"evicting non-leaf page {p}")
            if node.parent is not None:
                del node.parent.children[node.key]
            else:
                del self._root[node.key]
            del self._by_page[node.page]
            self.allocator.free([node.page])
            self.evictions += 1


__all__ = ["KvCache", "PageAllocator", "PrefixCache", "gather_kv",
           "init_kv_cache", "paged_attention", "write_kv"]
