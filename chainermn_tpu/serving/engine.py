"""Continuous-batching inference engine on the mesh stack.

One fixed-shape jitted forward serves every step: each of the ``B``
slots contributes up to ``S = chunk_tokens`` tokens — a prefill chunk, a
single decode token, or nothing (idle/finished slots write to the trash
page and their logits are ignored) — so prefill and decode FUSE into one
batched forward that never recompiles.  The step loop is:

1. rank 0 builds the admission plan (retire finished, pack waiting
   requests into free pages) and broadcasts it over the DCN control
   plane (:mod:`chainermn_tpu.runtime.control_plane`) so every
   controller applies the identical plan — lockstep by construction;
2. the fused forward writes the step's K/V into the paged cache, runs
   cache-offset-aware causal flash attention per layer, and greedily
   samples each slot's last valid position;
3. host state advances: sampled tokens append to their sequences,
   finished sequences retire next step.

With ``tp_size > 1`` the forward runs inside ``shard_map`` over a
``"tp"`` mesh axis: params are Megatron-sliced
(:func:`chainermn_tpu.serving.weights.shard_params_tp`), the KV cache is
sharded over its kv heads, and the blocks psum their row-parallel
outputs (:class:`chainermn_tpu.models.transformer.Block`), so the logits
— and therefore the greedy samples — are replicated across the axis.

With ``ep_size > 1`` (MoE models only) the mesh grows an ``"ep"`` axis
— ``(tp, ep)`` devices, axes ``("tp", "ep")`` — and every block's MoE
MLP dispatches its tokens over ``"ep"``: each device hosts
``moe_experts / ep_size`` experts and the two all-to-all exchanges ride
the step's one shard_map.  Tokens and gate math are replicated over the
axis, so the logits stay replicated (ep=2 decode is bit-identical to
ep=1) while expert FLOPs split ``ep`` ways.  ``moe_plan`` routes the
exchanges through the collective planner
(:func:`chainermn_tpu.planner.compiler.execute_alltoall`) so the
dispatch is a census-visible plan stage.

Wall-clock is only ever read on the host (latency bookkeeping); nothing
traced depends on time.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Any, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from chainermn_tpu.serving import kv_cache as _kv
from chainermn_tpu.serving.scheduler import AdmissionScheduler


@dataclasses.dataclass(frozen=True)
class ServingConfig:
    """Engine knobs (cache sizing is ``docs/serving.md``'s main topic)."""

    page_size: int = 16           # tokens per KV page
    num_pages: int = 64           # allocatable pages (excl. trash)
    max_seqs: int = 4             # batch slots B
    chunk_tokens: int = 8         # S: prefill chunk / step token budget
    max_pages_per_seq: int = 8    # page-table width (max ctx / page_size)
    eos_id: Optional[int] = None
    policy: str = "continuous"    # or "static" (benchmark baseline)
    tp_size: int = 1              # tensor-parallel ways
    ep_size: int = 1              # expert-parallel ways (MoE models)
    moe_plan: Any = None          # all-to-all Plan for the MoE exchanges
    cache_dtype: Any = jnp.float32
    keep_logits: bool = False     # stash last-position logits per step
    prefix_cache: bool = False    # copy-on-write prompt-prefix sharing
    spec_k: int = 0               # draft tokens per decode step (0 = off)


@dataclasses.dataclass
class Completion:
    """A finished request (rank 0 carries the timing fields)."""

    rid: int
    prompt_len: int
    tokens: List[int]
    arrival: float = 0.0
    token_times: List[float] = dataclasses.field(default_factory=list)

    @property
    def ttft(self) -> float:
        return self.token_times[0] - self.arrival if self.token_times \
            else float("nan")


@dataclasses.dataclass
class StepResult:
    step: int
    plan: dict
    emitted: list                  # [(rid, token, n_generated)]
    completed: List[Completion]
    ran_forward: bool
    last_logits: Optional[np.ndarray] = None   # [B, vocab] (keep_logits)
    n_new: Optional[np.ndarray] = None
    spec: Optional[dict] = None    # rows/proposed/accepted/out_tokens


class InferenceEngine:
    """``submit()`` on rank 0, then ``step()`` in lockstep on every rank
    (or :meth:`run_until_idle` on a single controller)."""

    def __init__(self, model, params, config: ServingConfig, *,
                 plane=None, draft_model=None, draft_params=None):
        from chainermn_tpu.observability import flight_recorder as _flight
        from chainermn_tpu.observability.registry import (enabled,
                                                          get_registry)
        from chainermn_tpu.runtime.control_plane import get_control_plane

        cfg = config
        self.cfg = cfg
        self.plane = plane if plane is not None else get_control_plane()
        self.model = model
        n_kv = model.n_kv_heads or model.n_heads
        head_dim = model.d_model // model.n_heads
        max_ctx = cfg.max_pages_per_seq * cfg.page_size
        if max_ctx + cfg.spec_k > model.max_len:
            raise ValueError(
                f"cache reach ({cfg.max_pages_per_seq} pages x "
                f"{cfg.page_size}) plus spec_k ({cfg.spec_k}) exceeds "
                f"the model's max_len ({model.max_len})")
        if cfg.spec_k:
            if draft_model is None or draft_params is None:
                raise ValueError(
                    "spec_k > 0 requires a draft_model and draft_params")
            if cfg.chunk_tokens < cfg.spec_k + 1:
                raise ValueError(
                    f"spec_k ({cfg.spec_k}) needs chunk_tokens >= "
                    f"spec_k + 1 (the verify pass scores k+1 positions "
                    f"in the [B, S] step shape), got {cfg.chunk_tokens}")
            if draft_model.vocab != model.vocab:
                raise ValueError(
                    f"draft vocab ({draft_model.vocab}) != target vocab "
                    f"({model.vocab})")
            if max_ctx + cfg.spec_k > draft_model.max_len:
                raise ValueError(
                    f"cache reach plus spec_k exceeds the draft model's "
                    f"max_len ({draft_model.max_len})")
        self.scheduler = AdmissionScheduler(
            max_seqs=cfg.max_seqs, page_size=cfg.page_size,
            num_pages=cfg.num_pages,
            max_pages_per_seq=cfg.max_pages_per_seq,
            chunk_tokens=cfg.chunk_tokens, eos_id=cfg.eos_id,
            policy=cfg.policy, prefix_cache=cfg.prefix_cache)

        tp = cfg.tp_size
        ep = cfg.ep_size
        if ep > 1:
            if not model.moe_experts:
                raise ValueError(
                    f"ep_size ({ep}) > 1 requires an MoE model "
                    f"(moe_experts > 0)")
            if model.moe_experts % ep:
                raise ValueError(
                    f"ep_size ({ep}) must divide moe_experts "
                    f"({model.moe_experts})")
            if cfg.spec_k:
                raise ValueError(
                    "speculative decoding (spec_k > 0) is not supported "
                    "with ep_size > 1")
        # MoE models always take the mesh path — their expert dispatch
        # needs the "ep" axis bound even at ep_size=1 (a 1-wide axis)
        moe = bool(getattr(model, "moe_experts", 0))
        if tp > 1 or ep > 1 or moe:
            from chainermn_tpu.serving.weights import shard_params_tp

            if n_kv % tp:
                raise ValueError(
                    f"tp_size ({tp}) must divide n_kv_heads ({n_kv})")
            devs = jax.devices()
            if len(devs) < tp * ep:
                raise ValueError(
                    f"tp_size {tp} x ep_size {ep} exceeds the "
                    f"{len(devs)} visible devices")
            if ep > 1 or moe:
                self._mesh = jax.sharding.Mesh(
                    np.array(devs[:tp * ep]).reshape(tp, ep),
                    ("tp", "ep"))
                self._model_tp = model.clone(
                    tp_size=tp, tp_axis="tp" if tp > 1 else None,
                    moe_axis="ep", moe_plan=cfg.moe_plan)
            else:
                self._mesh = jax.sharding.Mesh(np.array(devs[:tp]),
                                               ("tp",))
                self._model_tp = model.clone(tp_size=tp, tp_axis="tp")
            # Re-place everything onto THIS engine's tp mesh: params may
            # arrive committed elsewhere (e.g. the run_spmd output of
            # broadcast_inference_params lives on the communicator's
            # full-device mesh), and jit refuses mixed device sets.
            tp_sharding = jax.sharding.NamedSharding(
                self._mesh, jax.sharding.PartitionSpec("tp"))
            sliced = shard_params_tp(
                params, tp, n_heads=model.n_heads, n_kv_heads=n_kv) \
                if tp > 1 else jax.tree.map(
                    lambda x: jnp.broadcast_to(x[None], (1,) + x.shape),
                    params)
            self._params = jax.device_put(sliced, tp_sharding)
            cache = _kv.init_kv_cache(
                model.n_layers, cfg.num_pages, cfg.page_size,
                n_kv // tp, head_dim, cfg.cache_dtype)
            stack_tp = lambda x: jax.device_put(
                jnp.broadcast_to(x[None], (tp,) + x.shape), tp_sharding)
            self._ck, self._cv = stack_tp(cache.k), stack_tp(cache.v)
        else:
            self._mesh = None
            self._model_tp = model
            self._params = params
            cache = _kv.init_kv_cache(
                model.n_layers, cfg.num_pages, cfg.page_size,
                n_kv, head_dim, cfg.cache_dtype)
            self._ck, self._cv = cache.k, cache.v
        self._fwd = self._build_forward()

        self.draft_model = draft_model
        self._fwd_spec = None
        self._last_spec = None      # (step, accept decisions) of the last
        #                             spec forward — lockstep-verified via
        #                             the next step's plan envelope
        self._spec_pickups = 0
        if cfg.spec_k:
            dn_kv = draft_model.n_kv_heads or draft_model.n_heads
            dhead = draft_model.d_model // draft_model.n_heads
            if tp > 1:
                from chainermn_tpu.serving.weights import shard_params_tp
                if dn_kv % tp:
                    raise ValueError(
                        f"tp_size ({tp}) must divide the draft model's "
                        f"n_kv_heads ({dn_kv})")
                self._draft_tp = draft_model.clone(tp_size=tp,
                                                   tp_axis="tp")
                self._dparams = jax.device_put(shard_params_tp(
                    draft_params, tp, n_heads=draft_model.n_heads,
                    n_kv_heads=dn_kv), tp_sharding)
                dcache = _kv.init_kv_cache(
                    draft_model.n_layers, cfg.num_pages, cfg.page_size,
                    dn_kv // tp, dhead, cfg.cache_dtype)
                self._dck = stack_tp(dcache.k)
                self._dcv = stack_tp(dcache.v)
            else:
                self._draft_tp = draft_model
                self._dparams = draft_params
                dcache = _kv.init_kv_cache(
                    draft_model.n_layers, cfg.num_pages, cfg.page_size,
                    dn_kv, dhead, cfg.cache_dtype)
                self._dck, self._dcv = dcache.k, dcache.v
            self._fwd_spec = self._build_forward_spec()

        self._step_idx = 0
        self._arrivals: Dict[int, float] = {}
        self._token_times: Dict[int, List[float]] = {}
        self.completions: List[Completion] = []
        reg = get_registry() if enabled() else None
        self._m = None
        if reg is not None:
            self._m = {
                "steps": reg.counter("serving_steps",
                                     "engine steps run"),
                "gen": reg.counter("serving_generated_tokens",
                                   "tokens sampled and emitted"),
                "prefill": reg.counter("serving_prefill_tokens",
                                       "prompt tokens written to cache"),
                "admitted": reg.counter("serving_admitted",
                                        "requests admitted into slots"),
                "retired": reg.counter("serving_retired",
                                       "sequences retired"),
                "active": reg.gauge("serving_active_seqs",
                                    "occupied slots"),
                "queue": reg.gauge("serving_queue_depth",
                                   "waiting requests (rank 0)"),
                "pages": reg.gauge("serving_free_pages",
                                   "free KV pages"),
                # latency SLO family: streaming histograms (mergeable
                # fixed log-grid buckets) so the fleet-telemetry
                # aggregator can fold per-rank distributions into
                # exact fleet p50/p95/p99
                "step_s": reg.streaming_histogram(
                    "serving_step_seconds",
                    "wall time per engine step"),
                "ttft": reg.streaming_histogram(
                    "serving_ttft_seconds",
                    "arrival to first emitted token"),
                "tok_s": reg.streaming_histogram(
                    "serving_token_seconds",
                    "inter-token gap per emitted token"),
                # speculative-decoding family
                "spec_rows": reg.counter(
                    "serving_spec_rows",
                    "decode rows run through the draft+verify step"),
                "spec_proposed": reg.counter(
                    "serving_spec_proposed_tokens",
                    "draft tokens proposed (k per decode row)"),
                "spec_accepted": reg.counter(
                    "serving_spec_accepted_tokens",
                    "draft tokens accepted by the target verify pass"),
                "spec_out": reg.counter(
                    "serving_spec_out_tokens",
                    "tokens landed per verify pass (accepted + 1)"),
                # prefix-cache family (cumulative scheduler counters,
                # mirrored as gauges each step)
                "prefix_hits": reg.gauge(
                    "serving_prefix_hits", "admissions with a cache hit"),
                "prefix_hit_tokens": reg.gauge(
                    "serving_prefix_hit_tokens",
                    "prompt tokens served from shared pages"),
                "prefix_prompt_tokens": reg.gauge(
                    "serving_prefix_prompt_tokens",
                    "prompt tokens across all admissions"),
                "prefix_cached_pages": reg.gauge(
                    "serving_prefix_cached_pages",
                    "pages currently indexed by the prefix trie"),
                "prefix_evictions": reg.gauge(
                    "serving_prefix_evictions",
                    "trie pages evicted under page pressure"),
            }
        self._fr = _flight.get_flight_recorder()
        # last plan-table content hash this engine saw (online-tuner
        # hot-swaps ride the per-step plan broadcast — see step())
        self._plan_table_hash = None

    # -- online-tuner plan-table pickup --------------------------------------
    def _attach_plan_table(self, plan):
        """Rank-0 side: when the online tuner hot-swapped a plan table
        since this engine last broadcast one, piggyback the table on the
        step's plan envelope so every controller picks it up on the SAME
        serving step (the scheduler bcast is already the engine's
        lockstep decision channel)."""
        from chainermn_tpu.planner.online import (active_plan_table_meta,
                                                  get_active_plan_table)
        meta = active_plan_table_meta()
        if meta is not None and meta["table_hash"] != self._plan_table_hash:
            table = get_active_plan_table()
            plan = dict(plan, plan_table={
                "table_hash": meta["table_hash"],
                "swap_step": meta["swap_step"],
                "table": table.to_dict()})
        return plan

    def _pickup_plan_table(self, plan):
        """Every rank: strip a piggybacked plan table off the envelope,
        register it as this process's active table (sidecar pin +
        ``AutoCommunicator`` swaps read it), and mark the pickup with a
        flight event."""
        if not isinstance(plan, dict) or "plan_table" not in plan:
            return plan
        from chainermn_tpu.planner.autotune import PlanTable
        from chainermn_tpu.planner.online import set_active_plan_table
        entry = plan.pop("plan_table")
        if entry["table_hash"] != self._plan_table_hash:
            self._plan_table_hash = entry["table_hash"]
            if self.plane.rank != 0:
                set_active_plan_table(
                    PlanTable.from_dict(entry["table"]),
                    step=entry.get("swap_step"))
            if self._fr is not None:
                self._fr.record("plan_table_swap_pickup",
                                step=self._step_idx,
                                table_hash=entry["table_hash"],
                                swap_step=entry.get("swap_step"))
        return plan

    # -- spec-decode accept decisions on the plan envelope --------------------
    def _attach_spec(self, plan):
        """Rank-0 side: piggyback the previous step's accept/reject
        decisions on the plan broadcast.  Every rank computed the same
        decisions locally (argmax on replicated logits), so this is the
        lockstep PROOF channel, not the data channel — followers verify
        and fail loudly on divergence instead of silently forking."""
        if self._last_spec is not None:
            plan = dict(plan, spec={"step": self._last_spec[0],
                                    "decisions": self._last_spec[1]})
        return plan

    def _pickup_spec(self, plan):
        """Every rank: check rank 0's broadcast accept decisions against
        the ones this rank applied last step."""
        if not isinstance(plan, dict) or "spec" not in plan:
            return plan
        entry = plan.pop("spec")
        mine = self._last_spec
        if (mine is None or entry["step"] != mine[0]
                or entry["decisions"] != mine[1]):
            raise RuntimeError(
                f"lockstep desync: rank 0 broadcast spec-decode accept "
                f"decisions {entry} but this rank applied "
                f"{ {'step': None if mine is None else mine[0], 'decisions': None if mine is None else mine[1]} }")
        self._spec_pickups += 1
        return plan

    # -- forward -------------------------------------------------------------
    def _build_forward(self):
        model = self._model_tp
        n_layers = model.n_layers

        def forward(params, ck, cv, page_table, tokens, pos0, n_new):
            new_k: list = [None] * n_layers
            new_v: list = [None] * n_layers

            def attend(layer, q, k, v):
                lk = _kv.write_kv(ck[layer], page_table, pos0, n_new, k)
                lv = _kv.write_kv(cv[layer], page_table, pos0, n_new, v)
                new_k[layer], new_v[layer] = lk, lv
                return _kv.paged_attention(q, lk, lv, page_table, pos0)

            logits = model.apply(params, tokens, pos_offset=pos0,
                                 attend=attend)
            last = jnp.clip(n_new - 1, 0, tokens.shape[1] - 1)
            last_logits = jnp.take_along_axis(
                logits, last[:, None, None], axis=1)[:, 0]  # [B, vocab]
            sampled = jnp.argmax(last_logits, axis=-1).astype(jnp.int32)
            return sampled, last_logits, jnp.stack(new_k), jnp.stack(new_v)

        if self._mesh is None:
            return jax.jit(forward)

        from jax.sharding import PartitionSpec as P

        from chainermn_tpu import utils as _utils

        def body(params_st, ck_st, cv_st, page_table, tokens, pos0, n_new):
            params = jax.tree.map(lambda x: x[0], params_st)
            sampled, last_logits, nk, nv = forward(
                params, ck_st[0], cv_st[0], page_table, tokens, pos0,
                n_new)
            return sampled, last_logits, nk[None], nv[None]

        return jax.jit(_utils.shard_map(
            body, mesh=self._mesh,
            in_specs=(P("tp"), P("tp"), P("tp"), P(), P(), P(), P()),
            out_specs=(P(), P(), P("tp"), P("tp")), check_vma=False))

    def _build_forward_spec(self):
        """Fused draft+verify step (one jitted program, fixed [B, S]).

        Decode rows: the draft model greedily proposes ``k`` tokens in
        ``k`` micro-steps (its KV rides the same page tables in its own
        cache arrays), then ONE target pass scores all ``k+1`` positions
        ``[t0, d1..dk]``; the longest matching prefix is accepted and
        position ``a`` contributes the correction/bonus token, so every
        verify pass lands ``a+1`` tokens.  Rollback is free by
        construction: rejected positions hold stale KV strictly above
        every live query position (causal-masked) and the next step's
        writes start at the rolled-back ``pos0``, overwriting them
        before anything can attend.  Prefill rows flow through both
        models untouched (the draft must prefill too — its cache has to
        cover the prompt before it can extend it).
        """
        tmodel = self._model_tp
        dmodel = self._draft_tp
        K = self.cfg.spec_k

        def run(model, params, ck, cv, page_table, tokens, pos0, n_new):
            nl = model.n_layers
            new_k: list = [None] * nl
            new_v: list = [None] * nl

            def attend(layer, q, k, v):
                lk = _kv.write_kv(ck[layer], page_table, pos0, n_new, k)
                lv = _kv.write_kv(cv[layer], page_table, pos0, n_new, v)
                new_k[layer], new_v[layer] = lk, lv
                return _kv.paged_attention(q, lk, lv, page_table, pos0)

            logits = model.apply(params, tokens, pos_offset=pos0,
                                 attend=attend)
            return logits, jnp.stack(new_k), jnp.stack(new_v)

        def forward_spec(params, dparams, ck, cv, dck, dcv, page_table,
                         tokens, pos0, n_new, is_decode, prev):
            b, s = tokens.shape
            dec = is_decode.astype(bool)
            # draft pass 1: prefill rows feed their chunk; decode rows
            # feed [prev, t0] at positions L-1, L -> d1.  Re-feeding the
            # second-to-last token heals the draft cache after a fully
            # accepted round (the bonus token's predecessor was never
            # drafted, so its draft KV is missing); in every other round
            # the rewrite is an identical-value no-op.
            d_tok1 = jnp.where(
                dec[:, None],
                jnp.zeros((b, s), jnp.int32)
                .at[:, 0].set(prev).at[:, 1].set(tokens[:, 0]),
                tokens)
            d_n1 = jnp.where(dec, 2, n_new)
            d_pos1 = jnp.where(dec, pos0 - 1, pos0)
            dlog, dck, dcv = run(dmodel, dparams, dck, dcv, page_table,
                                 d_tok1, d_pos1, d_n1)
            last1 = jnp.clip(d_n1 - 1, 0, s - 1)
            cur = jnp.argmax(jnp.take_along_axis(
                dlog, last1[:, None, None], axis=1)[:, 0],
                axis=-1).astype(jnp.int32)
            drafts = [cur]
            for i in range(1, K):   # micro-steps: d_i at position L+i
                step_tokens = jnp.zeros((b, s), jnp.int32
                                        ).at[:, 0].set(cur)
                dlog, dck, dcv = run(dmodel, dparams, dck, dcv,
                                     page_table, step_tokens, pos0 + i,
                                     jnp.where(dec, 1, 0))
                cur = jnp.argmax(dlog[:, 0], axis=-1).astype(jnp.int32)
                drafts.append(cur)
            d_mat = jnp.stack(drafts, axis=1)            # [B, K]
            # one target pass over [t0, d1..dk] (decode) / chunk (prefill)
            dec_tokens = jnp.concatenate(
                [tokens[:, :1], d_mat,
                 jnp.zeros((b, s - (K + 1)), jnp.int32)], axis=1)
            ver_tokens = jnp.where(dec[:, None], dec_tokens, tokens)
            t_n = jnp.where(dec, K + 1, n_new)
            tlog, ck, cv = run(tmodel, params, ck, cv, page_table,
                               ver_tokens, pos0, t_n)
            g = jnp.argmax(tlog[:, :K + 1, :], axis=-1).astype(jnp.int32)
            # greedy accept: longest leading prefix with d_i == g_{i-1}
            match = (d_mat == g[:, :K]).astype(jnp.int32)
            a = jnp.cumprod(match, axis=1).sum(axis=1)   # [B] accepted
            j_idx = jnp.arange(K + 1)[None, :]
            g_a = jnp.take_along_axis(g, a[:, None], axis=1)  # correction
            d_pad = jnp.concatenate(
                [d_mat, jnp.zeros((b, 1), jnp.int32)], axis=1)
            out_dec = jnp.where(j_idx < a[:, None], d_pad, g_a)
            # prefill rows: greedy token at the last valid position
            lastp = jnp.clip(n_new - 1, 0, s - 1)
            p_logits = jnp.take_along_axis(
                tlog, lastp[:, None, None], axis=1)[:, 0]    # [B, vocab]
            p_tok = jnp.argmax(p_logits, axis=-1).astype(jnp.int32)
            out_pre = jnp.concatenate(
                [p_tok[:, None], jnp.zeros((b, K), jnp.int32)], axis=1)
            out = jnp.where(dec[:, None], out_dec, out_pre)  # [B, K+1]
            n_out = jnp.where(dec, a + 1, 1)
            last_logits = jnp.where(dec[:, None], tlog[:, 0, :], p_logits)
            return out, n_out, last_logits, ck, cv, dck, dcv

        if self._mesh is None:
            return jax.jit(forward_spec)

        from jax.sharding import PartitionSpec as P

        from chainermn_tpu import utils as _utils

        def body(params_st, dparams_st, ck_st, cv_st, dck_st, dcv_st,
                 page_table, tokens, pos0, n_new, is_decode, prev):
            params = jax.tree.map(lambda x: x[0], params_st)
            dparams = jax.tree.map(lambda x: x[0], dparams_st)
            out, n_out, last_logits, ck, cv, dck, dcv = forward_spec(
                params, dparams, ck_st[0], cv_st[0], dck_st[0], dcv_st[0],
                page_table, tokens, pos0, n_new, is_decode, prev)
            return (out, n_out, last_logits, ck[None], cv[None],
                    dck[None], dcv[None])

        return jax.jit(_utils.shard_map(
            body, mesh=self._mesh,
            in_specs=(P("tp"), P("tp"), P("tp"), P("tp"), P("tp"),
                      P("tp"), P(), P(), P(), P(), P(), P()),
            out_specs=(P(), P(), P(), P("tp"), P("tp"), P("tp"), P("tp")),
            check_vma=False))

    # -- client side ---------------------------------------------------------
    def submit(self, prompt, max_new_tokens: int,
               arrival: Optional[float] = None) -> int:
        """Queue a request (rank 0).  ``arrival`` defaults to now."""
        arrival = time.perf_counter() if arrival is None else arrival
        rid = self.scheduler.submit(list(map(int, prompt)),
                                    max_new_tokens, arrival)
        self._arrivals[rid] = arrival
        return rid

    def idle(self) -> bool:
        return self.scheduler.idle()

    # -- the step loop -------------------------------------------------------
    def step(self) -> StepResult:
        t0 = time.perf_counter()
        sched = self.scheduler
        if self.plane.size > 1:
            plan = self._attach_spec(self._attach_plan_table(
                sched.build_plan())) if self.plane.rank == 0 else None
            btok = None
            if self._fr is not None:
                btok = self._fr.span_begin("object", "serving_plan_bcast",
                                           step=self._step_idx)
            plan = self.plane.bcast_obj(plan, root=0)
            if self._fr is not None:
                self._fr.span_end(btok)
        else:
            plan = self._attach_spec(self._attach_plan_table(
                sched.build_plan()))
        plan = self._pickup_spec(self._pickup_plan_table(plan))
        tok = None
        if self._fr is not None:
            tok = self._fr.span_begin(
                "serving", "serving_step", step=self._step_idx,
                admitted=len(plan["admit"]), retired=len(plan["retire"]))
        retired = sched.apply_plan(plan)
        completed = [self._finish(slot) for _, slot in retired]

        batch = sched.step_batch()
        n_new = batch["n_new"]
        ran = bool(n_new.sum())
        emitted: list = []
        last_logits = None
        spec_stats = None
        if ran:
            ftok = None
            if self._fr is not None:
                # the decode/prefill forward sub-span: fwd dispatch plus
                # the sampled-token sync — the device-bound slice of a
                # serving step the attribution lane separates from
                # scheduling/bcast time
                n_arr = np.asarray(n_new)
                ftok = self._fr.span_begin(
                    "serving", "serving_forward", step=self._step_idx,
                    n_new=int(n_arr.sum()),
                    decode_slots=int((n_arr == 1).sum()),
                    prefill_slots=int((n_arr > 1).sum()),
                    spec=bool(self._fwd_spec is not None))
            if self._fwd_spec is not None:
                dec = batch["decode"]
                out_d, n_out_d, logits_d, self._ck, self._cv, \
                    self._dck, self._dcv = self._fwd_spec(
                        self._params, self._dparams, self._ck, self._cv,
                        self._dck, self._dcv,
                        jnp.asarray(batch["page_table"]),
                        jnp.asarray(batch["tokens"]),
                        jnp.asarray(batch["pos0"]), jnp.asarray(n_new),
                        jnp.asarray(dec), jnp.asarray(batch["prev"]))
                out = np.asarray(out_d)       # device sync point
                n_out = np.asarray(n_out_d)
                if self.cfg.keep_logits:
                    last_logits = np.asarray(logits_d)
                if self._fr is not None:
                    self._fr.span_end(ftok)
                emitted = sched.note_sampled_spec(n_new, out, n_out)
                decisions = [
                    [int(i), int(n_out[i]),
                     [int(t) for t in out[i, :n_out[i]]]]
                    for i in range(len(n_out))
                    if dec[i] and n_new[i] > 0]
                self._last_spec = [self._step_idx, decisions]
                rows = len(decisions)
                spec_stats = {
                    "rows": rows,
                    "proposed": rows * self.cfg.spec_k,
                    "accepted": sum(d[1] - 1 for d in decisions),
                    "out_tokens": sum(d[1] for d in decisions),
                }
            else:
                sampled_d, logits_d, self._ck, self._cv = self._fwd(
                    self._params, self._ck, self._cv,
                    jnp.asarray(batch["page_table"]),
                    jnp.asarray(batch["tokens"]),
                    jnp.asarray(batch["pos0"]),
                    jnp.asarray(n_new))
                sampled = np.asarray(sampled_d)   # device sync point
                if self.cfg.keep_logits:
                    last_logits = np.asarray(logits_d)
                if self._fr is not None:
                    self._fr.span_end(ftok)
                emitted = sched.note_sampled(n_new, sampled)
            now = time.perf_counter()
            for rid, _tok, _n in emitted:
                times = self._token_times.setdefault(rid, [])
                if self._m is not None:
                    if times:
                        self._m["tok_s"].observe(now - times[-1])
                    else:
                        arrival = self._arrivals.get(rid)
                        if arrival is not None:
                            self._m["ttft"].observe(now - arrival)
                times.append(now)

        if self._m is not None:
            self._m["steps"].inc()
            self._m["gen"].inc(len(emitted))
            if spec_stats is None:
                self._m["prefill"].inc(int(n_new.sum()) - len(emitted))
            else:
                dec_arr = batch["decode"]
                self._m["prefill"].inc(
                    int(n_new[dec_arr == 0].sum()))
                self._m["spec_rows"].inc(spec_stats["rows"])
                self._m["spec_proposed"].inc(spec_stats["proposed"])
                self._m["spec_accepted"].inc(spec_stats["accepted"])
                self._m["spec_out"].inc(spec_stats["out_tokens"])
            self._m["admitted"].inc(len(plan["admit"]))
            self._m["retired"].inc(len(plan["retire"]))
            self._m["active"].set(sched.active_count)
            self._m["queue"].set(sched.queue_depth)
            self._m["pages"].set(sched.allocator.num_free)
            if sched.prefix is not None:
                ps = sched.prefix_stats()
                self._m["prefix_hits"].set(ps["hits"])
                self._m["prefix_hit_tokens"].set(ps["hit_tokens"])
                self._m["prefix_prompt_tokens"].set(ps["prompt_tokens"])
                self._m["prefix_cached_pages"].set(ps["cached_pages"])
                self._m["prefix_evictions"].set(ps["evictions"])
            self._m["step_s"].observe(time.perf_counter() - t0)
        if self._fr is not None:
            self._fr.span_end(
                tok, emitted=len(emitted), ran_forward=ran,
                spec_accepted=0 if spec_stats is None
                else spec_stats["accepted"])
        res = StepResult(step=self._step_idx, plan=plan, emitted=emitted,
                         completed=completed, ran_forward=ran,
                         last_logits=last_logits, n_new=n_new,
                         spec=spec_stats)
        self._step_idx += 1
        return res

    def _finish(self, slot) -> Completion:
        comp = Completion(
            rid=slot.rid, prompt_len=len(slot.prompt),
            tokens=list(slot.generated),
            arrival=self._arrivals.get(slot.rid, 0.0),
            token_times=self._token_times.pop(slot.rid, []))
        self.completions.append(comp)
        return comp

    def run_until_idle(self, max_steps: int = 10_000) -> List[Completion]:
        """Step until every submitted request has retired (single
        controller convenience; multi-controller worlds drive ``step()``
        in lockstep themselves)."""
        start = len(self.completions)
        for _ in range(max_steps):
            if self.idle():
                break
            self.step()
        else:
            raise RuntimeError(
                f"engine still busy after {max_steps} steps "
                f"(active={self.scheduler.active_count}, "
                f"queued={self.scheduler.queue_depth})")
        return self.completions[start:]


__all__ = ["Completion", "InferenceEngine", "ServingConfig", "StepResult"]
