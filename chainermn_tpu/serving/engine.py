"""Continuous-batching inference engine on the mesh stack.

One fixed-shape jitted forward serves every step: each of the ``B``
slots contributes up to ``S = chunk_tokens`` tokens — a prefill chunk, a
single decode token, or nothing (idle/finished slots write to the trash
page and their logits are ignored) — so prefill and decode FUSE into one
batched forward that never recompiles.  The step loop is:

1. rank 0 builds the admission plan (retire finished, pack waiting
   requests into free pages) and broadcasts it over the DCN control
   plane (:mod:`chainermn_tpu.runtime.control_plane`) so every
   controller applies the identical plan — lockstep by construction;
2. the fused forward writes the step's K/V into the paged cache, runs
   cache-offset-aware causal flash attention per layer, and greedily
   samples each slot's last valid position;
3. host state advances: sampled tokens append to their sequences,
   finished sequences retire next step.

With ``tp_size > 1`` the forward runs inside ``shard_map`` over a
``"tp"`` mesh axis: params are Megatron-sliced
(:func:`chainermn_tpu.serving.weights.shard_params_tp`), the KV cache is
sharded over its kv heads, and the blocks psum their row-parallel
outputs (:class:`chainermn_tpu.models.transformer.Block`), so the logits
— and therefore the greedy samples — are replicated across the axis.

Wall-clock is only ever read on the host (latency bookkeeping); nothing
traced depends on time.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Any, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from chainermn_tpu.serving import kv_cache as _kv
from chainermn_tpu.serving.scheduler import AdmissionScheduler


@dataclasses.dataclass(frozen=True)
class ServingConfig:
    """Engine knobs (cache sizing is ``docs/serving.md``'s main topic)."""

    page_size: int = 16           # tokens per KV page
    num_pages: int = 64           # allocatable pages (excl. trash)
    max_seqs: int = 4             # batch slots B
    chunk_tokens: int = 8         # S: prefill chunk / step token budget
    max_pages_per_seq: int = 8    # page-table width (max ctx / page_size)
    eos_id: Optional[int] = None
    policy: str = "continuous"    # or "static" (benchmark baseline)
    tp_size: int = 1              # tensor-parallel ways
    cache_dtype: Any = jnp.float32
    keep_logits: bool = False     # stash last-position logits per step


@dataclasses.dataclass
class Completion:
    """A finished request (rank 0 carries the timing fields)."""

    rid: int
    prompt_len: int
    tokens: List[int]
    arrival: float = 0.0
    token_times: List[float] = dataclasses.field(default_factory=list)

    @property
    def ttft(self) -> float:
        return self.token_times[0] - self.arrival if self.token_times \
            else float("nan")


@dataclasses.dataclass
class StepResult:
    step: int
    plan: dict
    emitted: list                  # [(rid, token, n_generated)]
    completed: List[Completion]
    ran_forward: bool
    last_logits: Optional[np.ndarray] = None   # [B, vocab] (keep_logits)
    n_new: Optional[np.ndarray] = None


class InferenceEngine:
    """``submit()`` on rank 0, then ``step()`` in lockstep on every rank
    (or :meth:`run_until_idle` on a single controller)."""

    def __init__(self, model, params, config: ServingConfig, *,
                 plane=None):
        from chainermn_tpu.observability import flight_recorder as _flight
        from chainermn_tpu.observability.registry import (enabled,
                                                          get_registry)
        from chainermn_tpu.runtime.control_plane import get_control_plane

        cfg = config
        self.cfg = cfg
        self.plane = plane if plane is not None else get_control_plane()
        self.model = model
        n_kv = model.n_kv_heads or model.n_heads
        head_dim = model.d_model // model.n_heads
        max_ctx = cfg.max_pages_per_seq * cfg.page_size
        if max_ctx > model.max_len:
            raise ValueError(
                f"cache reach ({cfg.max_pages_per_seq} pages x "
                f"{cfg.page_size}) exceeds the model's max_len "
                f"({model.max_len})")
        self.scheduler = AdmissionScheduler(
            max_seqs=cfg.max_seqs, page_size=cfg.page_size,
            num_pages=cfg.num_pages,
            max_pages_per_seq=cfg.max_pages_per_seq,
            chunk_tokens=cfg.chunk_tokens, eos_id=cfg.eos_id,
            policy=cfg.policy)

        tp = cfg.tp_size
        if tp > 1:
            from chainermn_tpu.serving.weights import shard_params_tp

            if n_kv % tp:
                raise ValueError(
                    f"tp_size ({tp}) must divide n_kv_heads ({n_kv})")
            devs = jax.devices()
            if len(devs) < tp:
                raise ValueError(
                    f"tp_size {tp} exceeds the {len(devs)} visible "
                    f"devices")
            self._mesh = jax.sharding.Mesh(np.array(devs[:tp]), ("tp",))
            self._model_tp = model.clone(tp_size=tp, tp_axis="tp")
            # Re-place everything onto THIS engine's tp mesh: params may
            # arrive committed elsewhere (e.g. the run_spmd output of
            # broadcast_inference_params lives on the communicator's
            # full-device mesh), and jit refuses mixed device sets.
            tp_sharding = jax.sharding.NamedSharding(
                self._mesh, jax.sharding.PartitionSpec("tp"))
            self._params = jax.device_put(shard_params_tp(
                params, tp, n_heads=model.n_heads, n_kv_heads=n_kv),
                tp_sharding)
            cache = _kv.init_kv_cache(
                model.n_layers, cfg.num_pages, cfg.page_size,
                n_kv // tp, head_dim, cfg.cache_dtype)
            stack_tp = lambda x: jax.device_put(
                jnp.broadcast_to(x[None], (tp,) + x.shape), tp_sharding)
            self._ck, self._cv = stack_tp(cache.k), stack_tp(cache.v)
        else:
            self._mesh = None
            self._model_tp = model
            self._params = params
            cache = _kv.init_kv_cache(
                model.n_layers, cfg.num_pages, cfg.page_size,
                n_kv, head_dim, cfg.cache_dtype)
            self._ck, self._cv = cache.k, cache.v
        self._fwd = self._build_forward()

        self._step_idx = 0
        self._arrivals: Dict[int, float] = {}
        self._token_times: Dict[int, List[float]] = {}
        self.completions: List[Completion] = []
        reg = get_registry() if enabled() else None
        self._m = None
        if reg is not None:
            self._m = {
                "steps": reg.counter("serving_steps",
                                     "engine steps run"),
                "gen": reg.counter("serving_generated_tokens",
                                   "tokens sampled and emitted"),
                "prefill": reg.counter("serving_prefill_tokens",
                                       "prompt tokens written to cache"),
                "admitted": reg.counter("serving_admitted",
                                        "requests admitted into slots"),
                "retired": reg.counter("serving_retired",
                                       "sequences retired"),
                "active": reg.gauge("serving_active_seqs",
                                    "occupied slots"),
                "queue": reg.gauge("serving_queue_depth",
                                   "waiting requests (rank 0)"),
                "pages": reg.gauge("serving_free_pages",
                                   "free KV pages"),
                "step_s": reg.histogram("serving_step_seconds",
                                        "wall time per engine step"),
            }
        self._fr = _flight.get_flight_recorder()
        # last plan-table content hash this engine saw (online-tuner
        # hot-swaps ride the per-step plan broadcast — see step())
        self._plan_table_hash = None

    # -- online-tuner plan-table pickup --------------------------------------
    def _attach_plan_table(self, plan):
        """Rank-0 side: when the online tuner hot-swapped a plan table
        since this engine last broadcast one, piggyback the table on the
        step's plan envelope so every controller picks it up on the SAME
        serving step (the scheduler bcast is already the engine's
        lockstep decision channel)."""
        from chainermn_tpu.planner.online import (active_plan_table_meta,
                                                  get_active_plan_table)
        meta = active_plan_table_meta()
        if meta is not None and meta["table_hash"] != self._plan_table_hash:
            table = get_active_plan_table()
            plan = dict(plan, plan_table={
                "table_hash": meta["table_hash"],
                "swap_step": meta["swap_step"],
                "table": table.to_dict()})
        return plan

    def _pickup_plan_table(self, plan):
        """Every rank: strip a piggybacked plan table off the envelope,
        register it as this process's active table (sidecar pin +
        ``AutoCommunicator`` swaps read it), and mark the pickup with a
        flight event."""
        if not isinstance(plan, dict) or "plan_table" not in plan:
            return plan
        from chainermn_tpu.planner.autotune import PlanTable
        from chainermn_tpu.planner.online import set_active_plan_table
        entry = plan.pop("plan_table")
        if entry["table_hash"] != self._plan_table_hash:
            self._plan_table_hash = entry["table_hash"]
            if self.plane.rank != 0:
                set_active_plan_table(
                    PlanTable.from_dict(entry["table"]),
                    step=entry.get("swap_step"))
            if self._fr is not None:
                self._fr.record("plan_table_swap_pickup",
                                step=self._step_idx,
                                table_hash=entry["table_hash"],
                                swap_step=entry.get("swap_step"))
        return plan

    # -- forward -------------------------------------------------------------
    def _build_forward(self):
        model = self._model_tp
        n_layers = model.n_layers

        def forward(params, ck, cv, page_table, tokens, pos0, n_new):
            new_k: list = [None] * n_layers
            new_v: list = [None] * n_layers

            def attend(layer, q, k, v):
                lk = _kv.write_kv(ck[layer], page_table, pos0, n_new, k)
                lv = _kv.write_kv(cv[layer], page_table, pos0, n_new, v)
                new_k[layer], new_v[layer] = lk, lv
                return _kv.paged_attention(q, lk, lv, page_table, pos0)

            logits = model.apply(params, tokens, pos_offset=pos0,
                                 attend=attend)
            last = jnp.clip(n_new - 1, 0, tokens.shape[1] - 1)
            last_logits = jnp.take_along_axis(
                logits, last[:, None, None], axis=1)[:, 0]  # [B, vocab]
            sampled = jnp.argmax(last_logits, axis=-1).astype(jnp.int32)
            return sampled, last_logits, jnp.stack(new_k), jnp.stack(new_v)

        if self._mesh is None:
            return jax.jit(forward)

        from jax.sharding import PartitionSpec as P

        from chainermn_tpu import utils as _utils

        def body(params_st, ck_st, cv_st, page_table, tokens, pos0, n_new):
            params = jax.tree.map(lambda x: x[0], params_st)
            sampled, last_logits, nk, nv = forward(
                params, ck_st[0], cv_st[0], page_table, tokens, pos0,
                n_new)
            return sampled, last_logits, nk[None], nv[None]

        return jax.jit(_utils.shard_map(
            body, mesh=self._mesh,
            in_specs=(P("tp"), P("tp"), P("tp"), P(), P(), P(), P()),
            out_specs=(P(), P(), P("tp"), P("tp")), check_vma=False))

    # -- client side ---------------------------------------------------------
    def submit(self, prompt, max_new_tokens: int,
               arrival: Optional[float] = None) -> int:
        """Queue a request (rank 0).  ``arrival`` defaults to now."""
        arrival = time.perf_counter() if arrival is None else arrival
        rid = self.scheduler.submit(list(map(int, prompt)),
                                    max_new_tokens, arrival)
        self._arrivals[rid] = arrival
        return rid

    def idle(self) -> bool:
        return self.scheduler.idle()

    # -- the step loop -------------------------------------------------------
    def step(self) -> StepResult:
        t0 = time.perf_counter()
        sched = self.scheduler
        if self.plane.size > 1:
            plan = self._attach_plan_table(sched.build_plan()) \
                if self.plane.rank == 0 else None
            btok = None
            if self._fr is not None:
                btok = self._fr.span_begin("object", "serving_plan_bcast",
                                           step=self._step_idx)
            plan = self.plane.bcast_obj(plan, root=0)
            if self._fr is not None:
                self._fr.span_end(btok)
        else:
            plan = self._attach_plan_table(sched.build_plan())
        plan = self._pickup_plan_table(plan)
        tok = None
        if self._fr is not None:
            tok = self._fr.span_begin(
                "serving", "serving_step", step=self._step_idx,
                admitted=len(plan["admit"]), retired=len(plan["retire"]))
        retired = sched.apply_plan(plan)
        completed = [self._finish(slot) for _, slot in retired]

        batch = sched.step_batch()
        n_new = batch["n_new"]
        ran = bool(n_new.sum())
        emitted: list = []
        last_logits = None
        if ran:
            ftok = None
            if self._fr is not None:
                # the decode/prefill forward sub-span: fwd dispatch plus
                # the sampled-token sync — the device-bound slice of a
                # serving step the attribution lane separates from
                # scheduling/bcast time
                n_arr = np.asarray(n_new)
                ftok = self._fr.span_begin(
                    "serving", "serving_forward", step=self._step_idx,
                    n_new=int(n_arr.sum()),
                    decode_slots=int((n_arr == 1).sum()),
                    prefill_slots=int((n_arr > 1).sum()))
            sampled_d, logits_d, self._ck, self._cv = self._fwd(
                self._params, self._ck, self._cv,
                jnp.asarray(batch["page_table"]),
                jnp.asarray(batch["tokens"]), jnp.asarray(batch["pos0"]),
                jnp.asarray(n_new))
            sampled = np.asarray(sampled_d)   # device sync point
            if self.cfg.keep_logits:
                last_logits = np.asarray(logits_d)
            if self._fr is not None:
                self._fr.span_end(ftok)
            emitted = sched.note_sampled(n_new, sampled)
            now = time.perf_counter()
            for rid, _tok, _n in emitted:
                self._token_times.setdefault(rid, []).append(now)

        if self._m is not None:
            decode = sum(1 for i in range(len(n_new))
                         if n_new[i] == 1 and emitted)
            del decode  # derived lanes live in obs_report
            self._m["steps"].inc()
            self._m["gen"].inc(len(emitted))
            self._m["prefill"].inc(int(n_new.sum()) - len(emitted))
            self._m["admitted"].inc(len(plan["admit"]))
            self._m["retired"].inc(len(plan["retire"]))
            self._m["active"].set(sched.active_count)
            self._m["queue"].set(sched.queue_depth)
            self._m["pages"].set(sched.allocator.num_free)
            self._m["step_s"].observe(time.perf_counter() - t0)
        if self._fr is not None:
            self._fr.span_end(tok, emitted=len(emitted),
                              ran_forward=ran)
        res = StepResult(step=self._step_idx, plan=plan, emitted=emitted,
                         completed=completed, ran_forward=ran,
                         last_logits=last_logits, n_new=n_new)
        self._step_idx += 1
        return res

    def _finish(self, slot) -> Completion:
        comp = Completion(
            rid=slot.rid, prompt_len=len(slot.prompt),
            tokens=list(slot.generated),
            arrival=self._arrivals.get(slot.rid, 0.0),
            token_times=self._token_times.pop(slot.rid, []))
        self.completions.append(comp)
        return comp

    def run_until_idle(self, max_steps: int = 10_000) -> List[Completion]:
        """Step until every submitted request has retired (single
        controller convenience; multi-controller worlds drive ``step()``
        in lockstep themselves)."""
        start = len(self.completions)
        for _ in range(max_steps):
            if self.idle():
                break
            self.step()
        else:
            raise RuntimeError(
                f"engine still busy after {max_steps} steps "
                f"(active={self.scheduler.active_count}, "
                f"queued={self.scheduler.queue_depth})")
        return self.completions[start:]


__all__ = ["Completion", "InferenceEngine", "ServingConfig", "StepResult"]
