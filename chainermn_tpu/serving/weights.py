"""Inference weight loading: consolidate, quantize, shard, broadcast.

The path from a training checkpoint to serving params:

1. **Consolidate** — an FSDP-sharded train state restores only on the
   training world (shard layouts are bound to the world size);
   :func:`chainermn_tpu.extensions.checkpoint.consolidate_fsdp_checkpoint`
   turns its :class:`~chainermn_tpu.parallel.fsdp.FsdpState` nodes into
   full replicated parameter pytrees (no collective —
   ``fsdp_full_params``).  :func:`load_inference_params` wraps resume +
   consolidate + dtype cast into one call.
2. **Quantize** (optional) — :func:`quantize_inference_params` stores
   every matrix-shaped leaf as per-channel int8 codes + f32 scales
   (:func:`chainermn_tpu.compression.quantize.quantize_per_channel_int8`),
   ~4x fewer bytes on the broadcast wire and in artifacts;
   :func:`dequantize_inference_params` materializes f32 for the engine.
3. **Broadcast** — one controller holds the consolidated params;
   :func:`broadcast_inference_params` ships them to every device of a
   mesh communicator as a planner ``multicast`` plan
   (:func:`weights_multicast_plan`) run through the leaf-mode stage
   chain — the same compiled lowering the gradient planner uses, WITHOUT
   ``execute_plan``'s mean semantics (a broadcast divided by world size
   would scale the weights).
4. **TP-shard** — :func:`shard_params_tp` Megatron-slices the
   transformer blocks for the engine's ``tp_size > 1`` path: qkv/up
   kernels column-sliced per local head group, proj/down kernels
   row-sliced with their biases pre-divided by ``tp_size`` (flax's Dense
   adds the bias BEFORE the engine's row-parallel psum, so each shard
   must contribute ``bias / tp``); everything else replicated.  Leaves
   come back stacked ``[tp, ...]`` ready for ``shard_map`` over the
   ``"tp"`` axis.
"""

from __future__ import annotations

from typing import Any, Optional

import jax
import jax.numpy as jnp

_QUANT_CODES = "int8_codes"
_QUANT_SCALE = "int8_scale"


# -- checkpoint -> consolidated params ---------------------------------------

def load_inference_params(source, metas=None, *, checkpointer=None,
                          dtype=None, int8_weights: bool = False):
    """Produce a consolidated inference parameter tree.

    ``source`` is either a live state tree (params, or a tree holding
    FSDP-sharded sub-states) or — when ``checkpointer`` is given — the
    resume TEMPLATE whose latest consistent generation is restored
    first.  Any :class:`~chainermn_tpu.parallel.fsdp.FsdpState` in the
    result is consolidated via ``consolidate_fsdp_checkpoint`` (pass the
    matching ``metas``).  ``dtype`` casts floating leaves (e.g.
    ``jnp.bfloat16``); ``int8_weights=True`` round-trips matrix leaves
    through the per-channel int8 codec — the artifact/wire precision —
    before returning f32 (see :func:`quantize_inference_params` to keep
    the codes themselves).
    """
    from chainermn_tpu.extensions.checkpoint import (
        consolidate_fsdp_checkpoint)
    from chainermn_tpu.parallel.fsdp import iter_fsdp_states

    state = source
    if checkpointer is not None:
        state, gen = checkpointer.resume(source)
        if gen is None:
            raise FileNotFoundError(
                f"no consistent checkpoint generation found under "
                f"{getattr(checkpointer, 'path', '?')!r} for "
                f"{getattr(checkpointer, 'name', '?')!r} — train and "
                f"save first, or point the loader at the right path")
    if any(True for _ in iter_fsdp_states(state)):
        if metas is None:
            raise ValueError(
                "source holds FSDP-sharded state but no FsdpMeta was "
                "given — pass the meta(s) from fsdp_init (the sharded "
                "layout cannot be consolidated without it)")
        state = consolidate_fsdp_checkpoint(state, metas)
    if int8_weights:
        state = dequantize_inference_params(
            quantize_inference_params(state))
    if dtype is not None:
        state = jax.tree.map(
            lambda x: x.astype(dtype)
            if jnp.issubdtype(jnp.asarray(x).dtype, jnp.floating) else x,
            state)
    return state


# -- optional per-channel int8 ------------------------------------------------

def _is_matrix(x) -> bool:
    return hasattr(x, "ndim") and x.ndim >= 2 and jnp.issubdtype(
        jnp.asarray(x).dtype, jnp.floating)


def quantize_inference_params(params):
    """Per-channel int8 form of a parameter tree: every floating leaf
    with ``ndim >= 2`` becomes ``{"int8_codes", "int8_scale"}`` (channel
    = last axis, the output channel of flax Dense/Embed kernels);
    vectors and scalars stay f32 (biases/norms are tiny and
    precision-critical).  ~4x fewer bytes for artifacts and the
    multicast wire."""
    from chainermn_tpu.compression.quantize import (
        quantize_per_channel_int8)

    def leaf(x):
        if not _is_matrix(x):
            return x
        codes, scale = quantize_per_channel_int8(x, channel_axis=-1)
        return {_QUANT_CODES: codes, _QUANT_SCALE: scale}

    return jax.tree.map(leaf, params)


def dequantize_inference_params(qparams):
    """Inverse of :func:`quantize_inference_params`."""
    from chainermn_tpu.compression.quantize import dequantize_int8

    def walk(node):
        if isinstance(node, dict):
            if set(node) == {_QUANT_CODES, _QUANT_SCALE}:
                return dequantize_int8(node[_QUANT_CODES],
                                       node[_QUANT_SCALE])
            return {k: walk(v) for k, v in node.items()}
        if isinstance(node, (list, tuple)):
            t = type(node)
            return t(walk(v) for v in node)
        return node

    return walk(qparams)


# -- planner multicast broadcast ----------------------------------------------

def weights_multicast_plan(root: int = 0, name: str = "serving_weights",
                           hierarchical: bool = False, topology=None):
    """The broadcast as a serializable planner plan: a leaf-packed
    ``multicast`` chain over the communicator's full scope — one global
    stage, or (``hierarchical=True``) the intra-then-inter two-stage
    form that crosses the DCN boundary once per node instead of in a
    global fan (``planner.plans.multicast_plan``; a non-zero ``root``
    then needs the ``topology`` to split into (inter, intra) coords)."""
    from chainermn_tpu.planner.plans import multicast_plan

    return multicast_plan(hierarchical=hierarchical, root=root,
                          topology=topology, name=name)


def broadcast_inference_params(comm, params, root: int = 0, *,
                               plan=None):
    """Ship ``root``'s consolidated params to every device of ``comm``
    via the multicast plan's leaf-mode stage chain (NOT ``execute_plan``,
    whose gradient-mean division would scale the weights by 1/size).
    ``params`` is root's tree; returns the replicated tree (identical on
    every rank).  Quantized trees from
    :func:`quantize_inference_params` pass through — int8 codes ride the
    wire at 1/4 the bytes.  ``plan`` overrides the default flat
    multicast with any leaf-packed broadcast plan — e.g. a tuned entry
    from ``planner.broadcast_plans`` (hierarchical multicast crossing
    the DCN boundary once per node); it must deliver root's value, so
    build it with the same ``root``.
    """
    from chainermn_tpu.planner.compiler import _run_stages_leaf

    if plan is None:
        plan = weights_multicast_plan(root=root,
                                      topology=comm.plan_topology())
    plan.validate()
    if plan.packing != "leaf":
        raise ValueError(
            f"broadcast plan {plan.name!r} must use leaf packing "
            f"(arbitrary param trees); got {plan.packing!r}")
    topology = comm.plan_topology()
    size = comm.size

    def place(x):
        x = jnp.asarray(x)
        stacked = jnp.zeros((size,) + x.shape, x.dtype)
        return stacked.at[root].set(x)

    def body(p):
        # int8 leaves multicast exactly: the masked-psum lowering sums
        # one non-zero contribution, which int arithmetic represents
        return jax.tree.map(
            lambda leaf: _run_stages_leaf(plan, topology, leaf), p)

    out = comm.run_spmd(body, jax.tree.map(place, params))
    return jax.tree.map(lambda x: x[root], out)


# -- Megatron tensor-parallel slicing -----------------------------------------

def shard_params_tp(params, tp_size: int, *, n_heads: int,
                    n_kv_heads: Optional[int] = None):
    """Slice a :class:`~chainermn_tpu.models.transformer.TransformerLM`
    parameter tree Megatron-style into ``tp_size`` shards, stacked on a
    new leading ``[tp]`` axis (feed through ``shard_map`` with in_spec
    ``P("tp")``; each device squeezes its slice).

    Per block: ``qkv`` column-sliced by head group (q by ``n_heads``,
    k/v by ``n_kv_heads`` — GQA groups stay with their queries), ``up``
    column-sliced, ``proj``/``down`` row-sliced with bias pre-divided by
    ``tp_size`` (Dense adds bias before the block's row-parallel psum).
    Embeddings, layer norms, and the head are replicated.
    """
    try:
        from flax import traverse_util
    except ImportError as e:  # pragma: no cover - flax ships with models
        raise ImportError("shard_params_tp needs flax") from e

    tp = int(tp_size)
    n_kv = n_kv_heads or n_heads
    if tp < 1 or n_heads % tp or n_kv % tp:
        raise ValueError(
            f"tp_size ({tp}) must be >= 1 and divide n_heads "
            f"({n_heads}) and n_kv_heads ({n_kv})")

    flat = traverse_util.flatten_dict(params)

    def qkv_slices(w, axis):
        d = w.shape[axis]
        hd = d // (n_heads + 2 * n_kv)
        if hd * (n_heads + 2 * n_kv) != d:
            raise ValueError(
                f"qkv dim {d} is not (n_heads + 2*n_kv_heads) * head_dim "
                f"for n_heads={n_heads}, n_kv_heads={n_kv}")
        nq, nkv = n_heads * hd, n_kv * hd
        take = lambda a, b: jax.lax.slice_in_dim(w, a, b, axis=axis)
        q, k, v = take(0, nq), take(nq, nq + nkv), take(nq + nkv, d)
        lq, lkv = nq // tp, nkv // tp
        return [jnp.concatenate(
            [jax.lax.slice_in_dim(q, i * lq, (i + 1) * lq, axis=axis),
             jax.lax.slice_in_dim(k, i * lkv, (i + 1) * lkv, axis=axis),
             jax.lax.slice_in_dim(v, i * lkv, (i + 1) * lkv, axis=axis)],
            axis=axis) for i in range(tp)]

    def col_slices(w, axis):
        n = w.shape[axis] // tp
        if n * tp != w.shape[axis]:
            raise ValueError(
                f"dim {w.shape[axis]} not divisible by tp_size {tp}")
        return [jax.lax.slice_in_dim(w, i * n, (i + 1) * n, axis=axis)
                for i in range(tp)]

    out = {}
    for path, w in flat.items():
        module = path[-2] if len(path) >= 2 else ""
        leafname = path[-1]
        w = jnp.asarray(w)
        if module == "qkv":
            shards = qkv_slices(w, axis=-1 if leafname == "kernel" else 0)
        elif module == "up":
            shards = col_slices(w, axis=-1 if leafname == "kernel" else 0)
        elif module in ("proj", "down"):
            if leafname == "kernel":
                shards = col_slices(w, axis=0)       # row-parallel input
            else:
                shards = [w / tp] * tp               # bias before psum
        else:
            shards = [w] * tp                        # replicated
        out[path] = jnp.stack(shards)

    return traverse_util.unflatten_dict(out)


__all__ = ["broadcast_inference_params", "dequantize_inference_params",
           "load_inference_params", "quantize_inference_params",
           "shard_params_tp", "weights_multicast_plan"]
