"""Session-affine routing over N inference-engine replicas.

The fleet layer above :class:`~chainermn_tpu.serving.engine.InferenceEngine`:
one engine replica is one lockstep serving world (a mesh plus its
controllers), and the router is the DCN-side dispatcher that spreads
*requests* — never tokens — across replicas.  Two rules:

* **Session affinity** — every turn of a session lands on the replica
  that served its first turn, so the replica's prefix cache already
  holds the session's shared prompt pages (routing a turn elsewhere
  would re-prefill from scratch AND fragment the trie).
* **Least load** — a session's FIRST turn goes to the replica with the
  lowest load signal: queue depth + active slots + page pressure
  (``1 - free/total``), tie-broken by replica index so every controller
  that replays the same submit sequence picks the same replica.

Weight distribution to the fleet rides the planner's first-class
multicast stages: :meth:`Router.distribute_weights` wraps
:func:`~chainermn_tpu.serving.weights.broadcast_inference_params` with a
tuned :func:`~chainermn_tpu.planner.plans.multicast_plan` — hierarchical
(one DCN crossing per node) whenever the communicator spans more than
one node — instead of N repeated point-to-point sends.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Dict, Hashable, List, Optional, Tuple

from chainermn_tpu.serving.engine import Completion, InferenceEngine


@dataclasses.dataclass
class ReplicaStatus:
    """One replica's load signals (the dispatch inputs)."""

    replica: int
    queue_depth: int
    active: int
    free_pages: int
    num_pages: int

    @property
    def page_pressure(self) -> float:
        return 1.0 - self.free_pages / max(self.num_pages, 1)

    @property
    def load(self) -> float:
        return self.queue_depth + self.active + self.page_pressure


class Router:
    """Session-affine dispatch over ``engines`` (one per replica).

    ``submit()`` returns a router-scoped request id; ``step()`` advances
    every busy replica one engine step; ``run_until_idle()`` drains the
    fleet.  Completions aggregate in :attr:`completions` as
    ``(replica, session, Completion)`` triples.
    """

    def __init__(self, engines: List[InferenceEngine]):
        if not engines:
            raise ValueError("Router needs at least one engine replica")
        self.engines = list(engines)
        self._session_replica: Dict[Hashable, int] = {}
        self._rid_map: Dict[int, Tuple[int, int]] = {}  # router -> (rep, rid)
        self._session_of: Dict[Tuple[int, int], Hashable] = {}
        self._next_rid = 0
        self.dispatch_log: List[Tuple[int, Hashable, int]] = []
        self.completions: List[Tuple[int, Hashable, Completion]] = []
        self._claimed: Dict[int, int] = {}  # per-replica completions seen
        # elastic bookkeeping: what each live rid asked for (so a lost
        # replica's in-flight requests can be replayed), which replicas
        # are drained out of dispatch, and the work each drain stranded
        self._requests: Dict[int, Tuple[object, int, Optional[Hashable]]] = {}
        self._drained: set = set()
        self._lost: Dict[int, List[int]] = {}

        from chainermn_tpu.observability.registry import (enabled,
                                                          get_registry)
        self._m = None
        if enabled():
            reg = get_registry()
            self._m = {
                "dispatched": reg.counter(
                    "serving_router_dispatched",
                    "requests dispatched to replicas"),
                "sessions": reg.gauge(
                    "serving_router_sessions", "distinct sessions seen"),
                "load": reg.gauge(
                    "serving_router_replica_load",
                    "per-replica load signal at last dispatch"),
            }

    # -- load signals --------------------------------------------------------
    def status(self) -> List[ReplicaStatus]:
        out = []
        for i, eng in enumerate(self.engines):
            if i in self._drained:
                # a drained replica's engine may be a dead world — it
                # must neither be probed nor dispatched to
                out.append(ReplicaStatus(replica=i, queue_depth=0,
                                         active=0, free_pages=0,
                                         num_pages=0))
                continue
            sched = eng.scheduler
            out.append(ReplicaStatus(
                replica=i, queue_depth=sched.queue_depth,
                active=sched.active_count,
                free_pages=sched.allocator.num_free,
                num_pages=sched.num_pages))
        return out

    def _pick_replica(self, session: Optional[Hashable]) -> int:
        if session is not None and session in self._session_replica:
            rep = self._session_replica[session]
            if rep not in self._drained:
                return rep
            del self._session_replica[session]  # re-route the session
        st = [s for s in self.status() if s.replica not in self._drained]
        if not st:
            raise RuntimeError(
                "every replica is drained — readmit one "
                "(Router.readmit_replica) before submitting")
        best = min(st, key=lambda s: (s.load, s.replica))
        if session is not None:
            self._session_replica[session] = best.replica
        return best.replica

    # -- client side ---------------------------------------------------------
    def submit(self, prompt, max_new_tokens: int, *,
               session: Optional[Hashable] = None,
               arrival: Optional[float] = None) -> int:
        """Dispatch a request; same ``session`` -> same replica."""
        arrival = time.perf_counter() if arrival is None else arrival
        rep = self._pick_replica(session)
        eng_rid = self.engines[rep].submit(prompt, max_new_tokens,
                                           arrival=arrival)
        rid = self._next_rid
        self._next_rid += 1
        self._rid_map[rid] = (rep, eng_rid)
        self._session_of[(rep, eng_rid)] = session
        self._requests[rid] = (prompt, max_new_tokens, session)
        self.dispatch_log.append((rid, session, rep))
        if self._m is not None:
            self._m["dispatched"].inc(replica=str(rep))
            self._m["sessions"].set(len(self._session_replica))
            self._m["load"].set(self.status()[rep].load,
                                replica=str(rep))
        return rid

    def replica_of(self, rid: int) -> int:
        return self._rid_map[rid][0]

    def idle(self) -> bool:
        return all(e.idle() for i, e in enumerate(self.engines)
                   if i not in self._drained)

    # -- the fleet step loop -------------------------------------------------
    def _collect(self, rep: int) -> None:
        comps = self.engines[rep].completions
        seen = self._claimed.get(rep, 0)
        for comp in comps[seen:]:
            self.completions.append(
                (rep, self._session_of.get((rep, comp.rid)), comp))
        self._claimed[rep] = len(comps)

    def step(self) -> int:
        """Step every busy replica once; returns how many stepped."""
        stepped = 0
        for i, eng in enumerate(self.engines):
            if i in self._drained:
                continue
            if not eng.idle():
                eng.step()
                self._collect(i)
                stepped += 1
        return stepped

    def run_until_idle(self, max_steps: int = 10_000) \
            -> List[Tuple[int, Hashable, Completion]]:
        start = len(self.completions)
        for _ in range(max_steps):
            if self.idle():
                break
            self.step()
        else:
            raise RuntimeError(
                f"fleet still busy after {max_steps} steps: "
                f"{[(s.replica, s.queue_depth, s.active) for s in self.status()]}")
        return self.completions[start:]

    # -- elastic fleet membership --------------------------------------------
    def drain_replica(self, rep: int) -> Dict[str, object]:
        """Take a lost replica out of dispatch (supervisor ``on_incident``
        hook).

        Its session affinities are forgotten — the next turn of each
        session re-routes by least load, re-prefilling on the new home —
        and every request the replica had not completed is replayed onto
        a surviving replica under the SAME router rid, so callers'
        handles stay valid and at most the lost replica's in-flight
        decode work is repeated, never dropped.  Returns a summary dict
        (``sessions_rerouted``, ``requests_replayed``).
        """
        if not (0 <= rep < len(self.engines)):
            raise ValueError(f"no replica {rep} (fleet size "
                             f"{len(self.engines)})")
        self._drained.add(rep)

        done = {(r, c.rid) for r, _s, c in self.completions if r == rep}
        stranded = [rid for rid, (r, erid) in self._rid_map.items()
                    if r == rep and (r, erid) not in done]
        self._lost[rep] = list(stranded)

        moved_sessions = [s for s, r in self._session_replica.items()
                          if r == rep]
        for s in moved_sessions:
            del self._session_replica[s]

        replayed = 0
        for rid in stranded:
            prompt, max_new, session = self._requests[rid]
            old_rep, old_erid = self._rid_map[rid]
            self._session_of.pop((old_rep, old_erid), None)
            new_rep = self._pick_replica(session)
            new_erid = self.engines[new_rep].submit(prompt, max_new)
            self._rid_map[rid] = (new_rep, new_erid)
            self._session_of[(new_rep, new_erid)] = session
            self.dispatch_log.append((rid, session, new_rep))
            replayed += 1
            if self._m is not None:
                self._m["dispatched"].inc(replica=str(new_rep))
        return {"replica": rep, "sessions_rerouted": len(moved_sessions),
                "requests_replayed": replayed}

    def readmit_replica(self, rep: int, engine=None) -> None:
        """Return a drained replica to dispatch (supervisor
        ``on_recovered`` hook).  Pass ``engine`` when the restarted world
        came back as a fresh :class:`InferenceEngine` — its completion
        list starts empty, so the claim cursor resets with it.  New
        first-turn sessions may now land on it; replayed requests stay
        where the drain put them.
        """
        if rep not in self._drained:
            raise ValueError(f"replica {rep} is not drained")
        if engine is not None:
            self.engines[rep] = engine
            self._claimed[rep] = 0
        self._drained.discard(rep)
        self._lost.pop(rep, None)

    @property
    def drained(self) -> frozenset:
        return frozenset(self._drained)

    # -- fleet weight distribution -------------------------------------------
    @staticmethod
    def distribute_weights(comm, params, root: int = 0, *, plan=None):
        """Ship ``root``'s consolidated params to every replica device
        through a planner multicast plan (ONE masked-psum collective per
        stage — census-checkable — not repeated p2p sends).  Defaults to
        the tuned shape for the communicator's topology: hierarchical
        multicast (intra stage + one DCN crossing per node) when the
        topology spans multiple nodes, flat multicast otherwise."""
        from chainermn_tpu.serving.weights import (
            broadcast_inference_params, weights_multicast_plan)

        if plan is None:
            topo = comm.plan_topology()
            hier = any(n == "inter" and size > 1 for n, size in topo.axes)
            plan = weights_multicast_plan(root=root, hierarchical=hier,
                                          topology=topo,
                                          name="router_weights")
        return broadcast_inference_params(comm, params, root=root,
                                          plan=plan)


__all__ = ["ReplicaStatus", "Router"]
