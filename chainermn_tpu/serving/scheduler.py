"""Admission scheduling for continuous batching.

The scheduler is split into a pure DECISION step and a deterministic
APPLY step so a multi-controller serving world can run in lockstep over
the DCN control plane: rank 0 calls :meth:`AdmissionScheduler.build_plan`
(no mutation), broadcasts the resulting plain-dict plan with
``bcast_obj``, and then EVERY rank — rank 0 included — applies the same
plan with :meth:`AdmissionScheduler.apply_plan`.  Because the plan
carries the admitted prompts and the page allocator is deterministic
(:class:`~chainermn_tpu.serving.kv_cache.PageAllocator` hands out the
lowest free pages), all ranks evolve identical slot states, page tables,
and — greedy sampling being deterministic on replicated logits —
identical generated tokens.  Only rank 0 holds the waiting queue.

Two admission policies:

* ``"continuous"`` — every step, waiting requests are packed into any
  free slot whose page reservation fits (vLLM-style continuous
  batching; finished sequences retire and their slot refills next
  step).
* ``"static"`` — requests are admitted only when ALL slots are empty:
  the classic static batch, kept as the benchmark baseline
  (``benchmarks/bench_serving.py``).

Pages are reserved on admission for the worst case
(``ceil((prompt + max_new) / page_size)``) and freed on retirement —
admission control IS the eviction policy, so a running sequence can
never hit an out-of-pages condition mid-flight.
"""

from __future__ import annotations

import dataclasses
from collections import deque
from typing import Deque, Dict, List, Optional

import numpy as np

from chainermn_tpu.serving.kv_cache import PageAllocator, PrefixCache

_POLICIES = ("continuous", "static")


@dataclasses.dataclass
class Request:
    """One inference request (rank 0 / client side)."""

    rid: int
    prompt: List[int]
    max_new_tokens: int
    arrival: float = 0.0  # host-side submit time (never traced)


@dataclasses.dataclass
class _Slot:
    """Replicated per-slot decode state (identical on every rank)."""

    rid: int
    prompt: List[int]
    max_new: int
    pages: List[int]
    seq_len: int = 0                 # tokens whose KV sit in the cache
    generated: List[int] = dataclasses.field(default_factory=list)
    finished: bool = False
    hit_tokens: int = 0              # prompt tokens served from the prefix cache
    indexed: bool = False            # prompt pages already in the prefix trie


class AdmissionScheduler:
    def __init__(self, *, max_seqs: int, page_size: int, num_pages: int,
                 max_pages_per_seq: int, chunk_tokens: int,
                 eos_id: Optional[int] = None,
                 policy: str = "continuous",
                 prefix_cache: bool = False):
        if policy not in _POLICIES:
            raise ValueError(f"policy must be one of {_POLICIES}, "
                             f"got {policy!r}")
        self.max_seqs = max_seqs
        self.page_size = page_size
        self.num_pages = num_pages
        self.max_pages_per_seq = max_pages_per_seq
        self.chunk_tokens = chunk_tokens
        self.eos_id = eos_id
        self.policy = policy
        self.allocator = PageAllocator(num_pages)
        self.prefix: Optional[PrefixCache] = (
            PrefixCache(page_size, self.allocator) if prefix_cache else None)
        self.slots: List[Optional[_Slot]] = [None] * max_seqs
        self.waiting: Deque[Request] = deque()   # rank 0 only
        # trash page = physical index num_pages (kv_cache layout);
        # unassigned table entries point there
        self.page_table = np.full((max_seqs, max_pages_per_seq),
                                  num_pages, np.int32)
        self._next_rid = 0
        # prefix-cache stats, updated in apply_plan/note_sampled so every
        # rank counts identically
        self.prefix_admits = 0
        self.prefix_hits = 0
        self.prefix_hit_tokens = 0
        self.prefix_prompt_tokens = 0

    # -- client side (rank 0) ------------------------------------------------
    def pages_needed(self, prompt_len: int, max_new: int) -> int:
        total = prompt_len + max_new
        return -(-total // self.page_size)  # ceil

    def submit(self, prompt: List[int], max_new_tokens: int,
               arrival: float = 0.0) -> int:
        """Queue a request (rank 0 only); returns its request id."""
        if not prompt:
            raise ValueError("empty prompt")
        if max_new_tokens < 1:
            raise ValueError(f"max_new_tokens must be >= 1, "
                             f"got {max_new_tokens}")
        need = self.pages_needed(len(prompt), max_new_tokens)
        if need > self.max_pages_per_seq:
            raise ValueError(
                f"request needs {need} pages (prompt {len(prompt)} + "
                f"max_new {max_new_tokens} at page_size "
                f"{self.page_size}) but the page table holds "
                f"{self.max_pages_per_seq} per sequence — raise "
                f"max_pages_per_seq or shorten the request")
        rid = self._next_rid
        self._next_rid += 1
        self.waiting.append(Request(rid, list(map(int, prompt)),
                                    int(max_new_tokens), arrival))
        return rid

    @property
    def queue_depth(self) -> int:
        return len(self.waiting)

    @property
    def active_count(self) -> int:
        return sum(1 for s in self.slots if s is not None)

    def idle(self) -> bool:
        return self.active_count == 0 and not self.waiting

    # -- lockstep plan: decide (rank 0), broadcast, apply (all ranks) --------
    def build_plan(self) -> dict:
        """Pure decision: which finished slots retire this step, which
        prefix-cache pages are evicted, and which waiting requests are
        admitted into which slots (with their cache-hit pages).  Mutates
        nothing — the same plan is applied by every rank via
        :meth:`apply_plan`."""
        retire = [[i, s.rid] for i, s in enumerate(self.slots)
                  if s is not None and s.finished]
        retiring = {i for i, _ in retire}
        free_slots = [i for i, s in enumerate(self.slots)
                      if s is None or i in retiring]
        retiring_pages = [p for i in retiring for p in self.slots[i].pages]
        # Refcount-aware: a retiring slot's shared pages stay resident
        # (the prefix trie still holds them) — only pages whose last
        # holder is the retiring slot actually come back.
        free_pages = (self.allocator.num_free
                      + self.allocator.would_free(retiring_pages))
        admit = []
        evict: List[int] = []
        evicted_set: set = set()
        protect: set = set()
        if self.policy == "static" and len(free_slots) < self.max_seqs:
            free_slots = []  # static batch: wait for the whole batch
        for req in self.waiting:
            if not free_slots:
                break
            hit_pages: List[int] = []
            hit_tokens = 0
            if self.prefix is not None:
                hit_pages, hit_tokens = self.prefix.lookup(req.prompt)
                for j, p in enumerate(hit_pages):
                    if p in evicted_set:  # already claimed by this plan
                        hit_pages = hit_pages[:j]
                        hit_tokens = j * self.page_size
                        break
            need = self.pages_needed(len(req.prompt), req.max_new_tokens)
            need_new = need - len(hit_pages)
            if need_new > free_pages:
                shortfall = need_new - free_pages
                more = []
                if self.prefix is not None:
                    want = len(evict) + shortfall
                    full = self.prefix.plan_evictions(
                        want, exclude=protect | set(hit_pages))
                    if len(full) >= want:
                        more = full[len(evict):]
                if not more:
                    break  # FIFO head-of-line: keep admission order stable
                evict.extend(more)
                evicted_set.update(more)
                free_pages += len(more)
            protect.update(hit_pages)
            entry = [free_slots.pop(0), req.rid, list(req.prompt),
                     req.max_new_tokens]
            if self.prefix is not None:
                entry += [hit_tokens, list(hit_pages)]
            admit.append(entry)
            free_pages -= need_new
        plan = {"retire": retire, "admit": admit}
        if evict:
            plan["evict"] = evict
        return plan

    def apply_plan(self, plan: dict) -> list:
        """Apply a (possibly remote) plan deterministically.  Returns the
        retired ``(slot_idx, _Slot)`` pairs (the engine turns them into
        completions)."""
        retired = []
        for slot_idx, rid in plan["retire"]:
            slot = self.slots[slot_idx]
            if slot is None or slot.rid != rid:
                raise RuntimeError(
                    f"lockstep desync: plan retires rid {rid} from slot "
                    f"{slot_idx} but this rank holds "
                    f"{None if slot is None else slot.rid}")
            self.allocator.free(slot.pages)
            self.page_table[slot_idx, :] = self.num_pages
            self.slots[slot_idx] = None
            retired.append((slot_idx, slot))
        evict = plan.get("evict") or []
        if evict:
            if self.prefix is None:
                raise RuntimeError(
                    "lockstep desync: plan evicts prefix pages but this "
                    "rank has no prefix cache")
            self.prefix.evict_pages(evict)
        for entry in plan["admit"]:
            slot_idx, rid, prompt, max_new = entry[:4]
            hit_tokens = int(entry[4]) if len(entry) > 4 else 0
            hit_pages = [int(p) for p in entry[5]] if len(entry) > 5 else []
            if self.slots[slot_idx] is not None:
                raise RuntimeError(
                    f"lockstep desync: admitting rid {rid} into occupied "
                    f"slot {slot_idx}")
            if hit_pages:
                got, _ = self.prefix.lookup(prompt)
                if got[:len(hit_pages)] != hit_pages:
                    raise RuntimeError(
                        f"lockstep desync: plan admits rid {rid} with "
                        f"prefix hit {hit_pages} but this rank's trie "
                        f"holds {got[:len(hit_pages)]}")
                self.allocator.retain(hit_pages)
                self.prefix.touch(prompt, len(hit_pages))
            need = self.pages_needed(len(prompt), max_new)
            fresh = self.allocator.alloc(need - len(hit_pages))
            if fresh is None:
                raise RuntimeError(
                    f"lockstep desync: no pages for admitted rid {rid} "
                    f"(need {need - len(hit_pages)}, free "
                    f"{self.allocator.num_free})")
            pages = hit_pages + fresh
            self.slots[slot_idx] = _Slot(rid=rid, prompt=list(prompt),
                                         max_new=max_new, pages=pages,
                                         seq_len=hit_tokens,
                                         hit_tokens=hit_tokens)
            self.page_table[slot_idx, :] = self.num_pages
            self.page_table[slot_idx, :len(pages)] = pages
            self.prefix_admits += 1
            self.prefix_prompt_tokens += len(prompt)
            if hit_tokens:
                self.prefix_hits += 1
                self.prefix_hit_tokens += hit_tokens
            if self.waiting and self.waiting[0].rid == rid:
                self.waiting.popleft()  # rank 0 drains its queue
        return retired

    # -- per-step batch construction ----------------------------------------
    def step_batch(self) -> Dict[str, np.ndarray]:
        """Fixed-shape [B, S] batch for the fused prefill+decode forward:
        prefilling slots contribute their next prompt chunk (up to
        ``chunk_tokens``), decoding slots their last sampled token, idle
        or finished slots nothing (``n_new == 0``, writes go to the trash
        page)."""
        b, s = self.max_seqs, self.chunk_tokens
        tokens = np.zeros((b, s), np.int32)
        pos0 = np.zeros((b,), np.int32)
        n_new = np.zeros((b,), np.int32)
        decode = np.zeros((b,), np.int32)
        prev = np.zeros((b,), np.int32)
        for i, slot in enumerate(self.slots):
            if slot is None or slot.finished:
                continue
            pos0[i] = slot.seq_len
            if slot.seq_len < len(slot.prompt):          # prefill chunk
                chunk = slot.prompt[slot.seq_len:slot.seq_len + s]
                tokens[i, :len(chunk)] = chunk
                n_new[i] = len(chunk)
            else:                                        # decode: 1 token
                tokens[i, 0] = slot.generated[-1]
                n_new[i] = 1
                decode[i] = 1  # a 1-token prefill tail is NOT decode —
                #                only the host can tell (spec-decode mask)
                # second-to-last sequence token (position seq_len - 1):
                # the spec draft re-feeds it to heal the one-position
                # draft-cache hole a fully-accepted round leaves behind
                prev[i] = (slot.generated[-2] if len(slot.generated) > 1
                           else slot.prompt[-1])
        return {"tokens": tokens, "pos0": pos0, "n_new": n_new,
                "decode": decode, "prev": prev,
                "page_table": self.page_table.copy()}

    def _maybe_index_prefix(self, slot: _Slot) -> None:
        """Index a just-prefilled slot's full prompt pages in the trie
        (every rank runs this at the same step — lockstep-identical)."""
        if self.prefix is None or slot.indexed:
            return
        slot.indexed = True
        n_full = len(slot.prompt) // self.page_size
        if n_full:
            self.prefix.insert(slot.prompt, slot.pages, n_full)

    def note_sampled(self, n_new: np.ndarray, sampled: np.ndarray) -> list:
        """Advance slot state after the forward.  ``sampled[i]`` is the
        greedy token at slot ``i``'s last valid position.  Returns the
        emitted tokens ``[(rid, token, n_generated)]`` — a sequence emits
        only once its whole prompt is in the cache (the step that
        consumed the final prompt chunk produces its first token)."""
        sampled = np.asarray(sampled)
        return self.note_sampled_spec(
            n_new, sampled.reshape(len(sampled), 1),
            np.ones(len(sampled), np.int32))

    def note_sampled_spec(self, n_new: np.ndarray, out_tokens: np.ndarray,
                          n_out: np.ndarray) -> list:
        """Spec-decode generalization of :meth:`note_sampled`: a decoding
        slot may land up to ``n_out[i]`` tokens this step
        (``out_tokens[i, :n_out[i]]`` = accepted draft tokens plus the
        target's correction/bonus token), truncated at ``max_new``/EOS.
        ``seq_len`` advances by the kept count — the KV of every kept
        token except the last is already in the cache, preserving the
        vanilla decode invariant."""
        emitted = []
        for i, slot in enumerate(self.slots):
            if slot is None or slot.finished or n_new[i] == 0:
                continue
            if slot.seq_len < len(slot.prompt):          # prefill row
                slot.seq_len += int(n_new[i])
                if slot.seq_len < len(slot.prompt):
                    continue  # still prefilling
                self._maybe_index_prefix(slot)
                tok = int(out_tokens[i, 0])
                slot.generated.append(tok)
                emitted.append((slot.rid, tok, len(slot.generated)))
                if (len(slot.generated) >= slot.max_new
                        or (self.eos_id is not None
                            and tok == self.eos_id)):
                    slot.finished = True
                continue
            kept = 0                                     # decode row
            for j in range(int(n_out[i])):
                tok = int(out_tokens[i, j])
                slot.generated.append(tok)
                kept += 1
                emitted.append((slot.rid, tok, len(slot.generated)))
                if (len(slot.generated) >= slot.max_new
                        or (self.eos_id is not None
                            and tok == self.eos_id)):
                    slot.finished = True
                    break
            slot.seq_len += kept
        return emitted

    def prefix_stats(self) -> dict:
        """Prefix-cache counters (identical on every rank)."""
        return {
            "enabled": self.prefix is not None,
            "admits": self.prefix_admits,
            "hits": self.prefix_hits,
            "hit_tokens": self.prefix_hit_tokens,
            "prompt_tokens": self.prefix_prompt_tokens,
            "cached_pages": 0 if self.prefix is None else len(self.prefix),
            "evictions": 0 if self.prefix is None else self.prefix.evictions,
        }


__all__ = ["AdmissionScheduler", "Request"]
