"""Continuous-batching inference on the mesh stack.

:mod:`~chainermn_tpu.serving.kv_cache` — paged KV cache + deterministic
page allocator; :mod:`~chainermn_tpu.serving.scheduler` — lockstep
admission scheduling (continuous or static);
:mod:`~chainermn_tpu.serving.engine` — the fused prefill+decode step
loop; :mod:`~chainermn_tpu.serving.weights` — checkpoint consolidation,
int8 weight quantization, multicast broadcast, TP slicing.  See
``docs/serving.md``.
"""

from chainermn_tpu.serving.engine import (Completion, InferenceEngine,
                                          ServingConfig, StepResult)
from chainermn_tpu.serving.kv_cache import (KvCache, PageAllocator,
                                            PrefixCache, gather_kv,
                                            init_kv_cache,
                                            paged_attention, write_kv)
from chainermn_tpu.serving.router import ReplicaStatus, Router
from chainermn_tpu.serving.scheduler import AdmissionScheduler, Request
from chainermn_tpu.serving.weights import (broadcast_inference_params,
                                           dequantize_inference_params,
                                           load_inference_params,
                                           quantize_inference_params,
                                           shard_params_tp,
                                           weights_multicast_plan)

__all__ = [
    "AdmissionScheduler",
    "Completion",
    "InferenceEngine",
    "KvCache",
    "PageAllocator",
    "PrefixCache",
    "ReplicaStatus",
    "Request",
    "Router",
    "ServingConfig",
    "StepResult",
    "broadcast_inference_params",
    "dequantize_inference_params",
    "gather_kv",
    "init_kv_cache",
    "load_inference_params",
    "paged_attention",
    "quantize_inference_params",
    "shard_params_tp",
    "weights_multicast_plan",
    "write_kv",
]
