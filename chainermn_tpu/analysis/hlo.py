"""Collective parser over compiled HLO text.

The ONE parser behind every HLO-census consumer: the ``cmn-lint`` rules
(``census-drift``, ``wire-dtype-mismatch``, ``async-pair``), the
``tests/test_census.py`` gate, and the committed ``CENSUS_r*.json``
artifact (``bench_allreduce.py --census``) all read collectives through
:func:`parse_hlo_collectives` — before this module, benchmarks/ and the
test gate each carried their own regex and could drift apart.

Two HLO renderings the naive one-regex-per-line approach missed:

* **multi-line ops** — an instruction whose operand list or replica
  groups wrap across physical lines.  The parser first joins physical
  lines into logical instructions (a line that does not open a new
  ``name = shape op(...)`` binding continues the previous one).
* **async pairs** — on real TPU schedules collectives lower to
  ``all-reduce-start`` / ``all-reduce-done`` (likewise all-gather and
  collective-permute).  A start/done pair is ONE collective: it is
  counted once, at the start's position (issue order), with the payload
  read from the *done*'s result shape (the start's tuple shape would
  double-count) and the groups from the start (done ops carry none).  An
  unmatched start or done is recorded as a parse problem — the
  ``async-pair`` lint rule turns those into error findings, because an
  unmatched start in a schedule is exactly the shape of program the
  runtime hang watchdog ends up diagnosing on-mesh.
"""

from __future__ import annotations

import re
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

HLO_DTYPE_BYTES = {
    "f64": 8, "s64": 8, "u64": 8, "c64": 8,
    "f32": 4, "s32": 4, "u32": 4,
    "bf16": 2, "f16": 2, "s16": 2, "u16": 2,
    "f8e4m3fn": 1, "f8e5m2": 1, "f8e4m3": 1,
    "s8": 1, "u8": 1, "pred": 1,
}

#: base collective op kinds recognized (async suffixes handled separately)
COLLECTIVE_KINDS = (
    "all-reduce", "reduce-scatter", "all-gather", "all-to-all",
    "collective-permute", "ragged-all-to-all", "collective-broadcast",
)

_ASYNC_START = tuple(k + "-start" for k in COLLECTIVE_KINDS)
_ASYNC_DONE = tuple(k + "-done" for k in COLLECTIVE_KINDS)

# name = shape op(...) — the shape is either a tuple (...) or one token
_OP_RE = re.compile(
    r"(?P<name>%[\w.\-]+|[\w.\-]+)\s*=\s*"
    r"(?P<shape>\([^)]*\)|\S+)\s+"
    r"(?P<op>" + "|".join(
        re.escape(k) + "(?:-start|-done)?" for k in COLLECTIVE_KINDS)
    + r")\(")

# a new instruction binding starts a logical line; the HLO printer
# renders bindings with a SPACED " = " while instruction attributes
# (replica_groups=..., to_apply=...) use an unspaced "=" — that spacing
# is what separates a wrapped attribute line from a fresh binding
_BINDING_RE = re.compile(r"^\s*(?:ROOT\s+)?(?:%[\w.\-]+|[\w.\-]+)\s+=\s")
# computation headers / module lines never continue an instruction
_HEADER_RE = re.compile(
    r"^\s*(?:HloModule\b|ENTRY\b|%?[\w.\-]+\s*(?:\([^)]*\))?\s*->|\}|\{)")

_GROUPS_RE = re.compile(
    r"replica_groups=(\{(?:[^{}]|\{[^{}]*\})*\}"
    r"|\[[^\]]*\](?:<=\[[^\]]*\])?)")

_SHAPE_TOKEN_RE = re.compile(r"(\w+)\[([0-9,]*)\]")


@dataclass
class HloCollective:
    """One collective in a compiled HLO module (an async start/done pair
    folds into a single record)."""
    op: str                       # base kind, e.g. "all-reduce"
    nbytes: int                   # payload from the result shape
    groups: Optional[str]         # replica_groups text (or None)
    dtype: Optional[str]          # primary result dtype token, e.g. "f32"
    name: str = ""                # HLO instruction name
    is_async: bool = False        # came from a start/done pair
    line: int = 0                 # logical-line index (schedule order)

    def as_census_dict(self) -> dict:
        """The ``bench_allreduce.py --census`` artifact record shape —
        committed CENSUS_r*.json files compare on op/bytes/groups."""
        return {"op": self.op, "bytes": self.nbytes, "groups": self.groups,
                "dtype": self.dtype}


@dataclass
class HloParse:
    """Collectives plus any structural parse problems (unmatched async
    halves); ``ops`` is in schedule order (start position for pairs)."""
    ops: List[HloCollective] = field(default_factory=list)
    problems: List[dict] = field(default_factory=list)

    def kinds(self) -> Tuple[str, ...]:
        return tuple(o.op for o in self.ops)

    def count_by_kind(self) -> Dict[str, int]:
        out: Dict[str, int] = {}
        for o in self.ops:
            out[o.op] = out.get(o.op, 0) + 1
        return out


def _logical_lines(text: str) -> List[str]:
    """Join wrapped instruction renderings: a physical line that neither
    opens a new binding nor is a computation header continues the
    previous logical line."""
    out: List[str] = []
    for raw in text.splitlines():
        if not raw.strip():
            continue
        if out and not _BINDING_RE.match(raw) and not _HEADER_RE.match(raw):
            out[-1] += " " + raw.strip()
        else:
            out.append(raw)
    return out


def _shape_payload(shape_txt: str) -> Tuple[int, Optional[str]]:
    """(total bytes, primary dtype token) of a shape rendering."""
    size = 0
    dtype = None
    for dt, dims in _SHAPE_TOKEN_RE.findall(shape_txt):
        if dt not in HLO_DTYPE_BYTES:
            continue
        if dtype is None:
            dtype = dt
        count = 1
        for d in dims.split(","):
            if d:
                count *= int(d)
        size += count * HLO_DTYPE_BYTES[dt]
    return size, dtype


def _first_operand(line: str) -> Optional[str]:
    """Instruction name of the first operand inside the op's parens."""
    m = re.search(r"\(\s*(?:\([^)]*\)|\S+?)\s+(%[\w.\-]+|[\w.\-]+)", line)
    return m.group(1).lstrip("%") if m else None


def parse_hlo_collectives(hlo_text: str) -> HloParse:
    """Parse every collective out of optimized HLO text.

    Returns an :class:`HloParse`: records in schedule order with op kind,
    payload bytes, primary dtype, and replica groups; async
    start/done pairs folded into one record; unmatched halves reported in
    ``problems`` (``{"kind": "unmatched-async-start"|"unmatched-async-done",
    "op", "name", "line"}``).
    """
    parse = HloParse()
    pending_starts: Dict[str, HloCollective] = {}
    pending_order: List[str] = []
    for i, line in enumerate(_logical_lines(hlo_text)):
        m = _OP_RE.search(line)
        if not m:
            continue
        opname = m.group("op")
        name = m.group("name").lstrip("%")
        nbytes, dtype = _shape_payload(m.group("shape"))
        gm = _GROUPS_RE.search(line)
        groups = gm.group(1) if gm else None
        if opname.endswith("-start"):
            rec = HloCollective(op=opname[:-len("-start")], nbytes=nbytes,
                                dtype=dtype, groups=groups, name=name,
                                is_async=True, line=i)
            pending_starts[name] = rec
            pending_order.append(name)
            continue
        if opname.endswith("-done"):
            base = opname[:-len("-done")]
            src = _first_operand(line)
            start = pending_starts.pop(src, None) if src else None
            if start is None:
                # a done op whose start we never saw: count the
                # collective (payload is real) but flag the pairing
                parse.problems.append({"kind": "unmatched-async-done",
                                       "op": base, "name": name, "line": i})
                parse.ops.append(HloCollective(
                    op=base, nbytes=nbytes, dtype=dtype, groups=groups,
                    name=name, is_async=True, line=i))
                continue
            pending_order.remove(start.name)
            # ONE collective: start's position/groups, done's payload
            # (the start renders a tuple shape that double-counts)
            start.nbytes = nbytes or start.nbytes
            start.dtype = dtype or start.dtype
            parse.ops.append(start)
            continue
        parse.ops.append(HloCollective(
            op=opname, nbytes=nbytes, dtype=dtype, groups=groups,
            name=name, line=i))
    for name in pending_order:
        rec = pending_starts[name]
        parse.problems.append({"kind": "unmatched-async-start",
                               "op": rec.op, "name": name, "line": rec.line})
        parse.ops.append(rec)  # it is still issued — keep schedule order
    parse.ops.sort(key=lambda o: o.line)
    return parse


def collective_census(hlo_text: str) -> List[dict]:
    """Census-artifact view: op/bytes/groups/dtype dicts in schedule
    order — the exact rows ``bench_allreduce.py --census`` commits."""
    return [o.as_census_dict() for o in parse_hlo_collectives(hlo_text).ops]


__all__ = ["HLO_DTYPE_BYTES", "COLLECTIVE_KINDS", "HloCollective",
           "HloParse", "parse_hlo_collectives", "collective_census"]
