"""Static protocol model of the host object plane — the control-plane
side of cmn-lint.

Every hang the flight recorder / watchdog stack has ever diagnosed was a
*host-side* lockstep violation: a rank-guarded ``bcast_obj``, a tag
crossing wires, a send with no recv.  The data-plane rules see none of
that — they analyze jaxpr/HLO collectives, and the object plane
(``send_obj``/``recv_obj``/``bcast_obj``/... over DCN) never appears in
a trace.  This module recovers the missing half **statically**: an AST
walk over the package extracts every control-plane call site, resolves
its tag expression (constants, named registry tags, ``tag + 1``
arithmetic as used by ``allgather_obj``), its root, the enclosing rank
guards and exception paths, and the thread context, into a serializable
:class:`ProtocolModel` the protocol rules in ``rules.py`` check:

* **tag-band-collision** — resolved tag intervals from two subsystems
  intersect (including arithmetic neighbors), or a magic number lands in
  a reserved band it does not own (``RESERVED_TAG_BANDS``).
* **lockstep-divergence** — a collective object op reachable under a
  rank guard or except-branch with no matching call on the complementary
  path: the static twin of ``identify_desync``.
* **unmatched-send-recv** — a p2p send with no structurally matching
  recv on the same (plane, tag).
* **wrapper-surface-drift** — a wrapper class forwarding object ops
  while dropping parameters the wrapped surface accepts (the
  ``InstrumentedCommunicator`` tag-drop bug, generically).

:func:`replay_flight` projects a recorded flight dump's per-rank
object-plane event sequence against the model, so ``elastic_run``
incident manifests can be triaged as protocol violations
(``cmn_lint --protocol --events``).
"""

from __future__ import annotations

import ast
import json
import os
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence, Tuple

#: The object-plane API surface (matches InstrumentedCommunicator._OBJECT_OPS
#: plus the dedicated telemetry entry point).
OBJECT_OPS = ("send_obj", "recv_obj", "bcast_obj", "gather_obj",
              "allgather_obj", "scatter_obj", "allreduce_obj", "barrier",
              "gather_telemetry")

P2P_OPS = frozenset({"send_obj", "recv_obj"})
COLLECTIVE_OPS = frozenset(OBJECT_OPS) - P2P_OPS

#: Arithmetic tag consumers: a call at ``tag`` also uses ``tag + 1``
#: (allgather/allreduce fold+bcast; barrier rides allgather).
_ARITHMETIC_OPS = frozenset({"allgather_obj", "allreduce_obj", "barrier"})

#: op -> (positional index of the tag argument, default tag) — index
#: counts call arguments (receiver excluded).  ``None`` index: the op
#: has no tag parameter (gather_telemetry pins TELEMETRY_TAG itself).
_TAG_ARG: Dict[str, Tuple[Optional[int], Optional[int]]] = {
    "send_obj": (2, 0),
    "recv_obj": (1, 0),
    "bcast_obj": (2, 0),
    "gather_obj": (2, 0),
    "allgather_obj": (1, 0),
    "scatter_obj": (2, 0),
    "allreduce_obj": (2, 0),
    "barrier": (0, 900),
    "gather_telemetry": (None, None),
}

#: op -> positional index of the root argument (None: no root).
_ROOT_ARG: Dict[str, Optional[int]] = {
    "send_obj": None, "recv_obj": None, "bcast_obj": 1, "gather_obj": 1,
    "allgather_obj": None, "scatter_obj": 1, "allreduce_obj": None,
    "barrier": None, "gather_telemetry": 1,
}

#: Raw-transport surface: ``<transport>.send(dest, tag, payload)`` /
#: ``<transport>.recv(source, tag, ...)`` — the watchdog's FLIGHT_TAG
#: path bypasses the object plane and goes straight to the framing core.
_RAW_OPS = {"send": 1, "recv": 1}  # op -> tag positional index
_RAW_RECEIVERS = ("_tp", "transport", "_transport")


def _registry_bands():
    from chainermn_tpu.runtime.control_plane import RESERVED_TAG_BANDS
    return RESERVED_TAG_BANDS


def _reserved_tag_value(name: str) -> Optional[int]:
    band = _registry_bands().get(name)
    return None if band is None else band.base


# ---------------------------------------------------------------------------
# model dataclasses
# ---------------------------------------------------------------------------

@dataclass
class CallSite:
    """One static control-plane call site."""
    op: str
    file: str                      # path relative to the scanned root
    line: int
    subsystem: str                 # first directory component under root
    qualname: str                  # enclosing def/class chain ("" = module)
    cls: str = ""                  # enclosing class name, if any
    receiver: str = ""             # source of the object the op is called on
    raw: bool = False              # raw transport send/recv (not object plane)
    tag: Dict[str, Any] = field(default_factory=dict)
    width: int = 1                 # tags consumed: tag .. tag + width - 1
    root: Optional[int] = None
    guards: List[dict] = field(default_factory=list)   # enclosing If chain
    trys: List[dict] = field(default_factory=list)     # enclosing Try chain
    thread: bool = False           # enclosing function is a Thread target

    @property
    def collective(self) -> bool:
        return not self.raw and self.op in COLLECTIVE_OPS

    @property
    def rank_guards(self) -> List[dict]:
        return [g for g in self.guards if g.get("rank_guard")]

    def tag_interval(self) -> Optional[Tuple[int, int]]:
        """[start, stop) of resolved const tags, else None."""
        if self.tag.get("kind") != "const":
            return None
        base = self.tag["value"]
        return (base, base + self.width)

    def as_dict(self) -> dict:
        return {
            "op": self.op, "file": self.file, "line": self.line,
            "subsystem": self.subsystem, "qualname": self.qualname,
            "cls": self.cls, "receiver": self.receiver, "raw": self.raw,
            "tag": dict(self.tag), "width": self.width, "root": self.root,
            "guards": list(self.guards), "trys": list(self.trys),
            "thread": self.thread,
        }

    @classmethod
    def from_dict(cls, d: dict) -> "CallSite":
        return cls(**d)

    def where(self) -> str:
        ctx = f" in {self.qualname}" if self.qualname else ""
        return f"{self.file}:{self.line}{ctx}"


@dataclass
class ClassOpDef:
    """One object-plane method definition on a class: its accepted
    parameters, and — when the body forwards the same op to a wrapped
    attribute (``self._comm.bcast_obj(...)``) — which parameters actually
    make it across the forwarding boundary."""
    cls: str
    op: str
    file: str
    line: int
    params: List[str] = field(default_factory=list)           # after self
    optional_params: List[str] = field(default_factory=list)  # with defaults
    forwards_to: str = ""          # "" = an implementation, not a wrapper
    forwarded_params: List[str] = field(default_factory=list)

    def as_dict(self) -> dict:
        return {"cls": self.cls, "op": self.op, "file": self.file,
                "line": self.line, "params": list(self.params),
                "optional_params": list(self.optional_params),
                "forwards_to": self.forwards_to,
                "forwarded_params": list(self.forwarded_params)}

    @classmethod
    def from_dict(cls, d: dict) -> "ClassOpDef":
        return cls(**d)


@dataclass
class ProtocolModel:
    """Serializable whole-tree protocol model (``protocol_model/v1``)."""
    root: str
    sites: List[CallSite] = field(default_factory=list)
    class_ops: List[ClassOpDef] = field(default_factory=list)
    errors: List[dict] = field(default_factory=list)  # unparseable files

    def collectives(self) -> List[CallSite]:
        return [s for s in self.sites if s.collective]

    def p2p(self) -> List[CallSite]:
        return [s for s in self.sites
                if s.raw or s.op in P2P_OPS]

    def to_json(self) -> dict:
        return {
            "schema": "protocol_model/v1",
            "root": self.root,
            "sites": [s.as_dict() for s in self.sites],
            "class_ops": [c.as_dict() for c in self.class_ops],
            "errors": list(self.errors),
            "bands": [b.as_dict() for b in _registry_bands().values()],
        }

    @classmethod
    def from_json(cls, doc: dict) -> "ProtocolModel":
        return cls(root=doc.get("root", ""),
                   sites=[CallSite.from_dict(d) for d in doc["sites"]],
                   class_ops=[ClassOpDef.from_dict(d)
                              for d in doc.get("class_ops", [])],
                   errors=list(doc.get("errors", [])))


# ---------------------------------------------------------------------------
# pass 1 — module-level integer constants, resolved across modules
# ---------------------------------------------------------------------------

def _const_eval(node: ast.AST, env: Dict[str, int],
                aliases: Dict[str, str],
                modules: Dict[str, "_Module"]) -> Optional[int]:
    """Evaluate simple integer expressions: literals, known names,
    ``a.B`` through module aliases, +,-,*,<<,>>,| arithmetic, and
    ``reserved_tag("name")`` via the real registry."""
    if isinstance(node, ast.Constant) and isinstance(node.value, int) \
            and not isinstance(node.value, bool):
        return node.value
    if isinstance(node, ast.Name):
        return env.get(node.id)
    if isinstance(node, ast.Attribute) and isinstance(node.value, ast.Name):
        modkey = aliases.get(node.value.id)
        if modkey is not None and modkey in modules:
            return modules[modkey].env.get(node.attr)
        return None
    if isinstance(node, ast.BinOp):
        left = _const_eval(node.left, env, aliases, modules)
        right = _const_eval(node.right, env, aliases, modules)
        if left is None or right is None:
            return None
        return _apply_binop(node.op, left, right)
    if isinstance(node, ast.Call):
        fname = ""
        if isinstance(node.func, ast.Name):
            fname = node.func.id
        elif isinstance(node.func, ast.Attribute):
            fname = node.func.attr
        if fname.lstrip("_") == "reserved_tag" and len(node.args) == 1 \
                and isinstance(node.args[0], ast.Constant):
            return _reserved_tag_value(node.args[0].value)
        return None
    if isinstance(node, ast.UnaryOp) and isinstance(node.op, ast.USub):
        val = _const_eval(node.operand, env, aliases, modules)
        return None if val is None else -val
    return None


def _apply_binop(op: ast.operator, left: int, right: int) -> Optional[int]:
    if isinstance(op, ast.Add):
        return left + right
    if isinstance(op, ast.Sub):
        return left - right
    if isinstance(op, ast.Mult):
        return left * right
    if isinstance(op, ast.LShift):
        return left << right
    if isinstance(op, ast.RShift):
        return left >> right
    if isinstance(op, ast.BitOr):
        return left | right
    return None


class _Module:
    def __init__(self, path: str, rel: str, tree: ast.Module):
        self.path = path
        self.rel = rel
        self.tree = tree
        self.env: Dict[str, int] = {}          # name -> resolved int
        self.aliases: Dict[str, str] = {}      # local alias -> module key
        self.from_imports: Dict[str, Tuple[str, str]] = {}  # name->(mod,name)
        self.assigns: List[Tuple[str, ast.AST]] = []
        self.thread_targets: set = set()
        self._scan_toplevel()

    def _modkey(self, module: Optional[str], level: int) -> str:
        """Normalize an import to a key comparable across the tree: the
        trailing module path (absolute and relative imports of the same
        module collide on purpose)."""
        return module or ""

    def _scan_toplevel(self):
        for node in self.tree.body:
            self._scan_stmt(node)
        # function-local from-imports still bind constants worth seeing
        # (point_to_point_communication imports reserved_tag mid-module)
        for node in ast.walk(self.tree):
            if isinstance(node, ast.ImportFrom) and node.module:
                for a in node.names:
                    self.from_imports.setdefault(
                        a.asname or a.name, (node.module, a.name))
            elif isinstance(node, ast.Import):
                for a in node.names:
                    self.aliases.setdefault(a.asname or a.name, a.name)
            elif isinstance(node, ast.Call):
                self._scan_thread(node)

    def _scan_stmt(self, node: ast.stmt):
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name):
            self.assigns.append((node.targets[0].id, node.value))
        elif isinstance(node, ast.AnnAssign) and node.value is not None \
                and isinstance(node.target, ast.Name):
            self.assigns.append((node.target.id, node.value))

    def _scan_thread(self, call: ast.Call):
        fname = ""
        if isinstance(call.func, ast.Name):
            fname = call.func.id
        elif isinstance(call.func, ast.Attribute):
            fname = call.func.attr
        if fname != "Thread":
            return
        for kw in call.keywords:
            if kw.arg != "target":
                continue
            if isinstance(kw.value, ast.Name):
                self.thread_targets.add(kw.value.id)
            elif isinstance(kw.value, ast.Attribute):
                self.thread_targets.add(kw.value.attr)


def _resolve_constants(modules: Dict[str, _Module]) -> None:
    """Fixpoint resolution of module-level int constants across the tree
    (handles chains like FLIGHT_TAG = reserved_tag(...) imported
    elsewhere)."""
    by_suffix: Dict[str, List[_Module]] = {}
    for key, mod in modules.items():
        for i in range(len(key.split("."))):
            by_suffix.setdefault(".".join(key.split(".")[i:]), []).append(mod)

    def find_module(name: str) -> Optional[_Module]:
        cands = by_suffix.get(name) or by_suffix.get(name.split(".")[-1])
        return cands[0] if cands else None

    for _ in range(4):
        changed = False
        for mod in modules.values():
            # pull in from-imported constants resolved elsewhere
            for local, (src, orig) in mod.from_imports.items():
                if local in mod.env:
                    continue
                src_mod = find_module(src)
                if src_mod is not None and orig in src_mod.env:
                    mod.env[local] = src_mod.env[orig]
                    changed = True
            # resolve module aliases to canonical keys
            alias_map = {}
            for alias, target in mod.aliases.items():
                tgt = find_module(target)
                alias_map[alias] = tgt.rel_key if tgt else target
            for name, expr in mod.assigns:
                if name in mod.env:
                    continue
                val = _const_eval(expr, mod.env, alias_map,
                                  {m.rel_key: m for m in modules.values()})
                if val is not None:
                    mod.env[name] = val
                    changed = True
        if not changed:
            break


# ---------------------------------------------------------------------------
# pass 2 — call-site extraction
# ---------------------------------------------------------------------------

def _src(node: ast.AST) -> str:
    try:
        return ast.unparse(node)
    except Exception:  # noqa: BLE001 — display only
        return "<expr>"


def _mentions_rank(node: ast.AST) -> bool:
    for sub in ast.walk(node):
        if isinstance(sub, ast.Attribute) and sub.attr in (
                "rank", "inter_rank", "intra_rank"):
            return True
        if isinstance(sub, ast.Name) and sub.id == "rank":
            return True
        if isinstance(sub, ast.Call):
            f = sub.func
            if isinstance(f, ast.Attribute) and f.attr == "process_index":
                return True
    return False


def _is_rank_guard(test: ast.AST) -> bool:
    """True when the If test *compares* a rank expression — the shape of
    every root-only branch (``if rank == 0``, ``if comm.rank != root``).
    Size/flag guards (``if multi:``, ``if host_size > 1``) and bit tricks
    on derived vranks are not rank guards."""
    for sub in ast.walk(test):
        if isinstance(sub, ast.Compare):
            sides = [sub.left] + list(sub.comparators)
            if any(_mentions_rank(s) for s in sides):
                return True
    return False


class _Extractor:
    def __init__(self, mod: _Module, root: str,
                 modules: Dict[str, _Module]):
        self.mod = mod
        self.root = root
        self.modules = modules
        self.alias_map = {}
        for alias, target in mod.aliases.items():
            self.alias_map[alias] = target
        self.sites: List[CallSite] = []
        self.class_ops: List[ClassOpDef] = []
        rel = mod.rel
        parts = rel.split(os.sep)
        self.subsystem = parts[0] if len(parts) > 1 else \
            os.path.splitext(parts[0])[0]

    # -- scope-carrying recursion ---------------------------------------

    def run(self):
        self._walk_body(self.mod.tree.body, func_stack=(), cls="",
                        params=frozenset(), guards=(), trys=())

    def _walk_body(self, body, **ctx):
        for node in body:
            self._walk(node, **ctx)

    def _walk(self, node, func_stack, cls, params, guards, trys):
        ctx = dict(func_stack=func_stack, cls=cls, params=params,
                   guards=guards, trys=trys)
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            if cls and not func_stack and node.name in OBJECT_OPS:
                self._record_class_op(node, cls)
            new_params = frozenset(
                a.arg for a in (node.args.posonlyargs + node.args.args
                                + node.args.kwonlyargs)
                if a.arg != "self")
            self._walk_body(node.body, func_stack=func_stack + (node.name,),
                            cls=cls, params=params | new_params,
                            guards=guards, trys=trys)
            return
        if isinstance(node, ast.ClassDef):
            self._walk_body(node.body, func_stack=(), cls=node.name,
                            params=frozenset(), guards=(), trys=())
            return
        if isinstance(node, ast.If):
            info = {"line": node.lineno, "test": _src(node.test),
                    "rank_guard": _is_rank_guard(node.test)}
            self._walk_body(node.body, func_stack=func_stack, cls=cls,
                            params=params,
                            guards=guards + (dict(info, branch="body"),),
                            trys=trys)
            self._walk_body(node.orelse, func_stack=func_stack, cls=cls,
                            params=params,
                            guards=guards + (dict(info, branch="orelse"),),
                            trys=trys)
            self._visit_exprs(node.test, **ctx)
            return
        if isinstance(node, ast.Try):
            tinfo = {"line": node.lineno}
            self._walk_body(node.body, func_stack=func_stack, cls=cls,
                            params=params, guards=guards,
                            trys=trys + (dict(tinfo, branch="try"),))
            for handler in node.handlers:
                self._walk_body(handler.body, func_stack=func_stack,
                                cls=cls, params=params, guards=guards,
                                trys=trys + (dict(tinfo, branch="except"),))
            self._walk_body(node.orelse, func_stack=func_stack, cls=cls,
                            params=params, guards=guards,
                            trys=trys + (dict(tinfo, branch="try"),))
            self._walk_body(node.finalbody, func_stack=func_stack, cls=cls,
                            params=params, guards=guards,
                            trys=trys + (dict(tinfo, branch="finally"),))
            return
        if isinstance(node, (ast.For, ast.AsyncFor, ast.While, ast.With,
                             ast.AsyncWith)):
            for child in ast.iter_child_nodes(node):
                if isinstance(child, ast.stmt):
                    self._walk(child, **ctx)
                else:
                    self._visit_exprs(child, **ctx)
            return
        # plain statement: scan its expressions for call sites (including
        # lambdas — instrument.py forwards inside ``lambda:`` thunks)
        self._visit_exprs(node, **ctx)

    def _visit_exprs(self, node, func_stack, cls, params, guards, trys):
        for sub in ast.walk(node):
            if isinstance(sub, ast.Lambda):
                lam_params = frozenset(
                    a.arg for a in (sub.args.posonlyargs + sub.args.args
                                    + sub.args.kwonlyargs))
                params = params | lam_params
            if isinstance(sub, ast.Call):
                self._maybe_site(sub, func_stack, cls, params, guards, trys)

    # -- call-site recording --------------------------------------------

    def _maybe_site(self, call: ast.Call, func_stack, cls, params,
                    guards, trys):
        func = call.func
        if not isinstance(func, ast.Attribute):
            return
        op = func.attr
        recv_src = _src(func.value)
        raw = False
        if op in _RAW_OPS:
            # raw transport only: .send/.recv on a transport-ish receiver
            leaf = recv_src.split(".")[-1]
            if leaf not in _RAW_RECEIVERS:
                return
            raw = True
        elif op not in OBJECT_OPS:
            return
        site = CallSite(
            op=op, raw=raw, file=self.mod.rel, line=call.lineno,
            subsystem=self.subsystem,
            qualname=".".join(filter(None, (cls,) + func_stack)),
            cls=cls, receiver=recv_src,
            guards=[dict(g) for g in guards],
            trys=[dict(t) for t in trys],
            thread=any(f in self.mod.thread_targets for f in func_stack),
        )
        site.width = 2 if (not raw and op in _ARITHMETIC_OPS) else 1
        site.tag = self._resolve_tag(call, op, raw, params)
        site.root = self._resolve_root(call, op, raw)
        self.sites.append(site)

    def _tag_expr(self, call: ast.Call, op: str, raw: bool):
        idx = _RAW_OPS[op] if raw else _TAG_ARG[op][0]
        if idx is None:
            return None, None
        for kw in call.keywords:
            if kw.arg == "tag":
                return kw.value, idx
        if idx < len(call.args):
            arg = call.args[idx]
            if isinstance(arg, ast.Starred):
                return None, idx
            return arg, idx
        return None, idx

    def _resolve_tag(self, call: ast.Call, op: str, raw: bool,
                     params: frozenset) -> Dict[str, Any]:
        if not raw and op == "gather_telemetry":
            return {"kind": "const",
                    "value": _reserved_tag_value("telemetry"),
                    "provenance": "named", "source": "TELEMETRY_TAG"}
        expr, _ = self._tag_expr(call, op, raw)
        if expr is None:
            if raw:
                return {"kind": "dynamic", "source": "<missing>"}
            default = _TAG_ARG[op][1]
            return {"kind": "const", "value": default,
                    "provenance": "default", "source": str(default)}
        return self._eval_tag(expr, params)

    def _eval_tag(self, expr: ast.AST, params: frozenset) -> Dict[str, Any]:
        source = _src(expr)
        val = _const_eval(expr, self.mod.env, self.alias_map, {
            m.rel_key: m for m in self.modules.values()})
        if val is not None:
            provenance = "literal" if isinstance(expr, ast.Constant) \
                else "named"
            return {"kind": "const", "value": val,
                    "provenance": provenance, "source": source}
        # param / base + param forms
        if isinstance(expr, ast.Name) and expr.id in params:
            return {"kind": "param", "base": 0, "param": expr.id,
                    "source": source}
        if isinstance(expr, ast.BinOp) and isinstance(
                expr.op, (ast.Add, ast.Sub)):
            for const_side, name_side in ((expr.left, expr.right),
                                          (expr.right, expr.left)):
                base = _const_eval(const_side, self.mod.env, self.alias_map,
                                   {m.rel_key: m
                                    for m in self.modules.values()})
                if base is not None and isinstance(name_side, ast.Name) \
                        and name_side.id in params:
                    if isinstance(expr.op, ast.Sub):
                        if name_side is expr.left:
                            return {"kind": "param", "base": -base,
                                    "param": name_side.id, "source": source}
                        return {"kind": "dynamic", "source": source}
                    return {"kind": "param", "base": base,
                            "param": name_side.id, "source": source}
        return {"kind": "dynamic", "source": source}

    def _resolve_root(self, call: ast.Call, op: str,
                      raw: bool) -> Optional[int]:
        if raw:
            return None
        idx = _ROOT_ARG.get(op)
        expr = None
        for kw in call.keywords:
            if kw.arg == "root":
                expr = kw.value
        if expr is None and idx is not None and idx < len(call.args):
            expr = call.args[idx]
        if expr is None:
            return 0 if idx is not None else None
        return _const_eval(expr, self.mod.env, self.alias_map,
                           {m.rel_key: m for m in self.modules.values()})

    # -- class surface recording ----------------------------------------

    def _record_class_op(self, fn: ast.FunctionDef, cls: str):
        args = fn.args
        names = [a.arg for a in args.posonlyargs + args.args
                 if a.arg != "self"]
        n_opt = len(args.defaults)
        optional = names[len(names) - n_opt:] if n_opt else []
        kw_names = [a.arg for a in args.kwonlyargs]
        for a, d in zip(args.kwonlyargs, args.kw_defaults):
            if d is not None:
                optional.append(a.arg)
        names += kw_names
        forwards_to, forwarded = "", []
        for sub in ast.walk(fn):
            if not isinstance(sub, ast.Call):
                continue
            f = sub.func
            if not (isinstance(f, ast.Attribute) and f.attr == fn.name):
                continue
            if not (isinstance(f.value, ast.Attribute)
                    and isinstance(f.value.value, ast.Name)
                    and f.value.value.id == "self"):
                continue
            forwards_to = f.value.attr
            used = {n.id for arg in list(sub.args) + [
                kw.value for kw in sub.keywords]
                for n in ast.walk(arg) if isinstance(n, ast.Name)}
            forwarded = [p for p in names if p in used]
            break
        self.class_ops.append(ClassOpDef(
            cls=cls, op=fn.name, file=self.mod.rel, line=fn.lineno,
            params=names, optional_params=optional,
            forwards_to=forwards_to, forwarded_params=forwarded))


# ---------------------------------------------------------------------------
# entry point
# ---------------------------------------------------------------------------

def extract_protocol(root: Optional[str] = None) -> ProtocolModel:
    """Walk every ``*.py`` under ``root`` (default: the installed
    ``chainermn_tpu`` package) into a :class:`ProtocolModel`."""
    if root is None:
        import chainermn_tpu
        root = os.path.dirname(os.path.abspath(chainermn_tpu.__file__))
    root = os.path.abspath(root)
    modules: Dict[str, _Module] = {}
    errors: List[dict] = []
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames[:] = sorted(d for d in dirnames
                             if d not in ("__pycache__", ".git"))
        for fname in sorted(filenames):
            if not fname.endswith(".py"):
                continue
            path = os.path.join(dirpath, fname)
            rel = os.path.relpath(path, root)
            try:
                with open(path, encoding="utf-8") as fh:
                    tree = ast.parse(fh.read(), filename=path)
            except (SyntaxError, UnicodeDecodeError, OSError) as e:
                errors.append({"file": rel, "error": str(e)})
                continue
            mod = _Module(path, rel, tree)
            mod.rel_key = rel[:-3].replace(os.sep, ".")
            modules[mod.rel_key] = mod
    _resolve_constants(modules)
    model = ProtocolModel(root=root, errors=errors)
    for mod in modules.values():
        ex = _Extractor(mod, root, modules)
        ex.run()
        model.sites.extend(ex.sites)
        model.class_ops.extend(ex.class_ops)
    model.sites.sort(key=lambda s: (s.file, s.line))
    return model


# ---------------------------------------------------------------------------
# replay — project a flight dump against the static model
# ---------------------------------------------------------------------------

def _object_sequences(events_by_rank: Dict[int, Sequence[dict]]):
    """Per-rank (completed op list, open op list) from flight events."""
    out = {}
    for rank, events in events_by_rank.items():
        completed: List[str] = []
        open_spans: Dict[Tuple[str, Any], dict] = {}
        for ev in events:
            kind = ev.get("kind")
            if kind == "object_begin":
                open_spans[(ev.get("op"), ev.get("op_seq"))] = ev
            elif kind == "object_end":
                open_spans.pop((ev.get("op"), ev.get("op_seq")), None)
                completed.append(ev.get("op"))
        out[int(rank)] = (completed, [e.get("op")
                                      for e in open_spans.values()])
    return out


def replay_flight(model: ProtocolModel,
                  events_by_rank: Dict[int, Sequence[dict]]) -> List[dict]:
    """Project recorded per-rank object-plane event sequences against the
    static model.  Returns a list of violation dicts (empty = healthy):

    * ``divergence`` — two ranks completed different op sequences; the
      first differing index is reported with the rank-guarded collective
      sites from the static model as prime suspects.
    * ``straggler`` — a rank is stuck inside an object op (began, never
      finished) while a peer has moved on.
    * ``unknown-op`` — an op name the static model has no call site for
      (a dump from a different build than the tree under analysis).
    """
    seqs = _object_sequences(events_by_rank)
    findings: List[dict] = []
    if not seqs:
        return findings
    known_ops = {s.op for s in model.sites} | set(OBJECT_OPS)
    suspects = [
        {"where": s.where(), "op": s.op,
         "guard": (s.rank_guards or [{}])[-1].get("test", "")}
        for s in model.collectives() if s.rank_guards]
    ranks = sorted(seqs)
    ref_rank = ranks[0]
    ref_completed = seqs[ref_rank][0]
    for rank in ranks[1:]:
        completed = seqs[rank][0]
        n = min(len(ref_completed), len(completed))
        for i in range(n):
            if ref_completed[i] != completed[i]:
                findings.append({
                    "kind": "divergence", "index": i,
                    "ranks": [ref_rank, rank],
                    "ops": [ref_completed[i], completed[i]],
                    "message": (
                        f"rank {ref_rank} completed object op "
                        f"#{i} = {ref_completed[i]!r} but rank {rank} "
                        f"completed {completed[i]!r} — the ranks are "
                        f"running different object-plane programs"),
                    "suspect_sites": suspects,
                })
                break
        else:
            if len(ref_completed) != len(completed):
                ahead, behind = ((ref_rank, rank)
                                 if len(ref_completed) > len(completed)
                                 else (rank, ref_rank))
                longer = max(ref_completed, completed, key=len)
                findings.append({
                    "kind": "divergence", "index": n,
                    "ranks": [behind, ahead],
                    "ops": [None, longer[n]],
                    "message": (
                        f"rank {ahead} completed {abs(len(ref_completed) - len(completed))} "
                        f"more object op(s) than rank {behind} "
                        f"(next: {longer[n]!r}) — rank {behind} never "
                        f"reached a collective its peer entered"),
                    "suspect_sites": suspects,
                })
    for rank in ranks:
        completed, open_ops = seqs[rank]
        peers_ahead = [r for r in ranks
                       if len(seqs[r][0]) > len(completed)]
        if open_ops and peers_ahead:
            findings.append({
                "kind": "straggler", "ranks": [rank],
                "ops": list(open_ops),
                "message": (
                    f"rank {rank} is blocked inside object op(s) "
                    f"{open_ops} while rank(s) {peers_ahead} moved on"),
                "suspect_sites": suspects,
            })
        for op in completed:
            if op not in known_ops:
                findings.append({
                    "kind": "unknown-op", "ranks": [rank], "ops": [op],
                    "message": (
                        f"rank {rank} recorded object op {op!r} with no "
                        f"call site in the static model — dump and tree "
                        f"are from different builds"),
                })
                break
    return findings


def load_events_by_rank(dumps: Any) -> Dict[int, List[dict]]:
    """Normalize flight-dump input into ``{rank: [events]}``.  Accepts a
    ``{rank: events}`` map, a ``{rank: dump_doc}`` map (elastic restart
    manifests embed these), a single dump doc, or a flat event list."""
    if isinstance(dumps, dict) and dumps and all(
            isinstance(v, (list, tuple)) for v in dumps.values()):
        return {int(r): list(v) for r, v in dumps.items()}
    if isinstance(dumps, dict) and "events" in dumps:
        return {int(dumps.get("rank", 0)): list(dumps["events"])}
    if isinstance(dumps, dict):
        out = {}
        for r, doc in dumps.items():
            if isinstance(doc, dict):
                out[int(r)] = list(doc.get("events", []))
            else:
                out[int(r)] = list(doc)
        return out
    return {0: list(dumps or [])}


__all__ = [
    "OBJECT_OPS", "P2P_OPS", "COLLECTIVE_OPS",
    "CallSite", "ClassOpDef", "ProtocolModel",
    "extract_protocol", "replay_flight", "load_events_by_rank",
]
