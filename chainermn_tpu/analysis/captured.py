"""Closure-captured constant audit — the ``captured-constant`` rule core.

Moved here from ``chainermn_tpu.utils.jaxpr_audit`` (which remains as a
deprecation re-export) when the one-off guard was promoted into the
static-analysis subsystem.

Root cause this guards (NEXT.md round 5): the long-context example's
remote-compile request embedded closure-captured device arrays — every
array a traced function closes over becomes a *constant* of its jaxpr,
and constants are serialized into the compile request (HTTP 413 on the
remote-compile tunnel, silent recompiles + HBM duplication elsewhere).
The fix is always the same: pass the array as an explicit argument to
the jitted function.  ``assert_no_captured_constants(step,
*example_args)`` fails with the offending shapes/dtypes and that exact
fix in the message; the lint rule reports the same records as findings.

Scalar/config constants (loop bounds, eps values, small masks) are fine
and unavoidable; only constants above ``max_bytes`` are reported.
"""

from __future__ import annotations

from typing import Any, Dict, List

import jax
import numpy as np

# One 32x32 f32 tile.  Big enough to pass the small lookup tables and
# iota-style constants tracing legitimately bakes in, small enough that
# any real operand (a batch, a parameter leaf) trips it.
DEFAULT_MAX_BYTES = 4096


class CapturedConstantError(ValueError):
    """A traced function closed over array constants above the size
    threshold (see module docstring for why that is a bug)."""


def _const_nbytes(c: Any):
    nb = getattr(c, "nbytes", None)
    if nb is not None:
        return int(nb)
    try:
        return int(np.asarray(c).nbytes)
    except Exception:  # noqa: BLE001 — non-array consts are not operands
        return None


def _iter_closed_jaxprs(closed):
    """The top-level ClosedJaxpr plus every ClosedJaxpr reachable through
    equation params (pjit/scan/cond bodies) — inner calls keep their own
    consts in some jax versions rather than hoisting them to the top."""
    from jax.core import ClosedJaxpr

    stack, seen = [closed], set()
    while stack:
        cj = stack.pop()
        if id(cj) in seen:
            continue
        seen.add(id(cj))
        yield cj
        for eqn in cj.jaxpr.eqns:
            for v in eqn.params.values():
                vs = v if isinstance(v, (list, tuple)) else (v,)
                stack.extend(x for x in vs if isinstance(x, ClosedJaxpr))


def constants_in_jaxpr(closed, max_bytes: int = DEFAULT_MAX_BYTES) \
        -> List[Dict[str, Any]]:
    """Captured-constant records of an already-traced ClosedJaxpr —
    the shared core of :func:`find_captured_constants` and the lint
    rule (which traces once and runs every rule on the same jaxpr)."""
    findings: List[Dict[str, Any]] = []
    seen_ids = set()
    for cj in _iter_closed_jaxprs(closed):
        for c in cj.consts:
            if id(c) in seen_ids:
                continue
            seen_ids.add(id(c))
            nb = _const_nbytes(c)
            if nb is not None and nb > max_bytes:
                findings.append({
                    "shape": tuple(getattr(c, "shape", ())),
                    "dtype": str(getattr(c, "dtype", type(c).__name__)),
                    "nbytes": nb,
                })
    findings.sort(key=lambda f: -f["nbytes"])
    return findings


def find_captured_constants(fn, *args,
                            max_bytes: int = DEFAULT_MAX_BYTES,
                            **kwargs) -> List[Dict[str, Any]]:
    """Trace ``fn(*args, **kwargs)`` and return one record per jaxpr
    constant larger than ``max_bytes``:
    ``{"shape", "dtype", "nbytes"}``, largest first.  Empty list means
    every big operand is an explicit argument."""
    closed = jax.make_jaxpr(fn)(*args, **kwargs)
    return constants_in_jaxpr(closed, max_bytes=max_bytes)


def captured_constant_message(found: List[Dict[str, Any]], label: str,
                              max_bytes: int) -> str:
    lines = "\n".join(
        f"  - {f['dtype']}{list(f['shape'])} ({f['nbytes']} bytes)"
        for f in found)
    return (
        f"{label} closes over {len(found)} array constant(s) larger than "
        f"{max_bytes} bytes:\n{lines}\n"
        "Closure-captured arrays are embedded in the compile request "
        "(remote-compile HTTP 413; recompile-per-value and HBM "
        "duplication everywhere else).  Pass them to the jitted function "
        "as explicit arguments instead of capturing them.")


def assert_no_captured_constants(fn, *args,
                                 max_bytes: int = DEFAULT_MAX_BYTES,
                                 name: str = None,
                                 **kwargs) -> None:
    """Raise :class:`CapturedConstantError` if tracing ``fn`` bakes in
    array constants above ``max_bytes`` (closure-captured operands)."""
    found = find_captured_constants(fn, *args, max_bytes=max_bytes,
                                    **kwargs)
    if not found:
        return
    label = name or getattr(fn, "__name__", repr(fn))
    raise CapturedConstantError(
        captured_constant_message(found, label, max_bytes))


__all__ = ["CapturedConstantError", "DEFAULT_MAX_BYTES",
           "assert_no_captured_constants", "captured_constant_message",
           "constants_in_jaxpr", "find_captured_constants"]
