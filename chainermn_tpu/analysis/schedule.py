"""``CollectiveSchedule`` — the canonical ordered collective list.

A communicator flavor *is* its collective decomposition (SURVEY.md §2.1,
HiCCL's thesis in PAPERS.md), so the unit the static analyzer reasons
about is the ordered list of collectives a program will issue.  Two
extractors produce the same schedule type:

* :func:`extract_schedule` walks a traced ``ClosedJaxpr`` — through
  pjit / shard_map / scan / cond / while / custom_vjp bodies — and
  records every collective primitive (psum, all_gather, psum_scatter,
  ppermute, all_to_all, pmax, pmin) with its axes, dtype, payload, and
  nesting path.  This is the *trace-time* view: it exists before any
  backend is involved, so it runs on CPU with no TPU attached.
* :func:`schedule_from_hlo` reads the *compiled* view out of optimized
  HLO text via :mod:`chainermn_tpu.analysis.hlo` (one parser shared with
  the census gate and artifact).

Schedules canonicalize (:meth:`CollectiveSchedule.canonical`) so that
per-rank / per-config schedules can be compared for the static version
of the flight recorder's ``identify_desync``: two ranks whose canonical
schedules differ WILL wedge the mesh at the first divergence — the lint
rule names that op before anything runs.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, Iterator, List, Optional, Tuple

import jax
import numpy as np

from chainermn_tpu.analysis.hlo import HloParse, parse_hlo_collectives

#: jaxpr primitives that lower to cross-device communication
COLLECTIVE_PRIMITIVES = frozenset({
    "psum", "pmax", "pmin", "ppermute", "pshuffle",
    "all_gather", "all_to_all", "psum_scatter", "pgather",
    "reduce_scatter",
})


@dataclass(frozen=True)
class CollectiveOp:
    """One collective, from either extractor.  ``axes`` is the jaxpr
    axis-name tuple (None for HLO ops); ``groups`` the HLO
    replica_groups text (None for jaxpr ops)."""
    kind: str
    dtype: str
    shape: Tuple[int, ...]
    nbytes: int
    axes: Optional[Tuple[str, ...]] = None
    groups: Optional[str] = None
    path: Tuple[str, ...] = ()
    source: Optional[str] = None

    @property
    def key(self) -> tuple:
        """Order-sensitive identity used for schedule comparison: kind,
        where it communicates (axes or groups), and what it moves."""
        return (self.kind, self.axes or self.groups, self.dtype,
                self.nbytes)

    def describe(self) -> str:
        where = ("axes=" + ",".join(self.axes) if self.axes
                 else f"groups={self.groups}" if self.groups else "?")
        return (f"{self.kind}[{self.dtype}, {self.nbytes}B, {where}]"
                + (f" @ {'/'.join(self.path)}" if self.path else ""))


@dataclass
class CollectiveSchedule:
    """Ordered collectives of one traced/compiled program."""
    ops: List[CollectiveOp] = field(default_factory=list)
    origin: str = "jaxpr"            # "jaxpr" | "hlo"
    label: str = ""                  # e.g. "rank0", "flavor=xla"
    problems: List[dict] = field(default_factory=list)

    def __len__(self) -> int:
        return len(self.ops)

    def __iter__(self) -> Iterator[CollectiveOp]:
        return iter(self.ops)

    def canonical(self) -> Tuple[tuple, ...]:
        return tuple(op.key for op in self.ops)

    def kinds(self) -> Tuple[str, ...]:
        return tuple(op.kind for op in self.ops)

    def count_by_kind(self) -> Dict[str, int]:
        out: Dict[str, int] = {}
        for op in self.ops:
            out[op.kind] = out.get(op.kind, 0) + 1
        return out

    def counts_by_axes(self, kind: str) -> Dict[tuple, int]:
        out: Dict[tuple, int] = {}
        for op in self.ops:
            if op.kind == kind:
                k = op.axes or (op.groups,)
                out[k] = out.get(k, 0) + 1
        return out

    def diff(self, other: "CollectiveSchedule") -> Optional[dict]:
        """First structural divergence against ``other`` (None when the
        canonical schedules agree) — op index, and each side's op (or
        None past the shorter schedule's end)."""
        a, b = self.canonical(), other.canonical()
        if a == b:
            return None
        for i in range(max(len(a), len(b))):
            if i >= len(a) or i >= len(b) or a[i] != b[i]:
                return {
                    "index": i,
                    "left": self.ops[i].describe() if i < len(a) else None,
                    "right": other.ops[i].describe() if i < len(b) else None,
                    "left_label": self.label,
                    "right_label": other.label,
                }
        return None  # pragma: no cover — unreachable given a != b


def _sub_jaxprs(eqn) -> List[Tuple[str, Any]]:
    """(tag, jaxpr-like) children reachable through this equation's
    params — ClosedJaxprs (pjit/scan/cond bodies) and raw Jaxprs
    (shard_map)."""
    from jax.core import ClosedJaxpr

    out: List[Tuple[str, Any]] = []
    for pname, v in eqn.params.items():
        vals = v if isinstance(v, (list, tuple)) else (v,)
        for j, x in enumerate(vals):
            if isinstance(x, ClosedJaxpr) or hasattr(x, "eqns"):
                tag = eqn.primitive.name
                if isinstance(v, (list, tuple)) and len(vals) > 1:
                    tag = f"{tag}[{pname}{j}]"
                out.append((tag, x))
    return out


def _aval_payload(eqn) -> Tuple[str, Tuple[int, ...], int]:
    """(dtype, shape, nbytes) across an equation's array inputs."""
    dtype, shape, nbytes = "?", (), 0
    for var in eqn.invars:
        aval = getattr(var, "aval", None)
        if aval is None or not hasattr(aval, "dtype"):
            continue
        if dtype == "?":
            dtype = str(np.dtype(aval.dtype).name)
            shape = tuple(int(d) for d in getattr(aval, "shape", ()))
        try:
            nbytes += int(np.prod(aval.shape or (1,))
                          * np.dtype(aval.dtype).itemsize)
        except Exception:  # noqa: BLE001 — abstract dims etc. stay 0
            pass
    return dtype, shape, nbytes


def _eqn_axes(eqn) -> Optional[Tuple[str, ...]]:
    ax = eqn.params.get("axes", eqn.params.get("axis_name"))
    if ax is None:
        return None
    if isinstance(ax, (list, tuple)):
        return tuple(str(a) for a in ax)
    return (str(ax),)


def _eqn_source(eqn) -> Optional[str]:
    try:
        frame = jax.api_util.summarize_source_info(eqn.source_info)  # 0.5+
    except Exception:  # noqa: BLE001
        try:
            from jax._src import source_info_util
            frame = source_info_util.summarize(eqn.source_info)
        except Exception:  # noqa: BLE001
            frame = None
    return frame


def extract_schedule(fn_or_jaxpr, *args, label: str = "",
                     **kwargs) -> CollectiveSchedule:
    """Trace-time schedule of a function (traced via ``jax.make_jaxpr``)
    or of an already-traced ``ClosedJaxpr``.

    The walk descends through every jaxpr reachable from equation params
    — pjit, shard_map, scan, while, cond branches, custom_vjp/jvp bodies
    — so collectives hidden inside control flow or custom-derivative
    wrappers are all visible.  Both branches of a ``cond`` appear in the
    schedule (tagged in ``path``): a collective in only one branch is
    exactly the divergence hazard the desync rule exists to catch.
    """
    from jax.core import ClosedJaxpr

    closed = fn_or_jaxpr
    if not (isinstance(closed, ClosedJaxpr) or hasattr(closed, "eqns")):
        closed = jax.make_jaxpr(fn_or_jaxpr)(*args, **kwargs)
    sched = CollectiveSchedule(origin="jaxpr", label=label)
    seen: set = set()

    def walk(jaxpr_like, path: Tuple[str, ...]):
        if id(jaxpr_like) in seen:
            return
        seen.add(id(jaxpr_like))
        jaxpr = getattr(jaxpr_like, "jaxpr", jaxpr_like)
        for eqn in jaxpr.eqns:
            name = eqn.primitive.name
            if name in COLLECTIVE_PRIMITIVES:
                dtype, shape, nbytes = _aval_payload(eqn)
                sched.ops.append(CollectiveOp(
                    kind=name, dtype=dtype, shape=shape, nbytes=nbytes,
                    axes=_eqn_axes(eqn), path=path,
                    source=_eqn_source(eqn)))
            for tag, sub in _sub_jaxprs(eqn):
                walk(sub, path + (tag,))

    walk(closed, ())
    return sched


def schedule_from_hlo(hlo_text_or_parse, label: str = "") \
        -> CollectiveSchedule:
    """Compiled-view schedule from optimized HLO text (or a pre-built
    :class:`~chainermn_tpu.analysis.hlo.HloParse`).  Parse problems
    (unmatched async halves) ride along in ``problems`` for the
    ``async-pair`` rule."""
    parse = hlo_text_or_parse
    if not isinstance(parse, HloParse):
        parse = parse_hlo_collectives(parse)
    sched = CollectiveSchedule(origin="hlo", label=label,
                               problems=list(parse.problems))
    for o in parse.ops:
        sched.ops.append(CollectiveOp(
            kind=o.op, dtype=o.dtype or "?", shape=(), nbytes=o.nbytes,
            groups=o.groups, source=o.name or None))
    return sched


__all__ = ["COLLECTIVE_PRIMITIVES", "CollectiveOp", "CollectiveSchedule",
           "extract_schedule", "schedule_from_hlo"]
