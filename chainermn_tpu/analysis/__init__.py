"""cmn-lint — trace-time SPMD static analysis.

Every hang class the runtime observability stack (flight recorder, hang
watchdog — PR 2) diagnoses *after* a mesh is wedged is statically
visible in the jaxpr/HLO before a single step runs.  This package is
that check: a :class:`CollectiveSchedule` extractor over traced jaxprs
and compiled HLO, a rule registry (``schedule-desync``,
``census-drift``, ``unpinned-transpose``, ``captured-constant``,
``donation-alias``, ``wire-dtype-mismatch``, ``async-pair``), the
:func:`lint_step` one-liner, and the named entry points behind
``tools/cmn_lint.py``.  Rule catalog: ``docs/static_analysis.md``.

The *control-plane* half lives in ``analysis/protocol.py``: an AST
protocol model of every host object-plane call site (tags, roots, rank
guards, exception paths) feeding the ``tag-band-collision``,
``lockstep-divergence``, ``unmatched-send-recv``,
``wrapper-surface-drift``, and ``protocol-replay-desync`` rules —
``cmn_lint --protocol``.
"""

from chainermn_tpu.analysis.captured import (
    CapturedConstantError,
    DEFAULT_MAX_BYTES,
    assert_no_captured_constants,
    find_captured_constants,
)
from chainermn_tpu.analysis.hlo import (
    HloCollective,
    HloParse,
    collective_census,
    parse_hlo_collectives,
)
from chainermn_tpu.analysis.lint import (
    LintContext,
    LintError,
    LintReport,
    allreduce_hlo,
    build_grad_probe,
    lint_step,
)
from chainermn_tpu.analysis.protocol import (
    CallSite,
    ProtocolModel,
    extract_protocol,
    load_events_by_rank,
    replay_flight,
)
from chainermn_tpu.analysis.rules import (
    Finding,
    all_rules,
    expected_kinds,
    get_rule,
    rule,
)
from chainermn_tpu.analysis.schedule import (
    COLLECTIVE_PRIMITIVES,
    CollectiveOp,
    CollectiveSchedule,
    extract_schedule,
    schedule_from_hlo,
)

__all__ = [
    "COLLECTIVE_PRIMITIVES", "CallSite", "CapturedConstantError",
    "CollectiveOp", "CollectiveSchedule", "DEFAULT_MAX_BYTES",
    "Finding", "HloCollective", "HloParse",
    "LintContext", "LintError", "LintReport", "ProtocolModel", "all_rules",
    "allreduce_hlo", "assert_no_captured_constants", "build_grad_probe",
    "collective_census", "expected_kinds", "extract_protocol",
    "extract_schedule", "find_captured_constants", "get_rule",
    "lint_step", "load_events_by_rank", "parse_hlo_collectives",
    "replay_flight", "rule", "schedule_from_hlo",
]
