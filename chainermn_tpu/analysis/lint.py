"""``lint_step`` — run every applicable cmn-lint rule on one train step.

The one-line self-check the tentpole asks for::

    from chainermn_tpu.analysis import lint_step
    lint_step(step, params, opt_state, batch, comm=comm,
              loss=loss_fn, loss_args=(params, batch))

traces the step ONCE (jaxpr), compiles it ONCE (HLO, skipped with
``hlo=False``), derives the auxiliary probes each rule needs (the
in-SPMD gradient probe for ``unpinned-transpose``, the per-flavor
compiled allreduce for ``census-drift``), runs the registry, and raises
:class:`LintError` on any error-severity finding (``raise_on_error=False``
returns the :class:`LintReport` instead — the CLI's path).

Inputs a rule needs that the caller did not provide make the rule
*skipped with a reason*, never a crash: ``lint_step(step, *args)`` with
nothing else still runs ``captured-constant`` / ``donation-alias`` /
``async-pair`` and reports the rest as skipped.
"""

from __future__ import annotations

import json
import os
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

from chainermn_tpu.analysis.captured import DEFAULT_MAX_BYTES
from chainermn_tpu.analysis.rules import Finding, all_rules, get_rule
from chainermn_tpu.analysis.schedule import (
    CollectiveSchedule, extract_schedule, schedule_from_hlo)

_UNSET = object()


class LintError(AssertionError):
    """One or more error-severity lint findings.  The message is the
    rendered report; ``report`` carries the structured findings."""

    def __init__(self, report: "LintReport"):
        self.report = report
        super().__init__(report.render_text())


@dataclass
class LintReport:
    """Findings plus per-rule skip reasons for one linted target."""
    target: str = ""
    findings: List[Finding] = field(default_factory=list)
    skipped: Dict[str, str] = field(default_factory=dict)

    @property
    def errors(self) -> List[Finding]:
        return [f for f in self.findings if f.severity == "error"]

    @property
    def ok(self) -> bool:
        return not self.errors

    def to_json(self) -> dict:
        return {
            "suite": "cmn_lint",
            "target": self.target,
            "ok": self.ok,
            "findings": [f.as_dict() for f in self.findings],
            "skipped": dict(self.skipped),
        }

    def render_text(self) -> str:
        lines = [f"cmn-lint: {self.target or '<anonymous step>'} — "
                 f"{len(self.errors)} error(s), "
                 f"{len(self.findings) - len(self.errors)} other finding(s), "
                 f"{len(self.skipped)} rule(s) skipped"]
        for f in self.findings:
            lines.append("  " + f.render())
        for rule_id, why in sorted(self.skipped.items()):
            lines.append(f"  [skipped] {rule_id}: {why}")
        return "\n".join(lines)

    def raise_for_errors(self) -> "LintReport":
        if self.errors:
            raise LintError(self)
        return self


class LintContext:
    """Lazy per-target inputs the rules read.

    Every derived artifact (jaxpr, compiled HLO, gradient probe, census
    HLO) is computed at most once and memoized; a derivation that fails
    or lacks its inputs yields ``None`` with the reason recorded in
    ``unavailable`` — the driver turns that into a skip, so one broken
    probe never hides the other rules' findings.
    """

    def __init__(self, fn, args, kwargs, *, name="", comm=None, flavor=None,
                 inter_size=None, plan=None, loss=None, loss_args=None,
                 donate_argnums=(), fsdp_meta=None, fsdp_state=None,
                 variants=None, census=False, hlo=True,
                 max_const_bytes=DEFAULT_MAX_BYTES, flight_events=None,
                 artifact_root=None, protocol_root=None):
        self.fn = fn
        self.args = args
        self.kwargs = kwargs or {}
        self.name = name or getattr(fn, "__name__", "") or "step"
        self.comm = comm
        # an explicit plan is a first-class census/wire spec
        # (census-drift and wire-dtype-mismatch read it); when only a
        # communicator is given its flavor names the spec instead
        self.plan = plan
        self.flavor = (flavor if flavor is not None
                       else getattr(comm, "flavor", None)
                       if plan is None else flavor)
        self.inter_size = (inter_size if inter_size is not None
                           else getattr(comm, "inter_size", 1) or 1)
        self.loss = loss
        self.loss_args = loss_args
        self.donate_argnums = tuple(donate_argnums or ())
        self.fsdp_meta = fsdp_meta
        self.fsdp_state = fsdp_state
        self._variants_spec = variants
        self.flight_events = flight_events
        self.artifact_root = artifact_root
        self.protocol_root = protocol_root
        self.census = census
        self.hlo = hlo
        self.max_const_bytes = max_const_bytes
        self.unavailable: Dict[str, str] = {}
        self._cache: Dict[str, Any] = {}

    # -- memoized derivations -------------------------------------------

    def _memo(self, key: str, build: Callable[[], Any]):
        if key in self._cache:
            return self._cache[key]
        try:
            val = build()
        except Exception as e:  # noqa: BLE001 — reason becomes the skip
            self.unavailable[key] = f"{type(e).__name__}: {e}"
            val = None
        self._cache[key] = val
        return val

    @property
    def closed_jaxpr(self):
        def build():
            if self.fn is None:
                self.unavailable["closed_jaxpr"] = "no step function given"
                return None
            return jax.make_jaxpr(self.fn)(*self.args, **self.kwargs)
        return self._memo("closed_jaxpr", build)

    @property
    def schedule(self) -> Optional[CollectiveSchedule]:
        def build():
            closed = self.closed_jaxpr
            if closed is None:
                return None
            return extract_schedule(closed, label=self.name)
        return self._memo("schedule", build)

    @property
    def hlo_text(self) -> Optional[str]:
        def build():
            if not self.hlo:
                self.unavailable["hlo_text"] = "hlo=False"
                return None
            if self.fn is None:
                self.unavailable["hlo_text"] = "no step function given"
                return None
            fn = self.fn
            if not hasattr(fn, "lower"):
                fn = jax.jit(fn)
            return fn.lower(*self.args, **self.kwargs).compile().as_text()
        return self._memo("hlo_text", build)

    @property
    def hlo_schedule(self) -> Optional[CollectiveSchedule]:
        def build():
            text = self.hlo_text
            if text is None:
                return None
            return schedule_from_hlo(text, label=f"{self.name}:hlo")
        return self._memo("hlo_schedule", build)

    @property
    def census_schedule(self) -> Optional[CollectiveSchedule]:
        """The compiled program census-drift checks against the declared
        spec.  ``census=True`` compiles the communicator's own
        ``allreduce_grad`` (the training seam); ``census=<hlo text>``
        audits that HLO directly; ``census=<callable>`` is invoked lazily
        (no args) to produce the HLO — the serving/router entry points
        use this to put their OWN compiled program (fused decode step,
        multicast weight distribution) under the same drift check."""
        def build():
            if not self.census:
                self.unavailable["census_schedule"] = "census=False"
                return None
            if callable(self.census):
                try:
                    text = self.census()
                except Exception as e:  # noqa: BLE001 — probe, not crash
                    self.unavailable["census_schedule"] = \
                        f"census probe failed: {e}"
                    return None
                if not isinstance(text, str):
                    self.unavailable["census_schedule"] = \
                        (f"census callable returned "
                         f"{type(text).__name__}, want HLO text")
                    return None
                return schedule_from_hlo(text, label=f"{self.name}:census")
            if isinstance(self.census, str):
                return schedule_from_hlo(self.census,
                                         label=f"{self.name}:census")
            if self.comm is None:
                self.unavailable["census_schedule"] = "no communicator given"
                return None
            return schedule_from_hlo(
                allreduce_hlo(self.comm),
                label=f"{self.flavor or 'comm'}:allreduce_grad")
        return self._memo("census_schedule", build)

    @property
    def grad_probe(self) -> Optional[Dict[str, CollectiveSchedule]]:
        def build():
            if self.loss is None or self.loss_args is None:
                self.unavailable["grad_probe"] = \
                    "no loss/loss_args given (pass loss=, loss_args=)"
                return None
            if self.comm is None:
                self.unavailable["grad_probe"] = "no communicator given"
                return None
            return build_grad_probe(self.comm, self.loss, self.loss_args,
                                    label=self.name)
        return self._memo("grad_probe", build)

    @property
    def variants(self) -> Optional[Dict[str, CollectiveSchedule]]:
        def build():
            spec = self._variants_spec
            if not spec:
                self.unavailable["variants"] = \
                    "no variants given (pass variants={label: ...})"
                return None
            out: Dict[str, CollectiveSchedule] = {}
            for label, v in spec.items():
                if isinstance(v, CollectiveSchedule):
                    sched = v
                elif callable(v):
                    # a builder returning either a schedule or a traceable
                    # step function (traced with THIS context's args)
                    built = v()
                    sched = built if isinstance(built, CollectiveSchedule) \
                        else extract_schedule(built, *self.args, label=label,
                                              **self.kwargs)
                elif isinstance(v, tuple):
                    vfn, vargs = v[0], tuple(v[1:])
                    sched = extract_schedule(vfn, *vargs, label=label)
                else:
                    raise TypeError(
                        f"variants[{label!r}] must be a CollectiveSchedule, "
                        f"a callable, or a (fn, *args) tuple; got {type(v)}")
                sched.label = sched.label or label
                out[label] = sched
            return out
        return self._memo("variants", build)

    @property
    def flight_spans(self) -> Optional[Dict[int, list]]:
        """Per-rank paired spans rebuilt from flight-recorder events —
        the ``overlapping-collectives`` input.  ``flight_events`` is a
        flat event list (linted as rank 0) or ``{rank: events}``; a
        flight dump's ``events`` list feeds it directly."""
        def build():
            ev = self.flight_events
            if not ev:
                self.unavailable["flight_spans"] = \
                    "no flight_events given (pass flight_events=)"
                return None
            from chainermn_tpu.observability.spans import pair_events
            if isinstance(ev, dict):
                by_rank = {int(r): list(e) for r, e in ev.items()}
            else:
                by_rank = {0: list(ev)}
            return {r: pair_events(e, rank=r)
                    for r, e in sorted(by_rank.items())}
        return self._memo("flight_spans", build)

    @property
    def protocol_model(self):
        """Static control-plane protocol model (``analysis/protocol.py``)
        — the input of the tag-band-collision / lockstep-divergence /
        unmatched-send-recv / wrapper-surface-drift / replay rules.
        ``protocol_root=True`` walks the installed ``chainermn_tpu``
        package; a path walks that tree (the fixture tests' path); an
        already-built :class:`~chainermn_tpu.analysis.protocol.
        ProtocolModel` (or its ``to_json()`` dict) is used as-is."""
        def build():
            root = self.protocol_root
            if not root:
                self.unavailable["protocol_model"] = \
                    "no protocol_root given (pass protocol_root=)"
                return None
            from chainermn_tpu.analysis.protocol import (
                ProtocolModel, extract_protocol)
            if isinstance(root, ProtocolModel):
                return root
            if isinstance(root, dict):
                return ProtocolModel.from_json(root)
            return extract_protocol(None if root is True else root)
        return self._memo("protocol_model", build)

    @property
    def artifact_census(self) -> Optional[List[dict]]:
        """Every committed artifact under ``artifact_root``, parsed and
        classified against the run-ledger schema registry — the
        ``artifact-drift`` input.  One row per artifact: ``path``
        (relative), ``doc``, ``classification`` (``None`` =
        unknown schema), ``manifest`` (the ``run_manifest/v1`` record,
        carrying device kind and modeled/measured link rates)."""
        def build():
            root = self.artifact_root
            if not root:
                self.unavailable["artifact_census"] = \
                    "no artifact_root given (pass artifact_root=)"
                return None
            from chainermn_tpu.observability.ledger import (
                build_manifest, classify_artifact, iter_artifacts)
            rows: List[dict] = []
            for path in iter_artifacts(root):
                row = {"path": os.path.relpath(path, root)}
                try:
                    with open(path) as fh:
                        doc = json.load(fh)
                except Exception as e:  # noqa: BLE001 — itself a finding
                    row["error"] = f"{type(e).__name__}: {e}"
                    rows.append(row)
                    continue
                cls = classify_artifact(doc, path)
                row["doc"] = doc
                row["classification"] = cls
                row["manifest"] = build_manifest(
                    doc, path, root=root, classification=cls)
                rows.append(row)
            return rows
        return self._memo("artifact_census", build)


def allreduce_hlo(comm, nelems: int = 1024, dtype=jnp.float32,
                  plan=None) -> str:
    """Optimized HLO of the communicator's compiled ``allreduce_grad``
    over one flat ``nelems`` gradient — the census-drift probe (and the
    program ``bench_allreduce.py --census`` pins as an artifact).

    The census probe always compiles the communicator's OWN program (a
    ``LintContext.plan`` is the spec the program is checked AGAINST,
    never the program itself — otherwise census-drift could not catch a
    communicator that ignores its declared plan).  The explicit ``plan``
    argument here is for callers building their own probe of a specific
    plan through the same seam (``allreduce_grad(g, compressor=plan)``),
    e.g. to audit the plan compiler's census including per-hop
    compression."""
    stacked = jnp.zeros((comm.size, nelems), dtype)
    if plan is not None:
        return comm.compiled_hlo(
            lambda g: comm.allreduce_grad(g, compressor=plan), stacked)
    return comm.compiled_hlo(lambda g: comm.allreduce_grad(g), stacked)


def build_grad_probe(comm, loss, loss_args, label: str = "") \
        -> Dict[str, CollectiveSchedule]:
    """Primal vs backward collective schedules of ``loss`` differentiated
    INSIDE the communicator's SPMD region — the ``make_train_step`` shape,
    where an unpinned psum transpose is both statically visible (an extra
    backward psum) and numerically wrong (grads inflated by the axis
    size).

    ``loss(params, *rest)`` must return a scalar per-rank loss (or an
    ``(loss, aux)`` tuple); ``loss_args = (params, *rest)`` in GLOBAL
    layout — params replicated, the rest sharded on their leading axis
    over the communicator's data axes (a stacked ``[size, ...]`` batch).
    """
    from chainermn_tpu.utils import pvary, shard_map as _shard_map
    from jax.sharding import PartitionSpec as P

    axes = comm.data_axes
    params, rest = loss_args[0], tuple(loss_args[1:])

    def scalarize(p, rest_local):
        out = loss(p, *rest_local)
        val = out[0] if isinstance(out, tuple) else out
        return jnp.asarray(val)

    def primal_body(p, *rest_local):
        p = jax.tree.map(lambda x: pvary(x, axes), p)
        return scalarize(p, rest_local)[None]

    def grad_body(p, *rest_local):
        p = jax.tree.map(lambda x: pvary(x, axes), p)
        g = jax.grad(lambda q: scalarize(q, rest_local))(p)
        return jax.tree.map(lambda a: jnp.asarray(a)[None], g)

    in_specs = (P(),) + tuple(P(axes) for _ in rest)

    def mapped(body):
        return _shard_map(body, mesh=comm.mesh, in_specs=in_specs,
                          out_specs=P(axes), check_vma=False)

    return {
        "primal": extract_schedule(mapped(primal_body), params, *rest,
                                   label=f"{label}:primal"),
        "grad": extract_schedule(mapped(grad_body), params, *rest,
                                 label=f"{label}:grad"),
    }


def lint_step(fn, *args, comm=None, flavor=None, inter_size=None,
              plan=None, loss=None, loss_args=None, donate_argnums=(),
              fsdp_meta=None, fsdp_state=None, variants=None,
              census=False, hlo: bool = True,
              max_const_bytes: int = DEFAULT_MAX_BYTES,
              flight_events=None, artifact_root=None, protocol_root=None,
              rules: Optional[Sequence[str]] = None,
              raise_on_error: bool = True, name: str = "",
              **kwargs) -> LintReport:
    """Lint one train step (and its optional auxiliary probes).

    ``fn``/``*args``: the step exactly as it is called (a jitted function
    is lowered as-is, preserving donation; a plain function is traced and
    jitted for the HLO view).  Optional inputs unlock optional rules —
    see :class:`LintContext`.  Returns the :class:`LintReport`; raises
    :class:`LintError` on error findings unless ``raise_on_error=False``.
    """
    ctx = LintContext(fn, args, kwargs, name=name, comm=comm, flavor=flavor,
                      inter_size=inter_size, plan=plan,
                      loss=loss, loss_args=loss_args,
                      donate_argnums=donate_argnums, fsdp_meta=fsdp_meta,
                      fsdp_state=fsdp_state, variants=variants,
                      census=census, hlo=hlo,
                      max_const_bytes=max_const_bytes,
                      flight_events=flight_events,
                      artifact_root=artifact_root,
                      protocol_root=protocol_root)
    report = LintReport(target=ctx.name)
    selected = [get_rule(r) for r in rules] if rules else all_rules()
    for rule in selected:
        missing = rule.missing(ctx)
        if missing:
            reasons = [ctx.unavailable.get(m, f"{m} not provided")
                       for m in missing]
            report.skipped[rule.id] = "; ".join(reasons)
            continue
        try:
            report.findings.extend(rule.run(ctx))
        except Exception as e:  # noqa: BLE001 — a crashed rule is a skip
            report.skipped[rule.id] = \
                f"rule crashed: {type(e).__name__}: {e}"
    report.findings.sort(
        key=lambda f: ("error", "warning", "info").index(f.severity))
    if raise_on_error:
        report.raise_for_errors()
    return report


__all__ = ["LintContext", "LintError", "LintReport", "allreduce_hlo",
           "build_grad_probe", "lint_step"]
