"""cmn-lint rule registry — each rule proves one collective-schedule
invariant at trace/compile time, on CPU, before a mesh is involved.

Every rule has a **stable ID** (the contract for CI greps, findings
JSON, and the docs catalog in ``docs/static_analysis.md``) and names, in
its finding message, the runtime subsystem that would otherwise catch
the bug only after a pod is wedged — the flight recorder / hang
watchdog cross-link the tentpole asks for.

Rules read a duck-typed context object (``LintContext`` in ``lint.py``;
tests may pass any namespace with the same attributes).  A rule whose
required inputs are absent is *skipped*, not failed — ``LintReport``
records the reason.
"""

from __future__ import annotations

import re
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional

import numpy as np

SEVERITIES = ("error", "warning", "info")

#: numpy dtype name -> HLO shape dtype token (wire-dtype-mismatch rule)
NP_TO_HLO_DTYPE = {
    "float64": "f64", "float32": "f32", "float16": "f16",
    "bfloat16": "bf16", "float8_e4m3fn": "f8e4m3fn",
    "float8_e5m2": "f8e5m2", "int64": "s64", "int32": "s32",
    "int16": "s16", "int8": "s8", "uint8": "u8", "bool": "pred",
}


@dataclass
class Finding:
    """One lint finding.  ``rule`` is the stable ID; ``target`` names the
    linted program (entry point / flavor / function)."""
    rule: str
    severity: str
    message: str
    target: str = ""
    details: dict = field(default_factory=dict)

    def as_dict(self) -> dict:
        return {"rule": self.rule, "severity": self.severity,
                "target": self.target, "message": self.message,
                "details": self.details}

    def render(self) -> str:
        head = f"[{self.severity}] {self.rule}"
        if self.target:
            head += f" ({self.target})"
        return head + ": " + self.message


@dataclass(frozen=True)
class Rule:
    id: str
    severity: str
    summary: str
    requires: tuple            # context attributes that must be non-None
    fn: Callable               # fn(ctx) -> List[Finding]
    #: attributes of which AT LEAST ONE must be non-None (e.g. a rule
    #: that reads either a hand-declared spec or a plan-derived one)
    requires_any: tuple = ()

    def missing(self, ctx) -> List[str]:
        out = [r for r in self.requires
               if getattr(ctx, r, None) is None]
        if self.requires_any and not any(
                getattr(ctx, r, None) is not None
                for r in self.requires_any):
            out.append(" or ".join(self.requires_any))
        return out

    def run(self, ctx) -> List[Finding]:
        out = []
        for f in self.fn(ctx):
            f.rule = self.id
            f.severity = f.severity or self.severity
            f.target = f.target or getattr(ctx, "name", "") or ""
            out.append(f)
        return out


_REGISTRY: "Dict[str, Rule]" = {}


def rule(id: str, severity: str, summary: str, requires: tuple = (),
         requires_any: tuple = ()):
    assert severity in SEVERITIES, severity

    def deco(fn):
        _REGISTRY[id] = Rule(id=id, severity=severity, summary=summary,
                             requires=requires, requires_any=requires_any,
                             fn=fn)
        return fn
    return deco


def all_rules() -> List[Rule]:
    return list(_REGISTRY.values())


def get_rule(id: str) -> Rule:
    try:
        return _REGISTRY[id]
    except KeyError:
        raise ValueError(
            f"unknown lint rule {id!r}; available: "
            f"{sorted(_REGISTRY)}") from None


def _finding(message: str, **details) -> Finding:
    return Finding(rule="", severity="", message=message, details=details)


# ---------------------------------------------------------------------------
# schedule-desync — the static identify_desync
# ---------------------------------------------------------------------------

@rule("schedule-desync", "error",
      "per-rank/per-config traced schedules must be identical",
      requires=("variants",))
def _schedule_desync(ctx) -> List[Finding]:
    """Every rank traces the SAME Python; a branch on rank (or any
    nondeterminism in trace order) gives two ranks different collective
    schedules, and the mesh wedges at the first divergence.  This is the
    static version of the flight recorder's ``identify_desync``: the
    runtime analysis names the rank stuck behind after the hang — this
    rule names the diverging op before anything runs."""
    variants = ctx.variants      # dict label -> CollectiveSchedule
    labels = sorted(variants)
    if len(labels) < 2:
        return []
    base_label = labels[0]
    base = variants[base_label]
    out: List[Finding] = []
    for other_label in labels[1:]:
        d = base.diff(variants[other_label])
        if d is None:
            continue
        out.append(_finding(
            f"collective schedules diverge between {base_label!r} and "
            f"{other_label!r} at op #{d['index']}: "
            f"{base_label!r} issues {d['left'] or '<end of schedule>'}, "
            f"{other_label!r} issues {d['right'] or '<end of schedule>'}. "
            "On a live mesh this wedges every rank at that collective — "
            "the hang the flight-recorder watchdog diagnoses at runtime "
            "(docs/observability.md, identify_desync); fix the "
            "rank/config-dependent trace so all ranks issue one schedule.",
            index=d["index"], left=d["left"], right=d["right"],
            left_label=base_label, right_label=other_label))
        break  # first divergence is THE actionable one
    return out


# ---------------------------------------------------------------------------
# census-drift — per-flavor expected decomposition, DERIVED from the plan
# ---------------------------------------------------------------------------

def expected_kinds(flavor: str, inter_size: int = 1) -> tuple:
    """Expected ``allreduce_grad`` collective-kind sequence for a
    communicator flavor (shared with tests/test_census.py).

    Derived, not maintained: the flavor's fixed plan
    (``planner.plans.flavor_plan``) is compiled statically against an
    (inter, intra) topology and the census read off the IR
    (``planner.compiler.plan_census_kinds``) — the same IR the live
    lowering executes, so this table cannot drift from the code.  The
    pre-planner hand-written table survives only as the one-time
    cross-check inside ``tests/test_census.py`` (where its ``inter == 1``
    branches are documented as having been *wrong* against compiled
    reality — XLA keeps singleton-group collectives).
    """
    from chainermn_tpu.planner.compiler import plan_census_kinds
    from chainermn_tpu.planner.ir import PlanTopology
    from chainermn_tpu.planner.plans import flavor_plan
    plan = flavor_plan(flavor)  # raises ValueError on unknown flavors
    # kinds depend on which scopes HAVE axes, not on axis sizes; the
    # standard (inter, intra) mesh always declares both axes
    topo = PlanTopology(axes=(("inter", max(int(inter_size or 1), 1)),
                              ("intra", 1)))
    return plan_census_kinds(plan, topo)


#: narrow float wires CPU XLA promotes AROUND the collective on the lint
#: host (the cast seam stays compiled in; the collective itself runs
#: wider).  The census dtype lane accepts exactly these widenings —
#: int8's ``s8`` has no entry, so a quantized hop whose codes never hit
#: the wire (compression silently off: the collective moves f32) is
#: always a finding.
CPU_WIRE_PROMOTIONS = {
    "bf16": ("f32",),
    "f16": ("f32",),
    "f8e4m3fn": ("f16",),
    "f8e5m2": ("f16",),
}


def _interleaves(seqs, observed, match=None) -> bool:
    """True iff ``observed`` is a valid interleaving of the sequences in
    ``seqs`` — each sequence's internal order preserved, elements freely
    merged across sequences.  ``match(want, got)`` compares elements
    (default ``==``).

    This is the census comparison for striped plans: XLA is free to
    reorder collectives from INDEPENDENT concurrent stage groups (they
    share no data), so the compiled schedule is only required to be SOME
    interleaving of the per-group expected sequences, never an arbitrary
    permutation — within a group the chain order is a data dependency
    and must survive.  Memoized DP over per-sequence cursors.
    """
    if match is None:
        def match(w, g):
            return w == g
    seqs = [tuple(s) for s in seqs]
    observed = tuple(observed)
    if sum(len(s) for s in seqs) != len(observed):
        return False
    memo: Dict[tuple, bool] = {}

    def _ok(idx: tuple) -> bool:
        pos = sum(idx)
        if pos == len(observed):
            return True
        if idx in memo:
            return memo[idx]
        res = False
        for gi, s in enumerate(seqs):
            j = idx[gi]
            if j < len(s) and match(s[j], observed[pos]):
                if _ok(idx[:gi] + (j + 1,) + idx[gi + 1:]):
                    res = True
                    break
        memo[idx] = res
        return res

    return _ok(tuple(0 for _ in seqs))


@rule("census-drift", "error",
      "compiled allreduce_grad decomposition must match the flavor's "
      "plan-derived census",
      requires=("census_schedule",), requires_any=("flavor", "plan"))
def _census_drift(ctx) -> List[Finding]:
    inter = getattr(ctx, "inter_size", 1) or 1
    plan = getattr(ctx, "plan", None)
    flavor = getattr(ctx, "flavor", None)
    topo = None
    if plan is not None and getattr(plan, "groups", None) is not None:
        # striped plan: the groups are data-independent, so XLA may
        # interleave (but not reorder within) their chains — the
        # compiled schedule must be a valid interleaving of the
        # per-group expected sequences, first on kinds, then on
        # (kind, wire-dtype) lanes with the CPU promotion tolerance.
        from chainermn_tpu.planner.compiler import (
            plan_census_kinds, plan_wire_dtypes)
        from chainermn_tpu.planner.ir import PlanTopology
        comm = getattr(ctx, "comm", None)
        topo = (comm.plan_topology() if comm is not None else
                PlanTopology(axes=(("inter", inter), ("intra", 1))))
        n_groups = len(plan.groups)
        group_kinds = [tuple(plan_census_kinds(plan, topo, group=g))
                       for g in range(n_groups)]
        got = tuple(ctx.census_schedule.kinds())
        if not _interleaves(group_kinds, got):
            return [_finding(
                f"striped plan {plan.name!r} compiled allreduce_grad to "
                f"{list(got) or '<no collectives>'} which is not an "
                f"interleaving of its {n_groups} concurrent stage "
                f"groups' expected sequences "
                f"{[list(s) for s in group_kinds]} (inter_size={inter})."
                f"  Groups are independent so XLA may merge their "
                f"chains, but each group's internal order is a data "
                f"dependency — drift here means a stripe lost or grew a "
                f"hop and the per-link cost model prices a schedule the "
                f"program does not run.",
                expected_groups=[list(s) for s in group_kinds],
                observed=list(got), plan=plan.name, inter_size=inter)]
        group_lanes = []
        for g in range(n_groups):
            dts = plan_wire_dtypes(plan, topo, group=g)
            group_lanes.append(tuple(
                (k, NP_TO_HLO_DTYPE.get(d, d))
                for k, d in zip(group_kinds[g], dts)))
        got_lanes = tuple((op.kind, op.dtype)
                          for op in ctx.census_schedule)

        def _lane_match(w, g):
            return (w[0] == g[0]
                    and (g[1] == w[1]
                         or g[1] in CPU_WIRE_PROMOTIONS.get(w[1], ())))

        if not _interleaves(group_lanes, got_lanes, _lane_match):
            return [_finding(
                f"striped plan {plan.name!r} compiled collectives "
                f"{[list(l) for l in got_lanes]} do not interleave its "
                f"per-group (kind, wire-dtype) lanes "
                f"{[[list(l) for l in grp] for grp in group_lanes]}: "
                f"some hop runs at a width its stripe does not declare."
                f"  A compressed DCN stripe whose codes never hit the "
                f"wire is compression silently off at full wire cost; a "
                f"narrower-than-declared stripe silently drops numerics "
                f"— either way plan_link_bytes prices a wire the "
                f"program does not move.",
                expected_group_lanes=[[list(l) for l in grp]
                                      for grp in group_lanes],
                observed_lanes=[list(l) for l in got_lanes],
                plan=plan.name, inter_size=inter)]
        return []
    if plan is not None:
        # explicit plan spec (e.g. an autotuned table entry) — derive
        # the census against the communicator's declared topology
        from chainermn_tpu.planner.compiler import plan_census_kinds
        from chainermn_tpu.planner.ir import PlanTopology
        comm = getattr(ctx, "comm", None)
        topo = (comm.plan_topology() if comm is not None else
                PlanTopology(axes=(("inter", inter), ("intra", 1))))
        want = plan_census_kinds(plan, topo)
        spec_name = f"plan {plan.name!r}"
    else:
        want = expected_kinds(flavor, inter)
        spec_name = f"flavor {flavor!r}"
    got = ctx.census_schedule.kinds()
    if got != want:
        return [_finding(
            f"communicator {spec_name} compiled allreduce_grad to "
            f"{list(got) or '<no collectives>'} but its decomposition is "
            f"specified as {list(want)} (inter_size={inter}).  The "
            "decomposition IS the flavor (docs/performance.md census "
            "table; CENSUS_r*.json artifact): drift here means a "
            "different wire cost model and a schedule the other ranks do "
            "not expect.",
            expected=list(want), observed=list(got),
            flavor=flavor or (plan.name if plan is not None else None),
            inter_size=inter)]
    if plan is None:
        return []
    # Per-hop dtype census (explicit plans only): each compiled
    # collective must run at its stage's declared wire width — a
    # compressed stage at its COMPRESSOR's wire.  Same kinds with a
    # wider hop is the per-hop analogue of census drift: the cost model
    # (plan_wire_bytes) and the dcn_wire_bytes budget price the hop at
    # a width the program does not move.
    from chainermn_tpu.planner.compiler import plan_wire_dtypes
    want_np = plan_wire_dtypes(plan, topo)
    want_d = [NP_TO_HLO_DTYPE.get(d, d) for d in want_np]
    got_d = [op.dtype for op in ctx.census_schedule]
    out: List[Finding] = []
    for i, (w, g) in enumerate(zip(want_d, got_d)):
        if g == w or g in CPU_WIRE_PROMOTIONS.get(w, ()):
            continue
        out.append(_finding(
            f"plan {plan.name!r} hop {i} ({want[i]}) is specified to "
            f"run its wire in {w} (stage dtype {want_np[i]!r}) but the "
            f"compiled collective runs in {g} (per-hop dtypes: expected "
            f"{want_d}, observed {got_d}).  A compressed hop whose "
            f"codes never hit the wire is compression silently off at "
            f"full wire cost; a narrower-than-declared hop silently "
            f"drops numerics — either way plan_wire_bytes and the "
            f"dcn_wire_bytes budget are pricing a wire the program "
            f"does not move.",
            stage=i, expected_dtype=w, observed_dtype=g,
            expected_dtypes=want_d, observed_dtypes=got_d,
            plan=plan.name, inter_size=inter))
    return out


# ---------------------------------------------------------------------------
# unpinned-transpose — the PR 1 bug class
# ---------------------------------------------------------------------------

@rule("unpinned-transpose", "error",
      "a psum differentiated inside the SPMD body must pin its identity "
      "transpose",
      requires=("grad_probe",))
def _unpinned_transpose(ctx) -> List[Finding]:
    """A loss differentiated INSIDE the SPMD region (the
    ``make_train_step`` shape) that allreduces a replicated value with a
    raw ``psum`` gets the psum→psum transpose: the cotangent is summed
    again and every gradient arrives inflated by ``size``.  The pinned
    path (``chainermn_tpu.functions.allreduce``, a custom VJP whose
    backward is the identity) adds NO backward psum — so any psum excess
    of the grad trace over the primal trace, per axis set, is an
    unpinned transpose."""
    probe = ctx.grad_probe   # {"primal": schedule, "grad": schedule}
    primal_counts = probe["primal"].counts_by_axes("psum")
    grad_counts = probe["grad"].counts_by_axes("psum")
    out: List[Finding] = []
    for axes, n_grad in sorted(grad_counts.items()):
        extra = n_grad - primal_counts.get(axes, 0)
        if extra <= 0:
            continue
        ax_txt = ",".join(a for a in axes if a is not None) or "?"
        out.append(_finding(
            f"{extra} psum(s) over axes ({ax_txt}) appear in the "
            f"backward trace of the per-rank loss but not in its primal "
            f"trace: a psum's VJP was transposed to another psum, so "
            f"gradients are inflated by the axis size.  Wrap the "
            f"allreduce in chainermn_tpu.functions.allreduce (custom VJP "
            f"pinning the identity transpose) instead of calling "
            f"lax.psum/communicator.allreduce raw inside a loss that is "
            f"differentiated in the SPMD body.  At runtime this is "
            f"silent — no hang for the watchdog to catch, just a wrong "
            f"effective learning rate.",
            axes=list(ax_txt.split(",")), extra_backward_psums=extra,
            primal_psums=primal_counts.get(axes, 0),
            grad_psums=n_grad))
    return out


# ---------------------------------------------------------------------------
# captured-constant — the promoted utils/jaxpr_audit guard
# ---------------------------------------------------------------------------

@rule("captured-constant", "error",
      "traced program must not close over large array constants",
      requires=("closed_jaxpr",))
def _captured_constant(ctx) -> List[Finding]:
    from chainermn_tpu.analysis.captured import (
        DEFAULT_MAX_BYTES, captured_constant_message, constants_in_jaxpr)

    max_bytes = getattr(ctx, "max_const_bytes", None) or DEFAULT_MAX_BYTES
    found = constants_in_jaxpr(ctx.closed_jaxpr, max_bytes=max_bytes)
    if not found:
        return []
    label = getattr(ctx, "name", "") or "traced function"
    return [_finding(
        captured_constant_message(found, label, max_bytes),
        constants=found, max_bytes=max_bytes)]


# ---------------------------------------------------------------------------
# donation-alias — donated buffers read through a second alias
# ---------------------------------------------------------------------------

@rule("donation-alias", "error",
      "no argument buffer may alias a donated argument",
      requires=("args", "donate_argnums"))
def _donation_alias(ctx) -> List[Finding]:
    """Two checks on the step's ACTUAL operands:

    * the same device buffer passed through two argument positions while
      at least one of them is donated — XLA will reuse the storage for
      an output and the other alias reads freed/overwritten memory (or
      jax raises mid-run, which on a pod means one rank dying inside a
      collective: a hang everywhere else);
    * the same error-feedback ``CompressionState`` leaf aliased into two
      FSDP buckets — each bucket's reduce-scatter would accumulate its
      residual into one buffer and silently corrupt the other's EF
      stream.
    """
    import jax

    out: List[Finding] = []
    donated = set(ctx.donate_argnums or ())
    if donated:
        by_id: Dict[int, List[tuple]] = {}
        for argno, arg in enumerate(ctx.args):
            for path, leaf in jax.tree_util.tree_flatten_with_path(arg)[0]:
                if not hasattr(leaf, "nbytes") or not hasattr(leaf, "shape"):
                    continue
                by_id.setdefault(id(leaf), []).append(
                    (argno, jax.tree_util.keystr(path)))
        for _leaf_id, sites in sorted(by_id.items()):
            if len(sites) < 2:
                continue
            if not any(argno in donated for argno, _ in sites):
                continue
            where = ", ".join(f"arg{argno}{p}" for argno, p in sites)
            out.append(_finding(
                f"the same array object is passed at {where} while "
                f"argument(s) {sorted({a for a, _ in sites if a in donated})} "
                f"are donated: after donation the buffer belongs to the "
                f"output and every other alias reads poisoned memory.  "
                f"Pass an explicit copy, or stop donating that argument.",
                positions=[{"arg": a, "path": p} for a, p in sites],
                donated=sorted(donated)))
    # EF-state aliasing across FSDP buckets
    fsdp_state = getattr(ctx, "fsdp_state", None)
    if fsdp_state is not None and getattr(fsdp_state, "comp", ()):
        import jax

        seen: Dict[int, int] = {}
        for b, comp in enumerate(fsdp_state.comp):
            if comp is None:
                continue
            for leaf in jax.tree_util.tree_leaves(comp):
                if not hasattr(leaf, "nbytes"):
                    continue
                if id(leaf) in seen and seen[id(leaf)] != b:
                    out.append(_finding(
                        f"error-feedback state buffer is aliased into "
                        f"buckets {seen[id(leaf)]} and {b}: each bucket's "
                        f"compressed reduce-scatter feeds its residual "
                        f"back into the shared buffer, corrupting the "
                        f"other bucket's EF stream (convergence poison, "
                        f"invisible to the runtime watchdog).  Give every "
                        f"bucket its own CompressionState.",
                        buckets=[seen[id(leaf)], b]))
                seen.setdefault(id(leaf), b)
    return out


# ---------------------------------------------------------------------------
# wire-dtype-mismatch — compression spec vs compiled collective dtype
# ---------------------------------------------------------------------------

@rule("wire-dtype-mismatch", "error",
      "compiled collectives must run in their declared wire dtypes "
      "(FSDP bucket layouts and plan specs)",
      requires=("hlo_schedule",), requires_any=("fsdp_meta", "plan"))
def _wire_dtype_mismatch(ctx) -> List[Finding]:
    """DynamiQ-class pipelines (PAPERS.md) add a whole mismatch family:
    the spec SAYS int8-with-EF but the compiled program moves f32
    (compression silently off: 4x the wire), or vice versa (numerics
    silently narrowed).  Two spec sources:

    * an FSDP bucket layout — each bucket's declared wire dtype must
      appear among the compiled reduce-scatter dtypes (one per bucket);
    * a collective :class:`~chainermn_tpu.planner.ir.Plan` — the plan's
      (or a stage's) wire dtype must appear among the compiled
      collective dtypes; a stage carrying a per-hop ``compression`` spec
      expects its COMPRESSOR's wire (int8 -> ``s8``, fp8 ->
      ``f8e4m3fn``) instead — the DCN hop whose codes never hit the
      wire is compression silently off at 4x the bytes.
    """
    from chainermn_tpu.compression import resolve_compressor

    out: List[Finding] = []
    meta = getattr(ctx, "fsdp_meta", None)
    if meta is not None:
        expected: List[tuple] = []       # (bucket, hlo dtype token, why)
        for b, layout in enumerate(meta.buckets):
            if getattr(layout, "compressor", None):
                comp = resolve_compressor(layout.compressor)
                wire = np.dtype(
                    comp.wire_dtype_for(np.dtype("float32"))).name
                expected.append((b, NP_TO_HLO_DTYPE.get(wire, wire),
                                 f"compressor {comp.name!r}"))
            elif getattr(layout, "wire_dtype", None):
                wire = np.dtype(layout.wire_dtype).name
                expected.append((b, NP_TO_HLO_DTYPE.get(wire, wire),
                                 f"wire_dtype {wire!r}"))
        observed = [op.dtype for op in ctx.hlo_schedule
                    if op.kind == "reduce-scatter"]
        remaining = list(observed)
        for b, token, why in expected:
            if token in remaining:
                remaining.remove(token)
                continue
            out.append(_finding(
                f"bucket {b} declares {why} (wire dtype {token}) but no "
                f"compiled reduce-scatter runs in {token} "
                f"(observed reduce-scatter dtypes: {observed or 'none'}).  "
                f"The checkpoint sidecar and resume guard trust the "
                f"layout's spec — a program that moves a different dtype "
                f"is either paying full-precision wire cost or silently "
                f"narrowing numerics.",
                bucket=b, expected_dtype=token, observed_dtypes=observed,
                declared=why))
    plan = getattr(ctx, "plan", None)
    if plan is not None:
        wires = []                       # (hlo dtype token, why)
        if getattr(plan, "wire_dtype", None):
            wire = np.dtype(plan.wire_dtype).name
            wires.append((NP_TO_HLO_DTYPE.get(wire, wire),
                          f"plan {plan.name!r} wire_dtype {wire!r}"))
        # walk concurrent stage groups too: a striped plan keeps its
        # stages under plan.groups (plan.stages is empty), and its
        # compressed-DCN stripe's wire must be in the program exactly
        # like a plain compressed hop's
        if getattr(plan, "groups", None) is not None:
            chains = [(f" group {g} stage ", grp.stages)
                      for g, grp in enumerate(plan.groups)]
        else:
            chains = [(" stage ", getattr(plan, "stages", ()) or ())]
        for prefix, stages in chains:
            for i, st in enumerate(stages):
                if getattr(st, "compression", None):
                    comp = st.compressor()
                    wire = np.dtype(
                        str(comp.wire_dtype_for(np.dtype("float32")))).name
                    wires.append((NP_TO_HLO_DTYPE.get(wire, wire),
                                  f"plan {plan.name!r}{prefix}{i} "
                                  f"({st.op}) compressor {comp.name!r} "
                                  f"wire {wire!r}"))
                elif getattr(st, "wire_dtype", None):
                    wire = np.dtype(st.wire_dtype).name
                    wires.append((NP_TO_HLO_DTYPE.get(wire, wire),
                                  f"plan {plan.name!r}{prefix}{i} "
                                  f"({st.op}) wire_dtype {wire!r}"))
        observed = [op.dtype for op in ctx.hlo_schedule
                    if op.kind in ("all-reduce", "reduce-scatter",
                                   "all-gather", "collective-permute",
                                   "all-to-all")]
        # CPU XLA promotes bf16 collectives to f32 (the wire casts fuse
        # AROUND the all-reduce), so on the lint preflight host the wire
        # dtype may never appear ON a collective even when the cast seam
        # is compiled in.  Accept the dtype appearing anywhere in the
        # program as evidence the seam exists — a plan whose wire dtype
        # was silently dropped has NO trace of it at all.
        text = getattr(ctx, "hlo_text", None) or ""
        for token, why in wires:
            if token in observed:
                continue
            if re.search(rf"(?<!\w){re.escape(token)}\[", text):
                continue
            out.append(_finding(
                f"{why} (HLO dtype {token}) but no compiled collective "
                f"runs in {token} (observed collective dtypes: "
                f"{observed or 'none'}).  A plan whose wire dtype the "
                f"program does not move is either paying full-precision "
                f"wire cost or silently narrowing numerics — the same "
                f"trust contract as the FSDP layout spec.",
                expected_dtype=token, observed_dtypes=observed,
                declared=why))
    return out


# ---------------------------------------------------------------------------
# async-pair — unmatched all-reduce-start/done in the compiled schedule
# ---------------------------------------------------------------------------

@rule("async-pair", "error",
      "every async collective start must have a matching done",
      requires=("hlo_schedule",))
def _async_pair(ctx) -> List[Finding]:
    out: List[Finding] = []
    problems = list(ctx.hlo_schedule.problems)
    census = getattr(ctx, "census_schedule", None)
    if census is not None:
        problems += list(census.problems)
    for p in problems:
        if not str(p.get("kind", "")).startswith("unmatched-async"):
            continue
        half = "start" if p["kind"].endswith("start") else "done"
        out.append(_finding(
            f"async collective {p.get('op')!r} ({p.get('name')}) has an "
            f"unmatched -{half}: the compiled schedule "
            f"{'issues a collective it never awaits' if half == 'start' else 'awaits a collective it never issued'}"
            f" — on hardware that is a guaranteed wedge, the exact hang "
            f"class the collective watchdog exists to catch at runtime "
            f"(docs/observability.md).",
            **p))
    return out


# ---------------------------------------------------------------------------
# overlapping-collectives — independently-tuned plans contending for a link
# ---------------------------------------------------------------------------

@rule("overlapping-collectives", "warning",
      "concurrent same-link-class collectives with independently-tuned plans",
      requires=("flight_spans",))
def _overlapping_collectives(ctx) -> List[Finding]:
    """Flag spans that occupy the SAME link class at the SAME time but
    belong to DIFFERENT tuning identities (plan names / subsystems).

    Each independently-tuned plan prices the link at full bandwidth, so
    when two of them actually run concurrently both deliver below their
    modeled GB/s — the contention blind spot ROADMAP item 4 names.
    Spans sharing one identity are one co-tuned decision and are never
    flagged: a striped plan's concurrent groups split the link on
    purpose, and plans co-tuned in one ``StepWorkload``
    (``planner.schedule.jointly_tune``) carry the shared workload
    signature in their ``@wl:``-tagged names, which ``plan_identity``
    folds to one ``workload:<sig>`` identity — the joint scheduler's
    deliberate cross-communicator overlap is priced by the fair-share
    simulator, not a blind spot.  Full nesting counts: one identity's span time-containing
    another's IS overlap (the worst case — the inner transfer runs
    entirely under contention); only a true wrapper-over-decomposition
    pair (``leaf_comm_spans``) is exempt.  Severity is ``warning``:
    contention is a throughput bug, not a wedge.  Runtime evidence, not a compile-time proof — feed it
    the flight events of a representative window (``flight_events=``,
    or a flight dump's ``events`` via ``cmn_lint --events``).
    """
    from chainermn_tpu.observability.contention import (
        leaf_comm_spans, plan_identity, span_link)
    cells: Dict[tuple, dict] = {}
    for rank, spans in sorted(ctx.flight_spans.items()):
        per_link: Dict[str, list] = {}
        for sp in leaf_comm_spans(spans):
            link, ident = span_link(sp), plan_identity(sp)
            if link is not None and ident is not None:
                per_link.setdefault(link, []).append((sp.t0, sp.t1, ident))
        for link, rows in per_link.items():
            rows.sort()
            active: List[tuple] = []  # sweep: spans still open at t0
            for t0, t1, ident in rows:
                active = [r for r in active if r[1] > t0]
                for _a0, a1, aident in active:
                    if aident == ident:
                        continue
                    ov = min(a1, t1) - t0
                    if ov <= 0.0:
                        continue
                    a, b = sorted((aident, ident))
                    cell = cells.setdefault(
                        (link, a, b), {"s": 0.0, "n": 0, "ranks": set()})
                    cell["s"] += ov
                    cell["n"] += 1
                    cell["ranks"].add(rank)
                active.append((t0, t1, ident))
    out: List[Finding] = []
    for (link, a, b), cell in sorted(cells.items()):
        out.append(_finding(
            f"{a!r} and {b!r} overlap on the {link} link class for "
            f"{cell['s'] * 1e3:.3f} ms across {cell['n']} span pair(s) "
            f"but are tuned independently — each plan prices the link "
            f"at full bandwidth, so both run below their modeled GB/s "
            f"under contention.  Inspect with obs_report --contention; "
            f"co-tune them into one plan or serialize the issue order.",
            link=link, identities=[a, b], contended_s=cell["s"],
            n_pairs=cell["n"], ranks=sorted(cell["ranks"])))
    return out


# ---------------------------------------------------------------------------
# artifact-drift — committed artifacts vs the run-ledger schema registry
# ---------------------------------------------------------------------------

#: modeled-vs-measured link-rate disagreement a committed artifact may
#: carry before the rule flags its claims as priced on a stale wire
DRIFT_TOLERANCE_X = 1.5


@rule("artifact-drift", "warning",
      "committed artifacts must carry a registered schema, the common "
      "envelope, and modeled link rates consistent with the latest "
      "measured rates for the same device kind",
      requires=("artifact_census",))
def _artifact_drift(ctx) -> List[Finding]:
    """Three longitudinal invariants over the committed artifact set
    (``artifact_root=``, or ``cmn_lint --artifacts``):

    * **unknown schema** (error): an artifact the run-ledger registry
      cannot classify — it would land outside every gate and trend;
    * **missing envelope** (info, aggregated): artifacts predating the
      common envelope (no ``schema``+``git_sha`` stamp) — historical
      r01–r05 era files are expected here, NEW writers are not;
    * **modeled-rate drift** (warning): an artifact whose modeled
      ``link_gbps`` disagrees with the LATEST measured rates
      (LinkObservations / contention report) recorded for the SAME
      device kind by more than ``DRIFT_TOLERANCE_X`` — its gated claims
      are priced on a wire the fleet no longer has.  Rates measured on
      a different (or unknown) device kind never cross-contaminate.
    """
    census = ctx.artifact_census
    tol = float(getattr(ctx, "drift_tolerance", None)
                or DRIFT_TOLERANCE_X)
    out: List[Finding] = []
    legacy: List[str] = []
    # newest measured rates per (device_kind, link)
    measured: Dict[tuple, tuple] = {}   # (dk, link) -> (order, gbps, path)
    for row in census:
        man = row.get("manifest")
        if not man or man.get("device_kind") is None:
            continue
        order = (man.get("round") or "", man.get("timestamp") or "")
        for link, gbps in (man.get("link_gbps_measured") or {}).items():
            key = (man["device_kind"], link)
            if key not in measured or order >= measured[key][0]:
                measured[key] = (order, float(gbps), row["path"])
    for row in census:
        if "error" in row:
            out.append(Finding(
                rule="", severity="error", message=(
                    f"artifact {row['path']} is unreadable "
                    f"({row['error']}): it can be neither gated nor "
                    f"registered in the run ledger"),
                details={"artifact": row["path"],
                         "error": row["error"]}))
            continue
        cls = row.get("classification")
        if cls is None:
            doc = row.get("doc")
            declared = doc.get("schema") \
                if isinstance(doc, dict) else None
            out.append(Finding(
                rule="", severity="error", message=(
                    f"artifact {row['path']} has "
                    + (f"unregistered schema {declared!r}"
                       if declared else "no recognizable schema")
                    + " — register it in observability.ledger."
                    "KNOWN_SCHEMAS (and stamp the writer with "
                    "stamp_envelope) or the ledger, the gates, and the "
                    "trend lanes all skip it silently"),
                details={"artifact": row["path"],
                         "declared_schema": declared}))
            continue
        if cls.get("legacy"):
            legacy.append(row["path"])
        man = row["manifest"]
        dk = man.get("device_kind")
        if dk is None:
            continue
        for link, modeled in (man.get("link_gbps_modeled")
                              or {}).items():
            hit = measured.get((dk, link))
            if hit is None or modeled <= 0 or hit[1] <= 0:
                continue
            _order, meas, src = hit
            ratio = max(modeled / meas, meas / modeled)
            if ratio <= tol:
                continue
            out.append(_finding(
                f"artifact {row['path']} models the {link} link at "
                f"{modeled:g} GB/s but the latest measured rate for "
                f"device kind {dk!r} is {meas:g} GB/s ({src}) — "
                f"x{ratio:.2f} apart (tolerance x{tol:g}).  Every "
                f"speedup this artifact gates is priced on a wire the "
                f"fleet does not have; re-run the sweep or re-baseline "
                f"via perf_gate --ledger.",
                artifact=row["path"], link=link, device_kind=dk,
                modeled_gbps=modeled, measured_gbps=meas,
                measured_in=src, ratio=ratio, tolerance=tol))
    if legacy:
        out.append(Finding(
            rule="", severity="info", message=(
                f"{len(legacy)} committed artifact(s) predate the "
                f"common envelope (no schema/git_sha stamp): "
                f"{', '.join(legacy[:6])}"
                + (" ..." if len(legacy) > 6 else "")
                + ".  Historical artifacts stay as-is; new writers "
                "must stamp via observability.ledger.stamp_envelope."),
            details={"artifacts": legacy}))
    return out


# ---------------------------------------------------------------------------
# control-plane protocol rules — read the static ProtocolModel
# (analysis/protocol.py); ctx.protocol_model is built from protocol_root
# ---------------------------------------------------------------------------

def _overlaps(a, b) -> bool:
    return a[0] < b[1] and b[0] < a[1]


def _const_sites(model):
    """Sites with a resolved constant tag, excluding op-default tags (the
    sanctioned defaults: tag=0 plane, barrier 900)."""
    return [s for s in model.sites
            if s.tag.get("kind") == "const"
            and s.tag.get("provenance") != "default"
            and s.tag.get("value") is not None]


@rule("tag-band-collision", "error",
      "control-plane tag sets of two subsystems must not intersect",
      requires=("protocol_model",))
def _tag_band_collision(ctx) -> List[Finding]:
    """Tags are the only thing keeping concurrent object-plane protocols
    apart on a shared DCN edge (TELEMETRY_TAG=770, barrier 900,
    FLIGHT_TAG=(1<<28)+7, the default tag-0 plane) — and until now they
    were kept apart by comments.  Two failure shapes: a magic number
    landing inside a reserved band it does not own, and two subsystems'
    resolved tag intervals intersecting (arithmetic neighbors included:
    an allgather at t also consumes t+1).  A collision means a recv can
    complete against the WRONG protocol's payload — the worst kind of
    desync, because nothing hangs until the unpickle explodes."""
    from chainermn_tpu.runtime.control_plane import RESERVED_TAG_BANDS
    model = ctx.protocol_model
    out: List[Finding] = []
    bands = [b for b in RESERVED_TAG_BANDS.values() if b.name != "default"]
    default = RESERVED_TAG_BANDS["default"]
    sites = [s for s in _const_sites(model)
             # intervals fully inside the default band ride the shared
             # tag-0 plane — sanctioned for everyone
             if not (s.tag_interval()[0] >= default.base
                     and s.tag_interval()[1] <= default.stop)]
    # (a) magic literals inside a reserved band
    for s in sites:
        if s.tag.get("provenance") != "literal":
            continue
        iv = s.tag_interval()
        for band in bands:
            if _overlaps(iv, (band.base, band.stop)):
                out.append(_finding(
                    f"{s.where()}: literal tag {s.tag['source']} lands in "
                    f"the reserved {band.name!r} band "
                    f"[{band.base}, {band.stop}) owned by {band.owner} — "
                    f"import the named tag from "
                    f"runtime.control_plane.RESERVED_TAG_BANDS instead "
                    f"of a magic number",
                    site=s.as_dict(), band=band.as_dict()))
    # (b) cross-subsystem interval intersections
    seen = set()
    for i, a in enumerate(sites):
        for b in sites[i + 1:]:
            if a.subsystem == b.subsystem:
                continue
            iva, ivb = a.tag_interval(), b.tag_interval()
            if not _overlaps(iva, ivb):
                continue
            # a matched p2p channel across subsystems is deliberate
            if {a.op, b.op} == {"send_obj", "recv_obj"} \
                    or (a.raw and b.raw and {a.op, b.op} == {"send",
                                                            "recv"}):
                continue
            # both sides naming the same reserved band is the sanctioned
            # way to share it (gather_telemetry's producer + consumer)
            band = next((bd for bd in bands
                         if iva[0] >= bd.base and iva[1] <= bd.stop
                         and ivb[0] >= bd.base and ivb[1] <= bd.stop), None)
            if band is not None and a.tag.get("provenance") == "named" \
                    and b.tag.get("provenance") == "named":
                continue
            key = (a.file, a.line, b.file, b.line)
            if key in seen:
                continue
            seen.add(key)
            out.append(_finding(
                f"{a.where()} ({a.subsystem}: {a.op} on tags "
                f"[{iva[0]}, {iva[1]})) collides with {b.where()} "
                f"({b.subsystem}: {b.op} on tags [{ivb[0]}, {ivb[1]})) — "
                f"two subsystems share a wire tag, so either protocol "
                f"can consume the other's payload; claim a band in "
                f"RESERVED_TAG_BANDS",
                site=a.as_dict(), other=b.as_dict()))
    return out


@rule("lockstep-divergence", "error",
      "collective object ops must be reachable on every rank's path",
      requires=("protocol_model",))
def _lockstep_divergence(ctx) -> List[Finding]:
    """The static twin of the flight recorder's ``identify_desync``: a
    collective object op under a rank guard (``if rank == 0:``) with no
    collective on the complementary branch means the guarded ranks enter
    a tree collective their peers never join — the exact hang the
    watchdog diagnoses post-mortem, caught before a mesh is involved.
    Same logic for except-handlers: a collective that only runs on the
    exception path desyncs the ranks that did not fault."""
    model = ctx.protocol_model
    out: List[Finding] = []
    collectives = model.collectives()
    for s in collectives:
        if s.rank_guards:
            g = s.rank_guards[-1]
            complement = "orelse" if g["branch"] == "body" else "body"
            matched = any(
                o is not s and o.file == s.file and any(
                    og.get("line") == g["line"]
                    and og.get("branch") == complement
                    for og in o.guards)
                for o in collectives)
            if not matched:
                out.append(_finding(
                    f"{s.where()}: collective {s.op} runs only under rank "
                    f"guard `{g['test']}` ({g['branch']} branch) with no "
                    f"collective on the complementary path — unguarded "
                    f"ranks never join the tree and the mesh wedges "
                    f"(identify_desync would report this rank stuck in "
                    f"{s.op})",
                    site=s.as_dict(), guard=g))
        for t in s.trys:
            if t["branch"] != "except":
                continue
            matched = any(
                o is not s and o.collective and o.file == s.file and any(
                    ot.get("line") == t["line"]
                    and ot.get("branch") == "try"
                    for ot in o.trys)
                for o in model.sites)
            if not matched:
                out.append(_finding(
                    f"{s.where()}: collective {s.op} runs only on an "
                    f"except path (try at line {t['line']}) — ranks that "
                    f"did not fault sail past while the faulted rank "
                    f"blocks in {s.op}",
                    site=s.as_dict(), try_line=t["line"]))
    return out


def _p2p_key_matches(a, b) -> bool:
    """Can send site ``a`` pair with recv site ``b``? Same plane (raw vs
    object), and overlapping tag sets: const↔const by interval, param↔
    param by base offset; a dynamic tag is a wildcard (statically
    unknowable — never report it unmatched, never let it mask a const
    mismatch elsewhere)."""
    if a.raw != b.raw:
        return False
    ta, tb = a.tag, b.tag
    if "dynamic" in (ta.get("kind"), tb.get("kind")):
        return True
    if ta.get("kind") == "const" and tb.get("kind") == "const":
        return _overlaps(a.tag_interval(), b.tag_interval())
    if ta.get("kind") == "param" and tb.get("kind") == "param":
        return ta.get("base") == tb.get("base")
    # const vs param: a parametric endpoint can be instantiated at the
    # const tag iff the const lies in the param namespace's band
    cs, ps = (ta, tb) if ta.get("kind") == "const" else (tb, ta)
    return cs.get("value", -1) >= ps.get("base", 0)


@rule("unmatched-send-recv", "error",
      "every p2p send needs a structurally matching recv (and vice versa)",
      requires=("protocol_model",))
def _unmatched_send_recv(ctx) -> List[Finding]:
    """A ``send_obj`` whose (plane, tag) no ``recv_obj`` in the tree can
    match blocks forever once the transport's buffering runs out — and an
    orphaned recv blocks immediately.  This is the seam ROADMAP item 2's
    pipeline-parallel p2p stages will stress: every new stage boundary
    adds a send/recv pair that must line up by tag."""
    model = ctx.protocol_model
    out: List[Finding] = []
    sends = [s for s in model.p2p()
             if s.op in ("send_obj", "send")]
    recvs = [s for s in model.p2p()
             if s.op in ("recv_obj", "recv")]
    for s in sends:
        if not any(_p2p_key_matches(s, r) for r in recvs):
            out.append(_finding(
                f"{s.where()}: {s.op} on tag {s.tag.get('source')} has no "
                f"structurally matching recv anywhere in the tree — the "
                f"payload is never consumed and the peer's inbox grows "
                f"until the transport stalls",
                site=s.as_dict()))
    for r in recvs:
        if not any(_p2p_key_matches(s, r) for s in sends):
            out.append(_finding(
                f"{r.where()}: {r.op} on tag {r.tag.get('source')} has no "
                f"structurally matching send anywhere in the tree — this "
                f"endpoint blocks forever",
                site=r.as_dict()))
    return out


@rule("wrapper-surface-drift", "error",
      "wrapper classes must accept and forward the full wrapped surface",
      requires=("protocol_model",))
def _wrapper_surface_drift(ctx) -> List[Finding]:
    """A proxy that forwards an object op while silently narrowing its
    signature turns a working call into a TypeError — exactly the
    ``InstrumentedCommunicator`` bug where ``gather_obj`` dropped
    ``tag=`` and every instrumented ``gather_telemetry``
    (tag=TELEMETRY_TAG) exploded.  Generic check: a class forwarding two
    or more object ops to the same wrapped attribute must, for each
    forwarded op, accept every optional parameter some implementation of
    that op defines, and actually pass it across the forwarding
    boundary."""
    model = ctx.protocol_model
    out: List[Finding] = []
    reference: Dict[str, set] = {}
    for c in model.class_ops:
        if not c.forwards_to:
            reference.setdefault(c.op, set()).update(c.optional_params)
    by_wrapper: Dict[tuple, list] = {}
    for c in model.class_ops:
        if c.forwards_to:
            by_wrapper.setdefault((c.file, c.cls, c.forwards_to),
                                  []).append(c)
    for (file, cls, attr), ops in by_wrapper.items():
        if len(ops) < 2:   # a one-off delegation is not a wrapper surface
            continue
        for c in ops:
            ref = reference.get(c.op, set())
            dropped = sorted(ref - set(c.params))
            if dropped:
                out.append(_finding(
                    f"{c.file}:{c.line}: {cls}.{c.op} forwards to "
                    f"self.{attr} but does not accept "
                    f"{', '.join(dropped)} — parameters the wrapped "
                    f"surface takes; callers passing them get a "
                    f"TypeError only through the wrapper",
                    cls=cls, op=c.op, file=c.file, line=c.line,
                    dropped=dropped, forwards_to=attr))
            swallowed = sorted((ref & set(c.params))
                               - set(c.forwarded_params))
            if swallowed:
                out.append(_finding(
                    f"{c.file}:{c.line}: {cls}.{c.op} accepts "
                    f"{', '.join(swallowed)} but drops them at the "
                    f"forwarding boundary to self.{attr} — the wrapped "
                    f"call silently runs with defaults",
                    cls=cls, op=c.op, file=c.file, line=c.line,
                    swallowed=swallowed, forwards_to=attr))
    return out


@rule("protocol-replay-desync", "error",
      "recorded object-plane event sequences must agree across ranks",
      requires=("protocol_model", "flight_events"))
def _protocol_replay_desync(ctx) -> List[Finding]:
    """Replay a flight dump's per-rank object-plane events against the
    static model: ranks that completed different op sequences, or a rank
    wedged inside an op its peers sailed past, are protocol violations —
    with the model's rank-guarded collective sites attached as prime
    suspects.  This is the triage path for ``elastic_run`` incident
    manifests (restart_manifest/v1 embeds the per-rank dumps)."""
    from chainermn_tpu.analysis.protocol import (
        load_events_by_rank, replay_flight)
    events = load_events_by_rank(ctx.flight_events)
    out: List[Finding] = []
    for v in replay_flight(ctx.protocol_model, events):
        f = _finding(v["message"], **{k: val for k, val in v.items()
                                      if k != "message"})
        if v.get("kind") == "unknown-op":
            f.severity = "info"
        out.append(f)
    return out


__all__ = ["CPU_WIRE_PROMOTIONS", "DRIFT_TOLERANCE_X", "Finding",
           "NP_TO_HLO_DTYPE", "Rule", "SEVERITIES", "all_rules",
           "expected_kinds", "get_rule", "rule"]
