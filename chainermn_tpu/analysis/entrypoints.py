"""Named lintable entry points — the programs ``tools/cmn_lint.py`` (and
the CI clean sweep) hold to zero error findings.

Each entry point rebuilds the example's train step the way the example
itself does — same builder (:func:`make_train_step` / the long-context
jit), same loss structure, same donation — but at toy sizes, because the
lint only reads the *schedule*: collective structure is invariant to
width, so a 16-unit MLP proves the same theorem as the 1000-unit one at
a fraction of the trace/compile cost.

Everything here runs on the tier-1 CPU mesh: no TPU, no process spawn.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
import optax

from chainermn_tpu.analysis.lint import LintReport, lint_step

#: the seven communicator flavors the mnist sweep must hold clean
#: (pure_nccl is the xla alias and is accepted as a spelling)
MNIST_FLAVORS = ("naive", "flat", "hierarchical", "two_dimensional",
                 "single_node", "non_cuda_aware", "xla")

#: flavors whose decomposition needs a two-level topology on 8 devices
_NEEDS_INTRA = {"hierarchical": 4, "two_dimensional": 4}


def _mnist_target(flavor: str):
    """The mnist example's step at toy width: MLP + multi-node Adam +
    ``make_train_step(has_aux=True)`` (donating params/opt_state exactly
    like the example's hot loop)."""
    import chainermn_tpu
    from chainermn_tpu.models import MLP
    from chainermn_tpu.optimizers import init_opt_state, make_train_step

    comm = chainermn_tpu.create_communicator(
        flavor, intra_size=_NEEDS_INTRA.get(flavor))
    model = MLP(16, 10)
    params = model.init(jax.random.key(0), jnp.zeros((1, 784)))
    optimizer = chainermn_tpu.create_multi_node_optimizer(
        optax.adam(1e-3), comm)
    opt_state = init_opt_state(comm, optimizer, params)

    def loss_fn(p, batch):
        x, y = batch
        logits = model.apply(p, x)
        loss = optax.softmax_cross_entropy_with_integer_labels(
            logits, y).mean()
        acc = (logits.argmax(-1) == y).mean()
        return loss, {"accuracy": acc}

    step = make_train_step(comm, loss_fn, optimizer, has_aux=True)
    batch = (jnp.zeros((comm.size * 4, 784), jnp.float32),
             jnp.zeros((comm.size * 4,), jnp.int32))
    return comm, step, (params, opt_state, batch), loss_fn


def lint_mnist(flavors: Optional[Sequence[str]] = None,
               rules: Optional[Sequence[str]] = None,
               hlo: bool = True) -> List[LintReport]:
    """One report per communicator flavor for the mnist step.  Every rule
    runs: schedule-desync over two independent traces (every rank runs
    this same builder, so identical traces ARE the invariant),
    census-drift over the flavor's compiled allreduce, the gradient
    probe over the example's loss, captured-constant/donation-alias/
    async-pair over the traced + compiled step."""
    reports = []
    for flavor in (flavors or MNIST_FLAVORS):
        comm, step, args, loss_fn = _mnist_target(flavor)
        params, opt_state, batch = args
        reports.append(lint_step(
            step, *args,
            name=f"examples/mnist[{flavor}]",
            comm=comm, flavor=flavor,
            loss=loss_fn, loss_args=(params, batch),
            donate_argnums=(0, 1),
            variants={"rank0": (step,) + args, "rank1": (step,) + args},
            census=True, hlo=hlo, rules=rules,
            raise_on_error=False))
    return reports


def _long_context_target():
    """The long-context example's non-FSDP ring-attention step at toy
    size: seq 128 over the 8-way ``sp`` mesh, loss traced through the
    example's own shard_map (ppermute ring + explicit psums)."""
    from jax.sharding import Mesh, PartitionSpec as P

    from chainermn_tpu.models import TransformerLM
    from chainermn_tpu.utils import shard_map

    devices = jax.devices()
    n_sp = len(devices)
    seq_len = 16 * n_sp
    t_local = seq_len // n_sp
    kw = dict(vocab=32, d_model=16, n_layers=1, n_heads=2,
              max_len=seq_len)
    model = TransformerLM(attention_impl="ring", axis_name="sp", **kw)
    ref_init = TransformerLM(attention_impl="xla", **kw)
    mesh = Mesh(np.array(devices[:n_sp]), ("sp",))
    toks = jnp.zeros((2, seq_len), jnp.int32)
    params = ref_init.init(jax.random.key(0), toks[:, :8])
    opt = optax.sgd(1e-2)
    opt_state = opt.init(params)

    def sp_body(pp, tkk):
        me = jax.lax.axis_index("sp")
        logits = model.apply(pp, tkk, pos_offset=me * t_local)
        nxt = jax.lax.ppermute(
            tkk[:, :1], "sp",
            perm=[(i, (i - 1) % n_sp) for i in range(n_sp)])
        targets = jnp.concatenate([tkk[:, 1:], nxt], axis=1)
        ce = optax.softmax_cross_entropy_with_integer_labels(
            logits, targets)
        mask = jnp.ones_like(ce).at[:, -1].set(
            jnp.where(me == n_sp - 1, 0.0, 1.0))
        total = jax.lax.psum((ce * mask).sum(), "sp")
        count = jax.lax.psum(mask.sum(), "sp")
        return total / count

    def loss_fn(p_, tk):
        return shard_map(sp_body, mesh=mesh,
                         in_specs=(P(), P(None, "sp")),
                         out_specs=P(), check_vma=False)(p_, tk)

    @jax.jit
    def step(p_, s_, tk):
        l, g = jax.value_and_grad(loss_fn)(p_, tk)
        updates, s_ = opt.update(g, s_, p_)
        return optax.apply_updates(p_, updates), s_, l

    return step, (params, opt_state, toks)


def lint_long_context(rules: Optional[Sequence[str]] = None,
                      hlo: bool = True) -> List[LintReport]:
    """One report for the long-context ring-attention step.  No
    communicator object is in play (the example drives shard_map
    directly), so the comm-bound rules (census-drift, the gradient
    probe) report as skipped; schedule-desync, captured-constant,
    donation-alias, and async-pair all run."""
    step, args = _long_context_target()
    return [lint_step(
        step, *args,
        name="examples/long_context[ring]",
        variants={"rank0": (step,) + args, "rank1": (step,) + args},
        hlo=hlo, rules=rules, raise_on_error=False)]


def _resnet_fused_target(flavor: str = "xla"):
    """The resnet example's step with the fused normalization path
    (``ops.FusedBatchNormAct`` at every BN boundary) at toy width — the
    program the fusednorm probe variant and the remat autotuner time.
    The Pallas kernels ride inside the shard_map'd loss via their custom
    VJP; they contain no collectives, so the lintable schedule must stay
    exactly the flavor's gradient-allreduce plan (census-drift) and the
    backward must add no unpinned psum (the custom VJP *is* the pin)."""
    import chainermn_tpu
    from chainermn_tpu.models import ResNet
    from chainermn_tpu.models.resnet import BasicBlock
    from chainermn_tpu.ops import FusedBatchNormAct
    from chainermn_tpu.optimizers import (
        init_model_state, init_opt_state, make_train_step)

    comm = chainermn_tpu.create_communicator(
        flavor, intra_size=_NEEDS_INTRA.get(flavor))
    model = ResNet(stage_sizes=(1,), block_cls=BasicBlock, num_filters=8,
                   num_classes=10, norm_cls=FusedBatchNormAct)
    x0 = jnp.zeros((1, 16, 16, 3), jnp.float32)
    variables = model.init(jax.random.key(0), x0)
    params = variables["params"]
    stats0 = variables["batch_stats"]
    optimizer = chainermn_tpu.create_multi_node_optimizer(
        optax.sgd(0.1, momentum=0.9), comm)
    model_state = init_model_state(comm, stats0)
    opt_state = init_opt_state(comm, optimizer, params)

    def loss_fn(p, state, batch):
        x, y = batch
        logits, mut = model.apply(
            {"params": p, "batch_stats": state}, x, train=True,
            mutable=["batch_stats"])
        loss = optax.softmax_cross_entropy_with_integer_labels(
            logits, y).mean()
        return loss, mut["batch_stats"]

    # The gradient probe wants loss(params, *sharded_rest): close over the
    # (tiny, replicated) initial stats so only the batch is sharded.
    def probe_loss(p, batch):
        return loss_fn(p, stats0, batch)[0]

    step = make_train_step(comm, loss_fn, optimizer, with_model_state=True)
    batch = (jnp.zeros((comm.size * 2, 16, 16, 3), jnp.float32),
             jnp.zeros((comm.size * 2,), jnp.int32))
    args = (params, model_state, opt_state, batch)
    return comm, step, args, probe_loss


def lint_resnet_fused(rules: Optional[Sequence[str]] = None,
                      hlo: bool = True) -> List[LintReport]:
    """One report for the fused-norm resnet train step (xla flavor).
    Every rule runs: the desync variants trace the builder twice, the
    census holds the compiled collectives to the flavor's plan (the
    Pallas calls must contribute zero), and the gradient probe
    differentiates through the fused kernels' custom VJP inside the SPMD
    region — a regrown stats-path psum would land here as
    unpinned-transpose."""
    comm, step, args, probe_loss = _resnet_fused_target()
    params, _, _, batch = args
    return [lint_step(
        step, *args,
        name="examples/resnet_fused[xla]",
        comm=comm, flavor="xla",
        loss=probe_loss, loss_args=(params, batch),
        donate_argnums=(0, 1, 2),
        variants={"rank0": (step,) + args, "rank1": (step,) + args},
        census=True, hlo=hlo, rules=rules,
        raise_on_error=False)]


def _moe_train_target():
    """The MoE LM example's train step at toy size over the 2x4
    ``("ep", "data")`` mesh: tokens sharded over ``data``, the expert
    MLPs dispatched over ``ep`` through a planner all-to-all plan
    (``moe_plan=``), loss differentiated outside the shard_map exactly
    like the example.  The census spec is the plan itself: the
    ``census=`` callable compiles ONE ``execute_alltoall`` over the ep
    axis, so census-drift holds the compiled exchange to
    ``plan_census_kinds`` of the MoE dispatch plan — expected kinds are
    DERIVED from the IR, never hand-written."""
    from jax.sharding import Mesh, PartitionSpec as P

    from chainermn_tpu.models import TransformerLM
    from chainermn_tpu.planner.compiler import execute_alltoall
    from chainermn_tpu.planner.ir import PlanTopology
    from chainermn_tpu.planner.plans import alltoall_plans
    from chainermn_tpu.utils import shard_map

    devices = jax.devices()
    if len(devices) < 8:
        raise RuntimeError(
            f"moe/train needs 8 devices for the 2x4 ep x data mesh, "
            f"have {len(devices)}")
    mesh = Mesh(np.array(devices[:8]).reshape(2, 4), ("ep", "data"))
    topo = PlanTopology(axes=(("ep", 2),))
    plan = next(p for p in alltoall_plans(topo)
                if p.name == "alltoall_flat_bfloat16")
    model = TransformerLM(vocab=32, d_model=16, n_layers=1, n_heads=2,
                          max_len=32, attention_impl="xla",
                          moe_experts=4, moe_top_k=2, moe_axis="ep",
                          moe_plan=plan)
    toks = jnp.zeros((8, 16), jnp.int32)

    # init inside the SPMD region (router/expert shapes bind the ep axis)
    params = jax.jit(shard_map(
        lambda tk: model.init(jax.random.key(0), tk), mesh=mesh,
        in_specs=P("data"), out_specs=P(), check_vma=False))(toks)
    opt = optax.sgd(1e-2)
    opt_state = opt.init(params)

    def loss_fn(p_, tk):
        def body(pp, tkk):
            logits, mut = model.apply(pp, tkk, mutable=["moe_stats"])
            ce = optax.softmax_cross_entropy_with_integer_labels(
                logits[:, :-1], tkk[:, 1:]).mean()
            aux = mut["moe_stats"]["block_0"]["aux_loss"][0]
            return jax.lax.pmean(ce, ("ep", "data")) + 1e-2 * aux

        return shard_map(body, mesh=mesh, in_specs=(P(), P(None, "data")),
                         out_specs=P(), check_vma=False)(p_, tk)

    @jax.jit
    def step(p_, s_, tk):
        l, g = jax.value_and_grad(loss_fn)(p_, tk)
        updates, s_ = opt.update(g, s_, p_)
        return optax.apply_updates(p_, updates), s_, l

    # the census program: ONE plan execution over the ep axis — the
    # exchange the MoE layer rides twice per application
    buf = jnp.zeros((4, 8, 16), jnp.float32)

    def census_hlo():
        return jax.jit(shard_map(
            lambda b: execute_alltoall(plan, topo, b), mesh=mesh,
            in_specs=P("ep"), out_specs=P("ep"),
            check_vma=False)).lower(buf).compile().as_text()

    return step, (params, opt_state, toks), plan, census_hlo


def lint_moe_train(rules: Optional[Sequence[str]] = None,
                   hlo: bool = True) -> List[LintReport]:
    """One report for the MoE transformer train step (2x4 ep x data
    mesh).  census-drift holds the compiled token exchange to the MoE
    dispatch plan's derived kinds and per-hop wire dtypes;
    wire-dtype-mismatch checks the plan's declared bf16 wire actually
    appears in the step's compiled program; schedule-desync,
    captured-constant, donation-alias and async-pair run over the full
    step.  No communicator object is in play (the example drives
    shard_map directly), so the gradient-probe rule reports as
    skipped."""
    step, args, plan, census_hlo = _moe_train_target()
    return [lint_step(
        step, *args,
        name="examples/moe_lm[ep2xdata4]",
        plan=plan, census=census_hlo,
        variants={"rank0": (step,) + args, "rank1": (step,) + args},
        hlo=hlo, rules=rules, raise_on_error=False)]


def _serving_decode_target(tp: int = 2):
    """The serving engine's fused prefill+decode forward at toy size,
    tensor-parallel over 2 devices — the jitted program every serving
    step replays.  The interesting schedule is the tp > 1 one: Megatron
    row-parallel psums over the ``"tp"`` axis inside shard_map (tp=1
    compiles to a collective-free program)."""
    from chainermn_tpu.models.transformer import TransformerLM
    from chainermn_tpu.serving import InferenceEngine, ServingConfig

    model = TransformerLM(vocab=32, d_model=16, n_layers=1, n_heads=2,
                          max_len=64, attention_impl="xla")
    params = model.init(jax.random.key(0), jnp.zeros((1, 4), jnp.int32))
    cfg = ServingConfig(page_size=4, num_pages=8, max_seqs=2,
                        chunk_tokens=4, max_pages_per_seq=4, tp_size=tp)
    eng = InferenceEngine(model, params, cfg)
    eng.submit([1, 2, 3], max_new_tokens=2)
    eng.scheduler.apply_plan(eng.scheduler.build_plan())
    batch = eng.scheduler.step_batch()
    args = (eng._params, eng._ck, eng._cv,
            jnp.asarray(batch["page_table"]),
            jnp.asarray(batch["tokens"]), jnp.asarray(batch["pos0"]),
            jnp.asarray(batch["n_new"]))
    return eng._fwd, args


def _serving_spec_target(tp: int = 2):
    """The serving engine's fused draft+verify speculative step at toy
    size, tensor-parallel over 2 devices.  Self-draft (the 1-layer toy
    is its own draft): the schedule theorem — k greedy draft micro-steps
    plus one target verify pass, all Megatron psums over ``"tp"`` inside
    ONE jitted program — is invariant to which weights the draft loads.
    """
    from chainermn_tpu.models.transformer import TransformerLM
    from chainermn_tpu.serving import InferenceEngine, ServingConfig

    model = TransformerLM(vocab=32, d_model=16, n_layers=1, n_heads=2,
                          max_len=64, attention_impl="xla")
    params = model.init(jax.random.key(0), jnp.zeros((1, 4), jnp.int32))
    cfg = ServingConfig(page_size=4, num_pages=8, max_seqs=2,
                        chunk_tokens=4, max_pages_per_seq=4, tp_size=tp,
                        spec_k=2)
    eng = InferenceEngine(model, params, cfg,
                          draft_model=model, draft_params=params)
    eng.submit([1, 2, 3], max_new_tokens=4)
    eng.scheduler.apply_plan(eng.scheduler.build_plan())
    batch = eng.scheduler.step_batch()
    args = (eng._params, eng._dparams, eng._ck, eng._cv,
            eng._dck, eng._dcv,
            jnp.asarray(batch["page_table"]),
            jnp.asarray(batch["tokens"]), jnp.asarray(batch["pos0"]),
            jnp.asarray(batch["n_new"]), jnp.asarray(batch["decode"]),
            jnp.asarray(batch["prev"]))
    return eng._fwd_spec, args


def lint_serving_decode(rules: Optional[Sequence[str]] = None,
                        hlo: bool = True) -> List[LintReport]:
    """Two reports for the serving forward programs (tp=2): the plain
    fused prefill+decode step and the fused draft+verify speculative
    step.  Lockstep serving has the same SPMD obligation as training —
    every controller must trace the identical schedule from the
    broadcast plan (for the spec step: including the accept/reject
    computation, whose decisions ride that plan's envelope) — so the
    schedule-desync variants run each builder twice, exactly as a rank
    pair would.  No communicator object is in play (the engine drives
    shard_map directly), so the comm-bound rules report as skipped."""
    step, args = _serving_decode_target()
    reports = [lint_step(
        step, *args,
        name="serving/decode[tp2]",
        variants={"rank0": (step,) + args, "rank1": (step,) + args},
        hlo=hlo, rules=rules, raise_on_error=False)]
    spec_step, spec_args = _serving_spec_target()
    reports.append(lint_step(
        spec_step, *spec_args,
        name="serving/decode[tp2,spec]",
        variants={"rank0": (spec_step,) + spec_args,
                  "rank1": (spec_step,) + spec_args},
        hlo=hlo, rules=rules, raise_on_error=False))
    return reports


def _serving_weights_target():
    """The router's multicast weight-distribution program: the per-leaf
    staged broadcast (``planner.compiler._run_stages_leaf``) the fleet
    replicates params through, compiled on the flat 8-way communicator,
    with the plan IR as the census spec."""
    import chainermn_tpu
    from chainermn_tpu.planner.compiler import _run_stages_leaf
    from chainermn_tpu.serving import weights_multicast_plan

    comm = chainermn_tpu.create_communicator("flat")
    topo = comm.plan_topology()
    plan = weights_multicast_plan(root=0, topology=topo,
                                  name="serving_weights")
    leaf = jnp.zeros((comm.size, 64), jnp.float32)

    def program(stacked):
        return _run_stages_leaf(plan, topo, stacked)

    def census_hlo():
        return comm.compiled_hlo(program, leaf)

    fn = comm._spmd_program(program, jit=True)
    return comm, plan, fn, ((leaf,),), census_hlo


def lint_serving_weights(rules: Optional[Sequence[str]] = None,
                         hlo: bool = True) -> List[LintReport]:
    """One report for the fleet weight-distribution multicast.  The
    census here is NOT the training allreduce: the ``census=`` callable
    compiles the router's own broadcast program and census-drift holds
    its collective decomposition to ``plan_census_kinds`` of the
    multicast plan — params must reach every replica through the plan's
    ONE masked-psum stage chain, never a fan of point-to-point sends."""
    comm, plan, fn, args, census_hlo = _serving_weights_target()
    return [lint_step(
        fn, *args,
        name="serving/weights[multicast]",
        comm=comm, plan=plan, census=census_hlo,
        variants={"rank0": (fn,) + args, "rank1": (fn,) + args},
        hlo=hlo, rules=rules, raise_on_error=False)]


ENTRY_POINTS: Dict[str, dict] = {
    "examples/mnist": {
        "fn": lint_mnist,
        "flavors": MNIST_FLAVORS,
        "help": "MLP data-parallel step, one report per communicator "
                "flavor (census + gradient probe + desync variants)",
    },
    "examples/long_context": {
        "fn": lint_long_context,
        "flavors": None,
        "help": "ring-attention sequence-parallel LM step (schedule, "
                "captured-constant, donation, async rules)",
    },
    "examples/resnet_fused": {
        "fn": lint_resnet_fused,
        "flavors": None,
        "help": "resnet train step with the fused BN(+ReLU) Pallas "
                "kernels at every norm boundary (census + gradient probe "
                "through the custom VJP + desync variants)",
    },
    "moe/train": {
        "fn": lint_moe_train,
        "flavors": None,
        "help": "MoE transformer train step over the 2x4 ep x data mesh: "
                "census-drift holds the compiled token exchange to the "
                "dispatch plan's derived kinds and wire dtypes (plus "
                "schedule/captured-constant/donation/async rules)",
    },
    "serving/decode": {
        "fn": lint_serving_decode,
        "flavors": None,
        "help": "serving engine fused forwards, tp=2 Megatron shard_map: "
                "plain prefill+decode AND the draft+verify speculative "
                "step (schedule, captured-constant, async rules)",
    },
    "serving/weights": {
        "fn": lint_serving_weights,
        "flavors": None,
        "help": "fleet weight-distribution multicast: census-drift holds "
                "the compiled broadcast program to the multicast plan IR "
                "(plus schedule/async rules)",
    },
}


def lint_entry_point(name: str, flavors: Optional[Sequence[str]] = None,
                     rules: Optional[Sequence[str]] = None,
                     hlo: bool = True) -> List[LintReport]:
    """Run a named entry point's lint sweep, returning its reports."""
    try:
        entry = ENTRY_POINTS[name]
    except KeyError:
        raise ValueError(
            f"unknown entry point {name!r}; available: "
            f"{sorted(ENTRY_POINTS)}") from None
    if entry["flavors"] is not None:
        return entry["fn"](flavors=flavors, rules=rules, hlo=hlo)
    if flavors:
        raise ValueError(f"{name} takes no --flavors")
    return entry["fn"](rules=rules, hlo=hlo)


__all__ = ["ENTRY_POINTS", "MNIST_FLAVORS", "lint_entry_point",
           "lint_long_context", "lint_mnist", "lint_moe_train",
           "lint_resnet_fused", "lint_serving_decode",
           "lint_serving_weights"]
