"""Multi-node optimizers — gradient allreduce woven into the update step.

Reference being rebuilt (path unverified, SURVEY.md provenance):
〔chainermn/optimizers.py〕 — ``create_multi_node_optimizer(opt, comm,
double_buffering=False)`` wraps any Chainer optimizer so ``update()`` runs
local forward/backward, then ``comm.allreduce_grad(model)``, then the inner
update rule; ``_DoubleBufferingOptimizer`` (the fork's flagship) keeps two
gradient buffer sets and a dedicated CUDA stream so the allreduce of step
t-1's gradients overlaps the forward/backward of step t, applying averaged
gradients with one step of staleness.

TPU-native design: the wrapped object is an **optax GradientTransformation**
(the Chainer-optimizer role in the JAX world) and the overlap is expressed as
*dataflow*, not streams.  In :class:`_DoubleBufferingOptimizer`, ``update``
allreduces the gradients stored from the previous step and stashes the fresh
local gradients for the next one.  Inside the jitted train step the psum of
the stale gradients has no data dependency on the current forward/backward,
so XLA's latency-hiding scheduler is free to overlap the collective with
compute — the very overlap the reference engineered with a side stream, here
obtained from the compiler.  The 1-step-staleness semantics (first update
applies zero gradients) are preserved exactly, because they are what changes
convergence (SURVEY.md §3.4).
"""

from __future__ import annotations

from typing import Any, Callable, NamedTuple

import jax

from chainermn_tpu.utils import shard_map as _shard_map
import jax.numpy as jnp
import optax
from jax.sharding import NamedSharding, PartitionSpec as P

from chainermn_tpu.communicators import _packing
from chainermn_tpu.utils import pvary
from chainermn_tpu.utils.placement import local_device_put


class _MultiNodeOptimizer:
    """optax-compatible wrapper: allreduce-mean the grads, then inner update.

    Reference: ``_MultiNodeOptimizer`` 〔optimizers.py〕, which delegated all
    attributes to the wrapped optimizer; here the optax interface is two
    functions, so delegation is explicit (`init`/`update` + passthrough).

    ``compression`` (a stateless codec, i.e. :class:`NoCompression`) is
    forwarded to ``allreduce_grad`` per call — ``NoCompression(wire)``
    lowers to the exact cast-allreduce-cast program of the legacy
    ``allreduce_grad_dtype`` knob.  Stateful quantizers live in
    :class:`_CompressedOptimizer` instead (they thread EF state).
    """

    def __init__(self, actual_optimizer: optax.GradientTransformation, comm,
                 compression=None):
        self.actual_optimizer = actual_optimizer
        self.communicator = comm
        self.compression = compression

    def init(self, params):
        return self.actual_optimizer.init(params)

    def update(self, grads, state, params=None, **kwargs):
        grads = self.communicator.allreduce_grad(
            grads, compressor=self.compression)
        return self.actual_optimizer.update(grads, state, params, **kwargs)

    # pytree spec of this optimizer's state inside an SPMD train step:
    # everything is device-invariant (replicated).
    def state_partition_spec(self):
        return P()


class _CompressedState(NamedTuple):
    inner: Any   # wrapped optimizer's state (replicated)
    comp: Any    # CompressionState — EF residual is per-rank (varying)


class _CompressedOptimizer:
    """Quantized gradient exchange: ``allreduce_grad(compressor=...)``
    with the error-feedback state carried inside the optimizer state —
    **beyond-reference extension** (see :mod:`chainermn_tpu.compression`).

    The EF residual is device-varying (each rank remembers ITS
    quantization error), so it rides the optimizer-state slot exactly the
    way the double-buffer's pending gradients do: stacked ``[size, ...]``
    outside the step, squeezed to the local state inside.
    """

    def __init__(self, actual_optimizer: optax.GradientTransformation, comm,
                 compression):
        self.actual_optimizer = actual_optimizer
        self.communicator = comm
        self.compression = compression

    def init(self, params):
        return _CompressedState(
            inner=self.actual_optimizer.init(params),
            comp=self.communicator.init_compression_state(
                params, self.compression))

    def update(self, grads, state, params=None, **kwargs):
        grads, comp = self.communicator.allreduce_grad(
            grads, compressor=self.compression, state=state.comp)
        updates, inner = self.actual_optimizer.update(
            grads, state.inner, params, **kwargs)
        return updates, _CompressedState(inner=inner, comp=comp)

    def state_partition_spec(self):
        return _CompressedState(inner=P(), comp=_VARYING)


class _DoubleBufferState(NamedTuple):
    inner: Any            # wrapped optimizer's state (replicated)
    pending: Any          # previous step's *local* grads (device-varying)
    step: jnp.ndarray     # update counter


class _DoubleBufferingOptimizer:
    """The fork's double-buffered optimizer, as dataflow.

    Semantics (reference 〔optimizers.py〕, SURVEY.md §3.4): update at step t
    applies the allreduced gradients of step t-1 (1-step staleness); step 0
    applies zero gradients (buffers start zero-filled).  The allreduce of the
    pending buffer is independent of step t's forward/backward, which is what
    lets the collective overlap compute under XLA's scheduler.
    """

    def __init__(self, actual_optimizer: optax.GradientTransformation, comm):
        self.actual_optimizer = actual_optimizer
        self.communicator = comm

    def init(self, params):
        zeros = jax.tree.map(jnp.zeros_like, params)
        return _DoubleBufferState(
            inner=self.actual_optimizer.init(params),
            pending=zeros,
            step=jnp.zeros((), jnp.int32),
        )

    def update(self, grads, state, params=None, **kwargs):
        comm_grads = self.communicator.allreduce_grad(state.pending)
        updates, inner = self.actual_optimizer.update(
            comm_grads, state.inner, params, **kwargs)
        new_state = _DoubleBufferState(
            inner=inner, pending=grads, step=state.step + 1)
        return updates, new_state

    def state_partition_spec(self):
        # ``pending`` holds per-device local grads — varying across the data
        # axes; inner state and counter are replicated.
        return _DoubleBufferState(
            inner=P(), pending=_VARYING, step=P())


# Sentinel replaced by the communicator's data axes in make_train_step.
_VARYING = "__varying__"


def _deprecate_raw_wire_knob(communicator, compression):
    """One-release shim (satellite of the compression subsystem): a
    communicator carrying a RAW ``allreduce_grad_dtype`` — i.e. the dtype
    knob was passed directly rather than spelled as a compression codec —
    still works unchanged, but points users at the replacement."""
    if compression is not None:
        return
    dt = getattr(communicator, "allreduce_grad_dtype", None)
    if dt is not None and getattr(communicator, "compression", None) is None:
        import warnings
        warnings.warn(
            f"allreduce_grad_dtype={str(dt)!r} without an explicit "
            "compression codec is deprecated; pass "
            f"compression=NoCompression(wire_dtype={str(dt)!r}) (or just "
            f"compression={str(dt)!r}) to create_communicator / "
            "create_multi_node_optimizer instead — it lowers to the "
            "identical cast-allreduce-cast program, and the raw dtype "
            "knob will be removed in the release after next",
            DeprecationWarning, stacklevel=3)


class _ZeroState(NamedTuple):
    inner: Any  # inner optax state over THIS device's flat shard (varying)


class _Zero1Optimizer:
    """ZeRO stage-1 optimizer-state sharding — **beyond-reference
    extension** (the reference had nothing like it; clearly labeled, like
    the other `parallel/` extensions).

    Each device owns 1/size of the flattened parameter space: gradients
    arrive via ``reduce_scatter`` (mean) as this device's shard, the inner
    optax update runs on the shard only — so optimizer state (e.g. Adam's
    m/v, 2x params) is divided by the world size — and the resulting
    update shards ``all_gather`` back to the full parameter vector, which
    stays replicated (stage 1: state sharded, params/grads not).

    Wire cost per step: the reduce-scatter leg is half a ring allreduce;
    the gather-back is a masked psum (~2x a ring gather's bytes — the
    price of an invariant-typed result, see the inline comment), so the
    total is ~1.5x one ring allreduce on the cheap ICI resource, while
    per-device optimizer memory drops by ~size.  The communicator's
    ``allreduce_grad_dtype`` (when set) applies to the reduce-scatter leg
    exactly as it applies to ``allreduce_grad``: cast in, reduce in the
    wire dtype, cast back before the inner update.  Inner optimizers
    whose ``init`` depends on parameter VALUES (not just shapes/dtypes)
    are unsupported — every standard optax rule
    (sgd/momentum/adam/adamw/...) initializes from shapes.  Layer-wise
    rules whose UPDATE depends on per-leaf structure (LARS/LAMB trust
    ratios) are also out: the flat per-dtype shards erase leaf
    boundaries, so the "layer-wise" norms would be shard-wise — silently
    different semantics (the ImageNet example rejects --zero with
    --optimizer lars for this reason).
    """

    def __init__(self, actual_optimizer: optax.GradientTransformation, comm,
                 compression=None):
        self.actual_optimizer = actual_optimizer
        self.communicator = comm
        self.compression = compression

    def _wire_dtype(self):
        """The reduce-scatter leg's wire dtype: an explicit
        ``NoCompression(wire_dtype)`` wins; else the communicator's legacy
        ``allreduce_grad_dtype`` knob (deprecated spelling of the same)."""
        if self.compression is not None and \
                getattr(self.compression, "wire", None) is not None:
            return self.compression.wire
        return getattr(self.communicator, "allreduce_grad_dtype", None)

    def _shard_zeros(self, params):
        """Zero-filled flat shards shaped like one device's slice —
        computed from leaf shapes alone (no transient full flat copy;
        mirrors _packing.pack's by-dtype grouping)."""
        size = self.communicator.size
        groups: dict = {}
        for leaf in jax.tree.leaves(params):
            key = str(leaf.dtype)
            n = 1
            for d in leaf.shape:
                n *= int(d)
            groups[key] = (groups.get(key, (0, leaf.dtype))[0] + n,
                           leaf.dtype)
        return [jnp.zeros(((n + (-n) % size) // size,), dt)
                for n, dt in groups.values()]

    def init(self, params):
        return _ZeroState(
            inner=self.actual_optimizer.init(self._shard_zeros(params)))

    def update(self, grads, state, params=None, **kwargs):
        comm = self.communicator
        size = comm.size
        idx = comm.axis_index()
        # honor the wire dtype (the pure_nccl fp16/bf16 recipe): cast in,
        # reduce in the wire dtype, cast back — same numerics as
        # allreduce_grad's cast-allreduce-cast path
        wire_dtype = self._wire_dtype()
        g_bufs, meta = _packing.pack(grads)
        p_bufs, _ = _packing.pack(params) if params is not None else (
            [None] * len(g_bufs), None)
        g_shards, p_shards, strips = [], [], []
        for g, p in zip(g_bufs, p_bufs):
            g, strip = _packing.pad_to_multiple(g, size)
            strips.append(strip)
            orig_dtype = g.dtype
            if wire_dtype is not None and g.dtype != wire_dtype:
                g = g.astype(wire_dtype)
            # reduce_scatter sums; the reference's allreduce_grad is a mean
            gs = comm.reduce_scatter(g) / size
            g_shards.append(gs.astype(orig_dtype))
            if p is not None:
                p, _ = _packing.pad_to_multiple(p, size)
                p_shards.append(
                    jax.lax.dynamic_index_in_dim(
                        p.reshape(size, -1), idx, axis=0, keepdims=False))
        updates_sh, inner = self.actual_optimizer.update(
            g_shards, state.inner,
            p_shards if params is not None else None, **kwargs)
        # Gather-back as a masked psum rather than all_gather: value-
        # identical, but psum output is INVARIANT in JAX's varying-axes
        # type system, so the updated parameters keep their replicated
        # out_spec (same trick as the two_dimensional communicator's
        # gather-back leg; ~2x the bytes of a ring gather on the cheap
        # ICI resource).
        upd_bufs = []
        for u, strip in zip(updates_sh, strips):
            placed = jax.lax.dynamic_update_slice_in_dim(
                jnp.zeros((u.shape[0] * size,), u.dtype), u,
                idx * u.shape[0], 0)
            upd_bufs.append(strip(comm.allreduce(placed, "sum")))
        return _packing.unpack(upd_bufs, meta), _ZeroState(inner=inner)

    def state_partition_spec(self):
        # the whole inner state lives on per-device shards
        return _ZeroState(inner=_VARYING)


def create_multi_node_optimizer(
    actual_optimizer: optax.GradientTransformation,
    communicator,
    double_buffering: bool = False,
    zero: bool = False,
    compression=None,
):
    """Reference signature: ``create_multi_node_optimizer(optimizer, comm,
    double_buffering)`` 〔optimizers.py〕.  ``actual_optimizer`` is an optax
    GradientTransformation (the Chainer-optimizer role).

    ``zero=True`` (beyond-reference extension) shards the optimizer state
    ZeRO-1-style over the communicator's devices — see
    :class:`_Zero1Optimizer`.  Mutually exclusive with ``double_buffering``
    (the pending-gradient buffer would defeat the memory saving).

    ``compression`` (beyond-reference extension) selects the gradient
    wire codec — a name (``"int8"``/``"fp8"``), dtype string, config
    dict, or :class:`~chainermn_tpu.compression.Compressor`.
    ``NoCompression(wire_dtype=...)`` reproduces the communicator-level
    ``allreduce_grad_dtype`` program bit for bit; the quantizers carry
    error-feedback state inside the optimizer state (initialize it with
    :func:`init_opt_state`, which places the per-rank EF residual).

    ``compression`` may also be a :class:`~chainermn_tpu.planner.Plan`
    whose stages carry per-hop ``Stage.compression`` specs (e.g.
    ``compressed_two_dimensional(...)``): the gradient exchange executes
    that plan with one EF state per quantized hop riding the optimizer
    state as a stage-indexed dict — the DynamiQ per-hop path."""
    from chainermn_tpu.compression import base as _cbase
    from chainermn_tpu.compression import quantize as _cq
    from chainermn_tpu.planner.compiler import plan_compressed_hops
    from chainermn_tpu.planner.ir import Plan as _Plan
    if isinstance(compression, _Plan):
        if zero or double_buffering:
            raise NotImplementedError(
                "compression=<Plan> (per-hop) composes with neither "
                "zero=True nor double_buffering=True — the per-hop EF "
                "states ride the plain compressed-optimizer state slot")
        if not plan_compressed_hops(compression,
                                    communicator.plan_topology()):
            raise ValueError(
                f"compression plan {compression.name!r} has no quantizing "
                "stages on this topology; pass the plan to "
                "create_communicator(plan_table=...) instead, or add "
                "Stage.compression specs")
        return _CompressedOptimizer(actual_optimizer, communicator,
                                    compression)
    compression = _cbase.resolve_compressor(compression)
    _deprecate_raw_wire_knob(communicator, compression)
    if zero and double_buffering:
        raise ValueError("zero=True and double_buffering=True are mutually "
                         "exclusive (the pending full-size gradient buffer "
                         "would defeat ZeRO's memory saving)")
    if _cq.is_quantizing(compression):
        if zero:
            raise NotImplementedError(
                "compression=<quantizer> with zero=True is not supported "
                "yet: ZeRO-1's reduce-scatter leg would need per-shard EF "
                "state (the bucketed FSDP engine has that — use "
                "fsdp_init(bucket_compressors=...))")
        if double_buffering:
            raise NotImplementedError(
                "compression=<quantizer> with double_buffering=True is not "
                "supported: stale-gradient buffering and error feedback "
                "both delay the update stream; composing them changes "
                "convergence semantics")
        return _CompressedOptimizer(actual_optimizer, communicator,
                                    compression)
    if zero:
        return _Zero1Optimizer(actual_optimizer, communicator,
                               compression=compression)
    if double_buffering:
        if compression is not None and compression.wire is not None:
            raise NotImplementedError(
                "compression=NoCompression(wire_dtype) with "
                "double_buffering=True: set allreduce_grad_dtype on the "
                "communicator instead (the pending-buffer allreduce "
                "honors it)")
        return _DoubleBufferingOptimizer(actual_optimizer, communicator)
    if compression is not None and compression.wire is None:
        compression = None  # bare NoCompression() is the do-nothing default
    return _MultiNodeOptimizer(actual_optimizer, communicator,
                               compression=compression)


def _resolve_spec(spec_tree, axes):
    is_sentinel = lambda s: isinstance(s, str) and s == _VARYING
    return jax.tree.map(
        lambda s: P(axes) if is_sentinel(s) else s,
        spec_tree,
        is_leaf=lambda s: isinstance(s, (P, str)),
    )


def make_train_step(
    communicator,
    loss_fn: Callable,
    optimizer,
    has_aux: bool = False,
    donate: bool = True,
    with_model_state: bool = False,
    scan_steps: int = 1,
    accum_steps: int = 1,
):
    """Build the canonical jitted SPMD train step (the hot loop of SURVEY.md
    §3.2): per-device forward/backward on the local batch shard -> explicit
    ``allreduce_grad`` -> inner optimizer update, all in one XLA program.

    ``loss_fn(params, batch)`` sees the *local* batch shard, exactly like a
    reference rank saw its local minibatch.  Returns
    ``step(params, opt_state, batch) -> (params, opt_state, loss[, aux])``
    where ``batch`` leaves are sharded on their leading axis across the
    communicator's data axes.

    ``scan_steps=K`` (K > 1) runs K consecutive optimizer steps on the same
    batch argument inside ONE XLA program via ``lax.scan`` and returns the
    last step's loss/aux.  Each scan iteration is the full step (backward,
    allreduce, update) — identical numerics to calling the step K times —
    but the host dispatches once per K steps, which matters when per-call
    dispatch overhead is comparable to the step itself (measured ~10 ms
    through this image's device tunnel vs a 98 ms ResNet step).  Meant for
    benchmarking / synthetic-data loops; real input pipelines feed a fresh
    batch per step and use ``scan_steps=1``.

    ``accum_steps=K`` (K > 1) — gradient accumulation: each device splits
    its local batch shard into K equal microbatches, runs forward/backward
    per microbatch under ``lax.scan``, and averages the K gradients before
    the ONE allreduce + optimizer update.  Because every microbatch loss
    is a mean over an equal slice, the averaged gradient equals the
    full-shard gradient exactly — same numerics as ``accum_steps=1`` (the
    parity test pins it bitwise-close), with peak activation memory
    divided by ~K.  That is the knob's purpose: fitting a reference
    global batch on fewer/smaller chips.  The exactness claim is scoped
    to batch-DECOMPOSABLE losses (a mean of independent per-sample
    terms).  Two caveats: (a) BatchNorm breaks decomposability — each
    microbatch normalizes over its own b/K samples, so the forward
    activations AND gradients differ from the full-shard computation
    (ghost-batch-norm semantics; smaller effective normalization batch),
    and the running statistics likewise update K times per step; (b) on
    TPU the scan body pins conv weight layouts
    (measured ~1.5x emitter regression for conv nets —
    docs/performance.md), so use it when memory demands it, not for
    speed.

    ``with_model_state=True`` adds a non-trainable mutable model state slot
    (flax ``batch_stats``) that stays **device-local** — the reference trains
    BatchNorm on local statistics and only syncs via ``AllreducePersistent``
    (SURVEY.md §7 hard part 5), so the state is carried stacked per-device
    ([size, ...], sharded over the data axes; see :func:`init_model_state`)
    and never reduced inside the step.  Signatures become
    ``loss_fn(params, model_state, batch) -> (loss, new_state)`` (or
    ``(loss, (new_state, aux))`` with ``has_aux``) and
    ``step(params, model_state, opt_state, batch) ->
    (params, model_state, opt_state, loss[, aux])``.
    """
    if accum_steps < 1:
        raise ValueError(f"accum_steps must be >= 1, got {accum_steps}")
    comm = communicator
    axes = comm.data_axes
    state_spec = _resolve_spec(
        optimizer.state_partition_spec()
        if hasattr(optimizer, "state_partition_spec") else P(), axes)

    def step(params, model_state, opt_state, batch):
        if isinstance(opt_state, _DoubleBufferState):
            # The stacked pending buffer arrives as per-device [1, ...]
            # slices; inside the SPMD body it is this rank's local grads.
            opt_state = opt_state._replace(
                pending=jax.tree.map(lambda a: jnp.squeeze(a, 0),
                                     opt_state.pending))
        if isinstance(opt_state, _ZeroState):
            # stacked per-device shard states arrive as [1, ...] slices
            opt_state = _ZeroState(inner=jax.tree.map(
                lambda a: jnp.squeeze(a, 0), opt_state.inner))
        if isinstance(opt_state, _CompressedState):
            # stacked per-device EF state arrives as [1, ...] slices
            opt_state = opt_state._replace(comp=jax.tree.map(
                lambda a: jnp.squeeze(a, 0), opt_state.comp))
        if with_model_state:
            model_state = jax.tree.map(lambda a: jnp.squeeze(a, 0), model_state)
        # Mark the replicated params device-varying for the local backward:
        # otherwise shard_map's autodiff inserts an automatic psum when
        # differentiating the per-device loss w.r.t. invariant params, and
        # gradients would arrive pre-summed — the explicit allreduce below
        # (the reference's semantics) must be the only cross-device reduction.
        params_local = jax.tree.map(lambda p: pvary(p, axes), params)
        grad_fn = jax.value_and_grad(
            loss_fn, has_aux=has_aux or with_model_state)

        def compute(model_state, batch):
            if with_model_state:
                (loss, packed), grads = grad_fn(
                    params_local, model_state, batch)
                model_state, aux = packed if has_aux else (packed, None)
            elif has_aux:
                (loss, aux), grads = grad_fn(params_local, batch)
            else:
                loss, grads = grad_fn(params_local, batch)
                aux = None
            return loss, aux, model_state, grads

        if accum_steps > 1:
            from chainermn_tpu.utils.accum import accumulate_microbatches

            loss, aux, model_state, grads = accumulate_microbatches(
                compute, model_state, batch, accum_steps, has_aux)
        else:
            loss, aux, model_state, grads = compute(model_state, batch)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        if isinstance(opt_state, _DoubleBufferState):
            # Anchor the loss/aux reporting reductions AFTER the parameter
            # update: XLA's all-reduce combiner otherwise merges them with
            # the pending-gradient psum into ONE collective, which then
            # cannot start until the loss (i.e. the whole forward) is ready
            # — squandering the overlap the double buffer exists for.  The
            # barrier makes merging a dependency cycle, so the gradient
            # psum keeps zero data dependencies and is schedulable from
            # program start.  (Found in the 8-device-mesh HLO; see
            # docs/performance.md "Double-buffering overlap".)
            anchor = jax.tree.leaves(params)[0]
            loss, anchor = jax.lax.optimization_barrier((loss, anchor))
            if aux is not None:
                aux, anchor = jax.lax.optimization_barrier((aux, anchor))
            opt_state = opt_state._replace(
                pending=jax.tree.map(lambda a: a[None], opt_state.pending))
        if isinstance(opt_state, _ZeroState):
            opt_state = _ZeroState(inner=jax.tree.map(
                lambda a: a[None], opt_state.inner))
        if isinstance(opt_state, _CompressedState):
            opt_state = opt_state._replace(comp=jax.tree.map(
                lambda a: a[None], opt_state.comp))
        if with_model_state:
            model_state = jax.tree.map(lambda a: a[None], model_state)
        loss = comm.allreduce(loss, "mean")
        if has_aux:
            aux = comm.allreduce(aux, "mean")
        outs = (params, model_state, opt_state, loss, aux)
        keep = (True, with_model_state, True, True, has_aux)
        return tuple(o for o, k in zip(outs, keep) if k)

    out_spec_all = (P(), P(axes), state_spec, P(), P())
    keep = (True, with_model_state, True, True, has_aux)
    out_specs = tuple(s for s, k in zip(out_spec_all, keep) if k)
    in_specs = ((P(), P(axes), state_spec, P(axes)) if with_model_state
                else (P(), state_spec, P(axes)))
    inner = step
    if not with_model_state:
        def inner(params, opt_state, batch):  # noqa: F811
            return step(params, None, opt_state, batch)
    if scan_steps > 1:
        n_state = 3 if with_model_state else 2
        base = inner

        def inner(*args):  # noqa: F811
            state, batch = args[:n_state], args[n_state]

            def body(carry, _):
                outs = base(*carry, batch)
                return outs[:n_state], outs[n_state:]

            state, tail = jax.lax.scan(
                body, tuple(state), None, length=scan_steps)
            # Report the LAST step's loss/aux: it depends (through the
            # parameter chain) on every preceding step, so reading it to
            # host is a fence over the whole scan.
            return (*state, *jax.tree.map(lambda a: a[-1], tail))
    mapped = _shard_map(
        inner,
        mesh=comm.mesh,
        in_specs=in_specs,
        out_specs=out_specs,
    )
    donate_argnums = ((0, 1, 2) if with_model_state else (0, 1)) if donate else ()
    return jax.jit(mapped, donate_argnums=donate_argnums)


class PerStageOptimizer:
    """Optimizer for model-parallel parameter lists (``MultiNodeChainList``):
    one optax state per stage, each update jitted on that stage's devices.

    A single optax update over the whole list would jit one computation over
    leaves committed to disjoint device groups, which XLA rejects; stage-wise
    application is also what the reference does (each rank updates only its
    own sub-chain's parameters).
    """

    def __init__(self, actual_optimizer: optax.GradientTransformation):
        self.actual_optimizer = actual_optimizer
        self._jit_update = jax.jit(actual_optimizer.update)
        self._jit_apply = jax.jit(optax.apply_updates)

    def init(self, params_list):
        return [self.actual_optimizer.init(p) for p in params_list]

    def update(self, grads_list, states, params_list):
        if not (len(grads_list) == len(states) == len(params_list)):
            raise ValueError(
                f"stage count mismatch: {len(grads_list)} grads, "
                f"{len(states)} states, {len(params_list)} params — "
                "re-init the optimizer after changing the chain list")
        new_params, new_states = [], []
        for g, s, p in zip(grads_list, states, params_list):
            updates, s2 = self._jit_update(g, s, p)
            new_params.append(self._jit_apply(p, updates))
            new_states.append(s2)
        return new_params, new_states


def create_per_stage_optimizer(actual_optimizer: optax.GradientTransformation):
    return PerStageOptimizer(actual_optimizer)


def init_model_state(communicator, model_state):
    """Stack per-device copies of initial mutable model state (``batch_stats``)
    into the device-local layout ``make_train_step(with_model_state=True)``
    expects: leading axis == communicator.size, sharded over the data axes.
    Every device starts from the same (typically zero/one-initialized) stats,
    then they drift apart — local BN, the reference's semantics."""
    comm = communicator
    stacked = jax.tree.map(
        lambda z: jnp.broadcast_to(z, (comm.size,) + z.shape), model_state)
    # identical on every rank — placement stays process-local
    # (utils/placement.py: cross-process device_put is order-hazardous)
    return local_device_put(
        stacked, NamedSharding(comm.mesh, P(comm.data_axes)))


def init_opt_state(communicator, optimizer, params):
    """Initialize optimizer state with the right shardings: replicated inner
    state; for double buffering, a stacked per-device ``pending`` buffer
    (leading axis == communicator.size) sharded over the data axes."""
    comm = communicator
    state = optimizer.init(params)
    if isinstance(state, _ZeroState):
        # every device's shard state starts as identical zeros; stack to
        # the device-local layout ([size, ...] sharded over the data axes)
        stacked = jax.tree.map(
            lambda z: jnp.broadcast_to(z, (comm.size,) + z.shape),
            state.inner)
        return _ZeroState(inner=local_device_put(
            stacked, NamedSharding(comm.mesh, P(comm.data_axes))))
    if isinstance(state, _CompressedState):
        # inner replicated; EF state stacked per device (each rank owns
        # its residual; scale/step start — and stay — rank-identical)
        stacked = jax.tree.map(
            lambda z: jnp.broadcast_to(z, (comm.size,) + z.shape),
            state.comp)
        return _CompressedState(
            inner=local_device_put(state.inner,
                                   NamedSharding(comm.mesh, P())),
            comp=local_device_put(
                stacked, NamedSharding(comm.mesh, P(comm.data_axes))))
    if not isinstance(state, _DoubleBufferState):
        return local_device_put(state, NamedSharding(comm.mesh, P()))
    stacked_pending = jax.tree.map(
        lambda z: jnp.zeros((comm.size,) + z.shape, z.dtype), state.pending)
    return _DoubleBufferState(
        inner=local_device_put(state.inner, NamedSharding(comm.mesh, P())),
        pending=local_device_put(
            stacked_pending, NamedSharding(comm.mesh, P(comm.data_axes))),
        step=local_device_put(state.step, NamedSharding(comm.mesh, P())),
    )
