"""VGG-16 — the reference's CIFAR-scale convnet.

Reference being rebuilt (SURVEY.md provenance / BASELINE.json configs[2]):
the VGG-16/CIFAR-10 configuration used to validate the double-buffered
allreduce optimizer.  Chainer-era VGG for CIFAR = conv-BN-ReLU stacks with
max-pooling and a small classifier head.

NHWC, bfloat16-capable (``dtype``), local-statistics BatchNorm in the
``batch_stats`` collection — same conventions as :mod:`.resnet`.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Sequence

import flax.linen as nn
import jax.numpy as jnp

# Channel plan per conv stage; 'M' = 2x2 max pool.  This is the standard
# VGG-16 configuration ("D").
_CFG_16 = (64, 64, "M", 128, 128, "M", 256, 256, 256, "M",
           512, 512, 512, "M", 512, 512, 512, "M")


class VGG(nn.Module):
    cfg: Sequence = _CFG_16
    num_classes: int = 10
    dtype: Any = jnp.float32
    hidden: int = 512
    dropout_rate: float = 0.5

    @nn.compact
    def __call__(self, x, train: bool = True):
        conv = partial(nn.Conv, kernel_size=(3, 3), use_bias=False,
                       dtype=self.dtype, param_dtype=jnp.float32,
                       padding="SAME")
        norm = partial(nn.BatchNorm, use_running_average=not train,
                       momentum=0.9, epsilon=1e-5,
                       dtype=self.dtype, param_dtype=jnp.float32)
        x = x.astype(self.dtype)
        for c in self.cfg:
            if c == "M":
                x = nn.max_pool(x, (2, 2), strides=(2, 2))
            else:
                x = nn.relu(norm()(conv(c)(x)))
        x = x.reshape((x.shape[0], -1))
        x = nn.relu(nn.Dense(self.hidden, dtype=self.dtype,
                             param_dtype=jnp.float32)(x))
        x = nn.Dropout(self.dropout_rate, deterministic=not train)(x)
        x = nn.Dense(self.num_classes, dtype=self.dtype,
                     param_dtype=jnp.float32)(x)
        return x.astype(jnp.float32)


VGG16 = VGG
