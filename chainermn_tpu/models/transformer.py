"""Decoder-only Transformer LM — the long-context flagship model.

**Beyond-reference extension** (the reference's model zoo is 2017 ImageNet
convnets + an LSTM seq2seq — SURVEY.md §2.6; transformers postdate it).
This model exists to make the sequence-parallel machinery concrete: its
attention is pluggable between

* ``attention_impl="flash"`` — the fused Pallas kernel
  (:func:`chainermn_tpu.ops.flash_attention`), single-shard;
* ``attention_impl="ring"`` — ring attention over a mesh axis
  (:func:`chainermn_tpu.parallel.sequence.ring_attention`) for sequences
  sharded across chips; ``"ring_flash"`` runs each visiting block through
  the fused Pallas kernel (logsumexp-merged);
* ``attention_impl="ulysses"`` — all-to-all head/sequence exchange;
* ``attention_impl="xla"`` — the unfused reference math.

Pre-LN blocks, learned positional embeddings, GELU MLP; bf16-capable with
f32 parameters (same conventions as the image zoo).
"""

from __future__ import annotations

import functools
from typing import Any, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp


def _attend(impl: str, axis_name, q, k, v, causal: bool):
    if impl == "flash":
        from chainermn_tpu.ops.flash_attention import flash_attention

        return flash_attention(q, k, v, causal)
    if impl == "ring":
        from chainermn_tpu.parallel.sequence import ring_attention

        return ring_attention(q, k, v, axis_name, causal=causal)
    if impl == "ring_flash":
        from chainermn_tpu.ops.flash_attention import flash_attention
        from chainermn_tpu.parallel.sequence import ring_attention

        return ring_attention(q, k, v, axis_name, causal=causal,
                              attn_fn=flash_attention)
    if impl == "ulysses":
        from chainermn_tpu.parallel.sequence import ulysses_attention

        return ulysses_attention(q, k, v, axis_name, causal=causal)
    if impl == "xla":
        from chainermn_tpu.parallel.sequence import attention

        return attention(q, k, v, causal=causal)
    raise ValueError(
        f"attention_impl must be flash|ring|ring_flash|ulysses|xla, "
        f"got {impl!r}")


class Block(nn.Module):
    """Pre-LN transformer block.  With ``moe_experts > 0`` the dense MLP is
    replaced by an expert-parallel MoE MLP
    (:class:`chainermn_tpu.parallel.expert.ExpertParallelMLP`) over
    ``moe_axis``; the load-balancing aux loss and overflow fraction are
    sowed into the ``"moe_stats"`` collection (retrieve with
    ``mutable=["moe_stats"]`` and add ``aux_weight * sum(aux_loss)`` to the
    training loss).

    Two inference extensions (``chainermn_tpu/serving``):

    * ``attend=`` (call-time) — replaces the built-in causal attention
      with an external callback ``attend(q, k, v) -> [B, T, H_local, Dh]``
      that owns masking and any KV-cache read/write (decode mode).  The
      callback sees GROUPED kv heads (no GQA expansion).
    * ``tp_size``/``tp_axis`` — Megatron tensor parallelism: the block
      computes ``n_heads / tp_size`` local heads from column-sliced
      qkv/up kernels and psums the row-parallel proj/down outputs over
      ``tp_axis`` (apply inside shard_map with params sliced by
      :func:`chainermn_tpu.serving.weights.shard_params_tp`, which also
      pre-divides the row-parallel biases by ``tp_size``)."""

    n_heads: int
    attention_impl: str = "xla"
    axis_name: Any = None
    dtype: Any = jnp.float32
    n_kv_heads: Optional[int] = None  # < n_heads = GQA/MQA (flash impl)
    moe_experts: int = 0          # 0 = dense MLP
    moe_top_k: int = 1
    moe_axis: Any = "ep"
    moe_capacity: Optional[int] = None
    moe_plan: Any = None          # all-to-all Plan for the MoE exchanges
    tp_size: int = 1              # tensor-parallel ways (serving)
    tp_axis: Any = None           # mesh axis for the row-parallel psums

    @nn.compact
    def __call__(self, x, attend=None):
        d_model = x.shape[-1]
        head_dim = d_model // self.n_heads
        n_kv = self.n_kv_heads or self.n_heads
        if self.n_heads % self.tp_size or n_kv % self.tp_size:
            raise ValueError(
                f"tp_size ({self.tp_size}) must divide n_heads "
                f"({self.n_heads}) and n_kv_heads ({n_kv})")
        n_local = self.n_heads // self.tp_size
        n_kv_local = n_kv // self.tp_size
        d_local = n_local * head_dim
        dense = lambda f, name: nn.Dense(
            f, dtype=self.dtype, param_dtype=jnp.float32, name=name)
        ln = lambda name: nn.LayerNorm(dtype=self.dtype,
                                       param_dtype=jnp.float32, name=name)
        row_psum = (lambda y: jax.lax.psum(y, self.tp_axis)) \
            if self.tp_axis is not None and self.tp_size > 1 else (lambda y: y)

        h = ln("ln_attn")(x)
        d_kv = n_kv_local * head_dim
        qkv = dense(d_local + 2 * d_kv, "qkv")(h)
        q = qkv[..., :d_local]
        k = qkv[..., d_local:d_local + d_kv]
        v = qkv[..., d_local + d_kv:]
        q = q.reshape(h.shape[:-1] + (n_local, head_dim))
        k = k.reshape(h.shape[:-1] + (n_kv_local, head_dim))
        v = v.reshape(h.shape[:-1] + (n_kv_local, head_dim))
        if attend is not None:
            out = attend(q, k, v)
        else:
            if n_kv != self.n_heads and self.attention_impl not in (
                    "flash", "ring_flash"):
                # the fused kernel reads grouped kv natively (and under
                # ring_flash the GROUPED blocks rotate the ring — 1/grp the
                # ppermute bytes, GQA's whole point); other impls see the
                # expanded heads
                k = jnp.repeat(k, n_local // n_kv_local, axis=-2)
                v = jnp.repeat(v, n_local // n_kv_local, axis=-2)
            out = _attend(self.attention_impl, self.axis_name, q, k, v,
                          causal=True)
        x = x + row_psum(dense(d_model, "proj")(
            out.reshape(h.shape[:-1] + (d_local,))))

        h = ln("ln_mlp")(x)
        if self.moe_experts:
            from chainermn_tpu.parallel.expert import ExpertParallelMLP

            y, stats = ExpertParallelMLP(
                hidden=4 * d_model, axis_name=self.moe_axis,
                capacity=self.moe_capacity, dtype=self.dtype,
                top_k=self.moe_top_k, num_experts=self.moe_experts,
                with_stats=True, plan=self.moe_plan, name="moe")(h)
            self.sow("moe_stats", "aux_loss", stats["aux_loss"])
            self.sow("moe_stats", "overflow_fraction",
                     stats["overflow_fraction"])
            self.sow("moe_stats", "expert_load", stats["expert_load"])
            return x + y
        h = nn.gelu(dense(4 * d_model // self.tp_size, "up")(h))
        return x + row_psum(dense(d_model, "down")(h))


class TransformerLM(nn.Module):
    """``apply(params, tokens[B, T]) -> logits[B, T, vocab]`` (causal).

    With ``attention_impl="ring"``/``"ulysses"``, apply inside an SPMD
    region (``shard_map``) with ``tokens`` sharded [B, T/P] on
    ``axis_name`` — positions are global via ``pos_offset``.

    Decode mode (``chainermn_tpu/serving``): ``pos_offset`` may be a
    ``[B]`` int32 vector — each sequence of the batch sits at its own
    global position (its KV-cache length) — and ``attend=`` installs a
    per-layer attention callback ``attend(layer, q, k, v)`` that owns
    masking and cache read/write.  ``tp_size``/``tp_axis`` shard every
    block Megatron-style (see :class:`Block`); embeddings, layer norms
    and the output head stay replicated.
    """

    vocab: int
    d_model: int = 256
    n_layers: int = 4
    n_heads: int = 8
    max_len: int = 8192
    attention_impl: str = "xla"
    axis_name: Any = None
    dtype: Any = jnp.float32
    n_kv_heads: Optional[int] = None  # < n_heads = GQA/MQA
    moe_experts: int = 0          # >0: MoE MLP in every block (EP over moe_axis)
    moe_top_k: int = 1
    moe_axis: Any = "ep"
    moe_capacity: Optional[int] = None
    moe_plan: Any = None          # all-to-all Plan for the MoE exchanges
    tp_size: int = 1              # tensor-parallel ways (serving)
    tp_axis: Any = None

    @nn.compact
    def __call__(self, tokens, pos_offset=0, attend=None):
        if self.d_model % self.n_heads:
            raise ValueError(
                f"n_heads ({self.n_heads}) must divide d_model "
                f"({self.d_model})")
        if self.n_kv_heads is not None and (
                self.n_kv_heads < 1 or self.n_heads % self.n_kv_heads):
            raise ValueError(
                f"n_kv_heads ({self.n_kv_heads}) must be >= 1 and divide "
                f"n_heads ({self.n_heads})")
        x = nn.Embed(self.vocab, self.d_model, param_dtype=jnp.float32,
                     dtype=self.dtype, name="tok_emb")(tokens)
        off = jnp.asarray(pos_offset, jnp.int32)
        if off.ndim == 0:                      # shared offset: [T] positions
            positions = off + jnp.arange(tokens.shape[-1])
        else:                                  # per-sequence: [B, T]
            positions = off[:, None] + jnp.arange(tokens.shape[-1])[None, :]
        pos = nn.Embed(self.max_len, self.d_model, param_dtype=jnp.float32,
                       dtype=self.dtype, name="pos_emb")(positions)
        x = x + pos
        for i in range(self.n_layers):
            blk_attend = None if attend is None else functools.partial(
                attend, i)
            x = Block(self.n_heads, self.attention_impl, self.axis_name,
                      self.dtype, n_kv_heads=self.n_kv_heads,
                      moe_experts=self.moe_experts,
                      moe_top_k=self.moe_top_k, moe_axis=self.moe_axis,
                      moe_capacity=self.moe_capacity,
                      moe_plan=self.moe_plan,
                      tp_size=self.tp_size, tp_axis=self.tp_axis,
                      name=f"block_{i}")(x, attend=blk_attend)
        x = nn.LayerNorm(dtype=self.dtype, param_dtype=jnp.float32,
                         name="ln_f")(x)
        logits = nn.Dense(self.vocab, dtype=self.dtype,
                          param_dtype=jnp.float32, name="head")(x)
        return logits.astype(jnp.float32)


__all__ = ["Block", "TransformerLM"]
