"""Decoder-only Transformer LM — the long-context flagship model.

**Beyond-reference extension** (the reference's model zoo is 2017 ImageNet
convnets + an LSTM seq2seq — SURVEY.md §2.6; transformers postdate it).
This model exists to make the sequence-parallel machinery concrete: its
attention is pluggable between

* ``attention_impl="flash"`` — the fused Pallas kernel
  (:func:`chainermn_tpu.ops.flash_attention`), single-shard;
* ``attention_impl="ring"`` — ring attention over a mesh axis
  (:func:`chainermn_tpu.parallel.sequence.ring_attention`) for sequences
  sharded across chips; ``"ring_flash"`` runs each visiting block through
  the fused Pallas kernel (logsumexp-merged);
* ``attention_impl="ulysses"`` — all-to-all head/sequence exchange;
* ``attention_impl="xla"`` — the unfused reference math.

Pre-LN blocks, learned positional embeddings, GELU MLP; bf16-capable with
f32 parameters (same conventions as the image zoo).
"""

from __future__ import annotations

from typing import Any, Optional

import flax.linen as nn
import jax.numpy as jnp


def _attend(impl: str, axis_name, q, k, v, causal: bool):
    if impl == "flash":
        from chainermn_tpu.ops.flash_attention import flash_attention

        return flash_attention(q, k, v, causal)
    if impl == "ring":
        from chainermn_tpu.parallel.sequence import ring_attention

        return ring_attention(q, k, v, axis_name, causal=causal)
    if impl == "ring_flash":
        from chainermn_tpu.ops.flash_attention import flash_attention
        from chainermn_tpu.parallel.sequence import ring_attention

        return ring_attention(q, k, v, axis_name, causal=causal,
                              attn_fn=flash_attention)
    if impl == "ulysses":
        from chainermn_tpu.parallel.sequence import ulysses_attention

        return ulysses_attention(q, k, v, axis_name, causal=causal)
    if impl == "xla":
        from chainermn_tpu.parallel.sequence import attention

        return attention(q, k, v, causal=causal)
    raise ValueError(
        f"attention_impl must be flash|ring|ring_flash|ulysses|xla, "
        f"got {impl!r}")


class Block(nn.Module):
    """Pre-LN transformer block.  With ``moe_experts > 0`` the dense MLP is
    replaced by an expert-parallel MoE MLP
    (:class:`chainermn_tpu.parallel.expert.ExpertParallelMLP`) over
    ``moe_axis``; the load-balancing aux loss and overflow fraction are
    sowed into the ``"moe_stats"`` collection (retrieve with
    ``mutable=["moe_stats"]`` and add ``aux_weight * sum(aux_loss)`` to the
    training loss)."""

    n_heads: int
    attention_impl: str = "xla"
    axis_name: Any = None
    dtype: Any = jnp.float32
    n_kv_heads: Optional[int] = None  # < n_heads = GQA/MQA (flash impl)
    moe_experts: int = 0          # 0 = dense MLP
    moe_top_k: int = 1
    moe_axis: Any = "ep"
    moe_capacity: Optional[int] = None

    @nn.compact
    def __call__(self, x):
        d_model = x.shape[-1]
        head_dim = d_model // self.n_heads
        n_kv = self.n_kv_heads or self.n_heads
        dense = lambda f, name: nn.Dense(
            f, dtype=self.dtype, param_dtype=jnp.float32, name=name)
        ln = lambda name: nn.LayerNorm(dtype=self.dtype,
                                       param_dtype=jnp.float32, name=name)

        h = ln("ln_attn")(x)
        d_kv = n_kv * head_dim
        qkv = dense(d_model + 2 * d_kv, "qkv")(h)
        q = qkv[..., :d_model]
        k = qkv[..., d_model:d_model + d_kv]
        v = qkv[..., d_model + d_kv:]
        q = q.reshape(h.shape[:-1] + (self.n_heads, head_dim))
        k = k.reshape(h.shape[:-1] + (n_kv, head_dim))
        v = v.reshape(h.shape[:-1] + (n_kv, head_dim))
        if n_kv != self.n_heads and self.attention_impl not in (
                "flash", "ring_flash"):
            # the fused kernel reads grouped kv natively (and under
            # ring_flash the GROUPED blocks rotate the ring — 1/grp the
            # ppermute bytes, GQA's whole point); other impls see the
            # expanded heads
            k = jnp.repeat(k, self.n_heads // n_kv, axis=-2)
            v = jnp.repeat(v, self.n_heads // n_kv, axis=-2)
        out = _attend(self.attention_impl, self.axis_name, q, k, v,
                      causal=True)
        x = x + dense(d_model, "proj")(out.reshape(h.shape))

        h = ln("ln_mlp")(x)
        if self.moe_experts:
            from chainermn_tpu.parallel.expert import ExpertParallelMLP

            y, stats = ExpertParallelMLP(
                hidden=4 * d_model, axis_name=self.moe_axis,
                capacity=self.moe_capacity, dtype=self.dtype,
                top_k=self.moe_top_k, num_experts=self.moe_experts,
                with_stats=True, name="moe")(h)
            self.sow("moe_stats", "aux_loss", stats["aux_loss"])
            self.sow("moe_stats", "overflow_fraction",
                     stats["overflow_fraction"])
            self.sow("moe_stats", "expert_load", stats["expert_load"])
            return x + y
        h = nn.gelu(dense(4 * d_model, "up")(h))
        return x + dense(d_model, "down")(h)


class TransformerLM(nn.Module):
    """``apply(params, tokens[B, T]) -> logits[B, T, vocab]`` (causal).

    With ``attention_impl="ring"``/``"ulysses"``, apply inside an SPMD
    region (``shard_map``) with ``tokens`` sharded [B, T/P] on
    ``axis_name`` — positions are global via ``pos_offset``.
    """

    vocab: int
    d_model: int = 256
    n_layers: int = 4
    n_heads: int = 8
    max_len: int = 8192
    attention_impl: str = "xla"
    axis_name: Any = None
    dtype: Any = jnp.float32
    n_kv_heads: Optional[int] = None  # < n_heads = GQA/MQA
    moe_experts: int = 0          # >0: MoE MLP in every block (EP over moe_axis)
    moe_top_k: int = 1
    moe_axis: Any = "ep"
    moe_capacity: Optional[int] = None

    @nn.compact
    def __call__(self, tokens, pos_offset=0):
        if self.d_model % self.n_heads:
            raise ValueError(
                f"n_heads ({self.n_heads}) must divide d_model "
                f"({self.d_model})")
        if self.n_kv_heads is not None and (
                self.n_kv_heads < 1 or self.n_heads % self.n_kv_heads):
            raise ValueError(
                f"n_kv_heads ({self.n_kv_heads}) must be >= 1 and divide "
                f"n_heads ({self.n_heads})")
        x = nn.Embed(self.vocab, self.d_model, param_dtype=jnp.float32,
                     dtype=self.dtype, name="tok_emb")(tokens)
        pos = nn.Embed(self.max_len, self.d_model, param_dtype=jnp.float32,
                       dtype=self.dtype, name="pos_emb")(
            pos_offset + jnp.arange(tokens.shape[-1]))
        x = x + pos
        for i in range(self.n_layers):
            x = Block(self.n_heads, self.attention_impl, self.axis_name,
                      self.dtype, n_kv_heads=self.n_kv_heads,
                      moe_experts=self.moe_experts,
                      moe_top_k=self.moe_top_k, moe_axis=self.moe_axis,
                      moe_capacity=self.moe_capacity, name=f"block_{i}")(x)
        x = nn.LayerNorm(dtype=self.dtype, param_dtype=jnp.float32,
                         name="ln_f")(x)
        logits = nn.Dense(self.vocab, dtype=self.dtype,
                          param_dtype=jnp.float32, name="head")(x)
        return logits.astype(jnp.float32)


__all__ = ["Block", "TransformerLM"]
