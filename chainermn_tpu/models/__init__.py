from chainermn_tpu.models.mlp import MLP

__all__ = ["MLP"]
