from chainermn_tpu.models.alexnet import AlexNet
from chainermn_tpu.models.googlenet import GoogLeNet, GoogLeNetBN
from chainermn_tpu.models.mlp import MLP
from chainermn_tpu.models.nin import NIN
from chainermn_tpu.models.resnet import (
    REMAT_POLICIES,
    BasicBlock,
    BottleneckBlock,
    ResNet,
    ResNet18,
    ResNet34,
    ResNet50,
    ResNet101,
    ResNet152,
)
from chainermn_tpu.models.transformer import TransformerLM
from chainermn_tpu.models.vgg import VGG, VGG16
from chainermn_tpu.models.vit import ViT, ViT_B16, ViT_S16

__all__ = [
    "TransformerLM",
    "MLP",
    "AlexNet",
    "NIN",
    "GoogLeNet",
    "GoogLeNetBN",
    "REMAT_POLICIES",
    "BasicBlock",
    "BottleneckBlock",
    "ResNet",
    "ResNet18",
    "ResNet34",
    "ResNet50",
    "ResNet101",
    "ResNet152",
    "VGG",
    "VGG16",
    "ViT",
    "ViT_S16",
    "ViT_B16",
]
