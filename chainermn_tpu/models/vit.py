"""Vision Transformer — the MXU-shaped image classifier.

**Beyond-reference extension** (the reference's model zoo is 2017 ImageNet
convnets + an LSTM seq2seq — SURVEY.md §2.6; ViT postdates it).  It exists
for a measured reason: the reference's flagship ResNet-50 is memory-bound
on TPU (14.7% MFU at the practical ceiling — docs/performance.md pins the
floor from every side), because its early stages are 64/128-channel convs
that under-fill the 128-lane MXU.  A ViT of the same parameter class is
almost entirely large matmuls, i.e. exactly what the MXU is built for —
so it demonstrates the framework's compute ceiling on the same
data-parallel machinery (`create_communicator` → `make_train_step`) the
convnets use.  `benchmarks/bench_vit.py` measures it on-chip.

Architecture (standard ViT, Dosovitskiy et al. 2020): patchify via a
stride-``patch`` conv, prepend a learned [CLS] token (or mean-pool with
``pooling="gap"``), learned position embeddings, pre-LN encoder blocks
(non-causal self-attention + GELU MLP), classify from the final LN'd
[CLS] row.  bf16-capable with f32 parameters, like the rest of the zoo.

``attention_impl`` is pluggable like :class:`TransformerLM`'s: ``"xla"``
(default — at 197 tokens the unfused math is a fine single fusion) or
``"flash"`` (the Pallas kernel; the whole sequence fits one tile).
"""

from __future__ import annotations

from typing import Any

import flax.linen as nn
import jax.numpy as jnp


def _encoder_attention(impl: str, q, k, v):
    if impl == "flash":
        from chainermn_tpu.ops.flash_attention import flash_attention

        return flash_attention(q, k, v, causal=False)
    if impl == "xla":
        from chainermn_tpu.parallel.sequence import attention

        return attention(q, k, v, causal=False)
    raise ValueError(f"attention_impl must be xla|flash, got {impl!r}")


class EncoderBlock(nn.Module):
    """Pre-LN non-causal transformer encoder block (attention + GELU MLP)."""

    n_heads: int
    mlp_ratio: int = 4
    attention_impl: str = "xla"
    dropout: float = 0.0
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = False):
        d_model = x.shape[-1]
        head_dim = d_model // self.n_heads
        dense = lambda f, name: nn.Dense(
            f, dtype=self.dtype, param_dtype=jnp.float32, name=name)
        ln = lambda name: nn.LayerNorm(dtype=self.dtype,
                                       param_dtype=jnp.float32, name=name)
        drop = lambda h: nn.Dropout(self.dropout, deterministic=not train)(h)

        h = ln("ln_attn")(x)
        qkv = dense(3 * d_model, "qkv")(h)
        q, k, v = jnp.split(qkv, 3, axis=-1)
        shape = h.shape[:-1] + (self.n_heads, head_dim)
        out = _encoder_attention(
            self.attention_impl, q.reshape(shape), k.reshape(shape),
            v.reshape(shape))
        x = x + drop(dense(d_model, "proj")(out.reshape(h.shape)))

        h = ln("ln_mlp")(x)
        h = nn.gelu(dense(self.mlp_ratio * d_model, "up")(h))
        return x + drop(dense(d_model, "down")(drop(h)))


class ViT(nn.Module):
    """``apply({"params": p}, images[B, H, W, 3], train=...) ->
    logits[B, num_classes]`` — same calling convention as the conv zoo
    (no BatchNorm state; LayerNorm throughout)."""

    num_classes: int = 1000
    patch: int = 16
    d_model: int = 768
    n_layers: int = 12
    n_heads: int = 12
    mlp_ratio: int = 4
    pooling: str = "cls"          # "cls" token or "gap" mean pooling
    attention_impl: str = "xla"
    dropout: float = 0.0
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = False):
        if self.d_model % self.n_heads:
            raise ValueError(
                f"n_heads ({self.n_heads}) must divide d_model "
                f"({self.d_model})")
        if x.shape[1] % self.patch or x.shape[2] % self.patch:
            raise ValueError(
                f"image size {x.shape[1]}x{x.shape[2]} must be a multiple "
                f"of the patch size ({self.patch})")
        if self.pooling not in ("cls", "gap"):
            raise ValueError(f"pooling must be cls|gap, got {self.pooling!r}")
        x = x.astype(self.dtype)
        # patchify: one stride-`patch` conv == per-patch linear projection
        x = nn.Conv(self.d_model, (self.patch, self.patch),
                    strides=(self.patch, self.patch), padding="VALID",
                    dtype=self.dtype, param_dtype=jnp.float32,
                    name="patch_embed")(x)
        b = x.shape[0]
        x = x.reshape(b, -1, self.d_model)
        if self.pooling == "cls":
            cls = self.param("cls_token", nn.initializers.zeros,
                             (1, 1, self.d_model), jnp.float32)
            x = jnp.concatenate(
                [jnp.broadcast_to(cls, (b, 1, self.d_model)).astype(
                    self.dtype), x], axis=1)
        pos = self.param("pos_embed",
                         nn.initializers.normal(stddev=0.02),
                         (1, x.shape[1], self.d_model), jnp.float32)
        x = x + pos.astype(self.dtype)
        x = nn.Dropout(self.dropout, deterministic=not train)(x)
        for i in range(self.n_layers):
            x = EncoderBlock(self.n_heads, self.mlp_ratio,
                             self.attention_impl, self.dropout, self.dtype,
                             name=f"block_{i}")(x, train=train)
        x = nn.LayerNorm(dtype=self.dtype, param_dtype=jnp.float32,
                         name="ln_f")(x)
        x = x[:, 0] if self.pooling == "cls" else x.mean(axis=1)
        logits = nn.Dense(self.num_classes, dtype=self.dtype,
                          param_dtype=jnp.float32, name="head")(x)
        return logits.astype(jnp.float32)


def ViT_S16(**kw):
    """ViT-Small/16: 384 wide, 12 layers, 6 heads (~22M params)."""
    kw.setdefault("d_model", 384)
    kw.setdefault("n_layers", 12)
    kw.setdefault("n_heads", 6)
    return ViT(**kw)


def ViT_B16(**kw):
    """ViT-Base/16: 768 wide, 12 layers, 12 heads (~86M params)."""
    return ViT(**kw)


__all__ = ["EncoderBlock", "ViT", "ViT_S16", "ViT_B16"]
