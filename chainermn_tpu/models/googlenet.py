"""GoogLeNet (Inception v1) and its BatchNorm variant.

Reference being rebuilt (path unverified, SURVEY.md provenance):
〔examples/imagenet/models/googlenet.py〕 and
〔examples/imagenet/models/googlenetbn.py〕 — the two Inception
architectures in the reference's ImageNet example.  The BN variant follows
the inception-BN recipe (BN after every conv, 3x3 factorization of the 5x5
tower); the plain variant matches Szegedy et al.'s v1 towers.  With
``aux_heads=True`` the two auxiliary classifiers (after 4a and 4d) are
built and returned during training — the reference example's recipe sums
``loss1*0.3 + loss2*0.3 + loss3`` 〔examples/imagenet/models/googlenet.py〕;
pass ``--aux-loss`` to ``train_imagenet.py`` for that objective.

NHWC / bf16-capable.  ``GoogLeNetBN`` carries ``batch_stats`` (local-BN,
same semantics as :mod:`.resnet`); plain ``GoogLeNet`` does not.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Optional, Tuple

import flax.linen as nn
import jax.numpy as jnp


class InceptionBlock(nn.Module):
    """Parallel 1x1 / 3x3 / 5x5 / pool-proj towers, channel-concatenated."""

    c1: int
    c3r: int
    c3: int
    c5r: int
    c5: int
    cp: int
    use_bn: bool = False
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = True):
        conv = partial(nn.Conv, padding="SAME", dtype=self.dtype,
                       param_dtype=jnp.float32, use_bias=not self.use_bn)
        def unit(y, f, k):
            y = conv(f, k)(y)
            if self.use_bn:
                y = nn.BatchNorm(use_running_average=not train, momentum=0.9,
                                 epsilon=1e-5, dtype=self.dtype,
                                 param_dtype=jnp.float32)(y)
            return nn.relu(y)

        t1 = unit(x, self.c1, (1, 1))
        t3 = unit(unit(x, self.c3r, (1, 1)), self.c3, (3, 3))
        if self.use_bn:
            # inception-BN factorizes the 5x5 tower into two 3x3 convs
            t5 = unit(x, self.c5r, (1, 1))
            t5 = unit(t5, self.c5, (3, 3))
            t5 = unit(t5, self.c5, (3, 3))
        else:
            t5 = unit(unit(x, self.c5r, (1, 1)), self.c5, (5, 5))
        tp = nn.max_pool(x, (3, 3), strides=(1, 1), padding="SAME")
        tp = unit(tp, self.cp, (1, 1))
        return jnp.concatenate([t1, t3, t5, tp], axis=-1)


# (c1, c3r, c3, c5r, c5, cp) per inception block, Szegedy et al. table 1.
_BLOCKS = {
    "3a": (64, 96, 128, 16, 32, 32),
    "3b": (128, 128, 192, 32, 96, 64),
    "4a": (192, 96, 208, 16, 48, 64),
    "4b": (160, 112, 224, 24, 64, 64),
    "4c": (128, 128, 256, 24, 64, 64),
    "4d": (112, 144, 288, 32, 64, 64),
    "4e": (256, 160, 320, 32, 128, 128),
    "5a": (256, 160, 320, 32, 128, 128),
    "5b": (384, 192, 384, 48, 128, 128),
}


class _AuxHead(nn.Module):
    """Auxiliary classifier (Szegedy et al. §5): 5x5/3 avgpool -> 1x1 conv
    128 -> dense 1024 -> dropout 0.7 -> classes."""

    num_classes: int
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = True):
        # 5x5/3 valid pool assumes the 224-px feature map (14x14 -> 4x4);
        # clamp the window so small inputs (tests, tiny images) still
        # produce a non-empty map instead of a zero-size Dense input
        win = (min(5, x.shape[1]), min(5, x.shape[2]))
        x = nn.avg_pool(x, win, strides=(3, 3))
        x = nn.relu(nn.Conv(128, (1, 1), dtype=self.dtype,
                            param_dtype=jnp.float32)(x))
        x = x.reshape(x.shape[0], -1)
        x = nn.relu(nn.Dense(1024, dtype=self.dtype,
                             param_dtype=jnp.float32)(x))
        x = nn.Dropout(0.7, deterministic=not train)(x)
        x = nn.Dense(self.num_classes, dtype=self.dtype,
                     param_dtype=jnp.float32)(x)
        return x.astype(jnp.float32)


class GoogLeNet(nn.Module):
    num_classes: int = 1000
    dtype: Any = jnp.float32
    use_bn: bool = False
    dropout_rate: float = 0.4
    aux_heads: bool = False   # return (logits, (aux1, aux2)) when training

    @nn.compact
    def __call__(self, x, train: bool = True):
        conv = partial(nn.Conv, padding="SAME", dtype=self.dtype,
                       param_dtype=jnp.float32, use_bias=not self.use_bn)

        def unit(y, f, k, s=(1, 1)):
            y = conv(f, k, s)(y)
            if self.use_bn:
                y = nn.BatchNorm(use_running_average=not train, momentum=0.9,
                                 epsilon=1e-5, dtype=self.dtype,
                                 param_dtype=jnp.float32)(y)
            return nn.relu(y)

        x = x.astype(self.dtype)
        x = unit(x, 64, (7, 7), (2, 2))
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding="SAME")
        x = unit(x, 64, (1, 1))
        x = unit(x, 192, (3, 3))
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding="SAME")
        for name in ("3a", "3b"):
            x = InceptionBlock(*_BLOCKS[name], use_bn=self.use_bn,
                               dtype=self.dtype, name=f"inc{name}")(x, train)
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding="SAME")
        aux = []
        for name in ("4a", "4b", "4c", "4d", "4e"):
            x = InceptionBlock(*_BLOCKS[name], use_bn=self.use_bn,
                               dtype=self.dtype, name=f"inc{name}")(x, train)
            if self.aux_heads and name in ("4a", "4d"):
                aux.append(_AuxHead(self.num_classes, self.dtype,
                                    name=f"aux{name}")(x, train))
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding="SAME")
        for name in ("5a", "5b"):
            x = InceptionBlock(*_BLOCKS[name], use_bn=self.use_bn,
                               dtype=self.dtype, name=f"inc{name}")(x, train)
        x = jnp.mean(x, axis=(1, 2))
        x = nn.Dropout(self.dropout_rate, deterministic=not train)(x)
        x = nn.Dense(self.num_classes, dtype=self.dtype,
                     param_dtype=jnp.float32)(x)
        logits = x.astype(jnp.float32)
        if self.aux_heads and train:
            return logits, tuple(aux)
        return logits


GoogLeNetBN = partial(GoogLeNet, use_bn=True)
