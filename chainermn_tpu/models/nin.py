"""Network-in-Network — one of the reference ImageNet example's architectures.

Reference being rebuilt (path unverified, SURVEY.md provenance):
〔examples/imagenet/models/nin.py〕 — Chainer's NIN: four "mlpconv" stacks
(a spatial conv followed by two 1x1 convs), max-pooling between them, global
average pooling over ``num_classes`` maps instead of a dense head.

NHWC / bf16-capable; no BatchNorm, so no ``batch_stats``.
"""

from __future__ import annotations

from typing import Any

import flax.linen as nn
import jax.numpy as jnp


class NIN(nn.Module):
    num_classes: int = 1000
    dtype: Any = jnp.float32
    dropout_rate: float = 0.5

    def _mlpconv(self, x, f, k, s):
        conv = lambda ff, kk, ss=(1, 1): nn.Conv(
            ff, kk, ss, padding="SAME", dtype=self.dtype,
            param_dtype=jnp.float32)
        x = nn.relu(conv(f, k, s)(x))
        x = nn.relu(conv(f, (1, 1))(x))
        return nn.relu(conv(f, (1, 1))(x))

    @nn.compact
    def __call__(self, x, train: bool = True):
        # stride-4 stem + three stride-2 valid pools: below ~48px the last
        # stack's spatial dims reach zero and the global mean silently
        # yields NaN logits — fail loudly instead
        if min(x.shape[1], x.shape[2]) < 48:
            raise ValueError(
                f"NIN needs inputs of at least 48x48 (got "
                f"{x.shape[1]}x{x.shape[2]}); smaller images collapse to "
                "an empty feature map under its stride-4 stem + three "
                "pools and the global average becomes NaN")
        x = x.astype(self.dtype)
        x = self._mlpconv(x, 96, (11, 11), (4, 4))
        x = nn.max_pool(x, (3, 3), strides=(2, 2))
        x = self._mlpconv(x, 256, (5, 5), (1, 1))
        x = nn.max_pool(x, (3, 3), strides=(2, 2))
        x = self._mlpconv(x, 384, (3, 3), (1, 1))
        x = nn.max_pool(x, (3, 3), strides=(2, 2))
        x = nn.Dropout(self.dropout_rate, deterministic=not train)(x)
        x = self._mlpconv(x, self.num_classes, (3, 3), (1, 1))
        return jnp.mean(x, axis=(1, 2)).astype(jnp.float32)
