"""MLP — the reference MNIST example's model.

Reference 〔examples/mnist/train_mnist.py〕 (path unverified, SURVEY.md
provenance): a 784-1000-1000-10 ReLU MLP.  Rebuilt in flax.linen (the
define-by-run Chainer Chain role in the JAX world).
"""

from typing import Sequence

import flax.linen as nn
import jax.numpy as jnp


class MLP(nn.Module):
    n_units: int = 1000
    n_out: int = 10

    @nn.compact
    def __call__(self, x):
        x = x.reshape((x.shape[0], -1)).astype(jnp.float32)
        x = nn.relu(nn.Dense(self.n_units)(x))
        x = nn.relu(nn.Dense(self.n_units)(x))
        return nn.Dense(self.n_out)(x)
