"""ResNet family — the reference ImageNet example's flagship model.

Reference being rebuilt (path unverified, SURVEY.md provenance):
〔examples/imagenet/models/resnet50.py〕 — the ResNet-50 used for the
north-star benchmark (BASELINE.json configs[1], configs[4]; the "ImageNet in
15 minutes" model of arXiv:1711.04325).

TPU-native design notes:

* NHWC layout (XLA's native TPU conv layout) with a ``dtype`` knob so the
  convs/matmuls run in bfloat16 on the MXU while parameters and BatchNorm
  statistics stay float32 (``param_dtype``).
* BatchNorm uses *local* per-device statistics during training — the
  reference's semantics (SURVEY.md §7 hard part 5); running stats live in
  the ``batch_stats`` collection and are synced on demand by
  ``AllreducePersistent``, never psum-ed inside the step.
* The generic :class:`ResNet` also yields ResNet-18/34/101/152 from stage
  sizes, and a width knob small enough to unit-test on the CPU mesh.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Callable, Sequence, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax.ad_checkpoint import checkpoint_name

ModuleDef = Any

#: remat_policy zoo swept by ``benchmarks/run_configs.py --tune-remat``.
REMAT_POLICIES = ("none", "block", "norm")


def _tag(x):
    """Name conv outputs (= norm inputs) for checkpoint policies.

    Identity unless a ``remat_policy="norm"`` wrapper references the name:
    that policy saves exactly these boundaries and recomputes the cheap
    normalize/ReLU tail in the backward pass.
    """
    return checkpoint_name(x, "norm_in")


def _norm_relu(norm: ModuleDef, x, **kwargs):
    """norm -> ReLU, fused into one kernel when the norm class supports it.

    ``ops.FusedBatchNormAct`` advertises ``supports_fused_relu`` and takes
    the ReLU along on the same HBM traversal; any other ``norm_cls`` (the
    default ``nn.BatchNorm`` included) keeps the reference unfused path.
    """
    cls = norm.func if isinstance(norm, partial) else norm
    if getattr(cls, "supports_fused_relu", False):
        return norm(fuse_relu=True, **kwargs)(x)
    return nn.relu(norm(**kwargs)(x))


def space_to_depth(x, block: int = 2):
    """NHWC space-to-depth: (N, H, W, C) -> (N, H/b, W/b, b*b*C).

    Pure data movement (a reshape/transpose pair); XLA lowers it to a
    layout change, not a gather.
    """
    n, h, w, c = x.shape
    if h % block or w % block:
        raise ValueError(f"spatial dims {(h, w)} not divisible by {block}")
    x = x.reshape(n, h // block, block, w // block, block, c)
    x = x.transpose(0, 1, 3, 2, 4, 5)
    return x.reshape(n, h // block, w // block, block * block * c)


class BottleneckBlock(nn.Module):
    """1x1 -> 3x3 -> 1x1 bottleneck with projection shortcut on shape change."""

    filters: int
    conv: ModuleDef
    norm: ModuleDef
    strides: Tuple[int, int] = (1, 1)

    @nn.compact
    def __call__(self, x):
        residual = x
        y = _tag(self.conv(self.filters, (1, 1))(x))
        y = _norm_relu(self.norm, y)
        y = _tag(self.conv(self.filters, (3, 3), self.strides)(y))
        y = _norm_relu(self.norm, y)
        y = _tag(self.conv(self.filters * 4, (1, 1))(y))
        # Zero-init the last BN scale so each block starts as identity —
        # standard large-batch ResNet recipe (matches the reference era's
        # training tricks for the 32k-batch runs).  No ReLU here: the
        # activation lands after the residual add.
        y = self.norm(scale_init=nn.initializers.zeros_init())(y)
        if residual.shape != y.shape:
            residual = _tag(self.conv(self.filters * 4, (1, 1), self.strides,
                                      name="conv_proj")(residual))
            residual = self.norm(name="norm_proj")(residual)
        return nn.relu(residual + y)


class BasicBlock(nn.Module):
    """3x3 -> 3x3 residual block (ResNet-18/34)."""

    filters: int
    conv: ModuleDef
    norm: ModuleDef
    strides: Tuple[int, int] = (1, 1)

    @nn.compact
    def __call__(self, x):
        residual = x
        y = _tag(self.conv(self.filters, (3, 3), self.strides)(x))
        y = _norm_relu(self.norm, y)
        y = _tag(self.conv(self.filters, (3, 3))(y))
        y = self.norm(scale_init=nn.initializers.zeros_init())(y)
        if residual.shape != y.shape:
            residual = _tag(self.conv(self.filters, (1, 1), self.strides,
                                      name="conv_proj")(residual))
            residual = self.norm(name="norm_proj")(residual)
        return nn.relu(residual + y)


class ResNet(nn.Module):
    """Generic ResNet over NHWC inputs.

    ``__call__(x, train=True)``; when ``train`` the BatchNorm layers use the
    minibatch (local-device) statistics and update ``batch_stats``.
    """

    stage_sizes: Sequence[int]
    block_cls: ModuleDef = BottleneckBlock
    num_classes: int = 1000
    num_filters: int = 64
    dtype: Any = jnp.float32
    momentum: float = 0.9
    norm_cls: Any = None  # default nn.BatchNorm; swap for perf probes/variants
    stem: str = "conv7"  # "conv7" (reference) | "s2d" (space-to-depth, TPU)
    remat_policy: str = "none"  # "none" | "block" (full nn.remat) | "norm"
    #  ("norm" saves only the checkpoint_name'd conv outputs at norm
    #   boundaries and recomputes the normalize/ReLU tail in backward —
    #   swept by benchmarks/run_configs.py --tune-remat)

    @nn.compact
    def __call__(self, x, train: bool = True):
        conv = partial(nn.Conv, use_bias=False, dtype=self.dtype,
                       param_dtype=jnp.float32, padding="SAME")
        norm = partial(self.norm_cls or nn.BatchNorm,
                       use_running_average=not train,
                       momentum=self.momentum, epsilon=1e-5,
                       dtype=self.dtype, param_dtype=jnp.float32)
        x = x.astype(self.dtype)
        if self.stem == "s2d":
            # Space-to-depth stem — the standard TPU MLPerf ResNet input
            # transform: the 7x7/s2 conv over (H, W, 3) is re-expressed as a
            # 4x4/s1 conv over the (H/2, W/2, 12) space-to-depth view.  Any
            # 7x7/s2 stem zero-padded to 8x8 maps exactly onto these 4x4x12
            # weights, so (trained from scratch) this parameterizes a
            # superset of the reference stem while feeding the MXU 12 input
            # lanes instead of 3.  The rest of the network is unchanged.
            x = space_to_depth(x, 2)
            x = conv(self.num_filters, (4, 4), name="conv_init")(x)
        elif self.stem == "conv7":
            x = conv(self.num_filters, (7, 7), (2, 2), name="conv_init")(x)
        else:
            raise ValueError(
                f"unknown stem {self.stem!r}: expected 'conv7' or 's2d'")
        x = _norm_relu(norm, _tag(x), name="bn_init")
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding="SAME")
        if self.remat_policy == "none":
            block_cls = self.block_cls
        elif self.remat_policy == "block":
            block_cls = nn.remat(self.block_cls)
        elif self.remat_policy == "norm":
            block_cls = nn.remat(
                self.block_cls,
                policy=jax.checkpoint_policies.save_only_these_names(
                    "norm_in"))
        else:
            raise ValueError(
                f"unknown remat_policy {self.remat_policy!r}: "
                f"expected one of {REMAT_POLICIES}")
        for i, block_count in enumerate(self.stage_sizes):
            for j in range(block_count):
                strides = (2, 2) if i > 0 and j == 0 else (1, 1)
                x = block_cls(self.num_filters * 2 ** i,
                              conv=conv, norm=norm, strides=strides)(x)
        x = jnp.mean(x, axis=(1, 2))
        x = nn.Dense(self.num_classes, dtype=self.dtype,
                     param_dtype=jnp.float32)(x)
        return x.astype(jnp.float32)


ResNet18 = partial(ResNet, stage_sizes=(2, 2, 2, 2), block_cls=BasicBlock)
ResNet34 = partial(ResNet, stage_sizes=(3, 4, 6, 3), block_cls=BasicBlock)
ResNet50 = partial(ResNet, stage_sizes=(3, 4, 6, 3), block_cls=BottleneckBlock)
ResNet101 = partial(ResNet, stage_sizes=(3, 4, 23, 3), block_cls=BottleneckBlock)
ResNet152 = partial(ResNet, stage_sizes=(3, 8, 36, 3), block_cls=BottleneckBlock)
