"""AlexNet — one of the reference ImageNet example's architectures.

Reference being rebuilt (path unverified, SURVEY.md provenance):
〔examples/imagenet/models/alex.py〕 — Chainer's AlexNet variant used in the
ImageNet example (conv5 + fc3, local response normalization after the first
two conv stages, dropout on the fc head).

NHWC / bf16-capable, same conventions as :mod:`.resnet`.  LRN is implemented
inline (XLA fuses the window sum); AlexNet has no BatchNorm, so it carries
no ``batch_stats`` — train it with ``make_train_step(with_model_state=False)``.
"""

from __future__ import annotations

from typing import Any

import flax.linen as nn
import jax.numpy as jnp


def local_response_normalization(x, n: int = 5, k: float = 2.0,
                                 alpha: float = 1e-4, beta: float = 0.75):
    """Krizhevsky-style LRN over the channel axis (NHWC)."""
    sq = jnp.square(x.astype(jnp.float32))
    c = x.shape[-1]
    half = n // 2
    pad = jnp.pad(sq, [(0, 0)] * (x.ndim - 1) + [(half, half)])
    windows = jnp.stack([pad[..., i:i + c] for i in range(n)], axis=0)
    denom = (k + alpha * windows.sum(axis=0)) ** beta
    return (x.astype(jnp.float32) / denom).astype(x.dtype)


class AlexNet(nn.Module):
    num_classes: int = 1000
    dtype: Any = jnp.float32
    dropout_rate: float = 0.5

    @nn.compact
    def __call__(self, x, train: bool = True):
        dense = lambda n: nn.Dense(n, dtype=self.dtype,
                                   param_dtype=jnp.float32)
        conv = lambda f, k, s=(1, 1): nn.Conv(
            f, k, s, padding="SAME", dtype=self.dtype,
            param_dtype=jnp.float32)
        x = x.astype(self.dtype)
        x = nn.relu(conv(96, (11, 11), (4, 4))(x))
        x = local_response_normalization(x)
        x = nn.max_pool(x, (3, 3), strides=(2, 2))
        x = nn.relu(conv(256, (5, 5))(x))
        x = local_response_normalization(x)
        x = nn.max_pool(x, (3, 3), strides=(2, 2))
        x = nn.relu(conv(384, (3, 3))(x))
        x = nn.relu(conv(384, (3, 3))(x))
        x = nn.relu(conv(256, (3, 3))(x))
        x = nn.max_pool(x, (3, 3), strides=(2, 2))
        x = x.reshape((x.shape[0], -1))
        x = nn.relu(dense(4096)(x))
        x = nn.Dropout(self.dropout_rate, deterministic=not train)(x)
        x = nn.relu(dense(4096)(x))
        x = nn.Dropout(self.dropout_rate, deterministic=not train)(x)
        return dense(self.num_classes)(x).astype(jnp.float32)
