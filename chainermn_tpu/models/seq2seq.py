"""Encoder/decoder sequence-to-sequence model, split for model parallelism.

Reference being rebuilt (path unverified, SURVEY.md provenance):
〔examples/seq2seq/seq2seq.py〕 — an NStepLSTM encoder on one rank and
decoder on another, wired through ``MultiNodeChainList``/send-recv
(BASELINE.json configs[3]).  Rebuilt as two flax modules whose cross-stage
interface is the LSTM carry pytree — exactly the tensor the reference
shipped between ranks.
"""

from typing import Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp


class Seq2SeqEncoder(nn.Module):
    """Embed + LSTM; returns the final carry (the cross-rank tensor)."""

    vocab_size: int
    embed_dim: int = 64
    hidden: int = 128

    @nn.compact
    def __call__(self, src):
        emb = nn.Embed(self.vocab_size, self.embed_dim)(src)
        carry, _ = nn.RNN(nn.OptimizedLSTMCell(self.hidden),
                          return_carry=True)(emb)
        return carry  # (c, h) pytree -> sent to the decoder's rank


class Seq2SeqDecoder(nn.Module):
    """Teacher-forced LSTM decoder seeded with the encoder carry."""

    vocab_size: int
    embed_dim: int = 64
    hidden: int = 128

    @nn.compact
    def __call__(self, enc_carry, tgt_in):
        emb = nn.Embed(self.vocab_size, self.embed_dim)(tgt_in)
        outs = nn.RNN(nn.OptimizedLSTMCell(self.hidden))(
            emb, initial_carry=enc_carry)
        return nn.Dense(self.vocab_size)(outs)


def make_copy_reverse_task(n: int, seq_len: int, vocab: int, seed: int = 0):
    """Synthetic translation stand-in: target = reversed source.  BOS token
    is id 1; ids 2.. are symbols; 0 is pad (unused — fixed lengths keep XLA
    shapes static)."""
    import numpy as np

    rng = np.random.RandomState(seed)
    src = rng.randint(2, vocab, size=(n, seq_len)).astype(np.int32)
    tgt = src[:, ::-1].copy()
    bos = np.ones((n, 1), np.int32)
    tgt_in = np.concatenate([bos, tgt[:, :-1]], axis=1)
    return src, tgt_in, tgt
