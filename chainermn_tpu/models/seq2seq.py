"""Encoder/decoder sequence-to-sequence model, split for model parallelism.

Reference being rebuilt (path unverified, SURVEY.md provenance):
〔examples/seq2seq/seq2seq.py〕 — an NStepLSTM encoder on one rank and
decoder on another, wired through ``MultiNodeChainList``/send-recv
(BASELINE.json configs[3]).  Rebuilt as two flax modules whose cross-stage
interface is the LSTM carry pytree — exactly the tensor the reference
shipped between ranks.

The reference's NStepLSTM consumed ragged sentence lists; the TPU-native
equivalent is padded buckets with explicit ``lengths``: the encoder uses
``nn.RNN(..., seq_lengths=...)`` so the carry it ships across the stage
boundary is the state at each sentence's TRUE final token, not at the pad
tail.  The decoder exposes both the teacher-forced ``__call__`` (training)
and a greedy autoregressive ``decode`` (translation/BLEU evaluation —
the reference example's ``translate`` path).
"""

from typing import Optional

import flax.linen as nn
import jax
import jax.numpy as jnp


class Seq2SeqEncoder(nn.Module):
    """Embed + LSTM; returns the final carry (the cross-rank tensor).

    ``lengths`` (optional, per-example true source lengths) makes the
    returned carry the state at each sequence's last real token.
    """

    vocab_size: int
    embed_dim: int = 64
    hidden: int = 128

    @nn.compact
    def __call__(self, src, lengths: Optional[jax.Array] = None):
        emb = nn.Embed(self.vocab_size, self.embed_dim)(src)
        carry, _ = nn.RNN(nn.OptimizedLSTMCell(self.hidden),
                          return_carry=True)(emb, seq_lengths=lengths)
        return carry  # (c, h) pytree -> sent to the decoder's rank


class Seq2SeqDecoder(nn.Module):
    """LSTM decoder seeded with the encoder carry.

    ``__call__`` is the teacher-forced training path; ``decode`` (use via
    ``module.apply(params, carry, max_len, method="decode")``) is greedy
    autoregressive generation for translation metrics.  Both share the
    same embed/cell/output parameters (setup-style submodules).
    """

    vocab_size: int
    embed_dim: int = 64
    hidden: int = 128

    def setup(self):
        self.embed = nn.Embed(self.vocab_size, self.embed_dim)
        self.cell = nn.OptimizedLSTMCell(self.hidden)
        self.out = nn.Dense(self.vocab_size)

    def _scan_cell(self, carry, emb):
        scan = nn.scan(lambda cell, c, x: cell(c, x),
                       variable_broadcast="params",
                       split_rngs={"params": False},
                       in_axes=1, out_axes=1)
        return scan(self.cell, carry, emb)

    def __call__(self, enc_carry, tgt_in):
        emb = self.embed(tgt_in)                      # (B, T, E)
        _, hs = self._scan_cell(enc_carry, emb)       # (B, T, H)
        return self.out(hs)

    def decode(self, enc_carry, max_len: int, bos_id: int = 1):
        """Greedy decode: feed BOS, then each argmax token back in.
        Returns (B, max_len) int32 token ids (caller truncates at EOS)."""
        batch = jax.tree.leaves(enc_carry)[0].shape[0]

        def step(cell, state, _):
            carry, tok = state
            carry, h = cell(carry, self.embed(tok))
            nxt = jnp.argmax(self.out(h), axis=-1).astype(jnp.int32)
            return (carry, nxt), nxt

        scan = nn.scan(step, variable_broadcast="params",
                       split_rngs={"params": False},
                       in_axes=0, out_axes=1, length=max_len)
        init = (enc_carry, jnp.full((batch,), bos_id, jnp.int32))
        _, toks = scan(self.cell, init, None)
        return toks


def make_copy_reverse_task(n: int, seq_len: int, vocab: int, seed: int = 0):
    """Synthetic translation stand-in: target = reversed source.  BOS token
    is id 1; ids 2.. are symbols; 0 is pad (unused — fixed lengths keep XLA
    shapes static)."""
    import numpy as np

    rng = np.random.RandomState(seed)
    src = rng.randint(2, vocab, size=(n, seq_len)).astype(np.int32)
    tgt = src[:, ::-1].copy()
    bos = np.ones((n, 1), np.int32)
    tgt_in = np.concatenate([bos, tgt[:, :-1]], axis=1)
    return src, tgt_in, tgt
