"""Fused BatchNorm(+ReLU) Pallas kernels for the normalization boundary.

ResNet-50 on TPU is memory-bound at its BatchNorm boundaries, not
MXU-bound (docs/performance.md rounds 2-5: deleting every BatchNorm
recovers ~19.5 ms of a 114 ms step, and the per-stage roofline analysis
puts stage 1/2 at the HBM ceiling).  The unfused flax path walks each
activation through HBM several times per boundary: the stat reductions
read x, the normalize chain reads x and round-trips intermediates, the
ReLU round-trips again, and the backward repeats the pattern for the
dγ/dβ reductions and dx.  These kernels collapse each direction to the
minimum number of full-activation traversals a batch-global
normalization permits:

  forward  (train): stats pass (read x once)  +  apply pass (read x,
                    write y)                      -> 3 traversals
  forward  (eval):  apply pass only               -> 2 traversals
  backward:         reduce pass (read x, g; emit dβ/dγ)  +
                    dx pass (read x, g; write dx) -> 5 traversals

BatchNorm's batch-global mean/var are a grid-wide barrier, so the stats
pass cannot fuse into the apply pass (every tile of y needs the *final*
statistics); the same holds for the backward sums feeding dx in train
mode.  Two passes per direction is therefore the floor, and the fused
kernels hit it.  ``fused_norm_traffic_bytes`` prices both sides of this
ledger so the reduction is a testable number (see its docstring for the
exact pass tables), analogously to ``planner.plan_wire_bytes``.

Numerics / parity notes (pinned by tests/test_fused_norm.py):

* All kernel arithmetic is float32 regardless of the activation dtype
  (free on the VPU).  flax's ``nn.BatchNorm`` instead *rounds the
  normalize chain to the promoted dtype* (bf16 when ``dtype=bf16``), so
  parity with flax is exact op-order in float32 and within bf16-ulp
  tolerance otherwise — the fused path is the numerically tighter one.
* Variance is the fast form mean(x^2) - mean(x)^2 clamped at 0, exactly
  as flax computes it.
* The backward is a ``jax.custom_vjp`` whose boundary encloses the
  statistics, so train-mode dx includes the full stats-gradient terms:
  dx = γ·invstd·(dz − Σdz/R − x̂·Σ(dz·x̂)/R).  The ReLU mask is
  recomputed in-kernel from x̂·γ+β (nothing extra is stored).  The
  cotangents of the returned batch mean/var are ignored and the
  gradients w.r.t. *running* stats are zero — matching flax, where
  running-stat updates are variable writes outside autodiff.

Kernels run in ``interpret=True`` on non-TPU backends so the CPU test
mesh exercises the identical kernel bodies (same pattern as
``ops.flash_attention``).
"""

from __future__ import annotations

import functools
from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

import flax.linen as nn

from chainermn_tpu.ops.flash_attention import _scratch, _shape_like, _VMEM

__all__ = [
    "fused_norm",
    "fused_norm_reference",
    "FusedBatchNormAct",
    "fused_norm_traffic_bytes",
    "resnet_bn_traffic_bytes",
]


# ---------------------------------------------------------------------------
# kernels: x is viewed as [R, C] (rows = every non-feature element), the grid
# streams row-tiles, and per-channel vectors ride as [1, C] blocks.
# ---------------------------------------------------------------------------


def _stats_kernel(x_ref, sum_ref, sq_ref, s_sum, s_sq):
    """Pass 1 (train fwd): accumulate Σx and Σx² per channel across tiles."""
    i = pl.program_id(0)

    @pl.when(i == 0)
    def _init():
        s_sum[...] = jnp.zeros_like(s_sum)
        s_sq[...] = jnp.zeros_like(s_sq)

    x = x_ref[...].astype(jnp.float32)
    s_sum[...] += jnp.sum(x, axis=0, keepdims=True)
    s_sq[...] += jnp.sum(x * x, axis=0, keepdims=True)

    @pl.when(i == pl.num_programs(0) - 1)
    def _done():
        sum_ref[...] = s_sum[...]
        sq_ref[...] = s_sq[...]


def _apply_kernel(x_ref, mean_ref, invstd_ref, scale_ref, bias_ref, y_ref, *,
                  relu):
    """Pass 2 (fwd): y = relu?((x − μ)·(invstd·γ) + β), flax op order."""
    x = x_ref[...].astype(jnp.float32)
    mul = invstd_ref[...] * scale_ref[...]
    y = (x - mean_ref[...]) * mul + bias_ref[...]
    if relu:
        y = jnp.maximum(y, 0.0)
    y_ref[...] = y.astype(y_ref.dtype)


def _dz_xhat(x_ref, g_ref, mean_ref, invstd_ref, scale_ref, bias_ref, relu):
    """Shared bwd prologue: recompute x̂ and the masked upstream grad dz."""
    x = x_ref[...].astype(jnp.float32)
    g = g_ref[...].astype(jnp.float32)
    xhat = (x - mean_ref[...]) * invstd_ref[...]
    if relu:
        z = xhat * scale_ref[...] + bias_ref[...]
        g = jnp.where(z > 0.0, g, 0.0)
    return g, xhat


def _bwd_reduce_kernel(x_ref, g_ref, mean_ref, invstd_ref, scale_ref,
                       bias_ref, dbeta_ref, dgamma_ref, s_db, s_dg, *, relu):
    """Bwd pass 1: dβ = Σdz and dγ = Σdz·x̂, fused into one traversal."""
    i = pl.program_id(0)

    @pl.when(i == 0)
    def _init():
        s_db[...] = jnp.zeros_like(s_db)
        s_dg[...] = jnp.zeros_like(s_dg)

    dz, xhat = _dz_xhat(x_ref, g_ref, mean_ref, invstd_ref, scale_ref,
                        bias_ref, relu)
    s_db[...] += jnp.sum(dz, axis=0, keepdims=True)
    s_dg[...] += jnp.sum(dz * xhat, axis=0, keepdims=True)

    @pl.when(i == pl.num_programs(0) - 1)
    def _done():
        dbeta_ref[...] = s_db[...]
        dgamma_ref[...] = s_dg[...]


def _bwd_dx_kernel(x_ref, g_ref, mean_ref, invstd_ref, scale_ref, bias_ref,
                   dbeta_ref, dgamma_ref, dx_ref, *, relu, train, inv_rows):
    """Bwd pass 2: dx, with the stats-gradient terms folded in (train)."""
    dz, xhat = _dz_xhat(x_ref, g_ref, mean_ref, invstd_ref, scale_ref,
                        bias_ref, relu)
    k = scale_ref[...] * invstd_ref[...]
    if train:
        dx = k * (dz - dbeta_ref[...] * inv_rows
                  - xhat * (dgamma_ref[...] * inv_rows))
    else:
        dx = k * dz
    dx_ref[...] = dx.astype(dx_ref.dtype)


# ---------------------------------------------------------------------------
# pallas_call wrappers
# ---------------------------------------------------------------------------


def _specs(br, c, n_vecs, kw):
    """x-tile spec followed by ``n_vecs`` per-channel [1, C] vector specs."""
    row = pl.BlockSpec((br, c), lambda i: (i, 0), **kw)
    vec = pl.BlockSpec((1, c), lambda i: (0, 0), **kw)
    return row, [vec] * n_vecs


def _stats_call(x2, block_rows, interpret):
    r, c = x2.shape
    kw = {} if _VMEM is None else {"memory_space": _VMEM}
    row, _ = _specs(block_rows, c, 0, kw)
    vec_out = pl.BlockSpec((1, c), lambda i: (0, 0), **kw)
    s_sum, s_sq = pl.pallas_call(
        _stats_kernel,
        grid=(r // block_rows,),
        in_specs=[row],
        out_specs=[vec_out, vec_out],
        out_shape=[_shape_like(x2, (1, c), jnp.float32),
                   _shape_like(x2, (1, c), jnp.float32)],
        scratch_shapes=_scratch([((1, c), jnp.float32),
                                 ((1, c), jnp.float32)]),
        interpret=interpret,
    )(x2)
    mean = s_sum / r
    var = jnp.maximum(s_sq / r - mean * mean, 0.0)  # fast variance, as flax
    return mean, var


def _apply_call(x2, mean, invstd, scale, bias, relu, block_rows, interpret):
    r, c = x2.shape
    kw = {} if _VMEM is None else {"memory_space": _VMEM}
    row, vecs = _specs(block_rows, c, 4, kw)
    return pl.pallas_call(
        functools.partial(_apply_kernel, relu=relu),
        grid=(r // block_rows,),
        in_specs=[row] + vecs,
        out_specs=row,
        out_shape=_shape_like(x2, (r, c), x2.dtype),
        interpret=interpret,
    )(x2, mean, invstd, scale, bias)


def _bwd_reduce_call(x2, g2, mean, invstd, scale, bias, relu, block_rows,
                     interpret):
    r, c = x2.shape
    kw = {} if _VMEM is None else {"memory_space": _VMEM}
    row, vecs = _specs(block_rows, c, 4, kw)
    vec_out = pl.BlockSpec((1, c), lambda i: (0, 0), **kw)
    return pl.pallas_call(
        functools.partial(_bwd_reduce_kernel, relu=relu),
        grid=(r // block_rows,),
        in_specs=[row, row] + vecs,
        out_specs=[vec_out, vec_out],
        out_shape=[_shape_like(x2, (1, c), jnp.float32),
                   _shape_like(x2, (1, c), jnp.float32)],
        scratch_shapes=_scratch([((1, c), jnp.float32),
                                 ((1, c), jnp.float32)]),
        interpret=interpret,
    )(x2, g2, mean, invstd, scale, bias)


def _bwd_dx_call(x2, g2, mean, invstd, scale, bias, dbeta, dgamma, relu,
                 train, block_rows, interpret):
    r, c = x2.shape
    kw = {} if _VMEM is None else {"memory_space": _VMEM}
    row, vecs = _specs(block_rows, c, 6, kw)
    return pl.pallas_call(
        functools.partial(_bwd_dx_kernel, relu=relu, train=train,
                          inv_rows=1.0 / r),
        grid=(r // block_rows,),
        in_specs=[row, row] + vecs,
        out_specs=row,
        out_shape=_shape_like(x2, (r, c), x2.dtype),
        interpret=interpret,
    )(x2, g2, mean, invstd, scale, bias, dbeta, dgamma)


# ---------------------------------------------------------------------------
# custom-VJP core over the flattened [R, C] view
# ---------------------------------------------------------------------------


@functools.partial(jax.custom_vjp, nondiff_argnums=(5, 6, 7, 8, 9))
def _fused_core(x2, scale, bias, mean_in, var_in, train, eps, relu,
                block_rows, interpret):
    (y2, mean, var), _ = _fused_core_fwd(x2, scale, bias, mean_in, var_in,
                                         train, eps, relu, block_rows,
                                         interpret)
    return y2, mean, var


def _fused_core_fwd(x2, scale, bias, mean_in, var_in, train, eps, relu,
                    block_rows, interpret):
    if train:
        mean, var = _stats_call(x2, block_rows, interpret)
    else:
        mean, var = mean_in, var_in
    invstd = jax.lax.rsqrt(var + eps)
    y2 = _apply_call(x2, mean, invstd, scale, bias, relu, block_rows,
                     interpret)
    return (y2, mean, var), (x2, scale, bias, mean, invstd)


def _fused_core_bwd(train, eps, relu, block_rows, interpret, res, cts):
    # mean/var cotangents are dropped: running-stat updates sit outside
    # autodiff (flax variable writes), so nothing real flows through them.
    gy2, _, _ = cts
    x2, scale, bias, mean, invstd = res
    dbeta, dgamma = _bwd_reduce_call(x2, gy2, mean, invstd, scale, bias,
                                     relu, block_rows, interpret)
    dx2 = _bwd_dx_call(x2, gy2, mean, invstd, scale, bias, dbeta, dgamma,
                       relu, train, block_rows, interpret)
    return (dx2, dgamma.astype(scale.dtype), dbeta.astype(bias.dtype),
            jnp.zeros_like(mean), jnp.zeros_like(invstd))


_fused_core.defvjp(_fused_core_fwd, _fused_core_bwd)


# ---------------------------------------------------------------------------
# public op
# ---------------------------------------------------------------------------


def _pick_block_rows(r, c):
    """Largest power-of-two row tile that divides R and keeps an f32 tile
    within ~1 MiB of VMEM (auto-halving, like flash_attention's defaults)."""
    budget = max(1, (1 << 20) // max(1, c * 4))
    br = 1
    while br * 2 <= min(budget, r):
        br *= 2
    while r % br:
        br //= 2
    return max(br, 1)


def fused_norm(x, scale, bias, mean=None, var=None, *,
               use_running_average=False, epsilon=1e-5, relu=True,
               block_rows=None, interpret=None):
    """Fused BatchNorm(+ReLU) over the last axis of ``x``.

    Returns ``(y, mean, var)`` where mean/var are the per-channel batch
    statistics actually used (in eval mode, the running stats passed in).
    Differentiable via a custom VJP whose backward fuses the dγ/dβ
    reductions with dx (two activation traversals total).

    Args:
      x: activations ``[..., C]`` (any rank; features last).
      scale, bias: per-channel ``[C]`` affine parameters (γ, β).
      mean, var: running statistics ``[C]`` — required when
        ``use_running_average=True``, ignored otherwise.
      use_running_average: eval mode — normalize with ``mean``/``var``
        instead of batch statistics.
      epsilon: added to variance before the rsqrt.
      relu: fuse ``max(y, 0)`` into the same traversal.
      block_rows: row-tile size (must divide the flattened row count);
        ``None`` auto-sizes to ~1 MiB f32 tiles.
      interpret: force Pallas interpret mode; ``None`` auto-selects
        (interpret off TPU).
    """
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    c = x.shape[-1]
    r = x.size // c
    if r == 0:
        raise ValueError(f"fused_norm: empty activation batch for {x.shape}")
    if block_rows is None:
        block_rows = _pick_block_rows(r, c)
    elif r % block_rows:
        raise ValueError(
            f"block_rows={block_rows} must divide row count {r} "
            f"(x.shape={x.shape})")
    x2 = x.reshape(r, c)
    s2 = jnp.asarray(scale, jnp.float32).reshape(1, c)
    b2 = jnp.asarray(bias, jnp.float32).reshape(1, c)
    if use_running_average:
        if mean is None or var is None:
            raise ValueError(
                "fused_norm(use_running_average=True) needs mean= and var=")
        m2 = jnp.asarray(mean, jnp.float32).reshape(1, c)
        v2 = jnp.asarray(var, jnp.float32).reshape(1, c)
    else:
        # placeholders; train mode computes batch stats inside the VJP
        # boundary (they are dead inputs, kept for a stable signature).
        m2 = jnp.zeros((1, c), jnp.float32)
        v2 = jnp.ones((1, c), jnp.float32)
    y2, m, v = _fused_core(x2, s2, b2, m2, v2, not use_running_average,
                           float(epsilon), bool(relu), int(block_rows),
                           bool(interpret))
    return y2.reshape(x.shape), m.reshape(c), v.reshape(c)


def fused_norm_reference(x, scale, bias, mean=None, var=None, *,
                         use_running_average=False, epsilon=1e-5, relu=True):
    """Pure-XLA oracle with the kernels' exact math (f32, fast variance,
    flax op order) — the gradient-parity reference for the custom VJP."""
    c = x.shape[-1]
    x2 = x.reshape(-1, c).astype(jnp.float32)
    if use_running_average:
        m = jnp.asarray(mean, jnp.float32)
        v = jnp.asarray(var, jnp.float32)
    else:
        m = jnp.mean(x2, axis=0)
        v = jnp.maximum(jnp.mean(x2 * x2, axis=0) - m * m, 0.0)
    mul = jax.lax.rsqrt(v + epsilon) * jnp.asarray(scale, jnp.float32)
    y = (x2 - m) * mul + jnp.asarray(bias, jnp.float32)
    if relu:
        y = jnp.maximum(y, 0.0)
    return y.reshape(x.shape).astype(x.dtype), m, v


# ---------------------------------------------------------------------------
# flax module: drop-in for nn.BatchNorm at the resnet norm_cls seam
# ---------------------------------------------------------------------------


class FusedBatchNormAct(nn.Module):
    """``nn.BatchNorm``-compatible module backed by the fused kernels.

    Identical parameter/stat tree to ``nn.BatchNorm`` (params ``scale``/
    ``bias`` in ``param_dtype``; float32 ``batch_stats`` ``mean``/``var``
    with the same momentum update), so checkpoints and the resnet
    ``norm_cls`` seam swap over without surgery.  ``fuse_relu=True``
    folds the activation into the same kernel traversal; the resnet
    blocks request it through the ``supports_fused_relu`` marker.
    """

    use_running_average: Optional[bool] = None
    momentum: float = 0.99
    epsilon: float = 1e-5
    dtype: Optional[Any] = None
    param_dtype: Any = jnp.float32
    use_bias: bool = True
    use_scale: bool = True
    bias_init: Callable = nn.initializers.zeros_init()
    scale_init: Callable = nn.initializers.ones_init()
    fuse_relu: bool = False
    block_rows: Optional[int] = None

    supports_fused_relu = True  # inspected by models.resnet (class attr,
    #                             not a dataclass field: no annotation)

    @nn.compact
    def __call__(self, x, use_running_average: Optional[bool] = None):
        use_ra = nn.merge_param("use_running_average",
                                self.use_running_average, use_running_average)
        feat = (x.shape[-1],)
        ra_mean = self.variable("batch_stats", "mean",
                                lambda s: jnp.zeros(s, jnp.float32), feat)
        ra_var = self.variable("batch_stats", "var",
                               lambda s: jnp.ones(s, jnp.float32), feat)
        scale = (self.param("scale", self.scale_init, feat, self.param_dtype)
                 if self.use_scale else jnp.ones(feat, jnp.float32))
        bias = (self.param("bias", self.bias_init, feat, self.param_dtype)
                if self.use_bias else jnp.zeros(feat, jnp.float32))
        odt = self.dtype or jnp.promote_types(x.dtype, self.param_dtype)
        y, mean, var = fused_norm(
            jnp.asarray(x, odt), scale, bias,
            mean=ra_mean.value, var=ra_var.value,
            use_running_average=use_ra, epsilon=self.epsilon,
            relu=self.fuse_relu, block_rows=self.block_rows)
        if not use_ra and not self.is_initializing():
            m = self.momentum
            ra_mean.value = m * ra_mean.value + (1.0 - m) * mean
            ra_var.value = m * ra_var.value + (1.0 - m) * var
        return y


# ---------------------------------------------------------------------------
# traffic model (the gateable number)
# ---------------------------------------------------------------------------


def fused_norm_traffic_bytes(shape, dtype=jnp.bfloat16, *, train=True,
                             relu=True, backward=True):
    """Modeled HBM bytes for one BN(+ReLU) boundary, fused vs unfused.

    The model counts full-activation HBM traversals (reads and writes of
    ``prod(shape)`` elements at ``dtype`` width) plus the per-channel
    float32 vectors each pass touches.  The *fused* side prices exactly
    what the kernels in this module do.  The *unfused* side prices
    flax's ``nn.BatchNorm`` + separate ReLU at one traversal per logical
    op — the no-inter-op-fusion roofline, the same convention
    ``planner.plan_wire_bytes`` uses for ring hops.  XLA does fuse some
    adjacent elementwise ops in practice, so the modeled ratio bounds
    the achievable saving from above; the *measured* delta is
    ``bench_resnet_probe.py``'s job (committed as RESNET_PROBE_r09).

    Pass tables (train, relu, fwd+bwd; R·C activation elements):

      unfused fwd: mean 1R · var 1R · normalize 1R+1W · scale/shift
                   1R+1W · relu 1R+1W                       = 8 acts
      unfused bwd: relu-bwd 2R+1W · dβ 1R · dγ 2R · dx 2R+1W = 9 acts
      fused   fwd: stats 1R · apply 1R+1W                    = 3 acts
      fused   bwd: reduce 2R · dx 2R+1W                      = 5 acts

    17 vs 8 traversals → 2.1× fewer modeled bytes per relu'd boundary
    (pinned ≥2× by tests).  Without relu: 11 vs 8; eval mode drops the
    stat passes on both sides.

    Returns a dict with both pass tables, totals, and the ratio.
    """
    shape = tuple(int(s) for s in shape)
    c = shape[-1]
    n = 1
    for s in shape:
        n *= s
    act = n * jnp.dtype(dtype).itemsize
    vec = c * 4  # per-channel f32 vectors

    def _table(passes):
        total = sum(b for _, b in passes)
        return {"passes": [[name, int(b)] for name, b in passes],
                "total_bytes": int(total)}

    fused = []
    if train:
        fused.append(("fwd_stats", act + 2 * vec))
    fused.append(("fwd_apply", 2 * act + 4 * vec))
    if backward:
        fused.append(("bwd_reduce", 2 * act + 6 * vec))
        fused.append(("bwd_dx", 3 * act + 6 * vec))

    unfused = []
    if train:
        unfused.append(("fwd_mean", act + vec))
        unfused.append(("fwd_var", act + vec))
    unfused.append(("fwd_normalize", 2 * act + 2 * vec))
    unfused.append(("fwd_scale_shift", 2 * act + 2 * vec))
    if relu:
        unfused.append(("fwd_relu", 2 * act))
    if backward:
        if relu:
            unfused.append(("bwd_relu", 3 * act))
        unfused.append(("bwd_dbeta", act + vec))
        unfused.append(("bwd_dgamma", 2 * act + vec))
        unfused.append(("bwd_dx", 3 * act + 4 * vec))

    f, u = _table(fused), _table(unfused)
    return {
        "shape": list(shape),
        "dtype": str(jnp.dtype(dtype)),
        "train": bool(train),
        "relu": bool(relu),
        "backward": bool(backward),
        "activation_bytes": int(act),
        "fused": f,
        "unfused": u,
        "ratio": u["total_bytes"] / f["total_bytes"],
    }


def resnet_bn_traffic_bytes(batch, *, image=224, stage_sizes=(3, 4, 6, 3),
                            num_filters=64, dtype=jnp.bfloat16, train=True):
    """Sum ``fused_norm_traffic_bytes`` over every BN boundary of a
    bottleneck ResNet (the shapes ``models.resnet.ResNet50`` emits).

    Boundaries per bottleneck block: norm1 (+relu, input spatial), norm2
    (+relu, output spatial), norm3 (no relu — the activation lands after
    the residual add) and, on shape-changing blocks, the no-relu
    ``norm_proj``.  Plus the stem's BN+relu.  Returns fused/unfused
    totals, the ratio, and the per-boundary list — the
    ``resnet_bn_traffic_bytes`` perf-gate budget reads
    ``fused_total_bytes``.
    """
    boundaries = []  # (name, shape, relu)
    s = image // 2  # stem conv 7x7 stride 2
    boundaries.append(("stem/bn_init", (batch, s, s, num_filters), True))
    s = s // 2  # 3x3 maxpool stride 2
    for i, blocks in enumerate(stage_sizes):
        f = num_filters * (2 ** i)
        for j in range(blocks):
            stride = 2 if (i > 0 and j == 0) else 1
            s_in, s_out = s, s // stride
            tag = f"stage{i + 1}/block{j + 1}"
            boundaries.append((f"{tag}/norm1", (batch, s_in, s_in, f), True))
            boundaries.append((f"{tag}/norm2", (batch, s_out, s_out, f),
                               True))
            boundaries.append((f"{tag}/norm3",
                               (batch, s_out, s_out, 4 * f), False))
            if j == 0:  # channel (and possibly spatial) change: projection
                boundaries.append((f"{tag}/norm_proj",
                                   (batch, s_out, s_out, 4 * f), False))
            s = s_out
    rows, fused_total, unfused_total = [], 0, 0
    for name, shape, relu in boundaries:
        t = fused_norm_traffic_bytes(shape, dtype, train=train, relu=relu)
        fused_total += t["fused"]["total_bytes"]
        unfused_total += t["unfused"]["total_bytes"]
        rows.append({"name": name, "shape": list(shape), "relu": relu,
                     "fused_bytes": t["fused"]["total_bytes"],
                     "unfused_bytes": t["unfused"]["total_bytes"]})
    return {
        "batch": int(batch),
        "image": int(image),
        "stage_sizes": list(stage_sizes),
        "dtype": str(jnp.dtype(dtype)),
        "train": bool(train),
        "num_boundaries": len(rows),
        "fused_total_bytes": int(fused_total),
        "unfused_total_bytes": int(unfused_total),
        "ratio": unfused_total / fused_total,
        "boundaries": rows,
    }
