"""Fused cast + scale Pallas kernel.

Reference being rebuilt (SURVEY.md §2.3, path unverified): the runtime-
compiled ``cupy.ElementwiseKernel`` strings inside
〔chainermn/communicators/pure_nccl_communicator.py〕 that (a) cast fp32
gradients into the fp16 communication buffer before ``ncclAllReduce`` and
(b) scale by 1/size fused with the fp16 -> fp32 cast-back afterwards.

TPU-native version: one Pallas VPU kernel ``y = (x * scale).astype(dst)``
over the packed flat gradient buffer.  XLA usually fuses the equivalent
``astype``+``mul`` on its own; this kernel exists as the native-kernel parity
item and as the guaranteed-fused path when profiling shows XLA didn't fuse
(enable with ``XlaCommunicator(use_pallas_cast=True)``).

Runs in interpret mode off-TPU so the CPU test mesh exercises it.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from chainermn_tpu.utils import pvary, typeof

_LANE = 128
_BLOCK_ROWS = 256  # 256 x 128 f32 = 128 KiB per buffer; in+out fit VMEM easily


def _kernel(x_ref, s_ref, o_ref):
    # Compute in f32 so a half-precision source is scaled at full precision,
    # matching the reference's cast-then-scale kernel semantics.  The scale
    # arrives as a (1, 1) input (not a closure constant) so its varying-axes
    # metadata matches x's under shard_map interpret mode.
    v = x_ref[...].astype(jnp.float32)
    o_ref[...] = (v * s_ref[0, 0]).astype(o_ref.dtype)


@functools.partial(jax.jit, static_argnames=("target_dtype", "scale"))
def cast_scale(x: jnp.ndarray, target_dtype: Optional[jnp.dtype], scale: float):
    """Elementwise ``(x * scale).astype(target_dtype)`` as one fused kernel.

    ``x`` may be any shape; it is processed as a flat buffer (this is the
    packed-gradient path).  ``target_dtype=None`` keeps ``x.dtype``.
    """
    dst = jnp.dtype(target_dtype) if target_dtype is not None else x.dtype
    orig_shape = x.shape
    flat = x.reshape(-1)
    n = flat.shape[0]
    in_vma = getattr(typeof(flat), "vma", None)
    in_spmd = bool(in_vma)
    if in_vma is None:
        # pre-vma jax: no vma metadata to inspect — detect "inside a
        # shard_map/axis-bound trace" from the axis env instead (there is
        # no shard_map replication rule for pallas_call there either)
        try:
            from jax._src import core as _src_core
            in_spmd = bool(_src_core.get_axis_env().axis_sizes)
        except Exception:
            pass
    interpret = jax.default_backend() != "tpu"
    if interpret and in_spmd:
        # jax's HLO interpreter for pallas is not vma-aware (its internal
        # dynamic_slice mixes varying/invariant operands and trips
        # check_vma), so inside a shard_map off-TPU we emit the XLA-fused
        # equivalent instead; the kernel itself is exercised by direct
        # interpret-mode tests and runs for real on TPU.
        return (flat.astype(jnp.float32) * jnp.float32(scale)).astype(dst).reshape(orig_shape)

    def _zeros(k):
        z = jnp.zeros((k,), flat.dtype)
        if in_vma:
            # match the input's varying-axes set so concatenate is legal
            z = pvary(z, tuple(in_vma))
        return z

    rows = -(-n // _LANE)
    pad = rows * _LANE - n
    if pad:
        flat = jnp.concatenate([flat, _zeros(pad)])
    grid_rows = -(-rows // _BLOCK_ROWS)
    padded_rows = grid_rows * _BLOCK_ROWS
    if padded_rows != rows:
        flat = jnp.concatenate([flat, _zeros((padded_rows - rows) * _LANE)])
    x2 = flat.reshape(padded_rows, _LANE)
    s_arr = jnp.full((1, 1), scale, jnp.float32)
    # Under shard_map with vma-checking, the out aval must carry the same
    # varying-across-mesh-axes set as the input (a cast is rank-local), and
    # every kernel input must share it.
    vma = getattr(typeof(x2), "vma", None)
    if vma is not None:
        if vma:
            s_arr = pvary(s_arr, tuple(vma))
        out_sds = jax.ShapeDtypeStruct((padded_rows, _LANE), dst, vma=vma)
    else:
        out_sds = jax.ShapeDtypeStruct((padded_rows, _LANE), dst)
    out = pl.pallas_call(
        _kernel,
        out_shape=out_sds,
        grid=(grid_rows,),
        in_specs=[pl.BlockSpec((_BLOCK_ROWS, _LANE), lambda i: (i, 0)),
                  pl.BlockSpec((1, 1), lambda i: (0, 0))],
        out_specs=pl.BlockSpec((_BLOCK_ROWS, _LANE), lambda i: (i, 0)),
        interpret=jax.default_backend() != "tpu",
    )(x2, s_arr)
    return out.reshape(-1)[:n].reshape(orig_shape)
