from chainermn_tpu.ops.cast_scale import cast_scale

__all__ = ["cast_scale"]
