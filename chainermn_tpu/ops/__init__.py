"""Native/fused TPU kernels (Pallas) — the reference's CUDA-kernel role
(SURVEY.md §2.3)."""

from chainermn_tpu.ops.cast_scale import cast_scale
from chainermn_tpu.ops.flash_attention import flash_attention
from chainermn_tpu.ops.fused_norm import (
    FusedBatchNormAct,
    fused_norm,
    fused_norm_reference,
    fused_norm_traffic_bytes,
    resnet_bn_traffic_bytes,
)

__all__ = [
    "cast_scale",
    "flash_attention",
    "fused_norm",
    "fused_norm_reference",
    "FusedBatchNormAct",
    "fused_norm_traffic_bytes",
    "resnet_bn_traffic_bytes",
]
