"""Native/fused TPU kernels (Pallas) — the reference's CUDA-kernel role
(SURVEY.md §2.3)."""

from chainermn_tpu.ops.cast_scale import cast_scale
from chainermn_tpu.ops.flash_attention import flash_attention

__all__ = ["cast_scale", "flash_attention"]
