"""Fused attention forward — Pallas TPU kernel (flash-attention style).

**Beyond-reference native kernel** (the reference's native surface was
CUDA elementwise strings — SURVEY.md §2.3; this is the TPU analogue for
the attention hot op used by the sequence-parallel extension).

One `pallas_call` program per (batch*head, q-tile): the q tile lives in
VMEM, K/V for the whole (local) sequence stream through VMEM, and the
softmax is computed online (running max / denominator, never a full
[T, T] score matrix in HBM).  MXU does the two matmuls per K/V tile; the
online-softmax rescale rides the VPU.

Scope: per-shard sequence lengths where K/V fit VMEM (T*D*4B each —
thousands of positions at D=64..128), which is exactly the per-device
block regime of :func:`chainermn_tpu.parallel.sequence.ring_attention` /
``ulysses_attention`` (pass ``attn_fn=flash_attention``).

Differentiation: forward runs the fused kernel; backward is the standard
blockwise flash gradient (recompute softmax stats, then per-tile
dq/dk/dv accumulation) — the [T, T] matrix is materialized in NEITHER
direction, so training memory stays O(T * block) too.  Off-TPU the
kernel runs in Pallas interpret mode so the CPU test mesh exercises the
same code path.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:  # pltpu only imports on TPU-capable installs; interpret mode needs it not
    from jax.experimental.pallas import tpu as pltpu
    _VMEM = pltpu.VMEM
except Exception:  # pragma: no cover
    pltpu = None
    _VMEM = None

_BLOCK_Q = 256
_BLOCK_K = 256
_NEG_INF = -1e30


def _kernel(q_ref, k_ref, v_ref, o_ref, *, sm_scale, causal, block_k):
    # q_ref: [1, BQ, D]; k_ref/v_ref: [1, T, D]; o_ref: [1, BQ, D]
    # Keep matmul inputs in their storage dtype (bf16 rides the MXU at
    # full rate; f32 would quarter it) and accumulate in f32.
    q = q_ref[0]                                         # [BQ, D]
    t = k_ref.shape[1]
    bq = q.shape[0]
    q_off = pl.program_id(1) * bq

    def body(j, carry):
        acc, m, l = carry
        k = k_ref[0, pl.dslice(j * block_k, block_k), :]
        v = v_ref[0, pl.dslice(j * block_k, block_k), :]
        # scale after the matmul — same op order as the unfused reference,
        # so results match it to tight tolerance
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * sm_scale
        if causal:
            q_pos = q_off + jax.lax.broadcasted_iota(
                jnp.int32, (bq, block_k), 0)
            k_pos = j * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (bq, block_k), 1)
            s = jnp.where(q_pos >= k_pos, s, _NEG_INF)
        m_new = jnp.maximum(m, s.max(axis=1, keepdims=True))
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m - m_new)
        l_new = l * alpha + p.sum(axis=1, keepdims=True)
        acc_new = acc * alpha + jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        return acc_new, m_new, l_new

    n_k = t // block_k
    if causal:
        # K/V tiles strictly after this q tile's last row are fully masked;
        # skip them (upper bound depends on the q tile -> dynamic).
        n_k = jnp.minimum(n_k, (q_off + bq + block_k - 1) // block_k)
    d = q.shape[1]
    acc0 = jnp.zeros((bq, d), jnp.float32)
    m0 = jnp.full((bq, 1), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((bq, 1), jnp.float32)
    acc, m, l = jax.lax.fori_loop(0, n_k, body, (acc0, m0, l0))
    o_ref[0] = (acc / jnp.where(l == 0.0, 1.0, l)).astype(o_ref.dtype)


def _forward(q, k, v, causal, sm_scale, block_q, block_k, interpret):
    b, t, h, d = q.shape
    scale = sm_scale if sm_scale is not None else d ** -0.5
    bq = min(block_q, t)
    bk = min(block_k, t)
    if t % bq or t % bk:
        raise ValueError(
            f"flash_attention needs seq len ({t}) divisible by its tiles "
            f"({bq}, {bk}); pad the sequence or pass smaller block sizes")
    # [B, T, H, D] -> [B*H, T, D]
    def fold(x):
        return x.transpose(0, 2, 1, 3).reshape(b * h, t, d)
    qf, kf, vf = fold(q), fold(k), fold(v)

    kern = functools.partial(_kernel, sm_scale=scale, causal=causal,
                             block_k=bk)
    kw = {} if _VMEM is None else {"memory_space": _VMEM}
    # Inside shard_map the output must carry the inputs' varying-axes
    # metadata (vma) so the kernel composes with sequence parallelism.
    try:
        out_shape = jax.ShapeDtypeStruct((b * h, t, d), q.dtype,
                                         vma=jax.typeof(qf).vma)
    except (AttributeError, TypeError):
        out_shape = jax.ShapeDtypeStruct((b * h, t, d), q.dtype)
    out = pl.pallas_call(
        kern,
        grid=(b * h, t // bq),
        in_specs=[
            pl.BlockSpec((1, bq, d), lambda i, j: (i, j, 0), **kw),
            pl.BlockSpec((1, t, d), lambda i, j: (i, 0, 0), **kw),
            pl.BlockSpec((1, t, d), lambda i, j: (i, 0, 0), **kw),
        ],
        out_specs=pl.BlockSpec((1, bq, d), lambda i, j: (i, j, 0), **kw),
        out_shape=out_shape,
        interpret=interpret,
    )(qf, kf, vf)
    return out.reshape(b, h, t, d).transpose(0, 2, 1, 3)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def flash_attention(q, k, v, causal: bool = False,
                    sm_scale: Optional[float] = None,
                    block_q: int = _BLOCK_Q, block_k: int = _BLOCK_K):
    """Fused softmax attention: [B, T, H, D] q/k/v -> [B, T, H, D].

    Drop-in for :func:`chainermn_tpu.parallel.sequence.attention` (same
    signature minus offsets); pass as ``attn_fn=`` to
    ``ulysses_attention`` for a fused inner kernel.  ``block_q``/
    ``block_k`` tune the tile sizes (sequence length must be a multiple
    of each, or fit a single tile).
    """
    interpret = jax.default_backend() != "tpu"
    return _forward(q, k, v, causal, sm_scale, block_q, block_k, interpret)


def _fwd(q, k, v, causal, sm_scale, block_q, block_k):
    out = flash_attention(q, k, v, causal, sm_scale, block_q, block_k)
    return out, (q, k, v, out)


def _bwd(causal, sm_scale, block_q, block_k, res, g):
    """Blockwise flash backward — the [T, T] score matrix is never
    materialized in the backward either.

    Standard flash-attention gradient algebra, tile by tile (j over K/V
    tiles): recompute ``s_ij``/``p_ij`` from the saved q/k and the
    softmax stats, then

        dv_j  = p_ij^T @ dO_i
        dp_ij = dO_i @ v_j^T
        ds_ij = p_ij * (dp_ij - D_i) * scale,  D_i = rowsum(dO_i * O_i)
        dq_i += ds_ij @ k_j ;  dk_j = ds_ij^T @ q_i

    The softmax stats (m, l) are recomputed with one extra blockwise pass
    (primal math only — no autodiff residuals), keeping peak memory at
    O(T * block_k) per (batch, head) in both passes.
    """
    q, k, v, out = res
    b, t, h, d = q.shape
    scale = sm_scale if sm_scale is not None else d ** -0.5
    bk = min(block_k, t)
    if t % bk:
        raise ValueError(f"sequence length {t} not divisible by block_k {bk}")
    n = t // bk
    # [B, T, H, D] -> [B, H, T, D] f32 working layout
    tr = lambda x: x.transpose(0, 2, 1, 3).astype(jnp.float32)
    qT, kT, vT, oT, gT = tr(q), tr(k), tr(v), tr(out), tr(g)
    q_pos = jnp.arange(t)

    def stats_fold(carry, j):
        m, l = carry
        kb = jax.lax.dynamic_slice_in_dim(kT, j * bk, bk, axis=2)
        s = jnp.einsum("bhtd,bhsd->bhts", qT, kb,
                       preferred_element_type=jnp.float32) * scale
        if causal:
            mask = q_pos[:, None] >= (j * bk + jnp.arange(bk))[None, :]
            s = jnp.where(mask[None, None], s, _NEG_INF)
        m_new = jnp.maximum(m, s.max(-1))
        l_new = l * jnp.exp(m - m_new) + jnp.exp(
            s - m_new[..., None]).sum(-1)
        return (m_new, l_new), None

    m0 = jnp.full((b, h, t), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((b, h, t), jnp.float32)
    (m, l), _ = jax.lax.scan(stats_fold, (m0, l0), jnp.arange(n))
    l = jnp.where(l == 0.0, 1.0, l)
    D = (gT * oT).sum(-1)                                  # [B, H, T]

    def grad_fold(dq, j):
        kb = jax.lax.dynamic_slice_in_dim(kT, j * bk, bk, axis=2)
        vb = jax.lax.dynamic_slice_in_dim(vT, j * bk, bk, axis=2)
        s = jnp.einsum("bhtd,bhsd->bhts", qT, kb,
                       preferred_element_type=jnp.float32) * scale
        if causal:
            mask = q_pos[:, None] >= (j * bk + jnp.arange(bk))[None, :]
            s = jnp.where(mask[None, None], s, _NEG_INF)
        p = jnp.exp(s - m[..., None]) / l[..., None]       # [B, H, T, bk]
        dv_j = jnp.einsum("bhts,bhtd->bhsd", p, gT)
        dp = jnp.einsum("bhtd,bhsd->bhts", gT, vb)
        ds = p * (dp - D[..., None]) * scale
        dq = dq + jnp.einsum("bhts,bhsd->bhtd", ds, kb)
        dk_j = jnp.einsum("bhts,bhtd->bhsd", ds, qT)
        return dq, (dk_j, dv_j)

    dq0 = jnp.zeros_like(qT)
    dq, (dk_tiles, dv_tiles) = jax.lax.scan(grad_fold, dq0, jnp.arange(n))
    # [n, B, H, bk, D] -> [B, H, T, D]
    merge = lambda tiles: tiles.transpose(1, 2, 0, 3, 4).reshape(b, h, t, d)
    back = lambda x, ref: x.transpose(0, 2, 1, 3).astype(ref.dtype)
    return (back(dq, q), back(merge(dk_tiles), k), back(merge(dv_tiles), v))


flash_attention.defvjp(_fwd, _bwd)

__all__ = ["flash_attention"]
