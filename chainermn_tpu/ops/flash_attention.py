"""Fused attention — Pallas TPU kernels, forward AND backward.

**Beyond-reference native kernel** (the reference's native surface was
CUDA elementwise strings — SURVEY.md §2.3; this is the TPU analogue for
the attention hot op used by the sequence-parallel extension).

Forward: K/V-STREAMING grid (round 3) — grid (batch*head, q-tile,
k-tile): the q tile and the online-softmax accumulators (acc, running
max, denominator) live in VMEM scratch across the k-tile grid steps,
while each K/V TILE is fetched by the Pallas pipeline per step.  VMEM
residency is O(block) rather than O(T), which lifts the previous
full-sequence-resident bound (~T=12k at D=128) to HBM capacity; the
pipelined tile fetches overlap the MXU matmuls.  The softmax is online
(never a full [T, T] score matrix anywhere); the per-row logsumexp is
written out as a residual so the backward never re-derives it.

Backward: two streaming Pallas kernels in the standard flash-gradient
shape — grid (bh, k-tile, q-tile) accumulating (dk, dv) in scratch
while q/dO/lse/delta tiles stream, and grid (bh, q-tile, k-tile)
accumulating dq while K/V tiles stream — each recomputing its score
tile from q/k and the saved logsumexp, so the [T, T] matrix is
materialized in NEITHER direction and VMEM stays O(block) end to end.
A pure-XLA blockwise backward with identical math is kept
(``bwd_impl="blockwise"``) as the cross-check oracle for the
gradient-parity tests.

Masking and dropout:

* ``causal`` — lower-triangular mask; fully-masked K/V tiles are
  skipped (forward) / never visited (backward).
* ``q_segment_ids``/``kv_segment_ids`` ([B, T] int32) — attention is
  allowed only where the ids match, which expresses packed-sequence and
  padding masks (give padding a sentinel id that matches nothing).
  Fully-masked rows produce zero output and zero gradients.
* ``dropout_rate``/``dropout_seed`` — attention-weight dropout applied
  after normalization with inverted scaling (kept weights / keep_p).
  The mask is a counter-based hash of (seed, batch*head, q_pos, k_pos)
  computed identically in forward, backward, and the blockwise oracle —
  nothing random is stored, so the recompute-based backward stays exact.

Scope: per-shard sequence lengths where K/V fit VMEM (T*D*2B each —
thousands of positions at D=64..128), which is exactly the per-device
block regime of :func:`chainermn_tpu.parallel.sequence.ring_attention` /
``ulysses_attention`` (pass ``attn_fn=flash_attention``).  Off-TPU the
kernels run in Pallas interpret mode so the CPU test mesh exercises the
same code path.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:  # pltpu only imports on TPU-capable installs; interpret mode needs it not
    from jax.experimental.pallas import tpu as pltpu
    _VMEM = pltpu.VMEM
except Exception:  # pragma: no cover
    pltpu = None
    _VMEM = None

_BLOCK_Q = 1024  # measured optimum on v5e (benchmarks: 81 TFLOP/s fwd at
_BLOCK_K = 1024  # T=8k vs 24 at 256/256 — per-grid-step overhead amortizes)
_NEG_INF = -1e30
_LSE_SENTINEL = 1e30  # lse for fully-masked rows: exp(s - sentinel) == 0


def _keep_mask(seed_u32, bh_idx, q_pos, k_pos, rate):
    """Deterministic dropout keep-mask from a counter-based hash.

    ``q_pos``/``k_pos`` are GLOBAL positions (broadcastable int32
    arrays), so forward and backward — which tile the [T, T] plane
    differently — reproduce the identical mask.  Murmur3-finalizer
    rounds give well-mixed bits from pure uint32 VPU arithmetic (no
    stateful PRNG, works under both compiled and interpret modes).
    """
    x = (q_pos.astype(jnp.uint32) * jnp.uint32(0x9E3779B1)
         ^ k_pos.astype(jnp.uint32) * jnp.uint32(0x85EBCA77)
         ^ (bh_idx.astype(jnp.uint32) if hasattr(bh_idx, "astype")
            else jnp.uint32(bh_idx)) * jnp.uint32(0xC2B2AE35)
         ^ seed_u32)
    x = x ^ (x >> 16)
    x = x * jnp.uint32(0x7FEB352D)
    x = x ^ (x >> 15)
    x = x * jnp.uint32(0x846CA68B)
    x = x ^ (x >> 16)
    thresh = min(int(rate * 2 ** 32), 2 ** 32 - 1)
    return x >= jnp.uint32(thresh)


def _shape_like(template, shape, dtype):
    """ShapeDtypeStruct carrying ``template``'s varying-axes (vma) metadata
    when the JAX version supports it — needed for shard_map composition."""
    try:
        return jax.ShapeDtypeStruct(shape, dtype, vma=jax.typeof(template).vma)
    except (AttributeError, TypeError):
        return jax.ShapeDtypeStruct(shape, dtype)


def _unpack_rest(rest, has_seg, dropout_rate, has_offsets=False):
    """Split a kernel's trailing refs into (qseg, kseg, seed, offs, outputs)
    — shared by all three kernels so the optional-input threading lives
    once."""
    idx = 0
    qseg_ref = kseg_ref = seed_ref = offs_ref = None
    if has_seg:
        qseg_ref, kseg_ref = rest[0], rest[1]
        idx = 2
    if dropout_rate > 0.0:
        seed_ref = rest[idx]
        idx += 1
    if has_offsets:
        offs_ref = rest[idx]
        idx += 1
    return qseg_ref, kseg_ref, seed_ref, offs_ref, rest[idx:]


def _mask_tile(causal, q_pos, k_pos, seg_q, seg_k):
    """[bq, bk] bool allow-mask (or None when nothing masks)."""
    mask = None
    if causal:
        mask = q_pos >= k_pos
    if seg_q is not None:
        m2 = seg_q[:, None] == seg_k[None, :]
        mask = m2 if mask is None else (mask & m2)
    return mask


# ---------------------------------------------------------------------------
# forward kernel
# ---------------------------------------------------------------------------

def _fwd_kernel(q_ref, k_ref, v_ref, *rest, sm_scale, causal,
                has_seg, dropout_rate, has_offsets):
    # Streaming grid (bh, q-tile, k-tile): q_ref [1, BQ, D] (fixed per
    # (bh, j)); k_ref/v_ref [1, BK, D] = THIS grid step's tile; optional
    # qseg [1, 1, BQ], kseg [1, 1, BK], seed [1, 1], offs [1, 2]; outputs
    # o [1, BQ, D], lse [1, 1, BQ] (written at the last k step); scratch
    # acc [BQ, D], m [BQ, 1], l [BQ, 1] persist across the k dimension.
    qseg_ref, kseg_ref, seed_ref, offs_ref, rest = _unpack_rest(
        rest, has_seg, dropout_rate, has_offsets)
    o_ref, lse_ref, acc_s, m_s, l_s = rest

    q = q_ref[0]                                         # [BQ, D]
    k = k_ref[0]                                         # [BK, D]
    v = v_ref[0]
    bq, d = q.shape
    bk = k.shape[0]
    kk = pl.program_id(2)
    n_k = pl.num_programs(2)
    q_off = pl.program_id(1) * bq
    k_off = kk * bk
    bh_idx = pl.program_id(0)
    seed = seed_ref[0, 0].astype(jnp.uint32) if seed_ref is not None else None
    # global position offsets (ring-attention blocks of a longer sequence)
    goff_q = offs_ref[0, 0] if has_offsets else 0
    goff_k = offs_ref[0, 1] if has_offsets else 0

    @pl.when(kk == 0)
    def _init():
        acc_s[...] = jnp.zeros_like(acc_s)
        m_s[...] = jnp.full_like(m_s, _NEG_INF)
        l_s[...] = jnp.zeros_like(l_s)

    # causal full-tile skip: tile contributes only if some q row can see
    # its first k row (the fetch still pipelines; the MXU work is skipped)
    run = ((goff_q + q_off + bq - 1 >= goff_k + k_off)
           if causal else (kk >= 0))

    @pl.when(run)
    def _tile():
        q_pos = goff_q + q_off + jax.lax.broadcasted_iota(
            jnp.int32, (bq, bk), 0)
        k_pos = goff_k + k_off + jax.lax.broadcasted_iota(
            jnp.int32, (bq, bk), 1)
        # scale after the matmul — same op order as the unfused reference,
        # so results match it to tight tolerance
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * sm_scale
        seg_q = qseg_ref[0, 0] if has_seg else None
        seg_k = kseg_ref[0, 0] if has_seg else None
        mask = _mask_tile(causal, q_pos, k_pos, seg_q, seg_k)
        if mask is not None:
            s = jnp.where(mask, s, _NEG_INF)
        m = m_s[...]
        l = l_s[...]
        m_new = jnp.maximum(m, s.max(axis=1, keepdims=True))
        p = jnp.exp(s - m_new)
        if mask is not None:
            # when a whole row of the tile is masked, s - m_new == 0 and
            # exp would give 1 — zero the masked entries explicitly
            p = jnp.where(mask, p, 0.0)
        alpha = jnp.exp(m - m_new)
        l_s[...] = l * alpha + p.sum(axis=1, keepdims=True)
        m_s[...] = m_new
        if dropout_rate > 0.0:
            keep = _keep_mask(seed, bh_idx, q_pos, k_pos, dropout_rate)
            p_use = jnp.where(keep, p * (1.0 / (1.0 - dropout_rate)), 0.0)
        else:
            p_use = p
        acc_s[...] = acc_s[...] * alpha + jax.lax.dot_general(
            p_use.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(kk == n_k - 1)
    def _finish():
        l = l_s[...]
        empty = l == 0.0
        o_ref[0] = (acc_s[...] / jnp.where(empty, 1.0, l)).astype(
            o_ref.dtype)
        lse = jnp.where(empty[:, 0], _LSE_SENTINEL,
                        m_s[...][:, 0] + jnp.log(
                            jnp.where(empty[:, 0], 1.0, l[:, 0])))
        lse_ref[0, 0] = lse.astype(jnp.float32)


def _scratch(shapes_dtypes):
    """VMEM scratch allocations — the accumulators that persist across the
    streaming grid dimension (interpret mode allocates them as arrays).
    Installs without pltpu (pure-CPU jax) fall back to the memory-space-
    agnostic MemoryRef, which the interpreter accepts."""
    if pltpu is not None:
        return [pltpu.VMEM(s, dt) for s, dt in shapes_dtypes]
    return [pl.MemoryRef(jax.core.ShapedArray(s, dt), pl.ANY)
            for s, dt in shapes_dtypes]


def _forward(q, k, v, qseg, kseg, seed, offs, causal, sm_scale, block_q,
             block_k, dropout_rate, interpret):
    b, tq, h, d = q.shape
    tk, hk = k.shape[1], k.shape[2]
    grp = h // hk  # q heads per kv head (1 = MHA; >1 = GQA/MQA)
    scale = sm_scale if sm_scale is not None else d ** -0.5
    bq = min(block_q, tq)
    bk = min(block_k, tk)
    if tq % bq or tk % bk:
        raise ValueError(
            f"flash_attention needs seq lens ({tq}, {tk}) divisible by "
            f"their tiles ({bq}, {bk}); pad the sequence or pass smaller "
            f"block sizes")
    # [B, T, H, D] -> [B*H, T, D]
    def fold(x):
        return x.transpose(0, 2, 1, 3).reshape(-1, x.shape[1], d)
    qf, kf, vf = fold(q), fold(k), fold(v)
    # grid dim 0 iterates q heads (b*h programs); a kv tensor row for
    # program i is its (batch, kv-head) pair
    kv_row = lambda i: (i // h) * hk + (i % h) // grp
    has_seg = qseg is not None
    has_offsets = offs is not None

    kern = functools.partial(_fwd_kernel, sm_scale=scale, causal=causal,
                             has_seg=has_seg, dropout_rate=dropout_rate,
                             has_offsets=has_offsets)
    kw = {} if _VMEM is None else {"memory_space": _VMEM}
    ins = [qf, kf, vf]
    in_specs = [
        pl.BlockSpec((1, bq, d), lambda i, j, kk: (i, j, 0), **kw),
        pl.BlockSpec((1, bk, d), lambda i, j, kk: (kv_row(i), kk, 0), **kw),
        pl.BlockSpec((1, bk, d), lambda i, j, kk: (kv_row(i), kk, 0), **kw),
    ]
    if has_seg:
        # segment ids are per-batch; heads share them (index map i // h).
        # TPU tiling wants the last two block dims divisible by (8, 128) or
        # equal to the array dims — a singleton row dim satisfies that, so
        # host-side vectors ride as [*, 1, T].
        ins += [qseg.reshape(b, 1, tq), kseg.reshape(b, 1, tk)]
        in_specs += [
            pl.BlockSpec((1, 1, bq), lambda i, j, kk: (i // h, 0, j), **kw),
            pl.BlockSpec((1, 1, bk), lambda i, j, kk: (i // h, 0, kk), **kw),
        ]
    if dropout_rate > 0.0:
        ins.append(seed.reshape(1, 1))
        in_specs.append(pl.BlockSpec((1, 1), lambda i, j, kk: (0, 0), **kw))
    if has_offsets:
        # offs is [B, 2] (per-sequence global positions); each program
        # reads its batch row — a [1, 2] block, like the seg-id vectors.
        ins.append(offs)
        in_specs.append(
            pl.BlockSpec((1, 2), lambda i, j, kk: (i // h, 0), **kw))
    # Inside shard_map the outputs must carry the inputs' varying-axes
    # metadata (vma) so the kernel composes with sequence parallelism.
    out_shape = [_shape_like(qf, (b * h, tq, d), q.dtype),
                 _shape_like(qf, (b * h, 1, tq), jnp.float32)]
    out, lse = pl.pallas_call(
        kern,
        grid=(b * h, tq // bq, tk // bk),
        in_specs=in_specs,
        out_specs=[
            pl.BlockSpec((1, bq, d), lambda i, j, kk: (i, j, 0), **kw),
            pl.BlockSpec((1, 1, bq), lambda i, j, kk: (i, 0, j), **kw)],
        out_shape=out_shape,
        scratch_shapes=_scratch([((bq, d), jnp.float32),
                                 ((bq, 1), jnp.float32),
                                 ((bq, 1), jnp.float32)]),
        interpret=interpret,
    )(*ins)
    return out.reshape(b, h, tq, d).transpose(0, 2, 1, 3), lse


# ---------------------------------------------------------------------------
# backward kernels
# ---------------------------------------------------------------------------

def _dkv_kernel(q_ref, g_ref, k_ref, v_ref, lse_ref, delta_ref, *rest,
                sm_scale, causal, has_seg, dropout_rate,
                has_offsets, with_lse):
    # Streaming grid (bh, k-tile, q-tile): k_ref/v_ref [1, BK, D] fixed
    # per (bh, kk); q_ref/g_ref [1, BQ, D] = this step's q tile;
    # lse_ref/delta_ref [1, 1, BQ] tiles; optional glse [1, 1, BQ];
    # outputs dk/dv [1, BK, D] written at the last q step; scratch
    # dk/dv accumulators persist across the q dimension.
    qseg_ref, kseg_ref, seed_ref, offs_ref, outs = _unpack_rest(
        rest, has_seg, dropout_rate, has_offsets)
    if with_lse:
        glse_ref, dk_ref, dv_ref, dk_s, dv_s = outs
    else:
        glse_ref = None
        dk_ref, dv_ref, dk_s, dv_s = outs

    k = k_ref[0]                                          # [BK, D]
    v = v_ref[0]
    q = q_ref[0]                                          # [BQ, D]
    g = g_ref[0]
    bk = k.shape[0]
    bq = q.shape[0]
    qq = pl.program_id(2)
    n_q = pl.num_programs(2)
    k_off = pl.program_id(1) * bk
    q_off = qq * bq
    bh_idx = pl.program_id(0)
    seed = seed_ref[0, 0].astype(jnp.uint32) if seed_ref is not None else None
    goff_q = offs_ref[0, 0] if has_offsets else 0
    goff_k = offs_ref[0, 1] if has_offsets else 0

    @pl.when(qq == 0)
    def _init():
        dk_s[...] = jnp.zeros_like(dk_s)
        dv_s[...] = jnp.zeros_like(dv_s)

    # causal: this q tile contributes only if its last row sees the
    # k tile's first row
    run = ((goff_q + q_off + bq - 1 >= goff_k + k_off)
           if causal else (qq >= 0))

    @pl.when(run)
    def _tile():
        lse = lse_ref[0, 0]
        delta = delta_ref[0, 0]
        k_pos = goff_k + k_off + jax.lax.broadcasted_iota(
            jnp.int32, (bq, bk), 1)
        q_pos = goff_q + q_off + jax.lax.broadcasted_iota(
            jnp.int32, (bq, bk), 0)
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * sm_scale
        seg_q = qseg_ref[0, 0] if has_seg else None
        seg_k = kseg_ref[0, 0] if has_seg else None
        mask = _mask_tile(causal, q_pos, k_pos, seg_q, seg_k)
        a = jnp.exp(s - lse[:, None])                     # normalized probs
        if mask is not None:
            a = jnp.where(mask, a, 0.0)
        dp = jax.lax.dot_general(g, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        if dropout_rate > 0.0:
            keep = _keep_mask(seed, bh_idx, q_pos, k_pos, dropout_rate)
            inv = 1.0 / (1.0 - dropout_rate)
            a_drop = jnp.where(keep, a * inv, 0.0)
            da = jnp.where(keep, dp * inv, 0.0)
        else:
            a_drop = a
            da = dp
        dv_s[...] = dv_s[...] + jax.lax.dot_general(
            a_drop.astype(g.dtype), g, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        ds = a * (da - delta[:, None]) * sm_scale
        if with_lse:
            # cotangent flowing into the logsumexp output: d lse_i / d s_ij
            # = a_ij (same a as above), in scaled-score space
            glse = glse_ref[0, 0]
            ds = ds + a * glse[:, None] * sm_scale
        dk_s[...] = dk_s[...] + jax.lax.dot_general(
            ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(qq == n_q - 1)
    def _finish():
        dk_ref[0] = dk_s[...].astype(dk_ref.dtype)
        dv_ref[0] = dv_s[...].astype(dv_ref.dtype)


def _dq_kernel(q_ref, g_ref, k_ref, v_ref, lse_ref, delta_ref, *rest,
               sm_scale, causal, has_seg, dropout_rate,
               has_offsets, with_lse):
    # Streaming grid (bh, q-tile, k-tile): q_ref/g_ref [1, BQ, D] fixed
    # per (bh, j); k_ref/v_ref [1, BK, D] = this step's tile;
    # lse_ref/delta_ref [1, 1, BQ]; optional glse [1, 1, BQ]; output
    # dq [1, BQ, D] written at the last k step; scratch dq accumulator.
    qseg_ref, kseg_ref, seed_ref, offs_ref, outs = _unpack_rest(
        rest, has_seg, dropout_rate, has_offsets)
    if with_lse:
        glse_ref, dq_ref, dq_s = outs
    else:
        glse_ref = None
        dq_ref, dq_s = outs

    q = q_ref[0]
    g = g_ref[0]
    k = k_ref[0]
    v = v_ref[0]
    lse = lse_ref[0, 0]
    delta = delta_ref[0, 0]
    bq = q.shape[0]
    bk = k.shape[0]
    kk = pl.program_id(2)
    n_k = pl.num_programs(2)
    q_off = pl.program_id(1) * bq
    k_off = kk * bk
    bh_idx = pl.program_id(0)
    seed = seed_ref[0, 0].astype(jnp.uint32) if seed_ref is not None else None
    goff_q = offs_ref[0, 0] if has_offsets else 0
    goff_k = offs_ref[0, 1] if has_offsets else 0

    @pl.when(kk == 0)
    def _init():
        dq_s[...] = jnp.zeros_like(dq_s)

    run = ((goff_q + q_off + bq - 1 >= goff_k + k_off)
           if causal else (kk >= 0))

    @pl.when(run)
    def _tile():
        q_pos = goff_q + q_off + jax.lax.broadcasted_iota(
            jnp.int32, (bq, bk), 0)
        k_pos = goff_k + k_off + jax.lax.broadcasted_iota(
            jnp.int32, (bq, bk), 1)
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * sm_scale
        seg_q = qseg_ref[0, 0] if has_seg else None
        seg_k = kseg_ref[0, 0] if has_seg else None
        mask = _mask_tile(causal, q_pos, k_pos, seg_q, seg_k)
        a = jnp.exp(s - lse[:, None])
        if mask is not None:
            a = jnp.where(mask, a, 0.0)
        dp = jax.lax.dot_general(g, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        if dropout_rate > 0.0:
            keep = _keep_mask(seed, bh_idx, q_pos, k_pos, dropout_rate)
            da = jnp.where(keep, dp * (1.0 / (1.0 - dropout_rate)), 0.0)
        else:
            da = dp
        ds = a * (da - delta[:, None]) * sm_scale
        if with_lse:
            ds = ds + a * glse_ref[0, 0][:, None] * sm_scale
        dq_s[...] = dq_s[...] + jax.lax.dot_general(
            ds.astype(k.dtype), k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(kk == n_k - 1)
    def _finish():
        dq_ref[0] = dq_s[...].astype(dq_ref.dtype)


def _pallas_backward(q, k, v, out, lse, qseg, kseg, seed, offs, g, g_lse,
                     causal, sm_scale, block_q, block_k, dropout_rate,
                     interpret):
    b, tq, h, d = q.shape
    tk, hk = k.shape[1], k.shape[2]
    grp = h // hk  # q heads per kv head (GQA); dk/dv computed per q head
    scale = sm_scale if sm_scale is not None else d ** -0.5
    bq = min(block_q, tq)
    bk = min(block_k, tk)
    def fold(x):
        return x.transpose(0, 2, 1, 3).reshape(-1, x.shape[1], d)
    qf, kf, vf, of, gf = fold(q), fold(k), fold(v), fold(out), fold(g)
    kv_row = lambda i: (i // h) * hk + (i % h) // grp
    # delta = rowsum(dO * O): cheap fused elementwise+reduce, XLA's job.
    # lse arrives as [B*H, 1, T] (see _forward's tiling note); delta gets
    # the same singleton-row layout.
    delta = (gf.astype(jnp.float32) * of.astype(jnp.float32)).sum(
        -1, keepdims=True).swapaxes(1, 2)
    has_seg = qseg is not None
    has_offsets = offs is not None
    with_lse = g_lse is not None
    kw = {} if _VMEM is None else {"memory_space": _VMEM}
    shape = lambda s, dt: _shape_like(qf, s, dt)
    seed_in = ([] if dropout_rate == 0.0 else [seed.reshape(1, 1)])
    seed_spec = ([] if dropout_rate == 0.0 else
                 [pl.BlockSpec((1, 1), lambda i, j, kk: (0, 0), **kw)])
    offs_in = ([offs] if has_offsets else [])
    offs_spec = ([pl.BlockSpec((1, 2), lambda i, j, kk: (i // h, 0), **kw)]
                 if has_offsets else [])

    # dk/dv: grid (bh, k-tile, q-tile) — q/g/lse/delta stream over the
    # minor q dimension; the k/v tile and the scratch accumulators are
    # fixed per (bh, k-tile)
    dkv_kern = functools.partial(
        _dkv_kernel, sm_scale=scale, causal=causal,
        has_seg=has_seg, dropout_rate=dropout_rate,
        has_offsets=has_offsets, with_lse=with_lse)
    q_tile = lambda: pl.BlockSpec((1, bq, d), lambda i, j, qq: (i, qq, 0),
                                  **kw)
    vec_q = lambda: pl.BlockSpec((1, 1, bq), lambda i, j, qq: (i, 0, qq),
                                 **kw)
    ins = [qf, gf, kf, vf, lse, delta]
    in_specs = [q_tile(), q_tile(),
                pl.BlockSpec((1, bk, d),
                             lambda i, j, qq: (kv_row(i), j, 0), **kw),
                pl.BlockSpec((1, bk, d),
                             lambda i, j, qq: (kv_row(i), j, 0), **kw),
                vec_q(), vec_q()]
    if has_seg:
        ins += [qseg.reshape(b, 1, tq), kseg.reshape(b, 1, tk)]
        in_specs += [
            pl.BlockSpec((1, 1, bq), lambda i, j, qq: (i // h, 0, qq), **kw),
            pl.BlockSpec((1, 1, bk), lambda i, j, qq: (i // h, 0, j), **kw)]
    ins += seed_in
    in_specs += seed_spec
    ins += offs_in
    in_specs += offs_spec
    if with_lse:
        ins.append(g_lse)
        in_specs.append(vec_q())
    dk, dv = pl.pallas_call(
        dkv_kern,
        grid=(b * h, tk // bk, tq // bq),
        in_specs=in_specs,
        out_specs=[
            pl.BlockSpec((1, bk, d), lambda i, j, qq: (i, j, 0), **kw),
            pl.BlockSpec((1, bk, d), lambda i, j, qq: (i, j, 0), **kw)],
        out_shape=[shape((b * h, tk, d), k.dtype),
                   shape((b * h, tk, d), v.dtype)],
        scratch_shapes=_scratch([((bk, d), jnp.float32),
                                 ((bk, d), jnp.float32)]),
        interpret=interpret,
    )(*ins)
    if grp > 1:
        # each kv head's gradient is the sum over its q-head group —
        # accumulated in f32 (the kernel's partials were cast to the
        # output dtype; summing them in bf16 would compound rounding the
        # blockwise oracle doesn't have)
        group_sum = lambda x: x.astype(jnp.float32).reshape(
            b, hk, grp, tk, d).sum(2).reshape(b * hk, tk, d).astype(x.dtype)
        dk, dv = group_sum(dk), group_sum(dv)

    # dq: grid (bh, q-tile, k-tile) — k/v stream over the minor k
    # dimension; the q/g/lse/delta tiles and the dq scratch are fixed
    dq_kern = functools.partial(
        _dq_kernel, sm_scale=scale, causal=causal,
        has_seg=has_seg, dropout_rate=dropout_rate,
        has_offsets=has_offsets, with_lse=with_lse)
    vec_j = lambda: pl.BlockSpec((1, 1, bq), lambda i, j, kk: (i, 0, j),
                                 **kw)
    ins = [qf, gf, kf, vf, lse, delta]
    in_specs = [pl.BlockSpec((1, bq, d), lambda i, j, kk: (i, j, 0), **kw),
                pl.BlockSpec((1, bq, d), lambda i, j, kk: (i, j, 0), **kw),
                pl.BlockSpec((1, bk, d),
                             lambda i, j, kk: (kv_row(i), kk, 0), **kw),
                pl.BlockSpec((1, bk, d),
                             lambda i, j, kk: (kv_row(i), kk, 0), **kw),
                vec_j(), vec_j()]
    if has_seg:
        ins += [qseg.reshape(b, 1, tq), kseg.reshape(b, 1, tk)]
        in_specs += [
            pl.BlockSpec((1, 1, bq), lambda i, j, kk: (i // h, 0, j), **kw),
            pl.BlockSpec((1, 1, bk), lambda i, j, kk: (i // h, 0, kk), **kw)]
    ins += seed_in
    in_specs += seed_spec
    ins += offs_in
    in_specs += offs_spec
    if with_lse:
        ins.append(g_lse)
        in_specs.append(vec_j())
    dq = pl.pallas_call(
        dq_kern,
        grid=(b * h, tq // bq, tk // bk),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, bq, d), lambda i, j, kk: (i, j, 0), **kw),
        out_shape=shape((b * h, tq, d), q.dtype),
        scratch_shapes=_scratch([((bq, d), jnp.float32)]),
        interpret=interpret,
    )(*ins)

    unfold = lambda x, t_, h_: x.reshape(b, h_, t_, d).transpose(0, 2, 1, 3)
    return unfold(dq, tq, h), unfold(dk, tk, hk), unfold(dv, tk, hk)


def _blockwise_backward(q, k, v, out, lse, qseg, kseg, seed, offs, g, g_lse,
                        causal, sm_scale, block_k, dropout_rate):
    """Pure-XLA blockwise flash backward — the gradient-parity oracle.

    Identical math to the Pallas kernels (saved-lse softmax, the same
    hash-based dropout mask), expressed as a `lax.scan` over K/V tiles so
    the [T, T] matrix is still never materialized.
    """
    b, tq, h, d = q.shape
    tk, hk = k.shape[1], k.shape[2]
    grp = h // hk
    scale = sm_scale if sm_scale is not None else d ** -0.5
    bk = min(block_k, tk)
    n = tk // bk
    # [B, T, H, D] -> [B, H, T, D] f32 working layout
    tr = lambda x: x.transpose(0, 2, 1, 3).astype(jnp.float32)
    qT, oT, gT = tr(q), tr(out), tr(g)
    kT, vT = tr(k), tr(v)
    if grp > 1:  # GQA: expand kv to one head per q head for the math
        rep = lambda x: jnp.repeat(x, grp, axis=1)
        kT, vT = rep(kT), rep(vT)
    lseT = lse.reshape(b, h, tq)  # lse arrives [B*H, 1, Tq]
    glseT = g_lse.reshape(b, h, tq) if g_lse is not None else None
    # offs is [B, 2] (per-sequence offsets); broadcast as [B, 1, T|S, 1]
    # planes so the mask/dropout math matches the per-program scalars the
    # Pallas kernels read
    if offs is not None:
        goff_q = offs[:, 0].reshape(b, 1, 1, 1)
        goff_k = offs[:, 1].reshape(b, 1, 1, 1)
    else:
        goff_q = goff_k = jnp.zeros((1, 1, 1, 1), jnp.int32)
    q_pos = goff_q + jnp.arange(tq).reshape(1, 1, tq, 1)   # [B|1,1,T,1]
    bh_idx = jnp.arange(b * h).reshape(b, h, 1, 1)
    D = (gT * oT).sum(-1)                                  # [B, H, T]
    inv = 1.0 / (1.0 - dropout_rate) if dropout_rate > 0.0 else 1.0

    def k_pos_tile(j):
        return goff_k + (j * bk + jnp.arange(bk)).reshape(1, 1, 1, bk)

    def tile_mask(j):
        mask = None
        if causal:
            mask = q_pos >= k_pos_tile(j)                  # [B|1,1,T,S]
        if qseg is not None:
            kseg_j = jax.lax.dynamic_slice_in_dim(kseg, j * bk, bk, axis=1)
            m2 = (qseg[:, None, :, None] == kseg_j[:, None, None, :])
            mask = m2 if mask is None else (mask & m2)
        return mask

    def keep(j):
        if dropout_rate == 0.0:
            return None
        return _keep_mask(seed.astype(jnp.uint32), bh_idx,
                          q_pos, k_pos_tile(j), dropout_rate)

    def grad_fold(dq, j):
        kb = jax.lax.dynamic_slice_in_dim(kT, j * bk, bk, axis=2)
        vb = jax.lax.dynamic_slice_in_dim(vT, j * bk, bk, axis=2)
        s = jnp.einsum("bhtd,bhsd->bhts", qT, kb,
                       preferred_element_type=jnp.float32) * scale
        a = jnp.exp(s - lseT[..., None])
        mask = tile_mask(j)
        if mask is not None:
            a = jnp.where(mask, a, 0.0)
        dp = jnp.einsum("bhtd,bhsd->bhts", gT, vb)
        km = keep(j)
        if km is not None:
            a_drop = jnp.where(km, a * inv, 0.0)
            da = jnp.where(km, dp * inv, 0.0)
        else:
            a_drop = a
            da = dp
        dv_j = jnp.einsum("bhts,bhtd->bhsd", a_drop, gT)
        ds = a * (da - D[..., None]) * scale
        if glseT is not None:
            ds = ds + a * glseT[..., None] * scale
        dq = dq + jnp.einsum("bhts,bhsd->bhtd", ds, kb)
        dk_j = jnp.einsum("bhts,bhtd->bhsd", ds, qT)
        return dq, (dk_j, dv_j)

    dq0 = jnp.zeros_like(qT)
    dq, (dk_tiles, dv_tiles) = jax.lax.scan(grad_fold, dq0, jnp.arange(n))
    # [n, B, H, bk, D] -> [B, H, Tk, D]
    merge = lambda tiles: tiles.transpose(1, 2, 0, 3, 4).reshape(b, h, tk, d)
    back = lambda x, ref: x.transpose(0, 2, 1, 3).astype(ref.dtype)
    dk_full, dv_full = merge(dk_tiles), merge(dv_tiles)
    if grp > 1:  # sum each kv head's gradient over its q-head group
        gsum = lambda x: x.reshape(b, hk, grp, tk, d).sum(2)
        dk_full, dv_full = gsum(dk_full), gsum(dv_full)
    return (back(dq, q), back(dk_full, k), back(dv_full, v))


# ---------------------------------------------------------------------------
# custom_vjp plumbing + public API
# ---------------------------------------------------------------------------

@functools.partial(jax.custom_vjp,
                   nondiff_argnums=(7, 8, 9, 10, 11, 12, 13))
def _flash(q, k, v, qseg, kseg, seed, offs, dropout_rate, causal, sm_scale,
           block_q, block_k, bwd_impl, with_lse):
    interpret = jax.default_backend() != "tpu"
    out, lse = _forward(q, k, v, qseg, kseg, seed, offs, causal, sm_scale,
                        block_q, block_k, dropout_rate, interpret)
    if with_lse:
        b, t, h, _ = q.shape
        return out, lse.reshape(b, h, t)
    return out


def _flash_fwd(q, k, v, qseg, kseg, seed, offs, dropout_rate, causal,
               sm_scale, block_q, block_k, bwd_impl, with_lse):
    interpret = jax.default_backend() != "tpu"
    out, lse = _forward(q, k, v, qseg, kseg, seed, offs, causal, sm_scale,
                        block_q, block_k, dropout_rate, interpret)
    res = (q, k, v, out, lse, qseg, kseg, seed, offs)
    if with_lse:
        b, t, h, _ = q.shape
        return (out, lse.reshape(b, h, t)), res
    return out, res


def _flash_bwd(dropout_rate, causal, sm_scale, block_q, block_k, bwd_impl,
               with_lse, res, g):
    q, k, v, out, lse, qseg, kseg, seed, offs = res
    if with_lse:
        g, g_lse_bht = g
        b, t, h, _ = q.shape
        g_lse = g_lse_bht.reshape(b * h, 1, t).astype(jnp.float32)
    else:
        g_lse = None
    if bwd_impl == "pallas":
        interpret = jax.default_backend() != "tpu"
        dq, dk, dv = _pallas_backward(
            q, k, v, out, lse, qseg, kseg, seed, offs, g, g_lse, causal,
            sm_scale, block_q, block_k, dropout_rate, interpret)
    elif bwd_impl == "blockwise":
        dq, dk, dv = _blockwise_backward(
            q, k, v, out, lse, qseg, kseg, seed, offs, g, g_lse, causal,
            sm_scale, block_k, dropout_rate)
    else:
        raise ValueError(f"unknown bwd_impl {bwd_impl!r} "
                         "(expected 'pallas' or 'blockwise')")
    return dq, dk, dv, None, None, None, None


_flash.defvjp(_flash_fwd, _flash_bwd)


def _fit_block(t: int, requested: Optional[int], default: int) -> int:
    """Resolve a block size.  Explicit sizes are strict (must divide T, as
    before); the default auto-shrinks by halving until it divides — so the
    larger shipped default never rejects a T an older default accepted."""
    if requested is not None:
        b = min(int(requested), t)
        if t % b:
            raise ValueError(
                f"flash_attention needs seq len ({t}) divisible by its "
                f"tiles ({b}); pad the sequence or pass smaller block "
                f"sizes")
        return b
    b = min(default, t)
    while b > 1 and t % b:
        b //= 2
    if t % b:
        raise ValueError(
            f"flash_attention cannot tile seq len {t}; pass block_q/"
            f"block_k that divide it (or pad the sequence)")
    return b


def flash_attention(q, k, v, causal: bool = False,
                    sm_scale: Optional[float] = None,
                    block_q: Optional[int] = None,
                    block_k: Optional[int] = None,
                    *, q_segment_ids=None, kv_segment_ids=None,
                    dropout_rate: float = 0.0, dropout_seed=None,
                    q_offset=None, kv_offset=None,
                    return_lse: bool = False,
                    bwd_impl: str = "pallas"):
    """Fused softmax attention: q [B, Tq, H, D], k/v [B, Tkv, Hkv, D]
    -> [B, Tq, H, D].  ``Tq != Tkv`` is supported (cross-attention /
    decode-over-cache); with ``causal`` the mask compares GLOBAL
    positions (row ``q_offset+i`` sees column ``kv_offset+j`` iff
    ``i+q_offset >= j+kv_offset``).  ``Hkv`` may divide ``H``
    (grouped-query / multi-query attention): each kv head serves
    ``H/Hkv`` q heads — the kernels read the shared K/V tiles via index
    maps (no materialized repeat) and dk/dv sum over each group.

    Drop-in for :func:`chainermn_tpu.parallel.sequence.attention` (same
    signature minus offsets); pass as ``attn_fn=`` to
    ``ulysses_attention`` for a fused inner kernel.  ``block_q``/
    ``block_k`` tune the tile sizes: explicit values must divide the
    sequence length (or cover it in one tile); the default is
    dtype-aware (1024 for sub-4-byte q/k/v — the measured v5e optimum —
    and 512 when any operand is f32, whose tiles would overflow the
    backward's VMEM budget at 1024) and auto-halves until it divides,
    so any T a smaller default accepted still works.

    Extra keyword-only features:

    * ``q_segment_ids`` / ``kv_segment_ids`` — [B, T] int32 ids;
      position pairs attend only when ids match (packed sequences,
      padding).  Passing either defaults the other to zeros.
    * ``dropout_rate`` + ``dropout_seed`` — attention dropout; the seed
      is a traced uint32 scalar (vary it per training step).
    * ``q_offset`` / ``kv_offset`` — global positions of the first local
      row: traced int scalars (shared by the batch — ring attention's
      blocks of a longer sequence) or ``[B]`` int32 vectors giving every
      sequence its own offset (decode over a paged KV cache, where each
      batch row sits at a different cache length).  The causal mask and
      the dropout hash both use global positions.
    * ``return_lse`` — also return the per-row logsumexp [B, H, T]
      (float32; fully-masked rows hold the sentinel 1e30).  The lse is
      DIFFERENTIABLE: its cotangent adds ``a_ij * g_lse_i`` to the score
      gradients in both backward implementations, which is what lets
      downstream logsumexp merges (ring attention) backprop exactly.
    * ``bwd_impl`` — "pallas" (default, fused backward kernels) or
      "blockwise" (pure-XLA oracle with identical math).
    """
    if (q_segment_ids is not None) or (kv_segment_ids is not None):
        if q_segment_ids is None:
            q_segment_ids = jnp.zeros(q.shape[:2], jnp.int32)
        if kv_segment_ids is None:
            kv_segment_ids = jnp.zeros(k.shape[:2], jnp.int32)
        q_segment_ids = q_segment_ids.astype(jnp.int32)
        kv_segment_ids = kv_segment_ids.astype(jnp.int32)
    dropout_rate = float(dropout_rate)
    if not 0.0 <= dropout_rate < 1.0:
        raise ValueError(f"dropout_rate must be in [0, 1), got {dropout_rate}")
    if dropout_rate > 0.0:
        if dropout_seed is None:
            raise ValueError("dropout_rate > 0 requires dropout_seed")
        dropout_seed = jnp.asarray(dropout_seed, jnp.uint32)
    else:
        dropout_seed = None
    if (q_offset is not None) or (kv_offset is not None):
        # offsets ride as one [B, 2] int32 array: column 0 = q, column 1 =
        # kv.  Scalars broadcast over the batch (ring attention's shared
        # block offsets); [B] arrays give every sequence its own global
        # position — decode-over-a-paged-cache, where each row of the
        # batch sits at a different cache length.
        b = q.shape[0]

        def _off_vec(o, label):
            o = jnp.asarray(0 if o is None else o, jnp.int32)
            if o.ndim == 0:
                return jnp.broadcast_to(o, (b,))
            if o.shape != (b,):
                raise ValueError(
                    f"{label} must be a scalar or a [batch] vector; got "
                    f"shape {o.shape} for batch {b}")
            return o

        offs = jnp.stack([_off_vec(q_offset, "q_offset"),
                          _off_vec(kv_offset, "kv_offset")], axis=1)
    else:
        offs = None
    # cross-attention supported: Tq (from q) and Tkv (from k/v) may
    # differ; GQA/MQA supported: k/v head count may divide q's
    if k.shape != v.shape:
        raise ValueError(f"k and v shapes differ: {k.shape} vs {v.shape}")
    if (q.shape[0], q.shape[3]) != (k.shape[0], k.shape[3]):
        raise ValueError(
            f"q and k/v must share batch/dim: {q.shape} vs {k.shape}")
    if q.shape[2] % k.shape[2]:
        raise ValueError(
            f"q head count ({q.shape[2]}) must be a multiple of the kv "
            f"head count ({k.shape[2]}) for grouped-query attention")
    # default blocks are dtype-aware: 1024x1024 is the measured bf16
    # optimum, but f32 tiles double every VMEM buffer and the backward's
    # scoped allocation overflows the 16 MB budget — 512 fits with room
    # (widest of q/k/v decides: any f32 operand inflates the tiles)
    if max(jnp.dtype(a.dtype).itemsize for a in (q, k, v)) >= 4:
        dq_def, dk_def = min(_BLOCK_Q, 512), min(_BLOCK_K, 512)
    else:
        dq_def, dk_def = _BLOCK_Q, _BLOCK_K
    bq = _fit_block(q.shape[1], block_q, dq_def)
    bk = _fit_block(k.shape[1], block_k, dk_def)
    return _flash(q, k, v, q_segment_ids, kv_segment_ids, dropout_seed,
                  offs, dropout_rate, bool(causal), sm_scale, bq, bk,
                  bwd_impl, bool(return_lse))


__all__ = ["flash_attention"]
