"""Synthetic datasets for examples and benchmarks.

The reference examples download MNIST/ImageNet via Chainer's dataset
utilities; this environment has no network, so the example scripts default to
procedurally generated data with the same shapes and a learnable signal
(class-dependent Gaussian means), which lets the training loop demonstrate
real convergence.  Pass ``--data <path.npz>`` to the examples to use real
data instead.
"""

from __future__ import annotations

import numpy as np

from chainermn_tpu.datasets.scatter_dataset import TupleDataset


def make_classification(
    n: int = 60000,
    dim: int = 784,
    n_classes: int = 10,
    *,
    scale: float = 1.0,
    noise: float = 1.0,
    seed: int = 0,
    class_seed: int = 1234,
    image_shape=None,
):
    """Gaussian-blob classification dataset: x = mu[y] + noise*N(0, I).

    ``class_seed`` fixes the class means independently of ``seed`` so a
    train split (seed=0) and a test split (seed=1) sample the *same* task.
    """
    mus = (np.random.RandomState(class_seed)
           .randn(n_classes, dim).astype(np.float32) * scale)
    rng = np.random.RandomState(seed)
    y = rng.randint(0, n_classes, size=n).astype(np.int32)
    x = mus[y] + noise * rng.randn(n, dim).astype(np.float32)
    if image_shape is not None:
        x = x.reshape((n,) + tuple(image_shape))
    return TupleDataset(x, y)
