from chainermn_tpu.datasets.scatter_dataset import (
    SubDataset,
    TupleDataset,
    scatter_dataset,
    scatter_index,
)
from chainermn_tpu.datasets.synthetic import make_classification

__all__ = [
    "SubDataset",
    "TupleDataset",
    "scatter_dataset",
    "scatter_index",
    "make_classification",
]
