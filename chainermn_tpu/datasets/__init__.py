from chainermn_tpu.datasets.image_pipeline import (
    Augment,
    ImageFolderDataset,
    NpzImageDataset,
    PrefetchIterator,
    TransformDataset,
    normalize_image,
)
from chainermn_tpu.datasets.scatter_dataset import (
    SubDataset,
    TupleDataset,
    scatter_dataset,
    scatter_index,
)
from chainermn_tpu.datasets.nmt import (
    Vocab,
    bleu,
    bucket_batches,
    encode_pairs,
    load_corpus,
)
from chainermn_tpu.datasets.synthetic import make_classification

__all__ = [
    "Vocab",
    "bleu",
    "bucket_batches",
    "encode_pairs",
    "load_corpus",
    "Augment",
    "ImageFolderDataset",
    "NpzImageDataset",
    "PrefetchIterator",
    "SubDataset",
    "TransformDataset",
    "TupleDataset",
    "normalize_image",
    "scatter_dataset",
    "scatter_index",
    "make_classification",
]
