"""Dataset scattering across hosts.

Reference being rebuilt (path unverified, SURVEY.md provenance):
``scatter_dataset`` in 〔chainermn/datasets/scatter_dataset.py〕 — rank 0
draws a permutation (``shuffle``, ``seed``), slices the dataset into
``comm.size`` near-equal ``SubDataset`` shards and ``comm.scatter``-s the
pickled shards; every rank returns its shard.

TPU-native re-interpretation: sharding is **by host** (controller process),
not by device — within a host the global batch is sharded over devices by
the train step's input sharding, which together reproduces the reference's
per-GPU sharding.  Only the *seed* crosses the control plane (rank 0
broadcasts it); each host then computes the identical permutation locally
and takes its slice, so the global example order is a pure function of
(seed, len(dataset)) — identical regardless of host count (determinism
requirement, SURVEY.md §7 hard part 4) — and no pickled data moves at all.
"""

from __future__ import annotations

import math
from typing import Optional, Sequence

import numpy as np


class TupleDataset:
    """Minimal dataset over parallel arrays (the Chainer TupleDataset role)."""

    def __init__(self, *arrays):
        n = len(arrays[0])
        for a in arrays:
            if len(a) != n:
                raise ValueError("all arrays must share their first dimension")
        self._arrays = arrays

    def __len__(self):
        return len(self._arrays[0])

    def __getitem__(self, i):
        return tuple(a[i] for a in self._arrays)


class SubDataset:
    """A view of ``dataset`` through an index array (reference:
    ``chainer.datasets.SubDataset`` as used by ``scatter_dataset``)."""

    def __init__(self, dataset, indices: np.ndarray):
        self._dataset = dataset
        self._indices = np.asarray(indices)

    def __len__(self):
        return len(self._indices)

    def __getitem__(self, i):
        return self._dataset[int(self._indices[i])]

    @property
    def indices(self) -> np.ndarray:
        return self._indices


def scatter_index(n_total: int, comm, *, force_equal_length: bool = True):
    """Partition ``range(n_total)`` across hosts (upstream ChainerMN's
    ``scatter_index``): returns this host's index array."""
    return _host_slice(np.arange(n_total), comm.rank, comm.host_size,
                       force_equal_length)


def _host_slice(order: np.ndarray, rank: int, size: int,
                force_equal_length: bool) -> np.ndarray:
    n = len(order)
    per = -(-n // size)  # ceil
    if force_equal_length:
        # Pad by wrapping (reference behavior: every shard equal length so
        # every rank runs the same number of iterations per epoch).
        # np.resize repeats cyclically, covering even n < size.
        padded = np.resize(order, per * size)
        return padded[rank * per:(rank + 1) * per]
    return order[min(rank * per, n): min((rank + 1) * per, n)]


def scatter_dataset(
    dataset,
    comm,
    shuffle: bool = False,
    seed: Optional[int] = None,
    force_equal_length: bool = True,
    root: int = 0,
) -> SubDataset:
    """Shard ``dataset`` across the communicator's hosts.

    Reference signature 〔datasets/scatter_dataset.py〕:
    ``scatter_dataset(dataset, comm, root=0, shuffle=False, seed=None)``.
    Rank ``root`` decides the seed; every host derives the same global
    permutation from it and takes its own contiguous slice.
    """
    if comm.rank == root and shuffle and seed is None:
        seed = int(np.random.randint(0, 2**31 - 1))
    seed = comm.bcast_obj(seed, root=root)
    n = len(dataset)
    if shuffle:
        order = np.random.RandomState(seed).permutation(n)
    else:
        order = np.arange(n)
    local = _host_slice(order, comm.rank, comm.host_size, force_equal_length)
    return SubDataset(dataset, local)
