"""NMT corpus utilities: vocabulary, bucketing, padding, BLEU.

Reference being rebuilt (path unverified, SURVEY.md provenance):
〔examples/seq2seq/seq2seq.py〕 — the reference example's ~400 LoC of corpus
handling: load parallel token-per-line text files, build frequency-sorted
vocabularies with special tokens, batch ragged sentences, and score
held-out translations.  Rebuilt TPU-first: ragged sentences become padded
LENGTH BUCKETS (each bucket shape compiles once; `step` bounds the number
of distinct XLA programs) with explicit lengths + masks, instead of the
reference's per-batch ragged NStepLSTM lists.

Pure numpy/python — no model dependencies; BLEU is self-contained
(corpus-level BLEU-4 with brevity penalty, the standard Papineni metric).
"""

from __future__ import annotations

import math
from collections import Counter
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

import numpy as np

PAD_ID, BOS_ID, EOS_ID, UNK_ID = 0, 1, 2, 3
SPECIALS = ("<pad>", "<bos>", "<eos>", "<unk>")


class Vocab:
    """Frequency-sorted vocabulary with pinned special tokens.

    ``itos[0:4]`` are always ``<pad> <bos> <eos> <unk>``; remaining slots
    are corpus tokens, most frequent first (ties broken lexicographically
    so construction is deterministic across processes).
    """

    def __init__(self, counts: Dict[str, int],
                 max_size: Optional[int] = None):
        items = sorted(counts.items(), key=lambda kv: (-kv[1], kv[0]))
        if max_size is not None:
            if max_size <= len(SPECIALS):
                raise ValueError(
                    f"max_size={max_size} leaves no room beyond the "
                    f"{len(SPECIALS)} special tokens")
            items = items[:max_size - len(SPECIALS)]
        self.itos: List[str] = list(SPECIALS) + [t for t, _ in items]
        self.stoi: Dict[str, int] = {t: i for i, t in enumerate(self.itos)}

    @classmethod
    def build(cls, sentences: Iterable[Sequence[str]],
              max_size: Optional[int] = None) -> "Vocab":
        counts: Counter = Counter()
        for toks in sentences:
            counts.update(toks)
        for sp in SPECIALS:
            counts.pop(sp, None)
        return cls(counts, max_size)

    def __len__(self) -> int:
        return len(self.itos)

    def encode(self, tokens: Sequence[str]) -> List[int]:
        return [self.stoi.get(t, UNK_ID) for t in tokens]

    def decode(self, ids: Iterable[int]) -> List[str]:
        """Ids -> tokens, stopping at EOS, skipping pad/bos."""
        out = []
        for i in ids:
            i = int(i)
            if i == EOS_ID:
                break
            if i in (PAD_ID, BOS_ID):
                continue
            out.append(self.itos[i] if 0 <= i < len(self.itos)
                       else SPECIALS[UNK_ID])
        return out


def load_corpus(src_path: str, tgt_path: str,
                max_len: Optional[int] = None,
                ) -> List[Tuple[List[str], List[str]]]:
    """Parallel corpus: one sentence per line, whitespace-tokenized.
    Pairs where either side is empty (or longer than ``max_len``, when
    given) are skipped — the reference example filtered the same way."""
    with open(src_path, encoding="utf-8") as f:
        src_lines = f.read().splitlines()
    with open(tgt_path, encoding="utf-8") as f:
        tgt_lines = f.read().splitlines()
    if len(src_lines) != len(tgt_lines):
        raise ValueError(
            f"parallel corpus line-count mismatch: {src_path} has "
            f"{len(src_lines)} lines, {tgt_path} has {len(tgt_lines)}")
    pairs = []
    for s, t in zip(src_lines, tgt_lines):
        st, tt = s.split(), t.split()
        if not st or not tt:
            continue
        if max_len is not None and (len(st) > max_len or len(tt) > max_len):
            continue
        pairs.append((st, tt))
    if not pairs:
        raise ValueError(f"no usable sentence pairs in {src_path}")
    return pairs


def encode_pairs(pairs: Sequence[Tuple[Sequence[str], Sequence[str]]],
                 src_vocab: Vocab, tgt_vocab: Vocab,
                 ) -> List[Tuple[np.ndarray, np.ndarray]]:
    """Token pairs -> (src_ids, tgt_ids+EOS) int32 arrays."""
    out = []
    for s, t in pairs:
        out.append((np.asarray(src_vocab.encode(s), np.int32),
                    np.asarray(tgt_vocab.encode(t) + [EOS_ID], np.int32)))
    return out


def _pad_to(ids: np.ndarray, length: int) -> np.ndarray:
    return np.pad(ids, (0, length - len(ids)),
                  constant_values=PAD_ID).astype(np.int32)


def bucket_batches(examples: Sequence[Tuple[np.ndarray, np.ndarray]],
                   batch_size: int, step: int = 4,
                   shuffle: bool = True, seed: int = 0,
                   drop_remainder: bool = True):
    """Yield padded batches grouped by (src, tgt) length bucket.

    Each example lands in the bucket of its lengths rounded up to a
    multiple of ``step``; every batch from a bucket has that one padded
    shape, so XLA compiles one program per occupied bucket, not one per
    ragged batch.  Yields dicts with:

    - ``src`` (B, Ls): pad-right source ids
    - ``src_len`` (B,): true source lengths (feed the encoder so the
      carry is taken at the last real token)
    - ``tgt_in`` (B, Lt): BOS + target[:-1] (teacher forcing input)
    - ``tgt_out`` (B, Lt): target + EOS, pad-right (loss labels)
    - ``mask`` (B, Lt) float32: 1 on real target positions (incl. EOS)

    ``drop_remainder=False`` wrap-pads the final short batch of each
    bucket to ``batch_size`` and marks the padding rows with ``mask=0``
    (eval path: metrics stay exact, shapes stay static).  Every yielded
    batch has exactly ``batch_size`` rows, so pick a ``batch_size`` the
    stage's device-group size divides.
    """
    rng = np.random.RandomState(seed)
    buckets: Dict[Tuple[int, int], List[int]] = {}
    for i, (s, t) in enumerate(examples):
        key = (max(step, math.ceil(len(s) / step) * step),
               max(step, math.ceil(len(t) / step) * step))
        buckets.setdefault(key, []).append(i)

    order = sorted(buckets)
    if shuffle:
        order = [order[j] for j in rng.permutation(len(order))]
    for key in order:
        idx = buckets[key]
        if shuffle:
            idx = [idx[j] for j in rng.permutation(len(idx))]
        ls, lt = key
        for b0 in range(0, len(idx), batch_size):
            chunk = idx[b0:b0 + batch_size]
            real = len(chunk)
            if real < batch_size:
                if drop_remainder:
                    continue
                chunk = (chunk * math.ceil(batch_size / real))[:batch_size]
            src = np.stack([_pad_to(examples[i][0], ls) for i in chunk])
            src_len = np.asarray(
                [len(examples[i][0]) for i in chunk], np.int32)
            tgt_full = np.stack([_pad_to(examples[i][1], lt)
                                 for i in chunk])
            tgt_in = np.concatenate(
                [np.full((len(chunk), 1), BOS_ID, np.int32),
                 tgt_full[:, :-1]], axis=1)
            mask = (tgt_full != PAD_ID).astype(np.float32)
            if real < batch_size:  # wrap-padded eval rows don't count
                mask[real:] = 0.0
            yield {"src": src, "src_len": src_len, "tgt_in": tgt_in,
                   "tgt_out": tgt_full, "mask": mask, "n_real": real}


def bleu(hypotheses: Sequence[Sequence[str]],
         references: Sequence[Sequence[str]], max_n: int = 4,
         smooth: bool = True) -> float:
    """Corpus-level BLEU-``max_n`` with brevity penalty (Papineni et al.).
    ``smooth`` adds +1 smoothing to higher-order precisions (method-1),
    keeping short-corpus scores finite — the usual example-scale choice."""
    if len(hypotheses) != len(references):
        raise ValueError("hypothesis/reference count mismatch")
    clipped = np.zeros(max_n)
    totals = np.zeros(max_n)
    hyp_len = ref_len = 0
    for hyp, ref in zip(hypotheses, references):
        hyp, ref = list(hyp), list(ref)
        hyp_len += len(hyp)
        ref_len += len(ref)
        for n in range(1, max_n + 1):
            h_ngrams = Counter(tuple(hyp[i:i + n])
                               for i in range(len(hyp) - n + 1))
            r_ngrams = Counter(tuple(ref[i:i + n])
                               for i in range(len(ref) - n + 1))
            totals[n - 1] += max(0, len(hyp) - n + 1)
            clipped[n - 1] += sum(min(c, r_ngrams[g])
                                  for g, c in h_ngrams.items())
    log_p = 0.0
    for n in range(max_n):
        num, den = clipped[n], totals[n]
        if smooth and n > 0:
            num, den = num + 1.0, den + 1.0
        if num == 0 or den == 0:
            return 0.0
        log_p += math.log(num / den) / max_n
    bp = (1.0 if hyp_len >= ref_len
          else math.exp(1.0 - ref_len / max(hyp_len, 1)))
    return bp * math.exp(log_p)
