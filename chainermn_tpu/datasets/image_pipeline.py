"""Real-data input pipeline: datasets, augmentation, prefetch.

Reference behavior being rebuilt (path unverified, SURVEY.md provenance):
the reference's ImageNet example consumed real images with host-side
preprocessing — random crop + horizontal flip + mean subtraction — fed
from worker processes 〔examples/imagenet/train_imagenet.py〕.

TPU-native design:

* **Host does uint8 work, device does float work.**  Decode, crop and
  flip happen on the host in uint8 (4× fewer bytes over PCIe/DCN than
  f32); mean/std normalization is :func:`normalize_image`, one fused
  device op at the head of the loss — XLA folds it into the first conv's
  prologue.
* **Prefetch hides the host.**  :class:`PrefetchIterator` wraps any
  batch iterator: a producer thread pulls index batches and fans the
  per-sample decode+augment out to a thread pool (PIL releases the GIL
  in decode/resize), collating into a bounded queue ahead of the
  consumer.  The training loop's ``next()`` is a queue pop, so input
  work overlaps the device step like the reference's multiprocess
  feeders did.
* Epoch bookkeeping (``epoch`` / ``is_new_epoch`` / ``epoch_detail``)
  is snapshotted WITH each produced batch and restored at consumption,
  so look-ahead never skews trainer triggers.
"""

from __future__ import annotations

import os
import queue
import threading
from concurrent.futures import ThreadPoolExecutor
from typing import Callable, Optional, Sequence, Tuple

import numpy as np

_IMG_EXTS = (".jpg", ".jpeg", ".png", ".bmp", ".gif", ".webp")


class ImageFolderDataset:
    """``root/<class_name>/<image>`` tree, lazily decoded with PIL.

    ``__getitem__`` returns ``(uint8 [H, W, 3], int32 label)``; classes are
    the sorted subdirectory names.  ``resize`` (int) resizes the short side
    before any augmentation (the usual decode-time downscale).
    """

    def __init__(self, root: str, resize: Optional[int] = None):
        self.root = root
        self.classes = sorted(
            d for d in os.listdir(root)
            if os.path.isdir(os.path.join(root, d)))
        if not self.classes:
            raise ValueError(f"no class subdirectories under {root!r}")
        self.samples: list = []
        for label, cls in enumerate(self.classes):
            cdir = os.path.join(root, cls)
            for fn in sorted(os.listdir(cdir)):
                if fn.lower().endswith(_IMG_EXTS):
                    self.samples.append((os.path.join(cdir, fn), label))
        if not self.samples:
            raise ValueError(f"no images found under {root!r}")
        self.resize = resize

    def __len__(self):
        return len(self.samples)

    def __getitem__(self, i):
        from PIL import Image

        path, label = self.samples[i]
        with Image.open(path) as im:
            im = im.convert("RGB")
            if self.resize:
                w, h = im.size
                s = self.resize / min(w, h)
                if s != 1.0:
                    im = im.resize((max(1, round(w * s)),
                                    max(1, round(h * s))))
            arr = np.asarray(im, dtype=np.uint8)
        return arr, np.int32(label)


class NpzImageDataset:
    """npz/dict with image + label arrays (``x``/``y`` or
    ``x_train``/``y_train``); images uint8 or float, NHWC."""

    def __init__(self, path_or_arrays, x_key: Optional[str] = None,
                 y_key: Optional[str] = None):
        if isinstance(path_or_arrays, (str, os.PathLike)):
            data = np.load(path_or_arrays)
        else:
            data = path_or_arrays
        keys = set(getattr(data, "files", None) or data.keys())
        xk = x_key or ("x" if "x" in keys else "x_train")
        yk = y_key or ("y" if "y" in keys else "y_train")
        if xk not in keys or yk not in keys:
            raise KeyError(f"need {xk!r}/{yk!r} arrays, found {sorted(keys)}")
        self.x = np.asarray(data[xk])
        self.y = np.asarray(data[yk]).astype(np.int32)
        if len(self.x) != len(self.y):
            raise ValueError(f"x/y length mismatch: {len(self.x)} vs "
                             f"{len(self.y)}")

    def __len__(self):
        return len(self.x)

    def __getitem__(self, i):
        return self.x[i], self.y[i]


# ---------------------------------------------------------------------------
# augmentation (host side, uint8 in -> uint8 out)
# ---------------------------------------------------------------------------

def random_crop(img: np.ndarray, size: int, rng: np.random.RandomState,
                pad: int = 0) -> np.ndarray:
    """Random ``size``×``size`` crop, optionally after zero-padding ``pad``
    on each side (the CIFAR recipe)."""
    if pad:
        img = np.pad(img, ((pad, pad), (pad, pad), (0, 0)))
    h, w = img.shape[:2]
    if h < size or w < size:
        raise ValueError(f"image {h}x{w} smaller than crop {size}")
    top = rng.randint(h - size + 1)
    left = rng.randint(w - size + 1)
    return img[top:top + size, left:left + size]


def center_crop(img: np.ndarray, size: int) -> np.ndarray:
    h, w = img.shape[:2]
    top = max(0, (h - size) // 2)
    left = max(0, (w - size) // 2)
    return img[top:top + size, left:left + size]


def resize_short_side(img: np.ndarray, size: int) -> np.ndarray:
    """Resize so the short side equals ``size``, preserving aspect ratio.

    Used by the eval path to upscale images smaller than the crop size —
    without it an undersized image would pass through ``center_crop``
    unchanged and later break batch collation with a ragged ``np.stack``.
    """
    from PIL import Image

    h, w = img.shape[:2]
    if min(h, w) == size:
        return img
    if img.dtype != np.uint8:
        raise ValueError(
            f"cannot resize non-uint8 image (dtype={img.dtype}, "
            f"shape={img.shape}); resize before converting")
    scale = size / min(h, w)
    nh = max(size, int(round(h * scale)))
    nw = max(size, int(round(w * scale)))
    return np.asarray(Image.fromarray(img).resize((nw, nh), Image.BILINEAR))


def random_flip(img: np.ndarray, rng: np.random.RandomState) -> np.ndarray:
    return img[:, ::-1] if rng.rand() < 0.5 else img


def random_sized_crop(img: np.ndarray, size: int,
                      rng: np.random.RandomState,
                      scale: Tuple[float, float] = (0.3, 1.0),
                      ratio: Tuple[float, float] = (3 / 4, 4 / 3)):
    """Inception-style random-area crop resized to ``size``×``size``
    (the reference era's GoogLeNet/ResNet train-time augmentation)."""
    from PIL import Image

    h, w = img.shape[:2]
    area = h * w
    for _ in range(10):
        target = area * rng.uniform(*scale)
        ar = np.exp(rng.uniform(np.log(ratio[0]), np.log(ratio[1])))
        cw = int(round(np.sqrt(target * ar)))
        ch = int(round(np.sqrt(target / ar)))
        if cw <= w and ch <= h:
            top = rng.randint(h - ch + 1)
            left = rng.randint(w - cw + 1)
            crop = img[top:top + ch, left:left + cw]
            break
    else:
        crop = center_crop(img, min(h, w))
    if crop.shape[:2] != (size, size):
        crop = np.asarray(
            Image.fromarray(crop).resize((size, size)), dtype=np.uint8)
    return crop


class Augment:
    """Composable train/eval transform: ``Augment(image_size, train=True)``.

    Train: random-sized crop (or pad-and-crop when ``pad`` given, the
    CIFAR recipe) + horizontal flip.  Eval: center crop.  Seeded per
    instance; every sample draw advances the stream.
    """

    def __init__(self, image_size: int, train: bool = True,
                 pad: Optional[int] = None, flip: bool = True,
                 seed: int = 0):
        self.image_size = image_size
        self.train = train
        self.pad = pad
        self.flip = flip
        self._rng = np.random.RandomState(seed)
        self._lock = threading.Lock()

    def __call__(self, sample):
        img, label = sample
        img = np.asarray(img)
        with self._lock:  # RandomState is not thread-safe
            seed = self._rng.randint(2 ** 31)
        rng = np.random.RandomState(seed)
        if self.train:
            if self.pad is not None:
                img = random_crop(img, self.image_size, rng, pad=self.pad)
            elif img.shape[0] != self.image_size or \
                    img.shape[1] != self.image_size:
                img = random_sized_crop(img, self.image_size, rng)
            if self.flip:
                img = random_flip(img, rng)
        else:
            if min(img.shape[0], img.shape[1]) < self.image_size:
                img = resize_short_side(img, self.image_size)
            if img.shape[0] != self.image_size or \
                    img.shape[1] != self.image_size:
                img = center_crop(img, self.image_size)
        return np.ascontiguousarray(img), label


# ImageNet channel statistics (uint8 scale) — the reference subtracted a
# mean image; per-channel mean/std is the modern equivalent.
IMAGENET_MEAN = (123.675, 116.28, 103.53)
IMAGENET_STD = (58.395, 57.12, 57.375)


def normalize_image(x, mean: Sequence[float] = IMAGENET_MEAN,
                    std: Sequence[float] = IMAGENET_STD, dtype=None):
    """Device-side uint8 -> float normalize — call at the head of the loss
    so the host ships uint8 and XLA fuses the cast into the first conv."""
    import jax.numpy as jnp

    dt = dtype or jnp.float32
    m = jnp.asarray(mean, dt).reshape((1,) * (x.ndim - 1) + (-1,))
    s = jnp.asarray(std, dt).reshape((1,) * (x.ndim - 1) + (-1,))
    return (x.astype(dt) - m) / s


class TransformDataset:
    """Apply a per-sample transform at access time (``dataset[i] ->
    transform(dataset[i])``) — the collation-friendly shape for EVAL
    iterators, which must rewind every epoch and therefore cannot sit
    behind a :class:`PrefetchIterator` (no reset)."""

    def __init__(self, dataset, transform: Callable):
        self.dataset = dataset
        self.transform = transform

    def __len__(self):
        return len(self.dataset)

    def __getitem__(self, i):
        return self.transform(self.dataset[i])


# ---------------------------------------------------------------------------
# prefetch
# ---------------------------------------------------------------------------

class PrefetchIterator:
    """Wrap a batch iterator; decode/augment/collate ahead in threads.

    ``inner`` yields batches of samples (what :class:`SerialIterator`
    produces: a collated tuple OR a list of per-sample tuples — both are
    handled).  ``transform`` is applied per SAMPLE in a thread pool.  Up
    to ``prefetch`` finished batches wait in a bounded queue, so the
    device step and the host input work overlap.

    The iterator protocol (``next``, ``epoch``, ``is_new_epoch``,
    ``epoch_detail``, ``iteration``) matches ``SerialIterator``; epoch
    state is captured with each produced batch and restored when that
    batch is CONSUMED, so trainer triggers fire at the right step even
    with look-ahead.  Call :meth:`close` (or let the training process
    exit — the threads are daemons) to shut down.
    """

    # Capability flag checked by training.extensions.Evaluator: the producer
    # thread cannot rewind, so eval loops must not wrap this iterator.
    rewindable = False

    def __init__(self, inner, transform: Optional[Callable] = None,
                 prefetch: int = 2, workers: int = 4):
        self.inner = inner
        self.transform = transform
        self._q: queue.Queue = queue.Queue(maxsize=max(1, prefetch))
        self._pool = ThreadPoolExecutor(max_workers=max(1, workers))
        self._stop = threading.Event()
        self.epoch = getattr(inner, "epoch", 0)
        self.is_new_epoch = False
        self.iteration = 0
        self._epoch_detail = float(self.epoch)
        self._producer = threading.Thread(target=self._produce, daemon=True)
        self._producer.start()

    # -- producer side ------------------------------------------------------
    def _prepare(self, batch):
        if isinstance(batch, tuple):          # collated arrays -> per-sample
            samples = list(zip(*batch))
        else:
            samples = list(batch)
        if self.transform is not None:
            samples = list(self._pool.map(self.transform, samples))
        first = samples[0]
        if isinstance(first, tuple):
            return tuple(np.stack([s[i] for s in samples])
                         for i in range(len(first)))
        return np.stack(samples)

    def _produce(self):
        try:
            while not self._stop.is_set():
                try:
                    batch = self.inner.next()
                except StopIteration:
                    self._q.put(("stop", None, None))
                    return
                meta = (getattr(self.inner, "epoch", 0),
                        getattr(self.inner, "is_new_epoch", False),
                        getattr(self.inner, "epoch_detail", 0.0))
                out = self._prepare(batch)
                self._q.put(("batch", out, meta))
        except Exception as e:  # surface worker errors at the consumer
            self._q.put(("error", e, None))

    # -- consumer side ------------------------------------------------------
    def __iter__(self):
        return self

    def __next__(self):
        kind, payload, meta = self._q.get()
        if kind == "stop":
            raise StopIteration
        if kind == "error":
            self.close()
            raise payload
        self.epoch, self.is_new_epoch, self._epoch_detail = meta
        self.iteration += 1
        return payload

    next = __next__

    @property
    def epoch_detail(self):
        return self._epoch_detail

    def reset(self):
        raise NotImplementedError(
            "PrefetchIterator cannot rewind its producer; create a new one")

    def close(self):
        self._stop.set()
        # drain so a blocked producer can observe the stop flag
        try:
            while True:
                self._q.get_nowait()
        except queue.Empty:
            pass
        self._pool.shutdown(wait=False)


__all__ = [
    "Augment",
    "IMAGENET_MEAN",
    "IMAGENET_STD",
    "ImageFolderDataset",
    "NpzImageDataset",
    "PrefetchIterator",
    "TransformDataset",
    "center_crop",
    "normalize_image",
    "random_crop",
    "random_flip",
    "random_sized_crop",
]
