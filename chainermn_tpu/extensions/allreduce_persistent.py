"""Persistent-value (BatchNorm statistics) synchronization.

Reference being rebuilt (path unverified, SURVEY.md provenance):
``AllreducePersistent`` in 〔chainermn/extensions/allreduce_persistent.py〕 —
a trainer extension that allreduce-averages *persistent* (non-gradient)
arrays, i.e. BatchNorm running mean/var, so rank-0 snapshots and evaluation
see consistent statistics.  The reference deliberately trains BatchNorm on
*local* statistics and only syncs here (SURVEY.md §7 hard part 5) — psum-ing
BN inside the step would silently change semantics, so this rebuild keeps
the same posture: ``batch_stats`` stay device-varying during training and
this extension folds them together on demand.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P


def allreduce_persistent(stacked_stats, communicator):
    """Average device-varying persistent state.

    ``stacked_stats`` leaves have a leading per-device axis of length
    ``comm.size`` (the layout the train step keeps ``batch_stats`` in).
    Returns the same stacked layout with every slice replaced by the mean —
    the reference's in-place allreduce of each persistent array.  Cross-host
    averaging rides the same psum (the mesh spans all hosts).
    """
    comm = communicator

    def body(s):
        mean = comm.allreduce(s, "mean")
        return mean

    out = comm.run_spmd(body, stacked_stats)
    return out


class AllreducePersistent:
    """Trainer-extension form (reference class name kept).

    ``state_getter(trainer) -> stacked batch_stats`` and
    ``state_setter(trainer, new_stats)`` adapt it to wherever the updater
    keeps model state.
    """

    priority = 70
    trigger = (1, "epoch")

    def __init__(self, communicator, state_getter, state_setter):
        self._comm = communicator
        self._get = state_getter
        self._set = state_setter

    def __call__(self, trainer):
        stats = self._get(trainer)
        if stats is None:
            return
        self._set(trainer, allreduce_persistent(stats, self._comm))
