from chainermn_tpu.extensions.multi_node_evaluator import (
    create_multi_node_evaluator,
    make_eval_fn,
)
from chainermn_tpu.extensions.allreduce_persistent import (
    AllreducePersistent,
    allreduce_persistent,
)
from chainermn_tpu.extensions.checkpoint import (
    consolidate_fsdp_checkpoint,
    create_multi_node_checkpointer,
)

__all__ = [
    "create_multi_node_evaluator",
    "make_eval_fn",
    "AllreducePersistent",
    "allreduce_persistent",
    "consolidate_fsdp_checkpoint",
    "create_multi_node_checkpointer",
]
