"""Distributed checkpointing with generation GC and auto-resume.

Reference being rebuilt (path unverified, SURVEY.md provenance):
``create_multi_node_checkpointer`` in 〔chainermn/extensions/checkpoint.py〕
— each rank saves its own state under a shared name/path, old generations
are garbage-collected, and on startup ``resume()`` restores all ranks from
the latest generation present on *every* rank (crash recovery for long
multi-node runs; the reference's only failure-recovery mechanism —
fail-stop + snapshot/resume, SURVEY.md §5.3, a posture this rebuild keeps).

TPU-native form: per-host npz files of the flattened state pytree
(``{path}/{name}.{iteration}.rank{r}.npz``); consistency of a generation is
agreed over the control plane (allgather of locally available generations,
intersect, take max).
"""

from __future__ import annotations

import json
import os
import re
import zipfile
from typing import Any, Dict, List, Optional, Set, Tuple

import jax
import numpy as np

from chainermn_tpu.utils.placement import local_device_put

# Sidecar keys persisted next to the leaf_{i} arrays in each npz: the
# FSDP sharding layout (world size + shard lengths) so a resume into a
# mismatched world fails loudly (ADVICE r5).  Underscored names cannot
# collide with leaf keys.
_FSDP_META_KEY = "__fsdp_meta__"
# Gradient-compression config of any error-feedback state in the tree
# (compressor specs + EF version): a resume under a different compressor
# would silently mis-scale the restored residuals.
_COMPRESSION_META_KEY = "__compression_meta__"
# Content hash + swap step of a hot-swapped plan table (the online
# tuner's step-boundary re-tune, planner/online.py): a resume that would
# silently execute a DIFFERENT plan than the run that saved must refuse
# — plan provenance is part of the run's performance contract.
_PLAN_TABLE_META_KEY = "__plan_table_meta__"


def _flatten_state(state) -> Tuple[dict, Any]:
    leaves, treedef = jax.tree.flatten(state)
    arrays = {f"leaf_{i}": np.asarray(jax.device_get(l))
              for i, l in enumerate(leaves)}
    return arrays, treedef


def _unflatten_state(arrays: dict, treedef, like_leaves: List[Any]):
    leaves = [arrays[f"leaf_{i}"] for i in range(len(like_leaves))]
    return jax.tree.unflatten(treedef, leaves)


def _place_like(new, old):
    """Place one restored host array with the LIVE leaf's sharding.
    Restores must never cross processes — every rank's npz holds what
    its own devices need — so this rides ``local_device_put`` (see
    utils/placement.py for the gloo interleaving hazard a plain
    ``jax.device_put`` carries on multi-controller meshes)."""
    shd = getattr(old, "sharding", None)
    if shd is None:
        return new
    return local_device_put(new, shd)


class _MultiNodeCheckpointer:
    def __init__(self, comm, path: str, name: str, keep: int = 2):
        self.comm = comm
        self.path = path
        self.name = name
        self.keep = keep
        os.makedirs(path, exist_ok=True)

    # -- naming --------------------------------------------------------------
    def _file(self, iteration: int, rank: Optional[int] = None) -> str:
        r = self.comm.rank if rank is None else rank
        return os.path.join(self.path,
                            f"{self.name}.{iteration}.rank{r}.npz")

    def _local_generations(self) -> List[int]:
        pat = re.compile(
            rf"^{re.escape(self.name)}\.(\d+)\.rank{self.comm.rank}\.npz$")
        gens = []
        for f in os.listdir(self.path):
            m = pat.match(f)
            if m:
                gens.append(int(m.group(1)))
        return sorted(gens)

    # -- save / GC -----------------------------------------------------------
    def _snapshot_arrays(self, state) -> dict:
        """Device->host copy plus sidecar capture — the only part of a
        save that must happen at the step boundary.  Returns the full
        npz payload (leaf arrays + layout/compression/plan-table
        sidecars); :meth:`_persist` can then write it from any thread
        (the async backend's split — elastic/async_ckpt.py)."""
        from chainermn_tpu.parallel.fsdp import fsdp_layout

        arrays, _ = _flatten_state(state)
        layout = fsdp_layout(state)
        if layout is not None:
            # persist the FsdpMeta-derived layout so resume() can
            # validate world size / mode before touching the arrays
            arrays[_FSDP_META_KEY] = np.array(json.dumps(layout))
        from chainermn_tpu.compression import compression_layout
        clayout = compression_layout(state)
        if clayout is not None:
            # ditto for error-feedback compression state (FSDP
            # bucket compressors or a compressed optimizer)
            arrays[_COMPRESSION_META_KEY] = np.array(
                json.dumps(clayout))
        from chainermn_tpu.planner.online import active_plan_table_meta
        tmeta = active_plan_table_meta()
        if tmeta is not None:
            # pin the hot-swapped plan table's hash so resume can
            # refuse a silently different plan (planner/online.py)
            arrays[_PLAN_TABLE_META_KEY] = np.array(json.dumps(tmeta))
        return arrays

    def _persist(self, arrays: dict, iteration: int):
        """Write + atomically publish one snapshot, then GC.  The GC
        runs strictly after ``os.replace`` — the write-barrier the async
        backend relies on: a generation can never be collected while the
        one superseding it is still a torn temp file."""
        # np.savez appends .npz when missing, so the temp name must
        # end in it
        tmp = self._file(iteration) + ".tmp.npz"
        np.savez(tmp, **arrays)
        os.replace(tmp, self._file(iteration))  # atomic publish
        self._gc()

    def save(self, state, iteration: int):
        from chainermn_tpu.observability import flight_recorder as _flight

        fr = _flight.get_flight_recorder()
        tok = None
        if fr is not None:
            tok = fr.span_begin("checkpoint", "checkpoint_save",
                                iteration=iteration)
        try:
            self._persist(self._snapshot_arrays(state), iteration)
        finally:
            if tok is not None:
                fr.span_end(tok)

    def _all_rank_generations(self) -> Dict[int, Set[int]]:
        """generation -> ranks with a published file, from one directory
        scan (all ranks, not just our own)."""
        pat = re.compile(
            rf"^{re.escape(self.name)}\.(\d+)\.rank(\d+)\.npz$")
        out: Dict[int, Set[int]] = {}
        for f in os.listdir(self.path):
            m = pat.match(f)
            if m:
                out.setdefault(int(m.group(1)), set()).add(int(m.group(2)))
        return out

    def _gc(self):
        """Collect generations past ``keep`` — but never one some rank
        in the *current* world still needs.  A crashed peer may be one
        or more generations behind: deleting our copy of the newest
        generation it shares with us would leave the world with no
        consistent generation at all.  So only generations strictly
        older than the newest generation *complete* across every rank
        visible in the directory (capped at ``comm.size`` — files from
        a larger pre-resize world don't pin anything) are collected.
        On per-host directories only our own rank is visible and this
        degrades to the plain keep-newest policy."""
        if not self.keep:
            return
        gens = self._local_generations()
        candidates = gens[:-self.keep]
        if not candidates:
            return
        by_gen = self._all_rank_generations()
        present: Set[int] = set()
        for ranks in by_gen.values():
            present |= {r for r in ranks if r < self.comm.size}
        complete = [g for g, ranks in by_gen.items()
                    if present and present <= ranks]
        newest_complete = max(complete) if complete else None
        for g in candidates:
            if newest_complete is not None and g >= newest_complete:
                # still (part of) the newest world-consistent
                # generation — a lagging peer resumes from here
                continue
            try:
                os.remove(self._file(g))
            except OSError:
                pass

    # -- resume --------------------------------------------------------------
    def _is_readable(self, fn: str) -> bool:
        """True when the npz at ``fn`` is a complete, CRC-clean zip.  A
        rank killed mid-write leaves its *previous* generation intact
        (the temp-rename publish), but a torn filesystem / truncated
        copy can still surface — such a file must not be offered as a
        resumable generation."""
        try:
            with zipfile.ZipFile(fn) as z:
                return z.testzip() is None
        except Exception:
            return False

    def latest_consistent_generation(self) -> Optional[int]:
        """Newest generation every rank holds a *readable* file for.
        Each rank CRC-checks its local candidates first (truncated or
        torn npz files are excluded before the vote), so one corrupted
        rank file degrades the answer to the previous complete
        generation instead of crashing the resume."""
        local = set(g for g in self._local_generations()
                    if self._is_readable(self._file(g)))
        all_gens = self.comm.allgather_obj(sorted(local))
        common = set(all_gens[0])
        for g in all_gens[1:]:
            common &= set(g)
        return max(common) if common else None

    def _validate_restore(self, arrays: dict, state, leaves, gen: int):
        """Refuse a world-size or sharding-mode mismatch BEFORE any leaf
        is restored (ADVICE r5: an FSDP checkpoint silently reloaded into
        a different world trains on garbage shards).  The supported
        cross-mode/cross-size path is exporting the full parameters with
        ``fsdp_full_params`` and re-sharding with ``fsdp_init``."""
        from chainermn_tpu.parallel.fsdp import fsdp_layout

        raw = arrays.pop(_FSDP_META_KEY, None)
        saved = json.loads(str(raw)) if raw is not None else None
        live = fsdp_layout(state)
        where = f"{self.name}.{gen} (rank {self.comm.rank})"
        if saved is not None and live is None:
            raise ValueError(
                f"checkpoint {where} holds an FSDP-sharded state "
                f"(world_size={saved['world_size']}) but the resume "
                f"target is unsharded — export full parameters via "
                f"fsdp_full_params(state, meta) before saving, or resume "
                f"into an FsdpState from fsdp_init on the same world")
        if saved is not None:
            if saved["world_size"] != self.comm.size:
                raise ValueError(
                    f"checkpoint {where} was saved with FSDP "
                    f"world_size={saved['world_size']} but this world has "
                    f"comm.size={self.comm.size}; shard layouts are bound "
                    f"to the world size — restore on a matching world, "
                    f"export with fsdp_full_params and re-shard with "
                    f"fsdp_init (the cross-size/cross-mode path), or, for "
                    f"inference, consolidate on the training world with "
                    f"consolidate_fsdp_checkpoint and load the full "
                    f"params with chainermn_tpu.serving.weights."
                    f"load_inference_params (world-size-free)")
            if "num_buckets" in saved \
                    and saved["num_buckets"] != live["num_buckets"]:
                raise ValueError(
                    f"checkpoint {where} was saved with "
                    f"num_buckets={saved['num_buckets']} but the live "
                    f"FsdpState was built with "
                    f"num_buckets={live['num_buckets']}; the bucketed "
                    f"shard layout is bound to the bucket config — pass "
                    f"the same num_buckets/bucket_bytes to fsdp_init "
                    f"before resuming, or export with fsdp_full_params "
                    f"and re-shard under the new config")
            if saved["shard_lens"] != live["shard_lens"]:
                raise ValueError(
                    f"checkpoint {where} shard layout "
                    f"{saved['shard_lens']} does not match the live "
                    f"FsdpState layout {live['shard_lens']} — the model "
                    f"or packing changed since the save")
        # Gradient-compression EF state: restoring residuals/scales saved
        # under a DIFFERENT compressor config would feed mis-scaled error
        # into every subsequent step — refuse with the fix spelled out
        # (mirrors the num_buckets guard above).
        from chainermn_tpu.compression import compression_layout
        raw_c = arrays.pop(_COMPRESSION_META_KEY, None)
        saved_c = json.loads(str(raw_c)) if raw_c is not None else None
        live_c = compression_layout(state)
        if saved_c is not None and live_c is None:
            raise ValueError(
                f"checkpoint {where} carries error-feedback compression "
                f"state for {saved_c['specs']} but the resume target has "
                f"no compression configured — rebuild with the same "
                f"compression config (fsdp_init(bucket_compressors=...) "
                f"/ create_multi_node_optimizer(compression=...)), or "
                f"restart training fresh to drop the EF state")
        if saved_c is None and live_c is not None:
            raise ValueError(
                f"checkpoint {where} has no compression state but the "
                f"resume target expects EF state for {live_c['specs']} — "
                f"resume into an uncompressed state and re-init, or save "
                f"from a compressed run; EF residuals cannot be "
                f"fabricated from an uncompressed checkpoint")
        if saved_c is not None and saved_c != live_c:
            raise ValueError(
                f"checkpoint {where} compression config {saved_c} does "
                f"not match the live config {live_c} — the EF residuals "
                f"and delayed scales are bound to the compressor spec; "
                f"pass the identical compression config, or restart "
                f"fresh under the new one")
        # Plan-table pin: a checkpoint saved after an online hot-swap is
        # bound to the swapped table's content hash — resuming without
        # it (or with a different one) would silently execute different
        # plans than the run that saved (ADVICE-r5 posture: fail loudly,
        # name the fix).
        from chainermn_tpu.planner.online import active_plan_table_meta
        raw_t = arrays.pop(_PLAN_TABLE_META_KEY, None)
        saved_t = json.loads(str(raw_t)) if raw_t is not None else None
        live_t = active_plan_table_meta()
        if saved_t is not None and live_t is None:
            raise ValueError(
                f"checkpoint {where} was saved after an online plan-table "
                f"hot-swap (table_hash={saved_t['table_hash']}, swap step "
                f"{saved_t['swap_step']}) but no active plan table is "
                f"registered in this process — reload the swapped table "
                f"(PlanTable.load) and register it with "
                f"planner.online.set_active_plan_table before resuming, "
                f"or, to deliberately discard the tuned plans, clear the "
                f"pin by resuming into a fresh run without the sidecar "
                f"(re-save after planner.online.clear_active_plan_table)")
        if saved_t is not None and \
                saved_t["table_hash"] != live_t["table_hash"]:
            raise ValueError(
                f"checkpoint {where} pins plan table "
                f"{saved_t['table_hash']} (hot-swapped at step "
                f"{saved_t['swap_step']}) but the active table is "
                f"{live_t['table_hash']} — the run would silently execute "
                f"different collective plans than the one that saved; "
                f"register the matching table via "
                f"planner.online.set_active_plan_table(PlanTable.load(...)) "
                f"or re-tune from scratch with "
                f"planner.online.clear_active_plan_table()")
        # Generic leaf-shape validation (also catches a legacy FSDP
        # checkpoint without the sidecar, or a plain checkpoint resumed
        # into an FSDP target): every mismatch beats a cryptic unflatten
        # or a silently mis-sharded device_put.
        n_saved = sum(1 for k in arrays if k.startswith("leaf_"))
        if n_saved != len(leaves):
            raise ValueError(
                f"checkpoint {where} has {n_saved} leaves but the resume "
                f"target has {len(leaves)} — the state structure changed "
                f"(sharded vs unsharded states do not interchange; "
                f"fsdp_full_params is the export path)")
        for i, leaf in enumerate(leaves):
            want = tuple(getattr(leaf, "shape", ()) or ())
            got = tuple(arrays[f"leaf_{i}"].shape)
            if want != got:
                raise ValueError(
                    f"checkpoint {where} leaf_{i} has shape {got} but the "
                    f"resume target expects {want} — likely a world-size "
                    f"or sharding-mode mismatch (see fsdp_full_params for "
                    f"the supported cross-mode export)")

    def resume(self, state):
        """Restore the latest consistent generation into ``state``'s
        structure.  Returns ``(state, iteration)``; ``iteration`` is None
        when nothing could be resumed (fresh start)."""
        from chainermn_tpu.observability import flight_recorder as _flight

        gen = self.latest_consistent_generation()
        if gen is None:
            return state, None
        fr = _flight.get_flight_recorder()
        tok = None
        if fr is not None:
            tok = fr.span_begin("checkpoint", "checkpoint_resume",
                                generation=gen)
        try:
            leaves, treedef = jax.tree.flatten(state)
            with np.load(self._file(gen)) as data:
                arrays = {k: data[k] for k in data.files}
            self._validate_restore(arrays, state, leaves, gen)
            restored = _unflatten_state(arrays, treedef, leaves)
            # preserve shardings of the live state (host-local placement;
            # see _place_like for why this must not cross processes)
            restored = jax.tree.map(_place_like, restored, state)
        finally:
            if tok is not None:
                fr.span_end(tok)
        return restored, gen

    def finalize(self):
        self.comm.barrier()


class _OrbaxCheckpointer:
    """Orbax-backed variant — the TPU-ecosystem checkpoint format.

    Same interface as :class:`_MultiNodeCheckpointer`, delegating
    atomicity, generation GC (``max_to_keep``) and sharded array
    save/restore to ``orbax.checkpoint.CheckpointManager``.  Restore
    places arrays with the LIVE state's shardings (StandardRestore over
    the abstract pytree), so resuming a sharded train state keeps its
    mesh placement without the manual device_put pass the npz path does.
    Multi-controller runs coordinate through orbax's own barriers (it
    expects ``jax.distributed`` to be initialized, which our bootstrap
    does); the control plane is not involved.
    """

    def __init__(self, comm, path: str, name: str, keep: int = 2):
        import orbax.checkpoint as ocp

        self.comm = comm
        self.name = name
        self._ocp = ocp
        # keep=0 -> max_to_keep=None: "retain every generation", matching
        # the npz backend's GC (which skips collection when keep is 0).
        self._mgr = ocp.CheckpointManager(
            os.path.abspath(os.path.join(path, name)),
            options=ocp.CheckpointManagerOptions(
                max_to_keep=keep or None, create=True))

    def save(self, state, iteration: int):
        self._mgr.save(iteration,
                       args=self._ocp.args.StandardSave(state))

    def latest_consistent_generation(self) -> Optional[int]:
        # orbax only publishes fully-committed generations, so "latest
        # present" is already the consistency the npz path negotiates
        return self._mgr.latest_step()

    def resume(self, state):
        gen = self.latest_consistent_generation()
        if gen is None:
            return state, None
        abstract = jax.tree.map(ocp_utils_to_abstract, state)
        restored = self._mgr.restore(
            gen, args=self._ocp.args.StandardRestore(abstract))
        return restored, gen

    def finalize(self):
        self._mgr.wait_until_finished()
        self.comm.barrier()


def ocp_utils_to_abstract(x):
    """Live array -> abstract (shape/dtype/sharding) leaf for restore."""
    if hasattr(x, "sharding") and hasattr(x, "dtype"):
        return jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=x.sharding)
    return x


def consolidate_fsdp_checkpoint(state, metas):
    """Consolidate every FSDP-sharded sub-state of a (restored) training
    state into its full replicated parameter pytree — the world-size-free
    export the serving weight loader consumes
    (:func:`chainermn_tpu.serving.weights.load_inference_params`).

    ``state`` is a training state tree (dicts/lists/tuples) holding one
    or more :class:`~chainermn_tpu.parallel.fsdp.FsdpState` nodes —
    typically the tree just restored by ``checkpointer.resume`` on the
    *training* world (shard layouts are bound to the world size; resume
    on a mismatched world refuses, naming this path).  ``metas`` is the
    matching :class:`~chainermn_tpu.parallel.fsdp.FsdpMeta` — or a
    sequence of them, one per FsdpState in ``iter_fsdp_states`` order.
    Returns the tree with each FsdpState replaced by its full parameter
    pytree (``fsdp_full_params`` — no collective needed); the optimizer
    inner state and any error-feedback compression state are dropped
    (inference has no use for either).
    """
    from chainermn_tpu.parallel.fsdp import (FsdpMeta, FsdpState,
                                             fsdp_full_params,
                                             iter_fsdp_states)

    metas = [metas] if isinstance(metas, FsdpMeta) else list(metas)
    n_states = sum(1 for _ in iter_fsdp_states(state))
    if n_states != len(metas):
        raise ValueError(
            f"state tree holds {n_states} FsdpState(s) but {len(metas)} "
            f"FsdpMeta(s) were given — pass one meta per sharded "
            f"sub-state, in iter_fsdp_states order")
    it = iter(metas)

    def walk(node):
        if isinstance(node, FsdpState):
            return fsdp_full_params(node, next(it))
        if isinstance(node, dict):
            return {k: walk(v) for k, v in node.items()}
        if isinstance(node, tuple) and not hasattr(node, "_fields"):
            return tuple(walk(v) for v in node)
        if isinstance(node, list):
            return [walk(v) for v in node]
        return node

    return walk(state)


def create_multi_node_checkpointer(communicator, path: str,
                                   name: str = "snapshot", keep: int = 2,
                                   backend: str = "npz"):
    """Reference signature: ``create_multi_node_checkpointer(name, comm,
    path=...)`` 〔extensions/checkpoint.py〕.  ``backend="npz"`` (default)
    is the self-contained per-rank format; ``backend="orbax"`` delegates
    to the TPU ecosystem's checkpoint library (sharded arrays, async
    commit protocol, same save/resume/GC interface).
    ``backend="async"`` wraps the npz format in the elastic runtime's
    background-persist thread (:class:`chainermn_tpu.elastic.
    AsyncCheckpointer`): ``save`` only pays the device->host snapshot at
    the step boundary and the npz write happens off the critical path
    (``async_ckpt_stall_ms`` in docs/elasticity.md).

    ``keep`` retains the newest *keep* generations in both backends;
    ``keep=0`` disables garbage collection entirely (every generation is
    kept forever — both backends agree on this reading).
    """
    if keep < 0:
        raise ValueError(f"keep must be >= 0 (got {keep}); "
                         f"0 means retain every generation")
    if backend == "orbax":
        return _OrbaxCheckpointer(communicator, path, name, keep)
    if backend == "async":
        from chainermn_tpu.elastic.async_ckpt import AsyncCheckpointer
        return AsyncCheckpointer(
            _MultiNodeCheckpointer(communicator, path, name, keep))
    if backend != "npz":
        raise ValueError(f"unknown checkpoint backend {backend!r} "
                         "(expected 'npz', 'async' or 'orbax')")
    return _MultiNodeCheckpointer(communicator, path, name, keep)
