"""Multi-node evaluator.

Reference being rebuilt (path unverified, SURVEY.md provenance):
``create_multi_node_evaluator(evaluator, comm)`` in
〔chainermn/extensions/__init__.py〕 — dynamically subclasses the wrapped
evaluator so ``evaluate()`` runs on the local validation shard and then
**allreduce-averages the observation dict** across ranks; every rank reports
global validation metrics.

Two aggregation levels here, matching the two-level world:

* device level — :func:`make_eval_fn` builds a jitted SPMD eval step whose
  metrics are psum-averaged over the mesh (each device evaluates its shard
  of the batch);
* host level — :func:`create_multi_node_evaluator` wraps an evaluator so the
  per-host result dict is mean-reduced over the DCN control plane (the
  reference's observation-dict allreduce).
"""

from __future__ import annotations

from typing import Callable

import jax

from chainermn_tpu.utils import shard_map as _shard_map
from jax.sharding import PartitionSpec as P


def make_eval_fn(communicator, metrics_fn: Callable,
                 with_model_state: bool = False):
    """Jitted SPMD evaluation step.

    ``metrics_fn(params, local_batch) -> dict of scalars`` runs per device on
    its batch shard; the returned dict is psum-averaged across the mesh.

    ``with_model_state=True`` adds a device-local mutable-state slot
    (flax ``batch_stats`` — stacked [size, ...] like the training step's,
    see ``init_model_state``): ``metrics_fn(params, state, batch)``; each
    device evaluates with ITS running statistics, the reference's
    local-BN posture (sync beforehand with ``AllreducePersistent`` when a
    globally-consistent eval is wanted).
    """
    comm = communicator

    if with_model_state:
        def eval_step(params, state, batch):
            state = jax.tree.map(lambda a: a.squeeze(0), state)
            m = metrics_fn(params, state, batch)
            return comm.allreduce(m, "mean")

        mapped = _shard_map(
            eval_step, mesh=comm.mesh,
            in_specs=(P(), P(comm.data_axes), P(comm.data_axes)),
            out_specs=P())
        return jax.jit(mapped)

    def eval_step(params, batch):
        m = metrics_fn(params, batch)
        return comm.allreduce(m, "mean")

    mapped = _shard_map(
        eval_step, mesh=comm.mesh,
        in_specs=(P(), P(comm.data_axes)), out_specs=P())
    return jax.jit(mapped)


def create_multi_node_evaluator(actual_evaluator, communicator):
    """Wrap an evaluator so ``evaluate()`` returns globally averaged metrics.

    The wrapped object keeps its class behavior (the reference does this by
    dynamic subclassing; here we subclass at runtime the same way) — only
    ``evaluate`` is overridden to allreduce the result dict across hosts.
    """
    comm = communicator
    base = type(actual_evaluator)

    class _MultiNodeEvaluator(base):
        def evaluate(self, *args, **kwargs):
            local = base.evaluate(self, *args, **kwargs)
            summed = comm.allreduce_obj(local, op="sum")
            return {k: v / comm.host_size for k, v in summed.items()}

    actual_evaluator.__class__ = _MultiNodeEvaluator
    return actual_evaluator
