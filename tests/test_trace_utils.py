"""Device-trace analysis helpers (the profiling addition, SURVEY §5.1)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from chainermn_tpu.utils.trace import device_op_times, device_time, top_ops


def test_device_time_and_op_tables(tmp_path):
    """Capture a real trace of a jitted op; the parsers see device ops and
    device_time returns a positive per-call figure."""
    backend = jax.default_backend()
    device = f"/device:{backend.upper()}:0"
    f = jax.jit(lambda x: (x @ x).sum())
    x = jnp.ones((256, 256), jnp.float32)

    ms = device_time(f, (x,), steps=3, warmup=1,
                     trace_dir=str(tmp_path), device=device)
    assert ms >= 0.0

    times = device_op_times(str(tmp_path), device=device)
    assert isinstance(times, dict)
    rows = top_ops(str(tmp_path), n=5, device=device)
    assert all(len(r) == 3 for r in rows)
    cats = top_ops(str(tmp_path), n=5, by_category=True, device=device)
    # categories strip trailing .N so they are never finer-grained
    assert len(cats) <= max(len(rows), 5)


def test_missing_trace_raises(tmp_path):
    with pytest.raises(FileNotFoundError, match="no trace"):
        device_op_times(str(tmp_path / "nothing"))
