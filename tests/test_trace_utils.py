"""Device-trace analysis helpers (the profiling addition, SURVEY §5.1)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from chainermn_tpu.utils.trace import device_op_times, device_time, top_ops


def test_device_time_and_op_tables(tmp_path):
    """Capture a real trace of a jitted op; the parsers see device ops and
    device_time returns a positive per-call figure."""
    backend = jax.default_backend()
    device = f"/device:{backend.upper()}:0"
    f = jax.jit(lambda x: (x @ x).sum())
    x = jnp.ones((256, 256), jnp.float32)

    ms = device_time(f, (x,), steps=3, warmup=1,
                     trace_dir=str(tmp_path), device=device)
    assert ms >= 0.0

    times = device_op_times(str(tmp_path), device=device)
    assert isinstance(times, dict)
    rows = top_ops(str(tmp_path), n=5, device=device)
    assert all(len(r) == 3 for r in rows)
    cats = top_ops(str(tmp_path), n=5, by_category=True, device=device)
    # categories strip trailing .N so they are never finer-grained
    assert len(cats) <= max(len(rows), 5)


def test_missing_trace_raises(tmp_path):
    with pytest.raises(FileNotFoundError, match="no trace"):
        device_op_times(str(tmp_path / "nothing"))


def _write_synthetic_trace(trace_dir, tracks, events):
    """Minimal profiler-shaped capture: process_name metadata + complete
    events, gzipped where _load_trace expects it."""
    import gzip
    import json
    import os

    d = os.path.join(str(trace_dir), "plugins", "profile", "run")
    os.makedirs(d, exist_ok=True)
    trace_events = [
        {"ph": "M", "name": "process_name", "pid": pid,
         "args": {"name": name}}
        for pid, name in tracks.items()
    ]
    trace_events += [
        {"ph": "X", "pid": pid, "name": name, "dur": dur_us, "ts": 0}
        for pid, name, dur_us in events
    ]
    path = os.path.join(d, "host.trace.json.gz")
    with gzip.open(path, "wt") as f:
        json.dump({"traceEvents": trace_events}, f)


def test_device_autodetect_prefers_tpu_track(tmp_path):
    """device=None picks the TPU track when present — only its events are
    summed, not the CPU track's or the host threads'."""
    _write_synthetic_trace(
        tmp_path,
        tracks={1: "/device:TPU:0", 2: "/device:CPU:0", 3: "python"},
        events=[(1, "fusion.1", 2000), (1, "all-reduce", 1000),
                (2, "cpu-op", 9000), (3, "host-thing", 500)])
    times = device_op_times(str(tmp_path))  # no device argument
    assert set(times) == {"fusion.1", "all-reduce"}
    assert times["fusion.1"] == (2.0, 1)
    rows = top_ops(str(tmp_path), n=5)
    assert rows[0][0] == "fusion.1"


def test_device_autodetect_falls_back_to_first_device_track(tmp_path):
    """No TPU track (a CPU-mesh capture): the first /device: process is
    used instead of silently summing zero events."""
    _write_synthetic_trace(
        tmp_path,
        tracks={7: "/device:CPU:0", 8: "python"},
        events=[(7, "cpu-op", 4000), (8, "host-thing", 500)])
    times = device_op_times(str(tmp_path))
    assert times == {"cpu-op": (4.0, 1)}


def test_device_autodetect_no_device_track_raises(tmp_path):
    _write_synthetic_trace(tmp_path, tracks={3: "python"},
                           events=[(3, "host-thing", 500)])
    with pytest.raises(ValueError, match="no /device: track"):
        device_op_times(str(tmp_path))
