"""True multi-process tests: 2 controller processes over the DCN control
plane — the rebuild's "mpiexec -n 2 pytest" analogue (SURVEY.md §4: the
reference ran its suite under a real launcher; here two real processes
bootstrap via the coordinator env contract, no launcher).

Each subprocess runs `_worker_main` below with CHAINERMN_TPU_COORDINATOR /
_NUM_PROCESSES / _PROCESS_ID set; the parent asserts on their outputs.
"""

import json
import os
import socket
import subprocess
import sys

import pytest

_WORKER = r"""
import json, os, sys
sys.path.insert(0, os.environ["CHAINERMN_TPU_REPO"])
from chainermn_tpu.runtime.control_plane import get_control_plane

cp = get_control_plane()
rank, size = cp.rank, cp.size
out = {}

# object plane collectives
out["bcast"] = cp.bcast_obj({"seed": 123} if rank == 0 else None, root=0)
out["allreduce"] = cp.allreduce_obj(rank + 1, op="sum")
out["allgather"] = cp.allgather_obj(f"host{rank}")
cp.barrier()

# dataset scatter across real processes (host-level shard per process);
# a minimal comm facade supplies the attrs scatter_dataset reads
from chainermn_tpu.datasets.scatter_dataset import scatter_dataset
import numpy as np


class _CommFacade:
    rank = rank
    host_size = size

    @staticmethod
    def bcast_obj(obj, root=0):
        return cp.bcast_obj(obj, root=root)


shard = scatter_dataset(np.arange(10), _CommFacade(), shuffle=True, seed=7)
out["shard"] = [int(shard[i]) for i in range(len(shard))]

print("RESULT " + json.dumps(out))
"""


from chainermn_tpu.utils.proc_world import free_port as _free_port


@pytest.mark.parametrize("force_py", ["0", "1"],
                         ids=["native", "pure_python"])
def test_two_process_control_plane(tmp_path, force_py):
    coord = f"127.0.0.1:{_free_port()}"
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    procs = []
    for r in range(2):
        env = dict(os.environ)
        env.update({
            "CHAINERMN_TPU_COORDINATOR": coord,
            "CHAINERMN_TPU_NUM_PROCESSES": "2",
            "CHAINERMN_TPU_PROCESS_ID": str(r),
            "CHAINERMN_TPU_REPO": repo,
            "CHAINERMN_TPU_PURE_PY_TRANSPORT": force_py,
            "JAX_PLATFORMS": "cpu",
        })
        procs.append(subprocess.Popen(
            [sys.executable, "-c", _WORKER], env=env,
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True))
    results = {}
    for r, p in enumerate(procs):
        stdout, stderr = p.communicate(timeout=120)
        assert p.returncode == 0, f"rank {r} failed:\n{stderr}\n{stdout}"
        line = [l for l in stdout.splitlines() if l.startswith("RESULT ")]
        assert line, stdout
        results[r] = json.loads(line[0][len("RESULT "):])

    for r in range(2):
        assert results[r]["bcast"] == {"seed": 123}
        assert results[r]["allreduce"] == 3
        assert results[r]["allgather"] == ["host0", "host1"]
    # the two shards partition the (root-seeded, shuffled) index space
    all_idx = results[0]["shard"] + results[1]["shard"]
    assert sorted(all_idx) == sorted(set(all_idx))
    assert set(all_idx) == set(range(10))
    # same seed => both processes agreed on the same permutation
    assert results[0]["shard"] != list(range(5))  # actually shuffled (seed 7)


_TREE_WORKER = r"""
import json, os, sys
sys.path.insert(0, os.environ["CHAINERMN_TPU_REPO"])
import numpy as np
from chainermn_tpu.runtime.control_plane import get_control_plane

cp = get_control_plane()
rank, size = cp.rank, cp.size
out = {}
# binomial-tree collectives over REAL sockets, non-power-of-two world,
# non-zero root, structural + custom ops
out["bcast"] = cp.bcast_obj([rank, "payload"] if rank == 1 else None, root=1)
out["gather"] = cp.gather_obj(rank * 10, root=1)
out["scatter"] = cp.scatter_obj(
    [f"item{i}" for i in range(size)] if rank == 1 else None, root=1)
out["prod"] = cp.allreduce_obj(rank + 2, op="prod")
out["maxdict"] = cp.allreduce_obj({"a": rank, "b": [float(rank)]}, op="max")
out["union"] = sorted(cp.allreduce_obj({rank}, op=lambda a, b: a | b))
arr = cp.allreduce_obj(np.full(3, rank + 1.0))
out["arrsum"] = [float(v) for v in arr]
cp.barrier()
print("RESULT " + json.dumps(out))
"""


def test_three_process_tree_collectives(tmp_path):
    """Binomial-tree object collectives across 3 REAL processes (odd world,
    root != 0, custom/structural reduce ops, ndarray payloads)."""
    coord = f"127.0.0.1:{_free_port()}"
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    n = 3
    procs = []
    for r in range(n):
        env = dict(os.environ)
        env.update({
            "CHAINERMN_TPU_COORDINATOR": coord,
            "CHAINERMN_TPU_NUM_PROCESSES": str(n),
            "CHAINERMN_TPU_PROCESS_ID": str(r),
            "CHAINERMN_TPU_REPO": repo,
            "JAX_PLATFORMS": "cpu",
        })
        procs.append(subprocess.Popen(
            [sys.executable, "-c", _TREE_WORKER], env=env,
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True))
    results = {}
    for r, p in enumerate(procs):
        stdout, stderr = p.communicate(timeout=120)
        assert p.returncode == 0, f"rank {r} failed:\n{stderr}\n{stdout}"
        line = [l for l in stdout.splitlines() if l.startswith("RESULT ")]
        assert line, stdout
        results[r] = json.loads(line[0][len("RESULT "):])

    for r in range(n):
        assert results[r]["bcast"] == [1, "payload"]
        assert results[r]["scatter"] == f"item{r}"
        assert results[r]["prod"] == 2 * 3 * 4
        assert results[r]["maxdict"] == {"a": 2, "b": [2.0]}
        assert results[r]["union"] == [0, 1, 2]
        assert results[r]["arrsum"] == [6.0, 6.0, 6.0]
    assert results[1]["gather"] == [0, 10, 20]
    assert results[0]["gather"] is None and results[2]["gather"] is None


_BIG_OBJ_WORKER = r"""
import json, os, sys
sys.path.insert(0, os.environ["CHAINERMN_TPU_REPO"])
import numpy as np
from chainermn_tpu.runtime.control_plane import get_control_plane

cp = get_control_plane()
rank, size = cp.rank, cp.size

# ~12 MB bcast + 4 MB-per-rank scatter, under a 2 MiB inbox budget set by
# the parent: every frame is oversize relative to the budget (admitted
# one at a time), so buffering must stay ~ one frame, not the whole
# conversation.
big = cp.bcast_obj(np.arange(3 << 20, dtype=np.int32) if rank == 0
                   else None, root=0)
items = ([np.full(4 << 20, r, np.uint8) for r in range(size)]
         if rank == 0 else None)
mine = cp.scatter_obj(items, root=0)
cp.barrier()
out = {
    "bcast_ok": bool(big.shape == (3 << 20,) and int(big[-1]) == (3 << 20) - 1),
    "scatter_ok": bool(mine.shape == (4 << 20,) and int(mine[0]) == rank
                       and int(mine[-1]) == rank),
    "peak_inbox": int(cp._tp.peak_inbox_bytes),
}
print("RESULT " + json.dumps(out))
"""


def test_three_process_large_objects_bounded_inbox(monkeypatch):
    """scatter_dataset-scale objects through the object plane (VERDICT r3
    missing #3's consumer path): a 12 MB bcast and 4 MB/rank scatter
    across 3 real processes under a 2 MiB inbox budget — contents intact
    and receive-side buffering bounded at ~budget + one frame."""
    from chainermn_tpu.utils.proc_world import spawn_world

    hwm = 2 << 20
    n = 3
    # spawn_world snapshots os.environ, so the budget propagates to the
    # children; spawn_world also owns crash surfacing + orphan cleanup.
    monkeypatch.setenv("CHAINERMN_TPU_INBOX_HWM", str(hwm))
    results = spawn_world(_BIG_OBJ_WORKER, n_procs=n, local_devices=1,
                          timeout=180)
    for r in range(n):
        assert results[r]["bcast_ok"] and results[r]["scatter_ok"], results[r]
    # Largest single frame: the 12 MB bcast payload.  The bound must stay
    # BELOW the ~16.2 MiB total a non-root rank receives (12 MiB bcast +
    # 4 MiB scatter) or it could never fail; budget + one frame (~14.1
    # MiB) discriminates bounded buffering from unbounded buildup.
    frame = (12 << 20) + (1 << 16)
    for r in range(1, n):
        assert results[r]["peak_inbox"] <= hwm + frame, results[r]
