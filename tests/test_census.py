"""Collective-census regression gate.

The seven communicator flavors are defined by their collective
decompositions (SURVEY.md §2.1 — the decomposition IS the flavor).  The
round-4 judge ('next #5') asked for the docs/performance.md census table
to be re-verified per round by command, not per doc edit: these tests pin
the structure of each flavor's compiled allreduce_grad HLO on the
8-device virtual mesh, and ``bench_allreduce.py --census`` emits the same
parse as a committed JSON artifact (CENSUS_r05.json).

Both this gate and the artifact now read collectives through the ONE
shared parser, :mod:`chainermn_tpu.analysis.hlo` (they used to carry
duplicate regexes in benchmarks/ that could drift apart), and the
expected kind sequences come from the same table the ``census-drift``
lint rule enforces.
"""

import os
import sys

import jax
import jax.numpy as jnp
import pytest

import chainermn_tpu
from chainermn_tpu.analysis import collective_census, expected_kinds

N_ELEMS = 1000  # ~4 KB fp32 — census is about structure, not size


def _ops_for(name, **kwargs):
    comm = chainermn_tpu.create_communicator(name, **kwargs)
    stacked = jnp.tile(
        jnp.arange(comm.size, dtype="float32").reshape(comm.size, 1),
        (1, N_ELEMS))

    def body(g):
        return comm.allreduce_grad(g)

    return collective_census(comm.compiled_hlo(body, stacked))


@pytest.mark.parametrize("name", ["naive", "flat", "xla", "non_cuda_aware"])
def test_single_allreduce_flavors(name, devices):
    """Flat-family flavors compile to exactly ONE all-reduce over all 8
    devices (XLA's combiner merges naive's per-leaf psums by itself)."""
    ops = _ops_for(name)
    assert tuple(o["op"] for o in ops) == expected_kinds(name), ops
    assert [o["op"] for o in ops] == ["all-reduce"], ops
    assert "{0,1,2,3,4,5,6,7}" in ops[0]["groups"], ops


def test_hierarchical_two_level(devices):
    """hierarchical = AR over the intra (ICI) axis then AR over the inter
    (DCN) axis — two collectives, full buffer each."""
    ops = _ops_for("hierarchical", intra_size=4)
    assert tuple(o["op"] for o in ops) == expected_kinds(
        "hierarchical", inter_size=2), ops
    groups = [o["groups"] for o in ops]
    assert any("{0,1,2,3}" in g for g in groups), groups   # intra leg
    assert any("{0,4}" in g for g in groups), groups       # inter leg


def test_two_dimensional_scatter_small_inter_leg(devices):
    """two_dimensional = reduce-scatter(intra) + AR(inter) on the G/intra
    shard + gather-back.  The inter (DCN) leg carrying only G/intra_size
    is the property that justifies the flavor's existence."""
    ops = _ops_for("two_dimensional", intra_size=4)
    kinds = tuple(o["op"] for o in ops)
    assert kinds == expected_kinds("two_dimensional", inter_size=2), ops
    assert kinds == ("reduce-scatter", "all-reduce", "all-reduce"), ops
    full = max(o["bytes"] for o in ops)
    inter = [o for o in ops if o["op"] == "all-reduce"
             and "{0,4}" in (o["groups"] or "")]
    assert inter, ops
    # the inter leg moves ~G/intra_size, not G (pad slop allowed)
    assert inter[0]["bytes"] <= full / 4 + 64, (inter, full)


def test_hand_written_table_cross_check(devices):
    """One-time cross-check of the RETIRED hand-written census table.

    ``expected_kinds`` used to be this per-flavor lookup, maintained by
    hand in ``analysis/rules.py``; it is now DERIVED from the flavor's
    plan (``planner.plans.flavor_plan`` compiled statically through
    ``planner.compiler.plan_census_kinds``).  This test embeds the old
    table one last time and pins two facts:

    1. On every configuration the old gate actually exercised
       (flat family and single_node at any inter; hierarchical and
       two_dimensional at ``inter >= 2``), the derived census agrees
       exactly — the refactor changed the source of truth, not the spec.
    2. At ``inter == 1`` the old hierarchical/two_dimensional branches
       CONTRADICT compiled reality: XLA does not elide singleton-group
       collectives, so the inter leg still compiles (single_node's
       comment even said so).  The derived census is checked against the
       compiled HLO here — the hand-written branches were simply wrong,
       which is why the table is derived now.
    """
    old_table = {
        "naive": lambda inter: ("all-reduce",),
        "flat": lambda inter: ("all-reduce",),
        "xla": lambda inter: ("all-reduce",),
        "pure_nccl": lambda inter: ("all-reduce",),
        "non_cuda_aware": lambda inter: ("all-reduce",),
        "single_node": lambda inter: ("all-reduce", "all-reduce"),
        "hierarchical": lambda inter: (
            ("all-reduce", "all-reduce") if inter > 1
            else ("all-reduce",)),
        "two_dimensional": lambda inter: (
            ("reduce-scatter", "all-reduce", "all-reduce") if inter > 1
            else ("reduce-scatter", "all-reduce")),
    }
    # 1. agreement wherever the old gate ran
    for flavor in ("naive", "flat", "xla", "pure_nccl", "non_cuda_aware",
                   "single_node"):
        for inter in (1, 2, 4):
            assert expected_kinds(flavor, inter) == \
                old_table[flavor](inter), (flavor, inter)
    for flavor in ("hierarchical", "two_dimensional"):
        for inter in (2, 4):
            assert expected_kinds(flavor, inter) == \
                old_table[flavor](inter), (flavor, inter)
    # 2. the inter == 1 divergence, settled by the compiler
    assert old_table["hierarchical"](1) != expected_kinds(
        "hierarchical", 1)
    ops = _ops_for("hierarchical", intra_size=8)   # inter leg of size 1
    assert tuple(o["op"] for o in ops) == \
        expected_kinds("hierarchical", 1) == \
        ("all-reduce", "all-reduce"), ops
    ops = _ops_for("two_dimensional", intra_size=8)
    assert tuple(o["op"] for o in ops) == \
        expected_kinds("two_dimensional", 1) == \
        ("reduce-scatter", "all-reduce", "all-reduce"), ops


def test_bench_census_delegates_to_shared_parser(devices):
    """``bench_allreduce._collective_ops`` (the artifact writer) is the
    shared analysis parser — same records, byte for byte, so the gate and
    the committed CENSUS artifact cannot drift apart again."""
    sys.path.insert(0, os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "benchmarks"))
    from bench_allreduce import _collective_ops

    comm = chainermn_tpu.create_communicator("xla")
    stacked = jnp.zeros((comm.size, N_ELEMS), "float32")
    hlo = comm.compiled_hlo(lambda g: comm.allreduce_grad(g), stacked)
    assert _collective_ops(hlo) == collective_census(hlo)
    assert all(set(o) >= {"op", "bytes", "groups"}
               for o in _collective_ops(hlo))


def test_census_artifact_matches_live_parse(devices):
    """The committed CENSUS artifact (when present) agrees with a live
    census of the same flavors at the same payload — the artifact cannot
    silently rot."""
    import glob
    import json

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    paths = sorted(glob.glob(os.path.join(repo, "CENSUS_r*.json")))
    if not paths:
        pytest.skip("no committed census artifact yet")
    with open(paths[-1]) as f:
        committed = json.load(f)
    if committed.get("n_devices") != jax.device_count():
        pytest.skip("artifact from a different world size")
    for name, entry in committed["flavors"].items():
        if "skipped" in entry:
            continue
        kwargs = {}
        if committed.get("intra_size"):
            kwargs["intra_size"] = committed["intra_size"]
        n_elems = int(committed["payload_mib"] * (1 << 20) / 4)
        comm = chainermn_tpu.create_communicator(name, **kwargs)
        stacked = jnp.tile(
            jnp.arange(comm.size, dtype="float32").reshape(comm.size, 1),
            (1, n_elems))

        def body(g, comm=comm):
            return comm.allreduce_grad(g)

        live = collective_census(comm.compiled_hlo(body, stacked))
        want = [(o["op"], o["groups"]) for o in entry["collectives"]]
        got = [(o["op"], o["groups"]) for o in live]
        assert got == want, (name, got, want)
