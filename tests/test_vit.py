"""ViT model-family tests — structure, dtype policy, pooling, attention
impls, and the full multi-node train-step path at tiny widths on the CPU
mesh (same strategy as test_models.py; the model is a beyond-reference
extension, see chainermn_tpu/models/vit.py)."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

import chainermn_tpu
from chainermn_tpu.models import ViT, ViT_B16, ViT_S16
from chainermn_tpu.optimizers import init_opt_state, make_train_step
from chainermn_tpu.training import put_global_batch

TinyViT = lambda **kw: ViT(num_classes=5, patch=8, d_model=32, n_layers=2,
                           n_heads=4, **kw)


@pytest.fixture
def comm():
    return chainermn_tpu.create_communicator("flat")


class TestStructure:
    def test_vit_b16_param_count(self):
        # init on 64x64 to keep CPU time down: parameter count differs from
        # the 224-image model only in pos_embed (17 vs 197 rows)
        model = ViT_B16(num_classes=1000)
        variables = model.init(jax.random.key(0), jnp.zeros((1, 64, 64, 3)))
        n = sum(x.size for x in jax.tree.leaves(variables["params"]))
        assert 80e6 < n < 92e6, f"ViT-B/16 should have ~86M params, got {n}"

    def test_vit_s16_param_count(self):
        model = ViT_S16(num_classes=1000)
        variables = model.init(jax.random.key(0), jnp.zeros((1, 64, 64, 3)))
        n = sum(x.size for x in jax.tree.leaves(variables["params"]))
        assert 19e6 < n < 24e6, f"ViT-S/16 should have ~22M params, got {n}"

    def test_forward_shape_and_dtype(self):
        model = TinyViT()
        variables = model.init(jax.random.key(0), jnp.zeros((2, 32, 32, 3)))
        logits = model.apply(variables, jnp.ones((2, 32, 32, 3)))
        assert logits.shape == (2, 5)
        assert logits.dtype == jnp.float32

    def test_bf16_compute_fp32_params(self):
        model = TinyViT(dtype=jnp.bfloat16)
        variables = model.init(jax.random.key(0), jnp.zeros((2, 32, 32, 3)))
        for leaf in jax.tree.leaves(variables["params"]):
            assert leaf.dtype == jnp.float32
        logits = model.apply(variables, jnp.ones((2, 32, 32, 3)))
        assert logits.dtype == jnp.float32

    def test_gap_pooling(self):
        model = TinyViT(pooling="gap")
        variables = model.init(jax.random.key(0), jnp.zeros((2, 32, 32, 3)))
        # no cls token parameter in the gap variant
        assert "cls_token" not in variables["params"]
        assert variables["params"]["pos_embed"].shape == (1, 16, 32)
        logits = model.apply(variables, jnp.ones((2, 32, 32, 3)))
        assert logits.shape == (2, 5)

    def test_bad_config_raises(self):
        with pytest.raises(ValueError, match="must divide"):
            ViT(num_classes=5, patch=8, d_model=32, n_layers=2,
                n_heads=5).init(jax.random.key(0), jnp.zeros((1, 32, 32, 3)))
        with pytest.raises(ValueError, match="multiple"):
            TinyViT().init(jax.random.key(0), jnp.zeros((1, 30, 30, 3)))
        with pytest.raises(ValueError, match="pooling"):
            TinyViT(pooling="max").init(jax.random.key(0),
                                        jnp.zeros((1, 32, 32, 3)))

    def test_dropout_train_vs_eval(self):
        model = TinyViT(dropout=0.5)
        variables = model.init(
            {"params": jax.random.key(0), "dropout": jax.random.key(1)},
            jnp.zeros((2, 32, 32, 3)), train=True)
        x = jnp.ones((2, 32, 32, 3))
        # eval is deterministic and needs no rng
        e1 = model.apply(variables, x, train=False)
        e2 = model.apply(variables, x, train=False)
        np.testing.assert_array_equal(np.asarray(e1), np.asarray(e2))
        # train with different dropout keys differs
        t1 = model.apply(variables, x, train=True,
                         rngs={"dropout": jax.random.key(2)})
        t2 = model.apply(variables, x, train=True,
                         rngs={"dropout": jax.random.key(3)})
        assert not np.allclose(np.asarray(t1), np.asarray(t2))


class TestAttentionImpls:
    def test_flash_matches_xla(self):
        # same params, both impls: logits agree (flash runs in Pallas
        # interpret mode on the CPU backend — same code path as TPU)
        mx = TinyViT(attention_impl="xla")
        mf = TinyViT(attention_impl="flash")
        variables = mx.init(jax.random.key(0), jnp.zeros((2, 32, 32, 3)))
        x = jax.random.normal(jax.random.key(1), (2, 32, 32, 3))
        yx = mx.apply(variables, x)
        yf = mf.apply(variables, x)
        np.testing.assert_allclose(np.asarray(yx), np.asarray(yf),
                                   rtol=2e-4, atol=2e-4)


class TestTrainStep:
    def test_loss_decreases_multi_node(self, comm):
        model = TinyViT()
        variables = model.init(jax.random.key(0), jnp.zeros((1, 32, 32, 3)))
        params = comm.bcast_data(variables["params"])
        optimizer = chainermn_tpu.create_multi_node_optimizer(
            optax.adam(1e-3), comm)
        opt_state = init_opt_state(comm, optimizer, params)

        def loss_fn(p, batch):
            x, y = batch
            logits = model.apply({"params": p}, x)
            return optax.softmax_cross_entropy_with_integer_labels(
                logits, y).mean()

        step = make_train_step(comm, loss_fn, optimizer)
        x = np.random.RandomState(0).randn(
            comm.size * 2, 32, 32, 3).astype(np.float32)
        y = (np.arange(comm.size * 2) % 5).astype(np.int32)
        x += y.reshape(-1, 1, 1, 1) * 0.5   # learnable signal
        batch = put_global_batch(comm, (x, y))
        losses = []
        for _ in range(10):
            params, opt_state, loss = step(params, opt_state, batch)
            losses.append(float(loss))
        assert all(np.isfinite(losses))
        assert losses[-1] < losses[0]
