"""scatter_dataset tests.

Reference strategy (SURVEY.md §4): shard sizes partition the dataset;
shuffle is root-seeded; determinism across host counts (the global order is
a pure function of seed — SURVEY.md §7 hard part 4).
"""

import numpy as np
import pytest

import chainermn_tpu
from chainermn_tpu.datasets import (
    SubDataset,
    TupleDataset,
    make_classification,
    scatter_dataset,
    scatter_index,
)


class FakeComm:
    """Host-level stand-in so sharding across N hosts is testable in one
    process (scatter only touches rank/host_size/bcast_obj)."""

    def __init__(self, rank, size):
        self.rank = rank
        self.host_size = size

    def bcast_obj(self, obj, root=0):
        return obj


def dataset(n=103):
    return TupleDataset(np.arange(n, dtype=np.float32),
                        np.arange(n, dtype=np.int32))


class TestScatterDataset:
    def test_partition_no_shuffle(self):
        ds = dataset(100)
        shards = [scatter_dataset(ds, FakeComm(r, 4)) for r in range(4)]
        all_idx = np.concatenate([s.indices for s in shards])
        np.testing.assert_array_equal(np.sort(all_idx), np.arange(100))
        assert all(len(s) == 25 for s in shards)

    def test_equal_length_padding(self):
        ds = dataset(10)
        shards = [scatter_dataset(ds, FakeComm(r, 4)) for r in range(4)]
        assert all(len(s) == 3 for s in shards)  # ceil(10/4), wrap-padded
        seen = np.concatenate([s.indices for s in shards])
        assert set(seen) == set(range(10))

    def test_root_seeded_shuffle_identical_across_host_counts(self):
        ds = dataset(60)
        order4 = np.concatenate(
            [scatter_dataset(ds, FakeComm(r, 4), shuffle=True, seed=7).indices
             for r in range(4)])
        order2 = np.concatenate(
            [scatter_dataset(ds, FakeComm(r, 2), shuffle=True, seed=7).indices
             for r in range(2)])
        np.testing.assert_array_equal(order4, order2)  # same global order
        assert not np.array_equal(order4, np.arange(60))  # actually shuffled

    def test_real_comm_single_host(self):
        comm = chainermn_tpu.create_communicator("naive", intra_size=4)
        ds = dataset(50)
        shard = scatter_dataset(ds, comm, shuffle=True, seed=1)
        assert len(shard) == 50  # one host -> whole (permuted) dataset
        x, y = shard[0]
        assert float(x) == int(y)

    def test_scatter_index(self):
        parts = [scatter_index(10, FakeComm(r, 3)) for r in range(3)]
        assert all(len(p) == 4 for p in parts)
        assert set(np.concatenate(parts)) == set(range(10))


class TestSynthetic:
    def test_learnable_signal(self):
        ds = make_classification(n=100, dim=16, n_classes=3, noise=0.1)
        assert len(ds) == 100
        x, y = ds[0]
        assert x.shape == (16,) and 0 <= int(y) < 3


class TestEdgeCases:
    def test_fewer_examples_than_hosts(self):
        ds = dataset(3)
        shards = [scatter_dataset(ds, FakeComm(r, 8)) for r in range(8)]
        assert all(len(s) == 1 for s in shards)  # cyclic wrap, no empties

    def test_eval_partial_batch_padding(self):
        import jax.numpy as jnp
        from chainermn_tpu.training.trainer import put_global_batch

        comm = chainermn_tpu.create_communicator("naive", intra_size=4)
        x = np.arange(13, dtype=np.float32)  # 13 not divisible by 8
        out = put_global_batch(comm, (x,), pad_to_multiple=True)
        assert out[0].shape[0] == 16
        np.testing.assert_array_equal(
            np.asarray(out[0][:13]), x)  # original order preserved
