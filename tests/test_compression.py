"""Gradient-compression subsystem tests — resolver/spec round-trips,
NoCompression bit-exactness against the raw wire-dtype paths (allreduce
and bucketed FSDP), int8/fp8 error-feedback convergence, the optimizer
seam (deprecation shim, rejected combinations, per-hop compressed
plans), checkpoint config guards (incl. the per-hop ``hops`` sidecar),
the compression_* observability family, and the bench census as a
subprocess (chainermn_tpu/compression/ + the three seams)."""

import json
import os
import subprocess
import sys

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

import chainermn_tpu
from chainermn_tpu.compression import (
    CompressionState,
    Fp8Compressor,
    Int8Compressor,
    NoCompression,
    available_compressors,
    compression_layout,
    resolve_compressor,
)
from chainermn_tpu.optimizers import init_opt_state, make_train_step
from chainermn_tpu.planner.plans import compressed_two_dimensional
from chainermn_tpu.parallel.fsdp import (
    fsdp_full_params, fsdp_init, fsdp_layout, make_fsdp_train_step)
from chainermn_tpu.training import put_global_batch

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture
def comm():
    return chainermn_tpu.create_communicator("flat")


def per_rank_grads(size):
    """Stacked per-rank gradient pytrees with a pad-forcing odd leaf:
    rank r holds r * ones, so the exact mean is (size-1)/2."""
    ranks = jnp.arange(size, dtype=jnp.float32).reshape(size, 1, 1)
    return {
        "w": ranks * jnp.ones((size, 3, 4), jnp.float32),
        "b": ranks[:, 0].astype(jnp.bfloat16)
        * jnp.ones((size, 5), jnp.bfloat16),
        "odd": ranks[:, 0] * jnp.ones((size, 7), jnp.float32),
    }


def _mlp_problem(comm, n_layers=4, width=16, seed=0):
    rng = np.random.RandomState(seed)
    params = {f"layer{i}": {
        "w": jnp.asarray(rng.randn(width, width) / 4.0, jnp.float32),
        "b": jnp.asarray(rng.randn(width) / 4.0, jnp.float32)}
        for i in range(n_layers)}

    def loss_fn(p, batch):
        x, y = batch
        for i in range(n_layers):
            x = jnp.tanh(x @ p[f"layer{i}"]["w"] + p[f"layer{i}"]["b"])
        return jnp.mean((x - y) ** 2)

    xs = np.asarray(rng.randn(comm.size * 4, width), np.float32)
    ys = np.asarray(np.tanh(rng.randn(comm.size * 4, width)), np.float32)
    return params, loss_fn, (xs, ys)


# ---- resolver / spec round-trips --------------------------------------------

class TestResolve:
    def test_registry_names(self):
        names = available_compressors()
        for want in ("none", "int8", "fp8"):
            assert want in names, names

    def test_resolve_forms(self):
        assert resolve_compressor(None) is None
        c = Int8Compressor(chunk_size=256, stochastic=False)
        assert resolve_compressor(c) is c
        assert isinstance(resolve_compressor("int8"), Int8Compressor)
        assert isinstance(resolve_compressor("fp8"), Fp8Compressor)
        # a bare dtype string means "cast the wire" (the old knob)
        nc = resolve_compressor("bfloat16")
        assert isinstance(nc, NoCompression)
        assert nc.wire == jnp.bfloat16

    def test_spec_round_trip(self):
        for c in (NoCompression(), NoCompression(wire_dtype="bfloat16"),
                  Int8Compressor(chunk_size=256, stochastic=False, seed=3),
                  Fp8Compressor()):
            again = resolve_compressor(c.spec)
            assert again == c and again.spec == c.spec

    def test_unknown_name_raises(self):
        with pytest.raises(ValueError):
            resolve_compressor("zstd")

    def test_nocompression_rejects_int_wire(self):
        with pytest.raises(ValueError):
            NoCompression(wire_dtype="int8")


# ---- NoCompression == the raw wire-dtype program, bit for bit ---------------

class TestNoCompressionBitExact:
    def test_allreduce_matches_dtype_knob(self):
        """allreduce_grad(compressor=NoCompression(bf16)) on a plain
        communicator is bit-for-bit the allreduce_grad_dtype='bfloat16'
        program (same pack -> cast -> psum -> unpack lowering)."""
        c_knob = chainermn_tpu.create_communicator(
            "xla", intra_size=4, allreduce_grad_dtype="bfloat16")
        c_plain = chainermn_tpu.create_communicator("xla", intra_size=4)
        grads = per_rank_grads(c_knob.size)
        nc = NoCompression(wire_dtype="bfloat16")
        out_knob = c_knob.run_spmd(
            lambda g: c_knob.allreduce_grad(g), grads)
        out_comp = c_plain.run_spmd(
            lambda g: c_plain.allreduce_grad(g, compressor=nc), grads)
        for a, b in zip(jax.tree.leaves(out_knob),
                        jax.tree.leaves(out_comp)):
            assert a.dtype == b.dtype
            assert np.array_equal(np.asarray(a), np.asarray(b)), (a, b)

    def test_bare_nocompression_is_identity_path(self, comm):
        """NoCompression() without a wire dtype lowers to the exact
        default allreduce program."""
        grads = per_rank_grads(comm.size)
        out_plain = comm.run_spmd(lambda g: comm.allreduce_grad(g), grads)
        out_nc = comm.run_spmd(
            lambda g: comm.allreduce_grad(g, compressor=NoCompression()),
            grads)
        for a, b in zip(jax.tree.leaves(out_plain),
                        jax.tree.leaves(out_nc)):
            assert np.array_equal(np.asarray(a), np.asarray(b))

    def test_communicator_compression_kwarg_folds_to_wire(self):
        """create_communicator(compression='bfloat16') is the
        allreduce_grad_dtype knob under the new spelling."""
        c = chainermn_tpu.create_communicator(
            "xla", intra_size=4, compression="bfloat16")
        assert c.allreduce_grad_dtype == jnp.bfloat16
        with pytest.raises(ValueError, match="allreduce_grad_dtype"):
            chainermn_tpu.create_communicator(
                "xla", intra_size=4, allreduce_grad_dtype="float16",
                compression="bfloat16")

    def test_fsdp_bucket_compressors_match_wire_dtypes(self, comm):
        """num_buckets=4 with bucket_compressors=NoCompression(bf16) is
        bit-for-bit the bucket_wire_dtypes=['bfloat16']*4 trajectory."""
        params, loss_fn, data = _mlp_problem(comm)
        batch = put_global_batch(comm, data)
        trajs = {}
        for key, kw in (("wire", dict(bucket_wire_dtypes=["bfloat16"] * 4)),
                        ("comp", dict(bucket_compressors=NoCompression(
                            wire_dtype="bfloat16")))):
            state, meta = fsdp_init(comm, params, optax.adam(0.01),
                                    num_buckets=4, **kw)
            step = make_fsdp_train_step(comm, loss_fn, optax.adam(0.01),
                                        meta, donate=False)
            losses = []
            for _ in range(5):
                state, loss = step(state, batch)
                losses.append(float(loss))
            trajs[key] = (losses, fsdp_full_params(state, meta))
        assert trajs["wire"][0] == trajs["comp"][0]
        for a, b in zip(jax.tree.leaves(trajs["wire"][1]),
                        jax.tree.leaves(trajs["comp"][1])):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


# ---- error feedback: convergence semantics ----------------------------------

class TestErrorFeedback:
    def test_int8_time_averaged_error_decays(self, comm):
        """EF on a constant gradient stream: the per-step residual stays
        BOUNDED (it oscillates at quantization granularity), while the
        time-averaged applied gradient converges to the true mean at
        1/t — the textbook EF guarantee.  Deterministic rounding makes
        the decay exactly monotone."""
        comp = Int8Compressor(stochastic=False)
        rng = np.random.RandomState(0)
        grads = {
            "w": jnp.asarray(rng.randn(comm.size, 3, 4), jnp.float32),
            "b": jnp.asarray(rng.randn(comm.size, 7), jnp.float32),
        }
        state0 = comm.init_compression_state(grads, comp)
        assert isinstance(state0, CompressionState)
        st = jax.tree.map(
            lambda a: jnp.broadcast_to(a, (comm.size,) + a.shape), state0)
        fn = lambda g, s: comm.allreduce_grad(g, compressor=comp, state=s)
        ref = {k: np.asarray(v, np.float32).mean(axis=0)
               for k, v in grads.items()}
        acc = jax.tree.map(lambda _: 0.0, ref)
        errs = []
        checkpoints = (1, 4, 16, 64)
        for t in range(1, checkpoints[-1] + 1):
            out, st = comm.run_spmd(fn, grads, st)
            acc = {k: acc[k] + np.asarray(out[k][0], np.float32)
                   for k in ref}
            if t in checkpoints:
                errs.append(max(
                    np.max(np.abs(acc[k] / t - ref[k])) for k in ref))
        assert all(a > b for a, b in zip(errs, errs[1:])), errs
        assert errs[0] / errs[-1] >= 8.0, errs
        # residual bounded, not growing: one rank's EF norm stays finite
        # and small relative to the gradient scale
        ef = np.asarray(st.ef[0], np.float32)
        assert np.isfinite(ef).all() and np.linalg.norm(ef) < 10.0

    def test_quantizer_without_state_raises(self, comm):
        grads = per_rank_grads(comm.size)
        with pytest.raises(ValueError, match="init_compression_state"):
            comm.run_spmd(
                lambda g: comm.allreduce_grad(g, compressor="int8"), grads)

    def test_state_shape_mismatch_raises(self, comm):
        # > chunk_size elements so the padded EF length actually differs
        # from the tiny tree's (both would otherwise pad to one chunk)
        grads = {"w": jnp.zeros((comm.size, 40, 40), jnp.float32)}
        wrong = comm.init_compression_state(
            {"tiny": jnp.zeros(3)}, Int8Compressor())
        st = jax.tree.map(
            lambda a: jnp.broadcast_to(a, (comm.size,) + a.shape), wrong)
        with pytest.raises(ValueError, match="init_compression_state"):
            comm.run_spmd(
                lambda g, s: comm.allreduce_grad(
                    g, compressor="int8", state=s), grads, st)

    def test_world_size_clip_limit(self):
        """int8's in-wire summation runs out of code levels at W > 63;
        the error points at fp8 / uncompressed."""
        Int8Compressor().clip_limit(8)  # fine
        with pytest.raises(ValueError, match="fp8"):
            Int8Compressor().clip_limit(64)
        Fp8Compressor().clip_limit(64)  # fp8 still has headroom there

    def test_stochastic_rounding_unbiased(self, comm):
        """With stochastic rounding the quantizer is unbiased: averaging
        many independent rounds of the SAME gradient converges to the
        true mean even without exploiting the EF recursion."""
        comp = Int8Compressor(stochastic=True, seed=7)
        grads = per_rank_grads(comm.size)
        state0 = comm.init_compression_state(grads, comp)
        st = jax.tree.map(
            lambda a: jnp.broadcast_to(a, (comm.size,) + a.shape), state0)
        fn = lambda g, s: comm.allreduce_grad(g, compressor=comp, state=s)
        ref = np.asarray(grads["w"], np.float32).mean(axis=0)
        acc = 0.0
        for _ in range(32):
            out, st = comm.run_spmd(fn, grads, st)
            acc = acc + np.asarray(out["w"][0], np.float32)
        assert np.max(np.abs(acc / 32 - ref)) < 0.05


# ---- the optimizer seam -----------------------------------------------------

class TestOptimizerSeam:
    def _train(self, comm, optimizer, steps=6):
        params, loss_fn, data = _mlp_problem(comm)
        opt_state = init_opt_state(comm, optimizer, params)
        step = make_train_step(comm, loss_fn, optimizer)
        batch = put_global_batch(comm, data)
        losses = []
        for _ in range(steps):
            params, opt_state, loss = step(params, opt_state, batch)
            losses.append(float(loss))
        return losses, params

    def test_nocompression_matches_dtype_knob_trajectory(self):
        """compression=NoCompression(bf16) through the optimizer seam
        reproduces the allreduce_grad_dtype communicator knob bit for
        bit over a full training trajectory."""
        c_knob = chainermn_tpu.create_communicator(
            "xla", intra_size=4, allreduce_grad_dtype="bfloat16")
        c_plain = chainermn_tpu.create_communicator("xla", intra_size=4)
        with pytest.deprecated_call():
            opt_knob = chainermn_tpu.create_multi_node_optimizer(
                optax.adam(1e-2), c_knob)
        opt_comp = chainermn_tpu.create_multi_node_optimizer(
            optax.adam(1e-2), c_plain,
            compression=NoCompression(wire_dtype="bfloat16"))
        l_knob, p_knob = self._train(c_knob, opt_knob)
        l_comp, p_comp = self._train(c_plain, opt_comp)
        assert l_knob == l_comp
        for a, b in zip(jax.tree.leaves(p_knob), jax.tree.leaves(p_comp)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    def test_raw_dtype_knob_deprecation_names_replacement(self):
        c = chainermn_tpu.create_communicator(
            "xla", intra_size=4, allreduce_grad_dtype="bfloat16")
        with pytest.warns(DeprecationWarning, match="NoCompression"):
            chainermn_tpu.create_multi_node_optimizer(optax.adam(1e-3), c)

    def test_int8_trains_close_to_uncompressed(self, comm):
        l_base, _ = self._train(
            comm, chainermn_tpu.create_multi_node_optimizer(
                optax.adam(1e-2), comm), steps=12)
        l_q, _ = self._train(
            comm, chainermn_tpu.create_multi_node_optimizer(
                optax.adam(1e-2), comm, compression="int8"), steps=12)
        assert l_q[-1] < l_q[0]  # it trains
        # same trajectory within quantization tolerance
        assert abs(l_q[-1] - l_base[-1]) < 0.1 * abs(l_base[0]), (
            l_base, l_q)

    def test_rejected_combinations(self, comm):
        with pytest.raises(NotImplementedError, match="bucket_compressors"):
            chainermn_tpu.create_multi_node_optimizer(
                optax.adam(1e-3), comm, compression="int8", zero=True)
        with pytest.raises(NotImplementedError, match="error feedback"):
            chainermn_tpu.create_multi_node_optimizer(
                optax.adam(1e-3), comm, compression="int8",
                double_buffering=True)
        with pytest.raises(NotImplementedError,
                           match="allreduce_grad_dtype"):
            chainermn_tpu.create_multi_node_optimizer(
                optax.adam(1e-3), comm,
                compression=NoCompression(wire_dtype="bfloat16"),
                double_buffering=True)


# ---- the optimizer seam, per-hop: compression=<Plan> ------------------------

class TestPerHopOptimizerSeam:
    """``compression=<Plan>`` through ``create_multi_node_optimizer``:
    only the DCN hop quantizes (the ICI hops ride a bf16 wire), and the
    per-hop EF states ride the optimizer state as a stage-indexed
    dict."""

    def _train(self, comm, optimizer, steps=12):
        params, loss_fn, data = _mlp_problem(comm)
        opt_state = init_opt_state(comm, optimizer, params)
        step = make_train_step(comm, loss_fn, optimizer, donate=False)
        batch = put_global_batch(comm, data)
        losses = []
        for _ in range(steps):
            params, opt_state, loss = step(params, opt_state, batch)
            losses.append(float(loss))
        return losses, params, opt_state

    def test_int8_dcn_plan_trains_and_threads_state(self):
        comm = chainermn_tpu.create_communicator("xla", intra_size=4)
        plan = compressed_two_dimensional(
            {"name": "int8", "stochastic": False})
        l_base, _, _ = self._train(
            comm, chainermn_tpu.create_multi_node_optimizer(
                optax.adam(1e-2), comm))
        l_q, _, opt_state = self._train(
            comm, chainermn_tpu.create_multi_node_optimizer(
                optax.adam(1e-2), comm, compression=plan))
        assert l_q[-1] < l_q[0]  # it trains
        # same trajectory within quantization tolerance
        assert abs(l_q[-1] - l_base[-1]) < 0.1 * abs(l_base[0]), (
            l_base, l_q)
        # exactly one EF state, keyed by the quantizing stage index,
        # tagged for the checkpoint sidecar, advanced every step
        assert set(opt_state.comp) == {1}
        cs = opt_state.comp[1]
        assert isinstance(cs, CompressionState)
        assert cs.hop == 1 and "int8" in str(cs.spec)
        assert float(np.asarray(cs.step).max()) == 12.0

    def test_plan_without_quantizing_hops_rejected(self):
        from chainermn_tpu.planner.plans import flavor_plan

        comm = chainermn_tpu.create_communicator("xla", intra_size=4)
        with pytest.raises(ValueError, match="no quantizing"):
            chainermn_tpu.create_multi_node_optimizer(
                optax.adam(1e-3), comm,
                compression=flavor_plan("two_dimensional"))

    def test_plan_composition_rejected(self):
        comm = chainermn_tpu.create_communicator("xla", intra_size=4)
        plan = compressed_two_dimensional(
            {"name": "int8", "stochastic": False})
        for kw in (dict(zero=True), dict(double_buffering=True)):
            with pytest.raises(NotImplementedError, match="per-hop"):
                chainermn_tpu.create_multi_node_optimizer(
                    optax.adam(1e-3), comm, compression=plan, **kw)


# ---- the FSDP seam ----------------------------------------------------------

class TestFsdpSeam:
    def test_int8_buckets_train_and_report_layout(self, comm):
        params, loss_fn, data = _mlp_problem(comm)
        state, meta = fsdp_init(comm, params, optax.adam(0.01),
                                num_buckets=4, bucket_compressors="int8")
        assert all(bl.compressor for bl in meta.buckets)
        step = make_fsdp_train_step(comm, loss_fn, optax.adam(0.01), meta,
                                    donate=False)
        batch = put_global_batch(comm, data)
        losses = []
        for _ in range(12):
            state, loss = step(state, batch)
            losses.append(float(loss))
        assert losses[-1] < losses[0], losses
        layout = fsdp_layout(state)
        assert "compression" in layout
        assert any("int8" in s for s in layout["compression"]["specs"])
        # the EF step counter advanced on every bucket
        for cs in state.comp:
            assert float(np.asarray(cs.step).max()) == 12.0

    def test_mixed_buckets_quantize_only_where_asked(self, comm):
        """Per-bucket config: one int8 bucket, the rest on a plain f32
        wire — and the step still trains."""
        params, loss_fn, data = _mlp_problem(comm)
        state, meta = fsdp_init(
            comm, params, optax.adam(0.01), num_buckets=2,
            bucket_compressors=["int8", None])
        assert meta.buckets[0].compressor and not meta.buckets[1].compressor
        step = make_fsdp_train_step(comm, loss_fn, optax.adam(0.01), meta,
                                    donate=False)
        batch = put_global_batch(comm, data)
        losses = []
        for _ in range(8):
            state, loss = step(state, batch)
            losses.append(float(loss))
        assert losses[-1] < losses[0]

    def test_quantizer_with_accum_rejected(self, comm):
        params, loss_fn, _ = _mlp_problem(comm)
        _, meta = fsdp_init(comm, params, optax.adam(0.01), num_buckets=2,
                            bucket_compressors="int8")
        with pytest.raises(NotImplementedError, match="accum"):
            make_fsdp_train_step(comm, loss_fn, optax.adam(0.01), meta,
                                 donate=False, accum_steps=2)

    def test_bucket_compressors_length_mismatch_raises(self, comm):
        params, _, _ = _mlp_problem(comm)
        with pytest.raises(ValueError, match="bucket_compressors"):
            fsdp_init(comm, params, optax.adam(0.01), num_buckets=3,
                      bucket_compressors=["int8"])

    def test_wire_conflict_raises(self, comm):
        params, _, _ = _mlp_problem(comm)
        with pytest.raises(ValueError, match="wire"):
            fsdp_init(comm, params, optax.adam(0.01), num_buckets=1,
                      bucket_wire_dtypes=["float16"],
                      bucket_compressors=NoCompression(
                          wire_dtype="bfloat16"))


# ---- checkpoint guards ------------------------------------------------------

class TestCheckpointGuards:
    def _states(self, comm, **kw):
        params, loss_fn, data = _mlp_problem(comm)
        state, meta = fsdp_init(comm, params, optax.adam(0.01),
                                num_buckets=2, **kw)
        step = make_fsdp_train_step(comm, loss_fn, optax.adam(0.01), meta,
                                    donate=False)
        return state, meta, step, put_global_batch(comm, data)

    def test_compressed_state_roundtrips_and_continues(self, comm,
                                                       tmp_path):
        from chainermn_tpu.extensions import create_multi_node_checkpointer

        state, meta, step, batch = self._states(
            comm, bucket_compressors="int8")
        state, _ = step(state, batch)
        ckpt = create_multi_node_checkpointer(comm, str(tmp_path), "cmp")
        ckpt.save({"fsdp": state}, 1)
        restored, gen = ckpt.resume(
            jax.tree.map(jnp.zeros_like, {"fsdp": state}))
        assert gen == 1
        s2, l2 = step(restored["fsdp"], batch)
        s3, l3 = step(state, batch)
        assert float(l2) == float(l3)
        for a, b in zip(jax.tree.leaves(s2), jax.tree.leaves(s3)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    def test_saved_compressed_live_plain_refused(self, comm, tmp_path):
        from chainermn_tpu.extensions import create_multi_node_checkpointer

        state_c, _, _, _ = self._states(comm, bucket_compressors="int8")
        state_p, _, _, _ = self._states(comm)
        ckpt = create_multi_node_checkpointer(comm, str(tmp_path), "cmp")
        ckpt.save({"fsdp": state_c}, 1)
        with pytest.raises(ValueError, match="no compression configured"):
            ckpt.resume(jax.tree.map(jnp.zeros_like, {"fsdp": state_p}))

    def test_saved_plain_live_compressed_refused(self, comm, tmp_path):
        from chainermn_tpu.extensions import create_multi_node_checkpointer

        state_c, _, _, _ = self._states(comm, bucket_compressors="int8")
        state_p, _, _, _ = self._states(comm)
        ckpt = create_multi_node_checkpointer(comm, str(tmp_path), "cmp")
        ckpt.save({"fsdp": state_p}, 1)
        with pytest.raises(ValueError, match="no compression state"):
            ckpt.resume(jax.tree.map(jnp.zeros_like, {"fsdp": state_c}))

    def test_config_mismatch_refused(self, comm, tmp_path):
        from chainermn_tpu.extensions import create_multi_node_checkpointer

        state_a, _, _, _ = self._states(comm, bucket_compressors="int8")
        state_b, _, _, _ = self._states(comm, bucket_compressors="fp8")
        ckpt = create_multi_node_checkpointer(comm, str(tmp_path), "cmp")
        ckpt.save({"fsdp": state_a}, 1)
        with pytest.raises(ValueError, match="does not match the live"):
            ckpt.resume(jax.tree.map(jnp.zeros_like, {"fsdp": state_b}))


# ---- checkpoint guards, per-hop: the "hops" sidecar -------------------------

class TestPerHopCheckpointGuards:
    """The multi-node checkpointer's compression sidecar pins WHICH plan
    stage carries WHICH codec: matched per-hop specs restore the EF
    residual of every stage exactly; a resume under a different per-hop
    spec refuses loudly instead of silently re-quantizing with stale
    residuals."""

    def _opt_state(self, comm, spec, steps=2):
        params, loss_fn, data = _mlp_problem(comm)
        plan = compressed_two_dimensional(dict(spec))
        opt = chainermn_tpu.create_multi_node_optimizer(
            optax.adam(1e-2), comm, compression=plan)
        opt_state = init_opt_state(comm, opt, params)
        step = make_train_step(comm, loss_fn, opt, donate=False)
        batch = put_global_batch(comm, data)
        for _ in range(steps):
            params, opt_state, _ = step(params, opt_state, batch)
        return opt_state, (params, step, batch)

    def test_layout_pins_stage_to_spec(self):
        comm = chainermn_tpu.create_communicator("xla", intra_size=4)
        state, _ = self._opt_state(
            comm, {"name": "int8", "stochastic": False})
        layout = compression_layout({"opt": state})
        assert layout["n_states"] == 1
        (hop,) = layout["hops"]
        assert hop.startswith("1:") and "int8" in hop

    def test_per_hop_state_roundtrips_and_continues(self, tmp_path):
        from chainermn_tpu.extensions import create_multi_node_checkpointer

        comm = chainermn_tpu.create_communicator("xla", intra_size=4)
        state, (params, step, batch) = self._opt_state(
            comm, {"name": "int8", "stochastic": False})
        ckpt = create_multi_node_checkpointer(comm, str(tmp_path), "hop")
        ckpt.save({"opt": state}, 1)
        restored, gen = ckpt.resume(
            jax.tree.map(jnp.zeros_like, {"opt": state}))
        assert gen == 1
        # the per-stage EF residual (a real, nonzero array after two
        # quantized steps) came back bit for bit
        assert float(jnp.abs(state.comp[1].ef).max()) > 0.0
        for a, b in zip(jax.tree.leaves(state),
                        jax.tree.leaves(restored["opt"])):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        # and the resumed state continues the exact trajectory
        _, s2, l2 = step(params, restored["opt"], batch)
        _, s3, l3 = step(params, state, batch)
        assert float(l2) == float(l3)
        for a, b in zip(jax.tree.leaves(s2), jax.tree.leaves(s3)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    def test_per_hop_spec_mismatch_refused(self, tmp_path):
        from chainermn_tpu.extensions import create_multi_node_checkpointer

        comm = chainermn_tpu.create_communicator("xla", intra_size=4)
        state_a, _ = self._opt_state(
            comm, {"name": "int8", "stochastic": False})
        state_b, _ = self._opt_state(
            comm, {"name": "fp8", "stochastic": False})
        ckpt = create_multi_node_checkpointer(comm, str(tmp_path), "hop")
        ckpt.save({"opt": state_a}, 1)
        with pytest.raises(ValueError, match="does not match the live"):
            ckpt.resume(jax.tree.map(jnp.zeros_like, {"opt": state_b}))


# ---- per-channel int8 weight quantization (serving) -------------------------

class TestPerChannelInt8Weights:
    """Property tests for the serving weight codec
    (``quantize_per_channel_int8``): the round-trip error is bounded by
    half a quantization step PER CHANNEL, which is never worse — and on
    scale-skewed matrices strictly better — than one per-tensor step."""

    @pytest.mark.parametrize("seed", range(5))
    @pytest.mark.parametrize("shape", [(16, 8), (7, 33), (4, 4, 12)])
    def test_roundtrip_error_bounded_by_channel_step(self, seed, shape):
        from chainermn_tpu.compression.quantize import (
            dequantize_int8, quantize_per_channel_int8)

        rng = np.random.default_rng(seed)
        # skew channel scales over 4 orders of magnitude — the regime
        # per-tensor quantization loses small channels entirely
        scales = 10.0 ** rng.uniform(-2, 2, size=shape[-1])
        w = rng.normal(size=shape) * scales
        codes, scale = quantize_per_channel_int8(jnp.asarray(w))
        assert codes.dtype == jnp.int8
        err = np.abs(np.asarray(dequantize_int8(codes, scale)) - w)
        # |err| <= scale/2 per channel (round-to-nearest on amax/127)
        bound = np.broadcast_to(np.asarray(scale) / 2 + 1e-12, shape)
        assert (err <= bound).all(), float((err - bound).max())

    @pytest.mark.parametrize("seed", range(3))
    def test_beats_per_tensor_on_skewed_channels(self, seed):
        from chainermn_tpu.compression.quantize import (
            dequantize_int8, quantize_per_channel_int8,
            quantize_per_tensor_int8)

        rng = np.random.default_rng(100 + seed)
        scales = 10.0 ** rng.uniform(-3, 1, size=32)
        w = jnp.asarray(rng.normal(size=(64, 32)) * scales)
        cc, cs = quantize_per_channel_int8(w)
        tc, ts = quantize_per_tensor_int8(w)
        err_c = float(jnp.abs(dequantize_int8(cc, cs) - w).max())
        err_t = float(jnp.abs(dequantize_int8(tc, ts) - w).max())
        # per-channel max error also respects the PER-TENSOR bound...
        assert err_c <= float(ts) / 2 + 1e-12
        # ...and the mean error is strictly better on skewed channels
        mean_c = float(jnp.abs(dequantize_int8(cc, cs) - w).mean())
        mean_t = float(jnp.abs(dequantize_int8(tc, ts) - w).mean())
        assert mean_c < mean_t

    def test_zero_and_constant_channels(self):
        from chainermn_tpu.compression.quantize import (
            dequantize_int8, quantize_per_channel_int8)

        w = jnp.stack([jnp.zeros((8,)), jnp.full((8,), 3.0)], axis=-1)
        codes, scale = quantize_per_channel_int8(w)
        out = np.asarray(dequantize_int8(codes, scale))
        assert (out[:, 0] == 0).all()
        np.testing.assert_allclose(out[:, 1], 3.0, rtol=1e-6)


# ---- observability: compression_* family + report lane ----------------------

class TestObservability:
    @pytest.fixture(autouse=True)
    def clean(self):
        from chainermn_tpu import observability as obs
        from chainermn_tpu.observability import (
            get_registry, reset_flight_recorder)

        reset_flight_recorder()
        obs.disable()
        get_registry().reset()
        yield
        reset_flight_recorder()
        obs.disable()
        get_registry().reset()

    def test_compression_metric_family_published(self, comm):
        from chainermn_tpu import observability as obs
        from chainermn_tpu.observability import get_registry

        obs.enable()
        params, loss_fn, data = _mlp_problem(comm)
        state, meta = fsdp_init(comm, params, optax.adam(0.01),
                                num_buckets=2, bucket_compressors="int8")
        step = make_fsdp_train_step(comm, loss_fn, optax.adam(0.01), meta,
                                    donate=False)
        batch = put_global_batch(comm, data)
        state, loss = step(state, batch)
        jax.block_until_ready(loss)
        jax.effects_barrier()
        reg = get_registry()
        for b in ("0", "1"):
            bits = reg.gauge("compression_bits_per_param").value(
                seam="fsdp", bucket=b, compressor="int8")
            assert 8.0 <= bits < 16.0, bits  # 8-bit wire + scale/pad
            assert reg.counter("compression_wire_bytes_saved").value(
                seam="fsdp", bucket=b, compressor="int8") > 0
            rn = reg.gauge("compression_residual_norm").value(
                seam="fsdp", bucket=b, compressor="int8")
            assert np.isfinite(rn) and rn >= 0.0

    def test_instrumented_proxy_passes_codec_through(self, comm):
        """Regression: the observability proxy once pinned the old
        ``allreduce_grad(grads)`` signature, so ``--compression`` +
        ``--observability`` together crashed at the optimizer seam."""
        from chainermn_tpu import observability as obs
        from chainermn_tpu.observability import instrument_communicator

        obs.enable()
        icomm = instrument_communicator(comm)
        opt = chainermn_tpu.create_multi_node_optimizer(
            optax.sgd(0.1), icomm, compression="int8")
        params = {"w": jnp.ones((16,))}
        opt_state = init_opt_state(icomm, opt, params)
        step = make_train_step(
            icomm, lambda p, b: jnp.mean((p["w"] - b[0]) ** 2), opt,
            donate=False)
        batch = (jnp.ones((comm.size, 16)),)
        params, opt_state, loss = step(params, opt_state, batch)
        assert np.isfinite(float(loss))
        # the eager/default path through the proxy must also still work
        out = icomm.run_spmd(
            lambda g: icomm.allreduce_grad(g),
            {"w": jnp.ones((comm.size, 4))})
        assert float(out["w"][0][0]) == 1.0

    def test_disabled_observability_keeps_program_clean(self, comm):
        params, loss_fn, data = _mlp_problem(comm)
        state, meta = fsdp_init(comm, params, optax.adam(0.01),
                                num_buckets=2, bucket_compressors="int8")
        step = make_fsdp_train_step(comm, loss_fn, optax.adam(0.01), meta,
                                    donate=False)
        batch = put_global_batch(comm, data)
        assert "callback" not in step.lower(state, batch).as_text()

    def test_obs_report_compression_lane(self):
        sys.path.insert(0, os.path.join(REPO, "tools"))
        try:
            import obs_report
        finally:
            sys.path.pop(0)
        records = [
            {"kind": "metric", "name": "compression_bits_per_param",
             "labels": {"seam": "fsdp", "bucket": "0",
                        "compressor": "int8"}, "value": 8.25},
            {"kind": "metric", "name": "compression_wire_bytes_saved",
             "labels": {"seam": "fsdp", "bucket": "0",
                        "compressor": "int8"}, "value": 123456.0},
            {"kind": "metric", "name": "compression_residual_norm",
             "labels": {"seam": "fsdp", "bucket": "0",
                        "compressor": "int8"}, "value": 0.5},
        ]
        out = obs_report.compression_section(records)
        assert "int8" in out and "8.25" in out and "3.88x" in out
        empty = obs_report.compression_section([])
        assert "no compression_* metrics" in empty

    def test_obs_report_flight_compute_straggler(self, tmp_path):
        sys.path.insert(0, os.path.join(REPO, "tools"))
        try:
            import obs_report
        finally:
            sys.path.pop(0)
        dump = {"kind": "flight_dump", "rank": 0, "reason": "watchdog",
                "events": [],
                "collective_state": {
                    "last_completed": {}, "steps": 0, "event_seq": 1,
                    "ts": 0.0,
                    "open": [{"kind": "compute", "op": "compress:fsdp",
                              "op_seq": 1, "ts": 0.0, "age_s": 42.0}]}}
        path = tmp_path / "flight_0.json"
        path.write_text(json.dumps(dump))
        dumps = obs_report.load_flight_dumps([str(tmp_path)])
        section = obs_report.flight_desync_section(dumps)
        assert "compute straggler" in section
        assert "compress:fsdp" in section


# ---- the sweep as a subprocess (slow tier) ----------------------------------

@pytest.mark.slow
def test_bench_compression_sweep_runs():
    """End-to-end: the compressor x bucket sweep passes its own wire
    census asserts (>=3.5x int8 shrink, no extra collectives, barriers
    preserved) on the 8-device CPU mesh and emits valid JSON."""
    env = dict(os.environ,
               JAX_PLATFORMS="cpu",
               XLA_FLAGS="--xla_force_host_platform_device_count=8")
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "benchmarks",
                                      "bench_compression.py"),
         "--json", "--iters", "2", "--warmup", "1",
         "--layers", "4", "--width", "64",
         "--compressors", "none,none:bfloat16,int8,fp8",
         "--buckets", "1,4"],
        capture_output=True, text=True, timeout=480, env=env, cwd=REPO)
    assert out.returncode == 0, out.stderr[-2000:]
    rows = [json.loads(l) for l in out.stdout.splitlines() if l.strip()]
    assert len(rows) == 8
    assert all(r["census_ok"] for r in rows)
    int8 = [r for r in rows if r["compressor"] == "int8"]
    assert all(r["wire_ratio_vs_f32"] >= 3.5 for r in int8)


# ---- 2-process world: int8 EF on MNIST (acceptance criterion) ---------------

_MNIST_WORLD_WORKER = r"""
import json, os, sys
sys.path.insert(0, os.environ["CHAINERMN_TPU_REPO"])
import chainermn_tpu

chainermn_tpu.init_distributed(local_device_count=4)

import jax
import jax.numpy as jnp
import numpy as np
import optax

from chainermn_tpu.datasets import make_classification
from chainermn_tpu.models import MLP
from chainermn_tpu.optimizers import init_opt_state, make_train_step
from chainermn_tpu.training import put_global_batch

assert jax.process_count() == 2 and jax.device_count() == 8
comm = chainermn_tpu.create_communicator("hierarchical")

model = MLP(64, 10)
params0 = model.init(jax.random.key(0), jnp.zeros((1, 784)))
params0 = comm.bcast_data(params0)

# MNIST shapes, synthetic blobs (the example's no-download path); each
# controller trains on its own half so the allreduce is load-bearing
data = make_classification(n=1024, dim=784, n_classes=10, noise=4.0, seed=0)
xs = np.stack([data[i][0] for i in range(len(data))]).astype(np.float32)
ys = np.asarray([data[i][1] for i in range(len(data))], np.int32)
half = len(xs) // 2
sl = slice(comm.host_rank * half, (comm.host_rank + 1) * half)
x_local, y_local = xs[sl], ys[sl]


def loss_fn(p, batch):
    x, y = batch
    logits = model.apply(p, x)
    return optax.softmax_cross_entropy_with_integer_labels(logits, y).mean()


def train_run(compression):
    params = jax.tree.map(jnp.copy, params0)  # the step donates its args
    opt = chainermn_tpu.create_multi_node_optimizer(
        optax.adam(1e-3), comm, compression=compression)
    opt_state = init_opt_state(comm, opt, params)
    step = make_train_step(comm, loss_fn, opt)
    batch = put_global_batch(comm, (x_local, y_local))
    losses, ef_norms = [], []
    for _ in range(20):
        params, opt_state, loss = step(params, opt_state, batch)
        losses.append(float(loss))
        if compression is not None:
            ef = np.asarray(
                opt_state.comp.ef.addressable_shards[0].data, np.float32)
            ef_norms.append(float(np.linalg.norm(ef)))
    return losses, ef_norms


base, _ = train_run(None)
q, ef_norms = train_run("int8")
print("RESULT " + json.dumps({"rank": comm.host_rank, "base": base,
                              "int8": q, "ef": ef_norms}))
"""


@pytest.mark.slow
def test_two_process_mnist_int8_matches_uncompressed():
    """The acceptance run: int8-EF gradient exchange across a REAL
    2-process world (XLA cross-process collectives) tracks the
    uncompressed loss trajectory within quantization tolerance on the
    MNIST-shaped problem, stays globally synchronous (both controllers
    see the same losses), and the EF residual settles instead of
    growing."""
    from chainermn_tpu.utils.proc_world import spawn_world

    results = spawn_world(_MNIST_WORLD_WORKER, n_procs=2, local_devices=4,
                          timeout=300, repo=REPO)
    r0, r1 = results[0], results[1]
    # globally synchronous on both runs
    assert r0["base"] == pytest.approx(r1["base"], rel=1e-6)
    assert r0["int8"] == pytest.approx(r1["int8"], rel=1e-6)
    # both train, and int8 tracks the uncompressed trajectory
    assert r0["base"][-1] < r0["base"][0]
    assert r0["int8"][-1] < r0["int8"][0]
    assert abs(r0["int8"][-1] - r0["base"][-1]) < 0.1 * abs(r0["base"][0]), (
        r0["base"], r0["int8"])
    # EF residual bounded: the scale controller settles, so the tail of
    # the residual-norm series is no larger than its global peak would
    # be under divergence (strictly: last <= max, and the last quarter
    # does not exceed the first three quarters' peak)
    ef = r0["ef"]
    assert all(np.isfinite(ef))
    assert max(ef[15:]) <= max(ef[:15]), ef


# ---- 2-process world: int8 on the DCN hop only (acceptance criterion) -------

_PERHOP_WORLD_WORKER = r"""
import json, os, sys
sys.path.insert(0, os.environ["CHAINERMN_TPU_REPO"])
import chainermn_tpu

chainermn_tpu.init_distributed(local_device_count=4)

import jax
import jax.numpy as jnp
import numpy as np
import optax

from chainermn_tpu.datasets import make_classification
from chainermn_tpu.models import MLP
from chainermn_tpu.optimizers import init_opt_state, make_train_step
from chainermn_tpu.planner.plans import compressed_two_dimensional
from chainermn_tpu.training import put_global_batch

assert jax.process_count() == 2 and jax.device_count() == 8
comm = chainermn_tpu.create_communicator("hierarchical")

model = MLP(64, 10)
params0 = model.init(jax.random.key(0), jnp.zeros((1, 784)))
params0 = comm.bcast_data(params0)

data = make_classification(n=1024, dim=784, n_classes=10, noise=4.0, seed=0)
xs = np.stack([data[i][0] for i in range(len(data))]).astype(np.float32)
ys = np.asarray([data[i][1] for i in range(len(data))], np.int32)
half = len(xs) // 2
sl = slice(comm.host_rank * half, (comm.host_rank + 1) * half)
x_local, y_local = xs[sl], ys[sl]


def loss_fn(p, batch):
    x, y = batch
    logits = model.apply(p, x)
    return optax.softmax_cross_entropy_with_integer_labels(logits, y).mean()


def train_run(compression):
    params = jax.tree.map(jnp.copy, params0)  # the step donates its args
    opt = chainermn_tpu.create_multi_node_optimizer(
        optax.adam(1e-3), comm, compression=compression)
    opt_state = init_opt_state(comm, opt, params)
    step = make_train_step(comm, loss_fn, opt)
    batch = put_global_batch(comm, (x_local, y_local))
    losses, ef_norms = [], []
    for _ in range(20):
        params, opt_state, loss = step(params, opt_state, batch)
        losses.append(float(loss))
        if compression is not None:
            ef = np.asarray(
                opt_state.comp[1].ef.addressable_shards[0].data, np.float32)
            ef_norms.append(float(np.linalg.norm(ef)))
    return losses, ef_norms


# int8 on the inter (cross-process == DCN) hop, bf16 on the ICI hops
plan = compressed_two_dimensional({"name": "int8", "stochastic": False})
assert plan.stages[1].compression["name"] == "int8"
base, _ = train_run(None)
q, ef_norms = train_run(plan)
print("RESULT " + json.dumps({"rank": comm.host_rank, "base": base,
                              "int8_dcn": q, "ef": ef_norms}))
"""


@pytest.mark.slow
def test_two_process_mnist_int8_dcn_plan_matches_uncompressed():
    """The per-hop acceptance run: a plan that quantizes ONLY the
    cross-process (DCN) hop to int8 — reduce-scatter and gather stay on
    the intra bf16 wire — tracks the uncompressed loss trajectory
    within quantization tolerance across a REAL 2-process world, stays
    globally synchronous, and its single per-hop EF residual settles."""
    from chainermn_tpu.utils.proc_world import spawn_world

    results = spawn_world(_PERHOP_WORLD_WORKER, n_procs=2, local_devices=4,
                          timeout=300, repo=REPO)
    r0, r1 = results[0], results[1]
    # globally synchronous on both runs
    assert r0["base"] == pytest.approx(r1["base"], rel=1e-6)
    assert r0["int8_dcn"] == pytest.approx(r1["int8_dcn"], rel=1e-6)
    # both train, and the compressed-hop run tracks the uncompressed one
    assert r0["base"][-1] < r0["base"][0]
    assert r0["int8_dcn"][-1] < r0["int8_dcn"][0]
    assert abs(r0["int8_dcn"][-1] - r0["base"][-1]) < \
        0.1 * abs(r0["base"][0]), (r0["base"], r0["int8_dcn"])
    # the per-hop EF residual stays bounded (same settle criterion as
    # the whole-collective test above)
    ef = r0["ef"]
    assert all(np.isfinite(ef))
    assert max(ef[15:]) <= max(ef[:15]), ef
